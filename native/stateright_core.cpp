// Native host core: the performance-critical pieces of the CPU checker.
//
// The reference implements its whole runtime natively (Rust); this library
// is the C++ equivalent of its L0 hot paths (SURVEY §2.1): the stable
// 64-bit fingerprint mixer (src/lib.rs:340-387) and the lock-striped
// concurrent visited set with predecessor tracking — the DashMap analog of
// src/checker/bfs.rs:29-31.  Exposed through a plain C ABI for ctypes
// (pybind11 is not available in this environment).
//
// The mixer is bit-identical to ops/fingerprint.fp64_words (two
// murmur3-style 32-bit lanes), which tests pin.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

constexpr uint32_t C1 = 0xCC9E2D51u;
constexpr uint32_t C2 = 0x1B873593u;
constexpr uint32_t SEED_HI = 0x9E3779B9u;
constexpr uint32_t SEED_LO = 0x85EBCA6Bu;

inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

inline uint32_t mix32(uint32_t h, uint32_t w) {
  uint32_t k = w * C1;
  k = rotl32(k, 15);
  k = k * C2;
  h ^= k;
  h = rotl32(h, 13);
  h = h * 5u + 0xE6546B64u;
  return h;
}

inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  h ^= h >> 16;
  return h;
}

}  // namespace

extern "C" {

// Bit-identical to ops/fingerprint.fp64_words: nonzero (0 = empty slot)
// and never all-ones (device inactive-lane sentinel).
uint64_t sr_fp64_words(const uint32_t* words, uint64_t n) {
  uint32_t h1 = SEED_HI;
  uint32_t h2 = SEED_LO;
  for (uint64_t i = 0; i < n; ++i) {
    h1 = mix32(h1, words[i]);
    h2 = mix32(h2, words[i]);
  }
  h1 = fmix32(h1 ^ static_cast<uint32_t>(n));
  h2 = fmix32(h2 ^ static_cast<uint32_t>(n * 0x9E3779B1u));
  uint64_t fp = (static_cast<uint64_t>(h1) << 32) | h2;
  if (fp == 0) return 1;
  if (fp == ~0ull) return ~0ull - 1;
  return fp;
}

// Batched form: rows of a [count, width] uint32 matrix.
void sr_fp64_batch(const uint32_t* words, uint64_t count, uint64_t width,
                   uint64_t* out) {
  for (uint64_t i = 0; i < count; ++i) {
    out[i] = sr_fp64_words(words + i * width, width);
  }
}

// --- concurrent visited set (fp -> parent fp) -------------------------------
//
// Open addressing over power-of-two capacity with striped mutexes; the
// GIL is released during ctypes calls, so checker worker threads contend
// only per stripe — the moral equivalent of DashMap's shard locks.

struct FpSet {
  // Atomics: readers probe without stripe locks, so the key store must be
  // a release (after the parent store) and reads acquires — a plain-store
  // scheme would be a data race however the hardware orders it.
  std::vector<std::atomic<uint64_t>> keys;     // 0 = empty (fps are nonzero)
  std::vector<std::atomic<uint64_t>> parents;  // 0 = none
  std::vector<std::mutex> locks;
  std::atomic<uint64_t> count{0};
  uint64_t mask = 0;

  explicit FpSet(uint64_t capacity)
      : keys(capacity), parents(capacity), locks(256), mask(capacity - 1) {
    for (auto& k : keys) k.store(0, std::memory_order_relaxed);
    for (auto& p : parents) p.store(0, std::memory_order_relaxed);
  }
};

void* sr_fpset_new(uint64_t capacity_pow2) {
  if (capacity_pow2 == 0 || (capacity_pow2 & (capacity_pow2 - 1))) {
    return nullptr;
  }
  return new FpSet(capacity_pow2);
}

void sr_fpset_free(void* set) { delete static_cast<FpSet*>(set); }

uint64_t sr_fpset_len(void* set) {
  return static_cast<FpSet*>(set)->count.load(std::memory_order_relaxed);
}

static inline uint64_t home_of(uint64_t fp, uint64_t mask) {
  // Independent second mix so slot position is uncorrelated with the key.
  uint32_t h = fmix32(static_cast<uint32_t>(fp) ^
                      rotl32(static_cast<uint32_t>(fp >> 32), 16) ^
                      0x7FEB352Du);
  return (static_cast<uint64_t>(h) ^ (fp >> 17)) & mask;
}

// Insert fp with parent; returns 1 if newly inserted, 0 if already present,
// -1 if the table is full.
int32_t sr_fpset_insert(void* set_ptr, uint64_t fp, uint64_t parent) {
  FpSet* s = static_cast<FpSet*>(set_ptr);
  uint64_t idx = home_of(fp, s->mask);
  for (uint64_t probes = 0; probes <= s->mask; ++probes) {
    std::mutex& m = s->locks[idx & 255];
    {
      std::lock_guard<std::mutex> g(m);
      uint64_t cur = s->keys[idx].load(std::memory_order_acquire);
      if (cur == 0) {
        s->parents[idx].store(parent, std::memory_order_relaxed);
        // Release: the parent store is visible before the key appears.
        s->keys[idx].store(fp, std::memory_order_release);
        s->count.fetch_add(1, std::memory_order_relaxed);
        return 1;
      }
      if (cur == fp) {
        return 0;
      }
    }
    idx = (idx + 1) & s->mask;
  }
  return -1;
}

// Returns 1 and writes *parent_out if present; 0 otherwise.
int32_t sr_fpset_get_parent(void* set_ptr, uint64_t fp, uint64_t* parent_out) {
  FpSet* s = static_cast<FpSet*>(set_ptr);
  uint64_t idx = home_of(fp, s->mask);
  for (uint64_t probes = 0; probes <= s->mask; ++probes) {
    uint64_t cur = s->keys[idx].load(std::memory_order_acquire);
    if (cur == 0) {
      return 0;
    }
    if (cur == fp) {
      *parent_out = s->parents[idx].load(std::memory_order_relaxed);
      return 1;
    }
    idx = (idx + 1) & s->mask;
  }
  return 0;
}

int32_t sr_fpset_contains(void* set_ptr, uint64_t fp) {
  uint64_t unused;
  return sr_fpset_get_parent(set_ptr, fp, &unused);
}

}  // extern "C"
