// Native host core: the performance-critical pieces of the CPU checker.
//
// The reference implements its whole runtime natively (Rust); this library
// is the C++ equivalent of its L0 hot paths (SURVEY §2.1): the stable
// 64-bit fingerprint mixer (src/lib.rs:340-387) and the lock-striped
// concurrent visited set with predecessor tracking — the DashMap analog of
// src/checker/bfs.rs:29-31.  Exposed through a plain C ABI for ctypes
// (pybind11 is not available in this environment).
//
// The mixer is bit-identical to ops/fingerprint.fp64_words (two
// murmur3-style 32-bit lanes), which tests pin.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <shared_mutex>
#include <vector>

namespace {

constexpr uint32_t C1 = 0xCC9E2D51u;
constexpr uint32_t C2 = 0x1B873593u;
constexpr uint32_t SEED_HI = 0x9E3779B9u;
constexpr uint32_t SEED_LO = 0x85EBCA6Bu;

inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

inline uint32_t mix32(uint32_t h, uint32_t w) {
  uint32_t k = w * C1;
  k = rotl32(k, 15);
  k = k * C2;
  h ^= k;
  h = rotl32(h, 13);
  h = h * 5u + 0xE6546B64u;
  return h;
}

inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  h ^= h >> 16;
  return h;
}

}  // namespace

extern "C" {

// Bit-identical to ops/fingerprint.fp64_words: nonzero (0 = empty slot)
// and never all-ones (device inactive-lane sentinel).
uint64_t sr_fp64_words(const uint32_t* words, uint64_t n) {
  uint32_t h1 = SEED_HI;
  uint32_t h2 = SEED_LO;
  for (uint64_t i = 0; i < n; ++i) {
    h1 = mix32(h1, words[i]);
    h2 = mix32(h2, words[i]);
  }
  h1 = fmix32(h1 ^ static_cast<uint32_t>(n));
  h2 = fmix32(h2 ^ static_cast<uint32_t>(n * 0x9E3779B1u));
  uint64_t fp = (static_cast<uint64_t>(h1) << 32) | h2;
  if (fp == 0) return 1;
  if (fp == ~0ull) return ~0ull - 1;
  return fp;
}

// Batched form: rows of a [count, width] uint32 matrix.
void sr_fp64_batch(const uint32_t* words, uint64_t count, uint64_t width,
                   uint64_t* out) {
  for (uint64_t i = 0; i < count; ++i) {
    out[i] = sr_fp64_words(words + i * width, width);
  }
}

// --- concurrent visited set (fp -> parent fp) -------------------------------
//
// Open addressing over power-of-two capacity with striped mutexes; the
// GIL is released during ctypes calls, so checker worker threads contend
// only per stripe — the moral equivalent of DashMap's shard locks.
//
// Growth: like DashMap (and unlike a fixed device table), the set grows
// automatically — inserts hold a shared resize lock; crossing 3/4 load
// takes it uniquely, doubles the table, and rehashes.  An uncontended
// shared lock is tens of nanoseconds against the ~microsecond ctypes call
// that reaches here, so steady-state cost is noise.

struct FpSet {
  // Atomics: readers probe without stripe locks, so the key store must be
  // a release (after the parent store) and reads acquires — a plain-store
  // scheme would be a data race however the hardware orders it.
  std::shared_mutex resize_mx;
  std::vector<std::atomic<uint64_t>> keys;     // 0 = empty (fps are nonzero)
  std::vector<std::atomic<uint64_t>> parents;  // 0 = none
  std::vector<std::mutex> locks;
  std::atomic<uint64_t> count{0};
  uint64_t mask = 0;

  explicit FpSet(uint64_t capacity)
      : keys(capacity), parents(capacity), locks(256), mask(capacity - 1) {
    for (auto& k : keys) k.store(0, std::memory_order_relaxed);
    for (auto& p : parents) p.store(0, std::memory_order_relaxed);
  }
};

static inline bool needs_grow(const FpSet* s) {
  // Below 3/4 load a probe sweep practically always finds an empty slot
  // or the key.  This is only a fast-path heuristic: concurrent inserters
  // that all passed the check can still fill the table, so the insert
  // probe loop is BOUNDED and falls back to grow() on exhaustion rather
  // than spinning while holding the shared resize lock.
  return s->count.load(std::memory_order_relaxed) * 4 >= (s->mask + 1) * 3;
}

void* sr_fpset_new(uint64_t capacity_pow2) {
  if (capacity_pow2 == 0 || (capacity_pow2 & (capacity_pow2 - 1))) {
    return nullptr;
  }
  return new FpSet(capacity_pow2);
}

void sr_fpset_free(void* set) { delete static_cast<FpSet*>(set); }

uint64_t sr_fpset_len(void* set) {
  return static_cast<FpSet*>(set)->count.load(std::memory_order_relaxed);
}

static inline uint64_t home_of(uint64_t fp, uint64_t mask) {
  // Independent second mix so slot position is uncorrelated with the key.
  uint32_t h = fmix32(static_cast<uint32_t>(fp) ^
                      rotl32(static_cast<uint32_t>(fp >> 32), 16) ^
                      0x7FEB352Du);
  return (static_cast<uint64_t>(h) ^ (fp >> 17)) & mask;
}

// Doubles the table, unless another thread already grew it past the
// capacity the caller observed (then the caller's reason to grow is gone).
static void grow(FpSet* s, uint64_t observed_mask) {
  std::unique_lock<std::shared_mutex> g(s->resize_mx);
  if (s->mask != observed_mask) return;  // another thread grew first
  uint64_t new_cap = (s->mask + 1) * 2;
  std::vector<std::atomic<uint64_t>> nk(new_cap);
  std::vector<std::atomic<uint64_t>> np(new_cap);
  for (auto& k : nk) k.store(0, std::memory_order_relaxed);
  uint64_t new_mask = new_cap - 1;
  for (uint64_t i = 0; i <= s->mask; ++i) {
    uint64_t key = s->keys[i].load(std::memory_order_relaxed);
    if (key == 0) continue;
    uint64_t idx = home_of(key, new_mask);
    while (nk[idx].load(std::memory_order_relaxed) != 0) {
      idx = (idx + 1) & new_mask;
    }
    np[idx].store(s->parents[i].load(std::memory_order_relaxed),
                  std::memory_order_relaxed);
    nk[idx].store(key, std::memory_order_relaxed);
  }
  s->keys.swap(nk);
  s->parents.swap(np);
  s->mask = new_mask;
}

// Insert fp with parent; returns 1 if newly inserted, 0 if already present.
// (-1 "table full" is retained in the ABI but no longer reachable: the set
// grows at 3/4 load.)
int32_t sr_fpset_insert(void* set_ptr, uint64_t fp, uint64_t parent) {
  FpSet* s = static_cast<FpSet*>(set_ptr);
  for (;;) {
    uint64_t observed_mask;
    {
      std::shared_lock<std::shared_mutex> rg(s->resize_mx);
      observed_mask = s->mask;
      if (!needs_grow(s)) {
        uint64_t idx = home_of(fp, s->mask);
        // Bounded: a full sweep without an empty slot or a match means
        // concurrent inserters filled the table after the load check —
        // fall through to grow() instead of spinning under the shared
        // lock (which would block the grower forever).  Slots never
        // empty, so a clean sweep is conclusive.
        for (uint64_t probes = 0; probes <= s->mask; ++probes) {
          std::mutex& m = s->locks[idx & 255];
          {
            std::lock_guard<std::mutex> g(m);
            uint64_t cur = s->keys[idx].load(std::memory_order_acquire);
            if (cur == 0) {
              s->parents[idx].store(parent, std::memory_order_relaxed);
              // Release: the parent store is visible before the key appears.
              s->keys[idx].store(fp, std::memory_order_release);
              s->count.fetch_add(1, std::memory_order_relaxed);
              return 1;
            }
            if (cur == fp) {
              return 0;
            }
          }
          idx = (idx + 1) & s->mask;
        }
      }
    }
    grow(s, observed_mask);
  }
}

// Returns 1 and writes *parent_out if present; 0 otherwise.
int32_t sr_fpset_get_parent(void* set_ptr, uint64_t fp, uint64_t* parent_out) {
  FpSet* s = static_cast<FpSet*>(set_ptr);
  std::shared_lock<std::shared_mutex> rg(s->resize_mx);
  uint64_t idx = home_of(fp, s->mask);
  for (uint64_t probes = 0; probes <= s->mask; ++probes) {
    uint64_t cur = s->keys[idx].load(std::memory_order_acquire);
    if (cur == 0) {
      return 0;
    }
    if (cur == fp) {
      *parent_out = s->parents[idx].load(std::memory_order_relaxed);
      return 1;
    }
    idx = (idx + 1) & s->mask;
  }
  return 0;
}

int32_t sr_fpset_contains(void* set_ptr, uint64_t fp) {
  uint64_t unused;
  return sr_fpset_get_parent(set_ptr, fp, &unused);
}

// --- direct 2pc hot-loop BFS (the honest native denominator) ----------------
//
// The bench's vs_baseline ratio divides by this package's pure-Python BFS;
// this function is the native bound that framing cites (bench.py's
// `denominator_native` phase): a single-threaded C++ BFS of the direct
// two-phase-commit model — successor generation, 64-bit fingerprinting
// (the mixer above, bit-identical to the framework's), and dedup into an
// open-addressing visited set.  No property evaluation, no path
// reconstruction, no parent tracking: an UPPER bound on what a native
// single-thread checker's inner loop achieves, by construction.
//
// The packed encoding is models/twophase_compiled.py's, word for word:
//   w0: RM states, 2 bits each at bit 2*i (WORKING=0 / PREPARED=1 /
//       COMMITTED=2 / ABORTED=3); TM state (INIT=0/COMMITTED=1/ABORTED=2)
//       at bit 24.
//   w1: tm_prepared bitmap at [0, n); Prepared(i) message at bit n+i;
//       Commit at 2n; Abort at 2n+1.
// so the golden counts (288 at 3 RMs, 8,832 at 5, 61,515,776 at 10 —
// examples/2pc.rs + the suite pins) gate correctness end to end.

namespace {

// Minimal single-thread open-addressing fp set: the leanest possible
// dedup hot loop (the concurrent FpSet above pays stripe locks and
// atomics this single-thread bound should not).
struct LocalFpSet {
  std::vector<uint64_t> keys;  // 0 = empty (fps are nonzero)
  uint64_t mask;
  uint64_t count = 0;

  explicit LocalFpSet(uint64_t cap_pow2)
      : keys(cap_pow2, 0), mask(cap_pow2 - 1) {}

  void grow() {
    std::vector<uint64_t> old;
    old.swap(keys);
    keys.assign((mask + 1) * 2, 0);
    mask = mask * 2 + 1;
    for (uint64_t key : old) {
      if (key == 0) continue;
      uint64_t idx = home_of(key, mask);
      while (keys[idx] != 0) idx = (idx + 1) & mask;
      keys[idx] = key;
    }
  }

  // True iff newly inserted.
  bool insert(uint64_t fp) {
    if (count * 2 >= mask + 1) grow();
    uint64_t idx = home_of(fp, mask);
    for (;;) {
      uint64_t cur = keys[idx];
      if (cur == 0) {
        keys[idx] = fp;
        ++count;
        return true;
      }
      if (cur == fp) return false;
      idx = (idx + 1) & mask;
    }
  }
};

inline uint64_t tp_fp(uint64_t state) {
  uint32_t words[2] = {static_cast<uint32_t>(state),
                       static_cast<uint32_t>(state >> 32)};
  return sr_fp64_words(words, 2);
}

}  // namespace

// Exhaustive single-threaded BFS of direct 2pc with n_rms RMs (<= 12, the
// packed layout's bound).  Writes unique/generated/depth counts; returns
// 0 on completion, -1 on bad arguments or when unique states exceed
// max_unique (0 = unlimited) — a caller-supplied memory guard, not an
// error of the model.
int32_t sr_twophase_bfs(uint32_t n_rms, uint64_t max_unique,
                        uint64_t* unique_out, uint64_t* generated_out,
                        uint64_t* depth_out) {
  if (n_rms == 0 || n_rms > 12) return -1;
  const uint32_t n = n_rms;
  const uint32_t tm_shift = 24;
  const uint32_t prepared_mask = (1u << n) - 1;
  const uint64_t commit_bit = 1ull << (32 + 2 * n);
  const uint64_t abort_bit = 1ull << (32 + 2 * n + 1);

  LocalFpSet seen(1 << 16);
  std::vector<uint64_t> frontier, next;
  // depth counts states on the deepest path (init level = 1), the
  // framework's max_depth convention (suite pin: 2pc(10) -> 32).
  uint64_t generated = 0, depth = 1;

  const uint64_t init = 0;  // all RMs WORKING, TM INIT, no msgs
  seen.insert(tp_fp(init));
  frontier.push_back(init);
  ++generated;  // init states count, like the framework's state_count

  auto emit = [&](uint64_t s) {
    ++generated;
    if (seen.insert(tp_fp(s))) next.push_back(s);
  };

  while (!frontier.empty()) {
    if (max_unique != 0 && seen.count > max_unique) return -1;
    next.clear();
    for (uint64_t s : frontier) {
      const uint32_t w0 = static_cast<uint32_t>(s);
      const uint32_t w1 = static_cast<uint32_t>(s >> 32);
      const bool tm_init = ((w0 >> tm_shift) & 3u) == 0;
      const bool all_prepared = (w1 & prepared_mask) == prepared_mask;
      const bool commit_msg = (s & commit_bit) != 0;
      const bool abort_msg = (s & abort_bit) != 0;
      const uint64_t tm_cleared = s & ~(3ull << tm_shift);

      if (tm_init && all_prepared) {  // TmCommit
        emit((tm_cleared | (1ull << tm_shift)) | commit_bit);
      }
      if (tm_init) {  // TmAbort
        emit((tm_cleared | (2ull << tm_shift)) | abort_bit);
      }
      for (uint32_t rm = 0; rm < n; ++rm) {
        const uint32_t rm_bits = (w0 >> (2 * rm)) & 3u;
        const bool working = rm_bits == 0;
        const bool prep_msg = (w1 >> (n + rm)) & 1u;
        const uint64_t rm_cleared = s & ~(3ull << (2 * rm));
        if (tm_init && prep_msg) {  // TmRcvPrepared(rm)
          emit(s | (1ull << (32 + rm)));
        }
        if (working) {  // RmPrepare(rm)
          emit((rm_cleared | (1ull << (2 * rm))) |
               (1ull << (32 + n + rm)));
        }
        if (working) {  // RmChooseToAbort(rm)
          emit(rm_cleared | (3ull << (2 * rm)));
        }
        if (commit_msg) {  // RmRcvCommitMsg(rm)
          emit(rm_cleared | (2ull << (2 * rm)));
        }
        if (abort_msg) {  // RmRcvAbortMsg(rm)
          emit(rm_cleared | (3ull << (2 * rm)));
        }
      }
    }
    if (next.empty()) break;
    ++depth;
    frontier.swap(next);
  }

  if (unique_out) *unique_out = seen.count;
  if (generated_out) *generated_out = generated;
  if (depth_out) *depth_out = depth;
  return 0;
}

}  // extern "C"
