"""Multi-chip sharded wavefront: golden-count and discovery-set parity with
the host oracle on the 8-device virtual CPU mesh (conftest sets
xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twophase import TwoPhaseSys  # noqa: E402
from tests.test_tpu_wavefront import TrapCounter  # noqa: E402


def _mesh(n):
    # The virtual CPU mesh (conftest forces 8 host devices); the default
    # backend may be a single real TPU behind a tunnel.
    devices = jax.devices("cpu")
    assert len(devices) >= n, f"need {n} CPU devices, have {len(devices)}"
    return jax.sharding.Mesh(np.array(devices[:n]), ("shards",))


@pytest.mark.slow
def test_twophase3_sharded_parity_8_devices():
    model = TwoPhaseSys(rm_count=3)
    host = model.checker().spawn_bfs().join()
    sh = (
        model.checker()
        .spawn_tpu_sharded(mesh=_mesh(8), capacity=1 << 14, chunk_size=1 << 8)
        .join()
    )
    assert sh.unique_state_count() == host.unique_state_count() == 288
    assert sh.state_count() == host.state_count()
    assert sh.max_depth() == host.max_depth()
    assert sorted(sh.discoveries()) == sorted(host.discoveries())
    for _name, path in sh.discoveries().items():
        assert len(path) >= 1  # building a Path re-executes the host model


def test_eventually_sharded_parity():
    model = TrapCounter()
    host = model.checker().spawn_bfs().join()
    sh = (
        model.checker()
        .spawn_tpu_sharded(mesh=_mesh(4), capacity=1 << 13, chunk_size=1 << 4)
        .join()
    )
    assert sh.unique_state_count() == host.unique_state_count()
    assert sorted(sh.discoveries()) == sorted(host.discoveries())
    assert sh.discoveries()["reaches limit"].last_state() == model.trap_state


@pytest.mark.slow
def test_sharded_levels_span_multiple_chunks():
    """2pc(5): 8,832 states whose peak level (~2,000 wide globally) spans
    several 64-state chunks per shard — full parity with the host oracle
    through the fused sharded loop."""
    model = TwoPhaseSys(rm_count=5)
    tpu = (
        model.checker()
        .spawn_tpu_sharded(mesh=_mesh(8), capacity=1 << 16, chunk_size=1 << 6)
        .join()
    )
    host = model.checker().spawn_bfs().join()
    assert tpu.unique_state_count() == host.unique_state_count() == 8832
    assert tpu.state_count() == host.state_count()
    assert tpu.max_depth() == host.max_depth()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())


@pytest.mark.slow
def test_sharded_extreme_skew_tiny_model():
    """11 states spread over 8 shards: most shards run empty chunks most
    levels (hash-random ownership skew at its worst); counts and
    discoveries still match the host."""
    from stateright_tpu.models.ping_pong import PingPongCfg
    from stateright_tpu.models.ping_pong_compiled import compiled_ping_pong

    model = PingPongCfg(maintains_history=False, max_nat=5).into_model()
    tpu = (
        model.checker()
        .spawn_tpu_sharded(
            mesh=_mesh(8),
            capacity=1 << 13,
            chunk_size=1 << 5,
            compiled=compiled_ping_pong(model),
        )
        .join()
    )
    host = (
        PingPongCfg(maintains_history=False, max_nat=5)
        .into_model()
        .checker()
        .spawn_bfs()
        .join()
    )
    assert tpu.unique_state_count() == host.unique_state_count() == 11
    assert tpu.state_count() == host.state_count()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())


@pytest.mark.slow
def test_sharded_paxos_golden():
    """The flagship model through the multi-chip engine: paxos check 2 on
    an 8-device mesh reproduces the reference golden 16,668
    (examples/paxos.rs:328) with the host oracle's discovery set."""
    from stateright_tpu.actor import Network
    from stateright_tpu.models.paxos import PaxosModelCfg

    model = PaxosModelCfg(
        client_count=2,
        server_count=3,
        network=Network.new_unordered_nonduplicating(),
    ).into_model()
    tpu = (
        model.checker()
        .spawn_tpu_sharded(mesh=_mesh(8), capacity=1 << 16, chunk_size=1 << 8)
        .join()
    )
    assert tpu.unique_state_count() == 16_668
    host = (
        PaxosModelCfg(
            client_count=2,
            server_count=3,
            network=Network.new_unordered_nonduplicating(),
        )
        .into_model()
        .checker()
        .spawn_bfs()
        .join()
    )
    assert tpu.state_count() == host.state_count()
    assert tpu.max_depth() == host.max_depth()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())


def test_one_shard_mesh_elides_exchange_and_matches_host():
    """The 1-shard mesh traces the exchange-elided branch (no bucket/
    sort/all_to_all) — it must still match the host oracle exactly and
    say so in the accounting."""
    import jax
    import numpy as np

    from stateright_tpu.models.twophase import TwoPhaseSys

    mesh = jax.sharding.Mesh(np.array(jax.devices("cpu")[:1]), ("shards",))
    model = TwoPhaseSys(rm_count=3)
    host = TwoPhaseSys(rm_count=3).checker().spawn_bfs().join()
    c = (
        model.checker()
        .spawn_tpu_sharded(mesh=mesh, capacity=1 << 13, chunk_size=1 << 6)
        .join()
    )
    assert c.unique_state_count() == host.unique_state_count() == 288
    assert c.state_count() == host.state_count()
    assert c.max_depth() == host.max_depth()
    assert sorted(c.discoveries()) == sorted(host.discoveries())
    acc = c.accounting()
    assert acc["exchange_elided"] is True
    assert acc["all_to_all_bytes_total"] == 0
    assert acc["exchange_occupancy"] == 0.0


def test_bucketed_exchange_fingerprint_pin_vs_single_chip():
    """The bucketed exchange must not change WHAT is discovered, only
    the buffers it rides in: the sharded discovery SET (sorted state
    fingerprints) is bit-identical to the fused single-chip engine's at
    2 and 4 virtual shards, and the accounting's byte totals derive from
    the actual bucket geometry (occupancy × transmitted = useful)."""
    model = TwoPhaseSys(rm_count=3)
    single = (
        model.checker()
        .spawn_tpu(capacity=1 << 13, max_frontier=1 << 6)
        .join()
    )
    fps = single.discovered_fingerprints()
    assert len(fps) == single.unique_state_count() == 288
    for n in (2, 4):
        sh = (
            TwoPhaseSys(rm_count=3).checker()
            .spawn_tpu_sharded(
                mesh=_mesh(n), capacity=1 << 13, chunk_size=1 << 6
            )
            .join()
        )
        assert np.array_equal(sh.discovered_fingerprints(), fps)
        acc = sh.accounting()
        from stateright_tpu.parallel.compiled import compiled_model_for

        w = compiled_model_for(model).state_width
        assert acc["all_to_all_bytes_per_wave_per_shard"] == (
            n * acc["exchange_bucket_lanes"] * (w + 3) * 4
        )
        assert acc["all_to_all_bytes_total"] == (
            acc["waves"] * n * acc["all_to_all_bytes_per_wave_per_shard"]
        )
        # occupancy × transmitted = useful bytes (the accounting's own
        # stated identity, now over the bucketed denominator).
        assert acc["exchange_occupancy"] * acc["all_to_all_bytes_total"] \
            == pytest.approx(acc["exchange_payload_bytes_total"], rel=1e-9)


def test_bucket_overflow_retry_path_forced(tmp_path):
    """A deliberately tiny bucket slack forces the overflow-flag +
    retry-at-next-rung path: the run journals a ``grow`` event with
    flag 32, climbs the slack ladder, and still lands the exact
    single-chip discovery set — on the fused AND the traced loop."""
    from stateright_tpu.runtime.journal import read_journal

    model = TwoPhaseSys(rm_count=4)
    single = (
        model.checker()
        .spawn_tpu(capacity=1 << 14, max_frontier=1 << 7)
        .join()
    )
    fps = single.discovered_fingerprints()
    journal = str(tmp_path / "bucket_retry.jsonl")
    sh = (
        TwoPhaseSys(rm_count=4).checker()
        .spawn_tpu_sharded(
            mesh=_mesh(4), capacity=1 << 14, chunk_size=1 << 7,
            bucket_slack=1, journal=journal,
        )
        .join()
    )
    assert sh.unique_state_count() == 1568
    assert np.array_equal(sh.discovered_fingerprints(), fps)
    acc = sh.accounting()
    assert acc["bucket_retries"] >= 1
    assert acc["bucket_slack"] > 1  # the ladder actually climbed
    grows = [e for e in read_journal(journal) if e["event"] == "grow"]
    assert grows and any(e["flags"] & 32 for e in grows)

    traced = (
        TwoPhaseSys(rm_count=4).checker()
        .spawn_tpu_sharded(
            mesh=_mesh(4), capacity=1 << 14, chunk_size=1 << 7,
            bucket_slack=1, trace=True,
        )
        .join()
    )
    assert traced.unique_state_count() == 1568
    assert np.array_equal(traced.discovered_fingerprints(), fps)
    assert traced.accounting()["bucket_retries"] >= 1


@pytest.mark.slow
def test_bucketed_paxos_golden_all_mesh_sizes():
    """The ISSUE-8 acceptance pin: paxos c=2 (reference golden 16,668)
    through the bucketed sharded engine at 1/2/4/8 virtual shards is
    discovery-set bit-identical to the fused single-chip engine, and at
    8 shards the transmitted all_to_all total is ≤ 250 MB (vs 1,233 MB
    with the fixed [n, U] buffers) with measured lane occupancy ≥ 2%.
    An extra 8-shard run with a deliberately tiny slack factor forces
    the bucket-overflow retry path and must land the same set."""
    from stateright_tpu.actor import Network
    from stateright_tpu.models.paxos import PaxosModelCfg

    def paxos2():
        return PaxosModelCfg(
            client_count=2,
            server_count=3,
            network=Network.new_unordered_nonduplicating(),
        ).into_model()

    single = (
        paxos2().checker()
        .spawn_tpu(capacity=1 << 16, max_frontier=1 << 9)
        .join()
    )
    assert single.unique_state_count() == 16_668
    fps = single.discovered_fingerprints()
    for n in (1, 2, 4, 8):
        sh = (
            paxos2().checker()
            .spawn_tpu_sharded(
                mesh=_mesh(n), capacity=1 << 16, chunk_size=1 << 9
            )
            .join()
        )
        assert sh.unique_state_count() == 16_668
        assert np.array_equal(sh.discovered_fingerprints(), fps)
        if n == 8:
            acc = sh.accounting()
            assert acc["all_to_all_bytes_total"] <= 250_000_000
            assert acc["exchange_occupancy"] >= 0.02
    # Forced overflow-retry: same golden, same set.  The 2-shard mesh
    # is the forcing one — its per-destination candidate peaks (~450 per
    # wave) overflow the minimum 128-lane bucket, where the 8-shard
    # split (~80 per destination) fits even the tiny-slack bucket.
    forced = (
        paxos2().checker()
        .spawn_tpu_sharded(
            mesh=_mesh(2), capacity=1 << 16, chunk_size=1 << 9,
            bucket_slack=1,
        )
        .join()
    )
    assert forced.unique_state_count() == 16_668
    assert np.array_equal(forced.discovered_fingerprints(), fps)
    assert forced.accounting()["bucket_retries"] >= 1


def test_owner_mix_host_matches_device():
    """Seeding routes init states by the HOST owner mix while the run
    loop's exchange routes by the DEVICE mix — a divergence would seed
    states into the wrong shard's table and silently duplicate
    exploration, so the two are pinned bit-identical here."""
    import jax.numpy as jnp
    import numpy as np

    from stateright_tpu.parallel.sharded import _owner_mix, _owner_mix_host

    rng = np.random.default_rng(11)
    hi = rng.integers(0, 2**32, size=4096, dtype=np.uint32)
    lo = rng.integers(0, 2**32, size=4096, dtype=np.uint32)
    dev = np.asarray(_owner_mix(jnp.asarray(hi), jnp.asarray(lo)))
    host = np.array(
        [_owner_mix_host(int(h), int(l)) for h, l in zip(hi, lo)],
        np.uint32,
    )
    assert np.array_equal(dev, host)
