"""Multi-chip sharded wavefront: golden-count and discovery-set parity with
the host oracle on the 8-device virtual CPU mesh (conftest sets
xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twophase import TwoPhaseSys  # noqa: E402
from tests.test_tpu_wavefront import TrapCounter  # noqa: E402


def _mesh(n):
    # The virtual CPU mesh (conftest forces 8 host devices); the default
    # backend may be a single real TPU behind a tunnel.
    devices = jax.devices("cpu")
    assert len(devices) >= n, f"need {n} CPU devices, have {len(devices)}"
    return jax.sharding.Mesh(np.array(devices[:n]), ("shards",))


def test_twophase3_sharded_parity_8_devices():
    model = TwoPhaseSys(rm_count=3)
    host = model.checker().spawn_bfs().join()
    sh = (
        model.checker()
        .spawn_tpu_sharded(mesh=_mesh(8), capacity=1 << 14, chunk_size=1 << 8)
        .join()
    )
    assert sh.unique_state_count() == host.unique_state_count() == 288
    assert sh.state_count() == host.state_count()
    assert sh.max_depth() == host.max_depth()
    assert sorted(sh.discoveries()) == sorted(host.discoveries())
    for _name, path in sh.discoveries().items():
        assert len(path) >= 1  # building a Path re-executes the host model


def test_eventually_sharded_parity():
    model = TrapCounter()
    host = model.checker().spawn_bfs().join()
    sh = (
        model.checker()
        .spawn_tpu_sharded(mesh=_mesh(4), capacity=1 << 13, chunk_size=1 << 4)
        .join()
    )
    assert sh.unique_state_count() == host.unique_state_count()
    assert sorted(sh.discoveries()) == sorted(host.discoveries())
    assert sh.discoveries()["reaches limit"].last_state() == model.trap_state
