"""Device gates for the single-copy register — the *violation* workload:
with two servers its reachable space contains genuinely non-linearizable
histories (reference examples/single-copy-register.rs:111 demonstrates the
counterexample), so the shared device linearizability DP is exercised on
reachable violations, not just synthetic ones.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.actor import Network  # noqa: E402
from stateright_tpu.actor.model import Deliver  # noqa: E402
from stateright_tpu.core.has_discoveries import HasDiscoveries  # noqa: E402
from stateright_tpu.models.single_copy_compiled import (  # noqa: E402
    SingleCopyCompiled,
)
from stateright_tpu.models.single_copy_register import (  # noqa: E402
    SingleCopyModelCfg,
)
from stateright_tpu.ops.fingerprint import fingerprint  # noqa: E402


def sc_model(client_count: int, server_count: int):
    return SingleCopyModelCfg(
        client_count=client_count,
        server_count=server_count,
        network=Network.new_unordered_nonduplicating(),
    ).into_model()


def enumerate_reachable(model):
    seen = {}
    frontier = list(model.init_states())
    for s in frontier:
        seen[fingerprint(s)] = s
    while frontier:
        nxt = []
        for s in frontier:
            acts = []
            model.actions(s, acts)
            for a in acts:
                ns = model.next_state(s, a)
                if ns is None:
                    continue
                fp = fingerprint(ns)
                if fp not in seen:
                    seen[fp] = ns
                    nxt.append(ns)
        frontier = nxt
    return list(seen.values())


@pytest.mark.parametrize("c,s", [(1, 1), (2, 1), (2, 2)])
def test_full_reachable_differential(c, s):
    model = sc_model(c, s)
    cm = SingleCopyCompiled(model)
    states = enumerate_reachable(model)
    enc = np.stack([cm.encode(st) for st in states]).astype(np.uint32)
    for st in states:
        assert cm.decode(cm.encode(st)) == st
    lane_fn = jax.jit(
        jax.vmap(
            lambda st: jax.vmap(lambda k: cm._deliver_lane(st, k))(
                jnp.arange(cm.m, dtype=jnp.uint32)
            )
        )
    )
    nexts, valid, flags = (np.asarray(x) for x in lane_fn(jnp.asarray(enc)))
    assert not flags.any()
    for bi, st in enumerate(states):
        host_map = {}
        for env in st.network.iter_deliverable():
            ns = model.next_state(st, Deliver(env.src, env.dst, env.msg))
            host_map[cm._env_code(env)] = None if ns is None else cm.encode(ns)
        for k in range(cm.m):
            code = int(enc[bi][2 + k])
            if code == 0:
                assert not valid[bi, k]
                continue
            want = host_map[code]
            if want is None:
                assert not valid[bi, k]
            else:
                assert valid[bi, k] and np.array_equal(nexts[bi, k], want)
    conds = np.asarray(jax.jit(jax.vmap(cm.property_conds))(jnp.asarray(enc)))
    from stateright_tpu.models.single_copy_register import NULL_VALUE

    for bi, st in enumerate(states):
        assert bool(conds[bi, 0]) == (
            st.history.serialized_history() is not None
        )
        assert bool(conds[bi, 1]) == any(
            type(e.msg).__name__ == "GetOk" and e.msg.value != NULL_VALUE
            for e in st.network.iter_deliverable()
        )


def test_one_server_is_linearizable_golden_93():
    tpu = (
        sc_model(2, 1)
        .checker()
        .spawn_tpu(capacity=1 << 12, max_frontier=1 << 7)
        .join()
    )
    assert tpu.unique_state_count() == 93  # single-copy-register.rs:111
    assert sorted(tpu.discoveries()) == ["value chosen"]
    tpu.assert_properties()


def test_two_servers_violation_found_on_device():
    """The device DP discovers the genuine reachable linearizability
    violation, and the counterexample trace replays on the host model.
    Once every property has a discovery, expansion winds down (the
    reference's awaiting-discoveries rule, src/checker/bfs.rs:231-281) —
    exact counts in that regime are order-dependent, like the reference's
    racy thread-pool counts, so the assertions are on the discovery set,
    the trace, and the wind-down itself."""
    never = HasDiscoveries.all_of(["__not_a_property__"])
    tpu = (
        sc_model(2, 2)
        .checker()
        .finish_when(never)
        .spawn_tpu(capacity=1 << 12, max_frontier=1 << 7)
        .join()
    )
    host = sc_model(2, 2).checker().finish_when(never).spawn_bfs().join()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries()) == [
        "linearizable",
        "value chosen",
    ]
    # Both engines stopped well short of the 62-state full space.
    assert tpu.unique_state_count() < 62
    assert host.unique_state_count() < 62
    path = tpu.discoveries()["linearizable"]
    assert path.last_state().history.serialized_history() is None


@pytest.mark.slow
def test_spawn_tpu_single_copy_c3_matches_host():
    """3 clients / 1 server — first config past the round-2 client cap."""
    model = sc_model(3, 1)
    tpu = (
        model.checker().spawn_tpu(capacity=1 << 14, max_frontier=1 << 8).join()
    )
    host = sc_model(3, 1).checker().spawn_bfs().join()
    assert host.unique_state_count() == 4_243
    assert tpu.unique_state_count() == 4_243
    assert tpu.max_depth() == host.max_depth() == 13
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())


@pytest.mark.slow
def test_spawn_tpu_single_copy_check4_depth_bounded():
    """The reference bench workload `single-copy-register check 4`
    (bench.sh:29: 4 clients, 1 server), depth-bounded for suite runtime;
    the full-space parity (400,233 unique / depth 17, host-measured) runs
    on real hardware via the tpu-marked test below."""
    host = (
        sc_model(4, 1)
        .checker()
        .target_max_depth(11)
        .spawn_bfs()
        .join()
    )
    tpu = (
        sc_model(4, 1)
        .checker()
        .target_max_depth(11)
        .spawn_tpu(capacity=1 << 19, max_frontier=1 << 10)
        .join()
    )
    assert host.unique_state_count() == 33_849
    assert tpu.unique_state_count() == 33_849
    assert tpu.max_depth() == host.max_depth() == 11
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())


@pytest.mark.tpu
def test_spawn_tpu_single_copy_check4_full_device():
    """Full `single-copy-register check 4` on real hardware, against the
    host-measured golden (400,233 unique / depth 17)."""
    tpu = (
        sc_model(4, 1)
        .checker()
        .spawn_tpu(capacity=1 << 21, max_frontier=1 << 11)
        .join()
    )
    assert tpu.unique_state_count() == 400_233
    assert tpu.max_depth() == 17
