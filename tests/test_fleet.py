"""Fleet serving (fleet/, docs/SERVING.md "Fleet mode"): the durable
multi-worker store, gang batching, heterogeneous placement, and
preemption.

The two PR acceptance gates live here: gang parity (a K>=4 gang's
per-job fingerprints and verdicts are bit-equal to K solo runs) and
durability (kill -9 a worker mid-job; a sibling requeues and completes
it with an identical result, and the fleet journal alone reconstructs
the history).  The CI fleet smoke re-runs both through real processes.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.fleet import (  # noqa: E402
    DONE, FleetService, FleetStore, FleetWorker, QUEUED, QuotaExceeded,
    RUNNING, gang_eligibility, is_big, placement_order, run_gang,
    worker_takes,
)
from stateright_tpu.models.fixtures import (  # noqa: E402
    CapCounter, GridWalk, TrapCounter,
)
from stateright_tpu.serve.jobs import JobSpec  # noqa: E402
from stateright_tpu.serve.portfolio import checker_summary  # noqa: E402

GRID = {"workload": "grid_walk", "engine": "tpu"}


def grid_spec(bound):
    return JobSpec.from_dict(dict(GRID, n=bound))


def drain(root, **kw):
    kw.setdefault("lease_sec", 5.0)
    kw.setdefault("poll_interval", 0.01)
    w = FleetWorker(str(root), **kw)
    w.run(once=True)
    return w


# --- durable store -----------------------------------------------------------


def test_journal_alone_reconstructs_history(tmp_path):
    store = FleetStore(str(tmp_path))
    jid = store.submit(grid_spec(3), tenant="acme", priority=2)
    drain(tmp_path)
    # A fresh store instance (a different process, as far as the store
    # is concerned) folds the same journal to the same state.
    again = FleetStore(str(tmp_path)).fold()
    rec = again.jobs[jid]
    assert rec["state"] == DONE
    assert rec["tenant"] == "acme" and rec["priority"] == 2
    assert rec["worker"] is not None
    result = FleetStore(str(tmp_path)).read_result(jid)
    assert result["unique_state_count"] == 16  # (bound+1)^2


def test_claim_race_exactly_one_winner(tmp_path):
    a = FleetStore(str(tmp_path))
    b = FleetStore(str(tmp_path))
    a.submit(grid_spec(3))
    job_a = a.fold().queued()[0]
    job_b = b.fold().queued()[0]
    wins = [a.claim(job_a, worker="w-a"), b.claim(job_b, worker="w-b")]
    assert sorted(wins) == [False, True]
    events = [e["event"] for e in _events(tmp_path)]
    assert events.count("fleet_claimed") == 1
    # The loser's race is journaled, not silently swallowed.
    assert events.count("fleet_claim_lost") == 1
    assert a.fold().jobs[job_a["id"]]["state"] == RUNNING


def test_quota_refuses_admission_at_limit(tmp_path):
    store = FleetStore(str(tmp_path))
    store.set_quota("acme", 2)
    store.submit(grid_spec(3), tenant="acme")
    store.submit(grid_spec(4), tenant="acme")
    with pytest.raises(QuotaExceeded):
        store.submit(grid_spec(5), tenant="acme")
    # Another tenant is unaffected; finishing work frees the quota.
    store.submit(grid_spec(5), tenant="other")
    drain(tmp_path)
    store.submit(grid_spec(5), tenant="acme")


def test_cancel_queued_job_without_worker(tmp_path):
    store = FleetStore(str(tmp_path))
    jid = store.submit(grid_spec(3))
    assert store.cancel(jid) is True
    assert store.fold().jobs[jid]["state"] == "cancelled"
    assert store.cancel(jid) is False  # already terminal
    drain(tmp_path)  # a worker must not resurrect it
    assert store.fold().jobs[jid]["state"] == "cancelled"


def _events(root):
    out = []
    with open(os.path.join(str(root), "journal.jsonl")) as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


# --- kill -9 durability (the acceptance gate) --------------------------------


def test_sigkill_mid_job_requeued_by_sibling_with_identical_result(
    tmp_path,
):
    """A worker claims a job and dies with kill -9 (no atexit, no
    journal flush beyond what already hit disk).  After one lease
    period a sibling requeues and completes it; the result matches a
    clean run bit-for-bit."""
    store = FleetStore(str(tmp_path), lease_sec=1.0)
    jid = store.submit(grid_spec(5))
    # The doomed worker: claims + leases, then SIGKILLs itself mid-job.
    script = textwrap.dedent(f"""
        import os, signal
        from stateright_tpu.fleet import FleetStore
        store = FleetStore({str(tmp_path)!r}, lease_sec=1.0)
        job = store.fold().queued()[0]
        assert store.claim(job, worker="doomed@test")
        store.lease(job["id"], job["attempt"])
        os.kill(os.getpid(), signal.SIGKILL)
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", script], env=env, timeout=120,
    )
    assert proc.returncode == -signal.SIGKILL
    assert store.fold().jobs[jid]["state"] == RUNNING  # orphaned claim
    time.sleep(1.1)  # one lease period
    sibling = FleetWorker(str(tmp_path), lease_sec=1.0,
                          poll_interval=0.01)
    sibling.run(once=True)
    view = store.fold()
    rec = view.jobs[jid]
    assert rec["state"] == DONE
    assert rec["attempt"] == 1
    assert view.counters["fleet_lease_requeues"] >= 1
    requeued = store.read_result(jid)
    # Identical verdict to a clean run of the same spec.
    clean_store = FleetStore(str(tmp_path / "clean"))
    cid = clean_store.submit(grid_spec(5))
    drain(tmp_path / "clean")
    clean = clean_store.read_result(cid)
    for key in ("unique_state_count", "state_count", "max_depth",
                "violation", "properties"):
        assert requeued[key] == clean[key], key


def test_orphan_claim_requeued_when_claimant_died_before_lease(tmp_path):
    """Sharper crash window: the claim lock exists but the fold shows
    QUEUED (the claimant died between taking the lock and journaling).
    The orphan sweep must free it."""
    store = FleetStore(str(tmp_path), lease_sec=0.2)
    jid = store.submit(grid_spec(3))
    job = store.fold().queued()[0]
    lock = os.path.join(str(tmp_path), "locks", f"{jid}.claim.0")
    with open(lock, "w") as fh:
        fh.write("dead@test")
    past = time.time() - 5.0
    os.utime(lock, (past, past))
    assert store.requeue_expired() == 1
    rec = store.fold().jobs[jid]
    assert rec["state"] == QUEUED and rec["attempt"] == 1
    drain(tmp_path, lease_sec=0.2)
    assert store.fold().jobs[jid]["state"] == DONE


# --- gang batching (the parity gate) -----------------------------------------


def _solo_summaries(models):
    out = []
    for model in models:
        checker = model.checker().spawn_tpu(
            capacity=1 << 12, max_frontier=1 << 7
        )
        checker.join()
        out.append((checker_summary(checker),
                    checker.discovered_fingerprints()))
    return out


def _gang_members(models):
    members = []
    for i, model in enumerate(models):
        cm = model.compiled()
        members.append({
            "tag": i, "model": model, "cm": cm,
            "consts": cm.gang_constants(),
        })
    return members


def test_gang_of_four_bit_equal_to_solo_runs():
    """THE parity gate: one K=4 device dispatch produces, per member,
    the same discovered fingerprints, counts, depths, and property
    verdicts as four solo engine runs."""
    bounds = (3, 5, 6, 8)
    models = [GridWalk(bound=b) for b in bounds]
    results, waves = run_gang(_gang_members(models))
    assert waves > 0
    solos = [s for s, _ in _solo_summaries(models)]
    solo_fps = [f for _, f in _solo_summaries(models)]
    for (tag, checker, reason), solo, fps in zip(
        results, solos, solo_fps
    ):
        assert checker is not None, reason
        assert checker_summary(checker) == solo
        np.testing.assert_array_equal(
            checker.discovered_fingerprints(), fps
        )


def test_gang_mixed_verdicts_violating_member_isolated():
    """A violating member's verdict (and VIOLATION_RC-worthy
    ``violation`` field) matches its solo run while its gang-mates
    stay clean — no verdict bleed across the jobs axis."""
    params = [(4, 10), (12, 8), (6, 6), (9, 20)]
    models = [CapCounter(limit=lim, cap=cap) for lim, cap in params]
    results, _ = run_gang(_gang_members(models))
    solos = _solo_summaries(models)
    for (tag, checker, _), (solo, fps) in zip(results, solos):
        assert checker_summary(checker) == solo
        np.testing.assert_array_equal(
            checker.discovered_fingerprints(), fps
        )
    # (12, 8) counts past its cap: that member alone reports it.
    violations = [
        checker_summary(c)["violation"] for _, c, _ in results
    ]
    assert violations == [None, "within cap", None, None]


def test_gang_member_overgrowing_geometry_is_ejected():
    models = [GridWalk(bound=2), GridWalk(bound=12)]
    results, _ = run_gang(_gang_members(models), max_frontier=8)
    small, big = results
    assert small[1] is not None  # completed inside the budget
    assert big[1] is None and "frontier" in big[2]
    assert checker_summary(small[1])["unique_state_count"] == 9


def test_gang_eligibility_reasons():
    ok, _ = gang_eligibility(grid_spec(4))
    assert ok is not None
    # Same family, different constants: compatible keys.
    ok2, _ = gang_eligibility(grid_spec(7))
    assert ok2 == ok
    ineligible = [
        dict(GRID, engine="bfs"),              # host engine
        dict(GRID, target_state_count=10),     # early-stop target
        dict(GRID, engine_kwargs={"resume_from": "x"}),  # non-geometry
        {"workload": "fixtures", "engine": "tpu"},  # EVENTUALLY props
    ]
    for spec in ineligible:
        compat, reason = gang_eligibility(JobSpec.from_dict(spec))
        assert compat is None and reason


def test_worker_gang_dispatch_ejects_and_requeues_solo(tmp_path):
    """Through the worker: a gang member that overgrows is requeued
    ``solo`` and completed by the next pass, never gang-planned again."""
    store = FleetStore(str(tmp_path))
    small = [store.submit(grid_spec(b)) for b in (2, 3, 4)]
    big = store.submit(grid_spec(12))  # frontier outgrows the gang's
    w = FleetWorker(str(tmp_path), lease_sec=5.0, poll_interval=0.01,
                    gang_max=8, gang_frontier=8)
    w.run(once=True)
    view = store.fold()
    assert all(view.jobs[j]["state"] == DONE for j in small + [big])
    assert view.jobs[big]["gang"] is None  # completed solo
    assert view.jobs[big]["solo"] is True
    assert view.counters["gang_ejects"] == 1
    assert view.counters["gang_dispatches"] >= 1
    gang_sizes = [
        len(e.get("jobs", ())) for e in _events(tmp_path)
        if e["event"] == "gang_dispatch"
    ]
    assert max(gang_sizes) >= 3
    assert store.read_result(big)["unique_state_count"] == 13 * 13


# --- placement ---------------------------------------------------------------


CPU_DESC = {"platform": "cpu", "device_kind": "cpu", "memory_mb": 4096,
            "engines": ["tpu", "tiered", "bfs", "dfs", "simulation",
                        "tpu_simulation"],
            "accept_big": False}
TPU_DESC = {"platform": "tpu", "device_kind": "TPU v4",
            "memory_mb": 32768,
            "engines": ["tpu", "tiered", "sharded", "tiered-sharded",
                        "bfs", "dfs", "simulation", "tpu_simulation"],
            "accept_big": False}


def _knob_history(tmp_path, label_prefix, unique):
    knob_dir = tmp_path / "knobs"
    knob_dir.mkdir(exist_ok=True)
    (knob_dir / "knobs.json").write_text(json.dumps({
        f"{label_prefix}|cpu|cpu|tpu-wavefront-v3": {
            "knobs": {"capacity": 1 << 12}, "unique": unique,
        },
    }))
    return str(knob_dir)


def test_big_jobs_reserved_for_tpu_workers(tmp_path):
    from stateright_tpu.serve.workloads import workload_label

    label = workload_label("grid_walk", 5, None, False)
    knobs = _knob_history(tmp_path, label, unique=1 << 21)
    spec = {"workload": "grid_walk", "n": 5, "engine": "tpu"}
    assert is_big(spec, knobs) is True
    job = {"spec": spec}
    assert worker_takes(job, CPU_DESC, knobs) is False
    assert worker_takes(job, TPU_DESC, knobs) is True
    assert worker_takes(job, dict(CPU_DESC, accept_big=True),
                        knobs) is True
    # Unknown workloads default small; huge explicit capacity is big.
    assert is_big({"workload": "grid_walk", "n": 9}, knobs) is False
    assert is_big({"workload": "grid_walk", "n": 9,
                   "engine_kwargs": {"capacity": 1 << 22}}, None) is True
    # Mesh engines are big AND need the capability.
    mesh = {"spec": {"workload": "grid_walk", "engine": "sharded"}}
    assert worker_takes(mesh, CPU_DESC, None) is False
    assert worker_takes(mesh, TPU_DESC, None) is True


def test_tpu_workers_drain_big_jobs_first(tmp_path):
    from stateright_tpu.serve.workloads import workload_label

    label = workload_label("grid_walk", 5, None, False)
    knobs = _knob_history(tmp_path, label, unique=1 << 21)
    small = {"id": "s", "spec": {"workload": "grid_walk", "n": 3},
             "priority": 5}
    big = {"id": "b", "spec": {"workload": "grid_walk", "n": 5},
           "priority": 0}
    queue = [small, big]  # priority-sorted: small first
    assert [j["id"] for j in placement_order(queue, TPU_DESC, knobs)] \
        == ["b", "s"]
    assert [j["id"] for j in placement_order(queue, CPU_DESC, knobs)] \
        == ["s"]


# --- preemption / resume -----------------------------------------------------


def test_preempted_job_resumes_from_snapshot_with_identical_result(
    tmp_path,
):
    """store.preempt's requeue-with-resume contract end-to-end: the
    next claimant spawns with ``resume_from=`` and the final result
    matches an uninterrupted run."""
    store = FleetStore(str(tmp_path))
    jid = store.submit(grid_spec(8))
    job = store.fold().queued()[0]
    assert store.claim(job, worker="preemptor@test")
    # A real partial run: stop early via target_state_count, snapshot.
    partial = (
        GridWalk(bound=8).checker().target_state_count(20)
        .spawn_tpu(capacity=1 << 12, max_frontier=1 << 7)
    )
    partial.join()
    assert partial.unique_state_count() < 81
    snap = store.snapshot_path(jid, job["attempt"])
    partial.save_snapshot(snap)
    store.preempt(job, snap, "higher-priority job queued")
    rec = store.fold().jobs[jid]
    assert rec["state"] == QUEUED and rec["attempt"] == 1
    assert rec["resume"] == snap
    assert store.fold().counters["fleet_preemptions"] == 1
    drain(tmp_path)
    result = store.read_result(jid)
    assert result["unique_state_count"] == 81
    assert result["violation"] is None


# --- fleet service (the unchanged HTTP surface) ------------------------------


def test_fleet_service_matches_handler_surface(tmp_path):
    svc = FleetService(str(tmp_path))
    view = svc.submit(dict(GRID, n=3, tenant="acme", priority=1))
    assert view.state == QUEUED
    assert svc.get(view.id).id == view.id
    assert svc.get("nope") is None
    drain(tmp_path)
    assert view.wait(10.0)
    snap = view.snapshot()
    assert snap["state"] == DONE
    assert snap["tenant"] == "acme"
    assert snap["result"]["unique_state_count"] == 16
    assert snap["worker"] is not None
    with pytest.raises(ValueError):
        svc.explore(view)
    m = svc.metrics()
    assert m["mode"] == "fleet"
    assert m["jobs"]["done"] == 1
    assert "fleet_claims" in m
    assert svc.status()["jobs"]["done"] == 1


def test_fleet_backed_http_server(tmp_path):
    import threading
    import urllib.request

    from stateright_tpu.serve.server import serve

    svc = serve(("127.0.0.1", 0), block=False,
                fleet_dir=str(tmp_path))
    try:
        host, port = svc.address[:2]
        base = f"http://{host}:{port}"

        def post(path, body):
            req = urllib.request.Request(
                base + path, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as resp:
                return json.loads(resp.read())

        def get(path):
            with urllib.request.urlopen(base + path) as resp:
                return json.loads(resp.read())

        created = post("/jobs", dict(GRID, n=3))
        assert created["state"] == QUEUED
        worker = threading.Thread(target=drain, args=(tmp_path,))
        worker.start()
        done = get(f"/jobs/{created['id']}/result?wait=60")
        worker.join()
        assert done["state"] == DONE
        assert done["result"]["unique_state_count"] == 16
        metrics = get("/.metrics")
        assert metrics["mode"] == "fleet"
        assert metrics["jobs"]["done"] == 1
        assert metrics["workers_alive"] >= 0
        assert get("/.status")["workloads"]
        assert len(get("/jobs")) == 1
    finally:
        svc.shutdown()


def test_fleet_report_and_watch_render(tmp_path):
    """The journal a fleet run leaves behind feeds report/watch: the
    fleet section carries the counters and the gang occupancy."""
    store = FleetStore(str(tmp_path))
    for b in (3, 4, 5, 6):
        store.submit(grid_spec(b))
    drain(tmp_path)
    from stateright_tpu.obs.report import analyze_journal, render_markdown
    from stateright_tpu.obs.watch import render_line, summarize_events

    journal = os.path.join(str(tmp_path), "journal.jsonl")
    report = analyze_journal(journal)
    assert report["kind"] == "fleet"
    fleet = report["fleet"]
    assert fleet["jobs"]["done"] == 4
    assert fleet["gang_occupancy"] == 4.0
    md = render_markdown(report)
    assert "## Fleet" in md and "gang occupancy" in md
    s = summarize_events(_events(tmp_path))
    assert s["fleet"]["done"] == 4
    line = render_line(s)
    assert "fleet done=4" in line and "gang_occ=4" in line


def test_portfolio_diversifies_across_fleet(tmp_path):
    """A portfolio submission expands into member jobs any worker can
    claim; the group resolves from the members' verdicts."""
    store = FleetStore(str(tmp_path))
    parent = store.submit(JobSpec.from_dict({
        "workload": "fixtures", "engine": "tpu",
        "portfolio": {"size": 3, "seed": 7},
    }))
    view = store.fold()
    members = [j for j in view.jobs.values() if j["group"] == parent]
    assert len(members) == 3
    assert view.jobs[parent]["portfolio_parent"] is True
    assert all(j["id"].startswith(parent + ".m") for j in members)
    # Parents are bookkeeping: never claimable.
    assert parent not in [j["id"] for j in view.queued()]
    drain(tmp_path)
    final = store.fold()
    assert final.jobs[parent]["state"] == DONE
    result = store.read_result(parent)
    # fixtures (TrapCounter) violates: the first violating member wins.
    assert result["violation"] is not None
