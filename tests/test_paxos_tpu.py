"""Device-kernel gates for the paxos flagship workload.

Three layers of defense, per docs/TPU_PAXOS_DESIGN.md:

1. step-kernel differential: device successor sets == host successor sets
   over the *entire* reachable space (C=1 exhaustively per-lane, C=2 as
   successor-set equality over all 16,668 states);
2. exact-linearizability differential: the on-device Wing&Gong-style
   subset-DP (`_device_linearizable`) agrees with the host
   ``LinearizabilityTester.serialized_history()`` on an exhaustive
   enumeration of consistent tester states — crucially including
   NON-linearizable ones, which the reachable paxos space never produces;
3. full-checker golden: ``spawn_tpu`` reproduces the reference's 16,668
   unique states (examples/paxos.rs:328) with a discovery set identical to
   the host oracle's.
"""

import numpy as np
import pytest

from stateright_tpu.actor import Id
from stateright_tpu.actor.model import Deliver
from stateright_tpu.models.paxos import PaxosModelCfg
from stateright_tpu.models.paxos_compiled import PaxosCompiled

from .test_paxos_compiled import enumerate_reachable, paxos_model


def lane_fn_for(cm):
    import jax
    import jax.numpy as jnp

    return jax.jit(
        jax.vmap(
            lambda st: jax.vmap(lambda k: cm._deliver_lane(st, k))(
                jnp.arange(cm.m, dtype=jnp.uint32)
            )
        )
    )


def test_step_differential_full_reachable_c1(reachable_c1):
    """Per-lane: validity, successor words, and flags for all 265 states."""
    import jax.numpy as jnp

    model = paxos_model(1)
    cm = PaxosCompiled(model)
    states = list(reachable_c1.values())
    enc = np.stack([cm.encode(s) for s in states]).astype(np.uint32)
    nexts, valid, flags = (
        np.asarray(x) for x in lane_fn_for(cm)(jnp.asarray(enc))
    )
    assert not flags.any()
    for bi, s in enumerate(states):
        host_map = {}
        for env in s.network.iter_deliverable():
            ns = model.next_state(s, Deliver(env.src, env.dst, env.msg))
            host_map[cm._env_code(env)] = None if ns is None else cm.encode(ns)
        for k in range(cm.m):
            code = int(enc[bi][cm._NET0 + k])
            if code == 0:
                assert not valid[bi, k]
                continue
            want = host_map[code]
            if want is None:
                assert not valid[bi, k], cm._env_of(code)
            else:
                assert valid[bi, k], cm._env_of(code)
                assert np.array_equal(nexts[bi, k], want), cm._env_of(code)


@pytest.mark.slow
def test_step_differential_full_reachable_c2(reachable_c2):
    """Successor-set equality over the full golden 16,668-state space."""
    import jax.numpy as jnp

    model = paxos_model(2)
    cm = PaxosCompiled(model)
    states = list(reachable_c2.values())
    enc = np.stack([cm.encode(s) for s in states]).astype(np.uint32)
    lane_fn = lane_fn_for(cm)
    bad = 0
    for off in range(0, len(states), 2048):
        chunk = enc[off : off + 2048]
        nexts, valid, flags = (
            np.asarray(x) for x in lane_fn(jnp.asarray(chunk))
        )
        assert not flags.any()
        for bi in range(len(chunk)):
            s = states[off + bi]
            host_succ = set()
            for env in s.network.iter_deliverable():
                ns = model.next_state(s, Deliver(env.src, env.dst, env.msg))
                if ns is not None:
                    host_succ.add(cm.encode(ns).tobytes())
            dev_succ = {
                nexts[bi, k].tobytes() for k in range(cm.m) if valid[bi, k]
            }
            bad += dev_succ != host_succ
    assert bad == 0


def test_step_differential_bounded_c3():
    """The c=3-only paths (32-slot network, 2-slot last-completed snapshots,
    third-client packing) differentially checked per-lane over a bounded
    host BFS prefix (every state to depth 7, ~4,700 states)."""
    import jax.numpy as jnp

    from stateright_tpu.ops.fingerprint import fingerprint

    model = paxos_model(3)
    cm = PaxosCompiled(model)
    seen = {}
    frontier = model.init_states()
    for s in frontier:
        seen[fingerprint(s)] = s
    for _ in range(7):
        nxt = []
        for s in frontier:
            acts = []
            model.actions(s, acts)
            for a in acts:
                ns = model.next_state(s, a)
                if ns is None:
                    continue
                fp = fingerprint(ns)
                if fp not in seen:
                    seen[fp] = ns
                    nxt.append(ns)
        frontier = nxt
    states = list(seen.values())
    enc = np.stack([cm.encode(s) for s in states]).astype(np.uint32)
    lane_fn = lane_fn_for(cm)
    for off in range(0, len(states), 2048):
        chunk = enc[off : off + 2048]
        nexts, valid, flags = (
            np.asarray(x) for x in lane_fn(jnp.asarray(chunk))
        )
        assert not flags.any()
        for bi in range(len(chunk)):
            s = states[off + bi]
            host_map = {}
            for env in s.network.iter_deliverable():
                ns = model.next_state(s, Deliver(env.src, env.dst, env.msg))
                host_map[cm._env_code(env)] = (
                    None if ns is None else cm.encode(ns)
                )
            for k in range(cm.m):
                code = int(chunk[bi][cm._NET0 + k])
                if code == 0:
                    assert not valid[bi, k]
                    continue
                want = host_map[code]
                if want is None:
                    assert not valid[bi, k], cm._env_of(code)
                else:
                    assert valid[bi, k], cm._env_of(code)
                    assert np.array_equal(nexts[bi, k], want), cm._env_of(code)


def _consistent_tester_words(cm, rng=None, limit=None):
    """Enumerate (or sample) consistent synthetic tester states as per-client
    packed words.  Consistency: a last-completed snapshot about thread j
    cannot claim more completed ops than j currently has (counts only grow,
    so any reachable state satisfies this)."""
    c = cm.c
    lcb = 2 * (c - 1)
    choices = []
    for phase in (0, 1, 2, 3, 4):
        lc_opts = [0]
        if phase >= 3:
            lc_opts = range(1 << lcb)
        v_opts = [0]
        if phase == 4:
            v_opts = range(c + 1)
        for lc in lc_opts:
            if any(((lc >> (2 * s)) & 3) == 3 for s in range(c - 1)):
                continue  # code 3 (index 2) does not exist: ops/thread <= 2
            for v in v_opts:
                choices.append((phase, lc, v))
    import itertools

    combos = itertools.product(choices, repeat=c)
    if limit is not None:
        combos = list(combos)
        rng.shuffle(combos)
        combos = combos[:limit]
    for combo in combos:
        phases = [x[0] for x in combo]
        ok = True
        words = []
        for i, (phase, lc, v) in enumerate(combo):
            slot = 0
            for j in range(c):
                if j == i:
                    continue
                code = (lc >> (2 * slot)) & 3
                cnt_j = (phases[j] >= 2) + (phases[j] == 4)
                if code > cnt_j:
                    ok = False
                slot += 1
            words.append(phase | (lc << (3 + lcb)) | (v << (3 + 2 * lcb)))
        if ok:
            yield words


def _lin_cases(c, rng=None, limit=None):
    from stateright_tpu.models.paxos import NULL_VALUE
    from stateright_tpu.semantics import LinearizabilityTester, Register

    model = paxos_model(c)
    cm = PaxosCompiled(model)
    cases = []
    for words in _consistent_tester_words(cm, rng=rng, limit=limit):
        tester = LinearizabilityTester(Register(NULL_VALUE))
        for i, w in enumerate(words):
            cm._decode_tester_into(tester, w, i)
        state = np.zeros(cm.state_width, np.uint32)
        tst0 = cm._NET0 + cm.m
        for i, w in enumerate(words):
            state[tst0 + i] = w
        cases.append((state, tester.serialized_history() is not None))
    return cm, cases


def _assert_lin_matches(cm, cases):
    import jax
    import jax.numpy as jnp

    lin = jax.jit(jax.vmap(cm._device_linearizable))
    enc = np.stack([s for s, _ in cases])
    got = np.asarray(lin(jnp.asarray(enc)))
    want = np.array([w for _, w in cases])
    mism = np.flatnonzero(got != want)
    assert len(mism) == 0, (
        f"{len(mism)} mismatches, first state={enc[mism[0]]}, "
        f"host={want[mism[0]]}, device={got[mism[0]]}"
    )
    # The enumeration must actually exercise violations.
    assert (~want).sum() > 0


def test_device_linearizability_exhaustive_c2():
    cm, cases = _lin_cases(2)
    _assert_lin_matches(cm, cases)


def test_device_linearizability_sampled_c3():
    import random

    cm, cases = _lin_cases(3, rng=random.Random(7), limit=2500)
    _assert_lin_matches(cm, cases)


@pytest.mark.slow
def test_spawn_tpu_paxos2_matches_host_oracle(reachable_c2):
    model = paxos_model(2)
    tpu = (
        model.checker()
        .spawn_tpu(capacity=1 << 18, max_frontier=1 << 13)
        .join()
    )
    assert tpu.unique_state_count() == 16_668  # examples/paxos.rs:328
    host = paxos_model(2).checker().spawn_bfs().join()
    assert tpu.unique_state_count() == host.unique_state_count()
    assert tpu.state_count() == host.state_count()
    assert tpu.max_depth() == host.max_depth()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    # The device discovery must replay as a genuine example trace.
    tpu.assert_properties()


@pytest.mark.slow
def test_violating_variant_found_on_device():
    """The bench's time-to-first-violation variant: an always-"never
    decided" property that paxos falsifies; the device discovery must
    replay as a genuine counterexample trace."""
    from stateright_tpu.actor import Network
    from stateright_tpu.core.has_discoveries import HasDiscoveries

    model = PaxosModelCfg(
        client_count=2,
        server_count=3,
        network=Network.new_unordered_nonduplicating(),
        never_decided=True,
    ).into_model()
    tpu = (
        model.checker()
        .finish_when(HasDiscoveries.ANY_FAILURES)
        .spawn_tpu(capacity=1 << 16, max_frontier=1 << 10)
        .join()
    )
    assert "never decided" in tpu.discoveries()
    final = tpu.discoveries()["never decided"].last_state()
    assert any(getattr(a, "is_decided", False) for a in final.actor_states)


def test_step_flag_overflow_is_loud():
    """A delivery whose sends exceed the slot budget must flag, not corrupt."""
    import jax
    import jax.numpy as jnp

    from stateright_tpu.actor import Envelope
    from stateright_tpu.actor.register import Internal, Put
    from stateright_tpu.models.paxos import Prepare

    model = paxos_model(2)
    cm = PaxosCompiled(model)
    state = np.zeros(cm.state_width, np.uint32)
    # Slot 0: client 0's Put to server 0 (delivery broadcasts 2 Prepares).
    codes = [cm._env_code(Envelope(Id(3), Id(0), Put(3, "A")))]
    # Fill the rest with distinct well-formed Prepare envelopes.
    for r in range(2, 10):
        for src in range(3):
            for dst in range(3):
                if src != dst and len(codes) < cm.m:
                    codes.append(
                        cm._env_code(
                            Envelope(Id(src), Id(dst), Internal(Prepare((r, Id(src)))))
                        )
                    )
    assert len(codes) == cm.m
    for k, code in enumerate(sorted(codes)):
        state[cm._NET0 + k] = code
    nexts, valid, flag = cm.step(jnp.asarray(state))
    assert bool(jnp.any(flag))


def test_engine_surfaces_step_flag():
    """The wavefront engine turns a step flag into a hard error."""
    import jax.numpy as jnp

    from stateright_tpu.models.twophase import TwoPhaseSys
    from stateright_tpu.models.twophase_compiled import TwoPhaseCompiled

    class Flagging(TwoPhaseCompiled):
        step_flags = True

        def step(self, state):
            nexts, valid = super().step(state)
            return nexts, valid, jnp.ones((), jnp.bool_)

        def cache_key(self):
            return (type(self).__qualname__, self.n)

    model = TwoPhaseSys(rm_count=3)
    with pytest.raises(RuntimeError, match="encoding-capacity overflow"):
        model.checker().spawn_tpu(
            capacity=1 << 12, compiled=Flagging(model)
        ).join()


@pytest.fixture(scope="module")
def reachable_c1():
    return enumerate_reachable(paxos_model(1))


@pytest.fixture(scope="module")
def reachable_c2():
    return enumerate_reachable(paxos_model(2))


@pytest.mark.slow
def test_spawn_tpu_paxos_c4_depth_bounded_differential():
    """4 clients — past the round-2 client cap, exercising the widened
    proposal/value fields and base-8 envelope addressing.  Depth-bounded:
    the full c=4 space exceeds suite runtime (the full-scale anchor is
    bench.py's fatal golden on real hardware)."""
    host = (
        paxos_model(4)
        .checker()
        .target_max_depth(9)
        .spawn_bfs()
        .join()
    )
    tpu = (
        paxos_model(4)
        .checker()
        .target_max_depth(9)
        .spawn_tpu(capacity=1 << 20, max_frontier=1 << 10)
        .join()
    )
    assert host.unique_state_count() == 8_352
    assert tpu.unique_state_count() == 8_352
    assert tpu.max_depth() == host.max_depth() == 9
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())


def test_paxos_check6_codec_compiles():
    """`paxos check 6` (the reference bench workload, bench.sh:28) must at
    least construct, round-trip its init states, and lower the step kernel
    + property predicates to HLO.  (Full checking at c=6 is bounded by the
    linearizability DP's 2^(2C) subset space — see the cost-curve note in
    docs/TPU_PAXOS_DESIGN.md.)"""
    import jax
    import jax.numpy as jnp

    model = paxos_model(6)
    cm = PaxosCompiled(model)
    assert cm.c == 6 and cm.m == 64
    for s in model.init_states():
        enc = cm.encode(s)
        assert cm.decode(enc) == s
    enc0 = jnp.asarray(cm.encode(next(iter(model.init_states()))))
    jax.jit(cm.step).lower(enc0)
    jax.jit(cm.property_conds).lower(enc0)


@pytest.mark.slow
def test_spawn_tpu_paxos_c6_depth_bounded_differential():
    """`paxos check 6` — the biggest reference bench workload
    (bench.sh:28) — depth-bounded so the host oracle fits suite runtime.
    The bit-packed linearizability DP (128 subset words per value column
    at C=6) must agree exactly with the host tester; the full-scale
    anchors are the tpu-marked golden below and bench.py's device suite
    (full c=6 on hardware: 9,357,525 unique, depth 28, differential vs
    host pinned at depth 12: 283,217)."""
    host = (
        paxos_model(6)
        .checker()
        .target_max_depth(9)
        .spawn_bfs()
        .join()
    )
    tpu = (
        paxos_model(6)
        .checker()
        .target_max_depth(9)
        .spawn_tpu(capacity=1 << 20, max_frontier=1 << 10)
        .join()
    )
    assert host.unique_state_count() == tpu.unique_state_count()
    assert host.state_count() == tpu.state_count()
    assert tpu.max_depth() == host.max_depth() == 9
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())


@pytest.mark.tpu
def test_paxos_check5_full_golden_device():
    """Full `paxos check 5` on the real chip: this framework's pinned
    golden (no reference-pinned count exists past c=2); cross-validated
    by the depth-bounded host differentials and the c=6 depth-12
    differential (283,217 both engines, scratch run 2026-07-31)."""
    tpu = (
        paxos_model(5)
        .checker()
        .spawn_tpu(capacity=1 << 24, max_frontier=1 << 13, dedup_factor=8)
        .join()
    )
    assert tpu.unique_state_count() == 4_711_569
    assert tpu.max_depth() == 28
    assert sorted(tpu.discoveries()) == ["value chosen"]


@pytest.mark.tpu
def test_paxos_check6_full_golden_device():
    """Full `paxos check 6` (reference bench.sh:28) on the real chip:
    9,357,525 unique states at depth 28.  The decoupled table/row-log
    geometry (2^25 slots / 10.5M positions) is what fits the run on one
    16 GB chip."""
    tpu = (
        paxos_model(6)
        .checker()
        .spawn_tpu(
            capacity=1 << 25,
            log_capacity=10_500_000,
            max_frontier=1 << 13,
            dedup_factor=8,
        )
        .join()
    )
    assert tpu.unique_state_count() == 9_357_525
    assert tpu.max_depth() == 28
    assert sorted(tpu.discoveries()) == ["value chosen"]


@pytest.mark.slow
def test_step_valid_matches_full_kernel_c2(reachable_c2):
    """Two-phase contract: the phase-A ``step_valid`` plane must equal the
    full kernel's valid plane on every lane of every reachable state.

    This is the differential that would have caught the r4 regression
    class at trace time: it exercises the public two-phase surface
    (``step_valid`` + ``step_lane``) rather than the private kernel."""
    import jax
    import jax.numpy as jnp

    model = paxos_model(2)
    cm = PaxosCompiled(model)
    states = list(reachable_c2.values())
    enc = np.stack([cm.encode(s) for s in states]).astype(np.uint32)
    # Pad to a chunk multiple so every jit call sees one shape (the tail
    # would otherwise recompile both kernels); duplicates are harmless —
    # the assertion is elementwise va == vb.
    pad = (-len(enc)) % 2048
    enc = np.concatenate([enc, np.tile(enc[:1], (pad, 1))])
    valid_fn = jax.jit(jax.vmap(cm.step_valid))
    lane_fn = jax.jit(
        jax.vmap(
            lambda st: jax.vmap(lambda k: cm.step_lane(st, k))(
                jnp.arange(cm.m, dtype=jnp.uint32)
            )
        )
    )
    for off in range(0, len(enc), 2048):
        chunk = jnp.asarray(enc[off : off + 2048])
        va = np.asarray(valid_fn(chunk))
        nexts, vb, flags = (np.asarray(x) for x in lane_fn(chunk))
        assert not flags.any()
        assert np.array_equal(va, vb), (
            f"step_valid != step_lane valid plane in chunk at {off}"
        )


@pytest.mark.slow
def test_two_phase_matches_single_phase_full_run(monkeypatch):
    """Full-run golden: the two-phase engine path and the single-phase
    path must produce identical counts and discoveries on paxos c=2.

    Deleting ``step_valid`` forces the engine's single-phase branch
    (`parallel/wave_common.py` gates two-phase on hasattr).  The
    two-phase capability is part of the compiled-program cache key
    (`wavefront.py:_programs`), so the second run genuinely re-traces —
    asserted below via the cache keys."""
    from stateright_tpu.parallel import wavefront

    two = (
        paxos_model(2)
        .checker()
        .spawn_tpu(capacity=1 << 18, max_frontier=1 << 13)
        .join()
    )
    keys_before = set(wavefront._PROGRAM_CACHE)
    monkeypatch.delattr(PaxosCompiled, "step_valid")
    one = (
        paxos_model(2)
        .checker()
        .spawn_tpu(capacity=1 << 18, max_frontier=1 << 13)
        .join()
    )
    # A new program (single-phase) must have been compiled — if the
    # two-phase program had been served from cache this golden would be
    # comparing a run against itself.
    assert set(wavefront._PROGRAM_CACHE) - keys_before
    assert two.unique_state_count() == one.unique_state_count() == 16_668
    assert two.state_count() == one.state_count()
    assert two.max_depth() == one.max_depth()
    assert sorted(two.discoveries()) == sorted(one.discoveries())
