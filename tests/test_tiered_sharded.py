"""The composed tiered × sharded engine (tiered/sharded_engine.py) and
elastic resharding (tiered/reshard.py): ISSUE-17's acceptance matrix —
per-shard memory budgets force evictions into shard-local cold stores
while ``discovered_fingerprints()`` stays bit-identical to the
unconstrained engine at every mesh size, including across a supervised
kill-mid-run resume and across an 8→4 / 4→8 mid-run reshard."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twophase import TwoPhaseSys  # noqa: E402
from stateright_tpu.runtime.journal import read_journal  # noqa: E402
from stateright_tpu.tiered import ColdStore  # noqa: E402

# 256 slots/shard: the spill-forcing budget for 2pc3's 288 uniques
# (capacity_for_budget floors at 256; 288 states over <=2 shards cross
# the 45% spill threshold repeatedly).
FORCING_MB = 0.003


def _mesh(n):
    return jax.sharding.Mesh(
        np.array(jax.devices("cpu")[:n]), ("shards",)
    )


def _ref(model):
    return (
        model.checker()
        .spawn_tpu_sharded(mesh=_mesh(1), capacity=1 << 14,
                           chunk_size=1 << 6)
        .join()
    )


def _tiered_sharded(model, n, **kwargs):
    kwargs.setdefault("memory_budget_mb", FORCING_MB)
    kwargs.setdefault("chunk_size", 1 << 5)
    return model.checker().spawn_tpu_tiered_sharded(
        mesh=_mesh(n), **kwargs
    )


# --- host helpers (fast, no device work) -------------------------------------


def test_owner_mix_host_np_matches_scalar():
    """The vectorised owner router the reshard path uses must agree
    with the scalar mix the engines pin (parallel/sharded.py)."""
    from stateright_tpu.parallel.sharded import (
        _owner_mix_host, _owner_mix_host_np,
    )

    rng = np.random.default_rng(17)
    fps = rng.integers(0, 1 << 64, size=512, dtype=np.uint64)
    hi = (fps >> np.uint64(32)).astype(np.uint64)
    lo = (fps & np.uint64(0xFFFFFFFF)).astype(np.uint64)
    vec = _owner_mix_host_np(hi, lo)
    ref = np.asarray(
        [_owner_mix_host(int(h), int(lw)) for h, lw in zip(hi, lo)],
        dtype=np.uint64,
    )
    assert np.array_equal(vec, ref)


def test_sibling_spill_dirs_never_clobber_or_cross_adopt(tmp_path):
    """ISSUE-17 satellite: shard-local cold stores spill under sibling
    ``shard_<d>/`` subdirectories; one shard's spills and LSM merges
    must never touch — or be adopted by — a sibling's run files, and a
    reopened store sees exactly its own runs."""
    base = str(tmp_path / "cold")
    d0, d1 = os.path.join(base, "shard_0"), os.path.join(base, "shard_1")
    s0 = ColdStore(spill_dir=d0, max_runs=2)
    s1 = ColdStore(spill_dir=d1, max_runs=2)
    s0.add_run(np.asarray([2, 4], np.uint64))
    s1.add_run(np.asarray([3, 5], np.uint64))
    s0.add_run(np.asarray([6], np.uint64))
    # s0 crosses max_runs -> merge rewrites ITS disk set only.
    s0.add_run(np.asarray([8], np.uint64))
    assert s0.run_count == 1 and s0.entries == 4
    assert s1.run_count == 1 and s1.entries == 2
    assert s1.contains([3, 5, 2]).tolist() == [True, True, False]
    files0 = {os.path.join(d0, f) for f in os.listdir(d0)}
    files1 = {os.path.join(d1, f) for f in os.listdir(d1)}
    assert files0 and files1 and not files0 & files1
    # Reopening each directory adopts only that shard's runs.
    r0 = ColdStore.open(d0, max_runs=2)
    r1 = ColdStore.open(d1, max_runs=2)
    assert r0.entries == 4 and r0.contains([2, 4, 6, 8]).all()
    assert not r0.contains([3, 5]).any()
    assert r1.entries == 2 and r1.contains([3, 5]).all()
    assert not r1.contains([2, 4, 6, 8]).any()


def test_tiered_sharded_spawn_validation():
    m = TwoPhaseSys(rm_count=3)
    with pytest.raises(ValueError, match="trace"):
        m.checker().spawn_tpu_tiered_sharded(trace=True)
    with pytest.raises(ValueError, match="spill_threshold"):
        m.checker().spawn_tpu_tiered_sharded(spill_threshold=0.9)


def test_tiered_sharded_cli_refusals():
    """The composed engine has no traced mode, and plain --sharded
    still refuses CLI supervision (only the tiered-sharded snapshot
    carries everything a restart needs)."""
    from stateright_tpu.cli import example_main
    from stateright_tpu.models.twophase import cli_spec

    for bad in (
        ["check-tpu", "3", "--tiered", "--sharded", "--trace"],
        ["check-tpu", "3", "--sharded", "--supervise",
         "--checkpoint-dir", "/tmp/nope"],
        ["reshard", "3", "in.npz", "out.npz"],          # missing --shards
        ["reshard", "3", "--shards", "4"],              # missing paths
        ["reshard", "3", "in.npz", "out.npz", "--shards", "zero"],
    ):
        assert example_main(cli_spec(), bad) == 2, bad


# --- the acceptance pins (device-compiling; slow) ----------------------------


@pytest.mark.slow
def test_tiered_sharded_bit_identical_across_mesh_sizes(tmp_path):
    """The universal gate at 1/2/4/8 virtual shards: per-shard budgets
    force spills (at the widths where per-shard load crosses the
    threshold) and the discovery set stays bit-identical to the
    unconstrained engine."""
    model = TwoPhaseSys(rm_count=3)
    ref = _ref(model)
    ref_fps = ref.discovered_fingerprints()
    spilled_any = False
    for n in (1, 2, 4, 8):
        journal = str(tmp_path / f"ts{n}.jsonl")
        t = _tiered_sharded(model, n, journal=journal).join()
        m = t.metrics()
        assert t.unique_state_count() == ref.unique_state_count() == 288
        assert t.state_count() == ref.state_count()
        assert t.max_depth() == ref.max_depth()
        assert sorted(t.discoveries()) == sorted(ref.discoveries())
        assert np.array_equal(t.discovered_fingerprints(), ref_fps)
        events = read_journal(journal)
        spills = [e for e in events if e["event"] == "spill"]
        # Spill events are per shard and carry the owner.
        assert all(0 <= e["shard"] < n for e in spills)
        assert len(spills) == m.get("spills", 0) or m.get("spills", 0) > 0
        if spills:
            spilled_any = True
            assert m["cold_entries"] > 0
    assert spilled_any, "the forcing budget never spilled at any width"


@pytest.mark.slow
def test_tiered_sharded_kill_mid_run_supervised_resume(
    tmp_path, monkeypatch
):
    """The robustness pin: a supervised tiered-sharded child (virtual
    8-wide mesh, spill-forcing budget) dies the moment its first
    checkpoint lands, auto-resumes — rebuilding the hot planes and
    re-adopting the per-shard cold stores from the snapshot — and
    reports the same totals and discovery set as an uninterrupted
    run."""
    from stateright_tpu.runtime.supervisor import (
        CheckSpec, RunSupervisor, SupervisorConfig,
    )

    model = TwoPhaseSys(rm_count=3)
    ref = _ref(model)

    monkeypatch.setenv(
        "STATERIGHT_RUNTIME_FAULT_EXIT_AFTER_CHECKPOINT", "137"
    )
    run_dir = str(tmp_path / "run")
    spec = CheckSpec(
        model_factory=TwoPhaseSys,
        factory_kwargs={"rm_count": 3},
        engine="tiered-sharded",
        engine_kwargs={
            "memory_budget_mb": FORCING_MB,
            "chunk_size": 1 << 5,
        },
    )
    sup = RunSupervisor(
        SupervisorConfig(
            run_dir=run_dir,
            checkpoint_every_waves=1,
            checkpoint_every_sec=None,
            call_deadline_sec=240.0,
            poll_interval_sec=0.05,
            max_restarts=2,
        ),
        spec=spec,
    )
    result = sup.run()

    assert result["completed"]
    assert result["unique_state_count"] == ref.unique_state_count()
    assert result["state_count"] == ref.state_count()
    assert result["max_depth"] == ref.max_depth()
    assert result["discoveries"] == sorted(ref.discoveries())

    events = read_journal(os.path.join(run_dir, "journal.jsonl"))
    kinds = [e["event"] for e in events]
    assert "checkpoint" in kinds
    assert "crash" in kinds
    assert "resume" in kinds
    assert kinds.count("run_start") == 2


@pytest.mark.slow
def test_tiered_sharded_reshard_resume_both_directions(tmp_path):
    """Elastic resharding: a mid-run 8-shard checkpoint re-keyed to 4
    shards resumes to the exact unconstrained result, and a 4-shard
    checkpoint re-keyed to 8 does too (the widening AND narrowing
    directions of the acceptance matrix)."""
    from stateright_tpu.tiered.reshard import reshard_snapshot

    model = TwoPhaseSys(rm_count=3)
    ref = _ref(model)
    ref_fps = ref.discovered_fingerprints()

    for n_from, n_to in ((8, 4), (4, 8)):
        ck = str(tmp_path / f"ck{n_from}.npz")
        part = (
            model.checker()
            .target_max_depth(5)
            .spawn_tpu_tiered_sharded(
                mesh=_mesh(n_from), memory_budget_mb=FORCING_MB,
                chunk_size=1 << 5, checkpoint_path=ck,
                checkpoint_every_waves=1,
            )
            .join()
        )
        assert part.max_depth() <= 5  # genuinely mid-run
        out = str(tmp_path / f"rs{n_from}to{n_to}.npz")
        journal = str(tmp_path / f"rs{n_from}to{n_to}.jsonl")
        summary = reshard_snapshot(model, ck, out, n_to, journal=journal)
        assert summary["old_shards"] == n_from
        assert summary["new_shards"] == n_to
        assert len(summary["tails"]) == n_to
        assert any(
            e["event"] == "reshard" for e in read_journal(journal)
        )

        # Direct resume on the WRONG width stays loud and names the
        # reshard verb (ISSUE-17 satellite).
        with pytest.raises(ValueError, match="reshard"):
            model.checker().spawn_tpu_tiered_sharded(
                mesh=_mesh(n_from), memory_budget_mb=FORCING_MB,
                chunk_size=1 << 5, resume_from=out,
            ).join()

        res = (
            model.checker()
            .spawn_tpu_tiered_sharded(
                mesh=_mesh(n_to), memory_budget_mb=FORCING_MB,
                chunk_size=1 << 5, resume_from=out,
            )
            .join()
        )
        assert res.unique_state_count() == ref.unique_state_count()
        assert res.state_count() == ref.state_count()
        assert res.max_depth() == ref.max_depth()
        assert sorted(res.discoveries()) == sorted(ref.discoveries())
        assert np.array_equal(res.discovered_fingerprints(), ref_fps)


@pytest.mark.slow
def test_plain_sharded_snapshot_resharded_into_tiered(tmp_path):
    """The migration path: an UN-tiered sharded checkpoint reshards
    into a tiered-sharded snapshot and finishes under the composed
    engine with the identical discovery set."""
    from stateright_tpu.tiered.reshard import reshard_snapshot

    model = TwoPhaseSys(rm_count=3)
    ref = _ref(model)
    ck = str(tmp_path / "plain.npz")
    (
        model.checker()
        .target_max_depth(6)
        .spawn_tpu_sharded(
            mesh=_mesh(4), capacity=1 << 14, chunk_size=1 << 6,
            checkpoint_path=ck, checkpoint_every_waves=1,
        )
        .join()
    )
    out = str(tmp_path / "plain_rs2.npz")
    reshard_snapshot(model, ck, out, 2)
    res = (
        model.checker()
        .spawn_tpu_tiered_sharded(
            mesh=_mesh(2), capacity=(1 << 12) * 2, chunk_size=1 << 6,
            resume_from=out,
        )
        .join()
    )
    assert res.unique_state_count() == ref.unique_state_count()
    assert res.state_count() == ref.state_count()
    assert np.array_equal(
        res.discovered_fingerprints(), ref.discovered_fingerprints()
    )


@pytest.mark.slow
def test_tiered_sharded_serve_job(tmp_path):
    """A tiered-sharded service job completes, reports its engine, and
    persists its budget-keyed geometry under the composed engine's own
    knob tag (never shadowing sharded or tiered entries)."""
    from stateright_tpu.runtime.knob_cache import (
        TIERED_SHARDED_ENGINE, knob_key, load_knobs,
    )
    from stateright_tpu.serve import CheckService
    from stateright_tpu.serve.workloads import workload_label

    knobs = str(tmp_path / "knobs")
    svc = CheckService(journal=None, knob_cache_dir=knobs)
    try:
        spec = {
            "workload": "twophase", "n": 3, "engine": "tiered-sharded",
            "engine_kwargs": {"memory_budget_mb": FORCING_MB},
        }
        job = svc.submit(dict(spec))
        assert job.wait(timeout=240)
        assert job.state == "done", (job.state, job.error)
        assert job.result["unique_state_count"] == 288
        assert job.result["engine"] == "tiered-sharded"
        key = knob_key(
            workload_label("twophase", 3, None, False)
            + ":mb={}".format(FORCING_MB),
            engine=TIERED_SHARDED_ENGINE,
        )
        stored = load_knobs(knobs, key)
        assert stored is not None
        assert stored.get("memory_budget_mb") == FORCING_MB
    finally:
        svc.scheduler.shutdown()
