"""ABD and Paxos golden tests.

Reference anchors: examples/linearizable-register.rs:258-316 (544 unique
states) and examples/paxos.rs:301-353 (16,668 unique states, BFS = DFS).
"""

import pytest

from stateright_tpu.actor import Deliver, Id, Network
from stateright_tpu.actor.register import Get, GetOk, Internal, Put, PutOk
from stateright_tpu.models.abd import (
    AbdModelCfg,
    AckQuery,
    AckRecord,
    NULL_VALUE,
    Query,
    Record,
)
from stateright_tpu.models.paxos import (
    Accept,
    Accepted,
    Decided,
    PaxosModelCfg,
    Prepare,
    Prepared,
)


def test_can_model_linearizable_register_bfs():
    checker = (
        AbdModelCfg(
            client_count=2,
            server_count=2,
            network=Network.new_unordered_nonduplicating(),
        )
        .into_model()
        .checker()
        .spawn_bfs()
        .join()
    )
    checker.assert_properties()
    checker.assert_discovery(
        "value chosen",
        [
            Deliver(Id(3), Id(1), Put(3, "B")),
            Deliver(Id(1), Id(0), Internal(Query(3))),
            Deliver(Id(0), Id(1), Internal(AckQuery(3, (0, Id(0)), NULL_VALUE))),
            Deliver(Id(1), Id(0), Internal(Record(3, (1, Id(1)), "B"))),
            Deliver(Id(0), Id(1), Internal(AckRecord(3))),
            Deliver(Id(1), Id(3), PutOk(3)),
            Deliver(Id(3), Id(0), Get(6)),
            Deliver(Id(0), Id(1), Internal(Query(6))),
            Deliver(Id(1), Id(0), Internal(AckQuery(6, (1, Id(1)), "B"))),
            Deliver(Id(0), Id(1), Internal(Record(6, (1, Id(1)), "B"))),
            Deliver(Id(1), Id(0), Internal(AckRecord(6))),
        ],
    )
    assert checker.unique_state_count() == 544


def test_can_model_linearizable_register_dfs():
    checker = (
        AbdModelCfg(
            client_count=2,
            server_count=2,
            network=Network.new_unordered_nonduplicating(),
        )
        .into_model()
        .checker()
        .spawn_dfs()
        .join()
    )
    checker.assert_properties()
    assert checker.unique_state_count() == 544


@pytest.mark.slow
def test_can_model_paxos_bfs():
    checker = (
        PaxosModelCfg(
            client_count=2,
            server_count=3,
            network=Network.new_unordered_nonduplicating(),
        )
        .into_model()
        .checker()
        .spawn_bfs()
        .join()
    )
    checker.assert_properties()
    checker.assert_discovery(
        "value chosen",
        [
            Deliver(Id(4), Id(1), Put(4, "B")),
            Deliver(Id(1), Id(0), Internal(Prepare((1, Id(1))))),
            Deliver(Id(0), Id(1), Internal(Prepared((1, Id(1)), None))),
            Deliver(Id(1), Id(2), Internal(Accept((1, Id(1)), (4, Id(4), "B")))),
            Deliver(Id(2), Id(1), Internal(Accepted((1, Id(1))))),
            Deliver(Id(1), Id(4), PutOk(4)),
            Deliver(Id(1), Id(2), Internal(Decided((1, Id(1)), (4, Id(4), "B")))),
            Deliver(Id(4), Id(2), Get(8)),
        ],
    )
    assert checker.unique_state_count() == 16668


@pytest.mark.slow
def test_can_model_paxos_dfs():
    checker = (
        PaxosModelCfg(
            client_count=2,
            server_count=3,
            network=Network.new_unordered_nonduplicating(),
        )
        .into_model()
        .checker()
        .spawn_dfs()
        .join()
    )
    checker.assert_properties()
    assert checker.unique_state_count() == 16668
