"""Test configuration.

TPU/JAX tests run on a virtual 8-device CPU mesh so multi-chip sharding is
exercised without hardware; set up before any jax import.
"""

import os
import pathlib

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent XLA compilation cache: the wavefront programs take tens of
# seconds to compile cold but are stable across runs.
_CACHE = pathlib.Path(__file__).resolve().parent.parent / ".jax_cache"
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", str(_CACHE))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
