"""Test configuration.

TPU/JAX tests run on a virtual 8-device CPU mesh so multi-chip sharding is
exercised without hardware; set up before any jax import.
"""

import os
import pathlib
import sys

# Hermetic default: force the cpu platform (ambient JAX_PLATFORMS often
# points at a TPU plugin that sitecustomize preloads).  To validate on real
# hardware, opt in explicitly with the platform's jax name, e.g.
#   STATERIGHT_TPU_TEST_PLATFORM=tpu pytest -m tpu
# (on this box the tunneled device registers as the "axon" platform, so
#  STATERIGHT_TPU_TEST_PLATFORM=axon — all 3 tpu-marked goldens pass there).
_platform = os.environ.get("STATERIGHT_TPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# If jax is already imported (sitecustomize), the env var is too late —
# pin the config directly, before any backend initializes.
if "jax" in sys.modules:
    import jax

    jax.config.update("jax_platforms", _platform)

# Persistent XLA compilation cache: the wavefront programs take tens of
# seconds to compile cold but are stable across runs.
_CACHE = pathlib.Path(__file__).resolve().parent.parent / ".jax_cache"
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", str(_CACHE))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
