"""Test configuration.

TPU/JAX tests run on a virtual 8-device CPU mesh so multi-chip sharding is
exercised without hardware; set up before any jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
