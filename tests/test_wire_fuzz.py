"""Fuzzing the wire codec's malformed-datagram contract.

``wire.py:58`` documents that every decode failure must surface as
``ValueError`` — the runtime's receive loop treats that as "malformed
datagram, drop it"; any other exception type would kill the replica
thread on a hand-typed probe message.  Here that contract is asserted
both at the codec level (seeded random garbage, truncations, bit flips,
hand-typed hostile payloads) and against a live runtime (every garbage
datagram is dropped and the replica keeps answering).
"""

import random
from dataclasses import dataclass

from stateright_tpu.actor.base import Actor, Out
from stateright_tpu.actor.ids import Id
from stateright_tpu.actor.spawn import spawn
from stateright_tpu.actor.transport import LoopbackTransport
from stateright_tpu.actor.wire import (
    register_wire_types,
    wire_deserialize,
    wire_serialize,
)


@dataclass(frozen=True)
class FuzzPing:
    request_id: int
    payload: str


@dataclass(frozen=True)
class FuzzPong:
    request_id: int


@dataclass(frozen=True)
class FuzzBag:
    items: tuple
    tags: frozenset


register_wire_types(FuzzPing, FuzzPong, FuzzBag)


def _hand_typed_corpus():
    """Hostile payloads a human (or a confused client) might type at a
    replica with ``nc -u``."""
    return [
        b"",
        b"not json",
        b"\xff\xfe\x00garbage",  # not UTF-8
        b"5",
        b"null",
        b'"just a string"',
        b"[1, 2, 3]",
        b"{}",
        b'{"__t": "NoSuchType"}',
        b'{"__t": "FuzzPing"}',  # missing fields
        b'{"__t": "FuzzPing", "request_id": 1}',  # still missing payload
        b'{"__t": "FuzzPing", "request_id": 1, "payload": "x", "extra": 2}',
        b'{"__t": []}',  # unhashable tag: must not TypeError
        b'{"__t": {"a": 1}}',
        b'{"__t": null}',
        b'{"__id": "zero"}',
        b'{"__id": true}',
        b'{"__id": 1.5}',
        b'{"__tup": 5}',
        b'{"__set": 5}',
        b'{"__set": [[1]]}',  # unhashable element
        b"[" * 5000,  # nests past the recursion limit
        b'{"a":' * 5000,
        b"[" * 5000 + b"1" + b"]" * 5000,
    ]


def _seeded_corpus():
    rng = random.Random(0xC0FFEE)
    corpus = []
    for _ in range(300):
        corpus.append(bytes(rng.randrange(256) for _ in range(rng.randrange(1, 64))))
    valid = [
        wire_serialize(FuzzPing(7, "hello")),
        wire_serialize(FuzzBag(items=(FuzzPong(1), (1, 2)), tags=frozenset([3]))),
        wire_serialize(FuzzPing(2, "x" * 100)),
    ]
    for v in valid:
        for _ in range(60):
            cut = rng.randrange(len(v))
            corpus.append(v[:cut])  # truncation
            flipped = bytearray(v)
            flipped[rng.randrange(len(v))] ^= 1 << rng.randrange(8)
            corpus.append(bytes(flipped))  # bit flip
    return corpus


def test_wire_deserialize_failures_are_always_valueerror():
    """Decode either succeeds or raises ValueError — never TypeError /
    KeyError / RecursionError / UnicodeDecodeError-as-something-else."""
    decoded = failed = 0
    for datagram in _hand_typed_corpus() + _seeded_corpus():
        try:
            wire_deserialize(datagram)
            decoded += 1
        except ValueError:
            failed += 1
        # any other exception type propagates and fails the test
    assert failed > 0, "the corpus should contain undecodable datagrams"


# --- the trace envelope (actor/obs.py, ISSUE 15) -----------------------------


def test_trace_envelope_round_trips_under_fuzz():
    """Random payloads (including envelope-magic-looking ones), trace
    ids, hops, and timestamps: wrap → unwrap must reproduce the payload
    and header exactly."""
    from stateright_tpu.actor.obs import unwrap_datagram, wrap_datagram

    rng = random.Random(0x5EED)
    for _ in range(300):
        payload = bytes(
            rng.randrange(256) for _ in range(rng.randrange(0, 200))
        )
        trace_id = rng.getrandbits(64)
        hop = rng.randrange(256)
        sent_at = rng.random() * 2e9
        data = wrap_datagram(payload, trace_id, hop, sent_at)
        out, ctx = unwrap_datagram(data)
        assert out == payload
        assert ctx.trace_id == trace_id
        assert ctx.hop == hop
        assert abs(ctx.sent_at - sent_at) < 1e-6


def test_malformed_envelope_decode_is_always_valueerror():
    """Anything wearing the envelope magic either decodes or raises
    ValueError — never struct.error / IndexError — mirroring the wire
    codec's malformed-datagram contract."""
    from stateright_tpu.actor.obs import (
        ENVELOPE_OVERHEAD, MAGIC, unwrap_datagram, wrap_datagram,
    )

    rng = random.Random(0xBAD)
    good = wrap_datagram(b"payload-bytes", 12345, 7, 1234.5)
    corpus = [
        MAGIC,                      # bare magic
        MAGIC + b"\x00",            # torn header
        good[: ENVELOPE_OVERHEAD - 1],  # header truncated by one byte
        good[:-1],                  # payload shorter than declared
        good + b"x",                # payload longer than declared
    ]
    for _ in range(100):
        cut = rng.randrange(len(good))
        corpus.append(good[:cut] if good[:cut].startswith(MAGIC) else good)
        corpus.append(MAGIC + bytes(
            rng.randrange(256) for _ in range(rng.randrange(0, 40))
        ))
    decoded = failed = 0
    for datagram in corpus:
        try:
            payload, ctx = unwrap_datagram(datagram)
            assert ctx is not None  # it wore the magic: never "legacy"
            decoded += 1
        except ValueError:
            failed += 1
    assert failed > 0, "the corpus should contain malformed envelopes"


def test_legacy_unenveloped_datagrams_pass_through():
    """Every datagram the wire codec emits is magic-free, so the
    envelope layer hands it through byte-identical with no context —
    un-enveloped (legacy) senders interoperate with traced receivers."""
    from stateright_tpu.actor.obs import MAGIC, unwrap_datagram

    for datagram in [
        wire_serialize(FuzzPing(7, "hello")),
        wire_serialize(FuzzBag(items=(FuzzPong(1),), tags=frozenset([3]))),
        b"",
        b"not json",
        b"[1, 2, 3]",
    ]:
        assert not datagram.startswith(MAGIC)
        out, ctx = unwrap_datagram(datagram)
        assert out == datagram and ctx is None


def test_live_traced_replica_survives_garbage_and_fake_envelopes():
    """The fuzz corpus — plus magic-wearing garbage — against a replica
    behind a tracing ObservedTransport: everything malformed drops,
    enveloped and legacy probes both still answered."""
    from stateright_tpu.actor.obs import (
        ObservedTransport, unwrap_datagram, wrap_datagram,
    )

    obs = ObservedTransport(LoopbackTransport(), trace=True)
    replica = Id(1)
    runtime = spawn(
        wire_serialize,
        wire_deserialize,
        wire_serialize,
        wire_deserialize,
        [(replica, _EchoActor())],
        storage_dir="/tmp",
        transport=obs,
        metrics=obs.registry,
    )
    rng = random.Random(0xFADE)
    probe = obs.inner.bind(Id(99))  # raw fabric: full control of bytes
    try:
        corpus = _hand_typed_corpus() + _seeded_corpus()
        corpus += [
            b"\xabSR1" + bytes(rng.randrange(256) for _ in range(n))
            for n in (0, 1, 10, 30)
        ]
        for datagram in corpus:
            probe.send(replica, datagram)
        # A LEGACY (un-enveloped) probe is still accepted...
        probe.send(replica, wire_serialize(FuzzPing(-1, "legacy")))
        # ...and an enveloped one carries its trace through to the reply.
        probe.send(
            replica,
            wrap_datagram(wire_serialize(FuzzPing(-2, "traced")), 77, 3, 0.0),
        )
        wanted = {FuzzPong(-1): None, FuzzPong(-2): None}
        while any(v is None for v in wanted.values()):
            r = probe.recv(5.0)
            assert r is not None, (
                f"replica stopped answering; errors={runtime.errors!r}"
            )
            payload, ctx = unwrap_datagram(r[0])
            try:
                msg = wire_deserialize(payload)
            except ValueError:
                continue
            if msg in wanted:
                wanted[msg] = ctx
        assert wanted[FuzzPong(-2)].trace_id == 77
        assert wanted[FuzzPong(-2)].hop == 4  # 3 + the replica's send
        assert runtime.errors == []
    finally:
        probe.close()
        runtime.stop()
    assert runtime.registry.get("trace_envelope_malformed_total", 0) > 0


class _EchoActor(Actor):
    """Replies FuzzPong to every well-formed FuzzPing."""

    def on_start(self, id, storage, o: Out):
        return ()

    def on_msg(self, id, state, src, msg, o: Out):
        if isinstance(msg, FuzzPing):
            o.send(src, FuzzPong(msg.request_id))
        return None


def test_live_replica_survives_garbage_datagrams():
    """Blast the full garbage corpus at a running replica over the
    loopback transport: every datagram must be dropped without killing
    the replica thread, which must still answer a valid probe."""
    transport = LoopbackTransport()
    replica = Id(1)
    runtime = spawn(
        wire_serialize,
        wire_deserialize,
        wire_serialize,
        wire_deserialize,
        [(replica, _EchoActor())],
        storage_dir="/tmp",
        transport=transport,
    )
    probe = transport.bind(Id(99))
    try:
        corpus = _hand_typed_corpus() + _seeded_corpus()
        for i, datagram in enumerate(corpus):
            probe.send(replica, datagram)
            if i % 100 == 0:  # interleave probes with the garbage
                probe.send(replica, wire_serialize(FuzzPing(i, "probe")))
        probe.send(replica, wire_serialize(FuzzPing(-1, "final")))
        replies = []
        while True:
            r = probe.recv(2.0)
            if r is None:
                break
            replies.append(wire_deserialize(r[0]))
            if replies[-1] == FuzzPong(-1):
                break
        assert FuzzPong(-1) in replies, (
            f"replica stopped answering after garbage; errors={runtime.errors!r}"
        )
        assert runtime.errors == []
    finally:
        probe.close()
        runtime.stop()
