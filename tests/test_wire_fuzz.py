"""Fuzzing the wire codec's malformed-datagram contract.

``wire.py:58`` documents that every decode failure must surface as
``ValueError`` — the runtime's receive loop treats that as "malformed
datagram, drop it"; any other exception type would kill the replica
thread on a hand-typed probe message.  Here that contract is asserted
both at the codec level (seeded random garbage, truncations, bit flips,
hand-typed hostile payloads) and against a live runtime (every garbage
datagram is dropped and the replica keeps answering).
"""

import random
from dataclasses import dataclass

from stateright_tpu.actor.base import Actor, Out
from stateright_tpu.actor.ids import Id
from stateright_tpu.actor.spawn import spawn
from stateright_tpu.actor.transport import LoopbackTransport
from stateright_tpu.actor.wire import (
    register_wire_types,
    wire_deserialize,
    wire_serialize,
)


@dataclass(frozen=True)
class FuzzPing:
    request_id: int
    payload: str


@dataclass(frozen=True)
class FuzzPong:
    request_id: int


@dataclass(frozen=True)
class FuzzBag:
    items: tuple
    tags: frozenset


register_wire_types(FuzzPing, FuzzPong, FuzzBag)


def _hand_typed_corpus():
    """Hostile payloads a human (or a confused client) might type at a
    replica with ``nc -u``."""
    return [
        b"",
        b"not json",
        b"\xff\xfe\x00garbage",  # not UTF-8
        b"5",
        b"null",
        b'"just a string"',
        b"[1, 2, 3]",
        b"{}",
        b'{"__t": "NoSuchType"}',
        b'{"__t": "FuzzPing"}',  # missing fields
        b'{"__t": "FuzzPing", "request_id": 1}',  # still missing payload
        b'{"__t": "FuzzPing", "request_id": 1, "payload": "x", "extra": 2}',
        b'{"__t": []}',  # unhashable tag: must not TypeError
        b'{"__t": {"a": 1}}',
        b'{"__t": null}',
        b'{"__id": "zero"}',
        b'{"__id": true}',
        b'{"__id": 1.5}',
        b'{"__tup": 5}',
        b'{"__set": 5}',
        b'{"__set": [[1]]}',  # unhashable element
        b"[" * 5000,  # nests past the recursion limit
        b'{"a":' * 5000,
        b"[" * 5000 + b"1" + b"]" * 5000,
    ]


def _seeded_corpus():
    rng = random.Random(0xC0FFEE)
    corpus = []
    for _ in range(300):
        corpus.append(bytes(rng.randrange(256) for _ in range(rng.randrange(1, 64))))
    valid = [
        wire_serialize(FuzzPing(7, "hello")),
        wire_serialize(FuzzBag(items=(FuzzPong(1), (1, 2)), tags=frozenset([3]))),
        wire_serialize(FuzzPing(2, "x" * 100)),
    ]
    for v in valid:
        for _ in range(60):
            cut = rng.randrange(len(v))
            corpus.append(v[:cut])  # truncation
            flipped = bytearray(v)
            flipped[rng.randrange(len(v))] ^= 1 << rng.randrange(8)
            corpus.append(bytes(flipped))  # bit flip
    return corpus


def test_wire_deserialize_failures_are_always_valueerror():
    """Decode either succeeds or raises ValueError — never TypeError /
    KeyError / RecursionError / UnicodeDecodeError-as-something-else."""
    decoded = failed = 0
    for datagram in _hand_typed_corpus() + _seeded_corpus():
        try:
            wire_deserialize(datagram)
            decoded += 1
        except ValueError:
            failed += 1
        # any other exception type propagates and fails the test
    assert failed > 0, "the corpus should contain undecodable datagrams"


class _EchoActor(Actor):
    """Replies FuzzPong to every well-formed FuzzPing."""

    def on_start(self, id, storage, o: Out):
        return ()

    def on_msg(self, id, state, src, msg, o: Out):
        if isinstance(msg, FuzzPing):
            o.send(src, FuzzPong(msg.request_id))
        return None


def test_live_replica_survives_garbage_datagrams():
    """Blast the full garbage corpus at a running replica over the
    loopback transport: every datagram must be dropped without killing
    the replica thread, which must still answer a valid probe."""
    transport = LoopbackTransport()
    replica = Id(1)
    runtime = spawn(
        wire_serialize,
        wire_deserialize,
        wire_serialize,
        wire_deserialize,
        [(replica, _EchoActor())],
        storage_dir="/tmp",
        transport=transport,
    )
    probe = transport.bind(Id(99))
    try:
        corpus = _hand_typed_corpus() + _seeded_corpus()
        for i, datagram in enumerate(corpus):
            probe.send(replica, datagram)
            if i % 100 == 0:  # interleave probes with the garbage
                probe.send(replica, wire_serialize(FuzzPing(i, "probe")))
        probe.send(replica, wire_serialize(FuzzPing(-1, "final")))
        replies = []
        while True:
            r = probe.recv(2.0)
            if r is None:
                break
            replies.append(wire_deserialize(r[0]))
            if replies[-1] == FuzzPong(-1):
                break
        assert FuzzPong(-1) in replies, (
            f"replica stopped answering after garbage; errors={runtime.errors!r}"
        )
        assert runtime.errors == []
    finally:
        probe.close()
        runtime.stop()
