"""Host/device fault-fate parity (ISSUE 16 satellite).

The chaos-ensemble bridge requires the device fate kernel
(``ensemble/fate.py``) to be *bit-equal* to the host ``FaultyTransport``
schedule: same fate words, same threshold decisions, same partition
predicate.  Property-style sweeps over (seed, link, n) triples pin that
here — first against the host kernel function, then against the actual
decision stream a live ``FaultyTransport`` journals, including partition
windows."""

import jax.numpy as jnp
import numpy as np
import pytest

from stateright_tpu.actor.ids import Id
from stateright_tpu.actor.transport import LoopbackTransport
from stateright_tpu.ensemble.fate import (
    device_fault_fate,
    link_seed_limbs,
    partition_cuts,
    rate_threshold,
)
from stateright_tpu.runtime.chaos import (
    FATE_DELAY,
    FATE_DRAWS,
    FATE_DROP,
    FATE_DUPLICATE,
    FATE_REORDER,
    ChaosSpec,
    FaultyTransport,
    Partition,
    _link_rng_seed,
    fault_draws,
    fault_fate_u32,
)
from stateright_tpu.runtime.journal import read_journal


def test_device_fate_kernel_is_bit_equal_to_host_kernel():
    """Sweep (seed, src, dst, n, k): the uint32-limb splitmix64 on device
    equals the arbitrary-precision host integer math bit-for-bit."""
    cases = []
    for seed in (0, 1, 42, 0xDEADBEEF, (1 << 63) + 12345):
        for src, dst in ((0, 1), (1, 0), (2, 1), (255, 254)):
            cases.append((seed, src, dst))
    ns = list(range(40)) + [1000, 65535, 1 << 20, (1 << 29) - 1]
    for seed, src, dst in cases:
        link_seed = _link_rng_seed(seed, Id(src), Id(dst))
        hi, lo = link_seed_limbs(seed, src, dst)
        assert (hi << 32) | lo == link_seed
        n_arr = jnp.asarray(ns, dtype=jnp.uint32)
        for k in range(FATE_DRAWS):
            dev = np.asarray(
                device_fault_fate(jnp.uint32(hi), jnp.uint32(lo), n_arr, k)
            )
            host = [fault_fate_u32(link_seed, n, k) for n in ns]
            assert dev.tolist() == host, (seed, src, dst, k)


def test_rate_threshold_is_exact_for_every_decision():
    """``fate/2**32 < rate`` on host ⟺ ``always or fate < thr`` on
    device — across boundary rates and the fates straddling them."""
    rates = [
        0.0, 1.0, 0.5, 0.25, 0.1, 0.3, 0.6, 1e-12,
        1.0 / 4294967296.0,  # exactly one fate word passes
        1.0 - 1.0 / 8589934592.0,  # within 2**-32 of 1.0: always-fire
        0.7 + 1e-16,
    ]
    for rate in rates:
        thr, always = rate_threshold(rate)
        fates = {0, 1, thr - 1, thr, thr + 1, (1 << 32) - 1}
        for fate in fates:
            if not 0 <= fate < (1 << 32):
                continue
            host = (fate / 4294967296.0) < rate
            device = always or fate < thr
            assert host == device, (rate, fate)
    with pytest.raises(ValueError):
        rate_threshold(1.5)
    with pytest.raises(ValueError):
        rate_threshold(-0.1)


def test_host_fault_draws_are_the_fate_words():
    link_seed = _link_rng_seed(7, Id(0), Id(1))
    for n in range(20):
        draws = fault_draws(link_seed, n)
        fates = [fault_fate_u32(link_seed, n, k) for k in range(FATE_DRAWS)]
        order = (FATE_DROP, FATE_REORDER, FATE_DUPLICATE, FATE_DELAY)
        for slot, k in enumerate(order):
            assert draws[slot] == fates[k] / 4294967296.0


def _device_decision_stream(spec, seed, links, count):
    """Predict the FaultyTransport decision stream with the device
    kernel + thresholds, mirroring the host precedence
    (drop → reorder-hold → duplicate / delay)."""
    out = {}
    for src, dst in links:
        faults = spec.faults_for(Id(src), Id(dst))
        thr = {
            FATE_DROP: rate_threshold(faults.drop),
            FATE_REORDER: rate_threshold(faults.reorder),
            FATE_DUPLICATE: rate_threshold(faults.duplicate),
        }
        hi, lo = link_seed_limbs(seed, src, dst)
        n_arr = jnp.arange(count, dtype=jnp.uint32)
        fates = {
            k: np.asarray(device_fault_fate(jnp.uint32(hi), jnp.uint32(lo), n_arr, k))
            for k in (FATE_DROP, FATE_REORDER, FATE_DUPLICATE, FATE_DELAY)
        }

        def fires(k, n):
            t, always = thr[k]
            return always or int(fates[k][n]) < t

        decisions = []
        for n in range(count):
            if fires(FATE_DROP, n):
                decisions.append("chaos_drop")
            elif fires(FATE_REORDER, n):
                decisions.append("chaos_reorder")
            elif fires(FATE_DUPLICATE, n):
                decisions.append("chaos_duplicate")
            else:
                decisions.append(None)
        out[(src, dst)] = decisions
    return out


def test_device_kernel_matches_faulty_transport_decision_stream(tmp_path):
    """Drive a real FaultyTransport and check the journaled fault stream
    against the device prediction, event for event."""
    spec = ChaosSpec.from_json(
        '{"drop": 0.3, "duplicate": 0.25, "reorder": 0.2,'
        ' "links": {"2->1": {"drop": 0.55, "duplicate": 0.1}}}'
    )
    seed = 20260807
    count = 120
    journal = tmp_path / "fate.jsonl"
    lb = LoopbackTransport()
    ft = FaultyTransport(lb, spec, seed=seed, journal=str(journal))
    a, c = ft.bind(Id(0)), ft.bind(Id(2))
    b = ft.bind(Id(1))
    for i in range(count):
        a.send(Id(1), f"a{i}".encode())
        c.send(Id(1), f"c{i}".encode())
    while b.recv(0.05) is not None:
        pass
    ft.close()

    host = {(0, 1): {}, (2, 1): {}}
    for e in read_journal(str(journal)):
        if e["event"].startswith("chaos_") and "n" in e:
            if e["event"] == "chaos_delay":
                continue  # no delay configured; kept for completeness
            host[(e["src"], e["dst"])][e["n"]] = e["event"]

    predicted = _device_decision_stream(spec, seed, [(0, 1), (2, 1)], count)
    for link in ((0, 1), (2, 1)):
        for n in range(count):
            assert host[link].get(n) == predicted[link][n], (link, n)
    # Sanity: the sweep actually exercised every fault kind.
    kinds = {e for d in host.values() for e in d.values()}
    assert kinds == {"chaos_drop", "chaos_reorder", "chaos_duplicate"}


def test_device_partition_predicate_matches_host_cuts():
    """``partition_cuts`` equals ``Partition.cuts`` on a sweep of group
    layouts × (src, dst) × window positions (host windows evaluated at
    the same scalar the device sees as its step index)."""
    layouts = [
        (frozenset([0, 1]), frozenset([2])),
        (frozenset([0]), frozenset([1]), frozenset([2, 3])),
        (frozenset([0, 2]),),  # a single group never cuts
    ]
    windows = [(0, None), (2, 5), (3, 3), (1, 8)]
    ids = range(5)  # includes id 4, absent from every layout
    for groups in layouts:
        group_of = {}
        for gi, g in enumerate(groups):
            for node in g:
                group_of[node] = gi
        for at, heal in windows:
            p = Partition(at=float(at), heal=None if heal is None else float(heal),
                          groups=groups)
            for src in ids:
                for dst in ids:
                    for step in range(10):
                        host = p.cuts(src, dst, elapsed=float(step))
                        dev = bool(
                            partition_cuts(
                                group_of.get(src, -1), group_of.get(dst, -1),
                                step, at, -1 if heal is None else heal,
                            )
                        )
                        assert host == dev, (groups, at, heal, src, dst, step)


def test_partition_window_in_live_transport_matches_device_predicate(tmp_path):
    """A permanent (at=0) partition — the one wall-clock-independent
    window — journals chaos_partition exactly where the device predicate
    cuts, with fate thresholds still deciding the uncut links."""
    spec = ChaosSpec.from_json(
        '{"drop": 0.4, "partitions": [{"at": 0, "groups": [[0], [1]]}]}'
    )
    seed = 99
    count = 60
    journal = tmp_path / "part.jsonl"
    lb = LoopbackTransport()
    ft = FaultyTransport(lb, spec, seed=seed, journal=str(journal))
    a = ft.bind(Id(0))
    b = ft.bind(Id(1))
    c = ft.bind(Id(2))
    for i in range(count):
        a.send(Id(1), f"x{i}".encode())  # crosses the cut: all partitioned
        a.send(Id(2), f"y{i}".encode())  # dst in no group: fate-decided
    while b.recv(0.02) is not None:
        pass
    while c.recv(0.02) is not None:
        pass
    ft.close()

    host = {(0, 1): {}, (0, 2): {}}
    for e in read_journal(str(journal)):
        if e["event"].startswith("chaos_") and "n" in e:
            host[(e["src"], e["dst"])][e["n"]] = e["event"]

    # 0->1 crosses groups: every datagram partitioned (predicate True).
    assert bool(partition_cuts(0, 1, 0, 0, -1))
    assert host[(0, 1)] == {n: "chaos_partition" for n in range(count)}
    # 0->2: id 2 is in no group (predicate False) — fate words decide.
    assert not bool(partition_cuts(0, -1, 0, 0, -1))
    predicted = _device_decision_stream(spec, seed, [(0, 2)], count)[(0, 2)]
    for n in range(count):
        assert host[(0, 2)].get(n) == predicted[n], n
