"""ActorModel golden tests.

Mirrors the reference's inline tests in src/actor/model.rs:832-1400:
state-space sizes under each network semantics, no-op suppression rules,
ordered-network delivery, undeliverable messages, crash/recover.
"""

from stateright_tpu import Expectation
from stateright_tpu.actor import (
    Actor,
    ActorModel,
    Deliver,
    Drop,
    Envelope,
    Id,
    Network,
    Out,
)
from stateright_tpu.models.ping_pong import Ping, PingPongCfg, Pong


def test_visits_expected_states_lossy_dup_max1():
    # Reference: src/actor/model.rs:841-961 — 14 unique states.
    checker = (
        PingPongCfg(maintains_history=False, max_nat=1)
        .into_model()
        .lossy_network_(True)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 14


def test_maintains_fixed_delta_despite_lossy_duplicating_network():
    # Reference: src/actor/model.rs:1044-1057 — 4,094 unique states.
    checker = (
        PingPongCfg(maintains_history=False, max_nat=5)
        .into_model()
        .lossy_network_(True)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 4094
    checker.assert_no_discovery("delta within 1")


def test_may_never_reach_max_on_lossy_network():
    checker = (
        PingPongCfg(maintains_history=False, max_nat=5)
        .into_model()
        .lossy_network_(True)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 4094
    # Can lose the first message and get stuck.
    checker.assert_discovery(
        "must reach max", [Drop(Envelope(Id(0), Id(1), Ping(0)))]
    )


def test_eventually_reaches_max_on_perfect_delivery_network():
    checker = (
        PingPongCfg(maintains_history=False, max_nat=5)
        .into_model()
        .init_network_(Network.new_unordered_nonduplicating())
        .lossy_network_(False)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    checker.assert_no_discovery("must reach max")


def test_can_reach_max():
    checker = (
        PingPongCfg(maintains_history=False, max_nat=5)
        .into_model()
        .lossy_network_(False)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    assert checker.discovery("can reach max").last_state().actor_states == (4, 5)


def test_might_never_reach_beyond_max():
    checker = (
        PingPongCfg(maintains_history=False, max_nat=5)
        .into_model()
        .init_network_(Network.new_unordered_nonduplicating())
        .lossy_network_(False)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    assert checker.discovery("must exceed max").last_state().actor_states == (5, 5)


def test_maintains_history():
    # Reference: src/actor/model.rs (history variant) — with history
    # tracking on, the same model keeps #in/#out counters consistent.
    checker = (
        PingPongCfg(maintains_history=True, max_nat=3)
        .into_model()
        .lossy_network_(False)
        .checker()
        .spawn_bfs()
        .join()
    )
    checker.assert_no_discovery("#in <= #out")


class _NoOpActor(Actor):
    """Client sends Ignored then Interesting; server only reacts to
    Interesting.  Reference: src/actor/model.rs:963-1042."""

    def __init__(self, server=None):
        self.server = server

    def on_start(self, id, storage, o: Out):
        if self.server is not None:
            o.send(self.server, "Ignored")
            o.send(self.server, "Interesting")
        return "Awaiting an interesting message."

    def on_msg(self, id, state, src, msg, o: Out):
        if msg == "Interesting":
            return "Got an interesting message."
        return None


def _no_op_model():
    return (
        ActorModel()
        .actor(_NoOpActor(server=Id(1)))
        .actor(_NoOpActor())
        .lossy_network_(False)
        .property(Expectation.ALWAYS, "Check everything", lambda _m, _s: True)
    )


def test_no_op_depends_on_network():
    assert (
        _no_op_model()
        .init_network_(Network.new_unordered_duplicating())
        .checker()
        .spawn_bfs()
        .join()
        .unique_state_count()
        == 2
    )
    assert (
        _no_op_model()
        .init_network_(Network.new_unordered_nonduplicating())
        .checker()
        .spawn_bfs()
        .join()
        .unique_state_count()
        == 2
    )
    assert (
        _no_op_model()
        .init_network_(Network.new_ordered())
        .checker()
        .spawn_bfs()
        .join()
        .unique_state_count()
        == 3
    )


class _UnitActor(Actor):
    def on_start(self, id, storage, o: Out):
        return ()


def test_handles_undeliverable_messages():
    # Reference: src/actor/model.rs:1151-1167.
    checker = (
        ActorModel()
        .actor(_UnitActor())
        .property(Expectation.ALWAYS, "unused", lambda _m, _s: True)
        .init_network_(
            Network.new_unordered_duplicating([Envelope(Id(0), Id(99), ())])
        )
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 1


class _CountdownActor(Actor):
    """Actor 0 sends 2 then 1 to actor 1, which appends what it receives.
    Reference: src/actor/model.rs:1169-1243."""

    def on_start(self, id, storage, o: Out):
        if id == Id(0):
            o.send(Id(1), 2)
            o.send(Id(1), 1)
        return ()

    def on_msg(self, id, state, src, msg, o: Out):
        return state + (msg,)


def _countdown_model():
    return (
        ActorModel()
        .add_actors([_CountdownActor(), _CountdownActor()])
        .property(Expectation.ALWAYS, "", lambda _m, _s: True)
    )


def test_handles_ordered_network_flag():
    from stateright_tpu import StateRecorder

    recorder, accessor = StateRecorder.new_with_accessor()
    (
        _countdown_model()
        .init_network_(Network.new_ordered())
        .checker()
        .visitor(recorder)
        .spawn_bfs()
        .join()
    )
    recipient_states = {s.actor_states[1] for s in accessor()}
    assert recipient_states == {(), (2,), (2, 1)}

    recorder, accessor = StateRecorder.new_with_accessor()
    (
        _countdown_model()
        .init_network_(Network.new_unordered_nonduplicating())
        .checker()
        .visitor(recorder)
        .spawn_bfs()
        .join()
    )
    recipient_states = {s.actor_states[1] for s in accessor()}
    assert recipient_states == {(), (1,), (2,), (1, 2), (2, 1)}


class _CrashActor(Actor):
    """Persists its counter; volatile until saved."""

    def on_start(self, id, storage, o: Out):
        if storage is not None:
            return storage
        return 0

    def on_msg(self, id, state, src, msg, o: Out):
        o.save(state + 1)
        return state + 1


def test_crash_and_recover():
    checker = (
        ActorModel()
        .actor(_CrashActor())
        .init_network_(
            Network.new_unordered_duplicating([Envelope(Id(1), Id(0), "bump")])
        )
        .max_crashes_(1)
        .property(
            Expectation.ALWAYS,
            "storage is never ahead of state",
            lambda _m, s: all(
                (s.actor_storages[i] or 0) <= s.actor_states[i] or s.crashed[i]
                for i in range(len(s.actor_states))
            ),
        )
        .within_boundary_(lambda _c, s: all(c <= 3 for c in s.actor_states))
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.is_done()
    # Crashing wipes volatile state; recovery restores from storage.
    assert checker.unique_state_count() > 4


def test_as_svg_message_sequence_diagram():
    """ActorModel.as_svg renders a message-sequence chart for a path
    (reference src/actor/model.rs:600-821; structure snapshot mirrors the
    reference's Explorer SVG test, src/checker/explorer.rs:403-522)."""
    from stateright_tpu.core.path import Path
    from stateright_tpu.models.ping_pong import Ping, PingPongCfg, Pong
    from stateright_tpu.actor.model import Deliver

    model = PingPongCfg(maintains_history=False, max_nat=2).into_model()
    init = model.init_states()[0]
    path = Path.from_actions(
        model,
        init,
        [
            Deliver(Id(0), Id(1), Ping(0)),
            Deliver(Id(1), Id(0), Pong(0)),
        ],
    )
    svg = model.as_svg(path)
    assert svg is not None and svg.startswith("<svg") and svg.endswith("</svg>")
    # Two actor timelines with labels.
    assert svg.count("svg-actor-timeline") == 2
    assert "0 Pinger" in svg or ">0<" in svg or "svg-actor-label" in svg
    # Two delivery arrows: Ping(0) was sent at init (time 0) from actor 0,
    # delivered at time 1 on actor 1's line; Pong(0) sent at time 1,
    # delivered at time 2.
    assert svg.count("svg-event-line") == 2
    assert "<line x1='0' x2='100' y1='0' y2='30'" in svg
    assert "<line x1='100' x2='0' y1='30' y2='60'" in svg
    # Labels drawn last, over the shapes.
    assert "Ping(value=0)" in svg and "Pong(value=0)" in svg
    assert svg.index("svg-event-label") > svg.index("svg-event-line")


def test_as_svg_marks_timeouts_and_crashes():
    from stateright_tpu.core.path import Path
    from stateright_tpu.actor.model import Crash, Timeout

    class Ticker(Actor):
        def name(self):
            return "Ticker"

        def on_start(self, id, storage, o: Out):
            o.set_timer("tick")
            return 0

        def on_timeout(self, id, state, timer, o: Out):
            o.set_timer("tick")
            return state + 1

    model = (
        ActorModel()
        .actor(Ticker())
        .max_crashes_(1)
        .within_boundary_(lambda _c, s: all(c <= 3 for c in s.actor_states))
    )
    init = model.init_states()[0]
    path = Path.from_actions(
        model, init, [Timeout(Id(0), "tick"), Crash(Id(0))]
    )
    svg = model.as_svg(path)
    assert svg is not None
    assert svg.count("svg-event-shape'") >= 2  # circle markers
    assert "Timeout(" in svg and ">Crash<" in svg
