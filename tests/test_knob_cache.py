"""runtime/knob_cache.py under service load: stale-entry drop,
concurrent read/write from multiple jobs, and the scheduler-level
warm-start behaviors the checking service relies on (docs/SERVING.md)."""

import json
import os
import threading

import pytest

from stateright_tpu.runtime.knob_cache import (
    KNOBS_FILE, drop_knobs, load_knobs, store_knobs,
)


def test_store_load_drop_roundtrip(tmp_path):
    d = str(tmp_path)
    assert load_knobs(d, "k") is None
    store_knobs(d, "k", {"capacity": 1 << 14, "dedup_factor": 4},
                unique=288, depth=11)
    assert load_knobs(d, "k") == {"capacity": 1 << 14, "dedup_factor": 4}
    # Meta rides alongside for humans but is never read back as knobs.
    raw = json.load(open(os.path.join(d, KNOBS_FILE)))
    assert raw["k"]["unique"] == 288
    drop_knobs(d, "k")
    assert load_knobs(d, "k") is None
    drop_knobs(d, "k")  # idempotent


def test_stale_entry_drop_is_per_key(tmp_path):
    """The golden-gate staleness contract: dropping one failed entry
    leaves every other workload's knobs intact."""
    d = str(tmp_path)
    store_knobs(d, "good", {"capacity": 1024})
    store_knobs(d, "stale", {"capacity": 64})
    drop_knobs(d, "stale")
    assert load_knobs(d, "stale") is None
    assert load_knobs(d, "good") == {"capacity": 1024}


def test_torn_or_garbage_file_degrades_to_rediscovery(tmp_path):
    d = str(tmp_path)
    with open(os.path.join(d, KNOBS_FILE), "w") as fh:
        fh.write('{"k": {"knobs": {"capacity": 10')  # torn writer
    assert load_knobs(d, "k") is None
    store_knobs(d, "k", {"capacity": 32})  # recovers by overwriting
    assert load_knobs(d, "k") == {"capacity": 32}


def test_concurrent_jobs_never_lose_each_others_entries(tmp_path):
    """Service load: many jobs storing/reading different keys through
    one cache dir concurrently.  Every writer's final entry must
    survive (in-process mutations are read-merge-write under the module
    lock) and the file must always parse (atomic write + rename)."""
    d = str(tmp_path)
    writers, rounds = 8, 30
    errors = []

    def job(k):
        try:
            key = f"workload-{k}"
            for i in range(rounds):
                store_knobs(d, key, {"capacity": 1024 + i, "round": i})
                got = load_knobs(d, f"workload-{(k + 1) % writers}")
                assert got is None or isinstance(got, dict)
                if i % 10 == 9:
                    drop_knobs(d, f"tmp-{k}")
        except Exception as e:  # surfaced below; threads must not hide it
            errors.append(e)

    threads = [threading.Thread(target=job, args=(k,))
               for k in range(writers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for k in range(writers):
        assert load_knobs(d, f"workload-{k}") == {
            "capacity": 1024 + rounds - 1, "round": rounds - 1,
        }
    json.load(open(os.path.join(d, KNOBS_FILE)))  # parses whole


def test_scheduler_drops_stale_entry_and_recovers(tmp_path):
    """Service-level stale-entry drop (the golden-gate analog): a cached
    entry the engine can no longer accept — here a knob name from a
    retired protocol version, the failure mode of a cache outliving an
    engine change — fails the spawn, is dropped, and the job recovers
    with a fresh run whose working geometry replaces it."""
    pytest.importorskip("jax")
    from stateright_tpu.runtime.knob_cache import knob_key
    from stateright_tpu.serve import CheckService
    from stateright_tpu.serve.workloads import workload_label

    d = str(tmp_path / "knobs")
    key = knob_key(workload_label("twophase", 3, None))
    store_knobs(d, key, {"retired_knob_name": 7})
    svc = CheckService(journal=str(tmp_path / "j.jsonl"),
                       knob_cache_dir=d)
    try:
        job = svc.submit({"workload": "twophase", "n": 3})
        assert job.wait(300)
        assert job.state == "done", job.error
        assert job.result["unique_state_count"] == 288
        # The poisoned entry was dropped and replaced by the fresh
        # run's working geometry.
        knobs = load_knobs(d, key)
        assert knobs is not None and "retired_knob_name" not in knobs
        from stateright_tpu.runtime.journal import read_journal

        events = [e["event"] for e in read_journal(str(tmp_path / "j.jsonl"))]
        assert "knobs_dropped" in events
    finally:
        svc.scheduler.shutdown()


def test_sort_rung_cold_climb_persists_and_warm_run_skips_retry(tmp_path):
    """The sort-geometry rung rides the knob cache like bucket_slack
    (ISSUE 12 satellite): a cold run forced onto the smallest rung
    climbs the ladder (journaled flag-4 grows), its tuned_kwargs carry
    the discovered rung, and an identical warm run spawned from the
    cached knobs starts AT that rung — zero rung retries, identical
    fingerprint set."""
    pytest.importorskip("jax")
    import jax
    import numpy as np

    from stateright_tpu.models.twophase import TwoPhaseSys
    from stateright_tpu.parallel.wave_loop import SORT_RUNG_MIN
    from stateright_tpu.runtime.journal import read_journal

    d = str(tmp_path / "knobs")
    key = "twophase4|test|sort-rung"
    cpu = jax.devices("cpu")[0]

    def rung_grows(journal):
        return [
            e for e in read_journal(journal)
            if e["event"] == "grow"
            and e.get("flags", 0) & 4
            and "sort_lanes=" in str(e.get("grown", ""))
        ]

    j_cold = str(tmp_path / "cold.jsonl")
    cold = TwoPhaseSys(rm_count=4).checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=cpu,
        sort_lanes=SORT_RUNG_MIN, journal=j_cold,
    ).join()
    assert cold.unique_state_count() == 1568
    assert rung_grows(j_cold), "cold run never climbed — forcing is dead"
    tuned = cold.tuned_kwargs()
    assert tuned["sort_lanes"] > SORT_RUNG_MIN
    store_knobs(d, key, tuned, golden_unique=1568)

    warm_knobs = load_knobs(d, key)
    assert warm_knobs == {k: int(v) for k, v in tuned.items()}
    j_warm = str(tmp_path / "warm.jsonl")
    warm = TwoPhaseSys(rm_count=4).checker().spawn_tpu(
        device=cpu, journal=j_warm, **warm_knobs,
    ).join()
    assert warm.unique_state_count() == 1568
    assert not rung_grows(j_warm), "warm run re-paid the rung ramp"
    assert warm.metrics()["sort_lanes"] == tuned["sort_lanes"]
    assert np.array_equal(
        warm.discovered_fingerprints(), cold.discovered_fingerprints()
    )


def test_second_job_skips_autotune_warm_start(tmp_path):
    """Satellite pin: the second identical job loads the first job's
    final geometry instead of re-running discovery — asserted via the
    per-job knob_cache_hit flag and the stored entry equality."""
    pytest.importorskip("jax")
    from stateright_tpu.runtime.knob_cache import knob_key
    from stateright_tpu.serve import CheckService
    from stateright_tpu.serve.workloads import workload_label

    d = str(tmp_path / "knobs")
    svc = CheckService(knob_cache_dir=d)
    try:
        j1 = svc.submit({"workload": "fixtures", "n": 5})
        assert j1.wait(300) and j1.state == "done", j1.error
        stored = load_knobs(d, knob_key(workload_label("fixtures", 5, None)))
        assert stored is not None
        j2 = svc.submit({"workload": "fixtures", "n": 5})
        assert j2.wait(300) and j2.state == "done", j2.error
        assert j2.result["knob_cache_hit"] is True
        assert j2.result["unique_state_count"] == j1.result[
            "unique_state_count"
        ]
    finally:
        svc.scheduler.shutdown()
