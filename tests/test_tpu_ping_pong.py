"""Actor-model-on-TPU: the compiled ping_pong golden configurations.

Proves the actor-layer compilation path — network-in-state (duplicating
set + last-delivered marker), model-generated Deliver/Drop action families,
unordered no-op suppression, boundary filtering, and all three property
expectations — against the host oracle's golden counts
(src/actor/model.rs:875,1055,1095).
"""

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.ping_pong import PingPongCfg  # noqa: E402
from stateright_tpu.models.ping_pong_compiled import (  # noqa: E402
    compiled_ping_pong,
)


def _parity(max_nat, lossy, golden_unique):
    model = (
        PingPongCfg(maintains_history=False, max_nat=max_nat)
        .into_model()
        .lossy_network_(lossy)
    )
    host = model.checker().spawn_bfs().join()
    tpu = (
        model.checker()
        .spawn_tpu(
            capacity=1 << 13,
            max_frontier=1 << 11,
            device=jax.devices("cpu")[0],
            compiled=compiled_ping_pong(model),
        )
        .join()
    )
    assert host.unique_state_count() == golden_unique
    assert tpu.unique_state_count() == golden_unique
    assert tpu.state_count() == host.state_count()
    assert tpu.max_depth() == host.max_depth()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    return host, tpu


def test_ping_pong_lossy_duplicating_max1():
    # 14 unique states (src/actor/model.rs:875); "must reach max" has a
    # counterexample (drop everything), "must exceed max" is unreachable.
    _host, tpu = _parity(1, True, 14)
    d = tpu.discoveries()
    assert "can reach max" in d
    assert "must reach max" in d
    assert "must exceed max" in d


@pytest.mark.slow
def test_ping_pong_lossy_duplicating_max5():
    _parity(5, True, 4094)  # src/actor/model.rs:1055


def test_ping_pong_lossless_max5():
    # 11 unique states (src/actor/model.rs:1095); without loss the counter
    # must climb, so only the impossible "must exceed max" is discovered.
    _host, tpu = _parity(5, False, 11)
    d = tpu.discoveries()
    assert "must reach max" not in d
    assert "must exceed max" in d
