"""UDP actor runtime, ordered reliable link, and write-once register harness.

Reference: src/actor/spawn.rs (real-network event loop + storage recovery),
src/actor/ordered_reliable_link.rs:279-385 (the ORL's own model-checked
verification), src/actor/write_once_register.rs.
"""

import threading
import time
from dataclasses import dataclass
from typing import Any, Optional

import pytest

from stateright_tpu import Expectation
from stateright_tpu.actor import ActorModel, Deliver as DeliverAction, Id, Network, Out
from stateright_tpu.actor.base import Actor
from stateright_tpu.actor.ordered_reliable_link import (
    ActorWrapper,
    Deliver,
    LinkState,
)
from stateright_tpu.actor.spawn import (
    json_deserialize,
    json_serialize,
    spawn,
)
from stateright_tpu.actor.write_once_register import (
    Get,
    GetOk,
    Put,
    PutFail,
    PutOk,
    WORegisterClient,
    WORegisterServer,
    record_invocations,
    record_returns,
)
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.write_once_register import WORegister


# --- ordered reliable link: model-checked (reference:319-385) ----------------


class OrlSender(Actor):
    def __init__(self, receiver_id):
        self.receiver_id = receiver_id

    def on_start(self, id, storage, o: Out):
        o.send(self.receiver_id, 42)
        o.send(self.receiver_id, 43)
        return ()

    def on_msg(self, id, state, src, msg, o: Out):
        return None


class OrlReceiver(Actor):
    def on_start(self, id, storage, o: Out):
        return ()

    def on_msg(self, id, state, src, msg, o: Out):
        return state + ((src, msg),)


def _orl_model():
    def received(state):
        return state.actor_states[1].wrapped_state

    return (
        ActorModel(cfg=None, init_history=None)
        .actor(ActorWrapper.with_default_timeout(OrlSender(Id(1))))
        .actor(ActorWrapper.with_default_timeout(OrlReceiver()))
        .init_network_(Network.new_unordered_duplicating())
        .lossy_network_(True)
        .property(
            Expectation.ALWAYS,
            "no redelivery",
            lambda _m, s: sum(1 for (_, v) in received(s) if v == 42) < 2
            and sum(1 for (_, v) in received(s) if v == 43) < 2,
        )
        .property(
            Expectation.ALWAYS,
            "ordered",
            lambda _m, s: all(
                a[1] <= b[1]
                for a, b in zip(received(s), received(s)[1:])
            ),
        )
        .property(
            Expectation.SOMETIMES,
            "delivered",
            lambda _m, s: received(s) == ((Id(0), 42), (Id(0), 43)),
        )
        .within_boundary_(lambda _cfg, s: len(s.network) < 4)
    )


@pytest.fixture(scope="module")
def orl_checker():
    return _orl_model().checker().spawn_bfs().join()


def test_orl_messages_are_not_delivered_twice(orl_checker):
    orl_checker.assert_no_discovery("no redelivery")


def test_orl_messages_are_delivered_in_order(orl_checker):
    orl_checker.assert_no_discovery("ordered")


def test_orl_messages_are_eventually_delivered(orl_checker):
    orl_checker.assert_discovery(
        "delivered",
        [
            DeliverAction(src=Id(0), dst=Id(1), msg=Deliver(1, 42)),
            DeliverAction(src=Id(0), dst=Id(1), msg=Deliver(2, 43)),
        ],
    )


# --- write-once register harness ---------------------------------------------


@dataclass(frozen=True)
class WOServerState:
    value: Optional[Any]


class WOServer(Actor):
    """Single-copy write-once server: first Put wins, later Puts fail."""

    def on_start(self, id, storage, o: Out):
        return WOServerState(value=None)

    def on_msg(self, id, state, src, msg, o: Out):
        if isinstance(msg, Put):
            if state.value is None:
                o.send(src, PutOk(msg.request_id))
                return WOServerState(value=msg.value)
            o.send(src, PutFail(msg.request_id))
            return None
        if isinstance(msg, Get):
            o.send(src, GetOk(msg.request_id, state.value))
            return None
        return None


def test_write_once_register_harness_linearizable():
    model = (
        ActorModel(
            cfg=None, init_history=LinearizabilityTester(WORegister(None))
        )
        .actor(WORegisterServer(WOServer()))
        .actor(WORegisterClient(put_count=1, server_count=1))
        .actor(WORegisterClient(put_count=1, server_count=1))
        .init_network_(Network.new_unordered_nonduplicating())
        .property(
            Expectation.ALWAYS,
            "linearizable",
            lambda _m, s: s.history.serialized_history() is not None,
        )
        .property(
            Expectation.SOMETIMES,
            "value chosen",
            lambda _m, s: any(
                isinstance(e.msg, GetOk) and e.msg.value is not None
                for e in s.network.iter_deliverable()
            ),
        )
        .record_msg_in(record_returns)
        .record_msg_out(record_invocations)
    )
    checker = model.checker().spawn_bfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() > 10


# --- UDP runtime (reference: src/actor/spawn.rs:279-385) ---------------------


class CountingServer(Actor):
    """Counts received pings, persisting the count; replies with the total."""

    def on_start(self, id, storage, o: Out):
        return storage if storage is not None else 0

    def on_msg(self, id, state, src, msg, o: Out):
        if msg == "ping":
            o.save(state + 1)
            o.send(src, ["total", state + 1])
            return state + 1
        return None


class CollectingClient(Actor):
    """Sends one ping per timer tick until 3 replies arrive — resilient to
    the server binding after the client starts (plain UDP racing, as in the
    reference runtime)."""

    def __init__(self, server_id, results):
        self.server_id = server_id
        self.results = results

    def on_start(self, id, storage, o: Out):
        o.set_timer("ping", (0.02, 0.03))
        return ()

    def on_timeout(self, id, state, timer, o: Out):
        if len(self.results) < 3:
            o.send(self.server_id, "ping")
            o.set_timer("ping", (0.02, 0.03))
        return None

    def on_msg(self, id, state, src, msg, o: Out):
        if isinstance(msg, list) and msg[0] == "total":
            self.results.append(msg[1])
        return None


def test_udp_runtime_delivers_and_persists(tmp_path):
    server_id = Id.from_socket_addr((127, 0, 0, 1), 34001)
    client_id = Id.from_socket_addr((127, 0, 0, 1), 34002)
    results = []
    runtime = spawn(
        json_serialize,
        json_deserialize,
        json_serialize,
        json_deserialize,
        [
            (server_id, CountingServer()),
            (client_id, CollectingClient(server_id, results)),
        ],
        storage_dir=str(tmp_path),
    )
    deadline = time.time() + 10
    while len(results) < 3 and time.time() < deadline:
        time.sleep(0.02)
    runtime.stop()
    assert results[:3] == [1, 2, 3]
    # Storage survived: a restarted server resumes from the saved count
    # (the crash/recover pattern of src/actor/spawn.rs:279-385).
    results2 = []
    runtime2 = spawn(
        json_serialize,
        json_deserialize,
        json_serialize,
        json_deserialize,
        [
            (server_id, CountingServer()),
            (client_id, CollectingClient(server_id, results2)),
        ],
        storage_dir=str(tmp_path),
    )
    deadline = time.time() + 10
    while len(results2) < 3 and time.time() < deadline:
        time.sleep(0.02)
    runtime2.stop()
    # The restarted server resumed from its persisted count: totals continue
    # past everything phase one saw instead of restarting at 1.
    assert len(results2) >= 3
    assert results2[0] > max(results)
    assert results2 == sorted(results2)


class TimerActor(Actor):
    """Exercises SetTimer: emits a tick to a collector after a short delay."""

    def __init__(self, collector_id):
        self.collector_id = collector_id

    def on_start(self, id, storage, o: Out):
        o.set_timer("tick", (0.01, 0.02))
        return ()

    def on_timeout(self, id, state, timer, o: Out):
        if timer == "tick":
            o.send(self.collector_id, "ticked")
        return None


class Collector(Actor):
    def __init__(self, results):
        self.results = results

    def on_start(self, id, storage, o: Out):
        return ()

    def on_msg(self, id, state, src, msg, o: Out):
        self.results.append(msg)
        return None


def test_udp_runtime_timers_fire(tmp_path):
    timer_id = Id.from_socket_addr((127, 0, 0, 1), 34003)
    collector_id = Id.from_socket_addr((127, 0, 0, 1), 34004)
    results = []
    runtime = spawn(
        json_serialize,
        json_deserialize,
        json_serialize,
        json_deserialize,
        [
            (timer_id, TimerActor(collector_id)),
            (collector_id, Collector(results)),
        ],
        storage_dir=str(tmp_path),
    )
    deadline = time.time() + 10
    while not results and time.time() < deadline:
        time.sleep(0.02)
    runtime.stop()
    assert results == ["ticked"]
