"""UDP actor runtime, ordered reliable link, and write-once register harness.

Reference: src/actor/spawn.rs (real-network event loop + storage recovery),
src/actor/ordered_reliable_link.rs:279-385 (the ORL's own model-checked
verification), src/actor/write_once_register.rs.
"""

import time
from dataclasses import dataclass
from typing import Any, Optional

import pytest

from stateright_tpu import Expectation
from stateright_tpu.actor import ActorModel, Deliver as DeliverAction, Id, Network, Out
from stateright_tpu.actor.base import Actor
from stateright_tpu.actor.ordered_reliable_link import (
    ActorWrapper,
    Deliver,
    LinkState,
)
from stateright_tpu.actor.spawn import (
    json_deserialize,
    json_serialize,
    spawn,
)
from stateright_tpu.actor.write_once_register import (
    Get,
    GetOk,
    Put,
    PutFail,
    PutOk,
    WORegisterClient,
    WORegisterServer,
    record_invocations,
    record_returns,
)
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.write_once_register import WORegister


# --- ordered reliable link: model-checked (reference:319-385) ----------------


class OrlSender(Actor):
    def __init__(self, receiver_id):
        self.receiver_id = receiver_id

    def on_start(self, id, storage, o: Out):
        o.send(self.receiver_id, 42)
        o.send(self.receiver_id, 43)
        return ()

    def on_msg(self, id, state, src, msg, o: Out):
        return None


class OrlReceiver(Actor):
    def on_start(self, id, storage, o: Out):
        return ()

    def on_msg(self, id, state, src, msg, o: Out):
        return state + ((src, msg),)


def _orl_model(**wrapper_kwargs):
    def received(state):
        return state.actor_states[1].wrapped_state

    return (
        ActorModel(cfg=None, init_history=None)
        .actor(ActorWrapper(OrlSender(Id(1)), **wrapper_kwargs))
        .actor(ActorWrapper(OrlReceiver(), **wrapper_kwargs))
        .init_network_(Network.new_unordered_duplicating())
        .lossy_network_(True)
        .property(
            Expectation.ALWAYS,
            "no redelivery",
            lambda _m, s: sum(1 for (_, v) in received(s) if v == 42) < 2
            and sum(1 for (_, v) in received(s) if v == 43) < 2,
        )
        .property(
            Expectation.ALWAYS,
            "ordered",
            lambda _m, s: all(
                a[1] <= b[1]
                for a, b in zip(received(s), received(s)[1:])
            ),
        )
        .property(
            Expectation.SOMETIMES,
            "delivered",
            lambda _m, s: received(s) == ((Id(0), 42), (Id(0), 43)),
        )
        .within_boundary_(lambda _cfg, s: len(s.network) < 4)
    )


@pytest.fixture(scope="module")
def orl_checker():
    return _orl_model().checker().spawn_bfs().join()


def test_orl_messages_are_not_delivered_twice(orl_checker):
    orl_checker.assert_no_discovery("no redelivery")


def test_orl_messages_are_delivered_in_order(orl_checker):
    orl_checker.assert_no_discovery("ordered")


def test_orl_messages_are_eventually_delivered(orl_checker):
    orl_checker.assert_discovery(
        "delivered",
        [
            DeliverAction(src=Id(0), dst=Id(1), msg=Deliver(1, 42)),
            DeliverAction(src=Id(0), dst=Id(1), msg=Deliver(2, 43)),
        ],
    )


def test_orl_backoff_config_does_not_change_model(orl_checker):
    """The runtime retransmission hardening (exponential backoff, capped
    interval) must be invisible to the checker: backoff only scales timer
    *durations*, which the model ignores (src/actor/model.rs:79-81).
    Same properties, same state space, bit-identical transitions — over
    the same lossy unordered_duplicating network as the reference's own
    ORL verification."""
    checker = (
        _orl_model(
            resend_interval=(0.05, 0.1),
            backoff_factor=2.0,
            max_resend_interval=8.0,
        )
        .checker()
        .spawn_bfs()
        .join()
    )
    checker.assert_no_discovery("no redelivery")
    checker.assert_no_discovery("ordered")
    checker.assert_discovery(
        "delivered",
        [
            DeliverAction(src=Id(0), dst=Id(1), msg=Deliver(1, 42)),
            DeliverAction(src=Id(0), dst=Id(1), msg=Deliver(2, 43)),
        ],
    )
    assert checker.unique_state_count() == orl_checker.unique_state_count()


# --- ORL runtime hardening: backoff + give-up (unit level) -------------------


def test_orl_resend_interval_backs_off_exponentially_with_cap():
    w = ActorWrapper(
        OrlReceiver(),
        resend_interval=(0.1, 0.2),
        backoff_factor=2.0,
        max_resend_interval=1.0,
    )
    assert w._next_resend_interval() == (0.1, 0.2)
    w._resend_attempts = 2
    assert w._next_resend_interval() == (0.4, 0.8)
    w._resend_attempts = 3
    assert w._next_resend_interval() == (0.8, 1.0)  # hi capped
    w._resend_attempts = 50
    assert w._next_resend_interval() == (1.0, 1.0)  # both capped
    # A long-partitioned peer (or a deep model check) can push the
    # attempt counter arbitrarily high: the exponent must saturate, not
    # raise OverflowError inside on_timeout and kill the actor thread.
    w._resend_attempts = 100_000
    assert w._next_resend_interval() == (1.0, 1.0)


def test_orl_gives_up_after_max_resends_and_reports_dropped():
    from stateright_tpu.actor.base import SaveCmd, SendCmd, SetTimerCmd
    from stateright_tpu.actor.ordered_reliable_link import NETWORK_TIMER

    given_up = []
    w = ActorWrapper(
        OrlReceiver(),
        resend_interval=(0.01, 0.02),
        max_resends=2,
        on_give_up=lambda id, dropped: given_up.append((id, dropped)),
    )
    state = LinkState(
        next_send_seq=3,
        msgs_pending_ack=((1, (Id(1), 42)), (2, (Id(1), 43))),
        last_delivered_seqs=(),
        wrapped_state=(),
        wrapped_storage=None,
    )
    # Two resend rounds are allowed...
    for expected_attempts in (1, 2):
        out = Out()
        assert w.on_timeout(Id(0), state, NETWORK_TIMER, out) is None
        assert w._resend_attempts == expected_attempts
        sends = [c for c in out if isinstance(c, SendCmd)]
        assert [c.msg for c in sends] == [Deliver(1, 42), Deliver(2, 43)]
    # ...the third gives up: pending cleared, persisted, callback fired.
    out = Out()
    next_state = w.on_timeout(Id(0), state, NETWORK_TIMER, out)
    assert next_state.msgs_pending_ack == ()
    assert not any(isinstance(c, SendCmd) for c in out)
    assert any(isinstance(c, SetTimerCmd) for c in out)  # timer re-armed
    assert any(isinstance(c, SaveCmd) for c in out)  # give-up is durable
    assert given_up == [(Id(0), ((1, (Id(1), 42)), (2, (Id(1), 43))))]
    assert w._resend_attempts == 0  # ladder reset for future sends


def test_orl_give_up_is_per_message_not_per_wrapper():
    """Exhausting one message's resend budget (e.g. to a partitioned
    peer) must not drop a freshly-sent message to a healthy peer."""
    from stateright_tpu.actor.base import SendCmd
    from stateright_tpu.actor.ordered_reliable_link import NETWORK_TIMER

    given_up = []
    w = ActorWrapper(
        OrlReceiver(),
        resend_interval=(0.01, 0.02),
        max_resends=1,
        on_give_up=lambda id, dropped: given_up.append(dropped),
    )
    stuck_only = LinkState(
        next_send_seq=2,
        msgs_pending_ack=((1, (Id(1), "stuck")),),
        last_delivered_seqs=(),
        wrapped_state=(),
        wrapped_storage=None,
    )
    out = Out()
    assert w.on_timeout(Id(0), stuck_only, NETWORK_TIMER, out) is None  # 1st resend
    both = LinkState(
        next_send_seq=3,
        msgs_pending_ack=((1, (Id(1), "stuck")), (2, (Id(2), "fresh"))),
        last_delivered_seqs=(),
        wrapped_state=(),
        wrapped_storage=None,
    )
    out = Out()
    next_state = w.on_timeout(Id(0), both, NETWORK_TIMER, out)
    assert next_state.msgs_pending_ack == ((2, (Id(2), "fresh")),)
    sends = [c for c in out if isinstance(c, SendCmd)]
    assert [c.msg for c in sends] == [Deliver(2, "fresh")]
    assert given_up == [((1, (Id(1), "stuck")),)]


# --- write-once register harness ---------------------------------------------


@dataclass(frozen=True)
class WOServerState:
    value: Optional[Any]


class WOServer(Actor):
    """Single-copy write-once server: first Put wins, later Puts fail."""

    def on_start(self, id, storage, o: Out):
        return WOServerState(value=None)

    def on_msg(self, id, state, src, msg, o: Out):
        if isinstance(msg, Put):
            if state.value is None:
                o.send(src, PutOk(msg.request_id))
                return WOServerState(value=msg.value)
            o.send(src, PutFail(msg.request_id))
            return None
        if isinstance(msg, Get):
            o.send(src, GetOk(msg.request_id, state.value))
            return None
        return None


def test_write_once_register_harness_linearizable():
    model = (
        ActorModel(
            cfg=None, init_history=LinearizabilityTester(WORegister(None))
        )
        .actor(WORegisterServer(WOServer()))
        .actor(WORegisterClient(put_count=1, server_count=1))
        .actor(WORegisterClient(put_count=1, server_count=1))
        .init_network_(Network.new_unordered_nonduplicating())
        .property(
            Expectation.ALWAYS,
            "linearizable",
            lambda _m, s: s.history.serialized_history() is not None,
        )
        .property(
            Expectation.SOMETIMES,
            "value chosen",
            lambda _m, s: any(
                isinstance(e.msg, GetOk) and e.msg.value is not None
                for e in s.network.iter_deliverable()
            ),
        )
        .record_msg_in(record_returns)
        .record_msg_out(record_invocations)
    )
    checker = model.checker().spawn_bfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() > 10


# --- UDP runtime (reference: src/actor/spawn.rs:279-385) ---------------------


class CountingServer(Actor):
    """Counts received pings, persisting the count; replies with the total."""

    def on_start(self, id, storage, o: Out):
        return storage if storage is not None else 0

    def on_msg(self, id, state, src, msg, o: Out):
        if msg == "ping":
            o.save(state + 1)
            o.send(src, ["total", state + 1])
            return state + 1
        return None


class CollectingClient(Actor):
    """Sends one ping per timer tick until 3 replies arrive — resilient to
    the server binding after the client starts (plain UDP racing, as in the
    reference runtime)."""

    def __init__(self, server_id, results):
        self.server_id = server_id
        self.results = results

    def on_start(self, id, storage, o: Out):
        o.set_timer("ping", (0.02, 0.03))
        return ()

    def on_timeout(self, id, state, timer, o: Out):
        if len(self.results) < 3:
            o.send(self.server_id, "ping")
            o.set_timer("ping", (0.02, 0.03))
        return None

    def on_msg(self, id, state, src, msg, o: Out):
        if isinstance(msg, list) and msg[0] == "total":
            self.results.append(msg[1])
        return None


def test_udp_runtime_delivers_and_persists(tmp_path):
    server_id = Id.from_socket_addr((127, 0, 0, 1), 34001)
    client_id = Id.from_socket_addr((127, 0, 0, 1), 34002)
    results = []
    runtime = spawn(
        json_serialize,
        json_deserialize,
        json_serialize,
        json_deserialize,
        [
            (server_id, CountingServer()),
            (client_id, CollectingClient(server_id, results)),
        ],
        storage_dir=str(tmp_path),
    )
    deadline = time.time() + 10
    while len(results) < 3 and time.time() < deadline:
        time.sleep(0.02)
    runtime.stop()
    assert results[:3] == [1, 2, 3]
    # Storage survived: a restarted server resumes from the saved count
    # (the crash/recover pattern of src/actor/spawn.rs:279-385).
    results2 = []
    runtime2 = spawn(
        json_serialize,
        json_deserialize,
        json_serialize,
        json_deserialize,
        [
            (server_id, CountingServer()),
            (client_id, CollectingClient(server_id, results2)),
        ],
        storage_dir=str(tmp_path),
    )
    deadline = time.time() + 10
    while len(results2) < 3 and time.time() < deadline:
        time.sleep(0.02)
    runtime2.stop()
    # The restarted server resumed from its persisted count: totals continue
    # past everything phase one saw instead of restarting at 1.
    assert len(results2) >= 3
    assert results2[0] > max(results)
    assert results2 == sorted(results2)


class TimerActor(Actor):
    """Exercises SetTimer: emits a tick to a collector after a short delay."""

    def __init__(self, collector_id):
        self.collector_id = collector_id

    def on_start(self, id, storage, o: Out):
        o.set_timer("tick", (0.01, 0.02))
        return ()

    def on_timeout(self, id, state, timer, o: Out):
        if timer == "tick":
            o.send(self.collector_id, "ticked")
        return None


class Collector(Actor):
    def __init__(self, results):
        self.results = results

    def on_start(self, id, storage, o: Out):
        return ()

    def on_msg(self, id, state, src, msg, o: Out):
        self.results.append(msg)
        return None


def test_udp_runtime_timers_fire(tmp_path):
    timer_id = Id.from_socket_addr((127, 0, 0, 1), 34003)
    collector_id = Id.from_socket_addr((127, 0, 0, 1), 34004)
    results = []
    runtime = spawn(
        json_serialize,
        json_deserialize,
        json_serialize,
        json_deserialize,
        [
            (timer_id, TimerActor(collector_id)),
            (collector_id, Collector(results)),
        ],
        storage_dir=str(tmp_path),
    )
    deadline = time.time() + 10
    while not results and time.time() < deadline:
        time.sleep(0.02)
    runtime.stop()
    assert results == ["ticked"]


# --- transport pluggability: the same actors over in-process loopback --------


def test_loopback_runtime_delivers_and_persists(tmp_path):
    """The UDP round-trip/persistence scenario, hermetic: plain model
    indices as Ids, no ports bound — the chaos harness's substrate."""
    from stateright_tpu.actor.transport import LoopbackTransport

    server_id, client_id = Id(1), Id(2)
    results = []
    runtime = spawn(
        json_serialize,
        json_deserialize,
        json_serialize,
        json_deserialize,
        [
            (server_id, CountingServer()),
            (client_id, CollectingClient(server_id, results)),
        ],
        storage_dir=str(tmp_path),
        transport=LoopbackTransport(),
    )
    deadline = time.time() + 10
    while len(results) < 3 and time.time() < deadline:
        time.sleep(0.02)
    runtime.stop()
    assert results[:3] == [1, 2, 3]
    results2 = []
    runtime2 = spawn(
        json_serialize,
        json_deserialize,
        json_serialize,
        json_deserialize,
        [
            (server_id, CountingServer()),
            (client_id, CollectingClient(server_id, results2)),
        ],
        storage_dir=str(tmp_path),
        transport=LoopbackTransport(),
    )
    deadline = time.time() + 10
    while len(results2) < 3 and time.time() < deadline:
        time.sleep(0.02)
    runtime2.stop()
    assert results2 and results2[0] > max(results)


def test_duplicate_loopback_bind_raises_in_caller(tmp_path):
    """Endpoints bind in spawn()'s caller thread: an address collision
    surfaces synchronously, not asynchronously via runtime.errors."""
    from stateright_tpu.actor.transport import LoopbackTransport

    transport = LoopbackTransport()
    runtime = spawn(
        json_serialize, json_deserialize, json_serialize, json_deserialize,
        [(Id(1), Collector([]))],
        storage_dir=str(tmp_path),
        transport=transport,
    )
    try:
        with pytest.raises(OSError):
            spawn(
                json_serialize, json_deserialize, json_serialize,
                json_deserialize,
                [(Id(1), Collector([]))],
                storage_dir=str(tmp_path),
                transport=transport,
            )
    finally:
        runtime.stop()


# --- runtime teardown hardening ----------------------------------------------


def test_stop_is_idempotent_and_bounded(tmp_path):
    from stateright_tpu.actor.transport import LoopbackTransport

    runtime = spawn(
        json_serialize, json_deserialize, json_serialize, json_deserialize,
        [(Id(1), Collector([])), (Id(2), Collector([]))],
        storage_dir=str(tmp_path),
        transport=LoopbackTransport(),
    )
    t0 = time.monotonic()
    runtime.stop()
    runtime.stop()  # second call is a no-op, not an error
    assert time.monotonic() - t0 < 5.0, "teardown must be bounded"
    assert not any(t.is_alive() for t in runtime._threads)
    runtime.stop()  # still fine after threads are gone


class _FailingActor(Actor):
    def on_start(self, id, storage, o: Out):
        raise RuntimeError("boom at startup")


def test_stop_surfaces_actor_errors(tmp_path):
    """stop() re-raises collected actor-thread errors (previously only
    join() did), and can be told not to for best-effort teardown."""
    from stateright_tpu.actor.transport import LoopbackTransport

    runtime = spawn(
        json_serialize, json_deserialize, json_serialize, json_deserialize,
        [(Id(1), _FailingActor())],
        storage_dir=str(tmp_path),
        transport=LoopbackTransport(),
    )
    deadline = time.time() + 5
    while not runtime.errors and time.time() < deadline:
        time.sleep(0.01)
    with pytest.raises(RuntimeError, match="boom at startup"):
        runtime.stop()
    runtime.stop(raise_errors=False)  # idempotent, quiet teardown


def test_event_loop_never_reads_wall_clock(tmp_path, monkeypatch):
    """Pin the monotonic-deadline contract: the event loop computing
    timer/retransmit deadlines must never call time.time() — a wall-clock
    jump (NTP step) could otherwise fire timers early or starve them.
    The shim raises on any wall-clock read from the spawn module; timers
    must still fire."""
    import sys

    from stateright_tpu.actor.transport import LoopbackTransport

    # (the actor package re-exports the spawn *function* under the same
    # name, so `import stateright_tpu.actor.spawn` resolves to that —
    # fetch the module itself)
    spawn_mod = sys.modules["stateright_tpu.actor.spawn"]

    real_time = time

    class _NoWallClock:
        @staticmethod
        def monotonic():
            return real_time.monotonic()

        @staticmethod
        def time():
            raise AssertionError(
                "the actor event loop read the wall clock"
            )

    monkeypatch.setattr(spawn_mod, "time", _NoWallClock)
    results = []
    runtime = spawn(
        json_serialize, json_deserialize, json_serialize, json_deserialize,
        [(Id(1), TimerActor(Id(2))), (Id(2), Collector(results))],
        storage_dir=str(tmp_path),
        transport=LoopbackTransport(),
    )
    deadline = real_time.time() + 10
    while not results and real_time.time() < deadline:
        real_time.sleep(0.02)
    runtime.stop()
    assert results == ["ticked"], f"errors={runtime.errors!r}"
