"""Device-kernel gates for the compiled raft workload.

Raft is the generalization proof for the compiled path: every reference
action family except SelectRandom (src/actor/model.rs:269-333) — Deliver
over five message kinds with multiset counts > 1, two Timeout timers per
node, and Crash/Recover under ``max_crashes(1)`` — plus log truncation,
quorum commits, and buffered broadcasts.

Gate structure mirrors the paxos/ABD ones:

1. per-state differential: device successor sets, full successor rows
   (including the non-identity delivered/buffer words), validity, flags,
   and property predicates against the host model over the reachable
   space to a fixed depth;
2. engine golden: ``spawn_tpu`` reproduces the host BFS at
   ``target_max_depth(6)`` exactly (4,933 states, the host suite's pin);
3. deeper runs pin BOTH engine counts separately: states that merge under
   the reference's state identity (examples/raft.rs:39-56 excludes
   delivered_messages and buffer from Hash) can have buffer-dependent
   successors, so which representative expands decides a handful of
   deep states — host FIFO order and device sorted-key order first
   diverge at depth 8 (61,702 vs 61,697 of which all discoveries agree).
   The reference has the same nondeterminism across checker threads; at
   ``threads > 1`` its own counts vary run to run.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.models.raft import RaftModelCfg  # noqa: E402
from stateright_tpu.models.raft_compiled import RaftCompiled  # noqa: E402
from stateright_tpu.ops.fingerprint import fingerprint  # noqa: E402


def raft_model():
    return RaftModelCfg(server_count=3).into_model()


@pytest.mark.slow
def test_step_differential_to_depth_4():
    """Successors, rows, flags, and properties vs host over the 1,390
    states within 4 actions of init (elections, votes, crash/recover, and
    both timeout kinds are all reachable in this prefix)."""
    model = raft_model()
    cm = RaftCompiled(model)
    props = model.properties()
    seen = {}
    frontier = list(model.init_states())
    for s in frontier:
        seen[fingerprint(s)] = s
    depth = 0
    while frontier and depth < 4:
        depth += 1
        encs = np.stack([cm.encode(s) for s in frontier]).astype(np.uint32)
        nexts_b, valid_b, flag_b = jax.vmap(cm.step)(jnp.asarray(encs))
        nexts_b = np.asarray(nexts_b)
        valid_b = np.asarray(valid_b)
        assert not np.asarray(flag_b).any()
        conds_b = np.asarray(
            jax.vmap(cm.property_conds)(jnp.asarray(encs))
        )
        nxt = []
        for bi, s in enumerate(frontier):
            assert fingerprint(cm.decode(encs[bi])) == fingerprint(s)
            want = [bool(p.condition(model, s)) for p in props]
            assert want == [bool(x) for x in conds_b[bi]], s
            acts = []
            model.actions(s, acts)
            host_succ = {}
            for a in acts:
                ns = model.next_state(s, a)
                if ns is None:
                    continue
                host_succ[tuple(cm.encode(ns).tolist())] = a
                fp = fingerprint(ns)
                if fp not in seen:
                    seen[fp] = ns
                    nxt.append(ns)
            dev_succ = {
                tuple(nexts_b[bi, k].tolist())
                for k in range(cm.max_actions)
                if valid_b[bi, k]
            }
            # Full-row equality: identity words AND delivered/buffer.
            assert dev_succ == set(host_succ), s
        frontier = nxt
    assert len(seen) == 1390


@pytest.mark.slow
def test_spawn_tpu_raft_depth6_matches_host():
    """The host suite's determinism pin (4,933 states by depth 6) through
    the device engine, discovery sets included."""
    tpu = (
        raft_model()
        .checker()
        .target_max_depth(6)
        .spawn_tpu(capacity=1 << 15, max_frontier=1 << 8)
        .join()
    )
    host = raft_model().checker().target_max_depth(6).spawn_bfs().join()
    assert host.unique_state_count() == 4_933
    assert tpu.unique_state_count() == 4_933
    assert tpu.max_depth() == host.max_depth() == 6
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    tpu.assert_any_discovery("Election Liveness")
    tpu.assert_no_discovery("Election Safety")
    tpu.assert_no_discovery("State Machine Safety")


@pytest.mark.slow
def test_spawn_tpu_raft_depth8_dual_pin():
    """Depth 8: the first depth where representative choice under the
    reference's partial state identity matters (see module docstring) —
    both engine counts are pinned, discoveries must agree, and neither
    safety property may fire."""
    host = raft_model().checker().target_max_depth(8).spawn_bfs().join()
    tpu = (
        raft_model()
        .checker()
        .target_max_depth(8)
        .spawn_tpu(capacity=1 << 19, max_frontier=1 << 9)
        .join()
    )
    assert host.unique_state_count() == 61_702
    assert tpu.unique_state_count() == 61_697
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    tpu.assert_any_discovery("Log Liveness")
    tpu.assert_no_discovery("Election Safety")
    tpu.assert_no_discovery("State Machine Safety")


@pytest.mark.tpu
def test_spawn_tpu_raft_depth9_device():
    """Depth 9 on real hardware (host BFS: 225,379; the same engine
    config on the CPU backend measured 225,298 — the band covers the
    representative-order nondeterminism described in the module
    docstring)."""
    tpu = (
        raft_model()
        .checker()
        .target_max_depth(9)
        .spawn_tpu(capacity=1 << 20, max_frontier=1 << 10)
        .join()
    )
    assert 225_000 < tpu.unique_state_count() < 226_000
    tpu.assert_any_discovery("Election Liveness")
    tpu.assert_any_discovery("Log Liveness")
    tpu.assert_no_discovery("Election Safety")
    tpu.assert_no_discovery("State Machine Safety")


@pytest.mark.slow
def test_spawn_tpu_simulation_raft():
    """Device Monte-carlo over the crash/recover model: random walks are
    depth-bounded like the reference's default check (deep walks would
    exceed the packed term budget, which the step flag would loudly
    reject), find leaders fast, and never trip the safety properties."""
    sim = (
        raft_model()
        .checker()
        .target_max_depth(12)
        .target_state_count(5_000)
        .spawn_tpu_simulation(seed=3, walkers=128)
        .join()
    )
    assert sim.state_count() >= 5_000
    assert "Election Safety" not in sim.discoveries()
    assert "State Machine Safety" not in sim.discoveries()


@pytest.mark.tpu
def test_spawn_tpu_raft_default_check_depth12_device():
    """The reference's DEFAULT `raft check`: BFS to target_max_depth(12)
    (examples/raft.rs:520-535), whole on one chip.  Count pinned from the
    2026-07-31 device run (12,603,639 unique / 38.5M generated, ~220 s);
    representative-order nondeterminism under the partial state identity
    makes tiny drift possible across engine-shape changes, hence a band.
    The Election Safety counterexample is genuine — the reference actor
    persists nothing across crashes, so crash->recover->re-vote elects
    two leaders in one term; the host oracle finds the identical
    discovery set at depth 10 (host 844,999 vs device 844,306 unique,
    the usual representative-order band; runs of 2026-07-31)."""
    tpu = (
        raft_model()
        .checker()
        .target_max_depth(12)
        .spawn_tpu(
            capacity=1 << 26,
            log_capacity=14_000_000,
            max_frontier=1 << 13,
            dedup_factor=1,
        )
        .join()
    )
    assert 12_550_000 < tpu.unique_state_count() < 12_650_000
    assert tpu.max_depth() == 12
    tpu.assert_any_discovery("Election Liveness")
    tpu.assert_any_discovery("Log Liveness")
    tpu.assert_any_discovery("Election Safety")
    tpu.assert_no_discovery("State Machine Safety")
