"""The shared wave-loop core (parallel/wave_loop.py): exchange bucket
geometry units, the dedup-relax rule, checkpoint cadence, and — the
ISSUE-8 acceptance matrix — snapshot/resume + in-place auto-grow running
through the SAME extracted loop on BOTH wavefront engines."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twophase import TwoPhaseSys  # noqa: E402
from stateright_tpu.parallel.wave_loop import (  # noqa: E402
    BUCKET_SLACK_DEFAULT,
    SORT_RUNG_HEADROOM,
    SORT_RUNG_MIN,
    STEP_RUNG_HEADROOM,
    STEP_RUNG_MIN,
    CheckpointCadence,
    clamp_rung,
    clamp_sort_lanes,
    clamp_step_lanes,
    downshift_rung,
    downshift_sort_lanes,
    downshift_step_lanes,
    exchange_bucket_lanes,
    maybe_retune_sort,
    maybe_retune_step,
    next_bucket_slack,
    next_rung,
    next_sort_lanes,
    next_step_lanes,
    relax_dedup_geometry,
)


# --- bucket geometry ---------------------------------------------------------


def test_exchange_bucket_lanes_basics():
    # n=1 meshes elide the exchange and keep the full buffer shape.
    assert exchange_bucket_lanes(8192, 1, BUCKET_SLACK_DEFAULT) == 8192
    # 50% of the even share, 128-lane aligned: the doc workload's shape.
    assert exchange_bucket_lanes(8192, 8, 50) == 512
    assert exchange_bucket_lanes(8192, 2, 50) == 2048
    # Never exceeds the full buffer (which cannot overflow)...
    assert exchange_bucket_lanes(8192, 2, 10_000) == 8192
    # ...and never collapses below the tiny-mesh floor.
    assert exchange_bucket_lanes(64, 8, 1) >= 8


def test_exchange_bucket_lanes_monotone_in_slack():
    for u_sz in (96, 8192, 16384):
        for n in (2, 4, 8):
            prev = 0
            for slack in (1, 2, 25, 50, 100, 200, 400, 100_000):
                b = exchange_bucket_lanes(u_sz, n, slack)
                assert b >= prev
                assert b <= u_sz
                prev = b


def test_next_bucket_slack_ladder_terminates():
    """Doubling from any rung reaches the full-buffer cap (where
    overflow is impossible and the ladder reports None) in finitely many
    strictly-growing steps."""
    for u_sz in (96, 8192, 16384):
        for n in (2, 8):
            slack = 1
            seen = 0
            while True:
                nxt = next_bucket_slack(u_sz, n, slack)
                if nxt is None:
                    assert exchange_bucket_lanes(u_sz, n, slack) == u_sz
                    break
                assert exchange_bucket_lanes(u_sz, n, nxt) > \
                    exchange_bucket_lanes(u_sz, n, slack)
                slack = nxt
                seen += 1
                assert seen < 32, "bucket ladder failed to terminate"


# --- sort-geometry rung ladder -----------------------------------------------


def test_clamp_sort_lanes_pow2_and_floor():
    assert clamp_sort_lanes(1) == SORT_RUNG_MIN
    assert clamp_sort_lanes(SORT_RUNG_MIN) == SORT_RUNG_MIN
    assert clamp_sort_lanes(SORT_RUNG_MIN + 1) == SORT_RUNG_MIN * 2
    assert clamp_sort_lanes(3000) == 4096
    assert clamp_sort_lanes(1 << 20) == 1 << 20


def test_next_sort_lanes_ladder_terminates_at_full_buffer():
    """Doubling from any rung reaches the full U (where the rung
    criterion IS the pre-ladder dedup criterion) in finitely many
    strictly-growing steps, then reports None — the signal to fall back
    to relax_dedup_geometry."""
    for u_sz in (200, SORT_RUNG_MIN, 8192, 16384, 100_000):
        rung = min(SORT_RUNG_MIN, u_sz)
        seen = 0
        while True:
            nxt = next_sort_lanes(rung, u_sz)
            if nxt is None:
                assert rung >= u_sz
                break
            assert nxt > rung
            assert nxt <= u_sz
            rung = nxt
            seen += 1
            assert seen < 32, "sort-rung ladder failed to terminate"


def test_downshift_sort_lanes_hysteresis_floor_and_cap():
    u = 1 << 14
    # An at-least-halving move exists: downshift to peak*headroom pow2.
    assert downshift_sort_lanes(u, u, SORT_RUNG_MIN, 100.0) == 512
    # Hysteresis: no move when the target would not at least halve.
    assert downshift_sort_lanes(1024, u, SORT_RUNG_MIN, 200.0) is None
    # The overflow-proven floor is never revisited.
    assert downshift_sort_lanes(u, u, 4096, 100.0) == 4096
    # Never below the ladder minimum...
    assert downshift_sort_lanes(u, u, SORT_RUNG_MIN, 0.0) == SORT_RUNG_MIN
    # ...and never above the full buffer (tiny-U geometries are inert).
    assert downshift_sort_lanes(512, 512, SORT_RUNG_MIN, 1000.0) is None


# --- the shared rung-ladder helper (both ladders, one rule) ------------------


def test_ladder_wrappers_delegate_to_the_shared_helper():
    """The sort and step ladders are the ONE parameterized helper
    applied at their (min, headroom) — wrapper drift would resurrect
    the two-implementations bug class the helper exists to kill."""
    for req in (1, 7, 255, 256, 257, 3000, 1 << 20):
        assert clamp_sort_lanes(req) == clamp_rung(req, SORT_RUNG_MIN)
        assert clamp_step_lanes(req) == clamp_rung(req, STEP_RUNG_MIN)
    for cur in (256, 1024, 8192, 1 << 14):
        for full in (512, 8192, 1 << 14):
            assert next_sort_lanes(cur, full) == next_rung(
                cur, full, SORT_RUNG_MIN
            )
            assert next_step_lanes(cur, full) == next_rung(
                cur, full, STEP_RUNG_MIN
            )
            for floor in (SORT_RUNG_MIN, 2048):
                for peak in (0.0, 100.0, 900.0, 5000.0):
                    assert downshift_sort_lanes(
                        cur, full, floor, peak
                    ) == downshift_rung(
                        cur, full, floor, peak,
                        SORT_RUNG_MIN, SORT_RUNG_HEADROOM,
                    )
                    assert downshift_step_lanes(
                        cur, full, floor, peak
                    ) == downshift_rung(
                        cur, full, floor, peak,
                        STEP_RUNG_MIN, STEP_RUNG_HEADROOM,
                    )


def test_downshift_rung_parameterization():
    """The helper honors each parameter independently: min floor,
    headroom scaling, the overflow-proven floor, the full-buffer cap,
    and the at-least-halving hysteresis."""
    full = 1 << 14
    # min_rung floors the move.
    assert downshift_rung(full, full, 0, 0.0, 256, 4.0) == 256
    assert downshift_rung(full, full, 0, 0.0, 1024, 4.0) == 1024
    # Headroom scales the landing rung: peak 100 at 4x -> 512; at 16x
    # -> 2048 (next pow2 above 1600).
    assert downshift_rung(full, full, 0, 100.0, 256, 4.0) == 512
    assert downshift_rung(full, full, 0, 100.0, 256, 16.0) == 2048
    # The overflow-proven floor is never revisited.
    assert downshift_rung(full, full, 4096, 100.0, 256, 4.0) == 4096
    # Hysteresis: a move that would not at least halve is refused.
    assert downshift_rung(1024, full, 0, 200.0, 256, 4.0) is None
    # Capped at the full buffer (tiny-full geometries are inert).
    assert downshift_rung(512, 512, 0, 1000.0, 256, 4.0) is None


def test_downshift_step_lanes_hysteresis_floor_and_cap():
    full = 1 << 13
    # Live-frontier evidence is already in lanes (no density scaling):
    # peak 100 at the step ladder's 4x headroom lands on 512.
    assert downshift_step_lanes(full, full, STEP_RUNG_MIN, 100.0) == 512
    # Hysteresis mirrors the sort ladder's.
    assert downshift_step_lanes(1024, full, STEP_RUNG_MIN, 200.0) is None
    # The overflow-proven floor (a flag-128 climb) is never revisited.
    assert downshift_step_lanes(full, full, 2048, 10.0) == 2048
    # Never below the ladder minimum.
    assert downshift_step_lanes(full, full, 0, 0.0) == STEP_RUNG_MIN


class _LadderEng:
    """Minimal engine stub exposing both tuner attribute namespaces
    (_SORT_NS/_STEP_NS) so the ONE _maybe_retune implementation is
    exercised through both public wrappers."""

    def __init__(self, full=1 << 14):
        self._full = full
        self.applied = []
        # sort namespace
        self._sort_tune = True
        self._sort_quanta = 0
        self._sort_peak_valid = 0.0
        self._sort_rung_floor = 0
        self._sort_cur = full
        # step namespace
        self._step_tune = True
        self._step_quanta = 0
        self._step_peak_frontier = 0.0
        self._step_rung_floor = 0
        self._step_cur = full

    def _wl_full_sort_lanes(self):
        return self._full

    def _sort_width(self):
        return self._sort_cur

    def _wl_apply_sort_rung(self, rung):
        self._sort_cur = rung
        self.applied.append(("sort", rung))

    def _wl_full_step_lanes(self):
        return self._full

    def _step_width(self):
        return self._step_cur

    def _wl_apply_step_rung(self, rung):
        self._step_cur = rung
        self.applied.append(("step", rung))


def test_maybe_retune_is_shared_and_respects_min_quanta():
    """Both tuners run the one shared implementation: evidence
    accumulates per committed quantum, no move before the quanta
    window, then ONE downshift sized by the ladder's own headroom —
    density×full lanes for sort, raw frontier lanes for step."""
    eng = _LadderEng()
    # 7 quanta of evidence: no move yet (window is 8).
    for _ in range(7):
        assert not maybe_retune_sort(eng, 100.0 / (1 << 14))
        assert not maybe_retune_step(eng, 100.0)
    assert eng.applied == []
    # The 8th quantum moves BOTH ladders to the same rung (peak 100
    # lanes, 4x headroom -> 512): one rule, two namespaces.
    assert maybe_retune_sort(eng, 100.0 / (1 << 14))
    assert maybe_retune_step(eng, 100.0)
    assert eng.applied == [("sort", 512), ("step", 512)]
    # An explicit rung disarms each tuner independently.
    eng2 = _LadderEng()
    eng2._sort_tune = False
    for _ in range(10):
        assert not maybe_retune_sort(eng2, 100.0 / (1 << 14))
        maybe_retune_step(eng2, 100.0)
    assert all(kind == "step" for kind, _ in eng2.applied)


# --- shared growth rule ------------------------------------------------------


def test_relax_dedup_geometry_rule():
    lanes = lambda c, dd: max(min(c * 4, 1 << 14), c * 4 // dd)  # noqa: E731
    # Relax lands at dd=1 with the chunk kept when it fits the band.
    assert relax_dedup_geometry(4096, 8, lanes, 1 << 20, "chunk_size") == (
        1, 4096, "dedup_factor=1"
    )
    # Over the band: halve the chunk until it fits, noting each step.
    dd, c, note = relax_dedup_geometry(
        1 << 14, 8, lanes, 1 << 14, "chunk_size"
    )
    assert dd == 1 and c == 4096
    assert "chunk_size=4096" in note
    # Already at dd=1: nothing to relax.
    assert relax_dedup_geometry(4096, 1, lanes, 1 << 20, "x") is None
    # Even the floor chunk cannot fit: refuse.
    assert relax_dedup_geometry(4096, 8, lanes, 16, "x") is None


def test_checkpoint_cadence():
    c = CheckpointCadence(every_waves=4, every_sec=None)
    assert not c.due(2)
    assert c.due(2)
    c.mark()
    assert not c.due(3)
    assert c.due(1)
    # Time-based cadence.
    t = CheckpointCadence(every_waves=None, every_sec=0.0)
    assert t.due(1)
    n = CheckpointCadence(every_waves=None, every_sec=None)
    assert not n.due(1000)


# --- the cross-engine matrix: snapshot/resume + in-place auto-grow -----------


def _spawn(engine, model, tmp_path, **kwargs):
    b = model.checker()
    for k, v in kwargs.pop("builder", {}).items():
        b = getattr(b, k)(v)
    if engine == "tpu":
        return b.spawn_tpu(
            capacity=1 << 14, max_frontier=1 << 6,
            device=jax.devices("cpu")[0], **kwargs,
        )
    mesh = jax.sharding.Mesh(np.array(jax.devices("cpu")[:4]), ("shards",))
    return b.spawn_tpu_sharded(
        mesh=mesh, capacity=1 << 14, chunk_size=1 << 6, **kwargs,
    )


@pytest.mark.parametrize("engine", ["tpu", "sharded"])
def test_snapshot_resume_matrix_both_engines(engine, tmp_path):
    """One matrix, two engines, one extracted loop: a bounded run
    snapshots mid-search, the resume completes to the uninterrupted
    run's exact totals and discovery set."""
    model = TwoPhaseSys(rm_count=4)
    full = _spawn(engine, model, tmp_path).join()
    assert full.unique_state_count() == 1568

    bounded = _spawn(
        engine, TwoPhaseSys(rm_count=4), tmp_path,
        builder={"target_state_count": 400},
    ).join()
    assert bounded.unique_state_count() < 1568
    snap = str(tmp_path / f"{engine}.npz")
    bounded.save_snapshot(snap)

    resumed = _spawn(
        engine, TwoPhaseSys(rm_count=4), tmp_path, resume_from=snap,
    ).join()
    assert resumed.unique_state_count() == 1568
    assert resumed.state_count() == full.state_count()
    assert resumed.max_depth() == full.max_depth()
    assert sorted(resumed.discoveries()) == sorted(full.discoveries())
    assert np.array_equal(
        resumed.discovered_fingerprints(), full.discovered_fingerprints()
    )


@pytest.mark.parametrize("engine", ["tpu", "sharded"])
def test_auto_grow_in_place_matrix_both_engines(engine, tmp_path):
    """One matrix, two engines, one extracted loop: a run spawned with a
    deliberately undersized retryable knob grows IN PLACE (journaled
    ``grow`` event, no restart, no lost work) and still lands the exact
    full-run counts.  The forced knob is engine-appropriate — an
    undersized table for the single-chip engine (flag 1), an undersized
    exchange bucket for the sharded one (flag 32) — but the abort/grow/
    re-run contract they exercise is the one shared FusedWaveLoop."""
    from stateright_tpu.runtime.journal import read_journal

    journal = str(tmp_path / f"{engine}_grow.jsonl")
    model = TwoPhaseSys(rm_count=4)
    if engine == "tpu":
        ck = model.checker().spawn_tpu(
            capacity=1 << 10,  # 1568 uniques exceed 50% load -> flag 1
            max_frontier=1 << 6,
            device=jax.devices("cpu")[0],
            journal=journal,
        ).join()
        grown_flag = 1
    else:
        mesh = jax.sharding.Mesh(
            np.array(jax.devices("cpu")[:4]), ("shards",)
        )
        ck = model.checker().spawn_tpu_sharded(
            mesh=mesh, capacity=1 << 14, chunk_size=1 << 7,
            bucket_slack=1,  # tiny buckets -> flag 32
            journal=journal,
        ).join()
        grown_flag = 32
    assert ck.unique_state_count() == 1568
    grows = [e for e in read_journal(journal) if e["event"] == "grow"]
    assert grows, "no in-place grow event journaled"
    assert any(e["flags"] & grown_flag for e in grows)
    done = [e for e in read_journal(journal) if e["event"] == "engine_done"]
    assert done and done[-1]["unique"] == 1568
