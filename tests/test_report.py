"""Histograms, the Prometheus exposition, and journal-derived reports
(obs/metrics.py, obs/prometheus.py, obs/report.py;
docs/OBSERVABILITY.md "Run reports").
"""

import json

import pytest

from stateright_tpu.obs.metrics import (
    COUNT_BUCKETS, Histogram, MetricsRegistry,
)
from stateright_tpu.obs.prometheus import (
    ExpositionError, parse_prometheus, render_prometheus,
)
from stateright_tpu.obs.report import (
    analyze_journal, bench_trajectory, render_markdown,
    render_trajectory_markdown, report_main,
)

# --- histograms --------------------------------------------------------------


def test_histogram_buckets_sum_count_and_quantiles():
    h = Histogram((1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 1.5, 3.0, 100.0):
        h.observe(v)
    assert h.count == 5
    assert h.counts == [1, 2, 1, 1]  # (..1], (1..2], (2..4], +Inf
    assert h.sum == pytest.approx(106.5)
    # p50 falls in the (1..2] bucket; p99 in the +Inf tail (reported at
    # its lower bound — never an invented upper bound).
    assert 1.0 <= h.quantile(0.5) <= 2.0
    assert h.quantile(0.99) == 4.0
    snap = h.snapshot()
    assert snap["count"] == 5 and snap["p50"] <= snap["p95"] <= snap["p99"]


def test_histogram_weighted_observation_and_bad_boundaries():
    h = Histogram(COUNT_BUCKETS)
    h.observe(3, count=16)  # one fused quantum = 16 equal waves
    assert h.count == 16 and h.counts[2] == 16
    with pytest.raises(ValueError):
        Histogram((2.0, 1.0))
    with pytest.raises(ValueError):
        Histogram(())


def test_registry_observe_creates_and_snapshots():
    reg = MetricsRegistry()
    reg.observe("lat", 0.1, boundaries=(0.05, 0.5))
    reg.observe("lat", 0.01)  # boundaries fixed at first use
    snap = reg.snapshot_histograms()
    assert snap["lat"]["count"] == 2
    assert snap["lat"]["boundaries"] == [0.05, 0.5]
    assert reg.snapshot() == {}  # histograms never leak into the flat view


# --- prometheus exposition ---------------------------------------------------


def test_render_prometheus_types_and_parse_roundtrip():
    metrics = {
        "engine": "tpu-wavefront",
        "done": True,
        "unique_state_count": 288,
        "table_load_factor": 0.017,
        "device_call_sec_total": 1.25,
        "jobs": {"queued": 0, "done": 2},
        "histograms": {
            "wave_latency_sec": Histogram((0.01, 0.1)).snapshot(),
        },
        "trace_summary": {"nested": {"too": "deep"}},  # skipped
    }
    metrics["histograms"]["wave_latency_sec"]["counts"] = [3, 1, 1]
    metrics["histograms"]["wave_latency_sec"]["count"] = 5
    metrics["histograms"]["wave_latency_sec"]["sum"] = 0.5
    text = render_prometheus(metrics)
    fams = parse_prometheus(text)
    assert fams["stateright_unique_state_count"]["type"] == "counter"
    assert fams["stateright_device_call_sec_total"]["type"] == "counter"
    assert fams["stateright_table_load_factor"]["type"] == "gauge"
    assert fams["stateright_done"]["samples"][0][2] == 1
    # dict-of-numbers -> one labeled gauge family
    jobs = {
        labels["key"]: v
        for _, labels, v in fams["stateright_jobs"]["samples"]
    }
    assert jobs == {"queued": 0, "done": 2}
    # histogram: cumulative buckets, +Inf == count
    lat = fams["stateright_wave_latency_sec"]
    buckets = [
        (labels["le"], v)
        for n, labels, v in lat["samples"] if n.endswith("_bucket")
    ]
    assert buckets[-1] == ("+Inf", 5)
    assert [v for _, v in buckets] == [3, 4, 5]
    # strings land as labels on the info metric, not as samples
    info = fams["stateright_info"]["samples"][0]
    assert info[1]["engine"] == "tpu-wavefront"
    assert "stateright_trace_summary" not in fams


def test_wants_prometheus_respects_accept_preference_order():
    from stateright_tpu.obs.prometheus import wants_prometheus

    # Explicit query param always wins.
    assert wants_prometheus({"format": "prometheus"}, "application/json")
    assert not wants_prometheus({"format": "json"}, "text/plain")
    # A scraper's Accept (text exposition first) selects Prometheus ...
    assert wants_prometheus(
        {}, "application/openmetrics-text;version=1.0.0,"
            "text/plain;version=0.0.4;q=0.5,*/*;q=0.1")
    assert wants_prometheus({}, "text/plain")
    # ... but a JSON client listing text/plain as a FALLBACK keeps JSON
    # (axios et al. send exactly this default).
    assert not wants_prometheus({}, "application/json, text/plain, */*")
    assert not wants_prometheus({}, "*/*")
    assert not wants_prometheus({}, None)


def test_parse_prometheus_rejects_malformed_expositions():
    with pytest.raises(ExpositionError):
        parse_prometheus("this is not a sample\n")
    with pytest.raises(ExpositionError):
        parse_prometheus("# TYPE x wibble\nx 1\n")
    with pytest.raises(ExpositionError):
        parse_prometheus("x notanumber\n")
    # histogram with non-cumulative buckets
    bad = (
        "# TYPE h histogram\n"
        'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 3\n'
        "h_sum 1\nh_count 3\n"
    )
    with pytest.raises(ExpositionError):
        parse_prometheus(bad)


# --- journal run reports -----------------------------------------------------


def _wave(t, waves, unique, depth, call_sec, **extra):
    return {
        "t": t, "event": "wave", "waves": waves, "unique": unique,
        "states": unique * 2, "depth": depth, "flags": 0,
        "call_sec": call_sec, "occupancy": unique / 4096,
        "remaining": 0, **extra,
    }


def test_run_report_untraced_supervised_journal():
    """A supervisor-shaped journal (waves + crash/restart/resume) yields
    phase breakdown, bottleneck_phase, a throughput curve, and the
    restart timeline."""
    events = [
        {"t": 100.0, "event": "supervisor_start", "run_dir": "x"},
        {"t": 100.1, "event": "run_start"},
        _wave(101.0, 8, 1000, 3, 0.8),
        _wave(102.0, 16, 2500, 5, 0.7),
        {"t": 102.5, "event": "crash", "rc": 137},
        {"t": 102.6, "event": "restart", "restarts": 1},
        {"t": 102.7, "event": "resume"},
        _wave(104.0, 24, 5000, 8, 0.9),
        {"t": 104.1, "event": "checkpoint", "path": "ck.npz"},
        {"t": 104.2, "event": "grow", "flags": 1, "grown": "capacity"},
        _wave(106.0, 32, 9000, 11, 1.1),
        {"t": 106.1, "event": "engine_done", "unique": 9000},
        {"t": 106.2, "event": "supervisor_done"},
    ]
    rep = analyze_journal(events)
    assert rep["kind"] == "run"
    assert rep["unique"] == 9000 and rep["waves"] == 4
    assert rep["grows"] == 1 and rep["checkpoints"] == 1
    assert rep["restarts"] == 1 and rep["faults"] == 1
    assert rep["phase_source"] == "untraced-device/host-split"
    assert set(rep["phase_breakdown"]) == {"device", "host"}
    assert rep["bottleneck_phase"] in ("device", "host")
    curve = rep["throughput_curve"]
    assert curve[-1]["unique"] == 9000
    assert all(pt["uniq_per_sec"] >= 0 for pt in curve)
    assert [e["event"] for e in rep["timeline"]].count("crash") == 1
    md = render_markdown(rep)
    assert "bottleneck" in md.lower() and "crash" in md
    json.dumps(rep)  # the --json form must serialize


def test_run_report_traced_journal_names_device_phase():
    events = [
        _wave(1.0, 1, 100, 1, 0.5, wave_breakdown={
            "step": 0.1, "dedup": 0.3, "append": 0.05, "readback": 0.05,
        }),
        _wave(2.0, 2, 250, 2, 0.5, wave_breakdown={
            "step": 0.1, "dedup": 0.25, "append": 0.05, "readback": 0.1,
        }),
        {"t": 2.5, "event": "trace_summary", "hbm_util_frac": 0.004},
    ]
    rep = analyze_journal(events)
    assert rep["phase_source"] == "traced"
    assert rep["bottleneck_phase"] == "dedup"  # readback excluded
    assert rep["trace_summary"]["hbm_util_frac"] == 0.004


def test_service_journal_report_collects_job_spans():
    events = [
        {"t": 10.0, "event": "service_start", "workers": 1},
        {"t": 10.1, "event": "job_submitted", "job": "job-000001",
         "workload": "twophase", "engine": "tpu"},
        {"t": 10.2, "event": "job_running", "job": "job-000001"},
        {"t": 10.2, "event": "job_span", "job": "job-000001",
         "span": "queue_wait", "sec": 0.1},
        {"t": 12.0, "event": "job_done", "job": "job-000001"},
        {"t": 12.0, "event": "job_span", "job": "job-000001",
         "span": "run", "sec": 1.8},
        {"t": 12.0, "event": "job_span", "job": "job-000001",
         "span": "total", "sec": 1.9},
        {"t": 12.1, "event": "job_submitted", "job": "job-000002",
         "workload": "fixtures", "engine": "tpu"},
        {"t": 12.2, "event": "job_cancelled", "job": "job-000002"},
        {"t": 12.2, "event": "job_span", "job": "job-000002",
         "span": "total", "sec": 0.1},
    ]
    rep = analyze_journal(events)
    assert rep["kind"] == "service"
    jobs = rep["jobs"]
    assert jobs["count"] == 2
    assert jobs["by_state"] == {"done": 1, "cancelled": 1}
    assert jobs["detail"]["job-000001"]["spans"]["queue_wait"] == 0.1
    assert "queue_wait_p95_sec" in jobs
    md = render_markdown(rep)
    assert "job-000001" in md and "queue_wait" in md


# --- bench trajectory + regression flagging ----------------------------------


def _round(tmp_path, name, value, metric="paxos3_unique_states_per_sec",
           rc=0, **extra):
    parsed = (
        {"metric": metric, "value": value, "unit": "u/s",
         "vs_baseline": 1.0, **extra}
        if value is not None else {}
    )
    p = tmp_path / f"{name}.json"
    p.write_text(json.dumps({"rc": rc, "parsed": parsed}))
    return str(p)


def test_trajectory_flags_synthetic_degraded_round(tmp_path):
    paths = [
        _round(tmp_path, "BENCH_r01", 100_000.0),
        _round(tmp_path, "BENCH_r02", 250_000.0),
        _round(tmp_path, "BENCH_r03", 120_000.0),  # < 0.8 * best -> flag
        _round(tmp_path, "BENCH_r04", None, rc=1),  # partial: never flagged
        _round(tmp_path, "BENCH_r05", 260_000.0),
    ]
    traj = bench_trajectory(paths)
    assert [r["round"] for r in traj["rounds"]] == [
        "BENCH_r01", "BENCH_r02", "BENCH_r03", "BENCH_r04", "BENCH_r05",
    ]
    assert len(traj["regressions"]) == 1
    flag = traj["regressions"][0]
    assert flag["round"] == "BENCH_r03"
    assert flag["best_round"] == "BENCH_r02"
    assert flag["ratio"] == pytest.approx(0.48)
    md = render_trajectory_markdown(traj)
    assert "⚠" in md and "BENCH_r03" in md
    # A metric change (new headline workload) never cross-flags.
    paths.append(
        _round(tmp_path, "BENCH_r06", 10.0, metric="other_metric")
    )
    assert len(bench_trajectory(paths)["regressions"]) == 1


def test_trajectory_on_committed_rounds_is_clean():
    """The repo's real BENCH_r*.json history renders without error and
    carries no regression (the trajectory is monotone so far)."""
    import glob
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = sorted(glob.glob(os.path.join(repo, "BENCH_r*.json")))
    if not paths:
        pytest.skip("no BENCH rounds committed")
    traj = bench_trajectory(paths)
    assert len(traj["rounds"]) == len(paths)
    assert traj["regressions"] == []
    assert "BENCH_r01" in render_trajectory_markdown(traj)


# --- the report CLI verb -----------------------------------------------------


def test_report_main_on_journal_and_bench_glob(tmp_path, capsys):
    from stateright_tpu.runtime.journal import Journal

    jpath = str(tmp_path / "journal.jsonl")
    with Journal(jpath) as j:
        j.append("wave", waves=1, unique=10, depth=1, call_sec=0.1,
                 occupancy=0.01, remaining=0, states=20, flags=0)
        j.append("engine_done", unique=10)
    assert report_main([jpath]) == 0
    out = capsys.readouterr().out
    assert "Run report" in out and "bottleneck" in out.lower()

    _round(tmp_path, "BENCH_r01", 100.0)
    _round(tmp_path, "BENCH_r02", 10.0)
    md_out = tmp_path / "traj.md"
    assert report_main(
        [str(tmp_path / "BENCH_r*.json"), "--out", str(md_out)]
    ) == 0
    text = md_out.read_text()
    assert "BENCH_r02" in text and "⚠" in text

    # --json emits the dict; mixing journals and rounds is refused.
    capsys.readouterr()
    assert report_main([jpath, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["kind"] == "run" and rep["unique"] == 10
    assert report_main([jpath, str(tmp_path / "BENCH_r01.json")]) == 2
    assert report_main(["/nonexistent/path.jsonl"]) == 2
    assert report_main([]) == 2


def test_report_cli_verb_through_example_main(tmp_path, capsys):
    """`python -m stateright_tpu.models.<any> report <journal>` — the
    verb rides on every model CLI."""
    from stateright_tpu.cli import example_main
    from stateright_tpu.models.twophase import cli_spec

    jpath = str(tmp_path / "journal.jsonl")
    from stateright_tpu.runtime.journal import Journal

    with Journal(jpath) as j:
        j.append("wave", waves=1, unique=5, depth=1, call_sec=0.1,
                 occupancy=0.01, remaining=0, states=5, flags=0)
    rc = example_main(cli_spec(), ["report", jpath])
    assert rc == 0
    assert "Run report" in capsys.readouterr().out


# --- histogram edge cases (obs/metrics.Histogram) -----------------------------


def test_histogram_empty_percentile_readback():
    """An empty histogram reads back 0.0 quantiles (never a div-by-zero
    or an invented value) and a well-formed snapshot."""
    h = Histogram((1.0, 2.0))
    assert h.quantile(0.5) == 0.0 and h.quantile(0.99) == 0.0
    snap = h.snapshot()
    assert snap["count"] == 0 and snap["sum"] == 0.0
    assert snap["p50"] == snap["p95"] == snap["p99"] == 0.0
    assert snap["counts"] == [0, 0, 0]


def test_histogram_single_boundary_ladder():
    """A one-boundary ladder: two buckets ((-inf..b], +Inf); quantiles
    interpolate from 0 inside the finite bucket and report the boundary
    for the +Inf tail."""
    h = Histogram((10.0,))
    h.observe(4.0, count=2)
    assert h.counts == [2, 0]
    assert 0.0 <= h.quantile(0.5) <= 10.0
    h.observe(100.0)  # lands in +Inf
    assert h.counts == [2, 1]
    assert h.quantile(0.99) == 10.0  # +Inf reports its lower bound


def test_histogram_weighted_observations_straddling_inf():
    """Weighted observations split across the last finite bucket and
    the +Inf tail: counts, sum, and quantiles stay consistent."""
    h = Histogram((1.0,))
    h.observe(0.5, count=3)
    h.observe(5.0, count=7)  # +Inf bucket, weighted
    assert h.count == 10
    assert h.counts == [3, 7]
    assert h.sum == pytest.approx(0.5 * 3 + 5.0 * 7)
    # rank(0.5)=5 falls inside +Inf -> its lower bound, the last
    # finite boundary.
    assert h.quantile(0.5) == 1.0
    assert h.quantile(0.2) == pytest.approx(1.0 * (2 / 3), abs=1e-9)
    snap = h.snapshot()
    assert snap["p50"] == 1.0 and snap["p99"] == 1.0


# --- labeled gauge families (per-shard series) --------------------------------


def test_render_and_parse_labeled_gauge_families():
    """Flat numeric dicts (the sharded engine's per-shard gauges) render
    as ONE labeled family and validate."""
    text = render_prometheus({
        "shard_unique": {"0": 10, "1": 12},
        "unique_skew_max_over_mean": 1.2,
    })
    fams = parse_prometheus(text)
    fam = fams["stateright_shard_unique"]
    assert fam["type"] == "gauge"
    assert sorted(
        (labels["key"], v) for _n, labels, v in fam["samples"]
    ) == [("0", 10.0), ("1", 12.0)]


def test_parse_prometheus_rejects_inconsistent_labeled_families():
    # Mixed label-name sets within one family.
    with pytest.raises(ExpositionError, match="mixes label sets"):
        parse_prometheus(
            "# TYPE g gauge\n"
            'g{key="0"} 1\n'
            'g{shard="1"} 2\n'
        )
    # Duplicate series (same name + label set twice).
    with pytest.raises(ExpositionError, match="repeats series"):
        parse_prometheus(
            "# TYPE g gauge\n"
            'g{key="0"} 1\n'
            'g{key="0"} 2\n'
        )


# --- torn journal tails -------------------------------------------------------


def test_report_tolerates_torn_final_journal_line(tmp_path):
    """A crashed writer's torn tail — both an undecodable fragment and
    a truncation that still parses as a bare JSON scalar — is skipped
    with a report warning, never an exception (and never an
    AttributeError on a non-dict event)."""
    jpath = tmp_path / "journal.jsonl"
    jpath.write_text(
        json.dumps(_wave(1.0, 1, 100, 1, 0.5)) + "\n"
        + json.dumps(_wave(2.0, 2, 250, 2, 0.5)) + "\n"
        + '{"t": 3.0, "event": "wa'  # killed mid-os.write
    )
    rep = analyze_journal(str(jpath))
    assert rep["waves"] == 2 and rep["unique"] == 250
    assert any("torn" in w for w in rep["warnings"])
    md = render_markdown(rep)
    assert "⚠" in md and "torn" in md

    # Truncation that still decodes — as a scalar, not an object.
    with open(jpath, "a") as fh:
        fh.write("\n17\n")
    rep = analyze_journal(str(jpath))
    assert rep["waves"] == 2
    assert any("2 torn" in w for w in rep["warnings"])


# --- geometry advisor ---------------------------------------------------------


def test_advisor_recommends_dedup_rung_from_measured_density():
    from stateright_tpu.obs.report import advise_geometry

    events = [
        {"t": 0.0, "event": "geometry", "engine": "tpu-wavefront",
         "capacity": 1 << 20, "log_capacity": 1 << 20,
         "max_frontier": 1 << 15, "dedup_factor": 8, "u_lanes": 425_984,
         "waves_per_call": 256},
    ]
    for i in range(8):
        events.append(_wave(
            float(i + 1), i + 1, 10_000 * (i + 1), i, 0.5,
            density=0.01 + 0.002 * i,  # peak 0.024
        ))
    adv = advise_geometry(events)
    assert adv["measured"]["peak_density"] == pytest.approx(0.024)
    # 1/(0.024*4) ~ 10.4x shrink -> dedup rung 8 -> 64 capped by the
    # doubling-within-shrink rule: 8*2=16 <= 8*10.4, 16*2=32 <= 83,
    # 32*2=64 <= 83 -> 64.
    assert adv["recommended"]["dedup_factor"] == 64
    assert adv["recommended"]["unique_buffer_lanes"] <= 425_984
    assert adv["recommended"]["max_frontier"] == 1 << 15
    assert adv["recommended"]["capacity"] >= 2 * 80_000

    # An observed dedup overflow overrides: recommend the proven rung.
    events.append({"t": 9.0, "event": "grow", "flags": 4,
                   "grown": "dedup_factor=1"})
    adv = advise_geometry(events)
    assert adv["recommended"]["dedup_factor"] == 1
    assert adv["notes"]


def test_advisor_bucket_slack_consistent_with_bench_r06_rung():
    """Acceptance pin: fed the measured paxos c=2 virtual-8 exchange
    occupancies (BENCH_r06.json, the PR-8 bucketed-exchange round), the
    advisor's recommended bucket_slack must equal the knob-cache rung
    that round measured and persisted."""
    import os

    from stateright_tpu.obs.report import advise_geometry

    r06_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_r06.json",
    )
    with open(r06_path) as fh:
        r06 = json.load(fh)["parsed"]["sharded_virtual8"]
    events = [
        {"t": 0.0, "event": "geometry", "engine": "tpu-sharded",
         "shards": 8, "capacity_per_shard": 1 << 14,
         "chunk_size": 1 << 11, "dedup_factor": 4,
         "bucket_slack": r06["bucket_slack"],
         "exchange_bucket_lanes": r06["exchange_bucket_lanes"],
         "u_lanes": 8 * 16384, "waves_per_call": 1},
    ]
    for i in range(int(r06["waves"])):
        # Per-wave occupancies around the round's measured mean, with a
        # 2x peak wave — the shape the traced journal actually has.
        occ = r06["exchange_occupancy"] * (2.0 if i == 3 else 1.0)
        events.append(_wave(
            float(i + 1), i + 1, 40_000 * (i + 1), i, 0.5,
            density=0.005, exchange_occupancy=occ,
        ))
    adv = advise_geometry(events)
    assert adv["recommended"]["bucket_slack"] == r06["bucket_slack"]
    assert adv["measured"]["peak_exchange_occupancy"] == pytest.approx(
        2 * r06["exchange_occupancy"]
    )


def test_advisor_bucket_slack_after_observed_overflow_ramp():
    from stateright_tpu.obs.report import advise_geometry

    events = [
        {"t": 0.0, "event": "geometry", "engine": "tpu-sharded",
         "shards": 4, "bucket_slack": 50, "dedup_factor": 4,
         "chunk_size": 2048, "u_lanes": 4 * 16384},
        {"t": 0.5, "event": "grow", "flags": 32,
         "grown": "bucket_slack=100"},
        {"t": 0.6, "event": "grow", "flags": 32,
         "grown": "bucket_slack=200"},
        _wave(1.0, 1, 1000, 1, 0.5, density=0.01,
              exchange_occupancy=0.4),
    ]
    adv = advise_geometry(events)
    assert adv["recommended"]["bucket_slack"] == 200
    assert any("climbed" in n for n in adv["notes"])


def test_advisor_lands_in_report_and_markdown():
    events = [
        {"t": 0.0, "event": "geometry", "engine": "tpu-wavefront",
         "capacity": 4096, "max_frontier": 512, "dedup_factor": 8,
         "u_lanes": 4096, "waves_per_call": 4},
        _wave(1.0, 4, 500, 2, 0.5, density=0.05),
        _wave(2.0, 8, 900, 4, 0.5, density=0.08),
    ]
    rep = analyze_journal(events)
    assert "advisor" in rep
    md = render_markdown(rep)
    assert "Geometry advisor" in md and "dedup_factor" in md
    json.dumps(rep)


# --- the watch verb -----------------------------------------------------------


def test_watch_summarize_run_journal():
    from stateright_tpu.obs.watch import render_line, summarize_events

    events = [
        {"t": 0.0, "event": "geometry", "engine": "tpu-wavefront",
         "u_lanes": 4096, "dedup_factor": 8},
        _wave(1.0, 4, 500, 2, 0.5, density=0.03),
        _wave(2.0, 8, 900, 4, 0.5, density=0.05),
        {"t": 2.1, "event": "engine_done", "unique": 900},
    ]
    s = summarize_events(events)
    assert s["unique"] == 900 and s["depth"] == 4
    assert s["density"] == 0.05
    assert s["uniq_per_sec"] == pytest.approx(400.0)
    assert s["done"] is True
    line = render_line(s)
    assert "density=0.05" in line and "bottleneck=" in line
    assert "done" in line


def test_watch_flags_recompile_storm_and_torn_lines():
    from stateright_tpu.obs.watch import render_line, summarize_events

    events = [_wave(1.0, 1, 100, 1, 0.5)]
    events += [
        {"t": 1.0 + i * 0.1, "event": "compile", "label": f"p{i}",
         "sec": 0.2}
        for i in range(6)  # >= COMPILE_STORM_THRESHOLD inside the window
    ]
    s = summarize_events(events, skipped=1)
    assert s["recompile_storm"] is True
    line = render_line(s)
    assert "recompile-storm" in line and "torn-lines=1" in line


def test_watch_surfaces_sort_rung_and_thrash_badge():
    """ISSUE-12 satellite: the current sort rung renders next to density
    (latest geometry event, advanced by later rung-climb grow notes),
    and ≥3 flag-4 rung retries inside the window raise the ⚠ badge."""
    from stateright_tpu.obs.watch import render_line, summarize_events

    events = [
        {"t": 0.0, "event": "geometry", "engine": "tpu-wavefront",
         "u_lanes": 16384, "sort_lanes": 16384},
        _wave(1.0, 2, 200, 1, 0.4, density=0.01),
        {"t": 1.5, "event": "geometry", "engine": "tpu-wavefront",
         "u_lanes": 16384, "sort_lanes": 2048},  # tuner downshift
        _wave(2.0, 4, 500, 2, 0.4, density=0.02),
    ]
    s = summarize_events(events)
    assert s["sort_rung"] == 2048
    assert "rung_thrash" not in s
    line = render_line(s)
    assert "sort_rung=2048" in line

    # Three rung-climb retries in the trailing window: the climbed rung
    # wins (it is LATER than the geometry event) and the badge fires.
    events += [
        {"t": 2.0 + i, "event": "grow", "flags": 4,
         "grown": f"sort_lanes={4096 << i}", "unique": 500, "depth": 2}
        for i in range(3)
    ]
    s = summarize_events(events)
    assert s["sort_rung"] == 16384  # 4096 -> 8192 -> 16384
    assert s["sort_rung_retries"] == 3
    assert s["rung_thrash"] is True
    assert "rung-thrash" in render_line(s)


def test_advisor_recommends_sort_rung():
    """The geometry advisor sizes the sort rung from measured peak
    density (4× headroom, pow2), and a mid-run rung climb overrides the
    derivation with the proven rung — the bucket_slack rules, applied
    to the second ladder."""
    geometry = {
        "t": 0.0, "event": "geometry", "engine": "tpu-wavefront",
        "capacity": 1 << 15, "max_frontier": 1 << 11, "dedup_factor": 8,
        "sort_lanes": 16384, "u_lanes": 16384, "waves_per_call": 4,
    }
    events = [
        geometry,
        _wave(1.0, 4, 500, 2, 0.5, density=0.02),
        _wave(2.0, 8, 900, 4, 0.5, density=0.05),
    ]
    rec = analyze_journal(events)["advisor"]["recommended"]
    # peak 0.05 * 16384 * 4x headroom = 3276.8 -> pow2 4096.
    assert rec["sort_lanes"] == 4096

    climbed = events + [
        {"t": 3.0, "event": "grow", "flags": 4, "grown": "sort_lanes=8192",
         "unique": 900, "depth": 4},
    ]
    adv = analyze_journal(climbed)["advisor"]
    assert adv["recommended"]["sort_lanes"] == 8192
    assert any("sort-rung overflow" in n for n in adv["notes"])


def test_watch_summarize_service_journal():
    from stateright_tpu.obs.watch import render_line, summarize_events

    events = [
        {"t": 0.0, "event": "service_start", "workers": 1},
        {"t": 0.1, "event": "job_submitted", "job": "job-1"},
        {"t": 0.2, "event": "job_running", "job": "job-1"},
        {"t": 0.3, "event": "job_submitted", "job": "job-2"},
        {"t": 1.0, "event": "job_done", "job": "job-1"},
    ]
    s = summarize_events(events)
    assert s["jobs"] == {"done": 1, "queued": 1}
    assert "jobs" in render_line(s)


def test_watch_once_cli_smoke(tmp_path, capsys):
    """`watch <journal> --once` through the model CLI: one greppable
    line with the density and bottleneck fields, rc 0; a missing
    journal is rc 2."""
    from stateright_tpu.cli import example_main
    from stateright_tpu.models.twophase import cli_spec
    from stateright_tpu.runtime.journal import Journal

    jpath = str(tmp_path / "journal.jsonl")
    with Journal(jpath) as j:
        j.append("geometry", engine="tpu-wavefront", u_lanes=4096)
        j.append("wave", waves=1, unique=5, depth=1, call_sec=0.1,
                 occupancy=0.01, remaining=0, states=10, flags=0,
                 density=0.002)
        j.append("engine_done", unique=5)
    rc = example_main(cli_spec(), ["watch", jpath, "--once"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "density=0.002" in out and "bottleneck=" in out
    assert example_main(
        cli_spec(), ["watch", str(tmp_path / "nope.jsonl"), "--once"]
    ) == 2
    assert example_main(cli_spec(), ["watch", "--once"]) == 2


# --- actor/chaos journals (ISSUE 15) -----------------------------------------


def _actor_journal(consistent=True):
    """A synthetic chaos-run journal: injections, ops, stats, spans,
    give-up, summary, audit."""
    return [
        {"t": 0.0, "event": "chaos_start", "seed": 7, "spec": {}},
        {"t": 0.1, "event": "actor_op", "kind": "invoke", "client": 3,
         "request_id": 1},
        {"t": 0.15, "event": "actor_span", "trace": "ab" * 8, "hop": 0,
         "src": 3, "dst": 0, "latency_sec": 0.001},
        {"t": 0.2, "event": "chaos_drop", "src": 0, "dst": 1, "n": 0},
        {"t": 0.3, "event": "chaos_duplicate", "src": 0, "dst": 1, "n": 1},
        {"t": 0.35, "event": "actor_span", "trace": "ab" * 8, "hop": 1,
         "src": 0, "dst": 3, "latency_sec": 0.002},
        {"t": 0.4, "event": "actor_op", "kind": "return", "client": 3,
         "request_id": 1},
        {"t": 0.5, "event": "orl_give_up", "actor": 1, "dropped": 1,
         "seqs": [4]},
        {"t": 0.6, "event": "actor_stats", "datagrams": 40, "invoked": 1,
         "returned": 1, "retransmits": 6, "give_ups": 1,
         "partition_active": False},
        # A fault after the op window: attribution must exclude it.
        {"t": 2.0, "event": "chaos_drop", "src": 1, "dst": 0, "n": 2},
        {"t": 2.1, "event": "chaos_summary", "seed": 7, "total": 3,
         "by_kind": {"chaos_drop": 2, "chaos_duplicate": 1},
         "links": {"0->1": {"chaos_drop": 1, "chaos_duplicate": 1},
                   "1->0": {"chaos_drop": 1}}},
        {"t": 2.2, "event": "audit", "consistent": consistent,
         "invoked": 1, "returned": 1, "in_flight": 0, "violations": [],
         "completed": True, "expected": 2, "seed": 7},
    ]


def test_actor_only_journal_degrades_without_bottleneck_phase():
    """ISSUE-15 satellite regression: an actor-only journal (no engine
    wave events) must not crash analyze_journal and must NOT emit a
    bogus bottleneck_phase — it degrades to the actor section with a
    warning."""
    report = analyze_journal(_actor_journal())
    assert report["kind"] == "actor"
    assert "bottleneck_phase" not in report
    assert "phase_breakdown" not in report
    assert any("actor-only" in w for w in report["warnings"])
    actor = report["actor"]
    assert actor["fault_total"] == 3
    assert actor["faults_by_kind"] == {"chaos_drop": 2, "chaos_duplicate": 1}
    assert actor["faults_by_link"] == {
        "0->1": {"chaos_drop": 1, "chaos_duplicate": 1},
        "1->0": {"chaos_drop": 1},
    }
    # ...and it equals the transport's own journaled summary.
    assert actor["faults_by_link"] == actor["chaos_summary"]["links"]
    assert actor["orl_give_ups"] == 1
    assert actor["spans"] == 2 and actor["max_hop"] == 1
    assert actor["audit"]["consistent"] is True
    assert "fault_attribution" not in actor  # consistent: no window
    md = render_markdown(report)
    assert "## Actor runtime" in md and "consistent" in md
    json.dumps(report, default=str)


def test_rejected_audit_attribution_windows_on_ops():
    """A rejected history: the attribution table counts only faults
    inside the audited operation window."""
    report = analyze_journal(_actor_journal(consistent=False))
    attribution = report["actor"]["fault_attribution"]
    # The t=2.0 drop falls outside the [0.1, 0.4] op window.
    assert attribution["fault_total"] == 2
    assert attribution["faults_by_link"] == {
        "0->1": {"chaos_drop": 1, "chaos_duplicate": 1},
    }
    assert attribution["window"]["ops"] == 2
    md = render_markdown(report)
    assert "Fault attribution" in md and "REJECTED" in md


def test_engine_journal_with_actor_events_keeps_run_kind():
    """A run journal that ALSO carries chaos events (a supervised run
    under a chaos-wrapped transport) keeps its run analysis — the actor
    section rides alongside, no degrade warning."""
    events = [
        {"t": 0.5, "event": "wave", "waves": 1, "unique": 100, "depth": 2,
         "call_sec": 0.1, "occupancy": 0.1, "remaining": 0},
        {"t": 0.6, "event": "chaos_drop", "src": 0, "dst": 1, "n": 0},
        {"t": 0.9, "event": "engine_done", "unique": 100},
    ]
    report = analyze_journal(events)
    assert report["kind"] == "run"
    assert "bottleneck_phase" in report
    assert report["actor"]["fault_total"] == 1
    assert not any(
        "actor-only" in w for w in report.get("warnings", [])
    )


def test_watch_renders_actor_journal_fields_and_badges():
    from stateright_tpu.obs.watch import render_line, summarize_events

    events = _actor_journal()
    s = summarize_events(events)
    assert s["datagrams"] == 40 and s["retransmits"] == 6
    assert s["chaos_faults"] == 3 and s["orl_give_ups"] == 1
    assert s["done"] is True  # the audit verdict ends a chaos run
    line = render_line(s)
    assert "retransmits=6" in line and "faults=3" in line
    assert "audit=ok" in line
    assert "orl-give-ups=1" in line

    # msgs/s EMA over consecutive actor_stats events.
    events2 = [e for e in events if e["event"] != "actor_stats"] + [
        {"t": 1.0, "event": "actor_stats", "datagrams": 0, "invoked": 0,
         "returned": 0, "retransmits": 0, "give_ups": 0,
         "partition_active": False},
        {"t": 2.0, "event": "actor_stats", "datagrams": 50, "invoked": 1,
         "returned": 1, "retransmits": 2, "give_ups": 0,
         "partition_active": True},
    ]
    s2 = summarize_events(events2)
    assert s2["msgs_per_sec"] == pytest.approx(50.0)
    assert s2["partition_active"] is True
    assert "partition-active" in render_line(s2)

    # An inconsistent audit raises the badge.
    s3 = summarize_events(_actor_journal(consistent=False))
    assert "audit-inconsistent" in s3["warnings"]
    assert "audit=INCONSISTENT" in render_line(s3)


# --- chaos-ensemble journals (ensemble/engine.py) ----------------------------


def _ensemble_journal():
    """A synthetic ensemble journal: sweep -> failing -> shrink ->
    replay (rejected) -> repro, with the replay's audit event riding
    along (as run_ensemble journals it)."""
    return [
        {"t": 0.0, "event": "ensemble_start", "members": 256, "seed": 3,
         "steps": 48, "workload": "abd", "fault": "skip_ack",
         "spec": {"default": {"drop": 0.1}}},
        {"t": 1.0, "event": "ensemble_failing", "member": 6, "seed": 999,
         "property": "linearizable", "step": 4},
        {"t": 1.1, "event": "ensemble_sweep", "members": 256, "failing": 1,
         "states": 2000, "elapsed_sec": 1.0, "schedules_per_sec": 256.0,
         "ttff_sec": 1.0},
        {"t": 1.5, "event": "ensemble_shrink", "member": 6,
         "candidate": "prefix", "steps": 5, "accepted": True},
        {"t": 1.6, "event": "ensemble_shrink", "member": 6,
         "candidate": "drop", "accepted": False},
        {"t": 2.0, "event": "audit", "consistent": False, "invoked": 4,
         "returned": 4, "in_flight": 0, "violations": [], "seed": 999,
         "fault_links": {"0->1": {"chaos_drop": 1}}},
        {"t": 2.1, "event": "ensemble_replay", "member": 6, "seed": 999,
         "consistent": False, "violations": 0},
        {"t": 2.2, "event": "ensemble_repro", "member": 6, "seed": 999,
         "spec": {"default": {"drop": 0.0}}, "steps": 5,
         "partition_at": -1, "partition_heal": -1, "workload": "abd",
         "fault": "skip_ack", "client_count": 2, "put_count": 1,
         "server_count": 2, "property": "linearizable", "base_seed": 3},
    ]


def test_report_renders_ensemble_journal_as_first_class_kind():
    report = analyze_journal(_ensemble_journal())
    assert report["kind"] == "ensemble"
    ens = report["ensemble"]
    assert ens["members"] == 256 and ens["failing"] == 1
    assert ens["schedules_per_sec"] == 256.0
    assert ens["shrink_accepted"] == 1 and ens["shrink_candidates"] == 2
    assert ens["replay"]["rejected"] is True
    assert ens["repro"]["seed"] == 999 and ens["repro"]["steps"] == 5
    assert ens["failing_seeds"][0]["member"] == 6
    # No actor-only degrade warning: the replay's events ride under the
    # ensemble kind.
    assert not any("actor-only" in w for w in report.get("warnings", []))
    md = render_markdown(report)
    assert "## Chaos ensemble" in md
    assert "failing seeds: **1**" in md
    assert "REJECTED" in md and "repro journaled" in md
    json.dumps(report, default=str)


def test_watch_renders_ensemble_journal_without_audit_warning():
    from stateright_tpu.obs.watch import render_line, summarize_events

    s = summarize_events(_ensemble_journal())
    assert s["ensemble_members"] == 256 and s["ensemble_failing"] == 1
    assert s["ensemble_shrinks"] == 2
    assert s["ensemble_shrinks_accepted"] == 1
    assert s["ensemble_repro"] is True and s["done"] is True
    # The rejected replay audit is the ensemble's SUCCESS, not a warning.
    assert "audit-inconsistent" not in s["warnings"]
    line = render_line(s)
    assert "members=256" in line and "failing=1" in line
    assert "shrinks=1/2" in line and "repro=journaled" in line
    assert "audit=INCONSISTENT" not in line
