"""End-to-end CLI tests: every model module is a runnable mini-binary with
check/check-sym/check-simulation/check-tpu/explore/spawn subcommands,
mirroring the reference examples' pico_args CLIs (examples/paxos.rs:355-513).
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(module, *args, timeout=180):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", f"stateright_tpu.models.{module}", *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=REPO,
    )


def test_check_subcommand_single_copy_register():
    r = run_cli("single_copy_register", "check", "2")
    assert r.returncode == 0, r.stderr
    assert "unique=93" in r.stdout  # examples/single-copy-register.rs:111
    assert 'Discovered "value chosen" example' in r.stdout


def test_check_sym_subcommand_twophase():
    r = run_cli("twophase", "check-sym", "5")
    assert r.returncode == 0, r.stderr
    assert "unique=665" in r.stdout  # examples/2pc.rs:163-168


def test_check_sym_tpu_subcommand_twophase():
    """check-sym --tpu: the symmetry-reduced check on the device
    wavefront engine — dedup on the canonical-row fingerprint.  The
    full-record canon is the exact orbit invariant: 80 classes at rm=3
    (tests/test_tpu_symmetry.py pins the recipe; docs/SYMMETRY.md
    explains why this differs from the host DFS's tie-broken 107)."""
    r = run_cli("twophase", "check-sym", "3", "--tpu", timeout=600)
    assert r.returncode == 0, r.stderr
    assert "unique=80" in r.stdout


def test_network_positional():
    r = run_cli("single_copy_register", "check", "2", "ordered")
    assert r.returncode == 0, r.stderr
    assert "network=ordered" in r.stdout
    assert "Done." in r.stdout


def test_unknown_network_name_errors():
    r = run_cli("single_copy_register", "check", "2", "ordred")
    assert r.returncode == 2
    assert "unable to parse network name" in r.stderr


def test_unexpected_argument_errors():
    r = run_cli("twophase", "check", "3", "extra")
    assert r.returncode == 2
    assert "unexpected argument" in r.stderr


def test_check_simulation_subcommand():
    r = run_cli("increment", "check-simulation", "2", "7")
    assert r.returncode == 0, r.stderr
    assert "Done." in r.stdout


def test_usage_on_no_args():
    r = run_cli("paxos")
    assert r.returncode == 0
    assert "check [CLIENT_COUNT] [NETWORK]" in r.stdout
    assert "spawn" in r.stdout
    for name in ("ordered", "unordered_duplicating", "unordered_nonduplicating"):
        assert name in r.stdout


def test_unknown_subcommand_fails():
    r = run_cli("paxos", "frobnicate")
    assert r.returncode == 2


def test_explore_subcommand_serves_http():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "stateright_tpu.models.single_copy_register",
            "explore",
            "2",
            "localhost:3919",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        cwd=REPO,
    )
    try:
        status = None
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    "http://localhost:3919/.status", timeout=2
                ) as resp:
                    status = json.loads(resp.read())
                break
            except Exception:
                time.sleep(0.3)
        assert status is not None, "explorer never came up"
        assert "properties" in status or "model" in status
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_spawn_subcommand_real_udp_paxos():
    """`spawn` runs the checked actors on real UDP: a Put reaches quorum
    and returns PutOk; a Get on a *different* replica returns the decided
    value (the reference's spawn UX, examples/paxos.rs:488-512)."""
    import socket

    # The spawn subcommand binds fixed localhost ports (the reference UX);
    # skip rather than fail when the environment already holds them.
    for port in (3000, 3001, 3002, 3103):
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.bind(("127.0.0.1", port))
        except OSError:
            pytest.skip(f"udp port {port} unavailable in this environment")
        finally:
            probe.close()

    sys.path.insert(0, REPO)
    from stateright_tpu.actor.register import Get, GetOk, Internal, Put, PutOk
    from stateright_tpu.actor.wire import register_wire_types, wire_deserialize, wire_serialize
    from stateright_tpu.models.paxos import (
        Accept, Accepted, Decided, Prepare, Prepared,
    )

    register_wire_types(
        Put, Get, PutOk, GetOk, Internal, Prepare, Prepared, Accept,
        Accepted, Decided,
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, "-m", "stateright_tpu.models.paxos", "spawn"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        cwd=REPO,
    )
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.bind(("127.0.0.1", 3103))
        s.settimeout(20)
        time.sleep(2.0)
        s.sendto(
            wire_serialize(Put(request_id=1, value="X")), ("127.0.0.1", 3000)
        )
        msg, _ = s.recvfrom(65535)
        assert wire_deserialize(msg) == PutOk(request_id=1)
        s.sendto(wire_serialize(Get(request_id=2)), ("127.0.0.1", 3001))
        msg, _ = s.recvfrom(65535)
        assert wire_deserialize(msg) == GetOk(request_id=2, value="X")
    finally:
        s.close()
        proc.terminate()
        proc.wait(timeout=10)


@pytest.mark.slow
def test_check_tpu_subcommand():
    r = run_cli("twophase", "check-tpu", "3", timeout=300)
    assert r.returncode == 0, r.stderr
    assert "unique=288" in r.stdout


def test_runtime_flags_require_check_tpu():
    r = run_cli("twophase", "check", "3", "--supervise")
    assert r.returncode == 2
    assert "check-tpu" in r.stderr
    r = run_cli("twophase", "check-sym", "3", "--checkpoint-dir", "/tmp/x")
    assert r.returncode == 2


def test_supervise_requires_checkpoint_dir():
    r = run_cli("twophase", "check-tpu", "3", "--supervise")
    assert r.returncode == 2
    assert "--checkpoint-dir" in r.stderr


def test_resume_requires_checkpoint_dir():
    # Silently starting fresh would discard the progress the flag was
    # meant to continue.
    r = run_cli("twophase", "check-tpu", "3", "--resume")
    assert r.returncode == 2
    assert "--checkpoint-dir" in r.stderr


def test_checkpoint_dir_flag_value_missing_is_clean_error():
    r = run_cli("twophase", "check-tpu", "3", "--checkpoint-dir")
    assert r.returncode == 2
    assert "requires a directory" in r.stderr


def test_trace_flag_requires_check_tpu_and_rejects_resume():
    r = run_cli("twophase", "check", "3", "--trace")
    assert r.returncode == 2
    assert "check-tpu" in r.stderr
    r = run_cli("twophase", "check-tpu", "3", "--trace", "--resume",
                "--checkpoint-dir", "/tmp/x")
    assert r.returncode == 2
    assert "--trace" in r.stderr


@pytest.mark.slow
def test_check_tpu_trace_emits_breakdown(tmp_path):
    """`check-tpu --trace` completes with the golden count, prints the
    one-line roofline reduction, and (with --checkpoint-dir) leaves the
    enriched wave-trace records in the run journal — the CI artifact
    path (docs/OBSERVABILITY.md)."""
    run_dir = str(tmp_path / "trace-run")
    r = run_cli(
        "twophase", "check-tpu", "3", "--trace",
        "--checkpoint-dir", run_dir, timeout=600,
    )
    assert r.returncode == 0, r.stderr
    assert "unique=288" in r.stdout
    trace_line = next(
        ln for ln in r.stdout.splitlines() if ln.startswith("trace: ")
    )
    summary = json.loads(trace_line[len("trace: "):])
    assert summary["traced_waves"] >= 1
    assert set(summary["wave_breakdown"]) == {
        "step", "canon", "dedup", "append", "readback",
    }
    from stateright_tpu.runtime.journal import read_journal

    waves = [
        e for e in read_journal(os.path.join(run_dir, "journal.jsonl"))
        if e["event"] == "wave"
    ]
    assert waves and all("wave_breakdown" in w for w in waves)


@pytest.mark.slow
def test_check_tpu_supervised_writes_journal_and_checkpoint(tmp_path):
    """`check-tpu --supervise --checkpoint-dir` completes the check
    through the run supervisor and leaves the run artifacts: a JSONL
    journal with wave telemetry and an engine_done event, plus a
    checkpoint snapshot."""
    run_dir = str(tmp_path / "run")
    r = run_cli(
        "twophase", "check-tpu", "3", "--supervise",
        "--checkpoint-dir", run_dir, timeout=600,
    )
    assert r.returncode == 0, r.stderr
    assert "unique=288" in r.stdout  # the child's report streams through
    events = [
        json.loads(ln)
        for ln in open(os.path.join(run_dir, "journal.jsonl"))
        if ln.strip()
    ]
    kinds = [e["event"] for e in events]
    assert "supervisor_start" in kinds
    assert "wave" in kinds
    assert "engine_done" in kinds
    assert "supervisor_done" in kinds
    assert os.path.exists(os.path.join(run_dir, "checkpoint.npz"))


def test_check_tpu_violating_model_exits_violation_rc():
    """Satellite pin: a COMPLETED check-tpu that discovered a property
    violation exits VIOLATION_RC (4) so CI and service callers can gate
    on the verdict.  fixtures = TrapCounter, the known-violating
    compiled workload ("reaches limit" counterexample)."""
    r = run_cli("fixtures", "check-tpu", "5", timeout=600)
    assert r.returncode == 4, (r.returncode, r.stderr)
    assert "violation discovered: reaches limit" in r.stderr
    assert 'Discovered "reaches limit" counterexample' in r.stdout


def test_usage_lists_service_verbs():
    r = run_cli("twophase")
    for verb in ("serve [ADDRESS]", "submit [RM_COUNT]", "status [JOB_ID]"):
        assert verb in r.stdout, r.stdout


def test_submit_without_server_is_clean_error():
    # Port 9 (discard) refuses connections; the client must say what to
    # start, not stack-trace.
    r = run_cli("twophase", "submit", "3", "--address", "127.0.0.1:9")
    assert r.returncode == 1
    assert "cannot reach the checking service" in r.stderr


def test_submit_rejects_bad_flag_values():
    r = run_cli("twophase", "submit", "3", "--portfolio", "x")
    assert r.returncode == 2
    assert "--portfolio requires a int" in r.stderr
    r = run_cli("twophase", "submit", "3", "--address")
    assert r.returncode == 2
    assert "requires a value" in r.stderr


@pytest.mark.slow
def test_serve_submit_status_end_to_end(tmp_path):
    """The service UX end to end through real processes: a daemon, a
    clean submit (rc 0), a violating portfolio submit (rc VIOLATION_RC),
    and status.  The per-push CI serve smoke covers the same flow; this
    is the nightly in-tree pin."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    address = "127.0.0.1:3923"
    journal = str(tmp_path / "journal.jsonl")
    daemon = subprocess.Popen(
        [sys.executable, "-m", "stateright_tpu.serve", address,
         "--journal", journal, "--knob-cache", str(tmp_path / "knobs")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, cwd=REPO,
    )
    try:
        deadline = time.time() + 60
        up = False
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://{address}/.status", timeout=2
                ) as resp:
                    up = json.loads(resp.read())["service"] is not None
                break
            except Exception:
                time.sleep(0.3)
        assert up, "service daemon never came up"
        clean = run_cli("twophase", "submit", "3", "--address", address,
                        timeout=600)
        assert clean.returncode == 0, clean.stderr
        assert "submitted job-" in clean.stdout
        viol = run_cli("fixtures", "submit", "5", "--address", address,
                       "--portfolio", "3", timeout=600)
        assert viol.returncode == 4, (viol.returncode, viol.stderr)
        assert "violation discovered: reaches limit" in viol.stderr
        status = run_cli("twophase", "status", "--address", address)
        assert status.returncode == 0
        jobs = json.loads(status.stdout.strip().splitlines()[-1])
        assert [j["state"] for j in jobs] == ["done", "done"]
        events = [json.loads(ln)["event"] for ln in open(journal)]
        assert "portfolio_winner" in events
    finally:
        daemon.terminate()
        daemon.wait(timeout=10)


def test_wire_codec_malformed_messages_raise_valueerror():
    """A hand-typed probe datagram with wrong fields must surface as
    ValueError (which the UDP runtime drops) — never a TypeError that
    would kill a replica thread."""
    sys.path.insert(0, REPO)
    from stateright_tpu.actor.register import Put
    from stateright_tpu.actor.wire import register_wire_types, wire_deserialize

    register_wire_types(Put)
    with pytest.raises(ValueError):
        wire_deserialize(b'{"__t": "Put", "request_id": 1}')  # missing value
    with pytest.raises(ValueError):
        wire_deserialize(b'{"__t": "NoSuchType"}')
    with pytest.raises(ValueError):
        wire_deserialize(b'{"__tup": 5}')


def test_explore_invalid_port_is_clean_error():
    r = run_cli("paxos", "explore", "2", "localhost:abc")
    assert r.returncode == 2
    assert "invalid ADDRESS port" in r.stderr


# --- spawn --chaos: the fault-injecting runtime surface ----------------------


def test_spawn_chaos_rejects_malformed_spec():
    r = run_cli("abd", "spawn", "--chaos", '{"drop": 1.5}')
    assert r.returncode == 2
    assert "probability" in r.stderr


def test_spawn_chaos_rejects_bad_flag_values():
    r = run_cli("abd", "spawn", "--chaos", "{}", "--seed", "x")
    assert r.returncode == 2
    assert "--seed requires an integer" in r.stderr
    r = run_cli("abd", "spawn", "--seed")
    assert r.returncode == 2
    assert "requires a value" in r.stderr


def test_spawn_chaos_on_non_capable_model_is_clean_error():
    r = run_cli("paxos", "spawn", "--chaos", "{}")
    assert r.returncode == 2
    assert "not chaos-capable" in r.stderr


def test_spawn_chaos_audit_end_to_end(tmp_path):
    """The headline chaos flow: a seeded, hermetic ABD cluster under
    drop+duplicate+reorder, audited for linearizability, journaling every
    injected fault — exit code reports the verdict."""
    journal = str(tmp_path / "journal.jsonl")
    r = run_cli(
        "abd", "spawn",
        "--chaos", '{"drop": 0.1, "duplicate": 0.1, "reorder": 0.15}',
        "--seed", "7", "--audit", "--journal", journal,
        "--duration", "30",
        timeout=180,
    )
    assert r.returncode == 0, r.stderr
    verdict = json.loads(r.stdout.strip().splitlines()[-1])
    assert verdict["consistent"] is True
    assert verdict["returned"] >= 1
    from stateright_tpu.runtime.journal import read_journal

    kinds = [e["event"] for e in read_journal(journal)]
    assert kinds[0] == "chaos_start"
    assert "audit" in kinds
    assert any(k.startswith("chaos_") for k in kinds[1:])
