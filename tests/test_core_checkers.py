"""Core checker golden tests.

Mirrors the reference's inline test modules for BFS (src/checker/bfs.rs),
DFS (src/checker/dfs.rs), eventually-property semantics (src/checker.rs:589-681),
path reconstruction (src/checker.rs:683-707), and report format
(src/checker.rs:709-799).  The golden numbers (15/12/4 BFS, 55/55/28 DFS,
65,536 full enumeration, 9→6 symmetry) are the reference's own.
"""

import io
import re
from dataclasses import dataclass
from typing import List, Tuple

import pytest

from stateright_tpu import (
    HasDiscoveries,
    NondeterminismError,
    Path,
    PathRecorder,
    Property,
    StateRecorder,
    WriteReporter,
    fingerprint,
)
from stateright_tpu.core.model import Model
from stateright_tpu.models.fixtures import (
    BinaryClock,
    DGraph,
    FnModel,
    LinearEquation,
    Panicker,
)

Guess = LinearEquation.Guess


# --- BFS (src/checker/bfs.rs:411-489) ---------------------------------------


def test_visits_states_in_bfs_order():
    recorder, accessor = StateRecorder.new_with_accessor()
    LinearEquation(a=2, b=10, c=14).checker().visitor(recorder).spawn_bfs().join()
    assert accessor() == [
        (0, 0),
        (1, 0),
        (0, 1),
        (2, 0),
        (1, 1),
        (0, 2),
        (3, 0),
        (2, 1),
    ]


def test_bfs_can_complete_by_enumerating_all_states():
    checker = LinearEquation(a=2, b=4, c=7).checker().spawn_bfs().join()
    assert checker.is_done()
    checker.assert_no_discovery("solvable")
    assert checker.unique_state_count() == 256 * 256


def test_bfs_can_complete_by_eliminating_properties():
    checker = LinearEquation(a=2, b=10, c=14).checker().spawn_bfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() == 12
    assert checker.discovery("solvable").into_actions() == [
        Guess.INCREASE_X,
        Guess.INCREASE_X,
        Guess.INCREASE_Y,
    ]
    checker.assert_discovery("solvable", [Guess.INCREASE_Y] * 27)


def test_bfs_handles_panics_gracefully():
    with pytest.raises(RuntimeError, match="reached panic state"):
        Panicker().checker().threads(2).spawn_bfs().join()


# --- DFS (src/checker/dfs.rs:404-585) ---------------------------------------


def test_visits_states_in_dfs_order():
    recorder, accessor = StateRecorder.new_with_accessor()
    LinearEquation(a=2, b=10, c=14).checker().visitor(recorder).spawn_dfs().join()
    assert accessor() == [(0, y) for y in range(28)]


def test_dfs_can_complete_by_enumerating_all_states():
    checker = LinearEquation(a=2, b=4, c=7).checker().spawn_dfs().join()
    assert checker.is_done()
    checker.assert_no_discovery("solvable")
    assert checker.unique_state_count() == 256 * 256


def test_dfs_can_complete_by_eliminating_properties():
    checker = LinearEquation(a=2, b=10, c=14).checker().spawn_dfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() == 55
    assert checker.discovery("solvable").into_actions() == [Guess.INCREASE_Y] * 27
    checker.assert_discovery(
        "solvable", [Guess.INCREASE_X, Guess.INCREASE_Y, Guess.INCREASE_X]
    )


def test_dfs_handles_panics_gracefully():
    with pytest.raises(RuntimeError, match="reached panic state"):
        Panicker().checker().threads(2).spawn_dfs().join()


# --- Symmetry reduction (src/checker/dfs.rs:486-573) ------------------------

PAUSED, LOADING, RUNNING = 0, 1, 2  # Paused < Loading < Running, as reference


class SymSys(Model):
    def init_states(self):
        return [(LOADING, LOADING)]

    def actions(self, state, actions):
        actions.extend([0, 1])

    def next_state(self, state, action):
        procs = list(state)
        p = procs[action]
        procs[action] = RUNNING if p in (LOADING, PAUSED) else PAUSED
        return tuple(procs)

    def properties(self):
        return [
            Property.always("visit all states", lambda _m, _s: True),
            Property.sometimes(
                "a process pauses", lambda _m, s: PAUSED in s
            ),
        ]


def test_can_apply_symmetry_reduction():
    checker = SymSys().checker().spawn_dfs().join()
    assert checker.unique_state_count() == 9
    checker = SymSys().checker().spawn_bfs().join()
    assert checker.unique_state_count() == 9

    visitor, _ = PathRecorder.new_with_accessor()
    checker = (
        SymSys()
        .checker()
        .symmetry_fn(lambda s: tuple(sorted(s)))
        .visitor(visitor)
        .spawn_dfs()
        .join()
    )
    assert checker.unique_state_count() == 6


# --- eventually-property semantics (src/checker.rs:589-681) -----------------


def eventually_odd():
    return Property.eventually("odd", lambda _m, s: s % 2 == 1)


def test_eventually_can_validate():
    (
        DGraph.with_property(eventually_odd())
        .with_path([1])
        .with_path([2, 3])
        .with_path([2, 6, 7])
        .with_path([4, 9, 10])
        .check()
        .assert_properties()
    )
    for path in ([1], [2, 3], [2, 6, 7], [4, 9, 10]):
        DGraph.with_property(eventually_odd()).with_path(
            list(path)
        ).check().assert_properties()


def test_eventually_can_discover_counterexample():
    d = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 1])
        .with_path([0, 2])
        .check()
        .discovery("odd")
    )
    assert d.into_states() == [0, 2]
    d = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 1])
        .with_path([2, 4])
        .check()
        .discovery("odd")
    )
    assert d.into_states() == [2, 4]
    d = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 1, 4, 6])
        .with_path([2, 4, 8])
        .check()
        .discovery("odd")
    )
    assert d.into_states() == [2, 4, 6]


def test_fixme_can_miss_counterexample_when_revisiting_a_state():
    # The reference's documented false negative, intentionally reproduced
    # (src/checker.rs:663-680).
    assert (
        DGraph.with_property(eventually_odd())
        .with_path([0, 2, 4, 2])
        .check()
        .discovery("odd")
        is None
    )
    assert (
        DGraph.with_property(eventually_odd())
        .with_path([0, 2, 4])
        .with_path([1, 4, 6])
        .check()
        .discovery("odd")
        is None
    )


# --- Path (src/checker.rs:683-707, src/checker/path.rs:223-256) -------------


def test_can_build_path_from_fingerprints():
    model = LinearEquation(a=2, b=10, c=14)
    fps = [
        fingerprint((0, 0)),
        fingerprint((0, 1)),
        fingerprint((1, 1)),
        fingerprint((2, 1)),
    ]
    path = Path.from_fingerprints(model, fps)
    assert path.last_state() == (2, 1)
    assert path.last_state() == Path.final_state(model, fps)


def test_panics_if_unable_to_reconstruct_init_state():
    def fn(prev, out):
        if prev is None:
            out.append("UNEXPECTED")

    with pytest.raises(NondeterminismError):
        Path.from_fingerprints(FnModel(fn), [fingerprint("expected")])


def test_panics_if_unable_to_reconstruct_next_state():
    def fn(prev, out):
        if prev is None:
            out.append("expected")
        else:
            out.append("UNEXPECTED")

    with pytest.raises(NondeterminismError):
        Path.from_fingerprints(
            FnModel(fn), [fingerprint("expected"), fingerprint("expected")]
        )


# --- report format (src/checker.rs:709-799) ---------------------------------


def test_report_includes_property_names_and_paths():
    # BFS
    written = io.StringIO()
    LinearEquation(a=2, b=10, c=14).checker().spawn_bfs().report(
        WriteReporter(written, delay=0.02)
    )
    output = written.getvalue()
    assert re.search(r"Done\. states=15, unique=12, depth=4, sec=", output), output
    assert (
        'Discovered "solvable" example Path[3]:\n'
        "- IncreaseX\n- IncreaseX\n- IncreaseY\nFingerprint path: " in output
    ), output
    # the fingerprint path has 4 fingerprints
    m = re.search(r"Fingerprint path: ([0-9/]+)\n", output)
    assert m and len(m.group(1).split("/")) == 4

    # DFS
    written = io.StringIO()
    LinearEquation(a=2, b=10, c=14).checker().spawn_dfs().report(
        WriteReporter(written, delay=0.02)
    )
    output = written.getvalue()
    assert re.search(r"Done\. states=55, unique=55, depth=28, sec=", output), output
    assert 'Discovered "solvable" example Path[27]:\n' + "- IncreaseY\n" * 27 in output
    m = re.search(r"Fingerprint path: ([0-9/]+)\n", output)
    assert m and len(m.group(1).split("/")) == 28


# --- misc surface -----------------------------------------------------------


def test_binary_clock():
    checker = BinaryClock().checker().spawn_bfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() == 2


def test_finish_when_any():
    checker = (
        LinearEquation(a=2, b=10, c=14)
        .checker()
        .finish_when(HasDiscoveries.ANY)
        .spawn_bfs()
        .join()
    )
    assert checker.discovery("solvable") is not None


def test_target_max_depth():
    checker = (
        LinearEquation(a=2, b=4, c=7).checker().target_max_depth(3).spawn_bfs().join()
    )
    assert checker.is_done()
    # depth-3 states are generated but skipped, not expanded: 1 + 2 + 3
    assert checker.unique_state_count() == 6


def test_target_state_count():
    checker = (
        LinearEquation(a=2, b=4, c=7)
        .checker()
        .target_state_count(100)
        .spawn_bfs()
        .join()
    )
    assert checker.state_count() >= 100
    assert checker.unique_state_count() < 256 * 256
