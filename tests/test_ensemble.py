"""The chaos-ensemble engine end to end: one dispatch sweeping >=1024
fault schedules, the device->host repro bridge (shrink, journal, host
replay to a rejected history with the fault-attribution table), and the
purity of per-member schedule derivation."""

import json

import pytest

from stateright_tpu.ensemble.engine import (
    replay_repro,
    run_ensemble,
)
from stateright_tpu.ensemble.schedule import (
    EnsembleSchedule,
    derive_schedule,
    member_seed,
)
from stateright_tpu.runtime.chaos import ChaosSpec

_CHAOS = (
    '{"default": {"drop": 0.15, "reorder": 0.1, "duplicate": 0.05,'
    ' "delay": [0.0, 0.002]}}'
)


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.fixture(scope="module")
def replayed_run(tmp_path_factory):
    """One ensemble run with shrink + host replay, shared by the bridge
    tests below (the replay is the expensive part)."""
    journal = tmp_path_factory.mktemp("ensemble") / "journal.jsonl"
    result = run_ensemble(
        members=64,
        seed=3,
        steps=48,
        fault="skip_ack",
        chaos=_CHAOS,
        journal=str(journal),
        shrink=True,
        replay=True,
    )
    return result, journal


def test_one_dispatch_sweeps_1024_schedules():
    result = run_ensemble(
        members=1024,
        seed=7,
        steps=48,
        fault="skip_ack",
        chaos='{"default": {"drop": 0.1}}',
        shrink=False,
        replay=False,
    )
    assert result.dispatches == 1
    assert result.members == 1024
    assert result.states_walked > 0
    assert result.schedules_per_sec > 0
    # The known-violating workload: the sweep finds failing seeds, and
    # time-to-first-failure is the dispatch time (one dispatch).
    assert len(result.failing) > 0
    assert result.ttff_sec is not None
    assert all(f["property"] == "linearizable" for f in result.failing)


def test_failing_seed_shrinks_and_host_replay_rejects(replayed_run):
    result, _journal = replayed_run
    assert len(result.failing) > 0
    # The shrinker ran and the repro is at most the original horizon.
    assert result.shrink_steps > 0
    assert result.repro is not None
    assert result.repro["steps"] <= 48
    # The host replay REJECTED the history: the confirmation oracle.
    assert len(result.confirmed) == 1
    confirmed = result.confirmed[0]
    assert confirmed["seed"] == result.repro["seed"]
    assert confirmed["returned"] > 0  # a real history, not a stalled run
    # The fault-attribution table rode along as evidence.
    assert isinstance(confirmed["fault_links"], dict)


def test_ensemble_journal_carries_the_whole_story(replayed_run):
    result, journal = replayed_run
    events = _events(journal)
    by_kind = {}
    for e in events:
        by_kind.setdefault(e["event"], []).append(e)
    assert by_kind["ensemble_start"][0]["members"] == 64
    sweep = by_kind["ensemble_sweep"][0]
    assert sweep["failing"] == len(result.failing)
    assert by_kind["ensemble_failing"]  # at least one journaled failure
    assert by_kind["ensemble_shrink"]  # shrink candidates journaled
    assert len(by_kind["ensemble_repro"]) == 1
    # The replay journals the audit verdict with the attribution table.
    audits = by_kind["audit"]
    rejected = [a for a in audits if not a["consistent"]]
    assert rejected and "fault_links" in rejected[0]


def test_repro_replays_from_the_journal_event_alone(replayed_run):
    result, journal = replayed_run
    (repro_event,) = [
        e for e in _events(journal) if e["event"] == "ensemble_repro"
    ]
    # Strip the journal envelope; what remains is the repro payload.
    payload = {k: v for k, v in repro_event.items() if k not in ("t", "event")}
    assert payload["seed"] == result.repro["seed"]
    verdict = replay_repro(payload)
    assert verdict["consistent"] is False


def test_healthy_model_finds_no_failing_seed():
    result = run_ensemble(
        members=128,
        seed=7,
        steps=48,
        fault=None,
        chaos='{"default": {"drop": 0.1}}',
        shrink=True,
        replay=True,
    )
    assert result.failing == []
    assert result.confirmed == []
    assert result.repro is None


def test_schedule_derivation_is_pure():
    spec = ChaosSpec.from_json(_CHAOS)
    a = derive_schedule(3, 11, spec, 48)
    b = derive_schedule(3, 11, spec, 48)
    assert a == b
    assert a.seed == member_seed(3, 11)
    # Different members draw different seeds and different rate scales.
    c = derive_schedule(3, 12, spec, 48)
    assert c.seed != a.seed
    assert c.spec.default.drop != a.spec.default.drop
    # Scaled rates stay within the base rates.
    assert 0.0 <= a.spec.default.drop <= spec.default.drop
    assert 0.0 <= a.spec.default.delay[1] <= spec.default.delay[1]


def test_repro_payload_round_trips():
    spec = ChaosSpec.from_json(
        '{"default": {"drop": 0.2}, "links": {"0->1": {"reorder": 0.5}},'
        ' "partitions": [{"at": 0.0, "groups": [[0], [1]]}]}'
    )
    sch = derive_schedule(9, 5, spec, 32)
    # JSON round trip, as the journal would store it.
    payload = json.loads(json.dumps(sch.to_repro()))
    back = EnsembleSchedule.from_repro(payload)
    assert back.member == sch.member
    assert back.seed == sch.seed
    assert back.steps == sch.steps
    assert back.partition_at == sch.partition_at
    assert back.partition_heal == sch.partition_heal
    assert back.spec.to_dict() == sch.spec.to_dict()
