"""The guaranteed cross-engine ``metrics()`` schema and the always-on
fused-loop vitals (docs/OBSERVABILITY.md).

Two pins: (1) every engine — host graph, simulation, single-chip,
sharded, tiered — reports the guaranteed key set with consistent types
(incl. ``table_load_factor`` and the process-global program-cache
counters); (2) a FUSED (non-traced) device run reports nonzero
wave-latency histogram counts, a uniq/s EMA, and ``table_load_factor``
through ``metrics()`` and the Explorer's ``GET /.metrics`` — in both
JSON and Prometheus form — while the trace=False device-program
invariance pin (tests/test_obs.py) stays green.
"""

import json
import urllib.request

import pytest

jax = pytest.importorskip("jax")
import numpy as np  # noqa: E402

from stateright_tpu.core.simulation import UniformChooser  # noqa: E402
from stateright_tpu.models.fixtures import (  # noqa: E402
    BinaryClock, LinearEquation,
)
from stateright_tpu.models.twophase import TwoPhaseSys  # noqa: E402
from stateright_tpu.obs.prometheus import parse_prometheus  # noqa: E402


def _cpu():
    return jax.devices("cpu")[0]


# name -> required type(s); bool is checked FIRST (it is an int subclass).
GUARANTEED = {
    "engine": str,
    "done": bool,
    "state_count": int,
    "unique_state_count": int,
    "max_depth": int,
    "table_load_factor": (int, float),
    "program_cache_hits": int,
    "program_cache_misses": int,
    # Compile observability (ISSUE 11): the process-global first-call
    # compile time + storm counter ride the guaranteed schema so one
    # scrape answers "is this process recompiling / thrashing".
    "compile_sec_total": (int, float),
    "recompile_storms": int,
}


def _assert_schema(m: dict, who: str) -> None:
    for key, want in GUARANTEED.items():
        assert key in m, f"{who}: metrics() missing guaranteed key {key!r}"
        value = m[key]
        if want is bool:
            assert isinstance(value, bool), (who, key, type(value))
        elif want is int:
            assert isinstance(value, int) and not isinstance(value, bool), (
                who, key, type(value),
            )
        else:
            assert isinstance(value, want) and not isinstance(value, bool), (
                who, key, type(value),
            )
    # The snapshot must stay JSON-serializable: every surface (Explorer,
    # serve, result.json) ships it as JSON.
    json.dumps(m)


def test_guaranteed_schema_actor_runtime():
    """The actor runtime (ISSUE 15) reports the same guaranteed key set
    as every checking engine — a spawned production system scrapes like
    a checker.  Actor semantics: state_count counts handled messages,
    unique_state_count the spawned actors, max_depth the deepest causal
    hop; no device table, so table_load_factor is 0.0."""
    from stateright_tpu.actor.base import Actor, Out
    from stateright_tpu.actor.ids import Id
    from stateright_tpu.actor.obs import ObservedTransport
    from stateright_tpu.actor.spawn import (
        json_deserialize, json_serialize, spawn,
    )
    from stateright_tpu.actor.transport import LoopbackTransport

    class _Quiet(Actor):
        def on_start(self, id, storage, o: Out):
            return ()

        def on_msg(self, id, state, src, msg, o: Out):
            return None

    transport = ObservedTransport(LoopbackTransport(), trace=True)
    runtime = spawn(
        json_serialize, json_deserialize, json_serialize, json_deserialize,
        [(Id(1), _Quiet())], storage_dir="/tmp", transport=transport,
        metrics=transport.registry,
    )
    probe = transport.bind(Id(9))
    try:
        probe.send(Id(1), json_serialize({"poke": 1}))
        deadline_metrics = runtime.metrics()
        _assert_schema(deadline_metrics, "ActorRuntime (running)")
        assert deadline_metrics["done"] is False
    finally:
        probe.close()
        runtime.stop()
    m = runtime.metrics()
    _assert_schema(m, "ActorRuntime")
    assert m["engine"] == "ActorRuntime"
    assert m["done"] is True
    assert m["unique_state_count"] == 1
    assert m["table_load_factor"] == 0.0  # no device table
    assert "histograms" in m


def test_guaranteed_schema_host_and_simulation_engines():
    bfs = BinaryClock().checker().spawn_bfs().join()
    _assert_schema(bfs.metrics(), "GraphChecker")
    sim = (
        LinearEquation(a=2, b=10, c=14)
        .checker()
        .spawn_simulation(0, UniformChooser())
        .join()
    )
    _assert_schema(sim.metrics(), "SimulationChecker")
    assert sim.metrics()["table_load_factor"] == 0.0  # no device table


def test_guaranteed_schema_device_engines():
    model = TwoPhaseSys(rm_count=3)
    tpu = model.checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
    ).join()
    _assert_schema(tpu.metrics(), "TpuChecker")

    mesh = jax.sharding.Mesh(np.array(jax.devices("cpu")[:2]), ("shards",))
    sharded = model.checker().spawn_tpu_sharded(
        mesh=mesh, capacity=1 << 12, chunk_size=1 << 6,
    ).join()
    _assert_schema(sharded.metrics(), "ShardedTpuChecker")

    tiered = model.checker().spawn_tpu_tiered(
        capacity=512, max_frontier=1 << 6,
    ).join()
    _assert_schema(tiered.metrics(), "TieredTpuChecker")

    for who, m in (("tpu", tpu.metrics()), ("sharded", sharded.metrics()),
                   ("tiered", tiered.metrics())):
        assert m["unique_state_count"] == 288, who
        assert m["table_load_factor"] > 0, who


# --- always-on fused-loop vitals ---------------------------------------------


def test_fused_untraced_run_reports_vitals():
    """trace=False, fused device program untouched (the invariance pin
    in tests/test_obs.py covers byte-identity) — and yet metrics()
    carries the vitals: nonzero wave-latency histogram counts, a
    uniq/s EMA, grow counters, and the host/device time split."""
    ck = TwoPhaseSys(rm_count=3).checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
    ).join()
    m = ck.metrics()
    assert m["trace"] is False
    h = m["histograms"]["wave_latency_sec"]
    assert h["count"] > 0
    assert sum(h["counts"]) == h["count"]
    assert h["p50"] <= h["p95"] <= h["p99"]
    assert m["uniq_per_sec_ema"] > 0
    assert m["waves_per_sec_ema"] > 0
    assert m["host_sec_total"] >= 0
    assert m["device_call_sec_total"] > 0
    assert m["table_load_factor"] > 0
    # Density telemetry (ISSUE 11): the valid-candidates-vs-U-buffer
    # fraction, as EMA gauge + histogram, and the load-factor
    # trajectory — all on the untouched fused path.
    assert 0 < m["valid_density_ema"] <= 1.0
    assert m["histograms"]["valid_density"]["count"] > 0
    assert m["histograms"]["load_factor"]["count"] > 0


def test_every_device_engine_reports_density_keys():
    """The acceptance bar: every engine's metrics() reports the new
    density keys — single-chip, sharded (with per-shard skew), and
    tiered."""
    model = TwoPhaseSys(rm_count=3)
    tpu = model.checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
    ).join()
    mesh = jax.sharding.Mesh(np.array(jax.devices("cpu")[:2]), ("shards",))
    sharded = model.checker().spawn_tpu_sharded(
        mesh=mesh, capacity=1 << 12, chunk_size=1 << 6,
    ).join()
    tiered = model.checker().spawn_tpu_tiered(
        capacity=512, max_frontier=1 << 6,
    ).join()
    for who, m in (("tpu", tpu.metrics()), ("sharded", sharded.metrics()),
                   ("tiered", tiered.metrics())):
        assert 0 < m["valid_density_ema"] <= 1.0, who
        assert m["histograms"]["valid_density"]["count"] > 0, who
        assert m["histograms"]["load_factor"]["count"] > 0, who
    sm = sharded.metrics()
    assert set(sm["shard_unique"]) == {"0", "1"}
    assert sm["unique_skew_max_over_mean"] >= 1.0
    json.dumps(sm)


def test_forced_grow_records_waves_per_grow_histogram():
    """An undersized table forces the in-place auto-grow; the vitals
    must count it and record the waves-per-grow distribution."""
    ck = TwoPhaseSys(rm_count=3).checker().spawn_tpu(
        capacity=1 << 7, max_frontier=1 << 6, device=_cpu(),
    ).join()
    m = ck.metrics()
    assert m["unique_state_count"] == 288
    assert m["grows"] >= 1  # actual geometry changes (log_grow)
    assert m["overflow_retries"] >= m["grows"]  # every recovery re-run
    wpg = m["histograms"]["waves_per_grow"]
    assert wpg["count"] == m["overflow_retries"]


def test_explorer_metrics_serves_vitals_json_and_prometheus():
    from stateright_tpu.explorer.server import serve_checker

    ck = TwoPhaseSys(rm_count=3).checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
    ).join()
    serve_checker(ck, ("127.0.0.1", 0), block=False)
    host, port = ck.explorer_address
    base = f"http://{host}:{port}"
    try:
        with urllib.request.urlopen(base + "/.metrics", timeout=10) as r:
            m = json.loads(r.read())
        assert m["histograms"]["wave_latency_sec"]["count"] > 0
        assert m["uniq_per_sec_ema"] > 0
        assert m["table_load_factor"] > 0

        req = urllib.request.Request(
            base + "/.metrics?format=prometheus"
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            ctype = r.headers.get("Content-Type", "")
            text = r.read().decode()
        assert ctype.startswith("text/plain")
        fams = parse_prometheus(text)
        lat = fams["stateright_wave_latency_sec"]
        assert lat["type"] == "histogram"
        names = {n for n, _, _ in lat["samples"]}
        assert {
            "stateright_wave_latency_sec_bucket",
            "stateright_wave_latency_sec_sum",
            "stateright_wave_latency_sec_count",
        } <= names
        assert fams["stateright_unique_state_count"]["type"] == "counter"
        assert (
            fams["stateright_unique_state_count"]["samples"][0][2] == 288
        )
        assert fams["stateright_table_load_factor"]["samples"][0][2] > 0

        # An Accept header preferring the text exposition (a scraper's
        # request) selects Prometheus without the query param.
        req = urllib.request.Request(
            base + "/.metrics", headers={"Accept": "text/plain"}
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.headers.get("Content-Type", "").startswith(
                "text/plain"
            )
    finally:
        ck.explorer_server.shutdown()
