"""Cross-engine anchor for the flagship bench workload `paxos check 3`.

bench.py's golden (1,194,428 unique / depth 28) was self-measured in round
2; this pins the same configuration across all three engines — host BFS,
single-chip wavefront, and the sharded mesh engine — so a simultaneous
regression in the host and device encodings cannot go unnoticed.  Full
scale exceeds suite runtime on a CPU box (the host alone needs ~10 min),
so the pin is depth-bounded here; the full-scale count is verified fatally
on real hardware by bench.py every round (bench.py:GOLDEN_UNIQUE), and the
depth prefix below is exact for every engine (target_max_depth semantics
are level-accurate on all three).
"""

import pytest

from stateright_tpu.actor import Network
from stateright_tpu.models.paxos import PaxosModelCfg

PINNED_D11_UNIQUE = 21_838  # paxos check 3, depth <= 11 (exact BFS prefix)


def paxos3():
    return PaxosModelCfg(
        client_count=3,
        server_count=3,
        network=Network.new_unordered_nonduplicating(),
    ).into_model()


@pytest.mark.slow
def test_paxos3_depth11_pinned_across_engines():
    host = paxos3().checker().target_max_depth(11).spawn_bfs().join()
    assert host.unique_state_count() == PINNED_D11_UNIQUE
    assert host.max_depth() == 11

    tpu = (
        paxos3()
        .checker()
        .target_max_depth(11)
        .spawn_tpu(capacity=1 << 20, max_frontier=1 << 10)
        .join()
    )
    assert tpu.unique_state_count() == PINNED_D11_UNIQUE
    assert tpu.max_depth() == 11
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())

    sharded = (
        paxos3()
        .checker()
        .target_max_depth(11)
        .spawn_tpu_sharded(capacity=1 << 20, chunk_size=1 << 9)
        .join()
    )
    assert sharded.unique_state_count() == PINNED_D11_UNIQUE
    assert sharded.max_depth() == 11
    assert sorted(sharded.discoveries()) == sorted(host.discoveries())
