"""Device-kernel gates for the compiled LWW-register CRDT.

This closes the last reference action family on device: SelectRandom
(src/actor/model.rs:320-333).  The model also exercises reachable
multiset counts > 1 (a register-less SetValue re-broadcasts an identical
envelope), encoded as repeated sorted slots like raft's fabric.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.models.lww_compiled import LwwCompiled  # noqa: E402
from stateright_tpu.models.lww_register import build_model  # noqa: E402
from stateright_tpu.ops.fingerprint import fingerprint  # noqa: E402


@pytest.mark.slow
def test_step_differential_to_depth_3():
    """Successors (full rows), validity, flags, and the eventually-
    consistent predicate vs the host model over the 706 states within 3
    actions of init — SetValue/SetTime SelectRandom lanes and merge-by-
    (timestamp, updater) deliveries all fire in this prefix."""
    model = build_model(2)
    cm = LwwCompiled(model)
    props = model.properties()
    seen = {}
    frontier = list(model.init_states())
    for s in frontier:
        seen[fingerprint(s)] = s
    depth = 0
    while frontier and depth < 3:
        depth += 1
        encs = np.stack([cm.encode(s) for s in frontier]).astype(np.uint32)
        nb, vb, fb = jax.vmap(cm.step)(jnp.asarray(encs))
        nb = np.asarray(nb)
        vb = np.asarray(vb)
        assert not np.asarray(fb).any()
        cb = np.asarray(jax.vmap(cm.property_conds)(jnp.asarray(encs)))
        nxt = []
        for bi, s in enumerate(frontier):
            assert fingerprint(cm.decode(encs[bi])) == fingerprint(s)
            want = [bool(p.condition(model, s)) for p in props]
            assert want == [bool(x) for x in cb[bi]], s
            acts = []
            model.actions(s, acts)
            host_succ = set()
            for a in acts:
                ns = model.next_state(s, a)
                if ns is None:
                    continue
                host_succ.add(tuple(cm.encode(ns).tolist()))
                fp = fingerprint(ns)
                if fp not in seen:
                    seen[fp] = ns
                    nxt.append(ns)
            dev_succ = {
                tuple(nb[bi, k].tolist())
                for k in range(cm.max_actions)
                if vb[bi, k]
            }
            assert dev_succ == host_succ, s
        frontier = nxt
    assert len(seen) == 706


@pytest.mark.slow
def test_spawn_tpu_lww_depth5_matches_host():
    """Depth-bounded engine parity (the reference checks this model only
    depth-bounded, examples/lww-register.rs:190-196)."""
    tpu = (
        build_model(2)
        .checker()
        .target_max_depth(5)
        .spawn_tpu(capacity=1 << 14, max_frontier=1 << 8)
        .join()
    )
    host = (
        build_model(2).checker().target_max_depth(5).spawn_bfs().join()
    )
    assert tpu.unique_state_count() == host.unique_state_count()
    assert tpu.state_count() == host.state_count()
    assert tpu.max_depth() == host.max_depth()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    tpu.assert_no_discovery("eventually consistent")
