"""Runtime subsystem: telemetry journal, geometry-backoff policy,
isolated-child runner, engine checkpoint/journal hooks, and the
crash-resilience acceptance test — a supervised CPU-backend check whose
child is killed mid-run resumes from the latest checkpoint and finishes
with an IDENTICAL discovery set and counts to an uninterrupted run.
"""

import json
import os
import sys

import pytest

from stateright_tpu.runtime.journal import Journal, last_event, read_journal
from stateright_tpu.runtime.supervisor import (
    CheckSpec,
    RunSupervisor,
    SupervisorConfig,
    journal_events,
    relax_geometry,
    run_isolated,
)

jax = pytest.importorskip("jax")

from stateright_tpu.models.twophase import TwoPhaseSys  # noqa: E402


# --- journal -----------------------------------------------------------------


def test_journal_append_read_roundtrip(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with Journal(path) as j:
        j.append("wave", unique=10, depth=2)
        j.append("checkpoint", path="ck.npz")
    events = read_journal(path)
    assert [e["event"] for e in events] == ["wave", "checkpoint"]
    assert events[0]["unique"] == 10
    assert all("t" in e for e in events)
    assert last_event(path)["event"] == "checkpoint"
    assert last_event(path, "wave")["unique"] == 10


def test_journal_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "j.jsonl")
    Journal(path).append("wave", unique=1)
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"t": 1, "event": "wa')  # writer killed mid-write
    events = read_journal(path)
    assert len(events) == 1 and events[0]["unique"] == 1


def test_journal_concurrent_appenders_interleave_lines(tmp_path):
    path = str(tmp_path / "j.jsonl")
    a, b = Journal(path), Journal(path)
    for i in range(5):
        a.append("wave", src="a", i=i)
        b.append("wave", src="b", i=i)
    events = read_journal(path)
    assert len(events) == 10
    assert {e["src"] for e in events} == {"a", "b"}


def test_journal_rotation_rolls_over_mid_append(tmp_path):
    """Size-capped journals roll into ``.1..N`` segments: the append
    that would cross the cap first shifts segments (atomic renames
    under the lock), then lands whole in a fresh live file — no record
    is ever split across segments, and readers merge oldest-first."""
    import os

    path = str(tmp_path / "j.jsonl")
    j = Journal(path, max_bytes=256, max_segments=3)
    for i in range(30):
        j.append("e", i=i)
    j.close()
    names = sorted(os.listdir(tmp_path))
    assert "j.jsonl" in names and "j.jsonl.1" in names
    assert "j.jsonl.4" not in names  # oldest fell off at the cap
    # Every retained segment holds whole lines; merged read is a
    # contiguous, ordered suffix of what was appended.
    events = read_journal(path)
    idx = [e["i"] for e in events]
    assert idx == list(range(idx[0], 30))
    assert os.path.getsize(path) <= 256
    # A fresh instance on the same path keeps appending after the
    # existing segments (the reopen-after-rollover path).
    j2 = Journal(path, max_bytes=256, max_segments=3)
    j2.append("e", i=30)
    j2.close()
    assert read_journal(path)[-1]["i"] == 30
    assert last_event(path, "e")["i"] == 30


def test_journal_unrotated_default_never_renames(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = Journal(path)  # default: no cap, exactly the old behavior
    for i in range(50):
        j.append("e", i=i)
    j.close()
    import os

    assert sorted(os.listdir(tmp_path)) == ["j.jsonl"]
    assert len(read_journal(path)) == 50


def test_journal_reporter_streams_report_protocol(tmp_path):
    """JournalReporter adapts the standard Reporter protocol onto a
    journal: the reference's text report data lands as machine-readable
    events in the run artifact."""
    from stateright_tpu import JournalReporter

    path = str(tmp_path / "report.jsonl")
    (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_bfs()
        .join_and_report(JournalReporter(path, delay=0.05))
    )
    events = read_journal(path)
    done = [e for e in events if e["event"] == "done"]
    assert len(done) == 1 and done[0]["unique"] == 288
    discoveries = [e for e in events if e["event"] == "discovery"]
    assert {d["name"] for d in discoveries} == {
        "abort agreement", "commit agreement",
    }
    assert all("fingerprint_path" in d for d in discoveries)


# --- geometry backoff --------------------------------------------------------


def test_relax_goes_straight_to_dedup_one_never_stepwise():
    """The observed crash evidence: the intermediate stop (dd=2 at a
    doubled frontier) was itself a NEW worker-crash geometry; the relax
    must jump to the always-safe 1 in ONE step."""
    for dd in (2, 4, 8, 16):
        kwargs = {"dedup_factor": dd, "max_frontier": 1 << 14}
        relaxed = relax_geometry(kwargs)
        assert relaxed["dedup_factor"] == 1, f"stepwise relax from dd={dd}"
        assert relaxed["max_frontier"] == 1 << 14  # untouched on this step
        assert kwargs["dedup_factor"] == dd  # input not mutated


def test_relax_uses_engine_defaults_when_unset():
    # An empty kwargs dict means the engine default (dd=8) is in effect;
    # the first relax must still pin dd=1.
    assert relax_geometry({})["dedup_factor"] == 1
    assert relax_geometry({}, engine="sharded")["dedup_factor"] == 1


def test_relax_never_invents_a_frontier_from_defaults():
    """After dd=1, a kwargs dict WITHOUT an explicit frontier must be
    exhausted, not 'relaxed' to half the engine default: writing a
    default-derived frontier would override a smaller model-specific
    setting the caller never exposed (CLI tpu_kwargs), making the
    restarted geometry LARGER — the opposite of a backoff."""
    assert relax_geometry({"dedup_factor": 1}) is None
    assert relax_geometry({"dedup_factor": 1}, engine="sharded") is None
    step = relax_geometry({})  # dd pinned to 1...
    assert relax_geometry(step) is None  # ...then nothing else to relax


def test_relax_halves_frontier_then_waves_then_gives_up():
    kwargs = {"dedup_factor": 1, "max_frontier": 8192}
    step = relax_geometry(kwargs)
    assert step["max_frontier"] == 4096
    step = relax_geometry(step)
    assert step["max_frontier"] == 2048
    # At the frontier floor with no waves_per_call knob: exhausted.
    assert relax_geometry(step) is None
    # With an explicit waves_per_call, that halves next (per-call device
    # time is the crash driver), down to its floor.
    step["waves_per_call"] = 32
    step = relax_geometry(step)
    assert step["waves_per_call"] == 16
    step = relax_geometry(step)
    assert step["waves_per_call"] == 8
    assert relax_geometry(step) is None


def test_relax_sharded_uses_chunk_size():
    step = relax_geometry({"dedup_factor": 1, "chunk_size": 8192},
                          engine="sharded")
    assert step["chunk_size"] == 4096


# --- isolated-child runner ---------------------------------------------------


def test_run_isolated_success_first_try():
    res = run_isolated([sys.executable, "-c", "print('ok')"], attempts=2)
    assert res.returncode == 0 and res.attempts_used == 1
    assert "ok" in res.stdout and not res.timed_out


def test_run_isolated_retries_crash_in_fresh_process(tmp_path):
    # The child crashes on the first run (no marker file) and succeeds on
    # the second — the fresh-process-retry contract.
    marker = str(tmp_path / "marker")
    prog = (
        "import os, sys; p = sys.argv[1]\n"
        "sys.exit(0) if os.path.exists(p) else (open(p, 'w').close(),"
        " sys.exit(1))"
    )
    res = run_isolated(
        [sys.executable, "-c", prog, marker], attempts=2,
    )
    assert res.returncode == 0 and res.attempts_used == 2


def test_run_isolated_timeout_is_final():
    res = run_isolated(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        timeout=1.0, attempts=3,
    )
    assert res.timed_out and res.attempts_used == 1


# --- engine checkpoint/journal hooks (in-process, CPU backend) ---------------


def test_engine_journal_and_checkpoint_artifacts(tmp_path):
    """A checkpointing run leaves a parseable journal (wave telemetry with
    occupancy + device-call wall time, checkpoint events, engine_done) and
    a checkpoint that is itself a valid resumable snapshot."""
    journal_path = str(tmp_path / "journal.jsonl")
    ckpt = str(tmp_path / "checkpoint.npz")
    model = TwoPhaseSys(rm_count=3)
    ck = (
        model.checker()
        .spawn_tpu(
            capacity=1 << 14,
            max_frontier=1 << 7,
            waves_per_call=1,
            journal=journal_path,
            checkpoint_path=ckpt,
            checkpoint_every_waves=1,
            device=jax.devices("cpu")[0],
        )
        .join()
    )
    assert ck.unique_state_count() == 288
    events = read_journal(journal_path)
    waves = [e for e in events if e["event"] == "wave"]
    assert waves, "no wave telemetry in the journal"
    for w in waves:
        assert {"waves", "remaining", "tail", "unique", "states", "depth",
                "flags", "call_sec", "occupancy"} <= set(w)
    assert any(e["event"] == "checkpoint" for e in events)
    done = last_event(journal_path, "engine_done")
    assert done["unique"] == 288

    resumed = (
        model.checker()
        .spawn_tpu(
            capacity=1 << 14,
            max_frontier=1 << 7,
            resume_from=ckpt,
            journal=journal_path,
            device=jax.devices("cpu")[0],
        )
        .join()
    )
    assert resumed.unique_state_count() == 288
    assert last_event(journal_path, "resume")["path"] == ckpt


def test_sharded_checkpoint_resume_roundtrip(tmp_path):
    """The sharded engine exposes the same snapshot hooks: a bounded run
    snapshots, resumes to identical totals, and rejects a different
    model's snapshot — mirroring the single-chip round-trip test."""
    model = TwoPhaseSys(rm_count=3)
    journal_path = str(tmp_path / "sharded_journal.jsonl")
    full = (
        model.checker()
        .spawn_tpu_sharded(
            capacity=1 << 14,
            chunk_size=1 << 7,
            journal=journal_path,
            checkpoint_path=str(tmp_path / "sharded_ck.npz"),
            checkpoint_every_waves=4,
        )
        .join()
    )
    assert full.unique_state_count() == 288
    events = read_journal(journal_path)
    kinds = [e["event"] for e in events]
    assert "wave" in kinds and "checkpoint" in kinds
    assert last_event(journal_path, "engine_done")["unique"] == 288
    # The final sharded checkpoint is itself resumable: a resume of a
    # COMPLETED run finishes immediately with the same totals.
    redone = (
        model.checker()
        .spawn_tpu_sharded(
            capacity=1 << 14, chunk_size=1 << 7,
            resume_from=str(tmp_path / "sharded_ck.npz"),
        )
        .join()
    )
    assert redone.unique_state_count() == 288
    bounded = (
        model.checker()
        .target_state_count(300)
        .spawn_tpu_sharded(capacity=1 << 14, chunk_size=1 << 7)
        .join()
    )
    assert bounded.unique_state_count() < 288
    snap = str(tmp_path / "sharded.npz")
    bounded.save_snapshot(snap)

    resumed = (
        model.checker()
        .spawn_tpu_sharded(
            capacity=1 << 14, chunk_size=1 << 7, resume_from=snap
        )
        .join()
    )
    assert resumed.unique_state_count() == 288
    assert resumed.state_count() == full.state_count()
    assert resumed.max_depth() == full.max_depth()
    assert sorted(resumed.discoveries()) == sorted(full.discoveries())

    with pytest.raises(ValueError, match="snapshot does not match"):
        TwoPhaseSys(rm_count=4).checker().spawn_tpu_sharded(
            capacity=1 << 14, chunk_size=1 << 7, resume_from=snap
        ).join()


# --- the acceptance test: kill mid-run, resume, identical results ------------


def test_supervised_kill_mid_run_resumes_identical(tmp_path, monkeypatch):
    """A supervised CPU-backend check whose child dies mid-run (fault
    injection: the child ``os._exit``\\ s the moment its first checkpoint
    lands) auto-resumes from that checkpoint and reports the same
    ``unique_state_count``, ``state_count``, depth, and discovery set as
    an uninterrupted run; the journal records the checkpoint, the crash,
    and the resume."""
    model = TwoPhaseSys(rm_count=4)
    straight = (
        model.checker()
        .spawn_tpu(capacity=1 << 14, max_frontier=1 << 6, dedup_factor=1,
                   waves_per_call=2)
        .join()
    )

    monkeypatch.setenv(
        "STATERIGHT_RUNTIME_FAULT_EXIT_AFTER_CHECKPOINT", "137"
    )
    run_dir = str(tmp_path / "run")
    spec = CheckSpec(
        model_factory=TwoPhaseSys,
        factory_kwargs={"rm_count": 4},
        engine_kwargs={
            "capacity": 1 << 14,
            "max_frontier": 1 << 6,
            "dedup_factor": 1,
            "waves_per_call": 2,
        },
    )
    sup = RunSupervisor(
        SupervisorConfig(
            run_dir=run_dir,
            checkpoint_every_waves=2,
            checkpoint_every_sec=None,
            call_deadline_sec=240.0,
            poll_interval_sec=0.05,
            max_restarts=2,
        ),
        spec=spec,
    )
    result = sup.run()

    assert result["completed"]
    assert result["unique_state_count"] == straight.unique_state_count()
    assert result["state_count"] == straight.state_count()
    assert result["max_depth"] == straight.max_depth()
    assert result["discoveries"] == sorted(straight.discoveries())

    events = journal_events(run_dir)
    kinds = [e["event"] for e in events]
    assert "checkpoint" in kinds, "no checkpoint event in the journal"
    assert "crash" in kinds, "the child's death was not recorded"
    assert "resume" in kinds, "the restarted child did not resume"
    assert kinds.count("run_start") == 2  # original child + restarted one
    # The resumed child started from durable progress, not from scratch.
    resume = next(e for e in events if e["event"] == "resume")
    assert resume["unique"] > 0
    # The result file on disk matches what the supervisor returned.
    with open(os.path.join(run_dir, "result.json"), encoding="utf-8") as fh:
        assert json.load(fh) == result


def test_sharded_supervised_kill_mid_run_resumes_identical(
    tmp_path, monkeypatch
):
    """The sharded mirror of the kill-mid-run acceptance test, enabled
    by the shared wave-loop core (parallel/wave_loop.py): a supervised
    SHARDED child on the virtual mesh dies the moment its first
    checkpoint lands, auto-resumes from it, and reports the same
    totals and discovery set as an uninterrupted run."""
    model = TwoPhaseSys(rm_count=4)
    straight = (
        model.checker()
        .spawn_tpu_sharded(
            capacity=1 << 14, chunk_size=1 << 6, waves_per_call=2,
        )
        .join()
    )

    monkeypatch.setenv(
        "STATERIGHT_RUNTIME_FAULT_EXIT_AFTER_CHECKPOINT", "137"
    )
    run_dir = str(tmp_path / "run")
    spec = CheckSpec(
        model_factory=TwoPhaseSys,
        factory_kwargs={"rm_count": 4},
        engine="sharded",
        engine_kwargs={
            "capacity": 1 << 14,
            "chunk_size": 1 << 6,
            "waves_per_call": 2,
        },
    )
    sup = RunSupervisor(
        SupervisorConfig(
            run_dir=run_dir,
            checkpoint_every_waves=2,
            checkpoint_every_sec=None,
            call_deadline_sec=240.0,
            poll_interval_sec=0.05,
            max_restarts=2,
        ),
        spec=spec,
    )
    result = sup.run()

    assert result["completed"]
    assert result["unique_state_count"] == straight.unique_state_count()
    assert result["state_count"] == straight.state_count()
    assert result["max_depth"] == straight.max_depth()
    assert result["discoveries"] == sorted(straight.discoveries())

    events = journal_events(run_dir)
    kinds = [e["event"] for e in events]
    assert "checkpoint" in kinds
    assert "crash" in kinds
    assert "resume" in kinds
    assert kinds.count("run_start") == 2
    resume = next(e for e in events if e["event"] == "resume")
    assert resume["unique"] > 0


def test_sharded_resume_wrong_mesh_size_is_loud(tmp_path):
    """A sharded snapshot is bound to the mesh width that wrote it
    (gids encode the owner shard); resuming on a different width must
    fail with an error that NAMES both sizes, not a generic key
    mismatch."""
    import jax
    import numpy as np

    model = TwoPhaseSys(rm_count=3)
    bounded = (
        model.checker()
        .target_state_count(300)
        .spawn_tpu_sharded(
            mesh=jax.sharding.Mesh(
                np.array(jax.devices("cpu")[:4]), ("shards",)
            ),
            capacity=1 << 13, chunk_size=1 << 6,
        )
        .join()
    )
    snap = str(tmp_path / "mesh4.npz")
    bounded.save_snapshot(snap)
    with pytest.raises(
        ValueError, match=r"4-shard mesh and cannot resume on 2 shards"
    ):
        model.checker().spawn_tpu_sharded(
            mesh=jax.sharding.Mesh(
                np.array(jax.devices("cpu")[:2]), ("shards",)
            ),
            capacity=1 << 13, chunk_size=1 << 6, resume_from=snap,
        ).join()


def test_supervisor_deterministic_child_error_is_fatal(tmp_path):
    """A child that fails with a clean non-transient Python error (here:
    a model factory that raises) must NOT be retried into a crash loop;
    the supervisor raises with the child's error text."""
    from stateright_tpu.runtime.supervisor import SupervisorError

    spec = CheckSpec(model_factory=_raising_factory)
    sup = RunSupervisor(
        SupervisorConfig(
            run_dir=str(tmp_path / "run"),
            call_deadline_sec=120.0,
            poll_interval_sec=0.05,
            max_restarts=3,
        ),
        spec=spec,
    )
    with pytest.raises(SupervisorError, match="deliberately broken"):
        sup.run()
    events = journal_events(str(tmp_path / "run"))
    kinds = [e["event"] for e in events]
    # Exactly one attempt: deterministic errors never burn the restart
    # budget.
    assert kinds.count("run_start") == 1
    assert "give_up" in kinds


def _raising_factory():
    raise RuntimeError("deliberately broken model factory")


# --- knob cache (runtime/knob_cache.py) --------------------------------------


def test_knob_cache_roundtrip(tmp_path):
    from stateright_tpu.runtime.knob_cache import (
        drop_knobs, load_knobs, store_knobs,
    )

    d = str(tmp_path / "knobs")
    assert load_knobs(d, "k") is None
    store_knobs(d, "k", {"capacity": 1 << 20, "dedup_factor": 8},
                unique=314, discovery_sec=1.5)
    assert load_knobs(d, "k") == {"capacity": 1 << 20, "dedup_factor": 8}
    # Second key merges; first survives.
    store_knobs(d, "k2", {"max_frontier": 2048})
    assert load_knobs(d, "k") is not None
    assert load_knobs(d, "k2") == {"max_frontier": 2048}
    drop_knobs(d, "k")
    assert load_knobs(d, "k") is None
    assert load_knobs(d, "k2") is not None
    # Stored metadata is on disk for humans but not returned.
    data = json.load(open(os.path.join(d, "knobs.json")))
    assert data["k2"]["knobs"]["max_frontier"] == 2048


def test_knob_cache_degrades_on_torn_file(tmp_path):
    from stateright_tpu.runtime.knob_cache import load_knobs, store_knobs

    d = str(tmp_path / "knobs")
    store_knobs(d, "k", {"capacity": 4})
    with open(os.path.join(d, "knobs.json"), "w") as fh:
        fh.write('{"k": {"knobs": {"capa')  # torn write
    assert load_knobs(d, "k") is None  # degrade to rediscovery, no crash
    store_knobs(d, "k", {"capacity": 8})  # and the file heals on store
    assert load_knobs(d, "k") == {"capacity": 8}
