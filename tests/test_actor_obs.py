"""Actor-runtime observability (ISSUE 15): per-link metrics, the causal
trace envelope, chaos fault attribution, and the live ``/.metrics``
surface.

The acceptance pins: the trace envelope rides OUTSIDE the wire codec
(model encoding untouched, legacy datagrams accepted, zero wire overhead
when disabled); a handler's sends inherit the received trace id with
``hop + 1``; ``FaultyTransport``'s per-link fault counters, the journaled
``chaos_summary``, and the report's fault-attribution table all agree to
the count for a fixed seed; and ``runtime.metrics()`` serves JSON + a
valid Prometheus exposition over HTTP.
"""

import json
import urllib.request
from dataclasses import dataclass

import pytest

from stateright_tpu.actor.base import Actor, Out
from stateright_tpu.actor.ids import Id
from stateright_tpu.actor.obs import (
    ENVELOPE_OVERHEAD,
    MAGIC,
    ObservedTransport,
    serve_actor_metrics,
    unwrap_datagram,
    wrap_datagram,
)
from stateright_tpu.actor.spawn import spawn
from stateright_tpu.actor.transport import LoopbackTransport
from stateright_tpu.actor.wire import (
    register_wire_types,
    wire_deserialize,
    wire_serialize,
)
from stateright_tpu.obs.prometheus import parse_prometheus
from stateright_tpu.runtime.journal import read_journal


@dataclass(frozen=True)
class ObsPing:
    n: int


@dataclass(frozen=True)
class ObsPong:
    n: int


register_wire_types(ObsPing, ObsPong)


class _Echo(Actor):
    def on_start(self, id, storage, o: Out):
        return ()

    def on_msg(self, id, state, src, msg, o: Out):
        if isinstance(msg, ObsPing):
            o.send(src, ObsPong(msg.n))
        return None


class _Forwarder(Actor):
    """Relays each ping one hop down a chain, so a request's causal
    spans climb ``hop`` at every actor it crosses."""

    def __init__(self, nxt):
        self.next = nxt

    def on_start(self, id, storage, o: Out):
        return ()

    def on_msg(self, id, state, src, msg, o: Out):
        o.send(self.next, msg)
        return None


def _spawn(actors, transport, tmp_path):
    return spawn(
        wire_serialize, wire_deserialize, wire_serialize, wire_deserialize,
        actors, storage_dir=str(tmp_path), transport=transport,
        metrics=getattr(transport, "registry", None),
    )


# --- the envelope codec ------------------------------------------------------


def test_envelope_is_absent_when_tracing_disabled(tmp_path):
    """trace=False: the bytes on the wire are EXACTLY the wire codec's
    output — zero overhead, nothing for a legacy peer to choke on."""
    seen = []

    class _Tap(LoopbackTransport):
        def _deliver(self, src, dst, data):
            seen.append(bytes(data))
            super()._deliver(src, dst, data)

    obs = ObservedTransport(_Tap(), trace=False)
    runtime = _spawn([(Id(1), _Echo())], obs, tmp_path)
    probe = obs.bind(Id(9))
    try:
        probe.send(Id(1), wire_serialize(ObsPing(1)))
        reply = probe.recv(5.0)
        assert reply is not None and wire_deserialize(reply[0]) == ObsPong(1)
        assert seen and all(d == wire_serialize(ObsPing(1))
                            or d == wire_serialize(ObsPong(1))
                            for d in seen)
        assert all(not d.startswith(MAGIC) for d in seen)
    finally:
        probe.close()
        runtime.stop()


def test_trace_propagates_across_actors_with_incrementing_hops(tmp_path):
    """A request crossing forwarder → forwarder → echo keeps ONE trace
    id while the hop counter climbs — the causal chain the journal's
    actor_span events expose."""
    journal = str(tmp_path / "journal.jsonl")
    obs = ObservedTransport(LoopbackTransport(), trace=True, journal=journal)
    runtime = _spawn(
        [
            (Id(1), _Forwarder(Id(2))),
            (Id(2), _Forwarder(Id(3))),
            (Id(3), _Echo()),
        ],
        obs,
        tmp_path,
    )
    probe = obs.bind(Id(9))
    try:
        probe.send(Id(1), wire_serialize(ObsPing(7)))
        # The echo replies to the LAST forwarder (Id(2)) — the pong is
        # then relayed nowhere; just wait for the chain to complete.
        deadline_spans = 3  # 9->1, 1->2, 2->3 at hops 0, 1, 2
        import time

        t0 = time.monotonic()
        while obs.span_count < deadline_spans and time.monotonic() - t0 < 10:
            time.sleep(0.02)
    finally:
        probe.close()
        runtime.stop()
    spans = [e for e in read_journal(journal) if e["event"] == "actor_span"]
    chain = [s for s in spans if s["dst"] in (1, 2, 3) and s["src"] != 3]
    assert len(chain) >= 3, spans
    trace_ids = {s["trace"] for s in chain}
    assert len(trace_ids) == 1, "one request must carry one trace id"
    hops = sorted(s["hop"] for s in chain)
    assert hops[:3] == [0, 1, 2], chain
    m = runtime.metrics()
    assert m["max_depth"] >= 2
    assert m["actor_spans_total"] == len(spans)


def test_interrupt_sends_start_a_fresh_trace(tmp_path):
    """A timer-driven send must NOT continue the trace of whatever
    message the thread received last (actor/obs.clear_trace_context)."""

    class _TimerSender(Actor):
        def on_start(self, id, storage, o: Out):
            return ()

        def on_msg(self, id, state, src, msg, o: Out):
            o.set_timer("later", (0.01, 0.01))
            return None

        def on_timeout(self, id, state, timer, o: Out):
            o.send(Id(9), ObsPong(99))
            return None

    journal = str(tmp_path / "journal.jsonl")
    obs = ObservedTransport(LoopbackTransport(), trace=True, journal=journal)
    runtime = _spawn([(Id(1), _TimerSender())], obs, tmp_path)
    probe = obs.bind(Id(9))
    try:
        probe.send(Id(1), wire_serialize(ObsPing(1)))
        reply = probe.recv(5.0)
        assert reply is not None and wire_deserialize(reply[0]) == ObsPong(99)
    finally:
        probe.close()
        runtime.stop()
    spans = [e for e in read_journal(journal) if e["event"] == "actor_span"]
    inbound = [s for s in spans if s["dst"] == 1]
    outbound = [s for s in spans if s["dst"] == 9]
    assert inbound and outbound
    assert outbound[0]["trace"] != inbound[0]["trace"]
    assert outbound[0]["hop"] == 0


def test_link_metrics_count_datagrams_and_wire_bytes(tmp_path):
    obs = ObservedTransport(LoopbackTransport(), trace=True)
    runtime = _spawn([(Id(1), _Echo())], obs, tmp_path)
    probe = obs.bind(Id(9))
    try:
        for n in range(3):
            probe.send(Id(1), wire_serialize(ObsPing(n)))
        got = 0
        while got < 3:
            r = probe.recv(5.0)
            assert r is not None
            got += 1
    finally:
        probe.close()
        runtime.stop()
    m = runtime.metrics()
    links = m["link_datagrams_sent"]
    assert links["9->1"] == 3 and links["1->9"] == 3
    # Sent and received byte counts both measure the WIRE size (payload
    # + envelope) of the same datagrams, so the two sides agree.
    assert m["link_bytes_sent"]["9->1"] == m["link_bytes_received"]["9->1"]
    assert (
        m["link_bytes_sent"]["9->1"]
        == 3 * (len(wire_serialize(ObsPing(0))) + ENVELOPE_OVERHEAD)
    )
    assert m["datagrams_sent_total"] == 6
    assert m["histograms"]["actor_deliver_latency_sec"]["count"] >= 6


def test_runtime_metrics_handler_and_timer_counters(tmp_path):
    class _Ticker(Actor):
        def on_start(self, id, storage, o: Out):
            o.set_timer("tick", (0.01, 0.01))
            return 0

        def on_timeout(self, id, state, timer, o: Out):
            if state < 2:
                o.set_timer("tick", (0.01, 0.01))
            return state + 1

    transport = LoopbackTransport()
    runtime = _spawn([(Id(1), _Ticker())], transport, tmp_path)
    import time

    t0 = time.monotonic()
    while (
        int(runtime.registry.get("timer_fires_total", 0) or 0) < 3
        and time.monotonic() - t0 < 10
    ):
        time.sleep(0.02)
    runtime.stop()
    m = runtime.metrics()
    assert m["timer_sets_total"] >= 3
    assert m["timer_fires_total"] >= 3
    assert m["histograms"]["actor_handler_sec"]["count"] >= 3
    assert m["done"] is True


# --- the live /.metrics surface ----------------------------------------------


def test_serve_actor_metrics_json_and_prometheus(tmp_path):
    obs = ObservedTransport(LoopbackTransport(), trace=True)
    runtime = _spawn([(Id(1), _Echo())], obs, tmp_path)
    probe = obs.bind(Id(9))
    server = serve_actor_metrics(runtime, ("127.0.0.1", 0))
    host, port = server.server_address[:2]
    base = f"http://{host}:{port}"
    try:
        probe.send(Id(1), wire_serialize(ObsPing(1)))
        assert probe.recv(5.0) is not None
        with urllib.request.urlopen(base + "/.metrics", timeout=10) as r:
            m = json.loads(r.read())
        assert m["engine"] == "ActorRuntime"
        assert m["link_datagrams_sent"]["9->1"] == 1
        with urllib.request.urlopen(
            base + "/.metrics?format=prometheus", timeout=10
        ) as r:
            assert r.headers.get("Content-Type", "").startswith("text/plain")
            fams = parse_prometheus(r.read().decode())
        # The per-link counters render as a labeled gauge family.
        sent = fams["stateright_link_datagrams_sent"]
        assert any(
            labels.get("key") == "9->1" and v == 1
            for _n, labels, v in sent["samples"]
        )
        lat = fams["stateright_actor_deliver_latency_sec"]
        assert lat["type"] == "histogram"
        with urllib.request.urlopen(base + "/nope", timeout=10) as r:
            pass
    except urllib.error.HTTPError as e:
        assert e.code == 404
    finally:
        server.shutdown()
        probe.close()
        runtime.stop()


# --- chaos fault attribution -------------------------------------------------


def _chaos_run(tmp_path, name, **kwargs):
    from stateright_tpu.actor.register import RegisterServer
    from stateright_tpu.models.abd import NULL_VALUE, AbdActor
    from stateright_tpu.models.abd import (
        AckQuery, AckRecord, Internal, Query, Record,
    )
    from stateright_tpu.runtime.chaos import (
        ChaosSpec, run_chaos_register_system,
    )
    from stateright_tpu.semantics import LinearizabilityTester, Register

    journal = str(tmp_path / name)
    defaults = dict(
        server_count=3,
        client_count=1,
        put_count=1,
        spec=ChaosSpec.from_json('{"drop": 0.15, "duplicate": 0.15}'),
        seed=11,
        tester_factory=lambda: LinearizabilityTester(Register(NULL_VALUE)),
        wire_types=(Internal, Query, AckQuery, Record, AckRecord),
        journal=journal,
        deadline_sec=20.0,
    )
    defaults.update(kwargs)
    result = run_chaos_register_system(
        lambda peers: RegisterServer(AbdActor(peers)), **defaults
    )
    return result, journal


def test_chaos_summary_and_report_attribution_equal_journaled_injections(
    tmp_path,
):
    """The acceptance pin: for a fixed seed, the transport's per-link
    fault counters (result + chaos_summary event) and the report's
    attribution table all equal the journaled injection events."""
    from stateright_tpu.obs.report import analyze_journal

    result, journal = _chaos_run(
        tmp_path, "j.jsonl", trace=True, metrics_port=0
    )
    events = read_journal(journal)
    injections = [
        e for e in events
        if e["event"].startswith("chaos_")
        and e["event"] not in ("chaos_start", "chaos_summary")
    ]
    assert injections, "the seeded spec should have injected faults"

    # Per-link recount from the journal.
    by_link: dict = {}
    for e in injections:
        row = by_link.setdefault(f"{e['src']}->{e['dst']}", {})
        row[e["event"]] = row.get(e["event"], 0) + 1
    assert result["fault_links"] == by_link
    summary = [e for e in events if e["event"] == "chaos_summary"][-1]
    assert summary["links"] == by_link
    assert summary["total"] == len(injections)

    report = analyze_journal(journal)
    assert report["kind"] == "actor"
    assert report["actor"]["faults_by_link"] == by_link
    assert report["actor"]["fault_total"] == len(injections)

    # The live scrape agrees too (taken at quiescence, BEFORE teardown —
    # a retransmit timer may inject a few more faults between the scrape
    # and the final counters, so the scrape is a prefix: every scraped
    # per-link count is <= the final one, and something is nonzero),
    # and the exposition validated as Prometheus.
    assert result["prometheus_valid"] is True, result.get("scrape_error")
    scraped = result["metrics"]
    final_links = {
        link: sum(kinds.values()) for link, kinds in by_link.items()
    }
    assert scraped["link_faults"], "a per-link fault counter must appear"
    for link, count in scraped["link_faults"].items():
        assert 0 < count <= final_links[link], (link, count, final_links)
    json.dumps(result)  # the CLI prints the whole result verbatim


def test_chaos_run_records_orl_and_span_telemetry(tmp_path):
    """Under drops the ORL retransmits: the counters must land in the
    scraped metrics, and tracing must journal actor_span events."""
    result, journal = _chaos_run(
        tmp_path, "j.jsonl", trace=True, metrics_port=0
    )
    m = result["metrics"]
    assert m["orl_retransmits_total"] > 0
    assert m["orl_acks_total"] > 0
    assert m["actor_spans_total"] > 0
    assert m["trace"] is True
    events = read_journal(journal)
    spans = [e for e in events if e["event"] == "actor_span"]
    # The scrape happens at quiescence but BEFORE teardown — a few more
    # datagrams may land between the two, so journal >= scrape.
    assert len(spans) >= m["actor_spans_total"] > 0
    assert all("trace" in s and "hop" in s for s in spans)
    stats = [e for e in events if e["event"] == "actor_stats"]
    assert stats, "the harness must journal periodic actor_stats"
    assert stats[-1]["datagrams"] > 0


def test_watch_renders_the_chaos_journal(tmp_path):
    from stateright_tpu.obs.watch import render_line, summarize_events

    _result, journal = _chaos_run(tmp_path, "j.jsonl", trace=True)
    line = render_line(summarize_events(read_journal(journal)))
    assert "msgs/s=" in line
    assert "retransmits=" in line
    assert "faults=" in line
    assert "done" in line


def test_rejected_audit_report_correlates_fault_window(tmp_path):
    """The skip-ack replica's rejected history: the report must carry
    the fault-attribution section windowed on the audited ops."""
    from stateright_tpu.actor.register import RegisterServer
    from stateright_tpu.models.abd import (
        NULL_VALUE, AbdActor, AckQuery, AckRecord, Internal, Query, Record,
    )
    from stateright_tpu.obs.report import analyze_journal, render_markdown
    from stateright_tpu.runtime.chaos import (
        ChaosSpec, run_chaos_register_system,
    )
    from stateright_tpu.semantics import LinearizabilityTester, Register

    journal = str(tmp_path / "j.jsonl")
    result = run_chaos_register_system(
        lambda peers: RegisterServer(AbdActor(peers, fault="skip_ack")),
        server_count=3,
        client_count=1,
        put_count=1,
        spec=ChaosSpec.from_json('{"duplicate": 0.3}'),
        seed=5,
        tester_factory=lambda: LinearizabilityTester(Register(NULL_VALUE)),
        wire_types=(Internal, Query, AckQuery, Record, AckRecord),
        journal=journal,
        deadline_sec=15.0,
        trace=True,
    )
    assert result["completed"], result
    assert not result["consistent"], result
    report = analyze_journal(journal)
    actor = report["actor"]
    assert actor["audit"]["consistent"] is False
    attribution = actor["fault_attribution"]
    assert attribution["window"]["ops"] >= 2
    # Every windowed fault is a journaled injection (subset by count).
    assert attribution["fault_total"] <= actor["fault_total"]
    md = render_markdown(report)
    assert "REJECTED" in md and "Fault attribution" in md


def test_chaos_metrics_runtime_schema_has_guaranteed_keys(tmp_path):
    """The scraped snapshot carries the guaranteed cross-engine keys
    (the full typed pin lives in tests/test_metrics_schema.py)."""
    result, _journal = _chaos_run(tmp_path, "j.jsonl", metrics_port=0)
    m = result["metrics"]
    for key in (
        "engine", "done", "state_count", "unique_state_count", "max_depth",
        "table_load_factor", "program_cache_hits", "program_cache_misses",
        "compile_sec_total", "recompile_storms",
    ):
        assert key in m, key
    assert m["engine"] == "ActorRuntime"
    assert m["unique_state_count"] == 4  # 3 servers + 1 client


def test_chaos_fault_schedule_unchanged_by_tracing(tmp_path):
    """Tracing envelopes every datagram, but the fault fate of datagram
    n on a link is a pure function of (seed, link, n) — so the injected
    schedule prefixes must agree between a traced and an untraced run of
    the same seed."""

    def link_schedule(name, trace):
        _result, journal = _chaos_run(tmp_path, name, trace=trace)
        by_link: dict = {}
        for e in read_journal(journal):
            if e["event"].startswith("chaos_") and "src" in e:
                by_link.setdefault((e["src"], e["dst"]), []).append(
                    (e["event"], e["n"])
                )
        return by_link

    traced = link_schedule("traced.jsonl", True)
    untraced = link_schedule("untraced.jsonl", False)
    assert traced, "the seeded spec should have injected faults"
    for link in set(traced) | set(untraced):
        a, b = traced.get(link, []), untraced.get(link, [])
        n = min(len(a), len(b))
        assert a[:n] == b[:n], f"schedules diverge on link {link}"


def test_malformed_envelope_is_dropped_not_fatal(tmp_path):
    """A datagram wearing the envelope magic with a torn header must be
    counted and dropped — the replica keeps answering."""
    obs = ObservedTransport(LoopbackTransport(), trace=True)
    runtime = _spawn([(Id(1), _Echo())], obs, tmp_path)
    # Bind the probe on the RAW inner fabric so its garbage reaches the
    # observed endpoint unwrapped-by-us.
    raw = obs.inner.bind(Id(9))
    try:
        raw.send(Id(1), MAGIC + b"torn")
        raw.send(Id(1), wrap_datagram(wire_serialize(ObsPing(1)), 7, 0, 0.0))
        reply = raw.recv(5.0)
        assert reply is not None
        payload, ctx = unwrap_datagram(reply[0])
        assert wire_deserialize(payload) == ObsPong(1)
        assert ctx is not None and ctx.hop == 1
    finally:
        raw.close()
        runtime.stop()
    assert runtime.registry.get("trace_envelope_malformed_total") == 1
    assert runtime.errors == []


def test_observed_transport_requires_no_jax():
    """The actor observability layer must import/run without a device
    stack — it ships in production actor deployments."""
    import sys

    assert "stateright_tpu.actor.obs" in sys.modules
    probe = ObservedTransport(LoopbackTransport())
    a = probe.bind(Id(1))
    b = probe.bind(Id(2))
    a.send(Id(2), b"x")
    assert b.recv(1.0) == (b"x", Id(1))
    assert probe.link_metrics()["link_datagrams_sent"] == {"1->2": 1}
    with pytest.raises(ValueError):
        unwrap_datagram(MAGIC)
