"""TPU wavefront checker: device fingerprint, device hash set, compiled-model
step parity, and golden-count/discovery-set equivalence with the host oracle.

The decisive test per SURVEY §4: CPU and TPU checkers must produce identical
discovery sets and unique-state counts on the BASELINE configs.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.models.twophase import TwoPhaseSys  # noqa: E402
from stateright_tpu.ops.device_fp import device_fp64  # noqa: E402
from stateright_tpu.ops.fingerprint import fp64_words  # noqa: E402
from stateright_tpu.parallel.hashset import (  # noqa: E402
    insert_batch,
    make_hashset,
)


def test_device_fp_matches_host():
    rng = np.random.default_rng(7)
    for width in (1, 2, 3, 5):
        words = rng.integers(0, 2**32, size=(32, width), dtype=np.uint32)
        hi, lo = device_fp64(jnp.asarray(words))
        for i in range(32):
            host = fp64_words(words[i].tolist())
            assert ((int(hi[i]) << 32) | int(lo[i])) == host


def test_device_fp_nonzero():
    # The nonzero rule exists so (0,0) can mark empty hash slots.
    words = jnp.zeros((4, 2), jnp.uint32)
    hi, lo = device_fp64(words)
    assert all((int(h) | int(l)) != 0 for h, l in zip(hi, lo))


def test_hashset_insert_matches_python_set():
    rng = np.random.default_rng(3)
    table = make_hashset(1 << 11)
    seen = set()
    for _ in range(6):
        # Narrow key range forces duplicates within and across batches.
        keys = rng.integers(1, 2**13, size=192, dtype=np.uint64)
        hi = jnp.asarray((keys >> 32).astype(np.uint32))
        lo = jnp.asarray((keys & 0xFFFFFFFF).astype(np.uint32))
        active = jnp.asarray(rng.random(192) < 0.9)
        table, slot, is_new, probe_ok, dd_overflow = insert_batch(
            table, hi, lo, active
        )
        assert bool(probe_ok) and not bool(dd_overflow)
        active_np = np.asarray(active)
        inserted = {int(k) for k, a in zip(keys, active_np) if a}
        assert int(jnp.sum(is_new)) == len(inserted - seen)
        # Each newly inserted key has exactly one winning lane, and the
        # winners occupy distinct slots.
        slots = np.asarray(slot)
        new_np = np.asarray(is_new)
        winner_keys = [int(k) for i, k in enumerate(keys) if new_np[i]]
        assert len(winner_keys) == len(set(winner_keys))
        winner_slots = [int(slots[i]) for i in np.flatnonzero(new_np)]
        assert len(winner_slots) == len(set(winner_slots))
        seen |= inserted


@pytest.fixture(scope="module")
def twophase3():
    return TwoPhaseSys(rm_count=3)


def _reachable(model):
    from collections import deque

    seen, order, q = set(), [], deque(model.init_states())
    while q:
        s = q.popleft()
        if s in seen:
            continue
        seen.add(s)
        order.append(s)
        q.extend(ns for ns in model.next_states(s) if ns not in seen)
    return order


def test_twophase_encode_decode_roundtrip(twophase3):
    cm = twophase3.compiled()
    for s in _reachable(twophase3):
        assert cm.decode(cm.encode(s)) == s


def test_twophase_step_parity(twophase3):
    """Device successors == host successors on every reachable state."""
    cm = twophase3.compiled()
    states = _reachable(twophase3)
    enc = jnp.asarray(np.stack([cm.encode(s) for s in states]))
    nexts, valid = jax.jit(jax.vmap(cm.step))(enc)
    nexts, valid = np.asarray(nexts), np.asarray(valid)
    for i, s in enumerate(states):
        host = sorted(cm.encode(ns).tobytes() for ns in twophase3.next_states(s))
        dev = sorted(
            nexts[i, j].tobytes() for j in range(cm.max_actions) if valid[i, j]
        )
        assert host == dev


def test_twophase_property_conds_parity(twophase3):
    cm = twophase3.compiled()
    props = twophase3.properties()
    states = _reachable(twophase3)
    enc = jnp.asarray(np.stack([cm.encode(s) for s in states]))
    conds = np.asarray(jax.jit(jax.vmap(cm.property_conds))(enc))
    for i, s in enumerate(states):
        for p, prop in enumerate(props):
            assert bool(conds[i, p]) == bool(prop.condition(twophase3, s))


def _assert_checker_parity(model, **tpu_kwargs):
    host = model.checker().spawn_bfs().join()
    # Default to the (virtual) CPU backend: fast and always present.  The
    # real-TPU path is exercised by bench.py and the tpu-marked smoke test.
    tpu_kwargs.setdefault("device", jax.devices("cpu")[0])
    tpu = model.checker().spawn_tpu(**tpu_kwargs).join()
    assert tpu.unique_state_count() == host.unique_state_count()
    assert tpu.state_count() == host.state_count()
    assert tpu.max_depth() == host.max_depth()
    hd, td = host.discoveries(), tpu.discoveries()
    assert sorted(td) == sorted(hd)
    # Paths re-execute the host model, so building them validates them.
    for name, path in td.items():
        assert len(path) >= 1
    return host, tpu


def test_twophase3_golden_tpu(twophase3):
    """2pc with 3 RMs: 288 unique states (reference examples/2pc.rs:153-154),
    identical counts and discovery set between host BFS and TPU wavefront."""
    _host, tpu = _assert_checker_parity(
        twophase3, capacity=1 << 14, max_frontier=1 << 9
    )
    assert tpu.unique_state_count() == 288


@pytest.mark.slow
def test_twophase5_golden_tpu():
    """2pc with 5 RMs: 8,832 unique states (examples/2pc.rs:158-159)."""
    model = TwoPhaseSys(rm_count=5)
    _host, tpu = _assert_checker_parity(
        model, capacity=1 << 15, max_frontier=1 << 11
    )
    assert tpu.unique_state_count() == 8832


@pytest.mark.slow
def test_levels_wider_than_chunk_match_host():
    """A BFS level far wider than max_frontier is processed in chunks from
    the slot queue instead of failing; counts, depth, and discoveries still
    match the host oracle exactly (2pc(5)'s peak level is ~2,000 states,
    checked here with 128-state chunks)."""
    model = TwoPhaseSys(rm_count=5)
    _host, tpu = _assert_checker_parity(
        model, capacity=1 << 15, max_frontier=1 << 7
    )
    assert tpu.unique_state_count() == 8832


def test_target_max_depth_with_chunked_levels():
    """Depth gating must trigger at level boundaries, not chunk boundaries."""
    model = TwoPhaseSys(rm_count=5)
    host = model.checker().target_max_depth(6).spawn_bfs().join()
    tpu = (
        model.checker()
        .target_max_depth(6)
        .spawn_tpu(capacity=1 << 15, max_frontier=1 << 7)
        .join()
    )
    assert tpu.unique_state_count() == host.unique_state_count()
    assert tpu.max_depth() == host.max_depth()


# --- eventually-property machinery on device ---------------------------------

# The fixture moved to the package (models/fixtures.py) so the symmetry
# tests and PARITY's compiled-model inventory can reference it; re-exported
# here for the sibling test modules that import it from this one.
from stateright_tpu.models.fixtures import (  # noqa: E402
    TrapCounter,
    TrapCounterCompiled,  # noqa: F401  (re-export)
)


def test_eventually_parity_with_host():
    model = TrapCounter()
    host, tpu = _assert_checker_parity(
        model, capacity=1 << 10, max_frontier=1 << 4
    )
    names = sorted(tpu.discoveries())
    # "reaches one" holds on every path: no counterexample. "reaches limit"
    # is violated via the trap dead end; "trapped" is observed.
    assert names == ["reaches limit", "trapped"]
    ce = tpu.discoveries()["reaches limit"]
    assert ce.last_state() == model.trap_state


def test_eventually_satisfied_at_terminal_not_reported():
    # Without the trap edge every path ends at `limit`, satisfying the
    # property at the terminal state itself — the bit clears before the
    # terminal check, so no counterexample (src/checker/bfs.rs:326-333).
    model = TrapCounter(trap_at=10**6)
    tpu = (
        model.checker()
        .spawn_tpu(
            capacity=1 << 10,
            max_frontier=1 << 4,
            device=jax.devices("cpu")[0],
        )
        .join()
    )
    assert "reaches limit" not in tpu.discoveries()
    assert "reaches one" not in tpu.discoveries()


@pytest.mark.tpu
def test_twophase3_golden_on_default_device():
    """Smoke test on the default backend (the real TPU when present)."""
    if jax.devices()[0].platform == "cpu":
        pytest.skip("no accelerator present")
    model = TwoPhaseSys(rm_count=3)
    tpu = model.checker().spawn_tpu(capacity=1 << 14, max_frontier=1 << 9).join()
    assert tpu.unique_state_count() == 288


def test_checkpoint_resume_matches_straight_run(tmp_path):
    """A bounded run snapshots its full device state (visited table, store,
    parents, frontier queue, counters) and resumes to exactly the totals of
    an uninterrupted run.  The reference has no checker persistence at all
    (SURVEY §5: its visited set is not persistable)."""
    model = TwoPhaseSys(rm_count=5)
    partial = (
        model.checker()
        .target_state_count(3000)
        .spawn_tpu(capacity=1 << 15, max_frontier=1 << 7)
        .join()
    )
    assert partial.unique_state_count() < 8832
    snap = str(tmp_path / "run.npz")
    partial.save_snapshot(snap)

    resumed = (
        model.checker()
        .spawn_tpu(capacity=1 << 15, max_frontier=1 << 7, resume_from=snap)
        .join()
    )
    straight = (
        model.checker().spawn_tpu(capacity=1 << 15, max_frontier=1 << 7).join()
    )
    assert resumed.unique_state_count() == straight.unique_state_count() == 8832
    assert resumed.state_count() == straight.state_count()
    assert resumed.max_depth() == straight.max_depth()
    assert sorted(resumed.discoveries()) == sorted(straight.discoveries())
    resumed.assert_properties()

    # Geometry is NOT key material: a resume adopts the snapshot's
    # table/log sizes (an auto-tuned run persists its GROWN geometry, so
    # the original spawn arguments must still resume it).
    adopted = (
        model.checker()
        .spawn_tpu(capacity=1 << 16, max_frontier=1 << 7, resume_from=snap)
        .join()
    )
    assert adopted.unique_state_count() == 8832

    # A different MODEL must still be rejected loudly.
    with pytest.raises(ValueError, match="snapshot does not match"):
        TwoPhaseSys(rm_count=4).checker().spawn_tpu(
            capacity=1 << 15, max_frontier=1 << 7, resume_from=snap
        ).join()


@pytest.mark.slow
def test_auto_tune_grows_overfull_table():
    """A capacity far below the state count completes anyway: the engine
    restarts with a grown table instead of failing into a hand-tuning
    loop (VERDICT r3 weak #7).  2pc(3) has 288 unique states, so a
    256-slot table trips the 50%-load flag almost immediately."""
    model = TwoPhaseSys(rm_count=3)
    tpu = model.checker().spawn_tpu(capacity=1 << 8, max_frontier=1 << 9).join()
    assert tpu.unique_state_count() == 288

    with pytest.raises(RuntimeError, match="table overfull"):
        model.checker().spawn_tpu(
            capacity=1 << 8, max_frontier=1 << 9, auto_tune=False
        ).join()


@pytest.mark.slow
def test_auto_tune_grows_full_row_log():
    """log_capacity sizes the row log independently of the table; an
    undersized log grows on retry, and without auto_tune fails loudly
    naming the knob."""
    model = TwoPhaseSys(rm_count=3)
    tpu = (
        model.checker()
        .spawn_tpu(capacity=1 << 14, max_frontier=1 << 9, log_capacity=256)
        .join()
    )
    assert tpu.unique_state_count() == 288

    with pytest.raises(RuntimeError, match="row log is full"):
        model.checker().spawn_tpu(
            capacity=1 << 14,
            max_frontier=1 << 9,
            log_capacity=256,
            auto_tune=False,
        ).join()


def test_log_capacity_smaller_than_table_exact():
    """A decoupled (table=2^14, log=512) geometry — the `paxos check 6`
    memory shape in miniature — still produces exact counts, depth, and
    discoveries."""
    model = TwoPhaseSys(rm_count=3)
    host = model.checker().spawn_bfs().join()
    tpu = (
        model.checker()
        .spawn_tpu(capacity=1 << 14, max_frontier=1 << 9, log_capacity=512)
        .join()
    )
    assert tpu.unique_state_count() == host.unique_state_count() == 288
    assert tpu.max_depth() == host.max_depth()
    assert tpu.state_count() == host.state_count()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())


@pytest.mark.slow
def test_twophase10_depth_bounded_differential():
    """`2pc check 10` — the largest reference bench workload (bench.sh:27)
    — depth-bounded so the host oracle fits suite runtime.  Full scale
    runs in bench.py's reference-suite phase, golden-gated at 61,515,776
    unique states / depth 32 (device, 2026-07-31; depth-8 differential
    pinned 256,660 both engines)."""
    model = TwoPhaseSys(rm_count=10)
    host = model.checker().target_max_depth(7).spawn_bfs().join()
    tpu = (
        TwoPhaseSys(rm_count=10)
        .checker()
        .target_max_depth(7)
        .spawn_tpu(capacity=1 << 20, max_frontier=1 << 11, dedup_factor=1)
        .join()
    )
    assert host.unique_state_count() == tpu.unique_state_count()
    assert host.state_count() == tpu.state_count()
    assert tpu.max_depth() == host.max_depth() == 7
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())


def test_auto_tune_dedup_growth_clamps_frontier():
    """Relaxing dedup_factor must keep the compact/dedup buffer inside the
    device-safe band by halving max_frontier: a 1.7M-lane buffer (2pc
    rm=10 at f=2^15, dd=1) crashes the TPU worker outright, while both
    neighboring 426K-lane configs run to graceful overflow flags
    (isolation matrix, 2026-07-31)."""
    from stateright_tpu.models.twophase import TwoPhaseSys
    from stateright_tpu.parallel.hashset import unique_buffer_size
    from stateright_tpu.parallel.wavefront import (
        TpuChecker, max_safe_unique_lanes,
    )

    ck = TpuChecker.__new__(TpuChecker)  # knob logic only; no run thread
    ck._compiled = TwoPhaseSys(rm_count=10).compiled()
    ck._capacity = 1 << 20
    ck._log_capacity = 1 << 20
    ck._log_capacity_explicit = False
    ck._dedup_factor = 4
    ck._max_frontier = 1 << 15
    # Default sort-rung state (the full buffer) on the SORT path: the
    # flag-4 growth goes straight to the dd relax, not a rung climb
    # (and not the sortless fallback, which fires first when armed).
    ck._sortless = False
    ck._sort_lanes = None
    ck._step_lanes = None
    ck._sort_peak_valid = 0.0
    ck._journal = None  # the relax tail re-journals geometry when set
    msg = ck._grow(4)
    assert ck._dedup_factor == 1
    assert "max_frontier" in msg
    assert (
        unique_buffer_size(
            ck._max_frontier * ck._compiled.max_actions, ck._dedup_factor
        )
        <= max_safe_unique_lanes(ck._compiled.state_width)
    )
    # A small model's buffer already fits: no frontier change.
    ck._compiled = TwoPhaseSys(rm_count=3).compiled()
    ck._dedup_factor = 4
    ck._max_frontier = 1 << 13
    msg = ck._grow(4)
    assert ck._dedup_factor == 1
    assert "max_frontier" not in msg


def test_grow_refuses_when_floor_frontier_still_over_budget():
    """max_actions > 256 cannot fit the safe band even at the floor
    frontier: _grow must refuse (None -> loud RuntimeError upstream), not
    proceed into the worker-crash band."""
    from stateright_tpu.parallel.wavefront import TpuChecker

    class WideCM:
        max_actions = 512
        state_width = 2

    ck = TpuChecker.__new__(TpuChecker)
    ck._compiled = WideCM()
    ck._capacity = 1 << 20
    ck._log_capacity = 1 << 20
    ck._log_capacity_explicit = False
    ck._dedup_factor = 4
    ck._max_frontier = 1 << 15
    ck._sortless = False  # sort path: no fallback move left either
    ck._sort_lanes = None  # full-buffer rung: nothing left to climb
    ck._step_lanes = None
    ck._sort_peak_valid = 0.0
    ck._journal = None
    assert ck._grow(4) is None


def test_spawn_clamps_crash_band_geometry():
    """A requested (max_frontier, dedup_factor) in the worker-crash band
    is clamped at spawn, not run as-is."""
    from stateright_tpu.models.twophase import TwoPhaseSys
    from stateright_tpu.parallel.hashset import unique_buffer_size
    from stateright_tpu.parallel.wavefront import max_safe_unique_lanes

    ck = (
        TwoPhaseSys(rm_count=10)
        .checker()
        .target_max_depth(1)
        .spawn_tpu(max_frontier=1 << 15, dedup_factor=1)
    )
    ck.join()
    assert ck._max_frontier < (1 << 15)
    assert (
        unique_buffer_size(
            ck._max_frontier * ck._compiled.max_actions, 1
        )
        <= max_safe_unique_lanes(ck._compiled.state_width)
    )


def test_table_growth_drags_log_x2_not_to_half_capacity():
    """The defaulted row log follows a table growth by ×2 (its own growth
    step), NOT straight to capacity/2: at 4·state_width bytes a position,
    a capacity/2 drag after the ×16 table jump can allocate gigabytes
    past what the run needs (w=77: observed as an HBM-pressure risk on
    `paxos check 6`)."""
    import bench
    from stateright_tpu.parallel.wavefront import TpuChecker

    ck = TpuChecker.__new__(TpuChecker)
    ck._compiled = bench.paxos_model(6).compiled()
    ck._capacity = 1 << 24
    ck._log_capacity = 1 << 23
    ck._log_capacity_explicit = False
    ck._dedup_factor = 4
    ck._max_frontier = 8192
    msg = ck._grow(1)
    assert ck._capacity == 1 << 28
    assert ck._log_capacity == 1 << 24, msg  # ×2 drag
