"""C++ host core: fingerprint-mixer parity and the concurrent visited set.

Reference analog: the stable hasher (src/lib.rs:340-387) and the
lock-sharded visited DashMap (src/checker/bfs.rs:29-31), implemented
natively in native/stateright_core.cpp per the survey's stack decision.
"""

import threading

import numpy as np
import pytest

from stateright_tpu.ops.fingerprint import _py_fp64_words, fingerprint
from stateright_tpu.ops.native import (
    NativeFpSet,
    available,
    fp64_words_native,
)

pytestmark = pytest.mark.skipif(
    not available(), reason="no C++ toolchain for the native core"
)


def test_mixer_bit_identical_to_python():
    rng = np.random.default_rng(11)
    for n in (0, 1, 2, 15, 16, 17, 100, 1000):
        words = rng.integers(0, 2**32, n, dtype=np.uint32).tolist()
        assert fp64_words_native(words) == _py_fp64_words(words)


def test_batch_mixer_matches_python():
    from stateright_tpu.ops.native import fp64_batch_native

    rng = np.random.default_rng(5)
    m = rng.integers(0, 2**32, size=(64, 7), dtype=np.uint32)
    got = fp64_batch_native(m)
    assert got == [_py_fp64_words(row.tolist()) for row in m]


def test_fingerprint_dispatch_consistent():
    # Values small and large enough to cross the native-dispatch threshold
    # must produce identical digests either way.
    values = [
        (1, 2, 3),
        tuple(range(50)),
        frozenset(range(40)),
        ("str", (True, None, 3.5), b"bytes" * 20),
    ]
    for v in values:
        from stateright_tpu.ops import fingerprint as fp_mod

        words = []
        fp_mod.canon_words(v, words)
        assert fingerprint(v) == _py_fp64_words(words)


def test_fpset_matches_dict():
    import random

    rng = random.Random(3)
    s = NativeFpSet(1 << 12)
    ref = {}
    for _ in range(2000):
        fp = rng.randrange(1, 1 << 20)
        parent = rng.randrange(1, 1 << 40)
        inserted = s.insert(fp, parent)
        assert inserted == (fp not in ref)
        if inserted:
            ref[fp] = parent
    assert len(s) == len(ref)
    for fp, parent in list(ref.items())[:300]:
        assert fp in s
        assert s.parent(fp) == parent
    assert (1 << 21) + 1 not in s
    assert s.parent((1 << 21) + 1) is None


def test_fpset_concurrent_inserts():
    s = NativeFpSet(1 << 16)

    def worker(tag):
        for i in range(5000):
            s.insert(i + 1, tag + 1)

    threads = [
        threading.Thread(target=worker, args=(k,)) for k in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # All threads insert the same 5000 keys; exactly one wins each.
    assert len(s) == 5000
    assert all((i + 1) in s for i in range(0, 5000, 97))


def test_fpset_grows_past_initial_capacity():
    # DashMap-style: 3/4 load doubles the table, so a tiny initial
    # capacity accepts arbitrarily many keys and keeps every parent.
    s = NativeFpSet(1 << 4)
    for i in range(5000):
        assert s.insert(i + 1, i + 100)
    assert len(s) == 5000
    for i in range(0, 5000, 113):
        assert (i + 1) in s
        assert s.parent(i + 1) == i + 100
    assert 999999 not in s


def test_fpset_concurrent_inserts_across_growth():
    s = NativeFpSet(1 << 4)  # forces many growths under contention

    def worker(tag):
        for i in range(4000):
            s.insert(i + 1, tag + 1)

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(s) == 4000
    assert all((i + 1) in s for i in range(0, 4000, 59))


def test_graph_engine_uses_native_set_when_threaded():
    # threads > 1 routes the visited set through the C++ facade; counts,
    # discovery sets, and reconstructed paths must match the dict engine.
    from stateright_tpu.core.engine import _NativeGenerated
    from stateright_tpu.models.ping_pong import PingPongCfg

    model = PingPongCfg(maintains_history=True, max_nat=5).into_model()
    threaded = model.checker().threads(2).spawn_bfs().join()
    assert isinstance(threaded._generated, _NativeGenerated)
    single = model.checker().spawn_bfs().join()
    assert not isinstance(single._generated, _NativeGenerated)
    assert threaded.unique_state_count() == single.unique_state_count()
    assert set(threaded.discoveries()) == set(single.discoveries())
    for name, path in threaded.discoveries().items():
        assert path.last_state() is not None

    dfs = model.checker().threads(2).spawn_dfs().join()
    assert dfs.unique_state_count() == single.unique_state_count()


def test_twophase_native_bfs_reference_goldens():
    """The C++ hot-loop BFS (bench.py's `denominator_native` phase)
    explores exactly the direct 2pc reachable space: reference goldens
    288 (3 RMs) and 8,832 (5 RMs, examples/2pc.rs:151-159), with the
    framework's depth convention and generated-state counts."""
    from stateright_tpu.models.twophase import TwoPhaseSys
    from stateright_tpu.ops.native import twophase_bfs_native

    host = TwoPhaseSys(rm_count=3).checker().spawn_bfs().join()
    r = twophase_bfs_native(3)
    assert r["unique_states"] == host.unique_state_count() == 288
    assert r["generated"] == host.state_count()
    assert r["max_depth"] == host.max_depth()

    assert twophase_bfs_native(5)["unique_states"] == 8_832


def test_twophase_native_bfs_guards():
    from stateright_tpu.ops.native import twophase_bfs_native

    with pytest.raises(RuntimeError, match="rc="):
        twophase_bfs_native(13)  # past the packed layout's 12-RM bound
    with pytest.raises(RuntimeError, match="rc="):
        twophase_bfs_native(5, max_unique=100)  # memory guard trips
