"""Device Monte-carlo checker: vmapped random trace walks.

The stochastic sibling of spawn_tpu (host engine: core/simulation.py,
reference src/checker/simulation.rs).  Discoveries are random, so the tests
assert validity (paths replay on the host model, assert_properties) and
high-probability coverage rather than exact counts.
"""

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.core.has_discoveries import HasDiscoveries  # noqa: E402
from stateright_tpu.models.twophase import TwoPhaseSys  # noqa: E402

from .test_tpu_wavefront import TrapCounter  # noqa: E402


def test_simulation_finds_sometimes_discoveries():
    model = TwoPhaseSys(rm_count=3)
    c = (
        model.checker()
        .finish_when(
            HasDiscoveries.all_of(["abort agreement", "commit agreement"])
        )
        .spawn_tpu_simulation(seed=3, walkers=512, max_trace_len=64)
        .join()
    )
    d = c.discoveries()
    assert sorted(d) == ["abort agreement", "commit agreement"]
    # No global dedup, matching the host engine.
    assert c.unique_state_count() == c.state_count() > 0
    # Discovery traces replay on the host model per expectation semantics.
    c.assert_properties()
    final = d["commit agreement"].last_state()
    assert all(rs == 2 for rs in final.rm_state)  # COMMITTED


def test_simulation_finds_eventually_counterexample():
    """A trace ending in the trap terminal with its eventually-bit still
    set is a counterexample, exactly like the host engine's trace-end
    check."""
    model = TrapCounter(limit=5, trap_at=2)
    c = (
        model.checker()
        .finish_when(HasDiscoveries.any_of(["reaches limit"]))
        .spawn_tpu_simulation(seed=1, walkers=64, max_trace_len=32)
        .join()
    )
    path = c.discoveries()["reaches limit"]
    assert path.last_state() == model.trap_state


def test_simulation_target_state_count_stops():
    model = TwoPhaseSys(rm_count=3)
    c = (
        model.checker()
        .target_state_count(2_000)
        .spawn_tpu_simulation(seed=9, walkers=128, max_trace_len=64)
        .join()
    )
    assert c.state_count() >= 2_000
    assert c.is_done()


def test_simulation_rejects_visitors_and_symmetry():
    model = TwoPhaseSys(rm_count=3)
    from stateright_tpu.core.visitor import StateRecorder

    with pytest.raises(ValueError, match="visitors"):
        model.checker().visitor(StateRecorder()).spawn_tpu_simulation(seed=0)
    with pytest.raises(ValueError, match="symmetry"):
        model.checker().symmetry().spawn_tpu_simulation(seed=0)
