"""Observability subsystem: trace-off invariance, traced-run parity,
the device visitor stream, roofline units, and engine metrics.

The two contracts that matter most (ISSUE 4 acceptance):

- trace=False is the UNCHANGED fused path — golden counts and discovery
  sets identical to pre-change, and no additional per-wave device syncs
  (pinned via the journal: each host-loop iteration writes exactly one
  ``wave`` event, so an untraced run of a ≤256-wave model has exactly
  one);
- trace=True produces identical results (same kernels, same commit
  order) plus per-wave phase breakdowns whose seconds partition the
  measured wave time.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.fixtures import TrapCounter  # noqa: E402
from stateright_tpu.models.twophase import TwoPhaseSys  # noqa: E402
from stateright_tpu.obs.roofline import (  # noqa: E402
    hbm_util_frac,
    peaks_for_device,
    probe_bytes,
    sort_bytes,
    sort_passes,
)
from stateright_tpu.obs.trace import WaveTracer  # noqa: E402
from stateright_tpu.runtime.journal import read_journal  # noqa: E402


def _cpu():
    return jax.devices("cpu")[0]


# --- roofline units -----------------------------------------------------------


def test_peaks_for_device_known_and_fallback():
    class FakeV5e:
        device_kind = "TPU v5 lite"
        platform = "tpu"

    p = peaks_for_device(FakeV5e())
    assert p["hbm_bytes_per_sec"] == 8.19e11
    assert p["estimated"] is False

    p = peaks_for_device(_cpu())
    assert p["estimated"] is True  # unknown kinds never masquerade
    assert p["hbm_bytes_per_sec"] > 0


def test_byte_model_sanity():
    assert sort_passes(1) == 0
    assert sort_passes(2) == 1
    # 2^14 lanes: k=14 -> 105 passes; monotone in lanes.
    assert sort_passes(1 << 14) == 14 * 15 // 2
    assert sort_bytes(1 << 14, 3) == 2 * 105 * 3 * (1 << 14) * 4
    assert probe_bytes(100, 0) == 0
    assert probe_bytes(100, 2) == 6 * 2 * 100 * 4
    assert hbm_util_frac(0, 1.0, 1e9) == 0.0
    assert hbm_util_frac(1e9, 0.0, 1e9) == 0.0  # degenerate -> 0, not inf
    assert hbm_util_frac(5e8, 1.0, 1e9) == 0.5


def test_wave_tracer_totals_and_journal_enrichment():
    tracer = WaveTracer(_cpu(), "test-engine")
    rec = tracer.record_wave(
        {"step": 0.25, "dedup": 0.5, "readback": 0.25},
        {"step": 100_000_000, "dedup": 900_000_000},
        probe_rounds=3,
    )
    assert rec["wave_breakdown"] == {
        "step": 0.25, "dedup": 0.5, "readback": 0.25,
    }
    assert rec["bytes"] == {"step": 100_000_000, "dedup": 900_000_000}
    # 1 GB over 0.75 device seconds (readback excluded).
    peak = tracer.peaks["hbm_bytes_per_sec"]
    assert rec["hbm_util_frac"] == pytest.approx(
        1e9 / (0.75 * peak), rel=1e-3
    )
    tracer.record_wave({"step": 0.75}, {"step": 900_000_000})
    s = tracer.summary()
    assert s["traced_waves"] == 2
    assert s["wave_breakdown"]["step"] == pytest.approx(1.0)
    assert s["bytes"]["step"] == 1_000_000_000
    assert s["probe_rounds"] == 3
    # Fractions sum to ~1 over the recorded phases.
    assert sum(s["wave_breakdown_frac"].values()) == pytest.approx(
        1.0, abs=0.01
    )


# --- trace-off invariance -----------------------------------------------------


def test_trace_off_golden_and_no_per_wave_syncs(tmp_path):
    """trace=False: golden count unchanged AND exactly one host sync per
    waves_per_call quantum (2pc(3) finishes inside one 256-wave call, so
    the journal must hold exactly ONE wave event — a per-wave sync would
    write eleven)."""
    journal = str(tmp_path / "journal.jsonl")
    tpu = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu(
            capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
            journal=journal,
        )
        .join()
    )
    assert tpu.unique_state_count() == 288
    waves = [e for e in read_journal(journal) if e["event"] == "wave"]
    assert len(waves) == 1
    assert "wave_breakdown" not in waves[0]  # untraced records stay lean
    m = tpu.metrics()
    assert m["trace"] is False
    assert m["unique_state_count"] == 288
    assert m["device_calls"] == 1


# --- traced single-chip parity ------------------------------------------------


def test_traced_run_matches_host_and_breakdown_covers_wave_time(tmp_path):
    model = TwoPhaseSys(rm_count=3)
    host = model.checker().spawn_bfs().join()
    journal = str(tmp_path / "journal.jsonl")
    tpu = (
        model.checker()
        .spawn_tpu(
            capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
            trace=True, journal=journal,
        )
        .join()
    )
    assert tpu.unique_state_count() == host.unique_state_count() == 288
    assert tpu.state_count() == host.state_count()
    assert tpu.max_depth() == host.max_depth()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())

    s = tpu.trace_summary()
    assert s["traced_waves"] >= tpu.max_depth()  # >= one wave per level
    assert set(s["wave_breakdown"]) == {
        "step", "canon", "dedup", "append", "readback",
    }
    assert s["hbm_util_frac"] > 0
    assert s["bytes"]["dedup"] > 0

    # Per-wave records: the phase seconds partition call_sec (>= 90% is
    # the acceptance bar; the timers partition it exactly).
    waves = [e for e in read_journal(journal) if e["event"] == "wave"]
    assert len(waves) == s["traced_waves"]
    for w in waves:
        assert sum(w["wave_breakdown"].values()) >= 0.9 * w["call_sec"]
        assert 0 <= w["hbm_util_frac"]
    assert [e for e in read_journal(journal)
            if e["event"] == "trace_summary"]

    # The metrics surface carries the summary.
    assert tpu.metrics()["trace_summary"]["traced_waves"] == len(waves)


def test_traced_two_phase_model_matches_host():
    """paxos is the two-phase (step_valid/step_lane) compiled model: the
    traced step phase constructs successors on the compacted valid lanes
    — parity with the host oracle on the 265-state c=1 space."""
    from tests.test_paxos_compiled import paxos_model

    model = paxos_model(client_count=1)
    host = model.checker().spawn_bfs().join()
    tpu = (
        model.checker()
        .spawn_tpu(
            capacity=1 << 12, max_frontier=1 << 6, device=_cpu(),
            trace=True,
        )
        .join()
    )
    assert tpu.unique_state_count() == host.unique_state_count()
    assert tpu.state_count() == host.state_count()
    assert tpu.max_depth() == host.max_depth()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    assert tpu.trace_summary()["traced_waves"] >= 1


def test_traced_eventually_discoveries_match_host():
    model = TrapCounter()
    host = model.checker().spawn_bfs().join()
    tpu = (
        model.checker()
        .spawn_tpu(
            capacity=1 << 10, max_frontier=1 << 4, device=_cpu(),
            trace=True,
        )
        .join()
    )
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    assert tpu.discoveries()["reaches limit"].last_state() == model.trap_state


def test_traced_run_auto_grows_like_fused(tmp_path):
    """A traced run with a far-undersized table (and a visitor — the
    path that forces tracing on default-knob runs) grows in place and
    completes, exactly like the fused loop; a grow event lands in the
    journal; auto_tune=False still fails loudly."""
    from stateright_tpu.core.visitor import StateRecorder

    model = TwoPhaseSys(rm_count=3)
    journal = str(tmp_path / "journal.jsonl")
    recorder, accessor = StateRecorder.new_with_accessor()
    tpu = (
        model.checker()
        .visitor(recorder)
        .spawn_tpu(
            capacity=1 << 8, max_frontier=1 << 9, device=_cpu(),
            journal=journal,
        )
        .join()
    )
    assert tpu.unique_state_count() == 288
    assert len(accessor()) == 288  # re-run chunks never double-visit
    evs = read_journal(journal)
    assert any(e["event"] == "grow" for e in evs)

    with pytest.raises(RuntimeError, match="table overfull"):
        model.checker().spawn_tpu(
            capacity=1 << 8, max_frontier=1 << 9, device=_cpu(),
            trace=True, auto_tune=False,
        ).join()


def test_trace_rejects_resume(tmp_path):
    with pytest.raises(ValueError, match="resume_from"):
        TwoPhaseSys(rm_count=3).checker().spawn_tpu(
            trace=True, resume_from=str(tmp_path / "x.npz")
        )


# --- the device visitor stream ------------------------------------------------


def test_visitor_stream_coarse_wave_granularity():
    """The spawn_tpu visitor contract (docs/OBSERVABILITY.md): every
    unique state visited exactly once, at expansion, in BFS level order
    across waves (within a level the order is fingerprint-sorted, not
    insertion order — the coarse part of the contract)."""
    from stateright_tpu.core.visitor import StateRecorder

    model = TrapCounter()
    recorder, accessor = StateRecorder.new_with_accessor()
    tpu = (
        model.checker()
        .visitor(recorder)  # forces trace on — no rejection anymore
        .spawn_tpu(capacity=1 << 10, max_frontier=1 << 4, device=_cpu())
        .join()
    )
    assert tpu.metrics()["trace"] is True

    host_rec, host_acc = StateRecorder.new_with_accessor()
    host = model.checker().visitor(host_rec).spawn_bfs().join()

    got, want = accessor(), host_acc()
    assert len(got) == len(set(got))  # each unique state exactly once
    assert set(got) == set(want)
    assert len(got) == host.unique_state_count()

    # BFS level order: group the host's visit order into depth levels,
    # then check the device order equals the host order up to in-level
    # permutation.
    depth_of = {0: 0}
    for s in want:
        if s == 0:
            continue
        preds = [
            p for p in want
            if s in {
                p + 1 if p < model.limit else None,
                model.trap_state if p == model.trap_at else None,
            }
        ]
        depth_of[s] = min(depth_of[p] for p in preds) + 1
    got_depths = [depth_of[s] for s in got]
    assert got_depths == sorted(got_depths)  # level-monotone stream
    for d in set(got_depths):
        assert {s for s in got if depth_of[s] == d} == {
            s for s in want if depth_of[s] == d
        }


def test_visitor_single_state_paths():
    """Visited paths are single-state (no action prefix) — the documented
    coarse contract; last_state() is the visited state."""
    seen = []
    (
        TrapCounter()
        .checker()
        .visitor(lambda path: seen.append((len(path), path.last_state())))
        .spawn_tpu(capacity=1 << 10, max_frontier=1 << 4, device=_cpu())
        .join()
    )
    assert seen and all(n == 1 for n, _s in seen)


# --- traced sharded engine ----------------------------------------------------


def _mesh(n):
    devices = jax.devices("cpu")
    assert len(devices) >= n
    return jax.sharding.Mesh(np.array(devices[:n]), ("shards",))


def test_traced_sharded_parity_and_measured_exchange(tmp_path):
    model = TwoPhaseSys(rm_count=3)
    host = model.checker().spawn_bfs().join()
    journal = str(tmp_path / "journal.jsonl")
    sh = (
        model.checker()
        .spawn_tpu_sharded(
            mesh=_mesh(4), capacity=1 << 14, chunk_size=1 << 8,
            trace=True, journal=journal,
        )
        .join()
    )
    assert sh.unique_state_count() == host.unique_state_count() == 288
    assert sh.state_count() == host.state_count()
    assert sorted(sh.discoveries()) == sorted(host.discoveries())

    s = sh.trace_summary()
    assert set(s["wave_breakdown"]) == {
        "step", "canon", "dedup", "exchange", "append", "readback",
    }
    # Measured per-wave exchange instrumentation in the journal.
    waves = [e for e in read_journal(journal) if e["event"] == "wave"]
    assert waves
    for w in waves:
        assert "exchange_payload_bytes" in w
        assert 0.0 <= w["exchange_occupancy"] <= 1.0
    assert sum(w["exchange_payload_bytes"] for w in waves) == (
        s["exchange_payload_bytes"]
    )
    # Totals agree with the run accounting (same counters).
    acc = sh.accounting()
    assert acc["exchange_payload_bytes_total"] == s["exchange_payload_bytes"]
    assert 0.0 < acc["exchange_occupancy"] <= 1.0


def test_traced_sharded_one_shard_elides_exchange():
    model = TwoPhaseSys(rm_count=3)
    sh = (
        model.checker()
        .spawn_tpu_sharded(
            mesh=_mesh(1), capacity=1 << 14, chunk_size=1 << 8, trace=True,
        )
        .join()
    )
    assert sh.unique_state_count() == 288
    s = sh.trace_summary()
    assert s["bytes"]["exchange"] == 0
    assert s["exchange_payload_bytes"] == 0
    assert sh.accounting()["exchange_elided"] is True


# --- metrics surface ----------------------------------------------------------


def test_host_engine_base_metrics():
    m = TwoPhaseSys(rm_count=3).checker().spawn_bfs().join().metrics()
    assert m["unique_state_count"] == 288
    assert m["done"] is True


def test_sharded_metrics_include_accounting():
    sh = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sharded(
            mesh=_mesh(2), capacity=1 << 14, chunk_size=1 << 8,
        )
        .join()
    )
    m = sh.metrics()
    assert m["engine"] == "tpu-sharded"
    assert m["shards"] == 2
    assert m["accounting"]["waves"] >= 1
    assert "exchange_occupancy" in m["accounting"]


# --- density telemetry + compile observability (ISSUE 11) ---------------------


def test_untraced_run_reports_density_and_geometry_event(tmp_path):
    """trace=False (fused program pinned, no extra syncs — the golden
    test above stays green): metrics() still carries the density EMA +
    histogram and the load-factor trajectory, and the journal gains one
    ``geometry`` event plus a per-quantum ``density`` field."""
    journal = str(tmp_path / "journal.jsonl")
    tpu = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu(
            capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
            journal=journal,
        )
        .join()
    )
    assert tpu.unique_state_count() == 288
    m = tpu.metrics()
    assert 0 < m["valid_density_ema"] <= 1.0
    dh = m["histograms"]["valid_density"]
    assert dh["count"] > 0
    lf = m["histograms"]["load_factor"]
    assert lf["count"] > 0
    evs = read_journal(journal)
    geo = [e for e in evs if e["event"] == "geometry"]
    assert len(geo) == 1
    assert geo[0]["engine"] == "tpu-wavefront"
    assert geo[0]["u_lanes"] > 0 and geo[0]["dedup_factor"] == 8
    waves = [e for e in evs if e["event"] == "wave"]
    assert len(waves) == 1  # the no-extra-syncs pin, restated
    assert 0 < waves[0]["density"] <= 1.0


def test_traced_run_journals_density_per_wave(tmp_path):
    journal = str(tmp_path / "journal.jsonl")
    tpu = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu(
            capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
            trace=True, journal=journal,
        )
        .join()
    )
    assert tpu.unique_state_count() == 288
    waves = [e for e in read_journal(journal) if e["event"] == "wave"]
    assert waves and all(0 <= w["density"] <= 1.0 for w in waves)
    assert any(e["event"] == "geometry"
               for e in read_journal(journal))


def test_sharded_per_shard_gauges_and_skew(tmp_path):
    """The fused sharded loop exports per-shard frontier/insert/
    exchange gauges with a max/mean skew — derived from the stats
    readback it already holds (no extra syncs) — and the Prometheus
    exposition renders them as labeled families that validate."""
    from stateright_tpu.obs.prometheus import (
        parse_prometheus, render_prometheus,
    )

    journal = str(tmp_path / "journal.jsonl")
    sh = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu_sharded(
            mesh=_mesh(4), capacity=1 << 14, chunk_size=1 << 8,
            journal=journal,
        )
        .join()
    )
    assert sh.unique_state_count() == 288
    m = sh.metrics()
    for fam in ("shard_frontier", "shard_unique", "shard_exchange_bytes"):
        assert set(m[fam]) == {"0", "1", "2", "3"}, fam
    assert sum(m["shard_unique"].values()) == 288
    for skew in ("frontier_skew_max_over_mean", "unique_skew_max_over_mean",
                 "exchange_skew_max_over_mean"):
        assert m[skew] >= 1.0, skew
    # Hash ownership balances statically: skew stays near 1 on a
    # non-adversarial model.
    assert m["unique_skew_max_over_mean"] < 2.0
    assert 0 < m["valid_density_ema"] <= 1.0
    fams = parse_prometheus(render_prometheus(m))
    per_shard = fams["stateright_shard_unique"]
    assert {labels["key"] for _n, labels, _v in per_shard["samples"]} == {
        "0", "1", "2", "3",
    }
    geo = [e for e in read_journal(journal) if e["event"] == "geometry"]
    assert geo and geo[0]["shards"] == 4 and geo[0]["bucket_slack"] == 50


def test_compile_events_carry_label_provenance_and_timing(tmp_path):
    """A program-cache miss journals a ``compile`` event per built XLA
    program (first-call timed) with the key provenance, and the
    process-global compile metrics move."""
    from stateright_tpu.obs.metrics import GLOBAL

    journal = str(tmp_path / "journal.jsonl")
    before = float(GLOBAL.get("compile_sec_total", 0.0))
    tpu = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu(
            capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
            journal=journal, waves_per_call=5,  # unusual key: forced miss
        )
        .join()
    )
    assert tpu.unique_state_count() == 288
    compiles = [
        e for e in read_journal(journal) if e["event"] == "compile"
    ]
    assert len(compiles) >= 2  # the (seed, run) pair at least
    labels = {c["label"] for c in compiles}
    assert any(lb.startswith("TpuChecker.fused") for lb in labels)
    for c in compiles:
        assert c["sec"] >= 0
        assert c["provenance"]["waves_per_call"] == 5
        assert c["provenance"]["capacity"] == 1 << 14
    m = tpu.metrics()
    assert m["compile_sec_total"] >= before
    assert isinstance(m["recompile_storms"], int)


def test_recompile_storm_detector_rising_edge():
    """The storm detector fires once at the quiet->storm edge, not per
    compile, and resets when the window drains."""
    from stateright_tpu.parallel import wave_common as wc

    saved = (list(wc._COMPILE_TIMES), wc._STORM_ACTIVE[0])
    wc._COMPILE_TIMES.clear()
    wc._STORM_ACTIVE[0] = False
    try:
        t0 = 1000.0
        edges = [
            wc._note_compile(t0 + i) for i in range(wc.COMPILE_STORM_THRESHOLD)
        ]
        assert edges.count(True) == 1 and edges[-1] is True
        assert wc._note_compile(t0 + 10) is False  # still in storm: no edge
        # Window drains -> quiet -> a new burst fires a new edge.
        far = t0 + wc.COMPILE_STORM_WINDOW_SEC + 100
        assert wc._note_compile(far) is False
        for i in range(wc.COMPILE_STORM_THRESHOLD - 2):
            assert wc._note_compile(far + 1 + i) is False
        assert wc._note_compile(far + 50) is True
    finally:
        wc._COMPILE_TIMES.clear()
        wc._COMPILE_TIMES.extend(saved[0])
        wc._STORM_ACTIVE[0] = saved[1]
