"""Direct-model workload golden tests.

Reference anchors: examples/2pc.rs:151-170 (288 / 8,832 / 665),
examples/increment.rs module docs (13 → 8 with symmetry for 2 threads),
examples/increment_lock.rs (invariants hold).
"""

from stateright_tpu import Property
from stateright_tpu.core.symmetry import RewritePlan
from stateright_tpu.models.increment import Increment, IncrementLock
from stateright_tpu.models.twophase import TwoPhaseSys


def test_can_model_2pc():
    checker = TwoPhaseSys(rm_count=3).checker().spawn_bfs().join()
    assert checker.unique_state_count() == 288
    checker.assert_properties()

    checker = TwoPhaseSys(rm_count=5).checker().spawn_dfs().join()
    assert checker.unique_state_count() == 8832
    checker.assert_properties()

    checker = TwoPhaseSys(rm_count=5).checker().symmetry().spawn_dfs().join()
    assert checker.unique_state_count() == 665
    checker.assert_properties()


class _Exhaustive:
    """Mixin adding an unsatisfiable `sometimes` property so the checker
    explores the full space instead of early-exiting once the (violated)
    invariant's discovery is found."""

    def properties(self):
        return super().properties() + [
            Property.sometimes("unreachable", lambda _m, _s: False)
        ]


class ExhaustiveIncrement(_Exhaustive, Increment):
    pass


class ExhaustiveIncrementLock(_Exhaustive, IncrementLock):
    pass


def test_increment_finds_race():
    checker = Increment(thread_count=2).checker().spawn_bfs().join()
    # The naive counter's "fin" invariant is violated (the whole point).
    assert checker.discovery("fin") is not None


def test_increment_state_space_13_to_8_with_symmetry():
    # examples/increment.rs:36-105 documents 13 unique states for 2 threads,
    # reduced to 8 under symmetry.
    checker = ExhaustiveIncrement(thread_count=2).checker().spawn_dfs().join()
    assert checker.unique_state_count() == 13
    checker = (
        ExhaustiveIncrement(thread_count=2).checker().symmetry().spawn_dfs().join()
    )
    assert checker.unique_state_count() == 8


def test_increment_lock_invariants_hold():
    checker = IncrementLock(thread_count=2).checker().spawn_bfs().join()
    checker.assert_no_discovery("fin")
    checker.assert_no_discovery("mutex")
    checker = ExhaustiveIncrementLock(thread_count=3).checker().spawn_dfs().join()
    checker.assert_no_discovery("fin")
    checker.assert_no_discovery("mutex")


def test_rewrite_plan_from_sort_sorts():
    # Reference: src/checker/rewrite_plan.rs:132-138.
    original = ["B", "D", "C", "A"]
    plan = RewritePlan.from_values_to_sort(original, rewritten_type=int)
    assert plan.reindex(original, rewrite_elems=False) == ["A", "B", "C", "D"]
    assert plan.reindex([1, 3, 2, 0], rewrite_elems=False) == [0, 1, 2, 3]


def test_rewrite_plan_can_reindex():
    # Reference: src/checker/rewrite_plan.rs:141-159.
    swap = RewritePlan.from_values_to_sort([2, 1, 0], rewritten_type=int)
    rot = RewritePlan.from_values_to_sort([2, 0, 1], rewritten_type=int)
    original = ["A", "B", "C"]
    assert swap.reindex(original, rewrite_elems=False) == ["C", "B", "A"]
    assert rot.reindex(original, rewrite_elems=False) == ["B", "C", "A"]
