"""Device symmetry reduction: canonicalization kernels + engine wiring.

Pins, per docs/SYMMETRY.md:

- the canon kernel is IDEMPOTENT (``canon(canon(r)) == canon(r)``) and
  SOUND (``canon(r)`` stays in ``r``'s orbit) over the full reachable set;
- its equivalence classes are EXACTLY the brute-force orbit partition
  (full-record sort keys make the canonical form an orbit invariant);
- ``spawn_tpu`` + ``symmetry()`` dedups on the canonical fingerprint while
  logging originals: unique counts equal the distinct-canon count over the
  full space (80 / 166 / 314 at rm=3/4/5), the discovery set equals host
  DFS-sym's, and the host DFS golden 665 at rm=5 (reference recipe,
  examples/2pc.rs:163-168) stays untouched;
- ``spawn_tpu_sharded`` on an 8-device virtual mesh reproduces the same
  counts (canonical fps route owners, so the reduction is mesh-invariant);
- models with no canon capability fail the spawn loudly; the identity
  canon (trap-counter fixture) changes nothing.
"""

from collections import deque
from itertools import permutations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.models.fixtures import TrapCounter  # noqa: E402
from stateright_tpu.models.twophase import (  # noqa: E402
    TwoPhaseState,
    TwoPhaseSys,
)
from stateright_tpu.parallel.canon import (  # noqa: E402
    CanonSpec,
    canon_batch_host,
    canonicalize,
    field,
    make_canon,
    validate_spec,
)

CANON_GOLDEN = {3: 80, 4: 166, 5: 314}  # distinct orbits, pinned below
HOST_DFS_GOLDEN = {3: 107, 5: 665}  # tie-broken representative (DFS order)


def _reachable(model):
    seen, order, q = set(), [], deque(model.init_states())
    while q:
        s = q.popleft()
        if s in seen:
            continue
        seen.add(s)
        order.append(s)
        q.extend(ns for ns in model.next_states(s) if ns not in seen)
    return order


def _permute(s: TwoPhaseState, perm) -> TwoPhaseState:
    """Apply a genuine RM-index permutation (perm[new] = old)."""
    inv = [0] * len(perm)
    for new_i, old_i in enumerate(perm):
        inv[old_i] = new_i
    return TwoPhaseState(
        rm_state=tuple(s.rm_state[o] for o in perm),
        tm_state=s.tm_state,
        tm_prepared=tuple(s.tm_prepared[o] for o in perm),
        msgs=frozenset(
            ("prepared", inv[m[1]]) if m[0] == "prepared" else m
            for m in s.msgs
        ),
    )


def _orbit_key(s: TwoPhaseState, perms):
    def k(t):
        n = len(t.rm_state)
        prep = tuple(("prepared", i) in t.msgs for i in range(n))
        rest = tuple(sorted(m for m in t.msgs if m[0] != "prepared"))
        return (t.rm_state, t.tm_state, t.tm_prepared, prep, rest)

    return min(k(_permute(s, p)) for p in perms)


# --- the kernel --------------------------------------------------------------


def _canon_np(model):
    """(states, original rows, canonical rows) for the full reachable set."""
    cm = model.compiled()
    states = _reachable(model)
    rows = np.stack([cm.encode(s) for s in states]).astype(np.uint32)
    return cm, states, rows, canon_batch_host(cm, rows)


@pytest.mark.parametrize("rm", [3, 4])
def test_canon_idempotent_sound_and_orbit_exact(rm):
    """Over the FULL reachable set: canon is idempotent, lands inside the
    input's orbit, and partitions states exactly like brute-force orbit
    enumeration — i.e. the full-record sort is a perfect canonicalization
    (see docs/SYMMETRY.md for why perfection is what makes wavefront
    counts traversal-invariant)."""
    model = TwoPhaseSys(rm_count=rm)
    cm, states, rows, canon = _canon_np(model)
    # Idempotence: canon(canon(r)) == canon(r).
    assert np.array_equal(canon_batch_host(cm, canon), canon)
    perms = list(permutations(range(rm)))
    canon_of, orbit_of = {}, {}
    for s, crow in zip(states, canon.tolist()):
        cs = cm.decode(crow)
        # Soundness: the canonical state is a member of s's orbit.
        assert _orbit_key(cs, perms) == _orbit_key(s, perms)
        canon_of[s] = tuple(crow)
        orbit_of[s] = _orbit_key(s, perms)
    # Partition equality: same canon <=> same orbit, state by state.
    for a in states:
        for b in states:
            if orbit_of[a] == orbit_of[b]:
                assert canon_of[a] == canon_of[b], (a, b)
    assert len(set(canon_of.values())) == len(set(orbit_of.values()))
    assert len(set(canon_of.values())) == CANON_GOLDEN[rm]


def test_canon_identity_on_empty_spec():
    cm = TrapCounter().compiled()
    rows = np.arange(8, dtype=np.uint32).reshape(8, 1)
    assert np.array_equal(canon_batch_host(cm, rows), rows)


def test_canon_id_field_remap():
    """A per-record Id field and a global Id field both follow the
    permutation (the device RewritePlan.rewrite), and sentinel values
    >= n pass through unchanged."""
    # Row layout: word0 = three 8-bit record keys; word1 bits 0-1 a
    # per-record 2-bit Id field (stride 2); word2 a global 4-bit Id.
    spec = CanonSpec(
        n=3,
        fields=(
            field(word=0, shift=0, width=8),
            field(word=1, shift=0, width=2, is_id=True),
        ),
        id_fields=(field(word=2, shift=0, width=4),),
    )
    validate_spec(spec, 3)

    def canon(row):
        return canonicalize(spec, row)

    # Keys (30, 10, 20) sort to (10, 20, 30): order = [1, 2, 0], so the
    # old->new mapping is 0->2, 1->0, 2->1.
    keys = 30 | (10 << 8) | (20 << 16)
    ids = 0 | (2 << 2) | (1 << 4)  # per-record ids: r0=0, r1=2, r2=1
    row = jnp.asarray(np.array([keys, ids, 2], np.uint32))
    out = np.asarray(jax.jit(canon)(row))
    assert out[0] == 10 | (20 << 8) | (30 << 16)
    # Records permuted to (r1, r2, r0) = ids (2, 1, 0), then each id
    # value remapped old->new: 2->1, 1->0, 0->2.
    assert out[1] == 1 | (0 << 2) | (2 << 4)
    assert out[2] == 1  # global id 2 -> new index 1
    # Sentinel: a global id >= n is not an index; it must pass through.
    row2 = jnp.asarray(np.array([keys, ids, 9], np.uint32))
    assert np.asarray(jax.jit(canon)(row2))[2] == 9


def test_canon_validate_rejects_malformed():
    with pytest.raises(ValueError, match="outside"):
        validate_spec(
            CanonSpec(n=2, fields=(field(word=5, shift=0, width=2),)), 2
        )
    with pytest.raises(ValueError, match="exceed"):
        validate_spec(
            CanonSpec(n=12, fields=(field(word=0, shift=16, width=2),)), 2
        )
    with pytest.raises(ValueError, match="too narrow"):
        validate_spec(
            CanonSpec(n=5, id_fields=(field(word=0, shift=0, width=2),)), 2
        )
    # Sort-key fields outside the fp_words identity prefix would make
    # the permutation depend on non-identity data (silent count
    # inflation); id fields there are harmless (they never shape the
    # sort).
    beyond = CanonSpec(
        n=2, fields=(field(word=1, shift=0, width=4, word_stride=0),)
    )
    with pytest.raises(ValueError, match="identity prefix"):
        validate_spec(beyond, 2, fp_words=1)
    validate_spec(beyond, 2)  # full-identity models: fine
    validate_spec(
        CanonSpec(
            n=2,
            fields=(field(word=0, shift=0, width=4),),
            id_fields=(field(word=1, shift=0, width=4),),
        ),
        2,
        fp_words=1,
    )


# --- single-chip engine ------------------------------------------------------


def _spawn_sym(model, **kw):
    kw.setdefault("device", jax.devices("cpu")[0])
    kw.setdefault("capacity", 1 << 14)
    kw.setdefault("max_frontier", 1 << 9)
    return model.checker().symmetry().spawn_tpu(**kw).join()


def test_tpu_sym_2pc3_golden():
    """Device-sym unique count == distinct canon rows over the full
    space (the traversal-invariant orbit count), discovery set == host
    DFS-sym's; host DFS keeps its own tie-broken count."""
    model = TwoPhaseSys(rm_count=3)
    tpu = _spawn_sym(model)
    cm, _states, _rows, canon = _canon_np(model)
    want = len({tuple(r) for r in canon.tolist()})
    assert want == CANON_GOLDEN[3]
    assert tpu.unique_state_count() == want
    host = model.checker().symmetry().spawn_dfs().join()
    assert host.unique_state_count() == HOST_DFS_GOLDEN[3]
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    # Paths re-execute the host model over ORIGINAL (logged) rows, so
    # building them validates the store-original semantics.
    for name, path in tpu.discoveries().items():
        assert len(path) >= 1


@pytest.mark.slow
def test_tpu_sym_2pc5_goldens():
    """rm=5, the reference's symmetry showcase: host DFS-sym reproduces
    the reference golden 665 (examples/2pc.rs:163-168); the device
    wavefront reports 314 — the exact orbit count, a strictly stronger
    cut than the DFS-traversal-dependent 665 (8,832 full) — identically
    on any chunk geometry, with the same discovery set.  Canon
    idempotence rides along over the full 8,832-row set."""
    model = TwoPhaseSys(rm_count=5)
    host = model.checker().symmetry().spawn_dfs().join()
    assert host.unique_state_count() == HOST_DFS_GOLDEN[5]

    tpu = _spawn_sym(model, capacity=1 << 15, max_frontier=1 << 11)
    assert tpu.unique_state_count() == CANON_GOLDEN[5]
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    # Chunk geometry must not change the count (traversal invariance).
    narrow = _spawn_sym(model, capacity=1 << 15, max_frontier=1 << 6)
    assert narrow.unique_state_count() == CANON_GOLDEN[5]

    cm, _states, rows, canon = _canon_np(model)
    assert len({tuple(r) for r in canon.tolist()}) == CANON_GOLDEN[5]
    assert np.array_equal(canon_batch_host(cm, canon), canon)


def test_tpu_sym_trap_counter_identity():
    """The identity canon (empty spec): symmetry-on must match plain
    runs exactly — counts AND discovery set — on the fixture with no
    symmetric structure."""
    model = TrapCounter()
    dev = jax.devices("cpu")[0]
    sym = (
        model.checker().symmetry_fn(lambda s: s)
        .spawn_tpu(capacity=1 << 10, max_frontier=1 << 4, device=dev)
        .join()
    )
    plain = (
        model.checker()
        .spawn_tpu(capacity=1 << 10, max_frontier=1 << 4, device=dev)
        .join()
    )
    host = model.checker().symmetry_fn(lambda s: s).spawn_dfs().join()
    assert sym.unique_state_count() == plain.unique_state_count()
    assert sym.state_count() == plain.state_count()
    assert sorted(sym.discoveries()) == sorted(plain.discoveries())
    assert sorted(sym.discoveries()) == sorted(host.discoveries())
    assert sym.discoveries()["reaches limit"].last_state() == model.trap_state


def test_tpu_sym_requires_canon_capability():
    """No canon spec -> loud spawn error, never a silent full-space run
    reported as reduced."""
    from stateright_tpu.actor import Network
    from stateright_tpu.models.paxos import PaxosModelCfg

    model = PaxosModelCfg(
        client_count=2,
        server_count=3,
        network=Network.new_unordered_nonduplicating(),
    ).into_model()
    with pytest.raises(ValueError, match="canon"):
        model.checker().symmetry_fn(lambda s: s).spawn_tpu()
    with pytest.raises(ValueError, match="canon"):
        model.checker().symmetry_fn(lambda s: s).spawn_tpu_sharded()


def test_sym_snapshot_not_resumable_as_plain(tmp_path):
    """A symmetry run's table holds canonical fingerprints; resuming it
    without symmetry() must be rejected by the snapshot key."""
    model = TwoPhaseSys(rm_count=3)
    ck = _spawn_sym(model)
    snap = str(tmp_path / "sym.npz")
    ck.save_snapshot(snap)
    with pytest.raises(ValueError, match="snapshot does not match"):
        model.checker().spawn_tpu(
            capacity=1 << 14, max_frontier=1 << 9, resume_from=snap
        ).join()
    resumed = model.checker().symmetry().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, resume_from=snap
    ).join()
    assert resumed.unique_state_count() == CANON_GOLDEN[3]


# --- sharded engine ----------------------------------------------------------


def test_sharded_sym_2pc3_matches_single_chip():
    """8-device virtual mesh: canonical fps route owners, so the mesh
    reproduces the single-chip count exactly."""
    model = TwoPhaseSys(rm_count=3)
    sh = (
        model.checker().symmetry()
        .spawn_tpu_sharded(capacity=1 << 16, chunk_size=1 << 7)
        .join()
    )
    assert sh.unique_state_count() == CANON_GOLDEN[3]
    host = model.checker().symmetry().spawn_dfs().join()
    assert sorted(sh.discoveries()) == sorted(host.discoveries())


@pytest.mark.slow
def test_sharded_sym_2pc5_matches_single_chip():
    model = TwoPhaseSys(rm_count=5)
    sh = (
        model.checker().symmetry()
        .spawn_tpu_sharded(capacity=1 << 16, chunk_size=1 << 7)
        .join()
    )
    assert sh.unique_state_count() == CANON_GOLDEN[5]


# --- canon capability resolution ---------------------------------------------


def test_make_canon_resolution():
    assert make_canon(TwoPhaseSys(rm_count=3).compiled()) is not None
    assert make_canon(TrapCounter().compiled()) is not None

    from stateright_tpu.parallel.compiled import CompiledModel

    class NoCanon(CompiledModel):
        state_width = 1
        max_actions = 1

    assert make_canon(NoCanon()) is None

    class CustomCanon(CompiledModel):
        state_width = 1
        max_actions = 1

        def canon_rows(self, state):
            return state

    assert make_canon(CustomCanon()) is not None
