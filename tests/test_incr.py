"""Incremental re-checking: the persistent verification store (incr/).

Covers the acceptance gates of docs/INCREMENTAL.md:

- verdict cache: identical spec -> journaled verdict + counterexample
  path, zero device dispatches, zero waves;
- property-only re-check: zero exploration waves, verdict identical to
  a from-scratch run of the edited model;
- constant widening: seeded run's discovered_fingerprints() bit-equal
  to the unconstrained cold run;
- the DEGRADATION MATRIX: codec change, constant narrowing, property
  change with EVENTUALLY, symmetry toggle, bounds change, missing
  exhaustiveness witness — each lands in its documented mode with the
  reason journaled; engine-geometry-only changes still hit the cache;
- spec-hash determinism across processes (fresh PYTHONHASHSEED);
- ColdStore disk-tier lifecycle (no clobber / no orphan / open /
  close / torn-run-proof append);
- the serve surface (JobSpec.store, scheduler short-circuit, metrics).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from stateright_tpu.incr import (
    SpecFingerprint, VerificationStore, incremental_check,
)
from stateright_tpu.incr.store import (
    COLD, CONSTANT_WIDENING, IDENTICAL, PROPERTY_ONLY,
)
from stateright_tpu.models.fixtures import (
    GridWalk, TrapCounter, TwoPhaseEdited,
)
from stateright_tpu.models.twophase import TwoPhaseSys
from stateright_tpu.runtime.journal import read_journal
from stateright_tpu.tiered.cold_store import ColdStore

GRID_KW = dict(capacity=1 << 12, max_frontier=1 << 6)
TP_KW = dict(capacity=1 << 13, max_frontier=1 << 7)


@pytest.fixture()
def store_dir(tmp_path):
    return str(tmp_path / "store")


def _journal(store_dir):
    return os.path.join(store_dir, "journal.jsonl")


def _waves(store_dir) -> int:
    path = _journal(store_dir)
    if not os.path.exists(path):
        return 0
    return sum(1 for e in read_journal(path) if e.get("event") == "wave")


def _check(model, store_dir, reuse=True, store_result=True, builder=None,
           **kw):
    return incremental_check(
        builder if builder is not None else model.checker(),
        store_dir,
        engine_kwargs=kw or dict(GRID_KW),
        journal=_journal(store_dir),
        reuse=reuse,
        store_result=store_result,
    )


# --- spec hashing -------------------------------------------------------------


def test_spec_components_distinguish_deltas():
    base = SpecFingerprint(GridWalk(bound=4))
    widened = SpecFingerprint(GridWalk(bound=6))
    assert base.spec_key != widened.spec_key
    assert base.family_key == widened.family_key
    assert base.components["codec"] == widened.components["codec"]
    assert base.components["properties"] == widened.components["properties"]
    assert base.components["constants"] != widened.components["constants"]

    edited = SpecFingerprint(TwoPhaseEdited.build(3))
    stock = SpecFingerprint(TwoPhaseSys(rm_count=3))
    assert edited.components["codec"] == stock.components["codec"]
    assert edited.components["constants"] == stock.components["constants"]
    assert edited.components["properties"] != stock.components["properties"]

    # Engine geometry never enters the spec key (results are pinned
    # geometry-invariant by the engine test suites).
    small = SpecFingerprint(
        GridWalk(bound=4), engine_kwargs={"capacity": 1 << 10}
    )
    big = SpecFingerprint(
        GridWalk(bound=4), engine_kwargs={"capacity": 1 << 20}
    )
    assert small.spec_key == big.spec_key
    assert small.components["engine"] != big.components["engine"]

    sym = SpecFingerprint(TwoPhaseSys(rm_count=3), symmetry=True)
    assert sym.spec_key != stock.spec_key
    assert sym.components["symmetry"] != stock.components["symmetry"]


def test_spec_hash_stable_across_processes():
    """The persistence contract: component digests, spec key, and the
    snapshot key must survive a fresh interpreter with a DIFFERENT
    PYTHONHASHSEED (no ``hash()``/dict-order dependence anywhere in the
    recipe), and so must the knob-cache key format."""
    script = (
        "import json\n"
        "from stateright_tpu.incr import SpecFingerprint\n"
        "from stateright_tpu.models.fixtures import GridWalk\n"
        "from stateright_tpu.runtime.knob_cache import knob_key\n"
        "s = SpecFingerprint(GridWalk(bound=5))\n"
        "print(json.dumps({'components': s.components,"
        " 'spec_key': s.spec_key, 'family_key': s.family_key,"
        " 'snapshot_key': s.snapshot_key,"
        " 'knob_key': knob_key('incr-test')}))\n"
    )

    def run(seed):
        env = dict(os.environ)
        env["PYTHONHASHSEED"] = seed
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, env=env, timeout=180,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    a, b = run("1"), run("31337")
    assert a == b
    here = SpecFingerprint(GridWalk(bound=5))
    assert a["spec_key"] == here.spec_key
    assert a["components"] == here.components
    assert a["snapshot_key"] == here.snapshot_key


# --- the four modes -----------------------------------------------------------


def test_verdict_cache_round_trip(store_dir):
    ck, info = _check(GridWalk(bound=4), store_dir)
    assert info["mode"] == COLD
    assert ck.unique_state_count() == 25
    waves_cold = _waves(store_dir)
    assert waves_cold > 0

    ck2, info2 = _check(GridWalk(bound=4), store_dir)
    assert info2["mode"] == IDENTICAL
    assert _waves(store_dir) == waves_cold  # zero new waves
    assert ck2.unique_state_count() == ck.unique_state_count()
    assert ck2.state_count() == ck.state_count()
    assert ck2.max_depth() == ck.max_depth()
    assert sorted(ck2.discoveries()) == sorted(ck.discoveries())
    # The cached path re-executes to the same discovery.
    assert (
        ck2.discoveries()["reaches corner"]
        == ck.discoveries()["reaches corner"]
    )
    assert np.array_equal(
        ck2.discovered_fingerprints(), ck.discovered_fingerprints()
    )
    events = read_journal(_journal(store_dir))
    assert any(e["event"] == "incr_verdict_hit" for e in events)


def test_verdict_cache_serves_violations(store_dir):
    """A stored VIOLATING verdict replays with the counterexample path
    and the counterexample classification intact."""
    ck, info = _check(TrapCounter(limit=5), store_dir,
                      capacity=1 << 10, max_frontier=1 << 5)
    assert info["mode"] == COLD
    assert "reaches limit" in ck.discoveries()

    ck2, info2 = _check(TrapCounter(limit=5), store_dir,
                        capacity=1 << 10, max_frontier=1 << 5)
    assert info2["mode"] == IDENTICAL
    assert ck2.discovery_classification("reaches limit") == "counterexample"
    assert (
        ck2.discoveries()["reaches limit"]
        == ck.discoveries()["reaches limit"]
    )


def test_property_only_recheck_zero_waves_verdict_equal(store_dir):
    _, info = _check(TwoPhaseSys(rm_count=3), store_dir, **TP_KW)
    assert info["mode"] == COLD
    waves_cold = _waves(store_dir)

    ref = TwoPhaseEdited.build(3).checker().spawn_tpu(**TP_KW).join()
    ck, info2 = _check(TwoPhaseEdited.build(3), store_dir, **TP_KW)
    assert info2["mode"] == PROPERTY_ONLY
    assert _waves(store_dir) == waves_cold, "re-eval dispatched waves"
    # Verdict equality vs the from-scratch run of the edited model:
    # same discoveries, same paths, same counts.
    assert sorted(ck.discoveries()) == sorted(ref.discoveries())
    for name, path in ref.discoveries().items():
        assert ck.discoveries()[name] == path, name
    assert ck.unique_state_count() == ref.unique_state_count()
    assert ck.state_count() == ref.state_count()
    assert ck.max_depth() == ref.max_depth()
    events = read_journal(_journal(store_dir))
    assert any(
        e["event"] == "incr_property_recheck" for e in events
    )

    # The edited spec's verdict was itself stored: an identical
    # resubmission of the EDITED model is now an O(1) verdict hit.
    ck3, info3 = _check(TwoPhaseEdited.build(3), store_dir, **TP_KW)
    assert info3["mode"] == IDENTICAL
    assert sorted(ck3.discoveries()) == sorted(ref.discoveries())


def test_constant_widening_fingerprint_bit_equal(store_dir):
    _, info = _check(GridWalk(bound=4), store_dir)
    assert info["mode"] == COLD

    ck, info2 = _check(GridWalk(bound=7), store_dir)
    assert info2["mode"] == CONSTANT_WIDENING
    assert info2["seeded_states"] == 25
    assert ck.unique_state_count() == 64
    cold = GridWalk(bound=7).checker().spawn_tpu(**GRID_KW).join()
    assert np.array_equal(
        ck.discovered_fingerprints(), cold.discovered_fingerprints()
    )
    events = read_journal(_journal(store_dir))
    seeded = [e for e in events if e["event"] == "incr_seeded"]
    assert seeded and seeded[-1]["seeded_states"] == 25
    # The engine journaled a seeded-frontier resume, not a fresh seed.
    assert any(e["event"] == "resume" for e in events)

    # The widened run re-stored: widening again chains off the NEW set.
    ck2, info3 = _check(GridWalk(bound=8), store_dir)
    assert info3["mode"] == CONSTANT_WIDENING
    assert info3["seeded_states"] == 64
    cold2 = GridWalk(bound=8).checker().spawn_tpu(**GRID_KW).join()
    assert np.array_equal(
        ck2.discovered_fingerprints(), cold2.discovered_fingerprints()
    )


# --- widening on the real protocol models (paxos/raft bounds) -----------------


PX_KW = dict(capacity=1 << 12, max_frontier=1 << 6)


def _paxos(client_count, max_round=None):
    from stateright_tpu.actor import Network
    from stateright_tpu.models.paxos import PaxosModelCfg

    return PaxosModelCfg(
        client_count=client_count,
        server_count=3,
        network=Network.new_unordered_nonduplicating(),
        max_round=max_round,
    ).into_model()


def _raft(max_crashes=None):
    from stateright_tpu.models.raft import RaftModelCfg

    return RaftModelCfg(server_count=3, max_crashes=max_crashes).into_model()


def test_paxos_round_bound_spec_components():
    """max_round changes ONLY the constants component — codec,
    properties, and the snapshot key are bound-independent, which is
    exactly what lets the store classify a raise as a widening."""
    base = SpecFingerprint(_paxos(1, max_round=0))
    wide = SpecFingerprint(_paxos(1))
    assert base.spec_key != wide.spec_key
    assert base.family_key == wide.family_key
    assert base.components["codec"] == wide.components["codec"]
    assert base.components["properties"] == wide.components["properties"]
    assert base.components["constants"] != wide.components["constants"]
    assert base.snapshot_key == wide.snapshot_key
    assert wide.compiled.spec_widens(base.constants)
    assert not base.compiled.spec_widens(wide.constants)  # narrowing
    assert not wide.compiled.spec_widens({"max_round": "0"})  # keys gone
    # An explicit cap at the encoding limit hashes like the unbounded
    # default it behaves as (max_round normalization).
    capped = SpecFingerprint(_paxos(1, max_round=15))
    assert capped.components["constants"] == wide.components["constants"]
    # The device boundary exists only when the bound actually prunes,
    # keeping the default model's traced programs byte-identical.
    assert wide.compiled.boundary(np.zeros(
        (wide.compiled.state_width,), np.uint32
    )) is None
    assert base.compiled.boundary(np.zeros(
        (base.compiled.state_width,), np.uint32
    )) is not None
    with pytest.raises(ValueError, match="max_round"):
        SpecFingerprint(_paxos(1, max_round=16))


def test_raft_crash_budget_spec_components():
    """max_crashes is data the step kernel closes over, not codec: a
    frozen-budget raft shares family/codec/properties with the stock
    (budget-1) model and the raise is a declared widening."""
    frozen = SpecFingerprint(_raft(max_crashes=0))
    stock = SpecFingerprint(_raft())
    assert stock.constants["max_crashes"] == "1"  # (n-1)//2 default
    assert frozen.spec_key != stock.spec_key
    assert frozen.family_key == stock.family_key
    assert frozen.components["codec"] == stock.components["codec"]
    assert frozen.components["properties"] == stock.components["properties"]
    assert frozen.components["constants"] != stock.components["constants"]
    assert frozen.snapshot_key == stock.snapshot_key
    assert stock.compiled.spec_widens(frozen.constants)
    assert not frozen.compiled.spec_widens(stock.constants)  # narrowing
    assert not stock.compiled.spec_widens({"max_crashes": "0"})  # keys gone


def test_paxos_round_bound_widening_fingerprint_bit_equal(store_dir):
    """The GridWalk widening acceptance gate on the flagship protocol
    model: a bounded paxos run seeds the unbounded re-check, whose
    discovered set must be bit-equal to a from-scratch run."""
    _, info = _check(_paxos(1, max_round=0), store_dir, **PX_KW)
    assert info["mode"] == COLD

    ck, info2 = _check(_paxos(1), store_dir, **PX_KW)
    assert info2["mode"] == CONSTANT_WIDENING
    assert info2["seeded_states"] == 1  # rounds start at 0: init only
    assert ck.unique_state_count() == 265  # c=1 golden (test_paxos_tpu)
    cold = _paxos(1).checker().spawn_tpu(**PX_KW).join()
    assert np.array_equal(
        ck.discovered_fingerprints(), cold.discovered_fingerprints()
    )
    events = read_journal(_journal(store_dir))
    assert any(e["event"] == "incr_seeded" for e in events)


@pytest.mark.slow
def test_paxos_round_bound_partial_seed_c2(store_dir):
    """Partial seeding at the reference scale: max_round=1 prunes the
    c=2 space to 1,834 of its 16,668 states (examples/paxos.rs:328);
    the widened run seeds from all of them and must reproduce the
    unbounded golden bit-for-bit."""
    kw = dict(capacity=1 << 18, max_frontier=1 << 13)
    _, info = _check(_paxos(2, max_round=1), store_dir, **kw)
    assert info["mode"] == COLD

    ck, info2 = _check(_paxos(2), store_dir, **kw)
    assert info2["mode"] == CONSTANT_WIDENING
    assert info2["seeded_states"] == 1_834
    assert ck.unique_state_count() == 16_668
    cold = _paxos(2).checker().spawn_tpu(**kw).join()
    assert np.array_equal(
        ck.discovered_fingerprints(), cold.discovered_fingerprints()
    )


# --- the degradation matrix ---------------------------------------------------


def _classified(store_dir):
    """The journaled (mode, reason) trail."""
    return [
        (e.get("mode"), e.get("reason", ""))
        for e in read_journal(_journal(store_dir))
        if e.get("event") == "incr_classified"
    ]


def test_degradation_constant_narrowing(store_dir):
    _check(GridWalk(bound=6), store_dir)
    _, info = _check(GridWalk(bound=3), store_dir)
    assert info["mode"] == COLD
    assert "widening" in info["reason"]
    assert _classified(store_dir)[-1][0] == COLD


def test_degradation_codec_change(store_dir):
    _check(GridWalk(bound=4), store_dir)
    # A different model entirely: no shared codec — loud cold.
    _, info = _check(TwoPhaseSys(rm_count=3), store_dir, **TP_KW)
    assert info["mode"] == COLD
    assert "empty store" in info["reason"] or "component" in info["reason"]


def test_degradation_codec_change_same_family(store_dir):
    """rm_count changes the PACKED LAYOUT (action arity), so 2pc(3) vs
    2pc(4) is a codec change, never a widening."""
    _check(TwoPhaseSys(rm_count=3), store_dir, **TP_KW)
    _, info = _check(TwoPhaseSys(rm_count=4), store_dir, **TP_KW)
    assert info["mode"] == COLD
    assert "codec" in info["reason"]


def test_degradation_symmetry_toggle(store_dir):
    _check(TwoPhaseSys(rm_count=3), store_dir, **TP_KW)
    builder = TwoPhaseSys(rm_count=3).checker().symmetry()
    _, info = _check(
        TwoPhaseSys(rm_count=3), store_dir, builder=builder, **TP_KW
    )
    assert info["mode"] == COLD
    assert "symmetry" in info["reason"]


def test_degradation_bounds_change(store_dir):
    _check(GridWalk(bound=4), store_dir)
    builder = GridWalk(bound=4).checker().target_max_depth(3)
    _, info = _check(GridWalk(bound=4), store_dir, builder=builder,
                     **GRID_KW)
    assert info["mode"] == COLD
    assert "bounds" in info["reason"]


def test_degradation_eventually_properties_refused(store_dir):
    """TrapCounter's delta would classify property-only (constants
    equal), but the new set contains EVENTUALLY properties — refused
    with the documented reason, degraded to cold."""
    kw = dict(capacity=1 << 10, max_frontier=1 << 5)
    _check(TrapCounter(limit=5), store_dir, **kw)

    # The "edit": drop the sometimes property (host and device sides in
    # step), leaving the two EVENTUALLY properties.
    from stateright_tpu.models.fixtures import TrapCounterCompiled

    class TrapEditedCompiled(TrapCounterCompiled):
        def property_conds(self, state):
            return TrapCounterCompiled.property_conds(self, state)[:2]

    class TrapEdited(TrapCounter):
        def properties(self):
            return TrapCounter.properties(self)[:2]

        def compiled(self):
            return TrapEditedCompiled(self)

    _, info = _check(TrapEdited(limit=5), store_dir, **kw)
    assert info["mode"] == COLD
    assert "EVENTUALLY" in info["reason"]


def test_degradation_no_exhaustiveness_witness(store_dir):
    """A model whose EVERY property gets discovered stores a
    verdict-cache-only entry: the awaiting gate may have pruned, so a
    property edit must NOT reuse its row log."""
    from dataclasses import dataclass

    from stateright_tpu.models.fixtures import GridWalkCompiled

    @dataclass(frozen=True)
    class CornerOnly(GridWalk):
        def properties(self):
            return [GridWalk.properties(self)[1]]  # sometimes only

        def compiled(self):
            return CornerOnlyCompiled(self)

    class CornerOnlyCompiled(GridWalkCompiled):
        def property_conds(self, state):
            return GridWalkCompiled.property_conds(self, state)[1:]

    ck, info = _check(CornerOnly(bound=4), store_dir)
    assert info["mode"] == COLD
    store = VerificationStore(store_dir)
    entry = store.lookup(SpecFingerprint(CornerOnly(bound=4)))
    assert entry is not None
    assert not entry.rows_reusable
    assert "every property discovered" in entry.record["rows_reason"]

    # The verdict cache still serves it...
    _, info2 = _check(CornerOnly(bound=4), store_dir)
    assert info2["mode"] == IDENTICAL

    # ...but a widening re-check refuses the rows, loudly.
    _, info3 = _check(CornerOnly(bound=6), store_dir)
    assert info3["mode"] == COLD
    assert "not reusable" in info3["reason"]


def test_engine_geometry_change_still_hits(store_dir):
    """Engine knobs are evidence, not identity: the pinned
    geometry-invariance of the engines means a capacity change alone
    still returns the cached verdict."""
    _check(GridWalk(bound=4), store_dir)
    _, info = _check(
        GridWalk(bound=4), store_dir,
        capacity=1 << 14, max_frontier=1 << 8,
    )
    assert info["mode"] == IDENTICAL


def test_unstable_constants_degrade_loudly(store_dir):
    """A model with neither dataclass fields nor a spec_constants()
    override must never take a reuse path."""

    class Opaque(TrapCounter):
        def compiled(self):
            from stateright_tpu.models.fixtures import TrapCounterCompiled

            cm = TrapCounterCompiled(self)
            cm.spec_constants = lambda: None
            return cm

    kw = dict(capacity=1 << 10, max_frontier=1 << 5)
    _check(Opaque(limit=5), store_dir, **kw)
    _, info = _check(Opaque(limit=6), store_dir, **kw)
    assert info["mode"] == COLD
    assert "spec_constants" in info["reason"]


def test_partial_run_never_enters_verdict_cache(store_dir):
    """A truncated run (target_state_count here; the same gate covers
    wall timeouts and cooperative stops) must NOT store a verdict: its
    "nothing found" claims cover only the explored prefix, and the
    truncating knob is deliberately outside the spec hash."""
    builder = GridWalk(bound=6).checker().target_state_count(10)
    ck, info = _check(GridWalk(bound=6), store_dir, builder=builder,
                      **GRID_KW)
    assert info["mode"] == COLD
    assert ck.unique_state_count() < 49  # genuinely truncated
    store = VerificationStore(store_dir)
    assert store.entries() == []
    events = read_journal(_journal(store_dir))
    skips = [e for e in events if e["event"] == "incr_store_skipped"]
    assert skips and "partial" in skips[-1]["reason"]


def test_code_digest_sees_defaults_closures_and_sets():
    """The one-line edits code_digest must catch beyond co_code: a
    changed default argument and a changed captured value; and set
    literals must digest PYTHONHASHSEED-independently (sorted fold,
    not hash-ordered repr)."""
    from stateright_tpu.incr.spec_hash import code_digest

    def mk_default(k=5):
        def cond(_m, s, bound=k):
            return s <= bound

        return cond

    assert code_digest(mk_default(5)) == code_digest(mk_default(5))
    assert code_digest(mk_default(5)) != code_digest(mk_default(4))

    def mk_closure(k):
        return lambda _m, s: s <= k

    assert code_digest(mk_closure(5)) == code_digest(mk_closure(5))
    assert code_digest(mk_closure(5)) != code_digest(mk_closure(4))

    def set_cond(_m, s):
        return s in {"a", "b", "c"}

    script = (
        "from stateright_tpu.incr.spec_hash import code_digest\n"
        "def set_cond(_m, s):\n"
        "    return s in {'a', 'b', 'c'}\n"
        "print(code_digest(set_cond))\n"
    )
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = "424242"
    out = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-1000:]
    assert out.stdout.strip() == code_digest(set_cond)


def test_code_digest_folds_module_level_helpers():
    """Editing a shared module-level helper a condition CALLS is a
    one-line model edit too: the digest folds referenced globals'
    code, so the edit is visible even though the caller's own bytecode
    is unchanged."""
    from stateright_tpu.incr.spec_hash import code_digest

    def make(delta):
        mod = {}
        exec(
            "def helper(s):\n"
            f"    return s + {delta}\n"
            "def cond(_m, s):\n"
            "    return helper(s) > 3\n",
            mod,
        )
        return mod["cond"]

    assert code_digest(make(1)) == code_digest(make(1))
    assert code_digest(make(1)) != code_digest(make(2))


def _grid_variant(name, props_fn, conds_slice):
    """A GridWalk property variant: same codec+constants, edited
    property set (device side sliced to match)."""
    from dataclasses import dataclass

    from stateright_tpu.models.fixtures import GridWalkCompiled

    class _Compiled(GridWalkCompiled):
        def property_conds(self, state):
            return GridWalkCompiled.property_conds(self, state)[conds_slice]

    @dataclass(frozen=True)
    class _Variant(GridWalk):
        def properties(self):
            return props_fn(self)

        def compiled(self):
            return _Compiled(self)

    _Variant.__qualname__ = name
    return _Variant


def test_classify_tries_older_relatives_past_ineligible_newest(store_dir):
    """A NEWER sibling whose rows are ineligible (every property
    discovered — no exhaustiveness witness) must not shadow an older
    reusable entry: classification walks relatives newest-first until
    one passes the gate."""
    CornerOnly = _grid_variant(
        "CornerOnly", lambda m: [GridWalk.properties(m)[1]], slice(1, 2)
    )
    BoundsOnly = _grid_variant(
        "BoundsOnly", lambda m: [GridWalk.properties(m)[0]], slice(0, 1)
    )
    # Older reusable entry (A), then a newer non-reusable sibling (B).
    _check(GridWalk(bound=4), store_dir, reuse=False)
    _check(CornerOnly(bound=4), store_dir, reuse=False)
    store = VerificationStore(store_dir)
    by_reusable = {
        e.rows_reusable: e for e in store.entries()
    }
    assert set(by_reusable) == {True, False}

    ck, info = _check(BoundsOnly(bound=4), store_dir)
    assert info["mode"] == PROPERTY_ONLY, info
    assert info["entry"] == by_reusable[True].entry_id
    assert ck.discoveries() == {}  # the always property holds


def test_reuse_disabled_records_only(store_dir):
    _check(GridWalk(bound=4), store_dir, reuse=False)
    _, info = _check(GridWalk(bound=4), store_dir, reuse=False)
    assert info["mode"] == COLD
    assert "reuse disabled" in info["reason"]
    # The entries are there: turning reuse on hits immediately.
    _, info2 = _check(GridWalk(bound=4), store_dir)
    assert info2["mode"] == IDENTICAL


def test_classify_serves_from_index_without_reparsing_verdicts(store_dir):
    """The entry index (index.json, ISSUE 14 / ROADMAP #5 remainder):
    classification scales with the INDEX, not the store.  On an index
    hit, classify() parses ZERO per-entry verdict.json records for the
    family scan — only the exact-match lookup (identical hit) costs one
    parse — pinned via the store's ``verdict_reads`` counter."""
    _check(GridWalk(bound=4), store_dir, reuse=False)
    _check(GridWalk(bound=5), store_dir, reuse=False)

    # Fresh instance, warm index: a family (widening) classification
    # walks the entries entirely from index.json.
    store = VerificationStore(store_dir)
    delta = store.classify(SpecFingerprint(
        GridWalk(bound=6), engine_kwargs=dict(GRID_KW),
    ))
    assert delta.mode == CONSTANT_WIDENING
    assert store.verdict_reads == 0, (
        "family scan re-parsed per-entry verdict.json despite the index"
    )
    # The identical hit is the one documented per-entry parse (the
    # content-addressed exact-match lookup).
    delta = store.classify(SpecFingerprint(
        GridWalk(bound=5), engine_kwargs=dict(GRID_KW),
    ))
    assert delta.mode == IDENTICAL
    assert store.verdict_reads == 1

    # A missing/foreign index rebuilds ONCE (one parse per entry), then
    # serves from the rebuilt index again.
    os.remove(os.path.join(store_dir, "index.json"))
    store2 = VerificationStore(store_dir)
    delta = store2.classify(SpecFingerprint(
        GridWalk(bound=6), engine_kwargs=dict(GRID_KW),
    ))
    assert delta.mode == CONSTANT_WIDENING
    assert store2.verdict_reads == 2  # the rebuild's one-scan, 2 entries
    assert os.path.exists(os.path.join(store_dir, "index.json"))
    store3 = VerificationStore(store_dir)
    store3.classify(SpecFingerprint(
        GridWalk(bound=6), engine_kwargs=dict(GRID_KW),
    ))
    assert store3.verdict_reads == 0


# --- ColdStore lifecycle (satellite: disk-tier reuse) -------------------------


def test_cold_store_no_clobber_on_existing_dir(tmp_path):
    d = str(tmp_path / "cold")
    a = ColdStore(spill_dir=d)
    a.add_run(np.array([1, 2, 3], np.uint64))
    first = sorted(os.listdir(d))
    # A SECOND store on the same directory continues the sequence
    # instead of overwriting cold_run_1.npy.
    b = ColdStore(spill_dir=d)
    b.add_run(np.array([7, 8], np.uint64))
    assert sorted(os.listdir(d)) > first
    assert first[0] in os.listdir(d)
    np.testing.assert_array_equal(
        np.load(os.path.join(d, first[0])), [1, 2, 3]
    )


def test_cold_store_from_arrays_cleans_stale(tmp_path):
    d = str(tmp_path / "cold")
    a = ColdStore(spill_dir=d)
    a.add_run(np.array([1, 2, 3], np.uint64))
    a.add_run(np.array([9], np.uint64))
    fps, lens = a.to_arrays()
    a.close()
    b = ColdStore.from_arrays(fps, lens, spill_dir=d)
    # The restored runs hold the same data under fresh names; the dead
    # process's files are gone (no orphan accumulation across resumes).
    assert b.run_count == 2
    assert b.entries == 4
    on_disk = [f for f in os.listdir(d) if f.endswith(".npy")]
    assert len(on_disk) == 2
    hit = b.contains(np.array([1, 9, 5], np.uint64))
    np.testing.assert_array_equal(hit, [True, True, False])


def test_cold_store_open_and_close(tmp_path):
    d = str(tmp_path / "cold")
    a = ColdStore(spill_dir=d)
    a.add_run(np.array([4, 5], np.uint64))
    a.add_run(np.array([1], np.uint64))
    a.close()
    assert a.run_count == 0  # maps released
    b = ColdStore.open(d)
    assert b.run_count == 2
    assert b.entries == 3
    hit = b.contains(np.array([5, 2], np.uint64))
    np.testing.assert_array_equal(hit, [True, False])
    # No stray temp files from the fsync'd append path.
    assert not [f for f in os.listdir(d) if f.endswith(".tmp")]


# --- serve surface ------------------------------------------------------------


@pytest.mark.slow
def test_serve_store_jobs(tmp_path):
    from stateright_tpu.serve.server import CheckService

    store_dir = str(tmp_path / "store")
    svc = CheckService(
        journal=str(tmp_path / "journal.jsonl"), store_dir=store_dir,
        knob_cache_dir=str(tmp_path / "knobs"),
    )
    try:
        j1 = svc.submit({"workload": "twophase", "n": 3, "store": True})
        assert j1.wait(300) and j1.state == "done", (j1.state, j1.error)
        assert j1.result["recheck_mode"] == "cold"
        assert j1.result["unique_state_count"] == 288
        # The knob cache composes with the store: the cold run's final
        # geometry was persisted for the next cold-classified repeat.
        assert j1.result["knob_cache_hit"] is False
        from stateright_tpu.runtime.knob_cache import knob_key, load_knobs
        from stateright_tpu.serve.workloads import workload_label

        key = knob_key(workload_label("twophase", 3, None, False))
        assert load_knobs(str(tmp_path / "knobs"), key)

        j2 = svc.submit({"workload": "twophase", "n": 3, "store": True})
        assert j2.wait(60) and j2.state == "done", (j2.state, j2.error)
        assert j2.result["recheck_mode"] == "identical"
        assert j2.result["unique_state_count"] == 288

        m = svc.metrics()
        assert m["verdict_cache_hits"] == 1
        assert m["recheck_cold"] == 1

        with pytest.raises(ValueError):
            svc.submit({
                "workload": "twophase", "store": True,
                "portfolio": {"size": 2},
            })
        with pytest.raises(ValueError):
            svc.submit({
                "workload": "twophase", "store": True, "engine": "bfs",
            })
    finally:
        svc.scheduler.shutdown()


def test_store_requires_store_dir(tmp_path):
    """A store job against a service started without --store-dir is
    rejected at SUBMIT time (HTTP 400 through the server), and the
    scheduler-level belt fails loudly too instead of silently running
    un-stored."""
    from stateright_tpu.serve.jobs import JobSpec, JobStore
    from stateright_tpu.serve.scheduler import Scheduler
    from stateright_tpu.serve.server import CheckService

    svc = CheckService()
    try:
        with pytest.raises(ValueError, match="store-dir"):
            svc.submit({"workload": "fixtures", "n": 3, "store": True})
    finally:
        svc.scheduler.shutdown()

    sched = Scheduler(JobStore())
    try:
        job = sched.submit(JobSpec(workload="fixtures", n=3, store=True))
        assert job.wait(120)
        assert job.state == "failed"
        assert "store" in (job.error or "")
    finally:
        sched.shutdown()


# --- CLI ----------------------------------------------------------------------


def test_cli_store_flags_validation(capsys):
    from stateright_tpu.models.twophase import main as tp_main

    assert tp_main(["check-tpu", "3", "--incremental"]) == 2
    assert "--store-dir" in capsys.readouterr().err
    assert tp_main(["check", "3", "--store-dir", "/tmp/x"]) == 2
    assert "check-tpu" in capsys.readouterr().err
    assert tp_main(
        ["check-tpu", "3", "--store-dir", "/tmp/x", "--tiered"]
    ) == 2
    assert "does not combine" in capsys.readouterr().err


@pytest.mark.slow
def test_cli_incremental_end_to_end(tmp_path, capsys):
    from stateright_tpu.models.fixtures import main as fx_main
    from stateright_tpu.runtime.supervisor import VIOLATION_RC

    store = str(tmp_path / "store")
    # TrapCounter violates: the verdict (and its VIOLATION_RC exit)
    # must survive the cache round trip.
    rc1 = fx_main(["check-tpu", "5", "--store-dir", store, "--incremental"])
    out1 = capsys.readouterr().out
    assert rc1 == VIOLATION_RC
    line1 = [ln for ln in out1.splitlines() if ln.startswith("recheck: ")]
    assert json.loads(line1[-1][len("recheck: "):])["mode"] == "cold"

    rc2 = fx_main(["check-tpu", "5", "--store-dir", store, "--incremental"])
    out2 = capsys.readouterr().out
    assert rc2 == VIOLATION_RC
    line2 = [ln for ln in out2.splitlines() if ln.startswith("recheck: ")]
    assert (
        json.loads(line2[-1][len("recheck: "):])["mode"] == "identical"
    )


# --- watch / report rendering -------------------------------------------------


def test_watch_and_report_render_incr_events(store_dir):
    _check(GridWalk(bound=4), store_dir)
    _check(GridWalk(bound=4), store_dir)
    from stateright_tpu.obs.report import analyze_journal, render_markdown
    from stateright_tpu.obs.watch import render_line, summarize_events

    events = read_journal(_journal(store_dir))
    s = summarize_events(events)
    assert s["recheck"] == IDENTICAL
    assert s["verdict_hits"] == 1
    assert "recheck=identical" in render_line(s)
    report = analyze_journal(_journal(store_dir))
    incr = report["incremental"]
    assert incr["modes"] == {"cold": 1, "identical": 1}
    assert incr["verdict_hits"] == 1
    assert "Incremental re-checking" in render_markdown(report)
