"""Direct unit tests for the hash set's geometry helpers
(parallel/hashset.py): ``unique_buffer_size`` is THE compaction-buffer
width every overflow criterion and byte model derives from, and
``prededup`` / ``compact_valid`` / ``compact_valid_indices`` are the
device stages the tiered engine's eviction-threshold math builds on —
edge cases at ``dedup_factor=1`` and at full buffers were previously
only covered through whole-engine goldens."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.parallel.hashset import (  # noqa: E402
    compact_valid,
    compact_valid_indices,
    insert_batch,
    make_hashset,
    prededup,
    unique_buffer_size,
)


# --- unique_buffer_size: the single width definition -------------------------


def test_unique_buffer_size_dedup_factor_one_covers_whole_batch():
    # dd=1 is the always-safe geometry: the buffer spans every lane, so
    # the overflow criterion (n > size) can never fire.
    for b in (1, 7, 1 << 10, 1 << 14, 1 << 17):
        assert unique_buffer_size(b, 1) == b


def test_unique_buffer_size_floor_and_division():
    # Small batches: the min(B, 16K) floor wins over B/dd.
    assert unique_buffer_size(1 << 10, 4) == 1 << 10
    assert unique_buffer_size(1 << 14, 8) == 1 << 14
    # Past the 16K floor the division takes over.
    assert unique_buffer_size(1 << 17, 4) == 1 << 15
    assert unique_buffer_size(1 << 17, 8) == 1 << 14
    # Integer division truncates, never rounds up.
    assert unique_buffer_size(100_000, 3) == 100_000 // 3


def test_unique_buffer_size_monotone_in_dedup_factor():
    b = 1 << 17
    prev = b + 1
    for dd in (1, 2, 4, 8, 16):
        u = unique_buffer_size(b, dd)
        assert u <= prev
        prev = u


# --- prededup ----------------------------------------------------------------


def _keys(vals):
    """uint64 test keys split into (hi, lo) planes."""
    vals = np.asarray(vals, np.uint64)
    return (
        jnp.asarray((vals >> np.uint64(32)).astype(np.uint32)),
        jnp.asarray(vals.astype(np.uint32)),
    )


def test_prededup_elects_lowest_lane_in_sorted_key_order():
    hi, lo = _keys([30, 10, 30, 20, 10, 10, 40, 20])
    active = jnp.ones((8,), jnp.bool_)
    u_hi, u_lo, u_origin, u_active, overflow = prededup(hi, lo, active, 1)
    n = int(jnp.sum(u_active))
    assert n == 4 and not bool(overflow)
    keys = (
        np.asarray(u_hi[:n]).astype(np.uint64) << np.uint64(32)
    ) | np.asarray(u_lo[:n]).astype(np.uint64)
    assert keys.tolist() == [10, 20, 30, 40]  # sorted key order
    # The representative is the LOWEST original lane of each run — the
    # first-inserter ebits semantics depend on it.
    assert np.asarray(u_origin[:n]).tolist() == [1, 3, 0, 6]


def test_prededup_full_buffer_all_distinct_dd1_no_overflow():
    # dd=1, every lane active and distinct: the buffer is exactly full —
    # the boundary the overflow comparison (> not >=) must not trip.
    b = 64
    hi, lo = _keys(np.arange(1, b + 1, dtype=np.uint64))
    u_hi, u_lo, u_origin, u_active, overflow = prededup(
        hi, lo, jnp.ones((b,), jnp.bool_), 1
    )
    assert not bool(overflow)
    assert int(jnp.sum(u_active)) == b
    assert np.asarray(u_origin).tolist() == list(range(b))


def test_prededup_overflow_fires_past_buffer():
    # More distinct keys than the dd-shrunk buffer holds: loud flag.
    # (The buffer floors at min(B, 16K), so B must exceed 16K lanes.)
    b = 1 << 15
    dd = 4
    u = unique_buffer_size(b, dd)
    assert u < b
    hi, lo = _keys(np.arange(1, b + 1, dtype=np.uint64))
    *_rest, overflow = prededup(hi, lo, jnp.ones((b,), jnp.bool_), dd)
    assert bool(overflow)


def test_prededup_inactive_lanes_do_not_count():
    hi, lo = _keys([5, 6, 7, 8])
    active = jnp.asarray([True, False, True, False])
    u_hi, u_lo, u_origin, u_active, overflow = prededup(hi, lo, active, 1)
    assert int(jnp.sum(u_active)) == 2
    assert not bool(overflow)


# --- compact_valid / compact_valid_indices -----------------------------------


def test_compact_valid_identity_at_dd1_full_valid():
    # Every lane valid at dd=1: compaction is the identity permutation
    # and the VALID-lane overflow criterion sits exactly at the boundary.
    b = 128
    hi, lo = _keys(np.arange(1, b + 1, dtype=np.uint64))
    valid = jnp.ones((b,), jnp.bool_)
    v_hi, v_lo, v_orig, v_act, overflow = compact_valid(hi, lo, valid, 1)
    assert not bool(overflow)
    assert int(jnp.sum(v_act)) == b
    assert np.asarray(v_orig).tolist() == list(range(b))
    assert np.array_equal(np.asarray(v_hi), np.asarray(hi))


def test_compact_valid_overflow_on_valid_count():
    # The criterion counts VALID lanes (stricter than distinct keys): a
    # duplicate-heavy batch must still trip it when valid > buffer.
    b = 1 << 15
    dd = 4
    vals = np.ones((b,), np.uint64)  # ONE distinct key, all lanes valid
    hi, lo = _keys(vals)
    *_rest, overflow = compact_valid(hi, lo, jnp.ones((b,), jnp.bool_), dd)
    assert bool(overflow)


def test_compact_valid_indices_matches_compact_valid():
    # The index-only variant (two-phase engines) must pick the same
    # lanes in the same order as the key-compacting one.
    rng = np.random.default_rng(7)
    b = 256
    vals = rng.integers(1, 1 << 40, size=b, dtype=np.uint64)
    valid_np = rng.random(b) < 0.3
    hi, lo = _keys(vals)
    valid = jnp.asarray(valid_np)
    v_hi, v_lo, v_orig, v_act, ovf = compact_valid(hi, lo, valid, 4)
    i_orig, i_act, n_valid, i_ovf = compact_valid_indices(valid, 4)
    assert bool(ovf) == bool(i_ovf) is False
    assert int(n_valid) == int(valid_np.sum())
    n = int(n_valid)
    assert np.array_equal(np.asarray(v_orig)[:n], np.asarray(i_orig)[:n])
    assert np.array_equal(np.asarray(v_act), np.asarray(i_act))
    # And the gathered keys really are the valid lanes' keys, in order.
    assert np.asarray(v_hi)[:n].tolist() == [
        int(v >> np.uint64(32)) for v in vals[valid_np]
    ]


def test_compact_valid_zero_valid_lanes():
    b = 64
    hi, lo = _keys(np.arange(1, b + 1, dtype=np.uint64))
    v_hi, v_lo, v_orig, v_act, overflow = compact_valid(
        hi, lo, jnp.zeros((b,), jnp.bool_), 1
    )
    assert not bool(overflow)
    assert int(jnp.sum(v_act)) == 0


# --- load_factor: the cheap occupancy readback -------------------------------


def test_load_factor_readback():
    t = make_hashset(1 << 10)
    assert t.load_factor() == 0.0
    vals = np.arange(1, 129, dtype=np.uint64)
    hi, lo = _keys(vals)
    t, _slot, is_new, probe_ok, _ovf = insert_batch(
        t, hi, lo, jnp.ones((128,), jnp.bool_), dedup_factor=1
    )
    assert bool(probe_ok)
    assert int(jnp.sum(is_new)) == 128
    assert t.load_factor() == pytest.approx(128 / 1024)
    # Re-inserting the same keys adds no occupancy.
    t, _slot, is_new, probe_ok, _ovf = insert_batch(
        t, hi, lo, jnp.ones((128,), jnp.bool_), dedup_factor=1
    )
    assert int(jnp.sum(is_new)) == 0
    assert t.load_factor() == pytest.approx(128 / 1024)
