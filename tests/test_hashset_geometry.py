"""Direct unit tests for the hash set's geometry helpers
(parallel/hashset.py): ``unique_buffer_size`` is THE compaction-buffer
width every overflow criterion and byte model derives from, and
``prededup`` / ``compact_valid`` / ``compact_valid_indices`` are the
device stages the tiered engine's eviction-threshold math builds on —
edge cases at ``dedup_factor=1`` and at full buffers were previously
only covered through whole-engine goldens."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.parallel.hashset import (  # noqa: E402
    compact_valid,
    compact_valid_indices,
    insert_batch,
    make_hashset,
    prededup,
    unique_buffer_size,
)


# --- unique_buffer_size: the single width definition -------------------------


def test_unique_buffer_size_dedup_factor_one_covers_whole_batch():
    # dd=1 is the always-safe geometry: the buffer spans every lane, so
    # the overflow criterion (n > size) can never fire.
    for b in (1, 7, 1 << 10, 1 << 14, 1 << 17):
        assert unique_buffer_size(b, 1) == b


def test_unique_buffer_size_floor_and_division():
    # Small batches: the min(B, 16K) floor wins over B/dd.
    assert unique_buffer_size(1 << 10, 4) == 1 << 10
    assert unique_buffer_size(1 << 14, 8) == 1 << 14
    # Past the 16K floor the division takes over.
    assert unique_buffer_size(1 << 17, 4) == 1 << 15
    assert unique_buffer_size(1 << 17, 8) == 1 << 14
    # Integer division truncates, never rounds up.
    assert unique_buffer_size(100_000, 3) == 100_000 // 3


def test_unique_buffer_size_monotone_in_dedup_factor():
    b = 1 << 17
    prev = b + 1
    for dd in (1, 2, 4, 8, 16):
        u = unique_buffer_size(b, dd)
        assert u <= prev
        prev = u


# --- prededup ----------------------------------------------------------------


def _keys(vals):
    """uint64 test keys split into (hi, lo) planes."""
    vals = np.asarray(vals, np.uint64)
    return (
        jnp.asarray((vals >> np.uint64(32)).astype(np.uint32)),
        jnp.asarray(vals.astype(np.uint32)),
    )


def test_prededup_elects_lowest_lane_in_sorted_key_order():
    hi, lo = _keys([30, 10, 30, 20, 10, 10, 40, 20])
    active = jnp.ones((8,), jnp.bool_)
    u_hi, u_lo, u_origin, u_active, overflow = prededup(hi, lo, active, 1)
    n = int(jnp.sum(u_active))
    assert n == 4 and not bool(overflow)
    keys = (
        np.asarray(u_hi[:n]).astype(np.uint64) << np.uint64(32)
    ) | np.asarray(u_lo[:n]).astype(np.uint64)
    assert keys.tolist() == [10, 20, 30, 40]  # sorted key order
    # The representative is the LOWEST original lane of each run — the
    # first-inserter ebits semantics depend on it.
    assert np.asarray(u_origin[:n]).tolist() == [1, 3, 0, 6]


def test_prededup_full_buffer_all_distinct_dd1_no_overflow():
    # dd=1, every lane active and distinct: the buffer is exactly full —
    # the boundary the overflow comparison (> not >=) must not trip.
    b = 64
    hi, lo = _keys(np.arange(1, b + 1, dtype=np.uint64))
    u_hi, u_lo, u_origin, u_active, overflow = prededup(
        hi, lo, jnp.ones((b,), jnp.bool_), 1
    )
    assert not bool(overflow)
    assert int(jnp.sum(u_active)) == b
    assert np.asarray(u_origin).tolist() == list(range(b))


def test_prededup_overflow_fires_past_buffer():
    # More distinct keys than the dd-shrunk buffer holds: loud flag.
    # (The buffer floors at min(B, 16K), so B must exceed 16K lanes.)
    b = 1 << 15
    dd = 4
    u = unique_buffer_size(b, dd)
    assert u < b
    hi, lo = _keys(np.arange(1, b + 1, dtype=np.uint64))
    *_rest, overflow = prededup(hi, lo, jnp.ones((b,), jnp.bool_), dd)
    assert bool(overflow)


def test_prededup_inactive_lanes_do_not_count():
    hi, lo = _keys([5, 6, 7, 8])
    active = jnp.asarray([True, False, True, False])
    u_hi, u_lo, u_origin, u_active, overflow = prededup(hi, lo, active, 1)
    assert int(jnp.sum(u_active)) == 2
    assert not bool(overflow)


# --- the overflow-criterion pair (a pinned contract) --------------------------
#
# compact_valid / compact_valid_indices flag on the VALID-LANE count;
# prededup flags on the DISTINCT-REPRESENTATIVE count.  Both comparisons
# are strict (> not >=): exactly-full commits, one past trips.  The
# engines' flag 4 (and the sort-rung ladder's retry criterion) derive
# from these two, so the boundary is pinned here at exactly-u_sz and
# u_sz+1 for BOTH.


def test_compact_valid_overflow_boundary_exact_and_plus_one():
    b = 1 << 15
    dd = 4
    v_sz = unique_buffer_size(b, dd)
    assert v_sz < b
    hi, lo = _keys(np.arange(1, b + 1, dtype=np.uint64))
    exactly = jnp.asarray(np.arange(b) < v_sz)
    *_r, ovf = compact_valid(hi, lo, exactly, dd)
    assert not bool(ovf)
    *_r, i_ovf = compact_valid_indices(exactly, dd)
    assert not bool(i_ovf)
    plus_one = jnp.asarray(np.arange(b) < v_sz + 1)
    *_r, ovf = compact_valid(hi, lo, plus_one, dd)
    assert bool(ovf)
    *_r, i_ovf = compact_valid_indices(plus_one, dd)
    assert bool(i_ovf)


def test_compact_valid_counts_valid_lanes_not_distinct_keys():
    # ONE distinct key on v_sz+1 valid lanes still trips the flag: the
    # criterion is valid lanes, deliberately stricter than distinct
    # keys (the compaction buffer must hold every valid lane BEFORE the
    # dedup sort can collapse duplicates).
    b = 1 << 15
    dd = 4
    v_sz = unique_buffer_size(b, dd)
    hi, lo = _keys(np.ones((b,), np.uint64))
    valid = jnp.asarray(np.arange(b) < v_sz + 1)
    *_r, ovf = compact_valid(hi, lo, valid, dd)
    assert bool(ovf)


def test_prededup_overflow_boundary_exact_and_plus_one():
    # Distinct-representative criterion at the same u_sz boundary:
    # exactly u distinct keys (each on TWO valid lanes — twice the
    # buffer in valid lanes) commits; u+1 distinct keys trips.  The
    # duplicate-heavy exactly-full case is precisely where the two
    # criteria diverge: compact_valid WOULD flag this batch.
    b = 1 << 15
    dd = 4
    u = unique_buffer_size(b, dd)
    vals = np.repeat(np.arange(1, u + 1, dtype=np.uint64), b // u)
    hi, lo = _keys(vals)
    active = jnp.ones((b,), jnp.bool_)
    *_r, ovf = prededup(hi, lo, active, dd)
    assert not bool(ovf)
    *_r, cv_ovf = compact_valid(hi, lo, active, dd)
    assert bool(cv_ovf)  # the stricter valid-lane criterion fires
    vals_plus = vals.copy()
    vals_plus[-1] = np.uint64(u + 1)  # u+1 distinct keys
    hi, lo = _keys(vals_plus)
    *_r, ovf = prededup(hi, lo, active, dd)
    assert bool(ovf)


# --- the sort_lanes rung (wave_loop.py's sort-geometry ladder) ----------------


def test_sort_lanes_rung_shrinks_buffers_and_boundary():
    b = 1 << 12
    rung = 256
    hi, lo = _keys(np.arange(1, b + 1, dtype=np.uint64))
    exactly = jnp.asarray(np.arange(b) < rung)
    v_hi, v_lo, v_orig, v_act, ovf = compact_valid(
        hi, lo, exactly, 1, sort_lanes=rung
    )
    assert v_hi.shape[0] == rung  # the buffer IS the rung
    assert not bool(ovf)
    i_orig, i_act, n_valid, i_ovf = compact_valid_indices(
        exactly, 1, sort_lanes=rung
    )
    assert i_orig.shape[0] == rung and not bool(i_ovf)
    plus_one = jnp.asarray(np.arange(b) < rung + 1)
    *_r, ovf = compact_valid(hi, lo, plus_one, 1, sort_lanes=rung)
    assert bool(ovf)
    u_hi, u_lo, u_origin, u_active, p_ovf = prededup(
        hi, lo, plus_one, 1, sort_lanes=rung
    )
    assert u_hi.shape[0] == rung
    assert bool(p_ovf)  # rung+1 distinct representatives


def test_sort_lanes_rung_results_match_full_buffer_prefix():
    # A rung that holds the batch is invisible: the compacted prefix —
    # keys, origins, representatives — is bit-identical to the full
    # worst-case buffer's (the discovery-set bit-equality gate, at the
    # unit level).
    rng = np.random.default_rng(12)
    b = 1 << 12
    rung = 512
    vals = rng.integers(1, 1 << 40, size=b, dtype=np.uint64)
    valid_np = rng.random(b) < 0.05  # ~200 valid lanes, under the rung
    hi, lo = _keys(vals)
    valid = jnp.asarray(valid_np)
    full = compact_valid(hi, lo, valid, 1)
    slim = compact_valid(hi, lo, valid, 1, sort_lanes=rung)
    n = int(valid_np.sum())
    assert not bool(full[-1]) and not bool(slim[-1])
    for fu, sl in zip(full[:-1], slim[:-1]):
        assert np.array_equal(np.asarray(fu)[:n], np.asarray(sl)[:n])
    pfull = prededup(hi, lo, valid, 1)
    pslim = prededup(hi, lo, valid, 1, sort_lanes=rung)
    k = int(jnp.sum(pfull[3]))
    assert int(jnp.sum(pslim[3])) == k
    for fu, sl in zip(pfull[:-1], pslim[:-1]):
        assert np.array_equal(np.asarray(fu)[:k], np.asarray(sl)[:k])


def test_insert_batch_compact_sort_lanes_same_table():
    # Insert-if-absent through a rung-sized buffer lands the same table
    # contents as the full-buffer insert when distinct keys fit the rung.
    rng = np.random.default_rng(3)
    b = 1 << 10
    rung = 256
    vals = rng.integers(1, 1 << 40, size=rung // 2, dtype=np.uint64)
    vals = np.concatenate([vals] * (b // vals.shape[0]))  # duplicates
    hi, lo = _keys(vals)
    active = jnp.ones((b,), jnp.bool_)
    from stateright_tpu.parallel.hashset import insert_batch_compact

    t0, *_r0, ok0, ovf0 = insert_batch_compact(
        make_hashset(1 << 12), hi, lo, active, dedup_factor=1
    )
    t1, *_r1, ok1, ovf1 = insert_batch_compact(
        make_hashset(1 << 12), hi, lo, active, dedup_factor=1,
        sort_lanes=rung,
    )
    assert bool(ok0) and bool(ok1)
    assert not bool(ovf0) and not bool(ovf1)
    assert np.array_equal(np.asarray(t0.key_hi), np.asarray(t1.key_hi))
    assert np.array_equal(np.asarray(t0.key_lo), np.asarray(t1.key_lo))


# --- compact_valid / compact_valid_indices -----------------------------------


def test_compact_valid_identity_at_dd1_full_valid():
    # Every lane valid at dd=1: compaction is the identity permutation
    # and the VALID-lane overflow criterion sits exactly at the boundary.
    b = 128
    hi, lo = _keys(np.arange(1, b + 1, dtype=np.uint64))
    valid = jnp.ones((b,), jnp.bool_)
    v_hi, v_lo, v_orig, v_act, overflow = compact_valid(hi, lo, valid, 1)
    assert not bool(overflow)
    assert int(jnp.sum(v_act)) == b
    assert np.asarray(v_orig).tolist() == list(range(b))
    assert np.array_equal(np.asarray(v_hi), np.asarray(hi))


def test_compact_valid_overflow_on_valid_count():
    # The criterion counts VALID lanes (stricter than distinct keys): a
    # duplicate-heavy batch must still trip it when valid > buffer.
    b = 1 << 15
    dd = 4
    vals = np.ones((b,), np.uint64)  # ONE distinct key, all lanes valid
    hi, lo = _keys(vals)
    *_rest, overflow = compact_valid(hi, lo, jnp.ones((b,), jnp.bool_), dd)
    assert bool(overflow)


def test_compact_valid_indices_matches_compact_valid():
    # The index-only variant (two-phase engines) must pick the same
    # lanes in the same order as the key-compacting one.
    rng = np.random.default_rng(7)
    b = 256
    vals = rng.integers(1, 1 << 40, size=b, dtype=np.uint64)
    valid_np = rng.random(b) < 0.3
    hi, lo = _keys(vals)
    valid = jnp.asarray(valid_np)
    v_hi, v_lo, v_orig, v_act, ovf = compact_valid(hi, lo, valid, 4)
    i_orig, i_act, n_valid, i_ovf = compact_valid_indices(valid, 4)
    assert bool(ovf) == bool(i_ovf) is False
    assert int(n_valid) == int(valid_np.sum())
    n = int(n_valid)
    assert np.array_equal(np.asarray(v_orig)[:n], np.asarray(i_orig)[:n])
    assert np.array_equal(np.asarray(v_act), np.asarray(i_act))
    # And the gathered keys really are the valid lanes' keys, in order.
    assert np.asarray(v_hi)[:n].tolist() == [
        int(v >> np.uint64(32)) for v in vals[valid_np]
    ]


def test_compact_valid_zero_valid_lanes():
    b = 64
    hi, lo = _keys(np.arange(1, b + 1, dtype=np.uint64))
    v_hi, v_lo, v_orig, v_act, overflow = compact_valid(
        hi, lo, jnp.zeros((b,), jnp.bool_), 1
    )
    assert not bool(overflow)
    assert int(jnp.sum(v_act)) == 0


# --- load_factor: the cheap occupancy readback -------------------------------


def test_load_factor_readback():
    t = make_hashset(1 << 10)
    assert t.load_factor() == 0.0
    vals = np.arange(1, 129, dtype=np.uint64)
    hi, lo = _keys(vals)
    t, _slot, is_new, probe_ok, _ovf = insert_batch(
        t, hi, lo, jnp.ones((128,), jnp.bool_), dedup_factor=1
    )
    assert bool(probe_ok)
    assert int(jnp.sum(is_new)) == 128
    assert t.load_factor() == pytest.approx(128 / 1024)
    # Re-inserting the same keys adds no occupancy.
    t, _slot, is_new, probe_ok, _ovf = insert_batch(
        t, hi, lo, jnp.ones((128,), jnp.bool_), dedup_factor=1
    )
    assert int(jnp.sum(is_new)) == 0
    assert t.load_factor() == pytest.approx(128 / 1024)


# --- insert_batch_claim: the sortless claim-plane election -------------------


def _claim_insert(vals, active=None, capacity=1 << 8):
    from stateright_tpu.parallel.hashset import insert_batch_claim

    hi, lo = _keys(vals)
    n = len(vals)
    act = (
        jnp.ones((n,), jnp.bool_) if active is None
        else jnp.asarray(np.asarray(active, bool))
    )
    return insert_batch_claim(make_hashset(capacity), hi, lo, act)


def test_claim_election_all_duplicates_batch():
    # Every lane the same key: exactly one winner, and it is lane 0 —
    # the lowest lane of the (single) equal-key run.
    t, slot, new, origin, act, ok, ovf = _claim_insert([7] * 64)
    new = np.asarray(new)
    assert bool(ok) and not bool(ovf)
    assert new.sum() == 1 and new[0]
    # origin is the identity map (the sorted path's indexing contract).
    assert np.array_equal(np.asarray(origin), np.arange(64))


def test_claim_election_zero_valid_wave():
    t, slot, new, origin, act, ok, ovf = _claim_insert(
        [1, 2, 3, 4], active=[False] * 4
    )
    assert bool(ok)
    assert int(np.asarray(new).sum()) == 0
    assert t.load_factor() == 0.0


def test_claim_election_capacity_full_table():
    # More distinct keys than table slots: probing exhausts and the
    # call reports failure (probe_ok False) — the engines' flag-1
    # dispatch falls back to the sort path before growing the table.
    from stateright_tpu.parallel.hashset import insert_batch_claim

    vals = np.arange(1, 65, dtype=np.uint64)
    hi, lo = _keys(vals)
    t, _s, _n, _o, _a, ok, _ovf = insert_batch_claim(
        make_hashset(32), hi, lo, jnp.ones((64,), jnp.bool_)
    )
    assert not bool(ok)


def test_claim_election_colliding_fingerprint_lanes():
    # A tiny table forces distinct keys to contend for the same probe
    # slots (hash collisions): every distinct key must still land, the
    # winner of each equal-key run must still be its lowest lane, and
    # duplicates of different keys must never cross-resolve.
    rng = np.random.default_rng(7)
    vals = rng.integers(1, 40, size=200).astype(np.uint64)
    t, slot, new, origin, act, ok, ovf = _claim_insert(
        vals, capacity=1 << 7
    )
    new = np.asarray(new)
    slot = np.asarray(slot)
    assert bool(ok) and not bool(ovf)
    first = {}
    for i, v in enumerate(vals.tolist()):
        first.setdefault(v, i)
    assert {i for i in range(200) if new[i]} == set(first.values())
    # Winner slots hold exactly the winner's key.
    kh = np.asarray(t.key_hi)
    kl = np.asarray(t.key_lo)
    for i in range(200):
        if new[i]:
            key = (int(kh[slot[i]]) << 32) | int(kl[slot[i]])
            assert key == int(vals[i])


def test_claim_election_matches_prededup_representatives():
    # Election-vs-prededup representative equality: the claim winners
    # are exactly prededup's lowest-lane representatives, and the
    # resulting tables hold the identical key set.
    from stateright_tpu.parallel.hashset import (
        insert_batch_claim, insert_batch_compact,
    )

    rng = np.random.default_rng(3)
    vals = rng.integers(1, 500, size=1024).astype(np.uint64)
    active = rng.random(1024) > 0.4
    hi, lo = _keys(vals)
    act = jnp.asarray(active)

    tc, c_slot, c_new, c_origin, _ca, c_ok, c_ovf = insert_batch_claim(
        make_hashset(1 << 11), hi, lo, act
    )
    ts, _s, u_new, u_origin, u_active, s_ok, s_ovf = insert_batch_compact(
        make_hashset(1 << 11), hi, lo, act, dedup_factor=1
    )
    assert bool(c_ok) and bool(s_ok)
    claim_reps = {int(i) for i in np.where(np.asarray(c_new))[0]}
    sorted_reps = {
        int(o) for o, n in zip(
            np.asarray(u_origin).tolist(), np.asarray(u_new).tolist()
        ) if n
    }
    assert claim_reps == sorted_reps
    k_claim = set(
        zip(np.asarray(tc.key_hi).tolist(), np.asarray(tc.key_lo).tolist())
    )
    k_sort = set(
        zip(np.asarray(ts.key_hi).tolist(), np.asarray(ts.key_lo).tolist())
    )
    assert k_claim == k_sort


def test_claim_election_straggler_tail_batch():
    # Batches past the 16K straggler threshold route unresolved lanes
    # through the tail buffer; representatives stay the lowest lanes.
    from stateright_tpu.parallel.hashset import insert_batch_claim

    rng = np.random.default_rng(11)
    n = (1 << 14) + 256
    vals = rng.integers(1, 3000, size=n).astype(np.uint64)
    hi, lo = _keys(vals)
    t, slot, new, _o, _a, ok, _ovf = insert_batch_claim(
        make_hashset(1 << 13), hi, lo, jnp.ones((n,), jnp.bool_)
    )
    assert bool(ok)
    new = np.asarray(new)
    first = {}
    for i, v in enumerate(vals.tolist()):
        first.setdefault(v, i)
    assert {i for i in range(n) if new[i]} == set(first.values())
