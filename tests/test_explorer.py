"""Explorer web service: status, state navigation, run-to-completion.

Reference: src/checker/explorer.rs (endpoint behavior and JSON shapes,
src/checker/explorer.rs:134-320).
"""

import json
import time
import urllib.request
import urllib.error

import pytest

from stateright_tpu.models.fixtures import BinaryClock
from tests.test_tpu_wavefront import TrapCounter


@pytest.fixture()
def served():
    checker = BinaryClock().checker().serve(("127.0.0.1", 0), block=False)
    host, port = checker.explorer_address
    yield checker, f"http://{host}:{port}"
    checker.shutdown()
    checker.explorer_server.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return json.loads(r.read())


def test_status_endpoint(served):
    _checker, base = served
    status = _get(base + "/.status")
    assert status["model"] == "BinaryClock"
    assert status["unique_state_count"] == 2  # both init states
    assert status["properties"] == [["Always", "in [0, 1]", None]]
    assert status["done"] is False


def test_states_endpoint_navigation(served):
    checker, base = served
    model = checker.model()
    # Empty path -> the init states.
    inits = _get(base + "/.states/")
    assert len(inits) == 2
    assert sorted(s["state"] for s in inits) == ["0", "1"]
    fp0 = next(s["fingerprint"] for s in inits if s["state"] == "0")
    assert fp0 == str(model.fingerprint(0))
    # Following state 0's fingerprint lists its single GoHigh successor.
    nexts = _get(base + f"/.states/{fp0}")
    assert len(nexts) == 1
    assert nexts[0]["action"] == "GoHigh"
    assert nexts[0]["state"] == "1"
    # Descend once more: 0 -> 1 -> 0.
    fp1 = nexts[0]["fingerprint"]
    deeper = _get(base + f"/.states/{fp0}/{fp1}")
    assert deeper[0]["action"] == "GoLow"
    assert deeper[0]["state"] == "0"


def test_metrics_endpoint_parity_with_status(served):
    """GET /.metrics beside /.status: same counts, plus the engine tag —
    the live observability surface (docs/OBSERVABILITY.md)."""
    _checker, base = served
    status = _get(base + "/.status")
    metrics = _get(base + "/.metrics")
    for key in ("state_count", "unique_state_count", "max_depth", "done"):
        assert metrics[key] == status[key]
    assert metrics["engine"] == "OnDemandChecker"


def test_metrics_endpoint_on_tpu_backed_explorer():
    """A TPU-backed Explorer serves the device engine's metrics — wave
    cadence and table occupancy appear once the run completes."""
    from stateright_tpu.models.twophase import TwoPhaseSys

    checker = TwoPhaseSys(rm_count=3).checker().serve(
        ("127.0.0.1", 0),
        block=False,
        engine="tpu",
        capacity=1 << 14,
        max_frontier=1 << 9,
    )
    try:
        host, port = checker.explorer_address
        base = f"http://{host}:{port}"
        deadline = time.time() + 120
        metrics = _get(base + "/.metrics")
        while not metrics["done"] and time.time() < deadline:
            time.sleep(0.2)
            metrics = _get(base + "/.metrics")
        assert metrics["done"]
        assert metrics["engine"] == "tpu-wavefront"
        assert metrics["unique_state_count"] == 288
        assert metrics["waves"] >= 1
        assert 0 < metrics["table_occupancy"] <= 1
        status = _get(base + "/.status")
        assert metrics["unique_state_count"] == status["unique_state_count"]
    finally:
        checker.explorer_server.shutdown()


def test_states_endpoint_rejects_bad_fingerprints(served):
    _checker, base = served
    for bad in ("/.states/notanumber", "/.states/12345"):
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(base + bad)
        assert e.value.code == 404


def test_ui_files_served(served):
    _checker, base = served
    for path, marker in (
        ("/", b"Stateright-TPU Explorer"),
        ("/app.js", b"refreshStatus"),
        ("/app.css", b"main-flex"),
    ):
        with urllib.request.urlopen(base + path, timeout=5) as r:
            assert marker in r.read()


def test_run_to_completion_endpoint():
    checker = TrapCounter().checker().serve(("127.0.0.1", 0), block=False)
    try:
        host, port = checker.explorer_address
        base = f"http://{host}:{port}"
        req = urllib.request.Request(base + "/.runtocompletion", method="POST")
        with urllib.request.urlopen(req, timeout=5) as r:
            assert r.status == 200
        deadline = time.time() + 10
        while not checker.is_done() and time.time() < deadline:
            time.sleep(0.02)
        status = _get(base + "/.status")
        host_bfs = TrapCounter().checker().spawn_bfs().join()
        assert status["unique_state_count"] == host_bfs.unique_state_count()
        names = {p[1]: p[2] for p in status["properties"]}
        assert names["trapped"] is not None  # sometimes example found
        assert names["reaches limit"] is not None  # eventually counterexample
    finally:
        checker.shutdown()
        checker.explorer_server.shutdown()


def test_explorer_backed_by_tpu_run():
    """SURVEY §7 'done' criterion: the Explorer browsing a TPU-backed run —
    an exhaustive wavefront proceeds in the background while the UI polls
    live counts; discovery paths appear in the status once it completes,
    and state views navigate by host re-execution as usual."""
    from stateright_tpu.models.twophase import TwoPhaseSys

    model = TwoPhaseSys(rm_count=3)
    checker = model.checker().serve(
        ("127.0.0.1", 0),
        block=False,
        engine="tpu",
        capacity=1 << 14,
        max_frontier=1 << 9,
    )
    try:
        host, port = checker.explorer_address
        base = f"http://{host}:{port}"
        deadline = time.time() + 120
        status = _get(base + "/.status")
        while not status["done"] and time.time() < deadline:
            time.sleep(0.2)
            status = _get(base + "/.status")
        assert status["done"]
        assert status["unique_state_count"] == 288
        names = {p[1]: p[2] for p in status["properties"]}
        assert names["abort agreement"] is not None  # encoded discovery path
        assert names["commit agreement"] is not None
        assert names["consistent"] is None  # always-property holds
        # Browse: root state views, then one successor level deep.
        roots = _get(base + "/.states/")
        assert roots and roots[0]["fingerprint"]
        nxt = _get(base + "/.states/" + roots[0]["fingerprint"])
        assert any(s["state"] for s in nxt)
    finally:
        checker.explorer_server.shutdown()
