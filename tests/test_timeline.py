"""Unified timeline (obs/timeline.py): host-tail span decomposition,
clock_sync journal headers, the Chrome trace exporter, and the
surfaces that ride on them (watch/report host_share, fleet histogram
merge).

The contracts that matter (ISSUE 19 acceptance):

- the SpanRecorder's per-quantum ``host_span`` records decompose
  ``host_sec_total`` into named parts — on a real fused run their sum
  reconciles within 10% of the counter;
- trace=False stays zero-new-readback (the existing test_obs wave-event
  pin covers the device program; here we pin that span events are
  host-side journal lines only);
- ``timeline export`` emits valid Chrome trace JSON (well-nested X
  slices, resolving flows) and multi-journal merges are deterministic;
- the fleet ``/.metrics`` histogram merge is commutative.
"""

import json
import time

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twophase import TwoPhaseSys  # noqa: E402
from stateright_tpu.obs.metrics import (  # noqa: E402
    LATENCY_BUCKETS,
    Histogram,
    MetricsRegistry,
    merge_histogram_snapshots,
)
from stateright_tpu.obs.timeline import (  # noqa: E402
    SPAN_EVENT,
    SpanRecorder,
    build_trace,
    export_timeline,
    host_share_of,
    host_tail_sums,
    record_oneshot_span,
    timeline_main,
    validate_trace,
)
from stateright_tpu.runtime.journal import (  # noqa: E402
    CLOCK_SYNC_EVENT,
    Journal,
    read_clock_syncs,
    read_journal,
)


def _cpu():
    return jax.devices("cpu")[0]


class _ListJournal:
    """Journal stand-in capturing appended records in-memory."""

    def __init__(self):
        self.records = []

    def append(self, event, **fields):
        rec = {"t": 0.0, "event": event, **fields}
        self.records.append(rec)
        return rec


# --- SpanRecorder unit --------------------------------------------------------


def test_span_recorder_decomposes_tail():
    journal = _ListJournal()
    metrics = MetricsRegistry()
    rec = SpanRecorder(journal, metrics, worker="1@test")

    # Quantum 1: a tail with two named sections (real monotonic marks —
    # the recorder's span timestamps come from the same clock).
    with rec.step():
        pass
    rec.tail_start(time.monotonic())
    with rec.span("journal"):
        time.sleep(0.01)
    with rec.span("checkpoint"):
        time.sleep(0.01)
    # Quantum 2 opens: the previous tail flushes against this mark.
    rec.quantum_start(time.monotonic())
    assert len(journal.records) == 1
    ev = journal.records[0]
    assert ev["event"] == SPAN_EVENT
    assert ev["worker"] == "1@test"
    assert ev["quantum"] == 1
    spans = ev["spans"]
    # Named sections plus the residual: durations sum to the tail.
    assert set(spans) >= {"journal", "checkpoint", "other"}
    assert spans["journal"][1] >= 0.01
    assert sum(d for _rel, d in spans.values()) == pytest.approx(
        ev["host_sec"], rel=1e-2
    )
    # Per-phase histograms observed under the shared latency ladder.
    hists = metrics.snapshot_histograms()
    assert "host_journal_sec" in hists
    assert "host_other_sec" in hists
    assert hists["host_journal_sec"]["boundaries"] == list(LATENCY_BUCKETS)

    # Quantum 2: the flush write's own cost surfaces as a ``flush``
    # span in THIS record (negative rel — before this tail started).
    with rec.step():
        pass
    rec.tail_start(time.monotonic())
    time.sleep(0.005)
    tail2 = rec.finish(time.monotonic())
    assert tail2 >= 0.005
    ev2 = journal.records[1]
    assert ev2["quantum"] == 2
    assert "flush" in ev2["spans"]
    assert ev2["spans"]["flush"][0] < 0  # positioned at its true time
    # host_tail_sums reconciles the journal against the two tails
    # (the flush span rides along but measures real host work).
    sums = host_tail_sums(journal.records)
    assert sum(sums.values()) >= 0.025


def test_oneshot_span_excluded_from_tail_reconciliation():
    journal = _ListJournal()
    metrics = MetricsRegistry()
    record_oneshot_span(journal, metrics, "knob_cache", 0.125, job="j1")
    ev = journal.records[0]
    assert ev["event"] == SPAN_EVENT
    assert ev["scope"] == "run"
    assert ev["job"] == "j1"
    assert host_tail_sums(journal.records) == {}
    assert "host_knob_cache_sec" in metrics.snapshot_histograms()


def test_host_share_of():
    assert host_share_of(
        {"host_sec_total": 1.0, "device_call_sec_total": 3.0}
    ) == pytest.approx(0.25)
    assert host_share_of({"host_sec_total": 1.0}) is None
    assert host_share_of({}) is None


# --- runtime reconciliation ---------------------------------------------------


def test_fused_run_spans_reconcile_with_host_counter(tmp_path):
    """A real fused CPU run: the journal's host_span decomposition sums
    to within 10% of the engine's ``host_sec_total`` counter, and the
    run exports as a valid Chrome trace."""
    journal = str(tmp_path / "journal.jsonl")
    ck = (
        TwoPhaseSys(rm_count=3)
        .checker()
        .spawn_tpu(
            capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
            journal=journal,
        )
        .join()
    )
    assert ck.unique_state_count() == 288
    m = ck.metrics()
    events = read_journal(journal)
    span_events = [
        e for e in events
        if e["event"] == SPAN_EVENT and e.get("scope") != "run"
    ]
    assert span_events, "fused loop must journal host_span records"
    sums = host_tail_sums(events)
    total = sum(sums.values())
    host_counter = m["host_sec_total"]
    assert host_counter > 0
    assert total == pytest.approx(host_counter, rel=0.10)
    # Per-phase histograms ride the same metrics snapshot.
    hists = m.get("histograms") or {}
    assert any(n.startswith("host_") for n in hists)

    trace = export_timeline(journal)
    assert validate_trace(trace) == []
    names = {e.get("name") for e in trace["traceEvents"]}
    assert "wave" in names and "host" in names


# --- clock_sync headers -------------------------------------------------------


def test_clock_sync_header_written_once_and_filtered(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    with Journal(path) as j:
        j.append("a")
        j.append("b")
    events = read_journal(path)
    assert [e["event"] for e in events] == ["a", "b"]  # filtered
    syncs = read_clock_syncs(path)
    assert len(syncs) == 1
    s = syncs[0]
    assert s["event"] == CLOCK_SYNC_EVENT
    assert isinstance(s["mono"], float) and isinstance(s["t"], float)
    assert s["worker"] == f"{s['pid']}@{s['host']}"
    # The header precedes the first event in the raw stream.
    raw = read_journal(path, include_sync=True)
    assert raw[0]["event"] == CLOCK_SYNC_EVENT


def test_clock_sync_reanchors_each_segment(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    with Journal(path, max_bytes=256, max_segments=64) as j:
        for i in range(40):
            j.append("tick", i=i, pad="x" * 40)
    events = read_journal(path)
    assert [e["i"] for e in events] == list(range(40))  # nothing lost
    syncs = read_clock_syncs(path)
    assert len(syncs) >= 2  # every fresh segment re-anchors


# --- the exporter -------------------------------------------------------------


def _write_journal(path, events):
    with open(path, "w", encoding="utf-8") as fh:
        for e in events:
            fh.write(json.dumps(e, sort_keys=True) + "\n")
    return str(path)


def _worker_events(worker, t0, job):
    pid, host = worker.split("@")
    return [
        {"t": t0, "event": CLOCK_SYNC_EVENT, "mono": 1000.0,
         "pid": int(pid), "host": host, "worker": worker},
        {"t": t0 + 0.1, "event": "fleet_submitted", "job": job},
        {"t": t0 + 0.2, "event": "fleet_claimed", "job": job,
         "worker": worker},
        {"t": t0 + 1.2, "event": "wave", "worker": worker,
         "mono": 1000.2, "call_sec": 1.0, "waves": 8, "unique": 100},
        {"t": t0 + 1.3, "event": SPAN_EVENT, "worker": worker,
         "mono": 1001.2, "quantum": 1, "host_sec": 0.1,
         "spans": {"journal": [0.01, 0.02], "other": [0.03, 0.07]}},
        {"t": t0 + 1.4, "event": "job_span", "job": job, "span": "run",
         "sec": 1.1, "worker": worker},
        {"t": t0 + 1.5, "event": "fleet_done", "job": job,
         "worker": worker},
    ]


def test_export_two_worker_merge_valid_and_deterministic(tmp_path):
    a = _write_journal(
        tmp_path / "a.jsonl", _worker_events("100@hosta", 50.0, "job-a")
    )
    b = _write_journal(
        tmp_path / "b.jsonl", _worker_events("200@hostb", 50.05, "job-b")
    )
    ab = export_timeline([a, b])
    ba = export_timeline([b, a])
    assert json.dumps(ab, sort_keys=True) == json.dumps(ba, sort_keys=True)
    assert validate_trace(ab) == []
    evs = ab["traceEvents"]
    # One process track per worker, named by its pid@host stamp.
    procs = {
        e["args"]["name"] for e in evs
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert procs == {"100@hosta", "200@hostb"}
    # Flow arrows: each job's lifecycle starts and finishes.
    flow_phases = {}
    for e in evs:
        if e.get("ph") in ("s", "t", "f"):
            flow_phases.setdefault(e["id"], set()).add(e["ph"])
    assert len(flow_phases) == 2
    for phases in flow_phases.values():
        assert {"s", "f"} <= phases
    # host_span children nest inside their host slice per track.
    assert any(e.get("name") == "journal" for e in evs)


def test_exported_trace_is_loadable_json(tmp_path):
    a = _write_journal(
        tmp_path / "a.jsonl", _worker_events("100@hosta", 50.0, "j")
    )
    out = str(tmp_path / "out.trace.json")
    export_timeline([a], out=out)
    with open(out, "r", encoding="utf-8") as fh:
        loaded = json.load(fh)
    assert loaded["displayTimeUnit"] == "ms"
    assert validate_trace(loaded) == []


def test_validate_trace_catches_structural_breaks():
    # Overlapping, non-nesting X slices on one track.
    bad = {"traceEvents": [
        {"ph": "X", "pid": 1, "tid": 1, "name": "a", "ts": 0, "dur": 10},
        {"ph": "X", "pid": 1, "tid": 1, "name": "b", "ts": 5, "dur": 10},
    ]}
    assert any("overlaps" in p for p in validate_trace(bad))
    # A started flow that never finishes, bound to no slice.
    bad = {"traceEvents": [
        {"ph": "s", "pid": 1, "tid": 1, "id": 7, "ts": 0, "name": "j"},
    ]}
    problems = validate_trace(bad)
    assert any("never finishes" in p for p in problems)
    assert any("binds to no slice" in p for p in problems)
    # Unbalanced B/E.
    bad = {"traceEvents": [
        {"ph": "B", "pid": 1, "tid": 1, "name": "x", "ts": 0},
    ]}
    assert any("unclosed B" in p for p in validate_trace(bad))
    assert validate_trace({"traceEvents": []}) == []


def test_timeline_cli_verb(tmp_path, capsys):
    a = _write_journal(
        tmp_path / "journal.jsonl", _worker_events("100@hosta", 50.0, "j")
    )
    out = str(tmp_path / "t.trace.json")
    rc = timeline_main(["export", a, "--out", out])
    assert rc == 0
    assert "valid=yes" in capsys.readouterr().out
    with open(out, "r", encoding="utf-8") as fh:
        assert validate_trace(json.load(fh)) == []


# --- fleet histogram merge ----------------------------------------------------


def test_histogram_merge_commutative_and_ladder_checked():
    h1, h2, h3 = (Histogram(LATENCY_BUCKETS) for _ in range(3))
    for v in (0.001, 0.1, 4.0):
        h1.observe(v)
    for v in (0.002, 0.3):
        h2.observe(v, count=2)
    h3.observe(250.0)  # +Inf bucket
    maps = [
        {"wave_sec": h1.snapshot(), "host_journal_sec": h3.snapshot()},
        {"wave_sec": h2.snapshot()},
    ]
    ab = merge_histogram_snapshots(*maps)
    ba = merge_histogram_snapshots(*reversed(maps))
    assert ab == ba  # commutative: fleet view independent of worker order
    assert ab["wave_sec"]["count"] == 7
    assert ab["wave_sec"]["sum"] == pytest.approx(
        0.001 + 0.1 + 4.0 + 2 * 0.002 + 2 * 0.3
    )
    assert ab["host_journal_sec"]["count"] == 1
    # Differing ladders must fail loudly, not misbin.
    other = Histogram((1.0, 2.0))
    other.observe(1.5)
    with pytest.raises(ValueError):
        merge_histogram_snapshots(
            {"wave_sec": h1.snapshot()}, {"wave_sec": other.snapshot()}
        )


# --- watch / report surfaces --------------------------------------------------


def _run_events(host_sec):
    evs = []
    for q in range(4):
        t = 100.0 + q
        evs.append({
            "t": t, "event": "wave", "waves": 8 * (q + 1),
            "unique": 100 * (q + 1), "depth": q + 1, "call_sec": 0.5,
        })
        evs.append({
            "t": t + host_sec, "event": SPAN_EVENT, "quantum": q + 1,
            "worker": "1@test", "host_sec": host_sec,
            "spans": {"journal": [0.0, host_sec / 2],
                      "other": [host_sec / 2, host_sec / 2]},
        })
    return evs


def test_watch_host_share_and_badge():
    from stateright_tpu.obs.watch import render_line, summarize_events

    s = summarize_events(_run_events(0.1))
    assert s["host_share"] == pytest.approx(0.1 / 0.6, abs=1e-3)
    assert not any("host-share" in w for w in s["warnings"])
    assert "host_share=" in render_line(s)

    # A host-dominated loop (> 0.5) raises the ⚠ badge.
    s = summarize_events(_run_events(1.5))
    assert s["host_share"] > 0.5
    assert any("host-share" in w for w in s["warnings"])


def test_report_host_share_and_tail_breakdown():
    from stateright_tpu.obs.report import analyze_journal

    report = analyze_journal(_run_events(0.1))
    assert report["kind"] == "run"
    assert report["host_share"] == pytest.approx(0.1 / 0.6, abs=1e-3)
    assert report["host_tail_breakdown"]["journal"] == pytest.approx(
        0.2, abs=1e-6
    )


def test_trajectory_table_has_host_share_column(tmp_path):
    from stateright_tpu.obs.report import (
        bench_trajectory,
        render_trajectory_markdown,
    )

    p = tmp_path / "BENCH_r19.json"
    p.write_text(json.dumps({
        "rc": 0,
        "parsed": {"metric": "m", "value": 10.0, "host_share": 0.07},
    }))
    traj = bench_trajectory([str(p)])
    assert traj["rounds"][0]["host_share"] == 0.07
    md = render_trajectory_markdown(traj)
    header = next(l for l in md.splitlines() if l.startswith("| round"))
    assert "host share" in header
    row = next(l for l in md.splitlines() if "| BENCH_r19 |" in l)
    assert (
        row.count("|") == header.count("|")
    ), "host_share cell must keep the row aligned with the header"
    assert " 0.07 |" in row


def test_report_timeline_out_flag(tmp_path, capsys):
    from stateright_tpu.obs.report import report_main

    journal = _write_journal(tmp_path / "journal.jsonl", _run_events(0.1))
    out = str(tmp_path / "run.trace.json")
    rc = report_main([journal, "--timeline-out", out, "--json"])
    assert rc == 0
    with open(out, "r", encoding="utf-8") as fh:
        assert validate_trace(json.load(fh)) == []


def test_build_trace_wave_breakdown_children_nest():
    evs = [{
        "t": 10.0, "event": "wave", "call_sec": 1.0, "waves": 4,
        "wave_breakdown": {"step": 0.4, "dedup": 0.5, "readback": 0.1},
    }]
    trace = build_trace(evs)
    assert validate_trace(trace) == []
    names = [e["name"] for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert set(names) >= {"wave", "step", "dedup", "readback"}
