"""Differential tests for the paxos codec (models/paxos_compiled.py).

The packed encoding must be a bijection on the host model's *entire*
reachable set — this simultaneously validates every boundedness assumption
(rounds, in-flight envelopes, multiset counts <= 1, proposal space) against
reality before the device step kernel builds on the layout.  Reference
golden: 16,668 unique states at 2 clients / 3 servers
(/root/reference/examples/paxos.rs:328).
"""

import pytest

from stateright_tpu.actor import Envelope, Id, Network
from stateright_tpu.actor.register import Internal
from stateright_tpu.models.paxos import PaxosModelCfg, Prepare
from stateright_tpu.models.paxos_compiled import PaxosCompiled
from stateright_tpu.ops.fingerprint import fingerprint


def paxos_model(client_count: int):
    return PaxosModelCfg(
        client_count=client_count,
        server_count=3,
        network=Network.new_unordered_nonduplicating(),
    ).into_model()


def enumerate_reachable(model):
    """Full host-side BFS enumeration: fingerprint -> state."""
    seen = {}
    frontier = [s for s in model.init_states() if model.within_boundary(s)]
    for s in frontier:
        seen[fingerprint(s)] = s
    while frontier:
        nxt = []
        for s in frontier:
            acts = []
            model.actions(s, acts)
            for a in acts:
                ns = model.next_state(s, a)
                if ns is None or not model.within_boundary(ns):
                    continue
                fp = fingerprint(ns)
                if fp not in seen:
                    seen[fp] = ns
                    nxt.append(ns)
        frontier = nxt
    return seen


@pytest.fixture(scope="module")
def reachable_c1():
    return enumerate_reachable(paxos_model(1))


@pytest.fixture(scope="module")
def reachable_c2():
    return enumerate_reachable(paxos_model(2))


def test_roundtrip_full_reachable_set_c1(reachable_c1):
    cm = PaxosCompiled(paxos_model(1))
    assert len(reachable_c1) == 265  # pinned by this test suite's own BFS
    for s in reachable_c1.values():
        assert cm.decode(cm.encode(s)) == s


@pytest.mark.slow
def test_roundtrip_full_reachable_set_c2(reachable_c2):
    cm = PaxosCompiled(paxos_model(2))
    assert len(reachable_c2) == 16_668  # reference examples/paxos.rs:328
    for s in reachable_c2.values():
        words = cm.encode(s)
        s2 = cm.decode(words)
        assert s2 == s
        # The fingerprint must survive the codec too: path reconstruction
        # re-fingerprints decoded states.
        assert fingerprint(s2) == fingerprint(s)


def test_envelope_slot_overflow_is_loud(reachable_c1):
    """encode must refuse (not truncate) states with more in-flight
    envelopes than the packed layout holds."""
    cm = PaxosCompiled(paxos_model(1))
    some_state = next(iter(reachable_c1.values()))
    # Flood the network with distinct (but individually well-formed)
    # Prepare envelopes until the slot budget overflows.
    envs = list(some_state.network.counts)
    for r in range(1, 8):
        for src in range(3):
            for dst in range(3):
                if src != dst:
                    envs.append(
                        (Envelope(Id(src), Id(dst), Internal(Prepare((r, Id(src))))), 1)
                    )
    flooded = type(some_state)(
        actor_states=some_state.actor_states,
        network=Network(kind="unordered_nonduplicating", counts=frozenset(envs)),
        timers_set=some_state.timers_set,
        random_choices=some_state.random_choices,
        crashed=some_state.crashed,
        history=some_state.history,
        actor_storages=some_state.actor_storages,
    )
    with pytest.raises(ValueError, match="slots"):
        cm.encode(flooded)


def test_ballot_round_overflow_is_loud(reachable_c1):
    cm = PaxosCompiled(paxos_model(1))
    some_state = next(iter(reachable_c1.values()))
    big = Envelope(Id(0), Id(1), Internal(Prepare((99, Id(0)))))
    flooded = type(some_state)(
        actor_states=some_state.actor_states,
        network=Network(
            kind="unordered_nonduplicating",
            counts=frozenset(list(some_state.network.counts) + [(big, 1)]),
        ),
        timers_set=some_state.timers_set,
        random_choices=some_state.random_choices,
        crashed=some_state.crashed,
        history=some_state.history,
        actor_storages=some_state.actor_storages,
    )
    with pytest.raises(ValueError):
        cm.encode(flooded)
