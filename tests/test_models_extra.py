"""Raft, LWW-register, timers, interaction models + VectorClock utility.

Reference: examples/raft.rs, examples/lww-register.rs, examples/timers.rs,
examples/interaction.rs, src/util/vector_clock.rs.
"""

import pytest

from stateright_tpu.models.interaction import build_model as interaction_model
from stateright_tpu.models.lww_register import build_model as lww_model
from stateright_tpu.models.raft import LEADER, RaftModelCfg
from stateright_tpu.models.timers import build_model as timers_model
from stateright_tpu.utils.vector_clock import VectorClock


def test_raft_elects_leader_and_stays_safe():
    # Reference checks raft with target_max_depth BFS (examples/raft.rs:
    # 520-535).  By depth 6 an election completes; both safety properties
    # must stay unviolated.
    checker = (
        RaftModelCfg(server_count=3)
        .into_model()
        .checker()
        .target_max_depth(6)
        .spawn_bfs()
        .join()
    )
    checker.assert_any_discovery("Election Liveness")
    checker.assert_no_discovery("Election Safety")
    checker.assert_no_discovery("State Machine Safety")
    # Determinism pin for this port (not a reference-published value).
    assert checker.unique_state_count() == 4933


@pytest.mark.slow
def test_raft_commits_a_log_entry():
    checker = (
        RaftModelCfg(server_count=3)
        .into_model()
        .checker()
        .target_max_depth(8)
        .spawn_bfs()
        .join()
    )
    checker.assert_any_discovery("Log Liveness")
    checker.assert_no_discovery("Election Safety")
    checker.assert_no_discovery("State Machine Safety")


def test_lww_register_eventually_consistent():
    # Reference: lww-register check 2 with a depth bound
    # (examples/lww-register.rs:190-196).
    checker = (
        lww_model(2)
        .checker()
        .target_max_depth(5)
        .spawn_dfs()
        .join()
    )
    checker.assert_no_discovery("eventually consistent")
    assert checker.unique_state_count() > 50


def test_timers_model_explores_without_violation():
    checker = (
        timers_model(3)
        .checker()
        .target_max_depth(5)
        .spawn_dfs()
        .join()
    )
    checker.assert_no_discovery("true")
    assert checker.unique_state_count() > 10


def test_interaction_passes_on_default_duplicating_network():
    # Reference behavior (examples/interaction.rs check): the duplicating
    # default keeps every state expandable, so the depth-bounded check has
    # no terminal states and assert_properties passes.
    checker = (
        interaction_model(threshold=3)
        .checker()
        .target_max_depth(9)
        .spawn_bfs()
        .join()
    )
    checker.assert_properties()


def test_interaction_counterexample_on_nonduplicating_network():
    # Consuming delivery + no-op suppression creates a stuck terminal state
    # when the query overtakes the increment
    # (src/actor/model.rs:360-366 semantics, faithfully reproduced).
    from stateright_tpu.actor import Network

    checker = (
        interaction_model(
            threshold=3, network=Network.new_unordered_nonduplicating()
        )
        .checker()
        .target_max_depth(12)
        .spawn_bfs()
        .join()
    )
    ce = checker.assert_any_discovery("success")
    assert not any(
        getattr(s, "success", False)
        for s in ce.last_state().actor_states
    )


# --- VectorClock (src/util/vector_clock.rs tests) ----------------------------


def test_vector_clock_display():
    assert str(VectorClock([1, 2, 3, 4])) == "<1, 2, 3, 4, ...>"


def test_vector_clock_trailing_zeros_insignificant():
    assert VectorClock([1, 2]) == VectorClock([1, 2, 0, 0])
    assert hash(VectorClock([1, 2])) == hash(VectorClock([1, 2, 0]))
    from stateright_tpu.ops.fingerprint import fingerprint

    assert fingerprint(VectorClock([1, 2])) == fingerprint(VectorClock([1, 2, 0]))


def test_vector_clock_merge_and_increment():
    a = VectorClock([1, 0, 3])
    b = VectorClock([0, 2])
    assert a.merge_max(b) == VectorClock([1, 2, 3])
    assert VectorClock().incremented(2) == VectorClock([0, 0, 1])
    assert VectorClock([1]).incremented(0) == VectorClock([2])


def test_vector_clock_partial_order():
    assert VectorClock([1, 2]) < VectorClock([1, 3])
    assert VectorClock([1, 3]) > VectorClock([1, 2])
    assert VectorClock([1, 2]) <= VectorClock([1, 2, 0])
    # Concurrent clocks are incomparable.
    assert VectorClock([1, 0]).partial_cmp(VectorClock([0, 1])) is None
    assert not VectorClock([1, 0]) < VectorClock([0, 1])
    assert not VectorClock([1, 0]) > VectorClock([0, 1])
