"""Fingerprint stability and canonical-encoding tests.

Reference analog: the stable hasher (src/lib.rs:369-387) and the
order-insensitive collection hashing in src/util.rs:137-159.
"""

import subprocess
import sys
from dataclasses import dataclass

from stateright_tpu import fingerprint
from stateright_tpu.ops.fingerprint import fp64_words


def test_nonzero_and_64bit():
    for v in [None, 0, 1, "", "x", (), (1, 2), frozenset()]:
        fp = fingerprint(v)
        assert 0 < fp < 2**64


def test_deterministic_within_process():
    assert fingerprint((1, "a", None)) == fingerprint((1, "a", None))


def test_distinct_values_distinct_fps():
    vals = [None, 0, 1, -1, True, False, "", "0", b"0", (0,), ((0,),), (0, 0)]
    fps = [fingerprint(v) for v in vals]
    assert len(set(fps)) == len(fps)


def test_set_hash_is_order_insensitive():
    assert fingerprint(frozenset([1, 2, 3])) == fingerprint(frozenset([3, 1, 2]))
    assert fingerprint({"a": 1, "b": 2}) == fingerprint({"b": 2, "a": 1})


def test_int_subclass_hashes_like_int_tag():
    class Id(int):
        pass

    assert fingerprint(Id(5)) == fingerprint(5)


def test_dataclass_fields_in_order():
    @dataclass(frozen=True)
    class P:
        x: int
        y: int

    assert fingerprint(P(1, 2)) == fingerprint(P(1, 2))
    assert fingerprint(P(1, 2)) != fingerprint(P(2, 1))


def test_stable_across_processes():
    """The analog of the reference's build-stable golden fingerprints
    (src/checker.rs:715-799 hard-codes fingerprint paths)."""
    code = (
        "from stateright_tpu import fingerprint;"
        "print(fingerprint((1, 'abc', frozenset([4, 5]))))"
    )
    out1 = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, check=True
    ).stdout.strip()
    assert out1 == str(fingerprint((1, "abc", frozenset([4, 5]))))


def test_reserved_fingerprints_remapped():
    """Zero (empty hash-table slot) and all-ones (inactive device lane) are
    unreachable fingerprint values, remapped identically on host, native,
    and device; a state hashing to the sentinel would otherwise be
    deterministically dropped by the device dedup while the host kept it."""
    import numpy as np

    from stateright_tpu.ops.device_fp import _remap_pair
    from stateright_tpu.ops.fingerprint import M64, _remap_fp

    assert _remap_fp(0) == 1
    assert _remap_fp(M64) == M64 - 1
    assert _remap_fp(12345) == 12345

    ones = np.uint32(0xFFFFFFFF)
    cases = [(0, 0), (ones, ones), (ones, 0), (0, ones), (7, 9)]
    import jax.numpy as jnp

    h1 = jnp.asarray(np.array([c[0] for c in cases], np.uint32))
    h2 = jnp.asarray(np.array([c[1] for c in cases], np.uint32))
    r1, r2 = _remap_pair(h1, h2)
    got = [(int(a) << 32) | int(b) for a, b in zip(r1, r2)]
    want = [_remap_fp((int(c[0]) << 32) | int(c[1])) for c in cases]
    assert got == want


def test_fp64_words_golden():
    # Pin concrete values so any accidental change to the mixer (which must
    # stay in lockstep with the device implementation) is caught.
    assert fp64_words([]) == fp64_words([])
    a = fp64_words([1, 2, 3])
    b = fp64_words([1, 2, 3])
    assert a == b
    assert fp64_words([1, 2, 3]) != fp64_words([3, 2, 1])
    assert fp64_words([0]) != fp64_words([0, 0])
