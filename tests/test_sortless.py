"""The sortless claim-plane dedup + frontier-sized step (ISSUE 14).

Fingerprint-bit-identity matrix: the SORTLESS default (claim-plane
representative election, hashset.insert_batch_claim) and the
``step_lanes`` chunk rung must land the exact discovery set of the
sorted fixed-geometry path on every engine — single-chip fused and
traced, sharded at 1/2/4/8 virtual shards, tiered under forced
eviction, symmetry through the golden orbit count — including
forced-overflow runs: a tiny forced step rung climbs via the
non-committing flag 128, and a sortless run forced onto a tiny
compaction buffer FALLS BACK to the sort-rung path mid-run
(``grow sortless=0``) with no lost work.

The reference in every gate is ``sortless=False`` with ``sort_lanes``
pinned past the full buffer — the PR 12 fixed-geometry sort path."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twophase import TwoPhaseSys  # noqa: E402
from stateright_tpu.parallel.wave_loop import (  # noqa: E402
    SORT_RUNG_MIN, STEP_RUNG_MIN,
)
from stateright_tpu.runtime.journal import read_journal  # noqa: E402

RM = 4
GOLDEN = 1568
FULL = 1 << 30  # clamps to the full buffer = the fixed-geometry path


def _cpu():
    return jax.devices("cpu")[0]


def _mesh(n):
    return jax.sharding.Mesh(np.array(jax.devices("cpu")[:n]), ("shards",))


def _model():
    return TwoPhaseSys(rm_count=RM)


@pytest.fixture(scope="module")
def reference_fps():
    ck = _model().checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
        sortless=False, sort_lanes=FULL,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    return ck.discovered_fingerprints()


def _grows(journal, needle):
    return [
        e for e in read_journal(journal)
        if e["event"] == "grow" and needle in str(e.get("grown", ""))
    ]


def test_sortless_is_the_default_and_fused_bit_identical(
    tmp_path, reference_fps
):
    journal = str(tmp_path / "sortless.jsonl")
    ck = _model().checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
        journal=journal,
    ).join()
    m = ck.metrics()
    assert m["sortless"] is True  # the default path
    assert ck.unique_state_count() == GOLDEN
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)
    # The geometry journal event carries the dedup path + step rung.
    geoms = [
        e for e in read_journal(journal) if e["event"] == "geometry"
    ]
    assert geoms and geoms[0]["sortless"] is True
    assert geoms[0]["step_lanes"] == 1 << 9
    # The knob cache remembers the (un-fallen-back) path.
    assert ck.tuned_kwargs()["sortless"] == 1


def test_sortless_traced_bit_identical(tmp_path, reference_fps):
    ck = _model().checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
        trace=True, journal=str(tmp_path / "t.jsonl"),
    ).join()
    assert ck.unique_state_count() == GOLDEN
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)
    # bytes.dedup on the sortless path carries no sort term: strictly
    # below the sorted reference's at the same geometry.
    sorted_ck = _model().checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
        trace=True, sortless=False, sort_lanes=FULL,
    ).join()
    assert (
        ck.trace_summary()["bytes"]["dedup"]
        < sorted_ck.trace_summary()["bytes"]["dedup"]
    )


@pytest.mark.parametrize("shards", [1, 2, 4, 8])
def test_sortless_sharded_bit_identical(shards, tmp_path, reference_fps):
    ck = _model().checker().spawn_tpu_sharded(
        mesh=_mesh(shards), capacity=1 << 14, chunk_size=1 << 7,
        journal=str(tmp_path / f"sh{shards}.jsonl"),
    ).join()
    assert ck.unique_state_count() == GOLDEN
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)
    acc = ck.accounting()
    assert acc["sortless"] == 1


def test_sortless_tiered_forced_eviction_bit_identical(reference_fps):
    ck = _model().checker().spawn_tpu_tiered(
        memory_budget_mb=0.01, max_frontier=1 << 6,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    assert ck.metrics()["spills"] >= 1
    assert ck.metrics()["sortless"] is True
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)


def test_sortless_symmetry_golden_166():
    # The Ip & Dill perfect-canonicalization sort stays where symmetry
    # needs it; dedup on the canonical fingerprints is claim-elected.
    model = TwoPhaseSys(rm_count=4)
    sym = model.checker().symmetry().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
    ).join()
    ref = model.checker().symmetry().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
        sortless=False, sort_lanes=FULL,
    ).join()
    assert sym.unique_state_count() == 166
    assert ref.unique_state_count() == 166
    assert np.array_equal(
        sym.discovered_fingerprints(), ref.discovered_fingerprints()
    )


def test_forced_fallback_to_sort_rung_mid_run(tmp_path, reference_fps):
    """sortless=True + a tiny sort_lanes caps the claim compaction
    buffer (the forcing knob): the first overflowing wave raises the
    non-committing flag 4, the engine FALLS BACK to the sort-rung path
    (grow note ``sortless=0``), the sort ladder takes over — and the
    discovery set stays bit-identical."""
    journal = str(tmp_path / "fallback.jsonl")
    ck = _model().checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
        sortless=True, sort_lanes=SORT_RUNG_MIN, journal=journal,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)
    assert _grows(journal, "sortless=0"), "fallback never fired"
    m = ck.metrics()
    assert m["sortless"] is False  # flipped mid-run
    # The knob cache persists the per-workload selection.
    assert ck.tuned_kwargs()["sortless"] == 0
    # A geometry event re-journaled at the flip carries the new path.
    geoms = [
        e for e in read_journal(journal) if e["event"] == "geometry"
    ]
    assert any(g.get("sortless") is False for g in geoms)


def test_forced_tiny_step_rung_climbs_and_bit_identical(
    tmp_path, reference_fps
):
    """A forced tiny step rung clamps (flag 128, nothing commits), the
    host climbs one rung at a time, and the set is bit-identical; the
    discovered rung rides metrics()/tuned_kwargs like the sort rung."""
    journal = str(tmp_path / "step.jsonl")
    ck = _model().checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
        step_lanes=STEP_RUNG_MIN, journal=journal,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)
    climbs = _grows(journal, "step_lanes=")
    assert climbs and all(e["flags"] & 128 for e in climbs)
    m = ck.metrics()
    assert m["step_lanes"] > STEP_RUNG_MIN
    assert ck.tuned_kwargs()["step_lanes"] == m["step_lanes"]


def test_forced_tiny_step_rung_traced(tmp_path, reference_fps):
    journal = str(tmp_path / "step_traced.jsonl")
    ck = _model().checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
        trace=True, step_lanes=STEP_RUNG_MIN, journal=journal,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)
    assert _grows(journal, "step_lanes=")


def test_forced_tiny_step_rung_sharded(tmp_path, reference_fps):
    journal = str(tmp_path / "step_sh.jsonl")
    ck = _model().checker().spawn_tpu_sharded(
        mesh=_mesh(2), capacity=1 << 14, chunk_size=1 << 9,
        step_lanes=STEP_RUNG_MIN, journal=journal,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)
    acc = ck.accounting()
    if acc["step_retries"]:
        assert _grows(journal, "step_lanes=")


def test_forced_tiny_step_rung_tiered(reference_fps):
    ck = _model().checker().spawn_tpu_tiered(
        memory_budget_mb=0.01, max_frontier=1 << 9,
        step_lanes=STEP_RUNG_MIN,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    assert ck.metrics()["spills"] >= 1
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)


def test_sharded_snapshot_persists_path_and_step_rung(tmp_path):
    """A sharded snapshot carries the dedup path and step rung (the
    bucket_slack pattern): a resumed run adopts them instead of
    re-paying the fallback/climb ramps."""
    snap = str(tmp_path / "snap.npz")
    ck = _model().checker().target_state_count(400).spawn_tpu_sharded(
        mesh=_mesh(2), capacity=1 << 14, chunk_size=1 << 7,
        step_lanes=STEP_RUNG_MIN,
    ).join()
    ck.save_snapshot(snap)
    resumed = _model().checker().spawn_tpu_sharded(
        mesh=_mesh(2), capacity=1 << 14, chunk_size=1 << 7,
        resume_from=snap,
    ).join()
    assert resumed.unique_state_count() == GOLDEN
    m = resumed.metrics()
    assert m["sortless"] is True
    assert m["step_lanes_rung"] >= STEP_RUNG_MIN  # adopted, tuner off
