"""Device gates for the ABD quorum-register workload — the second compiled
register-harness protocol, proving the compilation path (and the shared
client/tester machinery with its exact linearizability DP) generalizes
beyond paxos.  Reference golden: 544 unique states at 2 clients / 2
servers (examples/linearizable-register.rs:288,315).
"""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.actor import Network  # noqa: E402
from stateright_tpu.actor.model import Deliver  # noqa: E402
from stateright_tpu.models.abd import AbdModelCfg  # noqa: E402
from stateright_tpu.models.abd_compiled import AbdCompiled  # noqa: E402
from stateright_tpu.ops.fingerprint import fingerprint  # noqa: E402


def abd_model(client_count: int):
    return AbdModelCfg(
        client_count=client_count,
        server_count=2,
        network=Network.new_unordered_nonduplicating(),
    ).into_model()


def enumerate_reachable(model):
    seen = {}
    frontier = [s for s in model.init_states()]
    for s in frontier:
        seen[fingerprint(s)] = s
    while frontier:
        nxt = []
        for s in frontier:
            acts = []
            model.actions(s, acts)
            for a in acts:
                ns = model.next_state(s, a)
                if ns is None:
                    continue
                fp = fingerprint(ns)
                if fp not in seen:
                    seen[fp] = ns
                    nxt.append(ns)
        frontier = nxt
    return seen


@pytest.fixture(scope="module", params=[1, 2])
def reachable(request):
    c = request.param
    model = abd_model(c)
    return model, AbdCompiled(model), list(enumerate_reachable(model).values())


def test_roundtrip_and_golden_count(reachable):
    model, cm, states = reachable
    assert len(states) in (13, 544)  # C=1 / C=2 (reference golden)
    for s in states:
        assert cm.decode(cm.encode(s)) == s
        assert fingerprint(cm.decode(cm.encode(s))) == fingerprint(s)


def test_step_differential_full_reachable(reachable):
    """Device successors, validity, and flags vs the host model on the
    entire reachable space."""
    model, cm, states = reachable
    enc = np.stack([cm.encode(s) for s in states]).astype(np.uint32)
    lane_fn = jax.jit(
        jax.vmap(
            lambda st: jax.vmap(lambda k: cm._deliver_lane(st, k))(
                jnp.arange(cm.m, dtype=jnp.uint32)
            )
        )
    )
    nexts, valid, flags = (np.asarray(x) for x in lane_fn(jnp.asarray(enc)))
    assert not flags.any()
    for bi, s in enumerate(states):
        host_map = {}
        for env in s.network.iter_deliverable():
            ns = model.next_state(s, Deliver(env.src, env.dst, env.msg))
            host_map[cm._env_code(env)] = None if ns is None else cm.encode(ns)
        for k in range(cm.m):
            code = int(enc[bi][3 + k])
            if code == 0:
                assert not valid[bi, k]
                continue
            want = host_map[code]
            if want is None:
                assert not valid[bi, k], cm._env_of(code)
            else:
                assert valid[bi, k], cm._env_of(code)
                assert np.array_equal(nexts[bi, k], want), cm._env_of(code)


def test_property_differential_full_reachable(reachable):
    model, cm, states = reachable
    enc = np.stack([cm.encode(s) for s in states]).astype(np.uint32)
    conds = np.asarray(jax.jit(jax.vmap(cm.property_conds))(jnp.asarray(enc)))
    from stateright_tpu.models.abd import NULL_VALUE

    for bi, s in enumerate(states):
        lin = s.history.serialized_history() is not None
        chosen = any(
            type(e.msg).__name__ == "GetOk" and e.msg.value != NULL_VALUE
            for e in s.network.iter_deliverable()
        )
        assert bool(conds[bi, 0]) == lin
        assert bool(conds[bi, 1]) == chosen


def test_spawn_tpu_abd_matches_host_oracle():
    model = abd_model(2)
    tpu = (
        model.checker()
        .spawn_tpu(capacity=1 << 13, max_frontier=1 << 8)
        .join()
    )
    assert tpu.unique_state_count() == 544  # linearizable-register.rs:288
    host = abd_model(2).checker().spawn_bfs().join()
    assert tpu.unique_state_count() == host.unique_state_count()
    assert tpu.state_count() == host.state_count()
    assert tpu.max_depth() == host.max_depth()
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    tpu.assert_properties()


def abd_ordered_model(client_count: int):
    return AbdModelCfg(
        client_count=client_count,
        server_count=2,
        network=Network.new_ordered(),
    ).into_model()


def test_ordered_step_differential_full_reachable():
    """FIFO-lane kernel vs host on the whole c=2 ordered space (620
    states; reference bench fabric, src/actor/network.rs:60-68).  Ordered
    no-op deliveries still consume the channel head and ARE successors
    (actor/model.py:299), unlike the unordered fabrics."""
    model = abd_ordered_model(2)
    cm = AbdCompiled(model)
    assert cm.ordered
    seen = {}
    frontier = list(model.init_states())
    for s in frontier:
        seen[fingerprint(s)] = s
    step = jax.jit(cm.step)
    while frontier:
        nxt = []
        for s in frontier:
            enc = cm.encode(s)
            assert cm.decode(enc) == s
            host_succ = set()
            acts = []
            model.actions(s, acts)
            for a in acts:
                ns = model.next_state(s, a)
                if ns is None:
                    continue
                host_succ.add(tuple(cm.encode(ns).tolist()))
                fp = fingerprint(ns)
                if fp not in seen:
                    seen[fp] = ns
                    nxt.append(ns)
            nexts, valid, flag = step(jnp.asarray(enc))
            assert not bool(flag), s
            dev_succ = {
                tuple(np.asarray(nexts[i]).tolist())
                for i in range(nexts.shape[0])
                if bool(valid[i])
            }
            assert dev_succ == host_succ, s
        frontier = nxt
    assert len(seen) == 620


@pytest.mark.slow
def test_spawn_tpu_abd_ordered_matches_host():
    """`linearizable-register check 2` on the ordered fabric, end to end
    on the device engine."""
    tpu = (
        abd_ordered_model(2)
        .checker()
        .spawn_tpu(capacity=1 << 13, max_frontier=1 << 8)
        .join()
    )
    host = abd_ordered_model(2).checker().spawn_bfs().join()
    assert host.unique_state_count() == 620
    assert tpu.unique_state_count() == 620
    assert tpu.state_count() == host.state_count()
    assert tpu.max_depth() == host.max_depth() == 25
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())
    tpu.assert_properties()


@pytest.mark.slow
def test_spawn_tpu_abd_ordered_check3_matches_host():
    """The reference's long bench workload `linearizable-register check 3
    ordered` (bench.sh:33): full golden parity host vs device."""
    tpu = (
        abd_ordered_model(3)
        .checker()
        .spawn_tpu(capacity=1 << 17, max_frontier=1 << 9)
        .join()
    )
    host = abd_ordered_model(3).checker().spawn_bfs().join()
    assert host.unique_state_count() == 46_516
    assert tpu.unique_state_count() == 46_516
    assert tpu.max_depth() == host.max_depth() == 37
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())


@pytest.mark.slow
def test_spawn_tpu_abd_unordered_check3_matches_host():
    """3 clients on the nonduplicating fabric (cap was 2 in round 2)."""
    tpu = (
        abd_model(3)
        .checker()
        .spawn_tpu(capacity=1 << 17, max_frontier=1 << 9)
        .join()
    )
    host = abd_model(3).checker().spawn_bfs().join()
    assert host.unique_state_count() == 35_009
    assert tpu.unique_state_count() == 35_009
    assert tpu.max_depth() == host.max_depth() == 37
    assert sorted(tpu.discoveries()) == sorted(host.discoveries())


def abd_skip_ack_model(client_count: int, ordered: bool = False):
    return AbdModelCfg(
        client_count=client_count,
        server_count=2,
        network=(
            Network.new_ordered()
            if ordered
            else Network.new_unordered_nonduplicating()
        ),
        fault="skip_ack",
    ).into_model()


def test_skip_ack_step_differential_full_reachable():
    """The deliberately-broken skip-ack replica (the chaos ensemble's
    known-violating workload) on device: full-reachable-space successor
    and property parity against the host model, and the linearizability
    violation the fault exists to create is actually reachable."""
    model = abd_skip_ack_model(2)
    cm = AbdCompiled(model)
    assert cm.fault == "skip_ack"
    assert cm.cache_key() != AbdCompiled(abd_model(2)).cache_key()
    states = list(enumerate_reachable(model).values())
    assert states
    enc = np.stack([cm.encode(s) for s in states]).astype(np.uint32)
    for s, e in zip(states, enc):
        assert cm.decode(e) == s
    lane_fn = jax.jit(
        jax.vmap(
            lambda st: jax.vmap(lambda k: cm._deliver_lane(st, k))(
                jnp.arange(cm.m, dtype=jnp.uint32)
            )
        )
    )
    nexts, valid, flags = (np.asarray(x) for x in lane_fn(jnp.asarray(enc)))
    assert not flags.any()
    for bi, s in enumerate(states):
        host_map = {}
        for env in s.network.iter_deliverable():
            ns = model.next_state(s, Deliver(env.src, env.dst, env.msg))
            host_map[cm._env_code(env)] = None if ns is None else cm.encode(ns)
        for k in range(cm.m):
            code = int(enc[bi][3 + k])
            if code == 0:
                assert not valid[bi, k]
                continue
            want = host_map[code]
            if want is None:
                assert not valid[bi, k], cm._env_of(code)
            else:
                assert valid[bi, k], cm._env_of(code)
                assert np.array_equal(nexts[bi, k], want), cm._env_of(code)
    conds = np.asarray(jax.jit(jax.vmap(cm.property_conds))(jnp.asarray(enc)))
    violations = 0
    for bi, s in enumerate(states):
        lin = s.history.serialized_history() is not None
        assert bool(conds[bi, 0]) == lin
        violations += not lin
    assert violations > 0  # the broken replica IS catchable


def test_skip_ack_ordered_step_differential():
    """Same hook on the ordered FIFO fabric (the ensemble's fabric)."""
    model = abd_skip_ack_model(2, ordered=True)
    cm = AbdCompiled(model)
    step = jax.jit(cm.step)
    seen = {}
    frontier = list(model.init_states())
    for s in frontier:
        seen[fingerprint(s)] = s
    violations = 0
    while frontier:
        nxt = []
        for s in frontier:
            enc = cm.encode(s)
            assert cm.decode(enc) == s
            violations += s.history.serialized_history() is None
            host_succ = set()
            acts = []
            model.actions(s, acts)
            for a in acts:
                ns = model.next_state(s, a)
                if ns is None:
                    continue
                host_succ.add(tuple(cm.encode(ns).tolist()))
                fp = fingerprint(ns)
                if fp not in seen:
                    seen[fp] = ns
                    nxt.append(ns)
            nexts, valid, flag = step(jnp.asarray(enc))
            assert not bool(flag), s
            dev_succ = {
                tuple(np.asarray(nexts[i]).tolist())
                for i in range(nexts.shape[0])
                if bool(valid[i])
            }
            assert dev_succ == host_succ, s
        frontier = nxt
    assert violations > 0


def _dup_send_differential(model, cm, net0):
    """Shared body: bump EACH in-flight envelope of every reachable state
    to count 2 in turn (duplicate runs at interior slots included), then
    codec round-trip + device step must match the host exactly — one
    Deliver per DISTINCT envelope (iter_deliverable), delivery consuming
    one copy."""
    dup_states = []
    for s in enumerate_reachable(model).values():
        counts = dict(s.network.counts)
        if not counts or len(s.network.counts) + 1 > cm.m:
            continue
        for env in sorted(counts, key=cm._env_code):
            counts2 = dict(counts)
            counts2[env] = 2
            dup_states.append(
                dataclasses.replace(
                    s,
                    network=dataclasses.replace(
                        s.network, counts=frozenset(counts2.items())
                    ),
                )
            )
    assert dup_states

    enc = np.stack([cm.encode(s) for s in dup_states]).astype(np.uint32)
    for s, e in zip(dup_states, enc):
        assert cm.decode(e) == s  # repeated code round-trips to count=2

    lane_fn = jax.jit(
        jax.vmap(
            lambda st: jax.vmap(lambda k: cm._deliver_lane(st, k))(
                jnp.arange(cm.m, dtype=jnp.uint32)
            )
        )
    )
    nexts, valid, flags = (np.asarray(x) for x in lane_fn(jnp.asarray(enc)))
    assert not flags.any()
    for bi, s in enumerate(dup_states):
        host_map = {}
        for env in s.network.iter_deliverable():
            ns = model.next_state(s, Deliver(env.src, env.dst, env.msg))
            host_map[cm._env_code(env)] = None if ns is None else cm.encode(ns)
        seen_codes = set()
        for k in range(cm.m):
            code = int(enc[bi][net0 + k])
            if code == 0 or code in seen_codes:
                # Empty or non-representative duplicate: not a lane.
                assert not valid[bi, k]
                if code:
                    seen_codes.add(code)
                continue
            seen_codes.add(code)
            want = host_map[code]
            if want is None:
                assert not valid[bi, k], cm._env_of(code)
            else:
                assert valid[bi, k], cm._env_of(code)
                assert np.array_equal(nexts[bi, k], want), cm._env_of(code)


def test_duplicate_inflight_send_step_differential_abd():
    """Duplicate in-flight messages (host multiset count = 2) are DATA in
    the slot codec — repeated codes, like the raft codec — not an engine
    error.  None of the register protocols reach such a state (the full-
    space differentials prove it), so the states are synthetic."""
    model = abd_model(2)
    _dup_send_differential(model, AbdCompiled(model), net0=3)


@pytest.mark.slow
def test_duplicate_inflight_send_step_differential_paxos():
    from stateright_tpu.models.paxos import PaxosModelCfg
    from stateright_tpu.models.paxos_compiled import PaxosCompiled

    model = PaxosModelCfg(
        client_count=2,
        server_count=3,
        network=Network.new_unordered_nonduplicating(),
    ).into_model()
    cm = PaxosCompiled(model)
    _dup_send_differential(model, cm, net0=7)


def test_duplicate_inflight_send_step_differential_single_copy():
    from stateright_tpu.models.single_copy_register import SingleCopyModelCfg
    from stateright_tpu.models.single_copy_compiled import SingleCopyCompiled

    model = SingleCopyModelCfg(
        client_count=2,
        server_count=1,
        network=Network.new_unordered_nonduplicating(),
    ).into_model()
    cm = SingleCopyCompiled(model)
    _dup_send_differential(model, cm, net0=2)
