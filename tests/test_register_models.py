"""Register-harness model golden tests.

Reference anchors: examples/single-copy-register.rs:89-138 (93 unique
states at 2 clients / 1 server; linearizability counterexample at 2
servers with 20 unique states).
"""

from stateright_tpu.actor import Deliver, Id, Network
from stateright_tpu.actor.register import Get, GetOk, Put, PutOk
from stateright_tpu.models.single_copy_register import (
    NULL_VALUE,
    SingleCopyModelCfg,
)


def test_can_model_single_copy_register_one_server():
    checker = (
        SingleCopyModelCfg(
            client_count=2,
            server_count=1,
            network=Network.new_unordered_nonduplicating(),
        )
        .into_model()
        .checker()
        .spawn_dfs()
        .join()
    )
    checker.assert_properties()
    checker.assert_discovery(
        "value chosen",
        [
            Deliver(Id(2), Id(0), Put(2, "B")),
            Deliver(Id(0), Id(2), PutOk(2)),
            Deliver(Id(2), Id(0), Get(4)),
        ],
    )
    assert checker.unique_state_count() == 93


def test_single_copy_register_two_servers_not_linearizable():
    checker = (
        SingleCopyModelCfg(
            client_count=2,
            server_count=2,
            network=Network.new_unordered_nonduplicating(),
        )
        .into_model()
        .checker()
        .spawn_bfs()
        .join()
    )
    checker.assert_discovery(
        "linearizable",
        [
            Deliver(Id(3), Id(1), Put(3, "B")),
            Deliver(Id(1), Id(3), PutOk(3)),
            Deliver(Id(3), Id(0), Get(6)),
            Deliver(Id(0), Id(3), GetOk(6, NULL_VALUE)),
        ],
    )
    checker.assert_discovery(
        "value chosen",
        [
            Deliver(Id(3), Id(1), Put(3, "B")),
            Deliver(Id(1), Id(3), PutOk(3)),
            Deliver(Id(2), Id(0), Put(2, "A")),
            Deliver(Id(3), Id(0), Get(6)),
        ],
    )
    # The reference sees 20 unique states here, but this run early-exits once
    # all properties have discoveries, so the count depends on successor
    # enumeration order (the reference's is ahash iteration order; ours is
    # sorted-envelope order).  22 is this implementation's deterministic count.
    assert checker.unique_state_count() == 22
