"""The checking service: job lifecycle, warm starts, portfolio racing,
cancellation, and the HTTP surface (docs/SERVING.md).

Everything runs in-process against CPU jax; the serve smoke in CI
exercises the same flows through a real daemon.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.runtime.journal import read_journal  # noqa: E402
from stateright_tpu.runtime.knob_cache import (  # noqa: E402
    knob_key, load_knobs,
)
from stateright_tpu.serve import (  # noqa: E402
    CANCELLED, DONE, CheckService, JobSpec, diversify,
)
from stateright_tpu.serve.workloads import (  # noqa: E402
    build_model, workload_label, workload_names,
)


@pytest.fixture
def service(tmp_path):
    svc = CheckService(
        journal=str(tmp_path / "journal.jsonl"),
        knob_cache_dir=str(tmp_path / "knobs"),
    )
    yield svc
    svc.scheduler.shutdown()


def submit_and_wait(svc, spec, timeout=300):
    job = svc.submit(spec)
    assert job.wait(timeout), f"job {job.id} never finished"
    return job


SMALL_2PC = {
    "workload": "twophase", "n": 3,
    "engine_kwargs": {"capacity": 1 << 14, "max_frontier": 1 << 7},
}


# --- single-job lifecycle ----------------------------------------------------


def test_served_job_parity_with_direct_check(service):
    """Acceptance: a job through the service reports identical
    unique-state counts and property verdicts to the same check run
    directly on the engine (the check-tpu path)."""
    job = submit_and_wait(service, SMALL_2PC)
    assert job.state == DONE, job.error
    r = job.result
    model, _, _ = build_model("twophase", 3)
    direct = model.checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 7
    ).join()
    assert r["unique_state_count"] == direct.unique_state_count() == 288
    assert r["max_depth"] == direct.max_depth()
    assert sorted(r["discoveries"]) == sorted(direct.discoveries())
    assert r["violation"] is None
    by_name = {p["name"]: p for p in r["properties"]}
    assert by_name["consistent"]["discovered"] is False
    assert by_name["commit agreement"]["classification"] == "example"


def test_second_identical_job_reuses_programs_and_knobs(tmp_path):
    """Acceptance: the second identical submission hits the knob cache
    (skipping auto-tune sizing) and the compiled-program cache (skipping
    compiles), visible both per-job and in the aggregated metrics."""
    svc = CheckService(
        journal=str(tmp_path / "j.jsonl"),
        knob_cache_dir=str(tmp_path / "knobs"),
    )
    try:
        j1 = submit_and_wait(svc, {"workload": "twophase", "n": 3})
        j2 = submit_and_wait(svc, {"workload": "twophase", "n": 3})
        assert j1.result["knob_cache_hit"] is False
        assert j2.result["knob_cache_hit"] is True
        # Identical persisted geometry => the spawn reproduces the first
        # job's program-cache keys, so the warm run compiled nothing.
        assert j2.result["program_cache_hits_delta"] > 0
        assert j2.result["unique_state_count"] == 288
        m = svc.metrics()
        assert m["knob_cache_hits"] == 1
        assert m["knob_cache_misses"] == 1
        assert m["jobs_completed"] == 2
        assert m["program_cache_hits"] >= j2.result[
            "program_cache_hits_delta"
        ]
        # The persisted entry is the run's final geometry.
        key = knob_key(workload_label("twophase", 3, None))
        knobs = load_knobs(str(tmp_path / "knobs"), key)
        assert knobs is not None and "capacity" in knobs
    finally:
        svc.scheduler.shutdown()


def test_violating_job_reports_violation(service):
    job = submit_and_wait(service, {"workload": "fixtures", "n": 5})
    assert job.state == DONE, job.error
    assert job.result["violation"] == "reaches limit"
    disc = job.result["discoveries"]["reaches limit"]
    assert disc["classification"] == "counterexample"
    assert disc["fingerprints"].count("/") >= 1


def test_job_priorities_order_the_queue(tmp_path):
    """With one worker busy, a higher-priority submission overtakes an
    earlier lower-priority one."""
    svc = CheckService(knob_cache_dir=str(tmp_path / "knobs"))
    try:
        blocker = svc.submit({"workload": "fixtures", "n": 5})
        low = svc.submit({"workload": "twophase", "n": 3,
                          "engine": "bfs", "priority": 0})
        high = svc.submit({"workload": "fixtures", "n": 4,
                           "engine": "bfs", "priority": 5})
        for j in (blocker, low, high):
            assert j.wait(300)
            assert j.state == DONE, j.error
        assert high.started_at <= low.started_at
    finally:
        svc.scheduler.shutdown()


def test_invalid_specs_are_rejected_at_submit():
    with pytest.raises(ValueError, match="workload"):
        JobSpec.from_dict({})
    with pytest.raises(ValueError, match="engine"):
        JobSpec.from_dict({"workload": "twophase", "engine": "warp"})
    with pytest.raises(ValueError, match="unknown job field"):
        JobSpec.from_dict({"workload": "twophase", "frobnicate": 1})
    with pytest.raises(ValueError, match="portfolio.size"):
        JobSpec.from_dict({"workload": "twophase", "portfolio": {"size": 1}})
    with pytest.raises(ValueError, match="no engine_kwargs"):
        JobSpec.from_dict({"workload": "twophase", "engine": "bfs",
                           "engine_kwargs": {"capacity": 1 << 14}})
    with pytest.raises(ValueError, match="unknown workload"):
        build_model("does_not_exist")
    assert "twophase" in workload_names()


# --- cancellation ------------------------------------------------------------


def test_cancel_queued_and_running_jobs(tmp_path):
    """One worker: a long host-BFS job is cancelled mid-run (cooperative
    request_stop — partial counts reported), and a job queued behind it
    is cancelled without ever starting."""
    svc = CheckService(
        journal=str(tmp_path / "j.jsonl"),
        knob_cache_dir=str(tmp_path / "knobs"),
    )
    try:
        # 2pc rm=8 host BFS (~millions of state evaluations at 1
        # thread): long enough that the cancel lands mid-run; the spec
        # timeout is only the no-cancel backstop.
        big = svc.submit({
            "workload": "twophase", "n": 8, "engine": "bfs",
            "threads": 1, "timeout": 120.0,
        })
        queued = svc.submit({"workload": "twophase", "n": 3})
        deadline = time.time() + 60
        while big.state != "running" and time.time() < deadline:
            time.sleep(0.02)
        assert big.state == "running"
        assert svc.cancel(queued.id)
        assert queued.state == CANCELLED
        t_cancel = time.monotonic()
        assert svc.cancel(big.id)
        assert big.wait(60)
        assert big.state == CANCELLED
        # Cooperative stop is prompt (a timeout would take ~120 s).
        assert time.monotonic() - t_cancel < 30
        assert big.result["completed"] is False
        assert big.result["unique_state_count"] > 0  # partial counts stand
        events = [e["event"] for e in read_journal(str(tmp_path / "j.jsonl"))]
        assert events.count("job_cancelled") == 2
        # Cancelling a terminal job is refused.
        assert not svc.cancel(big.id)
    finally:
        svc.scheduler.shutdown()


def test_job_spans_and_slo_metrics(tmp_path):
    """Per-job lifecycle spans (docs/SERVING.md "Job SLO metrics"): the
    scheduler stamps queue_wait/run/total ``job_span`` events into the
    journal for completed AND cancelled jobs, and the aggregated
    metrics carry the SLO histograms, queue p95, and the warm-start
    ratio; the whole dict renders as a parseable Prometheus
    exposition."""
    from stateright_tpu.obs.prometheus import (
        parse_prometheus, render_prometheus,
    )

    svc = CheckService(
        journal=str(tmp_path / "j.jsonl"),
        knob_cache_dir=str(tmp_path / "knobs"),
    )
    try:
        done = submit_and_wait(
            svc, {"workload": "fixtures", "n": 5, "engine": "bfs"})
        assert done.state == DONE
        # A blocker keeps the single worker busy so the next job is
        # deterministically cancelled while still queued.
        blocker = svc.submit({
            "workload": "twophase", "n": 8, "engine": "bfs",
            "threads": 1, "timeout": 120.0,
        })
        queued = svc.submit({"workload": "twophase", "n": 3})
        deadline = time.time() + 60
        while blocker.state != "running" and time.time() < deadline:
            time.sleep(0.02)
        assert svc.cancel(queued.id) and queued.state == CANCELLED
        assert svc.cancel(blocker.id) and blocker.wait(60)
        assert blocker.state == CANCELLED

        spans = [e for e in read_journal(str(tmp_path / "j.jsonl"))
                 if e["event"] == "job_span"]
        by_job = {}
        for s in spans:
            by_job.setdefault(s["job"], set()).add(s["span"])
        assert by_job[done.id] == {"queue_wait", "run", "total"}
        assert by_job[queued.id] == {"total"}  # never started: no run span
        assert by_job[blocker.id] == {"queue_wait", "run", "total"}
        assert all(s["sec"] >= 0 for s in spans)

        m = svc.metrics()
        hists = m["histograms"]
        assert hists["job_queue_wait_sec"]["count"] == 2  # done + blocker
        assert hists["job_total_sec"]["count"] == 3  # every terminal job
        assert hists["job_run_sec"]["count"] == 2
        assert m["queue_wait_p95_sec"] >= 0
        assert m["jobs_cancelled"] == 2
        assert 0.0 <= m.get("warm_start_ratio", 0.0) <= 1.0

        fams = parse_prometheus(render_prometheus(m))
        assert fams["stateright_job_total_sec"]["type"] == "histogram"
        assert fams["stateright_jobs_cancelled"]["type"] == "counter"
        assert fams["stateright_jobs_cancelled"]["samples"][0][2] == 2
    finally:
        svc.scheduler.shutdown()


def test_http_metrics_prometheus_exposition(http_service):
    """GET /.metrics?format=prometheus on the serve server: text
    exposition content type, parseable, job SLO series present."""
    from stateright_tpu.obs.prometheus import parse_prometheus

    svc, base = http_service
    job = svc.submit({"workload": "fixtures", "n": 5, "engine": "bfs"})
    assert job.wait(60)
    req = urllib.request.Request(base + "/.metrics?format=prometheus")
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.headers.get("Content-Type", "").startswith(
            "text/plain"
        )
        text = resp.read().decode()
    fams = parse_prometheus(text)
    assert fams["stateright_jobs_completed"]["samples"][0][2] == 1
    assert fams["stateright_job_queue_wait_sec"]["type"] == "histogram"
    jobs = {
        labels["key"]: v
        for _, labels, v in fams["stateright_jobs"]["samples"]
    }
    assert jobs["done"] == 1
    # JSON stays the default without the format param.
    assert http_json("GET", base + "/.metrics")["jobs_completed"] == 1


def test_request_stop_stops_tpu_engine_promptly():
    """Engine-level pin for the service's cancel path: request_stop on a
    running wavefront checker winds it down like a deadline."""
    model, _, _ = build_model("twophase", 5)
    # timeout forces waves_per_call=1, so the stop lands between waves.
    ck = model.checker().timeout(300).spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 5
    )
    ck.request_stop()
    t0 = time.monotonic()
    ck.join()
    assert time.monotonic() - t0 < 60
    assert ck.is_done()
    assert ck.stop_requested()


# --- portfolio mode ----------------------------------------------------------


def test_diversify_is_deterministic_and_anchored():
    a = diversify(6, seed=42, base_engine="tpu",
                  base_kwargs={"capacity": 1 << 12, "max_frontier": 1 << 6},
                  symmetry_capable=True)
    b = diversify(6, seed=42, base_engine="tpu",
                  base_kwargs={"capacity": 1 << 12, "max_frontier": 1 << 6},
                  symmetry_capable=True)
    assert [m.describe() for m in a] == [m.describe() for m in b]
    assert a[0].kind == "exhaustive"
    assert a[0].engine_kwargs == {"capacity": 1 << 12,
                                  "max_frontier": 1 << 6}
    assert any(m.kind == "simulation" for m in a)
    c = diversify(6, seed=43, base_engine="tpu",
                  base_kwargs={"capacity": 1 << 12, "max_frontier": 1 << 6})
    assert [m.describe() for m in a] != [m.describe() for m in c]


def test_diversify_folds_ensemble_winning_seeds_into_sim_members():
    """Chaos-ensemble winning seeds preempt the derived simulation-seed
    draws (masked to the 31-bit walker range); everything else in the
    portfolio — including later simulation members — is unchanged."""
    kwargs = {"capacity": 1 << 12, "max_frontier": 1 << 6}
    base = diversify(9, seed=42, base_engine="tpu", base_kwargs=kwargs)
    won = diversify(9, seed=42, base_engine="tpu", base_kwargs=kwargs,
                    winning_seeds=[12918135221727111561])
    sims_base = [m for m in base if m.kind == "simulation"]
    sims_won = [m for m in won if m.kind == "simulation"]
    assert len(sims_won) == len(sims_base) >= 2
    assert sims_won[0].seed == 12918135221727111561 & ((1 << 31) - 1)
    # The derived-seed stream still advanced: later sims are untouched.
    assert [m.seed for m in sims_won[1:]] == [m.seed for m in sims_base[1:]]
    # Member 0 stays the unmodified exhaustive anchor.
    assert won[0].describe() == base[0].describe()
    # Purity holds with the new argument too.
    again = diversify(9, seed=42, base_engine="tpu", base_kwargs=kwargs,
                      winning_seeds=[12918135221727111561])
    assert [m.describe() for m in won] == [m.describe() for m in again]


def test_ensemble_capable_workloads():
    from stateright_tpu.serve.workloads import ensemble_capable

    assert ensemble_capable("abd") is True
    assert ensemble_capable("paxos") is False
    with pytest.raises(ValueError):
        ensemble_capable("nonesuch")


def run_portfolio_job(tmp_path, tag, seed=7):
    svc = CheckService(
        journal=str(tmp_path / f"{tag}.jsonl"),
        knob_cache_dir=str(tmp_path / f"{tag}-knobs"),
    )
    try:
        job = submit_and_wait(svc, {
            "workload": "fixtures", "n": 5,
            "portfolio": {"size": 4, "seed": seed},
        })
        return job, read_journal(str(tmp_path / f"{tag}.jsonl")), svc.metrics()
    finally:
        svc.scheduler.shutdown()


def test_portfolio_first_winner_cancels_losers_deterministically(tmp_path):
    """Acceptance: on a violating model the first counterexample wins,
    remaining configs are cancelled, the winner (config + path) is
    journaled, and the outcome is deterministic given the seed set."""
    job1, events1, metrics1 = run_portfolio_job(tmp_path, "a")
    job2, events2, _ = run_portfolio_job(tmp_path, "b")
    for job in (job1, job2):
        assert job.state == DONE, job.error
        assert job.result["violation"] == "reaches limit"
    p1, p2 = job1.result["portfolio"], job2.result["portfolio"]
    assert p1["winner"] is not None
    # Determinism given the seed set: same winner, same config, same
    # counterexample fingerprints.
    assert p1["winner"]["member"] == p2["winner"]["member"]
    assert p1["winner"]["config"] == p2["winner"]["config"]
    assert (p1["winner"]["discovery"]["fingerprints"]
            == p2["winner"]["discovery"]["fingerprints"])
    # First winner cancels every loser.
    statuses = [m["status"] for m in p1["members"]]
    assert statuses.count("won") == 1
    win_idx = statuses.index("won")
    assert all(s in ("cancelled", "stopped", "completed")
               for i, s in enumerate(statuses) if i != win_idx)
    assert statuses.count("cancelled") >= 1
    kinds = [e["event"] for e in events1]
    assert "portfolio_start" in kinds
    assert "portfolio_winner" in kinds
    assert "portfolio_member_cancelled" in kinds
    assert metrics1["portfolio_wins"] == 1
    assert metrics1["violations_found"] == 1
    # The winning config is folded back into the knob cache.
    winner = p1["winner"]
    label = workload_label("fixtures", 5, None,
                           winner["config"]["symmetry"])
    if winner["config"]["engine"] != "tpu":
        label += ":portfolio-winner"
    assert load_knobs(str(tmp_path / "a-knobs"), knob_key(label)) is not None


def test_portfolio_on_clean_model_completes_exhaustively(service):
    """No violation anywhere: the exhaustive anchor completes and its
    counts are authoritative; there is no winner."""
    job = submit_and_wait(service, {
        "workload": "twophase", "n": 3,
        "engine_kwargs": {"capacity": 1 << 14, "max_frontier": 1 << 7},
        "portfolio": {"size": 3, "seed": 1, "simulation": False},
    })
    assert job.state == DONE, job.error
    assert job.result["violation"] is None
    assert job.result["portfolio"]["winner"] is None
    assert job.result["unique_state_count"] == 288


# --- HTTP surface ------------------------------------------------------------


@pytest.fixture
def http_service(tmp_path):
    from stateright_tpu.serve.server import serve

    svc = serve(
        ("127.0.0.1", 0), block=False,
        journal=str(tmp_path / "journal.jsonl"),
        knob_cache_dir=str(tmp_path / "knobs"),
    )
    host, port = svc.address
    yield svc, f"http://{host}:{port}"
    svc.http_server.shutdown()
    svc.scheduler.shutdown()


def http_json(method, url, body=None, timeout=30):
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_http_submit_status_result_metrics_cancel(http_service):
    svc, base = http_service
    # Submit a clean job and a violating portfolio job over HTTP.
    clean = http_json("POST", base + "/jobs", SMALL_2PC)
    viol = http_json("POST", base + "/jobs", {
        "workload": "fixtures", "n": 5,
        "portfolio": {"size": 3, "seed": 7},
    })
    assert clean["state"] == "queued"
    for jid, want_violation in ((clean["id"], None),
                                (viol["id"], "reaches limit")):
        deadline = time.time() + 300
        while time.time() < deadline:
            snap = http_json("GET", f"{base}/jobs/{jid}/result?wait=10")
            if snap["state"] not in ("queued", "running"):
                break
        assert snap["state"] == "done", snap
        assert snap["result"]["violation"] == want_violation
    listing = http_json("GET", base + "/jobs")
    assert [j["id"] for j in listing] == [clean["id"], viol["id"]]
    metrics = http_json("GET", base + "/.metrics")
    assert metrics["jobs"]["done"] == 2
    assert metrics["jobs_completed"] == 2
    assert metrics["violations_found"] == 1
    assert "program_cache_hits" in metrics
    status = http_json("GET", base + "/.status")
    assert "fixtures" in status["workloads"]
    # Errors: unknown job 404, bad spec 400, cancel-after-done 409.
    for method, path, body, code in (
        ("GET", "/jobs/nope", None, 404),
        ("POST", "/jobs", {"workload": "twophase", "bogus": 1}, 400),
        ("POST", f"/jobs/{clean['id']}/cancel", None, 409),
    ):
        with pytest.raises(urllib.error.HTTPError) as e:
            http_json(method, base + path, body)
        assert e.value.code == code


def test_http_explore_attaches_explorer_to_completed_job(http_service):
    svc, base = http_service
    resp = http_json("POST", base + "/jobs", SMALL_2PC)
    jid = resp["id"]
    snap = http_json("GET", f"{base}/jobs/{jid}/result?wait=120")
    assert snap["state"] == "done"
    attach = http_json("POST", f"{base}/jobs/{jid}/explore", {})
    ehost, eport = attach["explorer_address"]
    estatus = http_json("GET", f"http://{ehost}:{eport}/.status")
    assert estatus["unique_state_count"] == 288
    emetrics = http_json("GET", f"http://{ehost}:{eport}/.metrics")
    assert emetrics["engine"] == "tpu-wavefront"
    # Idempotent: a second attach returns the same address.
    again = http_json("POST", f"{base}/jobs/{jid}/explore", {})
    assert again["explorer_address"] == attach["explorer_address"]


def test_checker_retention_cap_releases_oldest(tmp_path):
    """A persistent daemon must not pin every completed job's checker
    (device table + row log) forever: past the retention cap the oldest
    unexplored checker is released — the result survives, only
    Explorer attach stops working."""
    svc = CheckService(knob_cache_dir=str(tmp_path / "knobs"),
                       retain_checkers=1)
    try:
        j1 = submit_and_wait(
            svc, {"workload": "fixtures", "n": 5, "engine": "bfs"})
        j2 = submit_and_wait(
            svc, {"workload": "fixtures", "n": 6, "engine": "bfs"})
        assert j1.checker is None  # released past the cap
        assert j2.checker is not None
        assert j1.result["violation"] == "reaches limit"  # result intact
        with pytest.raises(ValueError, match="no attached checker"):
            svc.explore(j1)
        assert svc.explore(j2) is not None
    finally:
        svc.scheduler.shutdown()


# --- service journal under concurrent jobs -----------------------------------


def test_service_journal_lines_never_tear_under_concurrent_writers(tmp_path):
    """Satellite pin: many threads appending through separate Journal
    instances sharing one path never produce a torn JSONL line (each
    append is a single O_APPEND write)."""
    from stateright_tpu.runtime.journal import Journal

    path = str(tmp_path / "shared.jsonl")
    writers, per = 8, 200
    payload = "x" * 512  # well past any buffered-chunk boundary

    def write_events(k):
        j = Journal(path)  # own descriptor, like a separate job/process
        for i in range(per):
            j.append("stress", writer=k, i=i, pad=payload)
        j.close()

    threads = [
        threading.Thread(target=write_events, args=(k,))
        for k in range(writers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with open(path, encoding="utf-8") as fh:
        lines = fh.read().splitlines()
    seen = set()
    syncs = 0
    for line in lines:
        rec = json.loads(line)  # raises on any torn/interleaved line
        if rec["event"] == "clock_sync":
            syncs += 1
            continue
        seen.add((rec["writer"], rec["i"]))
    assert len(seen) == writers * per
    # Each Journal instance contributes exactly one clock_sync header
    # (its monotonic anchor), itself a whole line like any other.
    assert syncs == writers
    assert len(lines) == writers * per + syncs


def test_running_job_snapshot_carries_live_vitals():
    """GET /jobs/{id} while RUNNING embeds the engine's live vitals
    subset (obs/metrics.VITALS_KEYS) — and drops it again once the job
    is terminal (the result carries the final counts instead)."""
    from stateright_tpu.obs.metrics import VITALS_KEYS
    from stateright_tpu.serve.jobs import RUNNING, Job

    class FakeChecker:
        def metrics(self):
            return {
                "unique_state_count": 123, "state_count": 456,
                "max_depth": 7, "waves": 9, "uniq_per_sec_ema": 1000.5,
                "table_load_factor": 0.02, "valid_density_ema": 0.004,
                "grows": 1, "overflow_retries": 2, "engine": "x",
                "not_a_vital": 1,
            }

    job = Job("job-000042", JobSpec(workload="twophase", n=3))
    assert "vitals" not in job.snapshot()  # queued: no checker yet
    job.state = RUNNING
    job.checker = FakeChecker()
    snap = job.snapshot()
    vit = snap["vitals"]
    assert vit["unique_state_count"] == 123
    assert vit["valid_density_ema"] == 0.004
    assert set(vit) <= set(VITALS_KEYS)
    assert "not_a_vital" not in vit
    json.dumps(snap)

    # A checker whose metrics() raises mid-teardown never breaks the
    # snapshot.
    class Exploding:
        def metrics(self):
            raise RuntimeError("buffers freed")

    job.checker = Exploding()
    assert "vitals" not in job.snapshot()

    job.state = DONE
    job.checker = FakeChecker()
    assert "vitals" not in job.snapshot()  # terminal: result is the record


def test_running_job_vitals_over_http(http_service):
    """Integration: poll GET /jobs/{id} while a job actually runs; at
    least one poll of a non-trivial job sees the vitals key (best
    effort — a fast box may finish first, so only the SHAPE is pinned
    when we do catch it)."""
    svc, base = http_service

    def req(method, path, body=None):
        data = None if body is None else json.dumps(body).encode()
        r = urllib.request.Request(base + path, data=data, method=method)
        with urllib.request.urlopen(r, timeout=30) as resp:
            return json.loads(resp.read())

    resp = req("POST", "/jobs", {
        "workload": "twophase", "n": 4,
        "engine_kwargs": {"capacity": 1 << 14, "max_frontier": 1 << 5,
                          "waves_per_call": 1},
    })
    saw_vitals = None
    for _ in range(400):
        snap = req("GET", f"/jobs/{resp['id']}")
        if snap["state"] not in ("queued", "running"):
            break
        if snap["state"] == "running" and "vitals" in snap:
            saw_vitals = snap["vitals"]
        time.sleep(0.01)
    final = req("GET", f"/jobs/{resp['id']}/result?wait=60")
    assert final["state"] == "done", final
    assert "vitals" not in final
    if saw_vitals is not None:
        assert saw_vitals["unique_state_count"] >= 0
        assert "table_load_factor" in saw_vitals


# --- servable-spec round-trips and worker attribution ------------------------


@pytest.mark.parametrize("name", workload_names())
def test_every_servable_cli_spec_defaults_validate_as_jobspec(name):
    """Every SERVABLE name must resolve a cli_spec() whose defaults
    survive JobSpec validation end-to-end — a workload registered but
    unsubmittable is a registration bug, caught here instead of by the
    first user."""
    from stateright_tpu.serve.workloads import cli_spec_for

    cli = cli_spec_for(name)
    spec = JobSpec.from_dict({
        "workload": name, "n": cli.default_n,
        "network": cli.default_network,
    })
    # The dict round-trip is exact (what the fleet store journals).
    assert JobSpec.from_dict(spec.to_dict()).to_dict() == spec.to_dict()
    model, _cli, n = build_model(spec.workload, spec.n, spec.network)
    assert n == cli.default_n
    assert model.properties()


def test_every_job_event_carries_worker_stamp(tmp_path):
    """Satellite: multi-worker attribution — every job_* lifecycle row
    is stamped with the worker (pid@host) that wrote it, and the report
    job table renders it."""
    import os as _os
    import socket as _socket

    journal = tmp_path / "journal.jsonl"
    svc = CheckService(journal=str(journal))
    try:
        job = svc.submit(SMALL_2PC)
        assert job.wait(300)
    finally:
        svc.scheduler.shutdown()
    stamp = f"{_os.getpid()}@{_socket.gethostname()}"
    job_events = [
        e for e in read_journal(str(journal))
        if str(e.get("event", "")).startswith("job_")
    ]
    assert job_events
    assert all(e.get("worker") == stamp for e in job_events)
    from stateright_tpu.obs.report import analyze_journal, render_markdown

    report = analyze_journal(str(journal))
    detail = report["jobs"]["detail"]
    assert all(j.get("worker") == stamp for j in detail.values())
    md = render_markdown(report)
    assert "| worker |" in md and stamp in md


def test_serve_main_rejects_nonpositive_workers(capsys):
    from stateright_tpu.serve.__main__ import main as serve_main

    for bad in ("0", "-3"):
        assert serve_main(["--workers", bad]) == 2
        err = capsys.readouterr().err
        assert "--workers must be >= 1" in err
        assert "fleet" in err  # points at the per-backend alternative


def test_serve_main_rejects_fleet_dir_with_inprocess_flags(
    tmp_path, capsys
):
    from stateright_tpu.serve.__main__ import main as serve_main

    rc = serve_main([
        "--fleet-dir", str(tmp_path), "--workers", "2",
    ])
    assert rc == 2
    assert "--fleet-dir" in capsys.readouterr().err
