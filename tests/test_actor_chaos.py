"""Chaos-capable actor runtime: fault-injecting transport determinism,
ABD under chaos with live linearizability auditing, and journal-visible
retransmission give-up.

The acceptance triangle (ISSUE 2): a fixed seed reproduces the injected
fault schedule bit-for-bit; healthy ABD replicas under
drop+duplicate+reorder+partition/heal produce a history the existing
``LinearizabilityTester`` accepts; a deliberately-broken skip-ack replica
produces one it rejects.
"""

import json

import pytest

from stateright_tpu.actor.ids import Id
from stateright_tpu.actor.transport import LoopbackTransport, TransportClosed
from stateright_tpu.runtime.chaos import (
    ChaosSpec,
    FaultyTransport,
    LiveAuditor,
    Partition,
    RecordingTransport,
    WireEnvelope,
)
from stateright_tpu.runtime.journal import read_journal


# --- chaos spec parsing ------------------------------------------------------


def test_chaos_spec_parses_shorthand_links_and_partitions():
    spec = ChaosSpec.from_json(
        '{"drop": 0.2, "delay": [0.0, 0.05],'
        ' "links": {"0->1": {"drop": 0.5}},'
        ' "partitions": [{"at": 0.1, "heal": 0.5, "groups": [[0, 1], [2]]}]}'
    )
    assert spec.default.drop == 0.2
    assert spec.default.delay == (0.0, 0.05)
    assert spec.faults_for(Id(0), Id(1)).drop == 0.5
    # Per-link overrides replace the whole fault set for that link.
    assert spec.faults_for(Id(0), Id(1)).delay == (0.0, 0.0)
    assert spec.faults_for(Id(1), Id(0)).drop == 0.2
    p = spec.partitions[0]
    assert p.cuts(0, 2, elapsed=0.3)
    assert not p.cuts(0, 1, elapsed=0.3)  # same group
    assert not p.cuts(0, 2, elapsed=0.05)  # before the window
    assert not p.cuts(0, 2, elapsed=0.6)  # healed
    assert not p.cuts(0, 5, elapsed=0.3)  # 5 is in no group: unaffected


def test_chaos_spec_rejects_malformed_input():
    for bad in (
        "[1, 2]",  # not an object
        '{"drop": 1.5}',  # rate out of range
        '{"drop": true}',  # not a number
        '{"frobnicate": 0.1}',  # unknown key
        '{"drop": 0.1, "default": {"drop": 0.2}}',  # both spellings
        '{"links": {"0-1": {}}}',  # malformed link key
        '{"delay": [0.5, 0.1]}',  # hi < lo
        '{"partitions": [{"at": 1.0, "heal": 0.5, "groups": [[0]]}]}',
        '{"partitions": [{"groups": [[0]]}]}',  # missing at
        "{nope",  # not JSON at all
    ):
        with pytest.raises(ValueError):
            ChaosSpec.from_json(bad)


def test_chaos_spec_errors_name_the_offending_key_path():
    """Malformed faults/partition blocks raise a single ValueError whose
    message names the offending key path — never a raw KeyError or
    TypeError from deep inside the parser."""
    for bad, path in (
        ('{"links": [1]}', "links"),  # links container not an object
        ('{"links": {"0->1": [0.5]}}', "links[0->1]"),  # link value
        ('{"default": [1]}', "default"),  # default block not an object
        ('{"partitions": {"at": 0}}', "partitions"),  # container not array
        ('{"partitions": [5]}', "partitions[0]"),  # entry not an object
        ('{"partitions": [{"at": 0, "groups": [[0]], "bogus": 1}]}',
         "partitions[0]"),  # unknown key
        ('{"partitions": [{"at": "x", "groups": [[0]]}]}',
         "partitions[0].at"),
        ('{"partitions": [{"at": 0, "heal": "x", "groups": [[0]]}]}',
         "partitions[0].heal"),
        ('{"partitions": [{"at": 0, "groups": 5}]}', "partitions[0].groups"),
        ('{"partitions": [{"at": 0, "groups": [5]}]}',
         "partitions[0].groups[0]"),
        ('{"partitions": [{"at": 0, "groups": [["x"]]}]}',
         "partitions[0].groups[0]"),
    ):
        with pytest.raises(ValueError) as exc:
            ChaosSpec.from_json(bad)
        assert path in str(exc.value), (bad, str(exc.value))


def test_chaos_spec_remap_ids_onto_real_addresses():
    """Specs are written with model indices; the UDP spawn path remaps
    them onto socket-addr ids so links/partitions actually match."""
    spec = ChaosSpec.from_json(
        '{"links": {"0->1": {"drop": 1.0}},'
        ' "partitions": [{"at": 0, "groups": [[0], [1]]}]}'
    )
    remapped = spec.remap_ids({0: 100, 1: 200})
    assert remapped.faults_for(Id(100), Id(200)).drop == 1.0
    assert remapped.faults_for(Id(0), Id(1)).drop == 0.0
    assert remapped.partitions[0].cuts(100, 200, elapsed=0.1)
    assert not remapped.partitions[0].cuts(0, 1, elapsed=0.1)


def test_partition_without_heal_is_permanent():
    p = Partition(at=0.0, heal=None, groups=(frozenset([0]), frozenset([1])))
    assert p.cuts(0, 1, elapsed=1e9)


# --- loopback transport ------------------------------------------------------


def test_loopback_transport_delivers_and_closes():
    lb = LoopbackTransport()
    a, b = lb.bind(Id(0)), lb.bind(Id(1))
    a.send(Id(1), b"hello")
    assert b.recv(1.0) == (b"hello", Id(0))
    assert b.recv(0.01) is None  # timeout, not closed
    a.send(Id(42), b"dropped")  # unbound destination: silent drop
    with pytest.raises(OSError):
        lb.bind(Id(0))  # address in use
    b.close()
    with pytest.raises(TransportClosed):
        b.recv(1.0)


# --- seeded fault-schedule reproducibility -----------------------------------

_SCHED_SPEC = ChaosSpec.from_json(
    '{"drop": 0.25, "duplicate": 0.2, "reorder": 0.2,'
    ' "links": {"2->1": {"drop": 0.6}}}'
)


def _drive_schedule(journal_path, seed):
    """Send a fixed two-link datagram sequence through FaultyTransport and
    return (fault events sans timestamps, delivered (data, src) sequence)."""
    lb = LoopbackTransport()
    ft = FaultyTransport(lb, _SCHED_SPEC, seed=seed, journal=str(journal_path))
    a, c = ft.bind(Id(0)), ft.bind(Id(2))
    b = ft.bind(Id(1))
    for i in range(150):
        src = a if i % 3 else c
        src.send(Id(1), f"m{i}".encode())
    received = []
    while True:
        r = b.recv(0.05)
        if r is None:
            break
        received.append((r[0], int(r[1])))
    ft.close()
    events = [
        {k: v for k, v in e.items() if k != "t"}
        for e in read_journal(str(journal_path))
        if e["event"].startswith("chaos_") and e["event"] != "chaos_start"
    ]
    return events, received


def test_fault_schedule_is_bit_reproducible_for_a_fixed_seed(tmp_path):
    ev1, got1 = _drive_schedule(tmp_path / "j1.jsonl", seed=7)
    ev2, got2 = _drive_schedule(tmp_path / "j2.jsonl", seed=7)
    assert ev1, "the seeded spec should have injected faults"
    assert ev1 == ev2, "same seed must reproduce the exact fault schedule"
    assert got1 == got2, "same seed must reproduce the delivered sequence"
    ev3, got3 = _drive_schedule(tmp_path / "j3.jsonl", seed=8)
    assert (ev1, got1) != (ev3, got3), "a different seed must differ"


def test_fault_schedule_is_per_link_not_per_interleaving(tmp_path):
    """The n-th datagram on a link gets the same fate regardless of what
    other links did in between: interleaving two links differently must
    not change either link's per-link schedule."""

    def fates(journal_path, interleave):
        lb = LoopbackTransport()
        ft = FaultyTransport(
            lb, _SCHED_SPEC, seed=3, journal=str(journal_path)
        )
        a, c = ft.bind(Id(0)), ft.bind(Id(2))
        ft.bind(Id(1))
        if interleave:
            for i in range(40):
                a.send(Id(1), b"x")
                c.send(Id(1), b"y")
        else:
            for i in range(40):
                a.send(Id(1), b"x")
            for i in range(40):
                c.send(Id(1), b"y")
        ft.close()
        by_link = {}
        for e in read_journal(str(journal_path)):
            if e["event"].startswith("chaos_") and "src" in e:
                by_link.setdefault((e["src"], e["dst"]), []).append(
                    (e["event"], e["n"])
                )
        return by_link

    assert fates(tmp_path / "a.jsonl", True) == fates(tmp_path / "b.jsonl", False)


def test_delay_faults_are_injected_and_journaled(tmp_path):
    spec = ChaosSpec.from_json('{"delay": [0.01, 0.03]}')
    lb = LoopbackTransport()
    ft = FaultyTransport(lb, spec, seed=1, journal=str(tmp_path / "j.jsonl"))
    a, b = ft.bind(Id(0)), ft.bind(Id(1))
    a.send(Id(1), b"late")
    assert b.recv(0.001) is None, "delayed datagram must not arrive instantly"
    assert b.recv(2.0) == (b"late", Id(0))
    ft.close()
    events = read_journal(str(tmp_path / "j.jsonl"))
    delays = [e for e in events if e["event"] == "chaos_delay"]
    assert len(delays) == 1 and 0.01 <= delays[0]["sec"] <= 0.03


# --- transport-boundary recording --------------------------------------------


def test_recording_transport_taps_both_directions():
    outs, ins = [], []
    rt = RecordingTransport(
        LoopbackTransport(),
        deserialize=lambda b: b.decode(),
        on_out=outs.append,
        on_in=ins.append,
    )
    a, b = rt.bind(Id(0)), rt.bind(Id(1))
    a.send(Id(1), b"ping")
    assert b.recv(1.0) == (b"ping", Id(0))
    assert outs == [WireEnvelope(Id(0), Id(1), "ping")]
    assert ins == [WireEnvelope(Id(0), Id(1), "ping")]
    rt.close()


# --- the live auditor (unit level) -------------------------------------------


def _env(src, dst, msg):
    return WireEnvelope(Id(src), Id(dst), msg)


def test_live_auditor_dedups_retransmits_and_checks_real_time_order():
    from stateright_tpu.actor.ordered_reliable_link import Deliver
    from stateright_tpu.actor.register import Get, GetOk, Put, PutOk
    from stateright_tpu.semantics import LinearizabilityTester, Register

    auditor = LiveAuditor(
        LinearizabilityTester(Register(None)), client_ids=[Id(3), Id(4)]
    )
    # Client 3 writes "A" — the ORL retransmits the datagram twice.
    auditor.on_out(_env(3, 0, Deliver(1, Put(3, "A"))))
    auditor.on_out(_env(3, 0, Deliver(1, Put(3, "A"))))
    auditor.on_in(_env(0, 3, Deliver(1, PutOk(3))))
    auditor.on_in(_env(0, 3, Deliver(1, PutOk(3))))  # chaos duplicate
    # Server-internal traffic is not part of the history.
    auditor.on_out(_env(0, 1, "internal gossip"))
    # Client 4 then reads and must see the completed write.
    auditor.on_out(_env(4, 1, Deliver(1, Get(4))))
    auditor.on_in(_env(1, 4, Deliver(1, GetOk(4, "A"))))
    assert auditor.invoked_count == 2 and auditor.returned_count == 2
    assert auditor.result()["consistent"] is True


def test_live_auditor_rejects_a_stale_read_after_a_completed_write():
    from stateright_tpu.actor.register import Get, GetOk, Put, PutOk
    from stateright_tpu.semantics import LinearizabilityTester, Register

    auditor = LiveAuditor(
        LinearizabilityTester(Register(None)), client_ids=[Id(3), Id(4)]
    )
    auditor.on_out(_env(3, 0, Put(3, "A")))  # plain (non-ORL) messages work too
    auditor.on_in(_env(0, 3, PutOk(3)))
    auditor.on_out(_env(4, 1, Get(4)))  # invoked strictly after the write
    auditor.on_in(_env(1, 4, GetOk(4, None)))  # ...but misses it
    result = auditor.result()
    assert result["consistent"] is False and result["violations"] == []


def test_live_auditor_flags_orphan_returns():
    from stateright_tpu.actor.register import PutOk
    from stateright_tpu.semantics import LinearizabilityTester, Register

    auditor = LiveAuditor(LinearizabilityTester(Register(None)), [Id(3)])
    auditor.on_in(_env(0, 3, PutOk(9)))
    result = auditor.result()
    assert not result["consistent"]
    assert "without invocation" in result["violations"][0]


# --- the acceptance triangle: ABD under chaos, audited live ------------------


class _Opts:
    def __init__(self, spec, seed, journal=None, duration=30.0, audit=True,
                 trace=False, metrics_port=None):
        self.spec = ChaosSpec.from_json(spec)
        self.seed = seed
        self.audit = audit
        self.journal = journal
        self.duration = duration
        self.trace = trace
        self.metrics_port = metrics_port


def test_abd_under_chaos_audits_linearizable(tmp_path):
    """Healthy ABD replicas under drop+duplicate+reorder+partition/heal:
    the live history must satisfy the same LinearizabilityTester the
    model checker runs, and the run must journal its faults."""
    from stateright_tpu.models.abd import run_chaos_audit

    journal = str(tmp_path / "journal.jsonl")
    result = run_chaos_audit(
        _Opts(
            '{"drop": 0.15, "duplicate": 0.15, "reorder": 0.2,'
            ' "partitions":'
            ' [{"at": 0.2, "heal": 0.8, "groups": [[0, 1, 3], [2, 4]]}]}',
            seed=11,
            journal=journal,
        )
    )
    assert result["consistent"], result
    assert result["errors"] == [], result
    assert result["returned"] >= 1, "some operations must have completed"
    faults = result["faults"]
    assert faults.get("chaos_drop") and faults.get("chaos_duplicate")
    assert faults.get("chaos_reorder")
    events = [e["event"] for e in read_journal(journal)]
    assert events[0] == "chaos_start"
    assert events[-1] == "audit"
    assert "chaos_drop" in events


def test_abd_chaos_run_is_seed_reproducible_in_its_fault_schedule(tmp_path):
    """Two chaos runs with the same seed inject identical per-link fault
    schedules (event kind + per-link datagram index), even though thread
    interleaving differs between runs."""
    from stateright_tpu.models.abd import run_chaos_audit

    def link_schedule(name):
        journal = str(tmp_path / name)
        run_chaos_audit(
            _Opts('{"drop": 0.2, "duplicate": 0.2}', seed=5, journal=journal)
        )
        by_link = {}
        for e in read_journal(journal):
            if e["event"].startswith("chaos_") and "src" in e:
                by_link.setdefault((e["src"], e["dst"]), []).append(
                    (e["event"], e["n"])
                )
        return by_link

    s1, s2 = link_schedule("r1.jsonl"), link_schedule("r2.jsonl")
    assert s1, "the seeded run should have injected faults"
    # The slower run may have carried a few more retransmits on a link;
    # the shared prefix of every link's schedule must agree exactly.
    for link in set(s1) | set(s2):
        a, b = s1.get(link, []), s2.get(link, [])
        n = min(len(a), len(b))
        assert a[:n] == b[:n], f"schedules diverge on link {link}"


def test_abd_chaos_schedule_reproducible_with_tracing_enabled(tmp_path):
    """ISSUE-15 acceptance: the causal trace envelope (actor/obs.py)
    wraps every datagram, yet the injected fault schedule for a fixed
    seed stays bit-identical — fault fate depends on the per-link
    datagram INDEX, never the bytes.  Same prefix-equality rule as the
    untraced reproducibility test, plus: the traced run audits
    consistent and journals actor_span events."""
    from stateright_tpu.models.abd import run_chaos_audit

    def link_schedule(name, trace):
        journal = str(tmp_path / name)
        result = run_chaos_audit(
            _Opts('{"drop": 0.2, "duplicate": 0.2}', seed=5,
                  journal=journal, trace=trace)
        )
        assert result["consistent"], result
        by_link = {}
        spans = 0
        for e in read_journal(journal):
            if e["event"].startswith("chaos_") and "src" in e:
                by_link.setdefault((e["src"], e["dst"]), []).append(
                    (e["event"], e["n"])
                )
            elif e["event"] == "actor_span":
                spans += 1
        return by_link, spans

    traced, spans = link_schedule("traced.jsonl", trace=True)
    untraced, no_spans = link_schedule("untraced.jsonl", trace=False)
    assert spans > 0, "tracing must journal actor_span events"
    assert no_spans == 0, "trace=False must journal no spans"
    assert traced, "the seeded run should have injected faults"
    for link in set(traced) | set(untraced):
        a, b = traced.get(link, []), untraced.get(link, [])
        n = min(len(a), len(b))
        assert a[:n] == b[:n], f"schedules diverge on link {link}"


def test_broken_skip_ack_replica_is_rejected_by_the_audit(tmp_path):
    """A replica that acks without a quorum round produces a history the
    LinearizabilityTester rejects (the read misses the completed write)."""
    from stateright_tpu.models.abd import run_chaos_audit

    journal = str(tmp_path / "journal.jsonl")
    result = run_chaos_audit(
        _Opts("{}", seed=0, journal=journal, duration=10.0),
        fault="skip_ack",
        client_count=1,
        put_count=1,
    )
    assert result["completed"], result
    assert not result["consistent"], (
        "the audit must reject the skip-ack replica's history"
    )
    audit = [e for e in read_journal(journal) if e["event"] == "audit"]
    assert audit and audit[-1]["consistent"] is False


def test_unknown_abd_fault_name_is_rejected():
    from stateright_tpu.models.abd import AbdActor

    with pytest.raises(ValueError):
        AbdActor([], fault="frobnicate")


def test_orl_gives_up_on_a_black_hole_link_and_journals_it(tmp_path):
    """A link dropping 100% of datagrams: the hardened ORL must stop
    retransmitting after max_resends and journal the give-up instead of
    hammering forever."""
    from stateright_tpu.actor.register import RegisterServer
    from stateright_tpu.models.abd import NULL_VALUE, AbdActor
    from stateright_tpu.runtime.chaos import run_chaos_register_system
    from stateright_tpu.semantics import LinearizabilityTester, Register

    journal = str(tmp_path / "journal.jsonl")
    result = run_chaos_register_system(
        lambda peers: RegisterServer(AbdActor(peers)),
        server_count=1,
        client_count=1,
        put_count=1,
        spec=ChaosSpec.from_json('{"links": {"1->0": {"drop": 1.0}}}'),
        seed=0,
        tester_factory=lambda: LinearizabilityTester(Register(NULL_VALUE)),
        journal=journal,
        deadline_sec=4.0,
        resend_interval=(0.02, 0.04),
        max_resends=3,
    )
    assert result["returned"] == 0
    assert result["in_flight"] == 1  # the Put is stuck, not lost silently
    give_ups = [e for e in read_journal(journal) if e["event"] == "orl_give_up"]
    assert give_ups, "the give-up must be journal-visible"
    assert give_ups[0]["actor"] == 1 and give_ups[0]["dropped"] >= 1
    # An unfinished run still audits cleanly: in-flight ops are optional.
    assert result["consistent"], result


def test_chaos_result_is_json_serializable(tmp_path):
    from stateright_tpu.models.abd import run_chaos_audit

    # One client: concurrent Puts can stall on a busy replica even
    # fault-free (the ORL acks a no-op'd delivery without redelivering),
    # so only a single sequential client makes completion deterministic.
    result = run_chaos_audit(
        _Opts("{}", seed=1, duration=10.0), client_count=1, put_count=2
    )
    assert result["consistent"] and result["completed"], result
    assert result["faults"] == {}
    json.dumps(result)  # the CLI prints this verbatim
