"""The tiered out-of-core engine (stateright_tpu/tiered/): ISSUE-9's
acceptance matrix — a workload exceeding the hot tier's capacity (forced
via a small budget) completes exactly, ``discovered_fingerprints()``
bit-identical to the in-HBM engine, including after a kill-mid-run
supervised resume; plus the cold store, the budget→capacity mapping, the
device merge-join, and the serve/CLI wiring."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from stateright_tpu.models.twophase import TwoPhaseSys  # noqa: E402
from stateright_tpu.runtime.journal import read_journal  # noqa: E402
from stateright_tpu.tiered import (  # noqa: E402
    ColdStore,
    capacity_for_budget,
)


def _tiered(model, **kwargs):
    kwargs.setdefault("capacity", 512)
    kwargs.setdefault("max_frontier", 1 << 6)
    return model.checker().spawn_tpu_tiered(**kwargs)


def _plain(model, **kwargs):
    kwargs.setdefault("capacity", 1 << 14)
    kwargs.setdefault("max_frontier", 1 << 6)
    return model.checker().spawn_tpu(**kwargs)


# --- cold store --------------------------------------------------------------


def test_cold_store_runs_merge_and_membership(tmp_path):
    s = ColdStore(max_runs=2)
    s.add_run(np.asarray([5, 1, 9], np.uint64))
    s.add_run(np.asarray([2, 9], np.uint64))  # overlap allowed
    assert s.run_count == 2
    assert s.entries == 5
    assert s.contains([1, 2, 3, 9]).tolist() == [True, True, False, True]
    # A third run crosses max_runs and triggers the LSM merge: one
    # deduplicated sorted run, same membership.
    s.add_run(np.asarray([3], np.uint64))
    assert s.run_count == 1
    assert s.entries == 5  # 1 2 3 5 9
    assert s.contains([1, 2, 3, 4, 5, 9]).tolist() == [
        True, True, True, False, True, True,
    ]
    # Empty spills are dropped.
    s.add_run(np.zeros((0,), np.uint64))
    assert s.run_count == 1

    # Snapshot round trip preserves the run structure.
    fps, lens = s.to_arrays()
    back = ColdStore.from_arrays(fps, lens)
    assert back.run_count == s.run_count and back.entries == s.entries
    assert back.contains([3, 4]).tolist() == [True, False]


def test_cold_store_disk_tier(tmp_path):
    d = str(tmp_path / "cold")
    s = ColdStore(spill_dir=d, max_runs=2)
    s.add_run(np.asarray([4, 2], np.uint64))
    s.add_run(np.asarray([8, 6], np.uint64))
    files = sorted(os.listdir(d))
    assert len(files) == 2 and all(f.endswith(".npy") for f in files)
    # Runs come back memory-mapped, sorted, and queryable.
    assert isinstance(s.runs[0], np.memmap)
    assert s.contains([2, 4, 6, 8, 10]).tolist() == [
        True, True, True, True, False,
    ]
    s.add_run(np.asarray([1], np.uint64))  # merge rewrites the disk set
    assert s.run_count == 1
    assert s.contains([1, 2, 4, 6, 8]).all()


def test_capacity_for_budget():
    # 12 B/slot (key planes + transient claim plane), power of two.
    assert capacity_for_budget(1.0) == 1 << 16
    assert capacity_for_budget(16) == 1 << 20
    assert capacity_for_budget(0.005) == 256  # the CI forcing budget
    for bad in (0, -1, 1e-9, float("nan"), float("inf")):
        # Sub-floor budgets refuse loudly (a silent round-up to the
        # minimum table would exceed the documented hard cap).
        with pytest.raises(ValueError):
            capacity_for_budget(bad)


# --- the acceptance pin: budget-constrained == unconstrained -----------------


def test_tiered_bit_identical_with_forced_evictions(tmp_path):
    """2pc(4)'s 1568 uniques against a 512-slot hot tier: multiple
    forced evictions, cold probes on device, and a discovery set
    bit-identical to the in-HBM engine."""
    journal = str(tmp_path / "tiered.jsonl")
    ref = _plain(TwoPhaseSys(rm_count=4)).join()
    t = _tiered(TwoPhaseSys(rm_count=4), journal=journal).join()

    assert t.unique_state_count() == ref.unique_state_count() == 1568
    assert t.state_count() == ref.state_count()
    assert t.max_depth() == ref.max_depth()
    assert sorted(t.discoveries()) == sorted(ref.discoveries())
    assert np.array_equal(
        t.discovered_fingerprints(), ref.discovered_fingerprints()
    )

    events = read_journal(journal)
    spills = [e for e in events if e["event"] == "spill"]
    probes = [e for e in events if e["event"] == "cold_probe"]
    assert len(spills) >= 2, "the budget did not force evictions"
    assert all(
        e["entries"] >= 0 and e["bytes"] == e["entries"] * 8
        for e in spills
    )
    assert probes, "no cold passes were journaled"
    assert all(e["passes"] >= 1 and e["bytes"] > 0 for e in probes)
    # The cold tier really answered duplicates (hits), and every spill's
    # watermark advanced monotonically.
    assert sum(e["hits"] for e in probes) > 0
    ends = [e["end"] for e in spills]
    assert ends == sorted(ends)

    m = t.metrics()
    assert m["engine"] == "tpu-tiered"
    assert m["spills"] == len(spills)
    assert m["cold_entries"] > 0 and m["cold_runs"] >= 1
    assert m["cold_probe_bytes_total"] == sum(e["bytes"] for e in probes)
    assert 0.0 <= m["table_load_factor"] <= 0.5


def test_memory_budget_knob_derives_capacity():
    """The user-facing knob: a small budget derives a tiny hot table,
    forces evictions, and still lands the golden."""
    t = TwoPhaseSys(rm_count=3).checker().spawn_tpu_tiered(
        memory_budget_mb=0.005, max_frontier=1 << 6,
    ).join()
    assert t.unique_state_count() == 288
    m = t.metrics()
    assert m["capacity"] == capacity_for_budget(0.005) == 256
    assert m["memory_budget_mb"] == 0.005
    assert m["spills"] >= 1
    # The budget is AUTHORITATIVE: a capacity riding along in merged
    # kwargs (workload-spec defaults, warm-started cache entries) must
    # not silently un-tier a budgeted run.
    t2 = TwoPhaseSys(rm_count=3).checker().spawn_tpu_tiered(
        memory_budget_mb=64, capacity=512, max_frontier=1 << 6,
    ).join()
    assert t2.metrics()["capacity"] == capacity_for_budget(64)


def test_tiered_ebits_and_violations_match():
    """A violating workload (trap counter: always- and sometimes-
    properties) discovered identically through the tiers."""
    from stateright_tpu.models.fixtures import TrapCounter

    ref = TrapCounter(50).checker().spawn_tpu(capacity=1 << 12).join()
    # capacity 64: the ~50-state chain spills at the 0.45 threshold.
    t = TrapCounter(50).checker().spawn_tpu_tiered(
        capacity=64, max_frontier=1 << 6
    ).join()
    assert sorted(t.discoveries()) == sorted(ref.discoveries())
    for name, path in ref.discoveries().items():
        assert t.discoveries()[name].into_actions() == path.into_actions()
    assert t.metrics()["spills"] >= 1


def test_tiered_symmetry_canonical_keys_through_tiers():
    """Symmetry reduction dedups on canonical fingerprints; spills must
    evict the same canonical keys (2pc rm=4 orbit golden 166)."""
    ref = (
        TwoPhaseSys(rm_count=4).checker().symmetry()
        .spawn_tpu(capacity=1 << 14, max_frontier=1 << 6).join()
    )
    t = (
        TwoPhaseSys(rm_count=4).checker().symmetry()
        .spawn_tpu_tiered(capacity=256, max_frontier=1 << 6).join()
    )
    assert t.unique_state_count() == ref.unique_state_count() == 166
    assert t.metrics()["spills"] >= 1
    assert np.array_equal(
        t.discovered_fingerprints(), ref.discovered_fingerprints()
    )


# --- snapshot / resume -------------------------------------------------------


def test_tiered_snapshot_resume_mid_search(tmp_path):
    full = _tiered(TwoPhaseSys(rm_count=4)).join()
    bounded = (
        TwoPhaseSys(rm_count=4).checker().target_state_count(900)
        .spawn_tpu_tiered(capacity=512, max_frontier=1 << 6).join()
    )
    assert 0 < bounded.unique_state_count() < 1568
    assert bounded.metrics()["cold_runs"] >= 1, (
        "the bounded run should already have spilled"
    )
    snap = str(tmp_path / "tiered.npz")
    bounded.save_snapshot(snap)

    resumed = _tiered(
        TwoPhaseSys(rm_count=4), resume_from=snap,
    ).join()
    assert resumed.unique_state_count() == 1568
    assert resumed.state_count() == full.state_count()
    assert resumed.max_depth() == full.max_depth()
    assert sorted(resumed.discoveries()) == sorted(full.discoveries())
    assert np.array_equal(
        resumed.discovered_fingerprints(), full.discovered_fingerprints()
    )

    # Resuming a COMPLETED run's snapshot (the supervisor's
    # kill-after-final-checkpoint window) is a no-op: in particular the
    # drained level must not roll and inflate max_depth.
    done_snap = str(tmp_path / "done.npz")
    resumed.save_snapshot(done_snap)
    again = _tiered(
        TwoPhaseSys(rm_count=4), resume_from=done_snap,
    ).join()
    assert again.unique_state_count() == 1568
    assert again.max_depth() == full.max_depth()
    assert again.state_count() == full.state_count()


def test_tiered_and_plain_snapshots_do_not_cross(tmp_path):
    t = _tiered(TwoPhaseSys(rm_count=3), capacity=256).join()
    snap_t = str(tmp_path / "t.npz")
    t.save_snapshot(snap_t)
    with pytest.raises(ValueError):
        _plain(TwoPhaseSys(rm_count=3), resume_from=snap_t).join()

    p = _plain(TwoPhaseSys(rm_count=3)).join()
    snap_p = str(tmp_path / "p.npz")
    p.save_snapshot(snap_p)
    with pytest.raises(ValueError, match="not written by the tiered"):
        _tiered(TwoPhaseSys(rm_count=3), resume_from=snap_p).join()

    # A resume whose budget disagrees with the snapshot's table must be
    # loud: the budget promise and adopt-the-snapshot-geometry rule can
    # only both hold when they agree.
    with pytest.raises(ValueError, match="memory_budget_mb"):
        TwoPhaseSys(rm_count=3).checker().spawn_tpu_tiered(
            memory_budget_mb=64, max_frontier=1 << 6, resume_from=snap_t,
        ).join()


def test_tiered_supervised_kill_mid_run_resumes_identical(
    tmp_path, monkeypatch
):
    """The acceptance criterion's resilience half: a supervised tiered
    child dies the moment its first checkpoint (cold tier embedded)
    lands, auto-resumes, and the final fingerprint set matches the
    in-HBM engine's."""
    from stateright_tpu.runtime import (
        CheckSpec, RunSupervisor, SupervisorConfig,
    )
    from stateright_tpu.runtime.supervisor import journal_events

    ref = _plain(TwoPhaseSys(rm_count=4)).join()
    monkeypatch.setenv(
        "STATERIGHT_RUNTIME_FAULT_EXIT_AFTER_CHECKPOINT", "137"
    )
    run_dir = str(tmp_path / "run")
    result = RunSupervisor(
        SupervisorConfig(
            run_dir=run_dir,
            checkpoint_every_waves=4,
            checkpoint_every_sec=None,
            call_deadline_sec=240.0,
            poll_interval_sec=0.05,
            max_restarts=2,
        ),
        spec=CheckSpec(
            model_factory=TwoPhaseSys,
            factory_kwargs={"rm_count": 4},
            engine="tiered",
            engine_kwargs={"capacity": 512, "max_frontier": 1 << 6},
        ),
    ).run()
    monkeypatch.delenv("STATERIGHT_RUNTIME_FAULT_EXIT_AFTER_CHECKPOINT")

    assert result["completed"]
    assert result["unique_state_count"] == ref.unique_state_count()
    assert result["state_count"] == ref.state_count()
    assert result["max_depth"] == ref.max_depth()
    assert result["discoveries"] == sorted(ref.discoveries())
    kinds = [e["event"] for e in journal_events(run_dir)]
    assert "crash" in kinds and "resume" in kinds
    assert "spill" in kinds, "no eviction before/after the kill"
    # The resumed child restored the cold tier, not just the hot table.
    resume = next(
        e for e in journal_events(run_dir) if e["event"] == "resume"
    )
    assert resume["unique"] > 0

    # And the resumed run's final snapshot still matches the in-HBM
    # engine bit for bit.
    final = _tiered(
        TwoPhaseSys(rm_count=4),
        resume_from=os.path.join(run_dir, "checkpoint.npz"),
    ).join()
    assert np.array_equal(
        final.discovered_fingerprints(), ref.discovered_fingerprints()
    )


def test_abort_cleanup_erases_uncommitted_table_keys():
    """A keep-partial (stop/deadline) break landing on a flagged wave
    must not persist the aborted insert's table keys — a resume would
    treat that wave's states as already visited and drop their
    subtrees.  The cleanup hook rebuilds the table from the committed
    log segment, erasing anything else."""
    from stateright_tpu.parallel.hashset import HashSet, insert_batch

    ck = _tiered(TwoPhaseSys(rm_count=3), capacity=256).join()
    cd = ck._carry_dev
    kh = jnp.asarray(np.asarray(cd["key_hi"]))
    kl = jnp.asarray(np.asarray(cd["key_lo"]))
    # Scribble a bogus (uncommitted) key, as an aborted insert would.
    t2, _slot, is_new, ok, _ovf = insert_batch(
        HashSet(kh, kl),
        jnp.asarray(np.array([0xDEAD], np.uint32)),
        jnp.asarray(np.array([0xBEEF], np.uint32)),
        jnp.ones((1,), jnp.bool_),
        dedup_factor=1,
    )
    assert bool(ok) and bool(np.asarray(is_new).any())
    polluted = t2.load_factor()
    carry = (
        t2.key_hi, t2.key_lo,
        jnp.asarray(np.asarray(cd["rows"])),
        jnp.asarray(np.asarray(cd["parent"])),
        jnp.asarray(np.asarray(cd["ebits"])),
    )
    cleaned = ck._wl_abort_cleanup(carry)
    lf = HashSet(cleaned[0], cleaned[1]).load_factor()
    assert lf < polluted
    # Exactly the committed-segment population, nothing else.
    assert lf == (ck._t_tail - ck._spill_tail) / ck._capacity


# --- device merge-join unit --------------------------------------------------


def test_cold_probe_binary_search_matches_host(tmp_path):
    """The vmapped lower-bound search (the cold filter's core) pinned
    against numpy membership on adversarial data: duplicates, all-miss,
    all-hit, boundary keys, and sentinel padding."""
    t = _tiered(TwoPhaseSys(rm_count=3), capacity=256).join()
    tp = t._tiered_programs()
    chunk = t._cold_chunk
    rng = np.random.default_rng(3)
    run = np.unique(rng.integers(1, 1 << 48, size=chunk, dtype=np.uint64))
    seg = np.concatenate([
        run,
        np.full(chunk - run.shape[0], np.uint64(0xFFFFFFFFFFFFFFFF)),
    ])
    queries = np.concatenate([
        rng.choice(run, 40),  # guaranteed hits
        rng.integers(1, 1 << 48, size=50, dtype=np.uint64),  # mostly miss
        run[:1], run[-1:],  # exact boundaries
        np.asarray([0xFFFFFFFFFFFFFFFE], np.uint64),  # near-sentinel
    ]).astype(np.uint64)
    q = np.zeros(1 << 14, np.uint64)  # pad to a plausible U width
    q[: queries.shape[0]] = queries
    found = tp["probe"](
        jnp.zeros(q.shape, jnp.bool_),
        jnp.asarray((q >> np.uint64(32)).astype(np.uint32)),
        jnp.asarray(q.astype(np.uint32)),
        jnp.asarray((seg >> np.uint64(32)).astype(np.uint32)),
        jnp.asarray(seg.astype(np.uint32)),
    )
    want = np.isin(q, run)
    assert np.array_equal(np.asarray(found), want)


# --- spawn validation & serve wiring -----------------------------------------


def test_tiered_spawn_validation():
    m = TwoPhaseSys(rm_count=3)
    with pytest.raises(ValueError, match="trace"):
        # Traced tiered runs are supported (docs/OBSERVABILITY.md) but
        # are diagnostic: they never resume.
        m.checker().spawn_tpu_tiered(
            capacity=256, trace=True, resume_from="nope.npz"
        )
    with pytest.raises(ValueError, match="visitor"):
        m.checker().visitor(lambda *a: True).spawn_tpu_tiered(capacity=256)
    with pytest.raises(ValueError, match="spill_threshold"):
        m.checker().spawn_tpu_tiered(capacity=256, spill_threshold=0.9)
    with pytest.raises(ValueError, match="cold_chunk"):
        m.checker().spawn_tpu_tiered(capacity=256, cold_chunk=100)
    with pytest.raises(ValueError, match="memory_budget_mb"):
        m.checker().spawn_tpu_tiered(memory_budget_mb=-1)


def test_tiered_cli_flags(capsys):
    """`check-tpu --tiered --memory-budget-mb` end to end in-process,
    plus the flag-combination refusals."""
    from stateright_tpu.cli import example_main
    from stateright_tpu.models.twophase import cli_spec

    rc = example_main(
        cli_spec(),
        ["check-tpu", "3", "--tiered", "--memory-budget-mb", "0.005"],
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "unique=288" in out
    for bad in (
        # The COMPOSED engine has no traced mode; --tiered --sharded
        # alone and --tiered --trace alone are both supported now.
        ["check-tpu", "3", "--tiered", "--sharded", "--trace"],
        ["check", "3", "--tiered"],
        ["check-tpu", "3", "--memory-budget-mb", "nope"],
        ["check-tpu", "3", "--memory-budget-mb", "-2"],
        ["check-tpu", "3", "--memory-budget-mb", "nan"],
        ["check-tpu", "3", "--memory-budget-mb", "inf"],
    ):
        assert example_main(cli_spec(), bad) == 2, bad


def test_tiered_trace_breaks_out_cold_probe(tmp_path):
    """ISSUE-17 satellite: `--tiered --trace` is supported — the tiered
    loop times its own phases (the base traced loop knows nothing of
    the tiers) and the wave breakdown gains the host-classed
    ``cold_probe`` phase; the run still spills and still matches the
    in-HBM engine."""
    journal = str(tmp_path / "trace.jsonl")
    ref = _plain(TwoPhaseSys(rm_count=3)).join()
    t = _tiered(
        TwoPhaseSys(rm_count=3), capacity=256, trace=True, journal=journal,
    ).join()
    assert t.unique_state_count() == ref.unique_state_count() == 288
    assert np.array_equal(
        t.discovered_fingerprints(), ref.discovered_fingerprints()
    )

    summary = t.trace_summary()
    assert summary["traced_waves"] > 0
    assert "cold_probe" in summary["wave_breakdown"]

    events = read_journal(journal)
    assert any(e["event"] == "spill" for e in events)
    waves = [
        e for e in events
        if e["event"] == "wave" and "wave_breakdown" in e
    ]
    assert waves, "traced waves must journal their phase breakdown"
    assert all("cold_probe" in w["wave_breakdown"] for w in waves)
    assert any(e["event"] == "trace_summary" for e in events)


def test_tiered_trace_cli(capsys):
    """The CLI refusal is lifted: `check-tpu --tiered --trace` runs and
    prints the parseable trace summary line."""
    from stateright_tpu.cli import example_main
    from stateright_tpu.models.twophase import cli_spec

    rc = example_main(
        cli_spec(),
        ["check-tpu", "3", "--tiered", "--memory-budget-mb", "0.005",
         "--trace"],
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "unique=288" in out
    assert "trace: " in out


def test_tiered_serve_job_and_knob_cache(tmp_path):
    """A tiered service job completes, reports its engine, and persists
    its budget-pinned geometry under the TIERED_ENGINE tag so a repeat
    warm-starts without shadowing in-HBM entries."""
    from stateright_tpu.runtime.knob_cache import (
        TIERED_ENGINE, knob_key, load_knobs,
    )
    from stateright_tpu.serve import CheckService
    from stateright_tpu.serve.workloads import workload_label

    knobs = str(tmp_path / "knobs")
    svc = CheckService(journal=None, knob_cache_dir=knobs)
    try:
        # The normal tiered job shape: a budget in engine_kwargs.  The
        # budget must NOT count as hand-tuned geometry, or the cache
        # store (and with it the warm start) would be unreachable for
        # exactly the jobs the TIERED_ENGINE tag exists for.
        spec = {
            "workload": "twophase", "n": 3, "engine": "tiered",
            "engine_kwargs": {"memory_budget_mb": 0.005},
        }
        job = svc.submit(dict(spec))
        assert job.wait(timeout=240)
        assert job.state == "done", (job.state, job.error)
        assert job.result["unique_state_count"] == 288
        assert job.result["engine"] == "tiered"
        # The tiered label is budget-keyed: one budget's pinned table
        # must never warm-start the same workload at another budget.
        key = knob_key(
            workload_label("twophase", 3, None, False) + ":mb=0.005",
            engine=TIERED_ENGINE,
        )
        stored = load_knobs(knobs, key)
        assert stored is not None and "capacity" in stored
        assert stored["capacity"] == capacity_for_budget(0.005)

        warm = svc.submit(dict(spec))
        assert warm.wait(timeout=240)
        assert warm.state == "done", (warm.state, warm.error)
        assert warm.result["knob_cache_hit"]
        assert warm.result["unique_state_count"] == 288
    finally:
        svc.scheduler.shutdown()
