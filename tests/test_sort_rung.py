"""The adaptive dedup-sort geometry ladder (parallel/wave_loop.py's
``sort_lanes`` rung): forced tiny-rung overflow-retry runs must land the
bit-identical discovery set on every engine (single-chip fused AND
traced, sharded at 1/2/4/8 virtual shards, tiered), the density tuner
must downshift a default run once it has evidence, and the traced byte
model must reflect the rung (``bytes.dedup`` is the regression gauge).

The fixed-geometry reference in every gate is ``sort_lanes`` pinned past
the full worst-case buffer — that clamps to today's pre-ladder geometry
and disarms the density tuner, so the comparison is rung-vs-no-rung on
otherwise identical programs."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from stateright_tpu.models.twophase import TwoPhaseSys  # noqa: E402
from stateright_tpu.parallel.wave_loop import SORT_RUNG_MIN  # noqa: E402
from stateright_tpu.runtime.journal import read_journal  # noqa: E402

RM = 4
GOLDEN = 1568
FULL = 1 << 30  # clamps to the full buffer = the fixed-geometry path


def _cpu():
    return jax.devices("cpu")[0]


def _mesh(n):
    return jax.sharding.Mesh(np.array(jax.devices("cpu")[:n]), ("shards",))


def _model():
    return TwoPhaseSys(rm_count=RM)


@pytest.fixture(scope="module")
def reference_fps():
    ck = _model().checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
        sort_lanes=FULL,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    return ck.discovered_fingerprints()


def _rung_grows(journal):
    return [
        e for e in read_journal(journal)
        if e["event"] == "grow"
        and e.get("flags", 0) & 4
        and "sort_lanes=" in str(e.get("grown", ""))
    ]


def test_forced_tiny_rung_single_chip_fused_bit_identical(
    tmp_path, reference_fps
):
    """The acceptance gate: a run started at the smallest rung overflows,
    climbs the ladder (journaled grow, flags&4, no lost work), and still
    lands the exact fingerprint set of the fixed-geometry path."""
    journal = str(tmp_path / "rung.jsonl")
    ck = _model().checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
        sort_lanes=SORT_RUNG_MIN, journal=journal,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    grows = _rung_grows(journal)
    assert grows, "the tiny rung never overflowed — the forcing is dead"
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)
    # The discovered rung is surfaced everywhere the knob cache reads.
    m = ck.metrics()
    assert m["sort_lanes"] > SORT_RUNG_MIN
    assert ck.tuned_kwargs()["sort_lanes"] == m["sort_lanes"]


def test_forced_tiny_rung_single_chip_traced_bit_identical(
    tmp_path, reference_fps
):
    journal = str(tmp_path / "rung_traced.jsonl")
    ck = _model().checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
        sort_lanes=SORT_RUNG_MIN, trace=True, journal=journal,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    assert _rung_grows(journal)
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)


@pytest.mark.parametrize("shards", [1, 2, 4, 8])
def test_forced_tiny_rung_sharded_bit_identical(
    shards, tmp_path, reference_fps
):
    """Sharded meshes at every width: the rung shapes the pre-exchange
    sort, the owner bucketing, AND the exchange buckets — the fingerprint
    set must still be bit-identical to the single-chip fixed path."""
    journal = str(tmp_path / f"rung_sh{shards}.jsonl")
    ck = _model().checker().spawn_tpu_sharded(
        mesh=_mesh(shards), capacity=1 << 14, chunk_size=1 << 7,
        sort_lanes=SORT_RUNG_MIN, journal=journal,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)
    acc = ck.accounting()
    assert acc["sort_lanes"] >= SORT_RUNG_MIN
    if acc["sort_retries"]:
        assert _rung_grows(journal)


def test_forced_tiny_rung_tiered_bit_identical(tmp_path, reference_fps):
    """The tiered engine inherits the ladder through the shared loop:
    a budget that forces spills plus a tiny rung must still reproduce
    the unconstrained fixed-geometry set bit for bit."""
    ck = _model().checker().spawn_tpu_tiered(
        memory_budget_mb=0.01, max_frontier=1 << 6,
        sort_lanes=SORT_RUNG_MIN,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    assert ck.metrics()["spills"] >= 1
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)


def test_density_tuner_downshifts_default_run(tmp_path, reference_fps):
    """A DEFAULT run (no explicit rung) measures its density and
    downshifts below the worst-case buffer once it has evidence —
    journaling a fresh geometry event — without perturbing the
    discovery set.  waves_per_call=1 gives the tuner per-wave quanta."""
    journal = str(tmp_path / "tuner.jsonl")
    # mf=2^11 puts 2pc(4) at the 16K buffer floor with ~9% peak density
    # — a measured at-least-halving downshift exists (mf=2^9's ~13%
    # density correctly does NOT downshift; the tuner must be able to
    # say "leave it alone" too, pinned in the explicit-rung test).
    ck = _model().checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 11, device=_cpu(),
        waves_per_call=1, journal=journal,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    full_u = ck._wl_cand_lanes()
    assert ck.metrics()["sort_lanes"] < full_u, (
        "the density tuner never downshifted a few-percent-density run"
    )
    geoms = [
        e for e in read_journal(journal) if e["event"] == "geometry"
    ]
    assert len(geoms) >= 2  # loop start + at least one retune
    assert geoms[-1]["sort_lanes"] < geoms[0]["sort_lanes"]
    assert np.array_equal(ck.discovered_fingerprints(), reference_fps)


def test_explicit_rung_disarms_tuner(tmp_path):
    """An explicit sort_lanes is a warm start: the tuner must not move
    it (the knob-cache contract — a warm run reproduces the cached
    program keys instead of re-adapting)."""
    rung = 1 << 11
    ck = _model().checker().spawn_tpu(
        capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
        waves_per_call=1, sort_lanes=rung,
    ).join()
    assert ck.unique_state_count() == GOLDEN
    assert ck.metrics()["sort_lanes"] == min(rung, ck._wl_cand_lanes())


def test_traced_bytes_dedup_reflect_rung(tmp_path):
    """The modeled ``bytes.dedup`` must drop with the rung — the
    regression gauge bench.py's dedup phase reports.  Byte totals are
    deterministic modulo probe rounds, so strict inequality is safe."""
    def spawn(sort_lanes):
        return _model().checker().spawn_tpu(
            capacity=1 << 14, max_frontier=1 << 9, device=_cpu(),
            trace=True, sort_lanes=sort_lanes,
        ).join()

    full = spawn(FULL)
    slim = spawn(1 << 10)
    assert full.unique_state_count() == slim.unique_state_count() == GOLDEN
    b_full = full.trace_summary()["bytes"]["dedup"]
    b_slim = slim.trace_summary()["bytes"]["dedup"]
    assert b_slim < b_full, (b_slim, b_full)
    assert np.array_equal(
        full.discovered_fingerprints(), slim.discovered_fingerprints()
    )


def test_sharded_snapshot_persists_rung(tmp_path):
    """The discovered rung rides sharded snapshots like bucket_slack:
    a resume adopts it instead of re-paying the ramp."""
    snap = str(tmp_path / "rung.npz")
    bounded = _model().checker().target_state_count(400).spawn_tpu_sharded(
        mesh=_mesh(4), capacity=1 << 14, chunk_size=1 << 6,
        sort_lanes=SORT_RUNG_MIN,
    ).join()
    bounded.save_snapshot(snap)
    rung_at_save = bounded.metrics()["sort_lanes"]
    resumed = _model().checker().spawn_tpu_sharded(
        mesh=_mesh(4), capacity=1 << 14, chunk_size=1 << 6,
        resume_from=snap,
    ).join()
    assert resumed.unique_state_count() == GOLDEN
    assert resumed.metrics()["sort_lanes"] >= rung_at_save
