"""Simulation and on-demand checkers.

Reference: src/checker/simulation.rs (seeded random trace walks, cycle
detection, no global dedup) and src/checker/on_demand.rs (control-channel
driven BFS behind the Explorer).
"""

import pytest

from stateright_tpu import HasDiscoveries, Model, Property
from stateright_tpu.core.simulation import UniformChooser
from stateright_tpu.models.fixtures import BinaryClock, DGraph, LinearEquation
from tests.test_tpu_wavefront import TrapCounter

Guess = LinearEquation.Guess


# --- simulation --------------------------------------------------------------


def test_simulation_finds_solution():
    # Reference: can_complete_by_eliminating_properties
    # (src/checker/simulation.rs:447-461).
    checker = (
        LinearEquation(a=2, b=10, c=14)
        .checker()
        .spawn_simulation(0, UniformChooser())
        .join()
    )
    checker.assert_properties()
    # Any reachable solution validates; (2, 1) solves 2x + 10y = 14.
    checker.assert_discovery(
        "solvable", [Guess.INCREASE_X, Guess.INCREASE_X, Guess.INCREASE_Y]
    )
    # The recorded trace itself must genuinely end in a solution.
    path = checker.discoveries()["solvable"]
    x, y = path.last_state()
    assert (2 * x + 10 * y) % 256 == 14


def test_simulation_is_seed_reproducible():
    def run(seed):
        c = (
            LinearEquation(a=3, b=7, c=111)
            .checker()
            .spawn_simulation(seed, UniformChooser())
            .join()
        )
        return c.discoveries()["solvable"]

    assert run(7) == run(7)


def test_simulation_cycle_detection_terminates():
    # BinaryClock is a pure 2-cycle: without per-trace loop detection a
    # simulation would walk forever (src/checker/simulation.rs:286-292).
    # The only property is an unviolated `always`, so the run can only end
    # via the state-count target — each individual trace must self-terminate
    # on the cycle for that to happen.
    checker = (
        BinaryClock()
        .checker()
        .target_state_count(100)
        .spawn_simulation(0, UniformChooser())
        .join()
    )
    checker.assert_properties()
    assert checker.state_count() >= 100


def test_simulation_counts_are_not_deduped():
    # 2x + 4y is always even: "solvable" is undiscoverable, so only the
    # target bounds the run.
    checker = (
        LinearEquation(a=2, b=4, c=7)
        .checker()
        .target_state_count(2000)
        .spawn_simulation(0, UniformChooser())
        .join()
    )
    # unique == total by definition (src/checker/simulation.rs:413-417).
    assert checker.unique_state_count() == checker.state_count()
    assert checker.state_count() >= 2000


def test_simulation_eventually_counterexample_at_trace_end():
    checker = (
        TrapCounter()
        .checker()
        .finish_when(HasDiscoveries.ANY_FAILURES)
        .spawn_simulation(3, UniformChooser())
        .join()
    )
    # Eventually "reaches limit" is violated via the trap dead end; the trap
    # path is reachable with positive probability per trace, and traces
    # repeat until the failure is found.
    assert "reaches limit" in checker.discoveries()
    ce = checker.discoveries()["reaches limit"]
    assert ce.last_state() == TrapCounter().trap_state


class _Cycle(Model):
    """0 -> 1 -> 2 -> 0; 'reaches three' can never hold, and the cycle break
    ends each trace, reporting the leftover eventually bit."""

    def init_states(self):
        return [0]

    def actions(self, state, actions):
        actions.append("next")

    def next_state(self, state, action):
        return (state + 1) % 3

    def properties(self):
        return [Property.eventually("reaches three", lambda _m, s: s == 3)]


def test_simulation_eventually_counterexample_on_cycle():
    checker = (
        _Cycle().checker().spawn_simulation(0, UniformChooser()).join()
    )
    assert "reaches three" in checker.discoveries()


# --- on-demand ---------------------------------------------------------------


def test_on_demand_computes_nothing_until_asked():
    import time

    checker = LinearEquation(a=2, b=10, c=14).checker().spawn_on_demand()
    time.sleep(0.2)
    # Only the init state is known; nothing was expanded.
    assert checker.unique_state_count() == 1
    assert checker.state_count() == 1
    assert checker.discoveries() == {}
    checker.shutdown()


def test_on_demand_expands_only_requested_fingerprints():
    import time

    model = LinearEquation(a=2, b=10, c=14)
    checker = model.checker().spawn_on_demand()
    init_fp = model.fingerprint((0, 0))
    checker.check_fingerprint(init_fp)
    deadline = time.time() + 5
    while checker.unique_state_count() < 3 and time.time() < deadline:
        time.sleep(0.01)
    # (0,0) expanded into (1,0) and (0,1), nothing further.
    assert checker.unique_state_count() == 3
    checker.shutdown()


def test_on_demand_run_to_completion_matches_bfs():
    m = TrapCounter()
    bfs = m.checker().spawn_bfs().join()
    od = m.checker().spawn_on_demand()
    od.run_to_completion()
    deadline = __import__("time").time() + 10
    while not od.is_done() and __import__("time").time() < deadline:
        __import__("time").sleep(0.01)
    assert od.unique_state_count() == bfs.unique_state_count()
    assert od.state_count() == bfs.state_count()
    assert sorted(od.discoveries()) == sorted(bfs.discoveries())
    od.shutdown()
