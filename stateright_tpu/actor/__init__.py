"""Actor framework: model-checkable actors that also run on a real UDP
network.  Reference: src/actor.rs and submodules."""

from .ids import Id
from .base import (
    Actor,
    Out,
    SendCmd,
    SetTimerCmd,
    CancelTimerCmd,
    ChooseRandomCmd,
    SaveCmd,
    is_no_op,
    is_no_op_with_timer,
    majority,
    model_peers,
    model_timeout,
)
from .network import Envelope, Network
from .model import (
    ActorModel,
    ActorModelState,
    Deliver,
    Drop,
    Timeout,
    Crash,
    Recover,
    SelectRandom,
)
from .spawn import ActorRuntime, json_deserialize, json_serialize, spawn
from .transport import (
    Endpoint,
    LoopbackTransport,
    Transport,
    TransportClosed,
    UdpTransport,
)

__all__ = [
    "Id", "Actor", "Out", "SendCmd", "SetTimerCmd", "CancelTimerCmd",
    "ChooseRandomCmd", "SaveCmd", "is_no_op", "is_no_op_with_timer",
    "majority", "model_peers", "model_timeout", "Envelope", "Network",
    "ActorModel", "ActorModelState", "Deliver", "Drop", "Timeout", "Crash",
    "Recover", "SelectRandom",
    "ActorRuntime", "spawn", "json_serialize", "json_deserialize",
    "Transport", "Endpoint", "TransportClosed", "UdpTransport",
    "LoopbackTransport",
]
