"""The real-network actor runtime: run model-checked actors over UDP.

Reference: src/actor/spawn.rs.  The *same* ``Actor`` implementations used
for model checking execute on a real network: one thread per actor, a
transport endpoint bound to the actor's ``Id`` (UDP by default — the
``Id``-encoded address of src/actor/spawn.rs:96-100), persistent storage
loaded from ``{addr}.storage`` before ``on_start``, and an event loop that
waits for the earliest pending interrupt (timer or scheduled random
choice) or an incoming datagram, dispatching ``on_msg`` / ``on_timeout`` /
``on_random`` and then applying the emitted commands
(src/actor/spawn.rs:106-164,177-256).

The wire is pluggable (``actor/transport.py``): pass ``transport=`` to run
the same actors over the in-process loopback fabric, optionally wrapped in
the fault-injecting chaos transport (``runtime/chaos.py``).

Every event-loop deadline — timers, scheduled random choices, and the
retransmit timers the ordered reliable link arms — is computed on
``time.monotonic()``, never wall time, so NTP steps and clock jumps can
neither fire a timer early nor starve it.

Message and storage serializers are caller-supplied functions, as in the
reference (whose examples use serde_json); ``json_serialize`` /
``json_deserialize`` below are ready-made JSON codecs for plain-data
messages.
"""

from __future__ import annotations

import json
import os
import random as _random
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

from .base import (
    Actor,
    CancelTimerCmd,
    ChooseRandomCmd,
    Out,
    SaveCmd,
    SendCmd,
    SetTimerCmd,
)
from .ids import Id
from .obs import clear_trace_context, find_in_stack, find_observed
from .transport import (
    MAX_DATAGRAM,
    Endpoint,
    Transport,
    TransportClosed,
    UdpTransport,
)

__all__ = [
    "ActorRuntime",
    "spawn",
    "json_serialize",
    "json_deserialize",
    "MAX_DATAGRAM",
]

_PRACTICALLY_NEVER = 1e18  # src/actor/spawn.rs practically_never()

# The longest one recv blocks before re-checking the stop flag: bounds
# teardown latency for a thread parked waiting for a datagram.
_STOP_POLL_SEC = 1.0


def json_serialize(msg: Any) -> bytes:
    return json.dumps(msg).encode()


def json_deserialize(data: bytes) -> Any:
    return json.loads(data)


def _addr_str(id: Id) -> str:
    ip, port = id.to_socket_addr()
    return f"{ip[0]}.{ip[1]}.{ip[2]}.{ip[3]}:{port}"


class ActorRuntime:
    """Handle for a set of spawned actor threads.

    Every runtime carries a ``MetricsRegistry`` (``self.registry``): the
    event loops record per-message handler durations, timer sets/fires,
    and malformed-datagram drops into it, and ``metrics()`` snapshots it
    in the guaranteed cross-engine schema so the actor runtime scrapes
    exactly like a checker (docs/OBSERVABILITY.md "Actor-runtime
    observability"; served live by ``actor/obs.serve_actor_metrics``).
    """

    def __init__(self, metrics=None):
        from ..obs.metrics import MetricsRegistry

        self._threads: List[threading.Thread] = []
        self._endpoints: List[Endpoint] = []
        self._transport: Optional[Transport] = None
        self._stop = threading.Event()
        self._stop_lock = threading.Lock()
        self._stopped = False
        self.registry = metrics if metrics is not None else MetricsRegistry()
        self.errors: List[BaseException] = []

    def metrics(self) -> dict:
        """Live observability snapshot in the guaranteed cross-engine
        schema (tests/test_metrics_schema.py), actor semantics:
        ``state_count`` counts handled messages, ``unique_state_count``
        the spawned actors, ``max_depth`` the deepest causal hop
        observed by the trace envelope (0 untraced), and
        ``table_load_factor`` is 0.0 (no device table).  Per-link
        datagram/byte dicts and chaos fault counters are merged in from
        the transport stack when present."""
        from ..obs.metrics import GLOBAL

        snap = self.registry.snapshot()
        out: dict = {
            "engine": type(self).__name__,
            "done": self._stopped,
            "actors": len(self._threads),
            "state_count": int(snap.get("msgs_handled_total", 0)),
            "unique_state_count": len(self._threads),
            "max_depth": 0,
            "table_load_factor": 0.0,
            "program_cache_hits": int(GLOBAL.get("program_cache_hits", 0)),
            "program_cache_misses": int(
                GLOBAL.get("program_cache_misses", 0)
            ),
            "compile_sec_total": round(
                float(GLOBAL.get("compile_sec_total", 0.0)), 4
            ),
            "recompile_storms": int(GLOBAL.get("recompile_storms", 0)),
        }
        out.update(snap)
        out["histograms"] = self.registry.snapshot_histograms()
        observed = find_observed(self._transport)
        if observed is not None:
            out["max_depth"] = int(observed.max_hop)
            out["trace"] = observed.trace
            out["actor_spans_total"] = int(observed.span_count)
            out.update(observed.link_metrics())
        faulty = self._find_faulty()
        if faulty is not None:
            summary = faulty.fault_summary()
            out["chaos_faults_total"] = int(summary["total"])
            if summary["by_kind"]:
                out["chaos_faults"] = summary["by_kind"]
            if summary["links"]:
                # Flat per-link totals (a labeled Prometheus gauge
                # family); the per-link-per-kind split stays JSON-only.
                out["link_faults"] = {
                    link: sum(kinds.values())
                    for link, kinds in summary["links"].items()
                }
                out["chaos_link_faults"] = summary["links"]
        return out

    def _find_faulty(self):
        from .obs import find_faulty

        return find_faulty(self._transport)

    def stop(self, timeout: float = 10.0, raise_errors: bool = True) -> None:
        """Stop all actor threads (closing their endpoints); idempotent.

        Teardown is bounded: each closed endpoint wakes its thread's
        ``recv`` immediately, and recv waits are capped at
        ``_STOP_POLL_SEC`` regardless, so ``timeout`` is a hard ceiling on
        the join — a chaos test can never hang CI on a thread parked in
        ``recvfrom``.  Actor-thread exceptions collected in
        ``self.errors`` are re-raised here (first one) unless
        ``raise_errors=False``.
        """
        with self._stop_lock:
            first = not self._stopped
            self._stopped = True
        if first:
            self._stop.set()
            for ep in self._endpoints:
                try:
                    ep.close()
                except Exception:
                    pass
            if self._transport is not None:
                try:
                    self._transport.close()
                except Exception:
                    pass
            deadline = time.monotonic() + timeout
            for t in self._threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
        if raise_errors and self.errors:
            raise self.errors[0]

    def join(self) -> None:
        """Block until the runtime stops (the reference blocks forever,
        src/actor/spawn.rs:84-168)."""
        for t in self._threads:
            t.join()
        if self.errors:
            raise self.errors[0]


def spawn(
    msg_serialize: Callable[[Any], bytes],
    msg_deserialize: Callable[[bytes], Any],
    storage_serialize: Callable[[Any], bytes],
    storage_deserialize: Callable[[bytes], Any],
    actors: List[Tuple[Id, Actor]],
    storage_dir: str = ".",
    transport: Optional[Transport] = None,
    metrics=None,
) -> ActorRuntime:
    """Run ``actors`` on a datagram transport; returns a runtime handle.

    ``transport`` defaults to real UDP sockets (``UdpTransport``).
    Endpoints are bound up front, in the caller's thread, so an
    already-taken address raises here instead of landing in
    ``runtime.errors`` asynchronously.

    ``metrics`` optionally supplies the runtime's ``MetricsRegistry`` —
    pass the same registry to an ``ObservedTransport`` wrapper and to
    ORL ``ActorWrapper``s so link, handler, and retransmit counters land
    in one ``runtime.metrics()`` snapshot.

    Reference: ``spawn``, src/actor/spawn.rs:70-168 (which blocks; call
    ``.join()`` on the returned handle for that behavior).
    """
    runtime = ActorRuntime(metrics=metrics)
    runtime._transport = transport = (
        transport if transport is not None else UdpTransport()
    )
    bound: List[Tuple[Id, Actor, Endpoint]] = []
    try:
        for id, actor in actors:
            id = Id(id)
            endpoint = transport.bind(id)
            runtime._endpoints.append(endpoint)
            bound.append((id, actor, endpoint))
    except BaseException:
        for ep in runtime._endpoints:
            try:
                ep.close()
            except Exception:
                pass
        raise
    for id, actor, endpoint in bound:
        t = threading.Thread(
            target=_actor_main,
            args=(
                runtime,
                id,
                actor,
                endpoint,
                msg_serialize,
                msg_deserialize,
                storage_serialize,
                storage_deserialize,
                storage_dir,
            ),
            name=f"actor-{_addr_str(id)}",
            daemon=True,
        )
        runtime._threads.append(t)
    for t in runtime._threads:
        t.start()
    return runtime


def _actor_main(
    runtime: ActorRuntime,
    id: Id,
    actor: Actor,
    endpoint: Endpoint,
    msg_serialize,
    msg_deserialize,
    storage_serialize,
    storage_deserialize,
    storage_dir: str,
) -> None:
    try:
        registry = runtime.registry
        storage_path = os.path.join(storage_dir, f"{_addr_str(id)}.storage")
        storage: Optional[Any] = None
        try:
            with open(storage_path, "rb") as f:
                storage = storage_deserialize(f.read())
        except (OSError, ValueError):
            storage = None

        # interrupt key -> fire_at (monotonic seconds)
        next_interrupts: dict = {}

        def on_command(cmd) -> None:
            # Reference: on_command, src/actor/spawn.rs:177-256.
            if isinstance(cmd, SendCmd):
                try:
                    data = msg_serialize(cmd.msg)
                except (ValueError, TypeError):
                    return  # unserializable: ignore, like the reference
                endpoint.send(Id(cmd.dst), data)
            elif isinstance(cmd, SetTimerCmd):
                registry.inc("timer_sets_total")
                lo, hi = cmd.duration
                duration = _random.uniform(lo, hi) if lo < hi else lo
                next_interrupts[("timeout", cmd.timer)] = (
                    time.monotonic() + duration
                )
            elif isinstance(cmd, CancelTimerCmd):
                key = ("timeout", cmd.timer)
                if key in next_interrupts:
                    next_interrupts[key] = _PRACTICALLY_NEVER
            elif isinstance(cmd, ChooseRandomCmd):
                if not cmd.choices:
                    return
                chosen = _random.choice(list(cmd.choices))
                duration = _random.uniform(0.0, 10.0)
                next_interrupts[("random", chosen)] = (
                    time.monotonic() + duration
                )
            elif isinstance(cmd, SaveCmd):
                with open(storage_path, "wb") as f:
                    f.write(storage_serialize(cmd.storage))

        out = Out()
        state = actor.on_start(id, storage, out)
        for c in out:
            on_command(c)

        while not runtime._stop.is_set():
            out = Out()
            if next_interrupts:
                min_key = min(next_interrupts, key=next_interrupts.get)
                min_at = next_interrupts[min_key]
            else:
                min_key, min_at = None, _PRACTICALLY_NEVER
            max_wait = min_at - time.monotonic()
            if max_wait > 0:
                try:
                    received = endpoint.recv(min(max_wait, _STOP_POLL_SEC))
                except TransportClosed:
                    return  # endpoint closed: runtime stopping
                if received is None:
                    continue  # timeout: re-check interrupts and stop flag
                data, src = received
                try:
                    msg = msg_deserialize(data)
                except (ValueError, KeyError):
                    registry.inc("malformed_datagrams_total")
                    continue  # unparseable: ignore, like the reference
                handler_start = time.monotonic()
                next_state = actor.on_msg(id, state, src, msg, out)
                registry.observe(
                    "actor_handler_sec", time.monotonic() - handler_start
                )
                registry.inc("msgs_handled_total")
            else:
                del next_interrupts[min_key]
                kind, payload = min_key
                # A send from an interrupt handler starts a new causal
                # chain — never a continuation of whatever message this
                # thread received last (actor/obs.py).
                clear_trace_context(endpoint)
                handler_start = time.monotonic()
                if kind == "timeout":
                    registry.inc("timer_fires_total")
                    next_state = actor.on_timeout(id, state, payload, out)
                else:
                    next_state = actor.on_random(id, state, payload, out)
                registry.observe(
                    "actor_handler_sec", time.monotonic() - handler_start
                )
            if next_state is not None:
                state = next_state
            for c in out:
                on_command(c)
    except BaseException as e:
        runtime.errors.append(e)
