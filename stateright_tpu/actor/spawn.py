"""The real-network actor runtime: run model-checked actors over UDP.

Reference: src/actor/spawn.rs.  The *same* ``Actor`` implementations used
for model checking execute on a real network: one thread per actor, a UDP
socket bound to the actor's ``Id``-encoded address, persistent storage
loaded from ``{addr}.storage`` before ``on_start`` (src/actor/spawn.rs:
96-100), and an event loop that waits for the earliest pending interrupt
(timer or scheduled random choice) or an incoming datagram, dispatching
``on_msg`` / ``on_timeout`` / ``on_random`` and then applying the emitted
commands (src/actor/spawn.rs:106-164,177-256).

Message and storage serializers are caller-supplied functions, as in the
reference (whose examples use serde_json); ``json_serialize`` /
``json_deserialize`` below are ready-made JSON codecs for plain-data
messages.
"""

from __future__ import annotations

import json
import os
import random as _random
import socket
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

from .base import (
    Actor,
    CancelTimerCmd,
    ChooseRandomCmd,
    Out,
    SaveCmd,
    SendCmd,
    SetTimerCmd,
)
from .ids import Id

_PRACTICALLY_NEVER = 1e18  # src/actor/spawn.rs practically_never()
MAX_DATAGRAM = 65_535


def json_serialize(msg: Any) -> bytes:
    return json.dumps(msg).encode()


def json_deserialize(data: bytes) -> Any:
    return json.loads(data)


def _addr_str(id: Id) -> str:
    ip, port = id.to_socket_addr()
    return f"{ip[0]}.{ip[1]}.{ip[2]}.{ip[3]}:{port}"


class ActorRuntime:
    """Handle for a set of spawned actor threads."""

    def __init__(self):
        self._threads: List[threading.Thread] = []
        self._sockets: List[socket.socket] = []
        self._stop = threading.Event()
        self.errors: List[BaseException] = []

    def stop(self) -> None:
        """Stop all actor threads (closing their sockets)."""
        self._stop.set()
        for s in self._sockets:
            try:
                s.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)

    def join(self) -> None:
        """Block until the runtime stops (the reference blocks forever,
        src/actor/spawn.rs:84-168)."""
        for t in self._threads:
            t.join()
        if self.errors:
            raise self.errors[0]


def spawn(
    msg_serialize: Callable[[Any], bytes],
    msg_deserialize: Callable[[bytes], Any],
    storage_serialize: Callable[[Any], bytes],
    storage_deserialize: Callable[[bytes], Any],
    actors: List[Tuple[Id, Actor]],
    storage_dir: str = ".",
) -> ActorRuntime:
    """Run ``actors`` on real UDP sockets; returns a runtime handle.

    Reference: ``spawn``, src/actor/spawn.rs:70-168 (which blocks; call
    ``.join()`` on the returned handle for that behavior).
    """
    runtime = ActorRuntime()
    for id, actor in actors:
        id = Id(id)
        t = threading.Thread(
            target=_actor_main,
            args=(
                runtime,
                id,
                actor,
                msg_serialize,
                msg_deserialize,
                storage_serialize,
                storage_deserialize,
                storage_dir,
            ),
            name=f"actor-{_addr_str(id)}",
            daemon=True,
        )
        runtime._threads.append(t)
    for t in runtime._threads:
        t.start()
    return runtime


def _actor_main(
    runtime: ActorRuntime,
    id: Id,
    actor: Actor,
    msg_serialize,
    msg_deserialize,
    storage_serialize,
    storage_deserialize,
    storage_dir: str,
) -> None:
    try:
        ip, port = id.to_socket_addr()
        addr = (f"{ip[0]}.{ip[1]}.{ip[2]}.{ip[3]}", port)
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(addr)
        runtime._sockets.append(sock)

        storage_path = os.path.join(storage_dir, f"{_addr_str(id)}.storage")
        storage: Optional[Any] = None
        try:
            with open(storage_path, "rb") as f:
                storage = storage_deserialize(f.read())
        except (OSError, ValueError):
            storage = None

        # interrupt key -> (kind, payload, fire_at)
        next_interrupts: dict = {}

        def on_command(cmd) -> None:
            # Reference: on_command, src/actor/spawn.rs:177-256.
            if isinstance(cmd, SendCmd):
                dst_ip, dst_port = Id(cmd.dst).to_socket_addr()
                dst = (
                    f"{dst_ip[0]}.{dst_ip[1]}.{dst_ip[2]}.{dst_ip[3]}",
                    dst_port,
                )
                try:
                    sock.sendto(msg_serialize(cmd.msg), dst)
                except (OSError, ValueError, TypeError):
                    pass  # unable to send/serialize: ignore, like the reference
            elif isinstance(cmd, SetTimerCmd):
                lo, hi = cmd.duration
                duration = _random.uniform(lo, hi) if lo < hi else lo
                next_interrupts[("timeout", cmd.timer)] = (
                    time.monotonic() + duration
                )
            elif isinstance(cmd, CancelTimerCmd):
                key = ("timeout", cmd.timer)
                if key in next_interrupts:
                    next_interrupts[key] = _PRACTICALLY_NEVER
            elif isinstance(cmd, ChooseRandomCmd):
                if not cmd.choices:
                    return
                chosen = _random.choice(list(cmd.choices))
                duration = _random.uniform(0.0, 10.0)
                next_interrupts[("random", chosen)] = (
                    time.monotonic() + duration
                )
            elif isinstance(cmd, SaveCmd):
                with open(storage_path, "wb") as f:
                    f.write(storage_serialize(cmd.storage))

        out = Out()
        state = actor.on_start(id, storage, out)
        for c in out:
            on_command(c)

        while not runtime._stop.is_set():
            out = Out()
            if next_interrupts:
                min_key = min(next_interrupts, key=next_interrupts.get)
                min_at = next_interrupts[min_key]
            else:
                min_key, min_at = None, _PRACTICALLY_NEVER
            max_wait = min_at - time.monotonic()
            if max_wait > 0:
                sock.settimeout(min(max_wait, 1.0))
                try:
                    data, src_addr = sock.recvfrom(MAX_DATAGRAM)
                except socket.timeout:
                    continue
                except OSError:
                    return  # socket closed: runtime stopping
                try:
                    msg = msg_deserialize(data)
                except (ValueError, KeyError):
                    continue  # unparseable: ignore, like the reference
                src = Id.from_socket_addr(
                    tuple(int(b) for b in src_addr[0].split(".")),
                    src_addr[1],
                )
                next_state = actor.on_msg(id, state, src, msg, out)
            else:
                del next_interrupts[min_key]
                kind, payload = min_key
                if kind == "timeout":
                    next_state = actor.on_timeout(id, state, payload, out)
                else:
                    next_state = actor.on_random(id, state, payload, out)
            if next_state is not None:
                state = next_state
            for c in out:
                on_command(c)
    except BaseException as e:
        runtime.errors.append(e)
