"""The ``Actor`` abstraction and its command output.

Reference: src/actor.rs.  An actor initializes internal state (``on_start``)
and then reacts to events (``on_msg`` / ``on_timeout`` / ``on_random``),
updating state and emitting ``Out`` commands (send / timers / random
choices / storage saves).

API translation note: the reference passes state as ``&mut Cow<State>`` so
no-op handlers avoid allocating (src/actor.rs:282-299).  Here handlers
*return* the next state, or ``None`` for "unchanged" — the direct analog of
``Cow::Borrowed`` — and no-op detection checks a ``None`` return plus an
empty command list.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable, List, Optional, Tuple

from .ids import Id


@dataclass(frozen=True)
class SendCmd:
    dst: Id
    msg: Any


@dataclass(frozen=True)
class SetTimerCmd:
    timer: Any
    duration: Tuple[float, float]  # seconds (lo, hi); irrelevant when checking


@dataclass(frozen=True)
class CancelTimerCmd:
    timer: Any


@dataclass(frozen=True)
class ChooseRandomCmd:
    key: str
    choices: Tuple[Any, ...]


@dataclass(frozen=True)
class SaveCmd:
    storage: Any


def model_timeout() -> Tuple[float, float]:
    """Timeout durations are irrelevant for model checking
    (reference: src/actor/model.rs:79-81)."""
    return (0.0, 0.0)


def model_peers(self_ix: int, count: int) -> List[Id]:
    """Peer ids for actor ``self_ix`` out of ``count``
    (reference: src/actor/model.rs:85-90)."""
    return [Id(j) for j in range(count) if j != self_ix]


def majority(count: int) -> int:
    """Minimum size of a majority quorum (reference: src/actor.rs:634-638)."""
    return count // 2 + 1


class Out:
    """Collects commands emitted by an actor handler.
    Reference: src/actor.rs:160-247."""

    __slots__ = ("commands",)

    def __init__(self):
        self.commands: List[Any] = []

    def send(self, recipient: Id, msg: Any) -> None:
        self.commands.append(SendCmd(Id(recipient), msg))

    def broadcast(self, recipients: Iterable[Id], msg: Any) -> None:
        for r in recipients:
            self.send(r, msg)

    def set_timer(self, timer: Any, duration: Tuple[float, float] = (0.0, 0.0)) -> None:
        self.commands.append(SetTimerCmd(timer, duration))

    def cancel_timer(self, timer: Any) -> None:
        self.commands.append(CancelTimerCmd(timer))

    def choose_random(self, key: str, choices: Iterable[Any]) -> None:
        """Record a nondeterministic choice set, creating a branch in the
        search tree.  Overwrites previous calls with the same key."""
        self.commands.append(ChooseRandomCmd(key, tuple(choices)))

    def remove_random(self, key: str) -> None:
        self.commands.append(ChooseRandomCmd(key, ()))

    def save(self, storage: Any) -> None:
        self.commands.append(SaveCmd(storage))

    def __len__(self) -> int:
        return len(self.commands)

    def __iter__(self):
        return iter(self.commands)

    def __repr__(self) -> str:
        return f"Out({self.commands!r})"


def is_no_op(returned_state: Optional[Any], out: Out) -> bool:
    """True iff the handler neither updated state nor emitted commands.
    Reference: src/actor.rs:282-284."""
    return returned_state is None and not out.commands


def is_no_op_with_timer(returned_state: Optional[Any], out: Out, timer: Any) -> bool:
    """True iff the handler only renewed the same timer.
    Reference: src/actor.rs:289-299."""
    keep_timer = any(
        isinstance(c, SetTimerCmd) and c.timer == timer for c in out.commands
    )
    return returned_state is None and len(out.commands) == 1 and keep_timer


class Actor:
    """Event-driven actor.  Reference: the ``Actor`` trait, src/actor.rs:305-411.

    Handlers other than ``on_start`` return the next actor state, or ``None``
    to indicate no change.
    """

    def on_start(self, id: Id, storage: Optional[Any], o: Out) -> Any:
        raise NotImplementedError

    def on_msg(self, id: Id, state: Any, src: Id, msg: Any, o: Out) -> Optional[Any]:
        return None

    def on_timeout(self, id: Id, state: Any, timer: Any, o: Out) -> Optional[Any]:
        return None

    def on_random(self, id: Id, state: Any, random: Any, o: Out) -> Optional[Any]:
        return None

    def name(self) -> str:
        return ""
