"""Pluggable datagram transports for the real-network actor runtime.

The reference's ``spawn`` (src/actor/spawn.rs) hard-wires UDP sockets into
the event loop.  Here the socket code is behind a three-method ``Transport``
interface so the *same* runtime can run over:

- :class:`UdpTransport` — the production wire (one UDP socket per actor,
  addresses encoded in the actor ``Id``, src/actor/spawn.rs:96-105);
- :class:`LoopbackTransport` — an in-process queue fabric for hermetic
  tests: actor ``Id``s are plain indices, no ports are bound, and a chaos
  wrapper (``runtime/chaos.py``) can inject seeded drop / duplicate /
  reorder / delay / partition faults deterministically.

Transports deal in raw datagrams (``bytes``) addressed by ``Id`` — message
codecs stay in the runtime, exactly where the reference keeps serde.
Datagram semantics are fire-and-forget: ``send`` to an unreachable or
unbound destination silently drops, like UDP.
"""

from __future__ import annotations

import queue
import socket
import threading
from typing import Optional, Tuple

from .ids import Id

MAX_DATAGRAM = 65_535


class TransportClosed(Exception):
    """Raised by ``Endpoint.recv`` once the endpoint is closed — the
    runtime's signal that the actor thread should exit."""


class Endpoint:
    """One actor's attachment to a transport (the analog of its socket)."""

    def send(self, dst: Id, data: bytes) -> None:
        """Fire-and-forget datagram send; never raises on delivery failure."""
        raise NotImplementedError

    def recv(self, timeout: float) -> Optional[Tuple[bytes, Id]]:
        """Wait up to ``timeout`` seconds for one datagram.

        Returns ``(data, src)`` or ``None`` on timeout; raises
        :class:`TransportClosed` once the endpoint is closed.
        """
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class Transport:
    """A datagram fabric actors bind endpoints onto."""

    def bind(self, id: Id) -> Endpoint:
        """Create the endpoint for actor ``id``; raises if the address is
        taken (mirroring a UDP bind failure)."""
        raise NotImplementedError

    def close(self) -> None:
        """Release any fabric-level resources (endpoints close themselves)."""


# --- UDP ---------------------------------------------------------------------


class UdpEndpoint(Endpoint):
    def __init__(self, id: Id):
        ip, port = Id(id).to_socket_addr()
        addr = (f"{ip[0]}.{ip[1]}.{ip[2]}.{ip[3]}", port)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind(addr)

    def send(self, dst: Id, data: bytes) -> None:
        ip, port = Id(dst).to_socket_addr()
        try:
            self._sock.sendto(
                data, (f"{ip[0]}.{ip[1]}.{ip[2]}.{ip[3]}", port)
            )
        except OSError:
            pass  # unable to send: ignore, like the reference

    def recv(self, timeout: float) -> Optional[Tuple[bytes, Id]]:
        self._sock.settimeout(timeout)
        try:
            data, src_addr = self._sock.recvfrom(MAX_DATAGRAM)
        except socket.timeout:
            return None
        except OSError:
            raise TransportClosed() from None
        src = Id.from_socket_addr(
            tuple(int(b) for b in src_addr[0].split(".")), src_addr[1]
        )
        return data, src

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class UdpTransport(Transport):
    """The production transport: actor ``Id``s are encoded socket addresses
    (``ip << 16 | port``), one bound UDP socket per actor."""

    def bind(self, id: Id) -> UdpEndpoint:
        return UdpEndpoint(id)


# --- in-process loopback -----------------------------------------------------

_CLOSE = object()  # queue sentinel waking a parked recv on close


class LoopbackEndpoint(Endpoint):
    def __init__(self, transport: "LoopbackTransport", id: Id):
        self._transport = transport
        self.id = Id(id)
        self._queue: "queue.Queue" = queue.Queue()
        self._closed = False

    def send(self, dst: Id, data: bytes) -> None:
        if self._closed or len(data) > MAX_DATAGRAM:
            return  # oversized datagrams drop, like UDP sendto failing
        self._transport._deliver(self.id, Id(dst), data)

    def recv(self, timeout: float) -> Optional[Tuple[bytes, Id]]:
        if self._closed:
            raise TransportClosed()
        try:
            item = self._queue.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is _CLOSE:
            raise TransportClosed()
        return item

    def close(self) -> None:
        self._closed = True
        self._transport._unbind(self.id)
        self._queue.put(_CLOSE)


class LoopbackTransport(Transport):
    """Hermetic in-process fabric: per-actor queues keyed by ``Id``.  Any
    hashable ``Id`` works (plain model indices included), so the actors a
    model checked can run unmodified without binding ports."""

    def __init__(self):
        self._endpoints = {}
        self._lock = threading.Lock()

    def bind(self, id: Id) -> LoopbackEndpoint:
        id = Id(id)
        with self._lock:
            if id in self._endpoints:
                raise OSError(f"loopback address already bound: {id!r}")
            ep = LoopbackEndpoint(self, id)
            self._endpoints[id] = ep
            return ep

    def _unbind(self, id: Id) -> None:
        with self._lock:
            self._endpoints.pop(Id(id), None)

    def _deliver(self, src: Id, dst: Id, data: bytes) -> None:
        with self._lock:
            ep = self._endpoints.get(dst)
        if ep is not None and not ep._closed:
            ep._queue.put((data, src))
