"""Actor identifiers.

Reference: src/actor.rs:110-158 — ``Id(u64)`` doubles as a model index
(0, 1, 2, …) and an encoded IPv4 socket address (``ip << 16 | port``) for
the real UDP runtime.  It is also the marker type that symmetry rewrite
plans renumber (src/checker/rewrite.rs).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple


class Id(int):
    """An actor identifier; an ``int`` subclass so it can index vectors
    directly while staying distinguishable for symmetry rewriting."""

    __slots__ = ()

    def __repr__(self) -> str:  # match the reference's Display (the index)
        return f"Id({int(self)})"

    @staticmethod
    def from_socket_addr(ip: Tuple[int, int, int, int], port: int) -> "Id":
        ip_u32 = (ip[0] << 24) | (ip[1] << 16) | (ip[2] << 8) | ip[3]
        return Id((ip_u32 << 16) | port)

    def to_socket_addr(self) -> Tuple[Tuple[int, int, int, int], int]:
        v = int(self)
        ip_u32 = v >> 16
        port = v & 0xFFFF
        return (
            ((ip_u32 >> 24) & 0xFF, (ip_u32 >> 16) & 0xFF, (ip_u32 >> 8) & 0xFF, ip_u32 & 0xFF),
            port,
        )

    @staticmethod
    def vec_from(values: Iterable[int]) -> List["Id"]:
        return [Id(v) for v in values]
