"""JSON wire codec for actor messages on the real-network runtime.

The reference's ``spawn`` examples serialize typed message enums with
serde_json, so running systems can be poked with ``nc -u`` and hand-written
JSON (examples/paxos.rs:488-512).  Python dataclass messages get the same
treatment here: a message encodes as a JSON object tagged with its class
name (``{"__t": "Put", "request_id": 1, "value": "X"}``), nested
dataclasses recurse, actor ``Id``s encode as ``{"__id": n}``, tuples and
frozensets as tagged lists.  Classes decode through an explicit registry —
register a protocol's message types once with :func:`register_wire_types`
before deserializing (the model CLIs' ``spawn`` subcommands register their
protocol's types when they start).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Type

from .ids import Id

_REGISTRY: Dict[str, Type] = {}


def register_wire_types(*classes: Type) -> None:
    for c in classes:
        existing = _REGISTRY.get(c.__name__)
        if existing is not None and existing is not c:
            raise ValueError(
                f"wire type name collision: {c.__name__} already registered "
                f"for {existing.__module__}.{existing.__qualname__}"
            )
        _REGISTRY[c.__name__] = c


def _enc(v: Any) -> Any:
    if isinstance(v, Id):
        return {"__id": int(v)}
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        out = {"__t": type(v).__name__}
        for f in dataclasses.fields(v):
            out[f.name] = _enc(getattr(v, f.name))
        return out
    if isinstance(v, tuple):
        return {"__tup": [_enc(x) for x in v]}
    if isinstance(v, (frozenset, set)):
        return {"__set": sorted((_enc(x) for x in v), key=json.dumps)}
    if isinstance(v, list):
        return [_enc(x) for x in v]
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    raise TypeError(f"cannot wire-encode {type(v).__name__}: {v!r}")


def _dec(v: Any) -> Any:
    # Decode failures must surface as ValueError: the runtime's receive
    # loop treats that as "malformed datagram, drop it" — anything else
    # would kill the replica thread on a hand-typed probe message.
    if isinstance(v, dict):
        if "__id" in v:
            if not isinstance(v["__id"], int) or isinstance(v["__id"], bool):
                raise ValueError(f"malformed __id payload: {v!r}")
            return Id(v["__id"])
        if "__tup" in v:
            if not isinstance(v["__tup"], list):
                raise ValueError(f"malformed __tup payload: {v!r}")
            return tuple(_dec(x) for x in v["__tup"])
        if "__set" in v:
            if not isinstance(v["__set"], list):
                raise ValueError(f"malformed __set payload: {v!r}")
            try:
                return frozenset(_dec(x) for x in v["__set"])
            except TypeError as e:  # unhashable element
                raise ValueError(f"malformed __set payload: {v!r}") from e
        if "__t" in v:
            if not isinstance(v["__t"], str):
                raise ValueError(f"malformed __t tag: {v!r}")
            cls = _REGISTRY.get(v["__t"])
            if cls is None:
                raise ValueError(f"unknown wire type {v['__t']!r}")
            fields = {k: _dec(x) for k, x in v.items() if k != "__t"}
            try:
                return cls(**fields)
            except TypeError as e:
                raise ValueError(
                    f"wire message fields do not match {v['__t']}: {e}"
                ) from e
        return {k: _dec(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_dec(x) for x in v]
    return v


def wire_serialize(msg: Any) -> bytes:
    return json.dumps(_enc(msg)).encode()


def wire_deserialize(data: bytes) -> Any:
    # The full failure surface must be ValueError (the runtime's
    # malformed-datagram contract): UnicodeDecodeError and JSONDecodeError
    # already subclass it; absurdly nested payloads would otherwise
    # surface as RecursionError and kill the replica thread.
    try:
        return _dec(json.loads(data.decode()))
    except RecursionError as e:
        raise ValueError("wire message nests too deeply") from e
