"""Write-once-register actor harness: the register harness variant whose
protocol adds ``PutFail`` and whose history records against the
write-once-register spec.

Reference: src/actor/write_once_register.rs.  Like the plain register
harness (actor/register.py), servers must precede clients in the model's
actor list so a server id can be derived as ``(client_index + k) %
server_count``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..semantics.register import ReadOk, ReadOp, WriteOp, WRITE_OK, READ
from ..semantics.write_once_register import WRITE_FAIL
from .base import Actor, Out
from .ids import Id


@dataclass(frozen=True)
class Internal:
    """Wraps a protocol-internal message (WORegisterMsg::Internal)."""

    msg: Any


@dataclass(frozen=True)
class Put:
    request_id: int
    value: Any


@dataclass(frozen=True)
class Get:
    request_id: int


@dataclass(frozen=True)
class PutOk:
    request_id: int


@dataclass(frozen=True)
class PutFail:
    """An unsuccessful Put (the write-once register refused to overwrite)."""

    request_id: int


@dataclass(frozen=True)
class GetOk:
    request_id: int
    value: Any


def record_invocations(_cfg, history, env) -> Optional[Any]:
    """Pass to ``ActorModel.record_msg_out``; records ``Read`` upon ``Get``
    and ``Write`` upon ``Put`` (reference:39-61)."""
    if isinstance(env.msg, Get):
        h = history.clone()
        try:
            h.on_invoke(env.src, READ)
        except ValueError:
            pass
        return h
    if isinstance(env.msg, Put):
        h = history.clone()
        try:
            h.on_invoke(env.src, WriteOp(env.msg.value))
        except ValueError:
            pass
        return h
    return None


def record_returns(_cfg, history, env) -> Optional[Any]:
    """Pass to ``ActorModel.record_msg_in``; records ``ReadOk`` / ``WriteOk``
    / ``WriteFail`` upon the corresponding response (reference:63-97)."""
    if isinstance(env.msg, GetOk):
        h = history.clone()
        try:
            h.on_return(env.dst, ReadOk(env.msg.value))
        except ValueError:
            pass
        return h
    if isinstance(env.msg, PutOk):
        h = history.clone()
        try:
            h.on_return(env.dst, WRITE_OK)
        except ValueError:
            pass
        return h
    if isinstance(env.msg, PutFail):
        h = history.clone()
        try:
            h.on_return(env.dst, WRITE_FAIL)
        except ValueError:
            pass
        return h
    return None


@dataclass(frozen=True)
class ClientState:
    awaiting: Optional[int]
    op_count: int


class WORegisterClient(Actor):
    """Scripted client: ``put_count`` Puts then a Get; a ``PutFail`` also
    advances the script (reference:230-276)."""

    def __init__(self, put_count: int, server_count: int):
        self.put_count = put_count
        self.server_count = server_count

    def name(self) -> str:
        return "Client"

    def on_start(self, id: Id, storage, o: Out):
        index = int(id)
        if index < self.server_count:
            raise RuntimeError(
                "WORegisterClient actors must be added to the model after servers."
            )
        if self.put_count == 0:
            return ClientState(awaiting=None, op_count=0)
        unique_request_id = 1 * index
        value = chr(ord("A") + (index - self.server_count))
        o.send(Id(index % self.server_count), Put(unique_request_id, value))
        return ClientState(awaiting=unique_request_id, op_count=1)

    def on_msg(self, id: Id, state, src: Id, msg, o: Out):
        if not isinstance(state, ClientState) or state.awaiting is None:
            return None
        index = int(id)
        if (
            isinstance(msg, (PutOk, PutFail))
            and msg.request_id == state.awaiting
        ):
            unique_request_id = (state.op_count + 1) * index
            if state.op_count < self.put_count:
                value = chr(ord("Z") - (index - self.server_count))
                o.send(
                    Id((index + state.op_count) % self.server_count),
                    Put(unique_request_id, value),
                )
            else:
                o.send(
                    Id((index + state.op_count) % self.server_count),
                    Get(unique_request_id),
                )
            return ClientState(
                awaiting=unique_request_id, op_count=state.op_count + 1
            )
        if isinstance(msg, GetOk) and msg.request_id == state.awaiting:
            return ClientState(awaiting=None, op_count=state.op_count + 1)
        return None


class WORegisterServer(Actor):
    """Wraps a server actor under test; delegates every event
    (reference:279-291)."""

    def __init__(self, server_actor: Actor):
        self.server_actor = server_actor

    def name(self) -> str:
        return self.server_actor.name()

    def on_start(self, id: Id, storage, o: Out):
        return self.server_actor.on_start(id, storage, o)

    def on_msg(self, id: Id, state, src: Id, msg, o: Out):
        return self.server_actor.on_msg(id, state, src, msg, o)

    def on_timeout(self, id: Id, state, timer, o: Out):
        return self.server_actor.on_timeout(id, state, timer, o)

    def on_random(self, id: Id, state, random, o: Out):
        return self.server_actor.on_random(id, state, random, o)
