"""The in-model communication fabric with three pluggable semantics.

Reference: src/actor/network.rs.

- ``unordered_duplicating``: a *set* of envelopes plus a last-delivered
  marker; delivery leaves the envelope in place (redelivery allowed), and
  remembering the last message delivered lets a redelivery that doesn't
  change actor state still change the state fingerprint
  (src/actor/network.rs:52, 224-228).
- ``unordered_nonduplicating``: a *multiset* (envelope -> count); delivery
  and drops decrement counts (src/actor/network.rs:55, 229-242).
- ``ordered``: per-directed-pair FIFO queues; only channel heads are
  deliverable (src/actor/network.rs:67, 243-265).

Networks here are immutable values (state snapshots share them); mutating
ops return new networks.  Iteration is deterministic (sorted by src, dst,
message fingerprint) so model re-execution is reproducible across
processes — the analog of the reference's fixed-seed hashers.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, FrozenSet, List, Optional, Tuple

from ..ops.fingerprint import fingerprint
from .ids import Id

UNORDERED_DUPLICATING = "unordered_duplicating"
UNORDERED_NONDUPLICATING = "unordered_nonduplicating"
ORDERED = "ordered"


@dataclass(frozen=True)
class Envelope:
    """Reference: src/actor/network.rs:25-29."""

    src: Id
    dst: Id
    msg: Any

    def _sort_key(self):
        return (int(self.src), int(self.dst), fingerprint(self.msg))


@dataclass(frozen=True)
class Network:
    kind: str
    # unordered_duplicating: envelopes = frozenset[Envelope], last_msg
    # unordered_nonduplicating: counts = frozenset[(Envelope, int)]
    # ordered: flows = tuple[((src, dst), tuple[msg, ...]), ...] sorted by key
    envelopes: FrozenSet[Envelope] = frozenset()
    last_msg: Optional[Envelope] = None
    counts: FrozenSet[Tuple[Envelope, int]] = frozenset()
    flows: Tuple[Tuple[Tuple[Id, Id], Tuple[Any, ...]], ...] = ()

    # --- constructors -------------------------------------------------------

    @staticmethod
    def new_unordered_duplicating(envelopes=()) -> "Network":
        n = Network(kind=UNORDERED_DUPLICATING)
        for e in envelopes:
            n = n.send(e)
        return n

    @staticmethod
    def new_unordered_duplicating_with_last_msg(envelopes, last_msg) -> "Network":
        n = Network.new_unordered_duplicating(envelopes)
        return Network(
            kind=UNORDERED_DUPLICATING, envelopes=n.envelopes, last_msg=last_msg
        )

    @staticmethod
    def new_unordered_nonduplicating(envelopes=()) -> "Network":
        n = Network(kind=UNORDERED_NONDUPLICATING)
        for e in envelopes:
            n = n.send(e)
        return n

    @staticmethod
    def new_ordered(envelopes=()) -> "Network":
        n = Network(kind=ORDERED)
        for e in envelopes:
            n = n.send(e)
        return n

    @staticmethod
    def names() -> List[str]:
        return [ORDERED, UNORDERED_DUPLICATING, UNORDERED_NONDUPLICATING]

    @staticmethod
    def from_name(name: str) -> "Network":
        """CLI string-to-network registry (reference src/actor/network.rs:318-331)."""
        if name == ORDERED:
            return Network.new_ordered()
        if name == UNORDERED_DUPLICATING:
            return Network.new_unordered_duplicating()
        if name == UNORDERED_NONDUPLICATING:
            return Network.new_unordered_nonduplicating()
        raise ValueError(f"unable to parse network name: {name}")

    @property
    def is_ordered(self) -> bool:
        return self.kind == ORDERED

    # --- queries ------------------------------------------------------------

    def __len__(self) -> int:
        if self.kind == UNORDERED_DUPLICATING:
            return len(self.envelopes)
        if self.kind == UNORDERED_NONDUPLICATING:
            return sum(c for (_e, c) in self.counts)
        return sum(len(msgs) for (_k, msgs) in self.flows)

    def iter_all(self) -> List[Envelope]:
        """All envelopes (multiset entries repeated; every queued ordered
        message).  Reference: src/actor/network.rs:169-177."""
        if self.kind == UNORDERED_DUPLICATING:
            return sorted(self.envelopes, key=Envelope._sort_key)
        if self.kind == UNORDERED_NONDUPLICATING:
            out = []
            for e, c in sorted(self.counts, key=lambda ec: ec[0]._sort_key()):
                out.extend([e] * c)
            return out
        out = []
        for (src, dst), msgs in self.flows:
            for m in msgs:
                out.append(Envelope(src, dst, m))
        return out

    def iter_deliverable(self) -> List[Envelope]:
        """Distinct deliverable envelopes; for ordered networks, only channel
        heads.  Reference: src/actor/network.rs:180-190."""
        if self.kind == UNORDERED_DUPLICATING:
            return sorted(self.envelopes, key=Envelope._sort_key)
        if self.kind == UNORDERED_NONDUPLICATING:
            return sorted(
                (e for (e, _c) in self.counts), key=Envelope._sort_key
            )
        return [
            Envelope(src, dst, msgs[0]) for (src, dst), msgs in self.flows
        ]

    # --- mutations (returning new networks) ---------------------------------

    def send(self, env: Envelope) -> "Network":
        """Reference: src/actor/network.rs:203-217."""
        if self.kind == UNORDERED_DUPLICATING:
            return Network(
                kind=self.kind,
                envelopes=self.envelopes | {env},
                last_msg=self.last_msg,
            )
        if self.kind == UNORDERED_NONDUPLICATING:
            counts = dict(self.counts)
            counts[env] = counts.get(env, 0) + 1
            return Network(kind=self.kind, counts=frozenset(counts.items()))
        flows = dict(self.flows)
        key = (env.src, env.dst)
        flows[key] = flows.get(key, ()) + (env.msg,)
        return Network(kind=self.kind, flows=tuple(sorted(flows.items())))

    def on_deliver(self, env: Envelope) -> "Network":
        """Reference: src/actor/network.rs:219-267."""
        if self.kind == UNORDERED_DUPLICATING:
            # Envelope stays (duplicating); remember the last delivery so a
            # no-op redelivery still perturbs the fingerprint.
            return Network(kind=self.kind, envelopes=self.envelopes, last_msg=env)
        if self.kind == UNORDERED_NONDUPLICATING:
            return self._remove_one(env)
        return self._remove_ordered(env)

    def on_drop(self, env: Envelope) -> "Network":
        """Reference: src/actor/network.rs:269-315."""
        if self.kind == UNORDERED_DUPLICATING:
            return Network(
                kind=self.kind,
                envelopes=self.envelopes - {env},
                last_msg=self.last_msg,
            )
        if self.kind == UNORDERED_NONDUPLICATING:
            return self._remove_one(env)
        return self._remove_ordered(env)

    def _remove_one(self, env: Envelope) -> "Network":
        counts = dict(self.counts)
        if env not in counts:
            raise KeyError(f"envelope not found: {env!r}")
        if counts[env] == 1:
            del counts[env]
        else:
            counts[env] -= 1
        return Network(kind=self.kind, counts=frozenset(counts.items()))

    def _remove_ordered(self, env: Envelope) -> "Network":
        flows = dict(self.flows)
        key = (env.src, env.dst)
        if key not in flows:
            raise KeyError(f"flow not found: src={env.src!r} dst={env.dst!r}")
        msgs = flows[key]
        try:
            i = msgs.index(env.msg)
        except ValueError:
            raise KeyError(f"message not found: {env.msg!r}") from None
        remaining = msgs[:i] + msgs[i + 1 :]
        if remaining:
            flows[key] = remaining
        else:
            del flows[key]  # canonicalize: no empty flows
        return Network(kind=self.kind, flows=tuple(sorted(flows.items())))

    def rewrite(self, plan) -> "Network":
        """Renumber actor ids for symmetry reduction
        (reference: src/actor/network.rs:333-348)."""
        from ..core.symmetry import rewrite_value

        def renv(e: Envelope) -> Envelope:
            return Envelope(
                Id(plan.rewrite(e.src)),
                Id(plan.rewrite(e.dst)),
                rewrite_value(e.msg, plan),
            )

        if self.kind == UNORDERED_DUPLICATING:
            return Network(
                kind=self.kind,
                envelopes=frozenset(renv(e) for e in self.envelopes),
                last_msg=renv(self.last_msg) if self.last_msg else None,
            )
        if self.kind == UNORDERED_NONDUPLICATING:
            return Network(
                kind=self.kind,
                counts=frozenset((renv(e), c) for (e, c) in self.counts),
            )
        return Network(
            kind=self.kind,
            flows=tuple(
                sorted(
                    (
                        (Id(plan.rewrite(src)), Id(plan.rewrite(dst))),
                        tuple(rewrite_value(m, plan) for m in msgs),
                    )
                    for (src, dst), msgs in self.flows
                )
            ),
        )
