"""Actor-runtime observability: per-link metrics, causal message
tracing, and the live ``/.metrics`` surface for spawned systems.

The device engines report always-on vitals, histograms, and a Prometheus
exposition (docs/OBSERVABILITY.md); this module gives the *actor* half
of the capability surface — the runtime that executes model-checked
actors over real UDP (``actor/spawn.py``) — the same three pieces:

- :class:`ObservedTransport` — wraps any ``Transport`` with per-link
  datagram/byte counters (``link_*`` flat dicts, rendered as labeled
  Prometheus gauge families) and, with ``trace=True``, the causal
  **trace envelope**: every outgoing datagram is wrapped with a
  ``(trace_id, hop, sent_at)`` header OUTSIDE the message codec, so
  ``wire.py`` encoding, ORL semantics, and every model-pinned golden
  stay bit-identical.  A handler's sends inherit the trace id of the
  message being handled with ``hop + 1`` (the runtime is
  one-thread-per-actor, so a thread-local carries the context), giving
  a request a causal chain followable across actors through the
  journal's ``actor_span`` events.  With ``trace=False`` the send path
  adds nothing to the datagram — zero wire overhead when disabled.
- envelope codec (:func:`wrap_datagram` / :func:`unwrap_datagram`) —
  a fixed binary header (magic + version, 64-bit trace id, hop byte,
  wall-clock send time, payload length).  Un-enveloped (legacy)
  datagrams pass through untouched; a datagram that *starts* with the
  magic but carries a torn or inconsistent header raises ``ValueError``
  (the malformed-datagram contract ``wire.py`` already guarantees,
  fuzzed in tests/test_wire_fuzz.py) and the transport drops it.
- :func:`serve_actor_metrics` — the ``spawn --metrics-port`` surface:
  ``GET /.metrics`` on the runtime, JSON by default and the Prometheus
  text exposition under ``?format=prometheus`` / an Accept header
  preferring it, exactly like the Explorer and the checking service.

Metric names are part of the documented surface
(docs/OBSERVABILITY.md "Actor-runtime observability").
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..obs.metrics import LATENCY_BUCKETS, MetricsRegistry
from .ids import Id
from .transport import Endpoint, Transport

__all__ = [
    "ENVELOPE_OVERHEAD",
    "MAGIC",
    "ObservedEndpoint",
    "ObservedTransport",
    "TraceContext",
    "clear_trace_context",
    "find_faulty",
    "find_in_stack",
    "find_observed",
    "serve_actor_metrics",
    "unwrap_datagram",
    "wrap_datagram",
]

# Envelope magic: 0xAB is not valid UTF-8 lead byte for JSON text, so no
# wire.py datagram (nor any hand-typed `nc -u` probe) can collide with
# an enveloped one; "SR1" carries the format version.
MAGIC = b"\xabSR1"
# trace_id (u64) | hop (u8) | sent_at (f64 wall seconds) | payload len (u32)
_HEADER = struct.Struct(">QBdI")
ENVELOPE_OVERHEAD = len(MAGIC) + _HEADER.size
_MAX_HOP = 255


@dataclass(frozen=True)
class TraceContext:
    """The decoded trace header of one received datagram."""

    trace_id: int
    hop: int
    sent_at: float


def wrap_datagram(
    payload: bytes, trace_id: int, hop: int, sent_at: float
) -> bytes:
    """Envelope ``payload`` with a trace header (see module docstring)."""
    return MAGIC + _HEADER.pack(
        trace_id & 0xFFFFFFFFFFFFFFFF,
        min(max(int(hop), 0), _MAX_HOP),
        float(sent_at),
        len(payload),
    ) + payload


def unwrap_datagram(data: bytes) -> Tuple[bytes, Optional[TraceContext]]:
    """``(payload, TraceContext)`` for an enveloped datagram,
    ``(data, None)`` for a legacy (un-enveloped) one.  A datagram that
    starts with the envelope magic but has a truncated header or a
    payload length that disagrees with the actual size raises
    ``ValueError`` — the same malformed-datagram contract as
    ``wire.wire_deserialize``, so the receive path treats it as "drop
    it", never as a thread-killing surprise."""
    if not data.startswith(MAGIC):
        return data, None
    if len(data) < ENVELOPE_OVERHEAD:
        raise ValueError("malformed trace envelope: truncated header")
    trace_id, hop, sent_at, length = _HEADER.unpack(
        data[len(MAGIC):ENVELOPE_OVERHEAD]
    )
    payload = data[ENVELOPE_OVERHEAD:]
    if len(payload) != length:
        raise ValueError(
            f"malformed trace envelope: payload length {len(payload)} != "
            f"declared {length}"
        )
    if sent_at != sent_at or sent_at in (float("inf"), float("-inf")):
        raise ValueError("malformed trace envelope: non-finite send time")
    return payload, TraceContext(trace_id, hop, sent_at)


def _new_trace_id() -> int:
    return int.from_bytes(os.urandom(8), "big") or 1


# --- the observing transport --------------------------------------------------


class ObservedEndpoint(Endpoint):
    def __init__(self, transport: "ObservedTransport", inner: Endpoint, id: Id):
        self._transport = transport
        self._inner = inner
        self.id = Id(id)

    def send(self, dst: Id, data: bytes) -> None:
        t = self._transport
        if t.trace:
            ctx = getattr(t._tls, "ctx", None)
            if ctx is not None:
                trace_id, hop = ctx.trace_id, min(ctx.hop + 1, _MAX_HOP)
            else:
                trace_id, hop = _new_trace_id(), 0
            data = wrap_datagram(data, trace_id, hop, time.time())
        t._count(int(self.id), int(dst), len(data), out=True)
        self._inner.send(dst, data)

    def recv(self, timeout: float):
        received = self._inner.recv(timeout)
        if received is None:
            return None
        data, src = received
        t = self._transport
        ctx = None
        wire_bytes = len(data)  # counted pre-unwrap: bytes on the wire
        if data.startswith(MAGIC):
            try:
                data, ctx = unwrap_datagram(data)
            except ValueError:
                t.registry.inc("trace_envelope_malformed_total")
                return None  # dropped, like any malformed datagram
        # The handler about to run on this thread inherits this context
        # (None for a legacy datagram — a stale context must never leak
        # into an unrelated message's sends).
        t._tls.ctx = ctx
        t._count(int(src), int(self.id), wire_bytes, out=False)
        if ctx is not None:
            t._record_span(int(src), int(self.id), ctx)
        return data, src

    def close(self) -> None:
        self._inner.close()


class ObservedTransport(Transport):
    """Counts per-link traffic and (with ``trace=True``) envelopes every
    datagram with the causal trace header.  Stack it at the actor-facing
    boundary — e.g. ``Recording(Observed(Faulty(Loopback)))`` in the
    chaos harness, so the auditor still decodes clean payloads while the
    fault injector treats the envelope as opaque bytes."""

    def __init__(
        self,
        inner: Transport,
        registry: Optional[MetricsRegistry] = None,
        trace: bool = False,
        journal=None,
    ):
        from ..runtime.journal import as_journal

        self.inner = inner
        self.registry = registry if registry is not None else MetricsRegistry()
        self.trace = bool(trace)
        self.journal = as_journal(journal)
        self._lock = threading.Lock()
        # (src, dst) -> [datagrams_sent, bytes_sent, datagrams_recv,
        # bytes_recv]; sender and receiver sides update different slots
        # of the same directed-link row.
        self._links: Dict[Tuple[int, int], List[int]] = {}
        self._tls = threading.local()
        self.max_hop = 0
        self.span_count = 0

    def bind(self, id: Id) -> ObservedEndpoint:
        return ObservedEndpoint(self, self.inner.bind(id), id)

    def close(self) -> None:
        self.inner.close()

    # -- internals -------------------------------------------------------------

    def _count(self, src: int, dst: int, nbytes: int, out: bool) -> None:
        base = 0 if out else 2
        with self._lock:
            row = self._links.get((src, dst))
            if row is None:
                row = self._links[(src, dst)] = [0, 0, 0, 0]
            row[base] += 1
            row[base + 1] += nbytes
        if out:
            self.registry.inc("datagrams_sent_total")
            self.registry.inc("bytes_sent_total", nbytes)
        else:
            self.registry.inc("datagrams_received_total")
            self.registry.inc("bytes_received_total", nbytes)

    def _record_span(self, src: int, dst: int, ctx: TraceContext) -> None:
        latency = max(0.0, time.time() - ctx.sent_at)
        self.registry.observe(
            "actor_deliver_latency_sec", latency, boundaries=LATENCY_BUCKETS
        )
        with self._lock:
            self.max_hop = max(self.max_hop, ctx.hop)
            self.span_count += 1
        if self.journal is not None:
            self.journal.append(
                "actor_span",
                trace=format(ctx.trace_id, "016x"),
                hop=ctx.hop,
                src=src,
                dst=dst,
                latency_sec=round(latency, 6),
            )

    def link_metrics(self) -> Dict[str, Dict[str, int]]:
        """The per-link counters as flat ``"src->dst" -> n`` dicts (the
        shape obs/prometheus.py renders as labeled gauge families, like
        the sharded engine's per-shard skew dicts)."""
        with self._lock:
            rows = dict(self._links)
        out: Dict[str, Dict[str, int]] = {
            "link_datagrams_sent": {},
            "link_bytes_sent": {},
            "link_datagrams_received": {},
            "link_bytes_received": {},
        }
        for (src, dst), row in sorted(rows.items()):
            key = f"{src}->{dst}"
            if row[0]:
                out["link_datagrams_sent"][key] = row[0]
                out["link_bytes_sent"][key] = row[1]
            if row[2]:
                out["link_datagrams_received"][key] = row[2]
                out["link_bytes_received"][key] = row[3]
        return {k: v for k, v in out.items() if v}


def find_in_stack(transport_or_endpoint, cls):
    """Walk a transport/endpoint wrapper stack (``inner`` / ``_inner`` /
    ``_transport`` links, cycle-safe) for the first ``cls`` instance."""
    seen = set()
    node = transport_or_endpoint
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        if isinstance(node, cls):
            return node
        node = (
            getattr(node, "_transport", None)
            or getattr(node, "inner", None)
            or getattr(node, "_inner", None)
        )
    return None


def find_observed(transport_or_endpoint) -> Optional[ObservedTransport]:
    """The :class:`ObservedTransport` in a wrapper stack, if any."""
    return find_in_stack(transport_or_endpoint, ObservedTransport)


def find_faulty(transport_or_endpoint):
    """The chaos :class:`~stateright_tpu.runtime.chaos.FaultyTransport`
    in a wrapper stack, if any — the lookup the runtime's ``/.metrics``
    fold-in and the chaos-ensemble replay harness use to surface the
    fault-attribution table beside the link counters."""
    from ..runtime.chaos import FaultyTransport

    return find_in_stack(transport_or_endpoint, FaultyTransport)


def clear_trace_context(endpoint) -> None:
    """Drop the calling thread's inherited trace context.  The runtime
    calls this before dispatching a timer/random interrupt: a send made
    from ``on_timeout`` starts a NEW causal chain, not a continuation of
    whatever message this thread happened to receive last."""
    observed = find_observed(endpoint)
    if observed is not None:
        observed._tls.ctx = None


# --- the live /.metrics surface ----------------------------------------------


def serve_actor_metrics(runtime, address=("127.0.0.1", 0)):
    """Serve ``GET /.metrics`` over ``runtime.metrics()`` — JSON by
    default, the Prometheus text exposition via ``?format=prometheus``
    or a scraper's Accept header (the ``spawn --metrics-port`` surface;
    content negotiation shared with the Explorer and the checking
    service).  Returns the started ``ThreadingHTTPServer`` (daemon
    thread; ``server_address`` carries the bound port when 0 was
    asked)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from urllib.parse import parse_qs, urlparse

    from ..obs.prometheus import (
        CONTENT_TYPE, render_prometheus, wants_prometheus,
    )

    class _Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet: stderr is the actors' own
            pass

        def do_GET(self):
            parsed = urlparse(self.path)
            if parsed.path not in ("/.metrics", "/"):
                self.send_error(404)
                return
            query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
            try:
                metrics = runtime.metrics()
            except Exception as e:  # mid-teardown must not 500-loop a scraper
                self.send_error(503, str(e))
                return
            if wants_prometheus(query, self.headers.get("Accept")):
                body = render_prometheus(metrics).encode()
                ctype = CONTENT_TYPE
            else:
                body = json.dumps(metrics, sort_keys=True).encode()
                ctype = "application/json"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = ThreadingHTTPServer(tuple(address), _Handler)
    thread = threading.Thread(
        target=server.serve_forever, daemon=True, name="actor-metrics"
    )
    thread.start()
    return server
