"""``ActorModel``: adapts an actor system to the ``Model`` interface.

Reference: src/actor/model.rs and src/actor/model_state.rs.  The system
snapshot holds per-actor states, the network, pending timers, pending
random-choice sets, crash flags, auxiliary history (TLA-style — this is
where consistency testers plug in), and per-actor persistent storage.

Action families enumerated (src/actor/model.rs:269-333): Deliver (channel
heads only for ordered nets), Drop (if lossy), Timeout (per pending timer),
Crash (bounded by max_crashes), Recover, SelectRandom.  Handler no-ops are
suppressed — except on ordered networks, where consuming the channel head
matters (src/actor/model.rs:364).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple

from ..core.model import Model, Property
from ..core.symmetry import RewritePlan, rewrite_value
from ..ops.fingerprint import fingerprint
from .base import (
    Actor,
    CancelTimerCmd,
    ChooseRandomCmd,
    Out,
    SaveCmd,
    SendCmd,
    SetTimerCmd,
    is_no_op,
    is_no_op_with_timer,
)
from .ids import Id
from .network import Envelope, Network


# --- actions (reference: ActorModelAction, src/actor/model.rs:43-65) --------


@dataclass(frozen=True)
class Deliver:
    src: Id
    dst: Id
    msg: Any


@dataclass(frozen=True)
class Drop:
    envelope: Envelope


@dataclass(frozen=True)
class Timeout:
    id: Id
    timer: Any


@dataclass(frozen=True)
class Crash:
    id: Id


@dataclass(frozen=True)
class Recover:
    id: Id


@dataclass(frozen=True)
class SelectRandom:
    actor: Id
    key: str
    random: Any


# --- system state (reference: ActorModelState, src/actor/model_state.rs) ----


@dataclass(frozen=True)
class ActorModelState:
    actor_states: Tuple[Any, ...]
    network: Network
    timers_set: Tuple[Any, ...]  # per actor: frozenset of timers
    random_choices: Tuple[Any, ...]  # per actor: tuple of (key, (choices...)) sorted
    crashed: Tuple[bool, ...]
    history: Any
    actor_storages: Tuple[Any, ...]

    def representative(self) -> "ActorModelState":
        """Canonicalize under actor renaming: sort actor states, then rewrite
        every nested Id.  Reference: src/actor/model_state.rs:176-197."""
        plan = RewritePlan.from_values_to_sort(
            [fingerprint(s) for s in self.actor_states]
        )
        return ActorModelState(
            actor_states=tuple(plan.reindex(self.actor_states)),
            network=self.network.rewrite(plan),
            timers_set=tuple(plan.reindex(self.timers_set)),
            random_choices=tuple(plan.reindex(self.random_choices)),
            crashed=tuple(plan.reindex(self.crashed)),
            history=rewrite_value(self.history, plan),
            actor_storages=tuple(plan.reindex(self.actor_storages)),
        )


class _MutState:
    """Unfrozen working copy used while applying an action."""

    __slots__ = (
        "actor_states",
        "network",
        "timers_set",
        "random_choices",
        "crashed",
        "history",
        "actor_storages",
    )

    def __init__(self, s: Optional[ActorModelState] = None):
        if s is not None:
            self.actor_states = list(s.actor_states)
            self.network = s.network
            self.timers_set = list(s.timers_set)
            self.random_choices = [dict(rc) for rc in s.random_choices]
            self.crashed = list(s.crashed)
            self.history = s.history
            self.actor_storages = list(s.actor_storages)

    def freeze(self) -> ActorModelState:
        return ActorModelState(
            actor_states=tuple(self.actor_states),
            network=self.network,
            timers_set=tuple(self.timers_set),
            random_choices=tuple(
                tuple(sorted(rc.items())) for rc in self.random_choices
            ),
            crashed=tuple(self.crashed),
            history=self.history,
            actor_storages=tuple(self.actor_storages),
        )


class ActorModel(Model):
    """Reference: src/actor/model.rs:24-188 (builder) and the Model impl."""

    def __init__(self, cfg: Any = None, init_history: Any = None):
        self.actors: List[Actor] = []
        self.cfg = cfg
        self.init_history = init_history
        self.init_network: Network = Network.new_unordered_duplicating()
        self.lossy_network: bool = False
        self.max_crashes: int = 0
        self._properties: List[Property] = []
        self._record_msg_in: Callable = lambda cfg, h, env: None
        self._record_msg_out: Callable = lambda cfg, h, env: None
        self._within_boundary: Callable = lambda cfg, state: True

    # --- fluent builder -----------------------------------------------------

    def actor(self, actor: Actor) -> "ActorModel":
        self.actors.append(actor)
        return self

    def add_actors(self, actors) -> "ActorModel":
        self.actors.extend(actors)
        return self

    def init_network_(self, network: Network) -> "ActorModel":
        self.init_network = network
        return self

    def lossy_network_(self, lossy: bool) -> "ActorModel":
        self.lossy_network = lossy
        return self

    def max_crashes_(self, n: int) -> "ActorModel":
        self.max_crashes = n
        return self

    def property(self, expectation, name: str, condition) -> "ActorModel":
        self._properties.append(Property(expectation, name, condition))
        return self

    def record_msg_in(self, fn) -> "ActorModel":
        """fn(cfg, history, envelope) -> new history or None."""
        self._record_msg_in = fn
        return self

    def record_msg_out(self, fn) -> "ActorModel":
        self._record_msg_out = fn
        return self

    def within_boundary_(self, fn) -> "ActorModel":
        self._within_boundary = fn
        return self

    # --- Model impl ---------------------------------------------------------

    def properties(self) -> List[Property]:
        return list(self._properties)

    def within_boundary(self, state) -> bool:
        return self._within_boundary(self.cfg, state)

    def _process_commands(self, id: Id, out: Out, s: _MutState) -> None:
        """Apply actor commands to the system snapshot.
        Reference: src/actor/model.rs:191-235."""
        index = int(id)
        for c in out.commands:
            if isinstance(c, SendCmd):
                env = Envelope(id, c.dst, c.msg)
                history = self._record_msg_out(self.cfg, s.history, env)
                if history is not None:
                    s.history = history
                s.network = s.network.send(env)
            elif isinstance(c, SetTimerCmd):
                while len(s.timers_set) <= index:
                    s.timers_set.append(frozenset())
                s.timers_set[index] = s.timers_set[index] | {c.timer}
            elif isinstance(c, CancelTimerCmd):
                s.timers_set[index] = s.timers_set[index] - {c.timer}
            elif isinstance(c, ChooseRandomCmd):
                if not c.choices:
                    s.random_choices[index].pop(c.key, None)
                else:
                    s.random_choices[index][c.key] = tuple(c.choices)
            elif isinstance(c, SaveCmd):
                while len(s.actor_storages) <= index:
                    s.actor_storages.append(None)
                s.actor_storages[index] = c.storage
            else:
                raise TypeError(f"unknown command {c!r}")

    def init_states(self) -> List[ActorModelState]:
        s = _MutState()
        n = len(self.actors)
        s.actor_states = []
        s.network = self.init_network
        s.timers_set = [frozenset() for _ in range(n)]
        s.random_choices = [dict() for _ in range(n)]
        s.crashed = [False] * n
        s.history = self.init_history
        s.actor_storages = [None] * n
        for index, actor in enumerate(self.actors):
            id = Id(index)
            out = Out()
            state = actor.on_start(id, s.actor_storages[index], out)
            s.actor_states.append(state)
            self._process_commands(id, out, s)
        return [s.freeze()]

    def actions(self, state: ActorModelState, actions: List[Any]) -> None:
        # Reference: src/actor/model.rs:269-333 (same enumeration order).
        for env in state.network.iter_deliverable():
            if self.lossy_network:
                actions.append(Drop(env))
            if int(env.dst) < len(self.actors):
                actions.append(Deliver(env.src, env.dst, env.msg))

        for index, timers in enumerate(state.timers_set):
            for timer in sorted(timers, key=fingerprint):
                actions.append(Timeout(Id(index), timer))

        n_crashed = sum(state.crashed)
        if n_crashed < self.max_crashes:
            for index, crashed in enumerate(state.crashed):
                if not crashed:
                    actions.append(Crash(Id(index)))

        for index, crashed in enumerate(state.crashed):
            if crashed:
                actions.append(Recover(Id(index)))

        for index, choices in enumerate(state.random_choices):
            for key, decision in choices:
                for choice in decision:
                    actions.append(SelectRandom(Id(index), key, choice))

    def next_state(
        self, last: ActorModelState, action: Any
    ) -> Optional[ActorModelState]:
        # Reference: src/actor/model.rs:335-457.
        if isinstance(action, Drop):
            s = _MutState(last)
            s.network = s.network.on_drop(action.envelope)
            return s.freeze()

        if isinstance(action, Deliver):
            index = int(action.dst)
            if index >= len(last.actor_states):
                return None
            if last.crashed[index]:
                return None
            last_actor_state = last.actor_states[index]
            out = Out()
            next_actor_state = self.actors[index].on_msg(
                action.dst, last_actor_state, action.src, action.msg, out
            )
            if is_no_op(next_actor_state, out) and not self.init_network.is_ordered:
                return None
            env = Envelope(action.src, action.dst, action.msg)
            history = self._record_msg_in(self.cfg, last.history, env)
            s = _MutState(last)
            s.network = s.network.on_deliver(env)
            if next_actor_state is not None:
                s.actor_states[index] = next_actor_state
            if history is not None:
                s.history = history
            self._process_commands(action.dst, out, s)
            return s.freeze()

        if isinstance(action, Timeout):
            index = int(action.id)
            out = Out()
            next_actor_state = self.actors[index].on_timeout(
                action.id, last.actor_states[index], action.timer, out
            )
            if is_no_op_with_timer(next_actor_state, out, action.timer):
                return None
            s = _MutState(last)
            s.timers_set[index] = s.timers_set[index] - {action.timer}
            if next_actor_state is not None:
                s.actor_states[index] = next_actor_state
            self._process_commands(action.id, out, s)
            return s.freeze()

        if isinstance(action, Crash):
            index = int(action.id)
            s = _MutState(last)
            s.timers_set[index] = frozenset()
            s.random_choices[index] = {}
            s.crashed[index] = True
            return s.freeze()

        if isinstance(action, Recover):
            index = int(action.id)
            assert last.crashed[index]
            out = Out()
            state = self.actors[index].on_start(
                action.id, last.actor_storages[index], out
            )
            s = _MutState(last)
            s.actor_states[index] = state
            s.crashed[index] = False
            self._process_commands(action.id, out, s)
            return s.freeze()

        if isinstance(action, SelectRandom):
            index = int(action.actor)
            out = Out()
            next_actor_state = self.actors[index].on_random(
                action.actor, last.actor_states[index], action.random, out
            )
            s = _MutState(last)
            s.random_choices[index].pop(action.key, None)
            if next_actor_state is not None:
                s.actor_states[index] = next_actor_state
            self._process_commands(action.actor, out, s)
            return s.freeze()

        raise TypeError(f"unknown action {action!r}")

    # --- formatting (reference: src/actor/model.rs:459-597) -----------------

    def as_svg(self, path) -> Optional[str]:
        """Message-sequence diagram for a path: one vertical timeline per
        actor, an arrow per delivery (from its send time on the sender's
        line to its delivery time on the receiver's), circles for
        timeout/crash/recover/random events, labels drawn last.

        Reference: src/actor/model.rs:600-821 — same layout constants
        (``spacing = max(100, longest name * 10)``, 30px per time step),
        same CSS class names so the Explorer styles carry over; message
        text is additionally XML-escaped here.
        """
        from xml.sax.saxutils import escape

        steps = path.into_vec() if hasattr(path, "into_vec") else list(path)
        if not steps:
            return None
        actor_names = []
        for i, a in enumerate(self.actors):
            name = a.name() or ""
            actor_names.append(f"{i} {name}" if name else str(i))
        max_name_len = max((len(n) for n in actor_names), default=0) * 10
        spacing = max(100, max_name_len)

        def plot(x: int, y: int) -> Tuple[int, int]:
            return (x * spacing, y * 30)

        actor_count = len(steps[-1][0].actor_states)
        svg_w, svg_h = plot(actor_count, len(steps))
        svg_w += 300  # KLUDGE kept from the reference: room for labels
        out = [
            f"<svg version='1.1' baseProfile='full' "
            f"width='{svg_w}' height='{svg_h}' "
            f"viewbox='-20 -20 {svg_w + 20} {svg_h + 20}' "
            f"xmlns='http://www.w3.org/2000/svg'>",
            "<defs>"
            "<marker class='svg-event-shape' id='arrow' markerWidth='12' "
            "markerHeight='10' refX='12' refY='5' orient='auto'>"
            "<polygon points='0 0, 12 5, 0 10' /></marker></defs>",
        ]
        for i, name in enumerate(actor_names):
            (x1, y1) = plot(i, 0)
            (x2, y2) = plot(i, len(steps))
            out.append(
                f"<line x1='{x1}' y1='{y1}' x2='{x2}' y2='{y2}' "
                "class='svg-actor-timeline' />"
            )
            out.append(
                f"<text x='{x1}' y='{y1}' class='svg-actor-label'>"
                f"{escape(name)}</text>"
            )

        def handler_sends(index: int, run) -> List[Tuple[Id, Any]]:
            o = Out()
            if index < len(self.actors):
                run(self.actors[index], o)
            return [
                (c.dst, c.msg) for c in o.commands if isinstance(c, SendCmd)
            ]

        # Arrows for deliveries, circles for other events; sends tracked so
        # arrows start at the send time (0 for init-time sends).
        send_time: dict = {}
        for time, (state, action) in enumerate(steps):
            time += 1  # the action leads out of this state
            if isinstance(action, Deliver):
                src_time = send_time.get(
                    (action.src, action.dst, action.msg), 0
                )
                (x1, y1) = plot(int(action.src), src_time)
                (x2, y2) = plot(int(action.dst), time)
                out.append(
                    f"<line x1='{x1}' x2='{x2}' y1='{y1}' y2='{y2}' "
                    "marker-end='url(#arrow)' class='svg-event-line' />"
                )
                index = int(action.dst)
                if index < len(state.actor_states):
                    for dst, msg in handler_sends(
                        index,
                        lambda actor, o: actor.on_msg(
                            action.dst,
                            state.actor_states[index],
                            action.src,
                            action.msg,
                            o,
                        ),
                    ):
                        send_time[(action.dst, dst, msg)] = time
            elif isinstance(action, (Timeout, Crash, Recover, SelectRandom)):
                actor_id = getattr(action, "id", getattr(action, "actor", None))
                (x, y) = plot(int(actor_id), time)
                out.append(
                    f"<circle cx='{x}' cy='{y}' r='10' "
                    "class='svg-event-shape' />"
                )
                index = int(actor_id)
                if isinstance(action, Timeout) and index < len(
                    state.actor_states
                ):
                    for dst, msg in handler_sends(
                        index,
                        lambda actor, o: actor.on_timeout(
                            actor_id, state.actor_states[index], action.timer, o
                        ),
                    ):
                        send_time[(actor_id, dst, msg)] = time
                elif isinstance(action, SelectRandom) and index < len(
                    state.actor_states
                ):
                    for dst, msg in handler_sends(
                        index,
                        lambda actor, o: actor.on_random(
                            actor_id, state.actor_states[index], action.random, o
                        ),
                    ):
                        send_time[(actor_id, dst, msg)] = time

        # Labels last so they draw over the shapes.
        for time, (_state, action) in enumerate(steps):
            time += 1
            if isinstance(action, Deliver):
                (x, y) = plot(int(action.dst), time)
                label = escape(repr(action.msg))
            elif isinstance(action, Timeout):
                (x, y) = plot(int(action.id), time)
                label = escape(f"Timeout({action.timer!r})")
            elif isinstance(action, Crash):
                (x, y) = plot(int(action.id), time)
                label = "Crash"
            elif isinstance(action, Recover):
                (x, y) = plot(int(action.id), time)
                label = "Recover"
            elif isinstance(action, SelectRandom):
                (x, y) = plot(int(action.actor), time)
                label = escape(f"Random({action.random!r})")
            else:
                continue
            out.append(
                f"<text x='{x}' y='{y}' class='svg-event-label'>{label}</text>"
            )
        out.append("</svg>")
        return "".join(out)

    def format_action(self, action) -> str:
        if isinstance(action, Deliver):
            return f"{action.src!r} → {action.msg!r} → {action.dst!r}"
        if isinstance(action, SelectRandom):
            return f"{action.actor!r} select random {action.random!r}"
        return repr(action)

    def format_step(self, last_state, action) -> Optional[str]:
        next_state = self.next_state(last_state, action)
        if next_state is None:
            index = int(getattr(action, "dst", getattr(action, "id", Id(0))))
            if index < len(last_state.actor_states):
                return f"UNCHANGED: {last_state.actor_states[index]!r}"
            return None
        index = int(
            getattr(action, "dst", getattr(action, "id", getattr(action, "actor", Id(0))))
        )
        if isinstance(action, Drop):
            return f"DROP: {action.envelope!r}"
        if index < len(last_state.actor_states):
            return (
                f"NEXT_STATE: {next_state.actor_states[index]!r}\n\n"
                f"PREV_STATE: {last_state.actor_states[index]!r}"
            )
        return None
