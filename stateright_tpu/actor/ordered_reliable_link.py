"""An ordered reliable link (ORL): transparent actor middleware adding
sequence numbers, acks, resends, and duplicate suppression.

Reference: src/actor/ordered_reliable_link.rs — based loosely on the
"perfect link" of Cachin, Guerraoui & Rodrigues, with per source/destination
pair ordering.  Sequencer state persists through ``Storage`` so actors can
restart without re-delivering or re-numbering (the wrapper model-checks
clean under a lossy duplicating network; see tests/test_actor_runtime.py).

Real-network hardening beyond the reference: the retransmit timer backs
off exponentially (``backoff_factor``, capped at ``max_resend_interval``)
instead of hammering a partitioned peer at a fixed interval, and an
optional ``max_resends`` cap bounds how long undeliverable messages are
retried — on expiry the pending messages are dropped and the
``on_give_up`` callback fires (the chaos runtime journals it).  All of
this lives *outside* the model-checked state: the backoff only changes
timer durations (irrelevant when checking, src/actor/model.rs:79-81) and
the cap defaults to off, so the checked transition system is bit-identical
to the reference semantics — pinned by
``tests/test_actor_runtime.py::test_orl_backoff_config_does_not_change_model``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

from .base import (
    Actor,
    CancelTimerCmd,
    ChooseRandomCmd,
    Out,
    SaveCmd,
    SendCmd,
    SetTimerCmd,
    is_no_op,
)
from .ids import Id


@dataclass(frozen=True)
class Deliver:
    """MsgWrapper::Deliver(seq, msg) (reference:41-44)."""

    seq: int
    msg: Any


@dataclass(frozen=True)
class Ack:
    seq: int


NETWORK_TIMER = "ORL-Network"  # TimerWrapper::Network


@dataclass(frozen=True)
class UserTimer:
    timer: Any


@dataclass(frozen=True)
class LinkState:
    """StateWrapper (reference:49-61); maps as sorted tuples for stable
    hashing/fingerprinting."""

    next_send_seq: int
    msgs_pending_ack: Tuple[Tuple[int, Tuple[Id, Any]], ...]
    last_delivered_seqs: Tuple[Tuple[Id, int], ...]
    wrapped_state: Any
    wrapped_storage: Any


@dataclass(frozen=True)
class LinkStorage:
    """StorageWrapper (reference:69-80)."""

    next_send_seq: int
    msgs_pending_ack: Tuple[Tuple[int, Tuple[Id, Any]], ...]
    last_delivered_seqs: Tuple[Tuple[Id, int], ...]
    wrapped_storage: Any


class ActorWrapper(Actor):
    """Wraps an actor to (1) maintain message order, (2) resend lost
    messages, (3) avoid redelivery.  Reference:27-222."""

    def __init__(
        self,
        wrapped_actor: Actor,
        resend_interval=(1.0, 2.0),
        backoff_factor: float = 1.0,
        max_resend_interval: float = 30.0,
        max_resends: Optional[int] = None,
        on_give_up: Optional[Callable[[Id, Tuple], None]] = None,
        metrics=None,
    ):
        self.wrapped_actor = wrapped_actor
        self.resend_interval = tuple(resend_interval)
        self.backoff_factor = float(backoff_factor)
        self.max_resend_interval = float(max_resend_interval)
        # Runtime-only observability: an optional ``MetricsRegistry``
        # (obs/metrics.py) fed ack/retransmit/give-up counters.  Like
        # ``max_resends`` this is a deployment knob — leave it ``None``
        # on a wrapper that is model checked (the counters are harmless
        # but meaningless across explored branches).
        self.metrics = metrics
        # Runtime-only knobs.  ``max_resends`` must stay ``None`` for a
        # wrapper that is model checked: the give-up decision reads the
        # mutable attempt counter below, which is shared across explored
        # branches (the counter is otherwise harmless during checking —
        # it only scales timer durations, which the model ignores).
        self.max_resends = max_resends
        self.on_give_up = on_give_up
        # Runtime-only counters: the backoff ladder position (reset when
        # everything pending is acked) and per-sequence-number resend
        # counts, so giving up on one undeliverable message never drops a
        # freshly-sent deliverable one to a different destination.
        self._resend_attempts = 0
        self._attempts_by_seq: dict = {}

    @staticmethod
    def with_default_timeout(wrapped_actor: Actor) -> "ActorWrapper":
        return ActorWrapper(wrapped_actor)

    def _next_resend_interval(self) -> Tuple[float, float]:
        """Current (lo, hi) retransmit delay: base interval scaled by
        ``backoff_factor ** attempts``, capped at ``max_resend_interval``.

        The exponent is clamped: the attempt counter grows without bound
        on a long-partitioned peer (and during model checking), and a
        naked ``2.0 ** 1025`` raises OverflowError — which would kill the
        actor thread mid-``on_timeout``.  Past the clamp every sane
        factor has long saturated the cap anyway.
        """
        lo, hi = self.resend_interval
        cap = self.max_resend_interval
        try:
            scale = self.backoff_factor ** min(self._resend_attempts, 64)
        except OverflowError:
            return (cap, cap)
        if scale == float("inf"):
            return (cap, cap)  # avoids 0.0 * inf = nan for a zero base
        return (min(lo * scale, cap), min(hi * scale, cap))

    def name(self) -> str:
        return self.wrapped_actor.name()

    # --- handlers ------------------------------------------------------------

    def on_start(self, id: Id, storage: Optional[LinkStorage], o: Out):
        o.set_timer(NETWORK_TIMER, self.resend_interval)
        wrapped_out = Out()
        if storage is not None:
            next_send_seq = storage.next_send_seq
            pending = storage.msgs_pending_ack
            last_seqs = storage.last_delivered_seqs
            wrapped_storage = storage.wrapped_storage
        else:
            next_send_seq, pending, last_seqs, wrapped_storage = 1, (), (), None
        wrapped_state = self.wrapped_actor.on_start(id, wrapped_storage, wrapped_out)
        state = LinkState(
            next_send_seq, pending, last_seqs, wrapped_state, wrapped_storage
        )
        return self._process_output(state, wrapped_out, o)

    def on_msg(self, id: Id, state: LinkState, src: Id, msg: Any, o: Out):
        if isinstance(msg, Deliver):
            # Always ack to stop resends; drop if already delivered
            # (reference:142-151).
            o.send(src, Ack(msg.seq))
            last = dict(state.last_delivered_seqs).get(src, 0)
            if msg.seq <= last:
                return None

            wrapped_out = Out()
            next_wrapped = self.wrapped_actor.on_msg(
                id, state.wrapped_state, src, msg.msg, wrapped_out
            )
            if is_no_op(next_wrapped, wrapped_out):
                return None

            last_seqs = dict(state.last_delivered_seqs)
            last_seqs[src] = msg.seq
            state = LinkState(
                state.next_send_seq,
                state.msgs_pending_ack,
                tuple(sorted(last_seqs.items())),
                next_wrapped if next_wrapped is not None else state.wrapped_state,
                state.wrapped_storage,
            )
            state = self._process_output(state, wrapped_out, o)
        elif isinstance(msg, Ack):
            if self.metrics is not None:
                self.metrics.inc("orl_acks_total")
            pending = tuple(
                (seq, dm) for seq, dm in state.msgs_pending_ack if seq != msg.seq
            )
            self._attempts_by_seq.pop(msg.seq, None)
            if not pending:
                # Progress: the peer is reachable again; restart the
                # backoff ladder from the base interval.
                self._resend_attempts = 0
            state = LinkState(
                state.next_send_seq,
                pending,
                state.last_delivered_seqs,
                state.wrapped_state,
                state.wrapped_storage,
            )
        else:
            return None
        # Non-volatile fields changed: persist (reference:182-189).
        o.save(
            LinkStorage(
                state.next_send_seq,
                state.msgs_pending_ack,
                state.last_delivered_seqs,
                state.wrapped_storage,
            )
        )
        return state

    def on_timeout(self, id: Id, state: LinkState, timer: Any, o: Out):
        if timer == NETWORK_TIMER:
            if not state.msgs_pending_ack:
                self._resend_attempts = 0
                o.set_timer(NETWORK_TIMER, self.resend_interval)
                return None
            if self.max_resends is None:
                # Reference behavior: re-arm (with backoff) and resend
                # everything pending, forever (reference:199-205).
                self._resend_attempts += 1
                if self.metrics is not None:
                    self.metrics.inc(
                        "orl_retransmits_total", len(state.msgs_pending_ack)
                    )
                o.set_timer(NETWORK_TIMER, self._next_resend_interval())
                for seq, (dst, msg) in state.msgs_pending_ack:
                    o.send(dst, Deliver(seq, msg))
                return None
            # Capped mode: each message carries its own resend budget —
            # giving up on a message the network has refused max_resends
            # times must not drop a freshly-sent one to a healthy peer.
            # The give-up is surfaced through the callback so the drop is
            # journal-visible, never silent.
            self._resend_attempts += 1
            kept, dropped = [], []
            for seq, (dst, msg) in state.msgs_pending_ack:
                n = self._attempts_by_seq.get(seq, 0) + 1
                if n > self.max_resends:
                    self._attempts_by_seq.pop(seq, None)
                    dropped.append((seq, (dst, msg)))
                else:
                    self._attempts_by_seq[seq] = n
                    kept.append((seq, (dst, msg)))
                    o.send(dst, Deliver(seq, msg))
            if not kept:
                self._resend_attempts = 0
            if self.metrics is not None and kept:
                self.metrics.inc("orl_retransmits_total", len(kept))
            o.set_timer(NETWORK_TIMER, self._next_resend_interval())
            if not dropped:
                return None
            if self.metrics is not None:
                self.metrics.inc("orl_give_ups_total")
                self.metrics.inc("orl_msgs_dropped_total", len(dropped))
            if self.on_give_up is not None:
                self.on_give_up(id, tuple(dropped))
            state = LinkState(
                state.next_send_seq,
                tuple(kept),
                state.last_delivered_seqs,
                state.wrapped_state,
                state.wrapped_storage,
            )
            o.save(
                LinkStorage(
                    state.next_send_seq,
                    state.msgs_pending_ack,
                    state.last_delivered_seqs,
                    state.wrapped_storage,
                )
            )
            return state
        if isinstance(timer, UserTimer):
            wrapped_out = Out()
            next_wrapped = self.wrapped_actor.on_timeout(
                id, state.wrapped_state, timer.timer, wrapped_out
            )
            if is_no_op(next_wrapped, wrapped_out):
                return None
            if next_wrapped is not None:
                state = LinkState(
                    state.next_send_seq,
                    state.msgs_pending_ack,
                    state.last_delivered_seqs,
                    next_wrapped,
                    state.wrapped_storage,
                )
            return self._process_output(state, wrapped_out, o)
        return None

    # --- plumbing (reference: process_output, :224-269) ----------------------

    def _process_output(self, state: LinkState, wrapped_out: Out, o: Out):
        next_send_seq = state.next_send_seq
        pending = dict(state.msgs_pending_ack)
        wrapped_storage = state.wrapped_storage
        should_save = False
        for c in wrapped_out:
            if isinstance(c, CancelTimerCmd):
                o.cancel_timer(UserTimer(c.timer))
            elif isinstance(c, SetTimerCmd):
                o.set_timer(UserTimer(c.timer), c.duration)
            elif isinstance(c, SendCmd):
                o.send(c.dst, Deliver(next_send_seq, c.msg))
                pending[next_send_seq] = (c.dst, c.msg)
                next_send_seq += 1
                should_save = True
            elif isinstance(c, ChooseRandomCmd):
                raise NotImplementedError(
                    "ChooseRandom is not supported by the ORL wrapper"
                )
            elif isinstance(c, SaveCmd):
                should_save = True
                wrapped_storage = c.storage
        state = LinkState(
            next_send_seq,
            tuple(sorted(pending.items())),
            state.last_delivered_seqs,
            state.wrapped_state,
            wrapped_storage,
        )
        if should_save:
            o.save(
                LinkStorage(
                    state.next_send_seq,
                    state.msgs_pending_ack,
                    state.last_delivered_seqs,
                    state.wrapped_storage,
                )
            )
        return state
