"""Register test harness: a message interface for register-like actors plus
a scripted client, and hooks wiring Get/Put traffic into a consistency
tester's history.

Reference: src/actor/register.rs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..semantics.register import ReadOk, ReadOp, WriteOk, WriteOp, READ, WRITE_OK
from .base import Actor, Out
from .ids import Id


# --- the message protocol (reference: RegisterMsg, src/actor/register.rs:17-30)


@dataclass(frozen=True)
class Internal:
    """Wraps a message specific to the register system's internal protocol."""

    msg: Any


@dataclass(frozen=True)
class Put:
    request_id: int
    value: Any


@dataclass(frozen=True)
class Get:
    request_id: int


@dataclass(frozen=True)
class PutOk:
    request_id: int


@dataclass(frozen=True)
class GetOk:
    request_id: int
    value: Any


def record_invocations(_cfg, history, env) -> Optional[Any]:
    """Pass to ``ActorModel.record_msg_out``: records ``ReadOp`` upon ``Get``
    and ``WriteOp`` upon ``Put``.  Reference: src/actor/register.rs:38-60."""
    if isinstance(env.msg, Get):
        h = history.clone()
        try:
            h.on_invoke(env.src, READ)
        except ValueError:
            pass  # invalid histories poison the tester, matching reference
        return h
    if isinstance(env.msg, Put):
        h = history.clone()
        try:
            h.on_invoke(env.src, WriteOp(env.msg.value))
        except ValueError:
            pass
        return h
    return None


def record_returns(_cfg, history, env) -> Optional[Any]:
    """Pass to ``ActorModel.record_msg_in``: records ``ReadOk`` upon
    ``GetOk`` and ``WriteOk`` upon ``PutOk``.
    Reference: src/actor/register.rs:66-90."""
    if isinstance(env.msg, GetOk):
        h = history.clone()
        try:
            h.on_return(env.dst, ReadOk(env.msg.value))
        except ValueError:
            pass
        return h
    if isinstance(env.msg, PutOk):
        h = history.clone()
        try:
            h.on_return(env.dst, WRITE_OK)
        except ValueError:
            pass
        return h
    return None


# --- actors (reference: RegisterActor, src/actor/register.rs:93-277) --------


@dataclass(frozen=True)
class ClientState:
    awaiting: Optional[int]
    op_count: int


@dataclass(frozen=True)
class ServerState:
    state: Any


class RegisterClient(Actor):
    """A scripted client: ``put_count`` Puts (round-robining servers) then a
    final Get.  Servers must precede clients in the actor list so server ids
    are ``0..server_count``."""

    def __init__(self, put_count: int, server_count: int):
        self.put_count = put_count
        self.server_count = server_count

    def name(self) -> str:
        return "Client"

    def on_start(self, id: Id, storage, o: Out):
        index = int(id)
        if index < self.server_count:
            raise RuntimeError(
                "RegisterClient actors must be added to the model after servers."
            )
        if self.put_count == 0:
            return ClientState(awaiting=None, op_count=0)
        unique_request_id = 1 * index  # next will be 2 * index
        value = chr(ord("A") + (index - self.server_count))
        o.send(Id(index % self.server_count), Put(unique_request_id, value))
        return ClientState(awaiting=unique_request_id, op_count=1)

    def on_msg(self, id: Id, state, src: Id, msg, o: Out):
        if not isinstance(state, ClientState) or state.awaiting is None:
            return None
        index = int(id)
        if isinstance(msg, PutOk) and msg.request_id == state.awaiting:
            unique_request_id = (state.op_count + 1) * index
            if state.op_count < self.put_count:
                value = chr(ord("Z") - (index - self.server_count))
                o.send(
                    Id((index + state.op_count) % self.server_count),
                    Put(unique_request_id, value),
                )
            else:
                o.send(
                    Id((index + state.op_count) % self.server_count),
                    Get(unique_request_id),
                )
            return ClientState(awaiting=unique_request_id, op_count=state.op_count + 1)
        if isinstance(msg, GetOk) and msg.request_id == state.awaiting:
            return ClientState(awaiting=None, op_count=state.op_count + 1)
        return None


class RegisterServer(Actor):
    """Wraps a server actor under test (the reference's
    ``RegisterActor::Server``); delegates every event."""

    def __init__(self, server_actor: Actor):
        self.server_actor = server_actor

    def name(self) -> str:
        return self.server_actor.name() or "Server"

    def on_start(self, id, storage, o: Out):
        return self.server_actor.on_start(id, storage, o)

    def on_msg(self, id, state, src, msg, o: Out):
        return self.server_actor.on_msg(id, state, src, msg, o)

    def on_timeout(self, id, state, timer, o: Out):
        return self.server_actor.on_timeout(id, state, timer, o)

    def on_random(self, id, state, random, o: Out):
        return self.server_actor.on_random(id, state, random, o)
