"""Multi-chip wavefront checking: frontier + visited set sharded over a mesh.

The reference scales with OS threads sharing one DashMap and a job market
(src/job_market.rs, SURVEY §2.7).  The TPU-native analog shards *both* the
frontier and the fingerprint table across chips by fingerprint ownership:

- every fingerprint has one owner shard (a second hash of the fp modulo the
  mesh size), so a local insert on the owner IS the global dedup — no
  cross-chip locking, the moral equivalent of DashMap's hash-sharded locks;
- each wave, every chip expands its local frontier, buckets the successor
  candidates by owner, and exchanges them with a single ``all_to_all`` over
  ICI — the collective replacement for the job market's split_and_push;
- termination and counts are ``psum`` reductions: the frontier is globally
  empty exactly when every shard's insert produced nothing new.

Parent links cross shards, so table entries store a *global id*
(shard << slot_bits | slot); the host walks these across the stacked
per-shard tables for path reconstruction.

Hash-random ownership keeps shards statistically balanced (the job-market
rebalancing analog); skew shows up only as idle lanes in a chunked wave.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from ..core.checker import Checker
from ..core.model import Expectation
from ..core.path import Path
from .compiled import CompiledModel, compiled_model_for

NO_GID = 0xFFFFFFFF

# One u32 stats vector per shard carries every host-visible scalar (the
# single-chip engine's STAT_* pattern, wavefront.py): tunneled readbacks
# cost ~100-170ms EACH, so per-call scalars travel in one transfer.
(
    S_LEVEL_START,
    S_LEVEL_END,
    S_TAIL,
    S_SC_LO,
    S_SC_HI,
    S_UNIQUE_G,
    S_UNIQUE_L,
    S_CAND_LO,
    S_CAND_HI,
    S_DEPTH,
    S_FLAGS,
    S_WAVES_LEFT,
) = range(12)
S_DISC = 12  # disc[P] rides at [S_DISC : S_DISC + n_props]

# Compiled shard_map programs shared across checker instances, exactly like
# the single-chip engine's cache (wavefront.py): without it every
# spawn_tpu_sharded() pays tens of seconds of re-trace + re-lower +
# program load even when XLA's persistent cache already has the binary —
# profiling the 1-device-mesh smoke on hardware showed the "run" was
# almost entirely this host-side work.
_PROGRAM_CACHE: dict = {}
_PROGRAM_CACHE_MAX = 16


def _shard_map(f, mesh, in_specs, out_specs):
    """Version-portable shard_map: ``jax.shard_map`` where it exists
    (newer jax), else ``jax.experimental.shard_map`` (0.4.x) with
    replication checking off — the 0.4.x checker predates the
    varying-type system this engine's seed program is written against."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )
    from jax.experimental.shard_map import shard_map as sm

    return sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def _pcast_varying(x):
    """Mark a shard-invariant value varying (``jax.lax.pcast``) on jax
    versions with the varying-manual-axes type system; identity on 0.4.x,
    which has no such typing."""
    import jax

    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, "shards", to="varying")
    return x


def _owner_mix(hi, lo):
    import jax.numpy as jnp

    from ..ops.device_fp import _fmix32, _rotl

    # Independent of both the key planes and the slot hash.
    return _fmix32(lo ^ _rotl(hi, 7) ^ jnp.uint32(0xA511E9B3))


def _owner_mix_host(hi: int, lo: int) -> int:
    """Bit-identical host evaluation of :func:`_owner_mix` (one int at a
    time), so seeding needs no device round trip to place init states —
    pinned against the device mix by
    tests/test_tpu_sharded.py::test_owner_mix_host_matches_device."""
    from ..ops.fingerprint import _fmix32

    M = 0xFFFFFFFF
    return _fmix32((lo ^ (((hi << 7) | (hi >> 25)) & M) ^ 0xA511E9B3) & M)


def _owner_mix_host_np(hi, lo):
    """Vectorized host evaluation of :func:`_owner_mix` over uint32
    numpy arrays — the bulk re-owner for resharding (every logged row
    re-routed by fingerprint) and for tiered-sharded seeding.  Pinned
    bit-identical to the scalar host mix (and therefore to the device
    mix) by tests/test_tiered_sharded.py."""
    hi = np.asarray(hi, np.uint32)
    lo = np.asarray(lo, np.uint32)
    rot = (hi << np.uint32(7)) | (hi >> np.uint32(25))
    h = lo ^ rot ^ np.uint32(0xA511E9B3)
    h ^= h >> np.uint32(16)
    h = h * np.uint32(0x85EBCA6B)
    h ^= h >> np.uint32(13)
    h = h * np.uint32(0xC2B2AE35)
    h ^= h >> np.uint32(16)
    return h


class ShardedTpuChecker(Checker):
    """Wavefront checker running one program per mesh device via shard_map."""

    def __init__(
        self,
        options,
        mesh=None,
        capacity: int = 1 << 20,
        chunk_size: int = 1 << 11,
        dedup_factor: int = 4,
        compiled: Optional[CompiledModel] = None,
        resume_from: Optional[str] = None,
        journal=None,
        checkpoint_path: Optional[str] = None,
        checkpoint_every_waves: Optional[int] = None,
        checkpoint_every_sec: Optional[float] = None,
        trace: bool = False,
        bucket_slack: Optional[int] = None,
        sort_lanes: Optional[int] = None,
        sortless: Optional[bool] = None,
        step_lanes: Optional[int] = None,
        waves_per_call: Optional[int] = None,
    ):
        """Same checkpoint/journal hooks as the single-chip engine
        (wavefront.py): ``journal`` streams wave-level telemetry as JSON
        lines, ``checkpoint_path`` + a cadence knob write periodic
        atomic mid-run snapshots, and ``resume_from`` continues a saved
        run.  A sharded snapshot is bound to the MESH SIZE (global ids
        encode the owner shard), but adopts the snapshot's per-shard
        capacity and chunk geometry as data.

        ``trace``: run the wave loop in phase-timed segments (step /
        canon+fp / dedup-sort+probe / exchange / append / host
        readback), one host sync per wave, with roofline byte accounting
        per phase AND the exchange instrumented live — measured payload
        bytes and lane occupancy PER WAVE in the journal (the fused
        loop only totals them at run end).  Same kernels and commit
        order as the fused loop; throughput is not comparable (per-wave
        dispatch+sync).  ``trace=False`` leaves the fused single-program
        path byte-for-byte unchanged.  Traced runs do not support
        ``resume_from``; docs/OBSERVABILITY.md states the contract.

        ``bucket_slack``: per-destination exchange bucket width, in
        PERCENT of the even share ``u_sz/n`` (wave_loop.py's
        ``exchange_bucket_lanes``; default 50).  The all_to_all ships
        ``[n, bucket, W+3]`` per shard instead of the former fixed
        ``[n, u_sz, W+3]`` — ~n× less transmitted per wave at the
        measured occupancies (docs/SHARDED_SCALING.md).  A wave whose
        candidates overflow any destination bucket commits NOTHING,
        raises flag 32, and the host retries the same chunk at the next
        rung (slack ×2) — the engine's standard overflow-flag + retry
        contract.  Warm starts pass the discovered rung back in (the
        knob cache persists it) and skip the ramp.

        ``sort_lanes``: the adaptive sort-geometry rung (wavefront.py's
        knob, shared ladder in wave_loop.py): a power-of-two width for
        the per-shard pre-exchange compact/dedup-sort buffers — the
        owner-bucketing argsort, the exchange buckets
        (``exchange_bucket_lanes`` is slack% of the RUNG's even share),
        and the post-exchange insert all shrink with it.  None starts at
        the full worst-case ``U`` and lets the density tuner downshift;
        a wave whose valid candidates exceed the rung raises the
        non-committing flag 4 and the host retries one rung up.  The
        discovered rung rides the knob cache and snapshots exactly like
        ``bucket_slack``.

        ``sortless``: the dedup-path selection (wavefront.py documents
        the contract; default = the claim-plane election unless an
        explicit ``sort_lanes`` selects the sorted fallback).  On this
        engine the election replaces the OWNER-SIDE insert's pre-dedup
        sort; the local pre-exchange ``prededup`` sort survives on
        meshes wider than one shard — the exchange ships only distinct
        keys, and electing without a table to claim into would need a
        scratch table per wave — but is skipped entirely on 1-shard
        meshes, where the claim insert IS the global dedup.

        ``step_lanes``: the frontier-sized chunk rung (wavefront.py's
        knob, shared ladder in wave_loop.py) — the per-wave chunk slice,
        candidate batch, compact buffers, and the exchange buckets
        derived from them all span the rung instead of the worst-case
        ``chunk_size`` width.  A shard whose remaining level exceeds
        the rung raises the non-committing flag 128; the host climbs
        one rung and re-runs."""
        super().__init__(options.model)
        import jax

        if options._visitor is not None:
            raise ValueError("spawn_tpu_sharded() does not support visitors")
        self._trace = bool(trace)
        if self._trace and resume_from is not None:
            raise ValueError(
                "spawn_tpu_sharded(trace=True) does not support "
                "resume_from: tracing is a diagnostic mode; resume "
                "untraced and trace a fresh (bounded) run instead"
            )
        self._options = options
        self._compiled = compiled or compiled_model_for(options.model)
        # Symmetry: dedup — and therefore OWNER ROUTING — keys on the
        # canonical row's fingerprint, so every member of an orbit lands
        # on one shard and the owner's local insert stays the global
        # dedup; stores keep the original rows (wavefront.py's policy,
        # docs/SYMMETRY.md).  Missing canon capability raises loudly,
        # like the single-chip engine.
        from .canon import make_canon

        self._canon = (
            make_canon(self._compiled)
            if options._symmetry is not None
            else None
        )
        if options._symmetry is not None and self._canon is None:
            raise ValueError(
                "spawn_tpu_sharded() with symmetry() requires the "
                "compiled model to declare a canonicalization, but "
                f"{type(self._compiled).__name__} defines neither "
                "canon_spec() nor canon_rows (parallel/canon.py); use "
                "spawn_dfs() for host-side symmetry"
            )
        if mesh is None:
            mesh = jax.sharding.Mesh(np.array(jax.devices()), ("shards",))
        self._mesh = mesh
        self._n = mesh.devices.size
        # Per-shard capacity: the largest power of two fitting the budget
        # (open addressing needs a power of two; the mesh size need not be).
        self._cap_s = 1 << max(capacity // self._n, 1 << 10).bit_length() - 1
        self._slot_bits = self._cap_s.bit_length() - 1
        # Global ids are shard << slot_bits | slot in one uint32; strict
        # < 32 keeps the all-ones NO_GID sentinel unreachable and the shift
        # from wrapping (shard bits must cover shard n-1, so ceil(log2 n)).
        if self._slot_bits + max(self._n - 1, 1).bit_length() >= 32:
            raise ValueError("capacity too large for 32-bit global ids")
        # Same spawn-time crash-band guard as the single-chip engine
        # (wavefront.max_safe_unique_lanes): buffers past the validated
        # band hard-crash the TPU worker mid-wave, and this engine has no
        # auto-tune retry to recover — clamp the chunk here, loudly.
        # The binding buffer is the POST-EXCHANGE insert over n*u_sz
        # receive lanes (each shard receives one u_sz bucket from every
        # peer), so the per-shard u_sz is bounded at cap/n; the payload
        # rides w+3 words per lane, which the width-dependent cap uses.
        from .hashset import unique_buffer_size
        from .wavefront import max_safe_unique_lanes

        a = self._compiled.max_actions
        u_cap = max_safe_unique_lanes(self._compiled.state_width + 3)
        clamped = False
        while (
            chunk_size > 2048
            and self._n * unique_buffer_size(chunk_size * a, dedup_factor)
            > u_cap
        ):
            chunk_size //= 2
            clamped = True
        if self._n * unique_buffer_size(chunk_size * a, dedup_factor) > u_cap:
            raise ValueError(
                f"chunk geometry (chunk_size={chunk_size}, max_actions="
                f"{a}, dedup_factor={dedup_factor}) exceeds the device-"
                "safe compact-buffer band even at the floor chunk; raise "
                "dedup_factor or use a narrower model"
            )
        if clamped:
            import logging

            logging.getLogger(__name__).warning(
                "spawn_tpu_sharded: chunk_size clamped to %d "
                "(max_actions=%d, dedup_factor=%d): requested geometry "
                "exceeds the device-safe compact-buffer band",
                chunk_size, a, dedup_factor,
            )
        self._chunk = chunk_size
        self._dedup_factor = dedup_factor
        from .wave_loop import BUCKET_SLACK_DEFAULT

        self._bucket_slack = (
            BUCKET_SLACK_DEFAULT if bucket_slack is None
            else int(bucket_slack)
        )
        if self._bucket_slack < 1:
            raise ValueError("bucket_slack must be a positive percentage")
        self._bucket_retries = 0  # overflow-retry rungs climbed this run
        # Adaptive sort-geometry rung (wave_loop.py's ladder; the
        # single-chip engine's knob, wavefront.py documents the
        # contract).  None = full worst-case buffer until the density
        # tuner has evidence; an explicit rung is a warm start.
        from .wave_loop import (
            SORT_RUNG_MIN, STEP_RUNG_MIN, clamp_sort_lanes,
            clamp_step_lanes,
        )

        self._sort_lanes = (
            None if sort_lanes is None else clamp_sort_lanes(sort_lanes)
        )
        # Explicit rung = warm start: the density tuner stands down
        # (the single-chip rule, wavefront.py).
        self._sort_tune = sort_lanes is None
        self._sort_rung_floor = SORT_RUNG_MIN
        self._sort_peak_valid = 0.0
        self._sort_quanta = 0
        self._sort_retries = 0  # flag-4 rung climbs this run
        # Dedup-path selection + the frontier-sized step rung
        # (wavefront.py's knobs; one shared ladder in wave_loop.py).
        self._sortless = (
            (sort_lanes is None) if sortless is None else bool(sortless)
        )
        self._step_lanes = (
            None if step_lanes is None else clamp_step_lanes(step_lanes)
        )
        self._step_tune = step_lanes is None
        self._step_rung_floor = STEP_RUNG_MIN
        self._step_peak_frontier = 0.0
        self._step_quanta = 0
        self._step_retries = 0  # flag-128 rung climbs this run
        if waves_per_call is None:
            from .wave_common import default_waves_per_call

            waves_per_call = default_waves_per_call(options)
        elif int(waves_per_call) < 1:
            # waves_per_call=0 would seed every run() call with an
            # exhausted budget: the device loop returns immediately with
            # no progress and the host loop spins forever.
            raise ValueError("waves_per_call must be >= 1")
        self._waves_per_call = int(waves_per_call)
        self._properties = self._model.properties()
        self._ev_indices = [
            i
            for i, p in enumerate(self._properties)
            if p.expectation is Expectation.EVENTUALLY
        ]
        self._discovery_gids: Dict[str, int] = {}
        self._state_count = 0
        self._unique_count = 0
        self._max_depth = 0
        self._done = threading.Event()
        self._errors: List[BaseException] = []
        self._lock = threading.Lock()
        self._tables_host: Optional[tuple] = None
        self._tables_dev: Optional[tuple] = None
        self._discoveries_cache: Optional[Dict[str, Path]] = None
        self._accounting: dict = {}
        self._resume_from = resume_from
        from ..obs.metrics import MetricsRegistry

        self._metrics = MetricsRegistry()
        self._tracer = None  # built by the traced host loop
        from ..runtime.journal import as_journal

        self._journal = as_journal(journal)
        self._checkpoint_path = checkpoint_path
        self._ckpt_every_waves = checkpoint_every_waves
        self._ckpt_every_sec = checkpoint_every_sec
        if (
            checkpoint_path is not None
            and checkpoint_every_waves is None
            and checkpoint_every_sec is None
        ):
            self._ckpt_every_sec = 30.0
        self._carry_dev: Optional[dict] = None  # full run state at stop

        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # --- exchange geometry ---------------------------------------------------

    def _step_width(self) -> int:
        """The EFFECTIVE per-wave chunk width in frontier lanes
        (wavefront.py's `_step_width`, same contract): the step rung
        capped at the live ``chunk_size``."""
        full = self._chunk
        if self._step_lanes is None:
            return full
        return min(self._step_lanes, full)

    def _u_sz(self) -> int:
        """Current compaction/dedup buffer width (hashset.py's single
        definition), from the LIVE chunk/dedup knobs — auto-grow and
        the step rung may have moved them mid-run."""
        from .hashset import unique_buffer_size

        return unique_buffer_size(
            self._step_width() * self._compiled.max_actions,
            self._dedup_factor,
        )

    def _sort_width(self) -> int:
        """The EFFECTIVE pre-exchange compact/sort buffer width: the
        sort-geometry rung capped at the live worst-case ``U``
        (wavefront.py's `_sort_width`, same contract).  Everything
        downstream — the owner argsort, the exchange buckets, the
        post-exchange insert — derives its shape from this number."""
        full = self._u_sz()
        if self._sort_lanes is None:
            return full
        return min(self._sort_lanes, full)

    def _bucket_lanes(self) -> int:
        """Per-destination exchange bucket width at the CURRENT slack
        rung — the one source of truth (wave_loop.exchange_bucket_lanes)
        shared by the device programs, the traced byte model, and
        ``accounting()``, so reported payload geometry can never drift
        from what the device transmits.  Sized from the SORT width (the
        buffer the exchange actually buckets), so the dedup rung shrinks
        transmitted bytes too; the cap at the full sort buffer keeps the
        top slack rung overflow-free by construction (a shard never has
        more candidates than its sort buffer holds)."""
        from .wave_loop import exchange_bucket_lanes

        return exchange_bucket_lanes(
            self._sort_width(), self._n, self._bucket_slack
        )

    # --- device program ------------------------------------------------------

    def _build_run(self):
        """Fused multi-chunk program, the sharded analog of the single-chip
        engine: each shard drains a FIFO slot queue of its own states with
        *global* BFS-level boundaries (depth advances only when a psum says
        every shard finished the level), exchanging successor candidates
        over ICI each chunk.  The whole loop runs inside one shard_map'd
        ``while_loop`` — the host syncs once per ``waves`` chunks instead
        of once per chunk per wave (on tunneled or DCN-attached hosts a
        single scalar sync costs ~100ms; the old per-chunk dispatch spent
        most of wall-clock there).

        All loop-control decisions (work-remaining, flags, finish_when,
        depth gating) derive from psum reductions, so every shard takes the
        same branch — a requirement for collectives inside the loop body.

        Exchange-buffer memory: candidates are locally pre-deduped before
        bucketing (hashset.prededup) and then routed into PER-DESTINATION
        BUCKETS, so the all_to_all operates on ``[n, bkt, W+3]`` uint32
        per shard with ``bkt = exchange_bucket_lanes(U, n, bucket_slack)``
        (≈ ``U/n · slack``, wave_loop.py) and
        ``U = max(min(chunk*max_actions, 16K), chunk*max_actions /
        dedup_factor)`` — transmitted bytes per wave scale with the real
        per-destination share instead of the full ``U`` buffer (the n²
        wall docs/SHARDED_SCALING.md measured).  A destination bucket
        overflow raises flag 32 and the wave commits nothing; the host
        retries the chunk at the next slack rung.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..ops.device_fp import device_fp64
        from .hashset import (
            HashSet, compact_valid, insert_batch_claim,
            insert_batch_compact, prededup,
        )
        from .wave_common import make_finish_when_device, wave_eval

        cm = self._compiled
        w = cm.state_width
        fpw = cm.fp_words or w  # identity = leading words (compiled.py)
        # Symmetry: fingerprints (dedup keys AND shard owners) come from
        # the canonical row; stores/queues/exchange payloads carry the
        # ORIGINAL rows (wavefront.py's policy).
        canon = self._canon

        def fp_of(rows):
            rows_c = rows if canon is None else jax.vmap(canon)(rows)
            return device_fp64(rows_c[:, :fpw])

        a = cm.max_actions
        f = self._chunk  # worst-case chunk (queue/seed geometry)
        # The live step-geometry rung: the per-wave chunk slice; a
        # shard whose remaining level exceeds it raises the
        # non-committing flag 128 (compiled out at the top rung).
        f_eff = self._step_width()
        n = self._n
        cap_s = self._cap_s
        qcap = cap_s
        slot_bits = self._slot_bits
        props = self._properties
        ev_indices = self._ev_indices
        dedup_factor = self._dedup_factor
        # Dedup path (wavefront.py's contract): claim election on the
        # owner-side insert by default; 1-shard meshes additionally
        # skip the local prededup sort (the insert IS the global
        # dedup there).
        sortless = self._sortless
        # The live sort-geometry rung: the pre-exchange compact/dedup
        # buffers below span this width, so the owner argsort, bucket
        # scatters, and exchange payload all follow it.  None = the
        # worst-case buffer of the live batch (sortless default).
        sort_lanes = (
            None if self._sort_lanes is None else self._sort_width()
        )
        b = f_eff * a  # per-shard candidate lanes (pre-compaction)
        # Per-destination exchange bucket (wave_loop.exchange_bucket_lanes
        # via _bucket_lanes — the same number accounting() reports).
        bkt = self._bucket_lanes()
        target_depth = self._options._target_max_depth or 0
        fw_found_matched = make_finish_when_device(
            self._options._finish_when, props
        )
        u = jnp.uint32

        def go_from(level_start, level_end, depth, disc, waves_left, flags):
            work = jax.lax.psum(level_end - level_start, "shards") > u(0)
            found = (
                jax.lax.psum((disc != u(NO_GID)).astype(u), "shards") > u(0)
            )
            go = work & (waves_left > 0) & (flags == u(0))
            go = go & ~fw_found_matched(found)
            if target_depth:
                go = go & (depth < u(target_depth - 1))
            return go

        def body(carry):
            (
                key_hi,
                key_lo,
                store,
                parent,
                ebits,
                queue,
                level_start,
                level_end,
                tail,
                sc_lo,
                sc_hi,
                unique_g,
                unique_l,
                cand_lo,
                cand_hi,
                depth,
                disc,
                waves_left,
                flags,
                _go,
            ) = carry
            me = jax.lax.axis_index("shards").astype(u)

            count = jnp.minimum(level_end - level_start, u(f_eff))
            chunk = jax.lax.dynamic_slice(queue, (level_start,), (f_eff,))
            lane = jnp.arange(f_eff, dtype=u)
            active = lane < count
            safe_slots = jnp.where(active, chunk, 0)
            states = store[safe_slots]

            # Shared expansion-time evaluation; ids are global this time.
            # ``disc_prev`` is kept so a retryable-overflow wave (which
            # must commit NOTHING — the host re-runs the same chunk at
            # grown knobs) can revert its discovery candidates too, the
            # single-chip engine's abort contract.
            my_gids = (me << u(slot_bits)) | safe_slots
            disc_prev = disc
            disc, eb, nexts, valid, gen_local, step_flag = wave_eval(
                cm, props, ev_indices, states, active, my_gids,
                ebits[safe_slots], disc, allow_two_phase=True,
            )
            generated = jax.lax.psum(gen_local, "shards")

            # Local pre-dedup BEFORE the exchange: one stable sort elects a
            # representative per distinct local key, so only distinct keys
            # (U = B/dedup_factor lanes, not B) pay for the owner bucketing
            # scatters, the single packed all_to_all, and the owner-side
            # row scatters.  Candidate batches are ~95% invalid/duplicate
            # lanes; profiling the single-chip engine showed exactly these
            # B-indexed row operations dominating the chunk.
            flat_valid = valid.reshape(b)
            if nexts is None:
                # TWO-PHASE expansion (same contract as the single-chip
                # engine, wavefront.py): compact the valid lane indices
                # first, construct successors via ``step_lane`` only for
                # the survivors, and fingerprint U lanes instead of B.
                from .hashset import compact_valid_indices

                v_orig, v_act, _n_valid, local_overflow = (
                    compact_valid_indices(
                        flat_valid, dedup_factor, sort_lanes=sort_lanes
                    )
                )
                rows_v, _valid_v, lane_flags_v = jax.vmap(cm.step_lane)(
                    states[v_orig // u(a)], v_orig % u(a)
                )
                step_flag = step_flag | jnp.any(lane_flags_v & v_act)
                v_hi, v_lo = fp_of(rows_v)
                if sortless and n == 1:
                    # 1-shard sortless: no exchange to minimize, so the
                    # claim insert below IS the global dedup — skip the
                    # local prededup sort entirely.
                    u_hi, u_lo, u_valid = v_hi, v_lo, v_act
                    rows_u = rows_v
                    orig_lane = v_orig
                else:
                    u_hi, u_lo, u_origin0, u_valid, _never = prededup(
                        v_hi, v_lo, v_act, dedup_factor=1
                    )
                    rows_u = rows_v[u_origin0]
                    orig_lane = v_orig[u_origin0]
            else:
                flat = nexts.reshape(b, w)
                hi, lo = fp_of(flat)
                # Same two-stage shrink as the single-chip engine: compact
                # the sparse valid lanes first (hashset.compact_valid,
                # shared so the overflow criterion cannot drift), then
                # dedup the compacted buffer — the sort and every
                # downstream scatter work on real keys, not the
                # sentinel-padded majority.
                v_hi, v_lo, v_orig, v_act, local_overflow = compact_valid(
                    hi, lo, flat_valid, dedup_factor,
                    sort_lanes=sort_lanes,
                )
                if sortless and n == 1:
                    u_hi, u_lo, u_valid = v_hi, v_lo, v_act
                    orig_lane = v_orig
                    rows_u = flat[orig_lane]
                else:
                    u_hi, u_lo, u_origin0, u_valid, _never = prededup(
                        v_hi, v_lo, v_act, dedup_factor=1
                    )
                    orig_lane = v_orig[u_origin0]
                    rows_u = flat[orig_lane]
            u_sz = u_hi.shape[0]
            gid_u = my_gids[orig_lane // u(a)]
            eb_u = eb[orig_lane // u(a)]

            def any_shard(x):
                return jax.lax.psum(x.astype(u), "shards") > u(0)

            # Retryable overflows are detected BEFORE any state mutation,
            # so an overflowing wave can commit NOTHING: validity is
            # masked off (the insert/store/queue writes become no-ops),
            # counters and ``disc`` revert, and level_start does not
            # advance — the host grows the tripped knob (dedup_factor /
            # bucket_slack) and re-runs the exact same chunk with no
            # work lost and no table rebuild needed.
            g_lovf = any_shard(local_overflow)
            # Step-rung clamp (flag 128, non-committing; compiled out
            # at the top rung): any shard's remaining level exceeding
            # the chunk rung aborts the wave mesh-wide — the host
            # climbs one rung and re-runs.
            g_sovf = (
                any_shard(level_end - level_start > u(f_eff))
                if f_eff < f else jnp.zeros((), jnp.bool_)
            )
            if n == 1:
                # One-shard mesh: every key's owner is self, so the whole
                # bucket/sort/all_to_all exchange is an identity — elide
                # it at trace time and reuse the already-computed keys
                # (this is most of the former 1-device overhead vs the
                # single-chip engine).
                g_bovf = jnp.zeros((), jnp.bool_)
                commit = ~(g_lovf | g_sovf)
                rw, rg, reb = rows_u, gid_u, eb_u
                rv = u_valid & commit
                rhi, rlo = u_hi, u_lo
            else:
                # Bucket the representatives by owner shard; exchange
                # over ICI.
                owner = _owner_mix(u_hi, u_lo) % u(n)
                key = jnp.where(u_valid, owner, u(n))
                order = jnp.argsort(key, stable=True)
                key_s = key[order]
                # Bucket sizes as n+1 dense reductions — NOT a
                # scatter-add: every lane collides into one of n+1 cells,
                # and TPU scatter serializes colliding updates (profiled
                # at seconds per chunk).
                counts = jnp.stack(
                    [jnp.sum((key == u(d)).astype(u)) for d in range(n + 1)]
                )
                # BUCKETED exchange: each destination gets a ``bkt``-lane
                # bucket (a slack-scaled slice of the even share u_sz/n,
                # wave_loop.exchange_bucket_lanes) instead of the full
                # u_sz buffer — transmitted bytes shrink ~n× while the
                # measured occupancies say real candidates fill a few
                # percent of even the slim bucket.  A destination count
                # past the bucket raises flag 32; nothing commits.
                g_bovf = any_shard(jnp.any(counts[:n] > u(bkt)))
                commit = ~(g_lovf | g_bovf | g_sovf)
                offsets = jnp.concatenate(
                    [jnp.zeros((1,), u), jnp.cumsum(counts)[:-1]]
                )
                pos = jnp.arange(u_sz, dtype=u) - offsets[key_s]
                dst = jnp.where(key_s < n, key_s, u(n))  # drop invalid

                # Pack the row + its parent gid, ebits, and validity into
                # one [n, bkt, W+3] buffer so a SINGLE all_to_all (one
                # collective launch per chunk, not four) carries the whole
                # exchange.  Lanes past a bucket's width drop out of the
                # scatter (mode="drop"); on an aborted wave the validity
                # column is zeroed, so receivers insert nothing.
                payload = jnp.concatenate(
                    [
                        rows_u,
                        gid_u[:, None],
                        eb_u[:, None],
                        (u_valid & commit).astype(u)[:, None],
                    ],
                    axis=1,
                )
                send = jnp.zeros((n, bkt, w + 3), u)
                send = send.at[dst, pos].set(payload[order], mode="drop")
                recv = jax.lax.all_to_all(
                    send, "shards", split_axis=0, concat_axis=0, tiled=False
                )

                # Local insert — the owner's insert IS the global dedup;
                # the compact form keeps the store/parent/queue scatters
                # proportional to distinct received keys.
                flatrecv = recv.reshape(n * bkt, w + 3)
                rw = flatrecv[:, :w]
                rg = flatrecv[:, w]
                reb = flatrecv[:, w + 1]
                rv = flatrecv[:, w + 2] != u(0)
                rhi, rlo = fp_of(rw)

            # Commit gating for the global counters (the psums are
            # shard-invariant, so every shard takes the same branch).
            generated = jnp.where(commit, generated, u(0))
            new_lo = sc_lo + generated
            sc_hi = sc_hi + (new_lo < sc_lo).astype(u)
            sc_lo = new_lo
            # Accounting: distinct candidates this shard contributes to
            # the exchange this wave (the all_to_all payload's real
            # occupancy); 64-bit via a lo/hi pair, like the state counter
            # — the one counter proportional to total candidates.
            new_cand_lo = cand_lo + jnp.sum(u_valid & commit, dtype=u)
            cand_hi = cand_hi + (new_cand_lo < cand_lo).astype(u)
            cand_lo = new_cand_lo
            disc = jnp.where(commit, disc, disc_prev)
            count = jnp.where(commit, count, u(0))
            # dedup_factor=1: the receive batch is already per-sender
            # deduped, so its distinct-key count can approach the full
            # batch (disjoint keys per shard) — a divided buffer here
            # would spuriously overflow on waves the old code handled.
            # dd_overflow is structurally False here (dedup_factor=1
            # gives the insert a buffer covering its whole receive
            # batch) but stays wired into the FATAL flag 64 below: if
            # the sizing rule ever changes, dropped received states must
            # be a loud error, never a silently wrong "verified" result
            # (the traced loop keeps the same invariant guard).
            if sortless:
                # Claim-plane election (hashset.insert_batch_claim):
                # the receive batch probes directly, winners are the
                # lowest receive lane of each key run, and r_origin is
                # the identity map — the gathers below elide.
                (
                    table, r_slot, r_new, r_origin, _r_active, probe_ok,
                    dd_overflow,
                ) = insert_batch_claim(
                    HashSet(key_hi, key_lo), rhi, rlo, rv
                )
                rows_r, rg_r, reb_r = rw, rg, reb
            else:
                (
                    table, r_slot, r_new, r_origin, _r_active, probe_ok,
                    dd_overflow,
                ) = insert_batch_compact(
                    HashSet(key_hi, key_lo), rhi, rlo, rv, dedup_factor=1
                )
                rows_r = rw[r_origin]
                rg_r = rg[r_origin]
                reb_r = reb[r_origin]
            sslot = jnp.where(r_new, r_slot, u(cap_s))
            store = store.at[sslot].set(rows_r, mode="drop")
            parent = parent.at[sslot].set(rg_r, mode="drop")
            ebits = ebits.at[sslot].set(reb_r, mode="drop")
            n_new = jnp.sum(r_new, dtype=u)
            unique_l = unique_l + n_new
            unique_g = unique_g + jax.lax.psum(n_new, "shards")

            # Append new slots at this shard's queue tail.  The drop
            # sentinel is the always-out-of-bounds all-ones index, NOT
            # qcap+f: auto-grow may halve the chunk mid-run, and a
            # sentinel derived from the CURRENT f would land in bounds
            # of the larger originally-minted queue buffer.
            qpos = tail + jnp.cumsum(r_new.astype(u)) - 1
            qidx = jnp.where(r_new, qpos, u(0xFFFFFFFF))
            queue = queue.at[qidx].set(r_slot, mode="drop")
            tail = tail + n_new

            # Advance within the level; the boundary is global.
            level_start = level_start + count
            rem_g = jax.lax.psum(level_end - level_start, "shards")
            done_level = rem_g == u(0)
            depth = depth + done_level.astype(u)
            level_end = jnp.where(done_level, tail, level_end)

            flags = flags | jnp.where(any_shard(~probe_ok), 1, 0).astype(u)
            flags = flags | jnp.where(
                any_shard(unique_l * u(2) > u(cap_s)), 1, 0
            ).astype(u)
            flags = flags | jnp.where(any_shard(tail > u(qcap)), 2, 0).astype(u)
            # The insert's own dedup buffer runs at dedup_factor=1 over
            # the receive batch, so its overflow is structurally
            # impossible (the buffer covers the whole batch); flag 4 is
            # exactly the pre-exchange compaction overflow, which the
            # host can retry because the aborted wave committed nothing.
            flags = flags | jnp.where(g_lovf, 4, 0).astype(u)
            flags = flags | jnp.where(g_bovf, 32, 0).astype(u)
            flags = flags | jnp.where(g_sovf, 128, 0).astype(u)
            flags = flags | jnp.where(
                any_shard(dd_overflow), 64, 0
            ).astype(u)
            flags = flags | jnp.where(any_shard(step_flag), 8, 0).astype(u)

            waves_left = waves_left - 1
            go = go_from(level_start, level_end, depth, disc, waves_left, flags)
            return (
                table.key_hi,
                table.key_lo,
                store,
                parent,
                ebits,
                queue,
                level_start,
                level_end,
                tail,
                sc_lo,
                sc_hi,
                unique_g,
                unique_l,
                cand_lo,
                cand_hi,
                depth,
                disc,
                waves_left,
                flags,
                go,
            )

        def cond(carry):
            return carry[-1]

        waves_per_call = self._waves_per_call

        def run_shard(key_hi, key_lo, store, parent, ebits, queue, stats):
            # stats: one [S_DISC + P] u32 vector per shard — every
            # host-visible scalar in ONE readback (wavefront's STAT_*
            # pattern; a tunneled readback costs ~100-170ms EACH).  The
            # waves budget is a program constant, so calls need no
            # per-call upload either.
            carry = (
                key_hi,
                key_lo,
                store,
                parent,
                ebits,
                queue,
                stats[S_LEVEL_START],
                stats[S_LEVEL_END],
                stats[S_TAIL],
                stats[S_SC_LO],
                stats[S_SC_HI],
                stats[S_UNIQUE_G],
                stats[S_UNIQUE_L],
                stats[S_CAND_LO],
                stats[S_CAND_HI],
                stats[S_DEPTH],
                stats[S_DISC:],
                jnp.int32(waves_per_call),
                stats[S_FLAGS],
                jnp.zeros((), jnp.bool_),
            )
            carry = carry[:-1] + (
                go_from(
                    carry[6], carry[7], carry[15], carry[16], carry[17],
                    carry[18],
                ),
            )
            out = jax.lax.while_loop(cond, body, carry)
            stats_out = jnp.concatenate(
                [
                    jnp.stack(
                        [
                            out[6],
                            out[7],
                            out[8],
                            out[9],
                            out[10],
                            out[11],
                            out[12],
                            out[13],
                            out[14],
                            out[15],
                            out[18],
                            out[17].astype(u),
                        ]
                    ),
                    out[16],
                ]
            )
            return (
                out[0],
                out[1],
                out[2],
                out[3],
                out[4],
                out[5],
                stats_out,
            )

        shard = P("shards")
        specs = (shard,) * 7
        run = jax.jit(
            _shard_map(
                run_shard,
                mesh=self._mesh,
                in_specs=specs,
                out_specs=(shard,) * 7,
            ),
            donate_argnums=(0, 1, 2, 3, 4, 5, 6),
        )
        return run

    def _programs(self):
        key = (
            self._compiled.cache_key(),
            # Two-phase capability is a trace-time branch (wave_eval's
            # hasattr gate) — key it, as in wavefront.py:_programs.
            hasattr(self._compiled, "step_valid")
            and hasattr(self._compiled, "step_lane"),
            # Symmetry is a trace-time branch, keyed like the two-phase
            # gate (wavefront.py:_programs).
            self._canon is not None,
            self._cap_s,
            self._chunk,
            self._dedup_factor,
            self._sortless,  # the dedup path is a trace-time branch
            self._sort_width(),  # the live sort-geometry rung
            self._step_width(),  # the live step-geometry rung
            self._bucket_slack,  # shapes the exchange buckets
            self._waves_per_call,  # baked into run() as a constant
            tuple((d.platform, d.id) for d in self._mesh.devices.flat),
            tuple(p.expectation for p in self._properties),
            (
                self._options._finish_when._kind,
                tuple(sorted(self._options._finish_when._names)),
                tuple(p.name for p in self._properties),
            ),
            self._options._target_max_depth or 0,
        )
        from .wave_common import cached_program

        return cached_program(
            _PROGRAM_CACHE, _PROGRAM_CACHE_MAX, key, self._build_run,
            label="ShardedTpuChecker.fused",
            journal=self._journal,
            provenance=self._key_provenance(),
        )

    def _key_provenance(self) -> dict:
        """Human-readable knobs behind the program-cache keys (the
        journaled ``compile`` events' attribution field —
        docs/OBSERVABILITY.md "Compile events")."""
        return {
            "model": type(self._compiled).__name__,
            "shards": self._n,
            "capacity_per_shard": self._cap_s,
            "chunk_size": self._chunk,
            "dedup_factor": self._dedup_factor,
            "sortless": self._sortless,
            "sort_lanes": self._sort_width(),
            "step_lanes": self._step_width(),
            "bucket_slack": self._bucket_slack,
            "waves_per_call": self._waves_per_call,
            "symmetry": self._canon is not None,
        }

    def _seed_program(self, seed_w: int):
        """Init-state seeding program, cached like the run program (the
        trace + lower alone costs seconds per checker otherwise).

        Mints EVERY device buffer internally (table planes, store, parent,
        ebits, queue) and emits the run loop's stats vector, so the whole
        spawn costs one upload (the packed per-shard init rows) + one
        dispatch — on a tunneled device each separate allocation dispatch
        or readback is a ~150 ms round trip, which dominated the 1-device
        overhead smoke.  A seed insert overflow surfaces as flag 16 in
        the stats vector; the run program's go-gate refuses to start on
        nonzero flags, and the host loop raises the seeding error."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..ops.device_fp import device_fp64
        from .hashset import insert_batch

        cm = self._compiled
        cap_s = self._cap_s
        f = self._chunk
        qcap = cap_s
        w = cm.state_width
        fpw = cm.fp_words or w
        canon = self._canon  # table keys are canonical fps (symmetry)
        eb0 = (1 << len(self._ev_indices)) - 1
        n_props = len(self._properties)
        key = (
            "seed",
            cm.cache_key(),
            canon is not None,
            cap_s,
            f,
            seed_w,
            eb0,
            n_props,
            tuple((d.platform, d.id) for d in self._mesh.devices.flat),
        )

        def seed_shard(packed):
            from .hashset import HashSet
            from .wave_common import compact

            u = jnp.uint32

            def pv(x):
                # Buffers minted INSIDE the shard_map body are typed
                # shard-invariant; mark them varying so they can join
                # while_loop carries with the (varying) seeded keys.
                # (Identity on 0.4.x jax, which has no varying typing.)
                return _pcast_varying(x)

            sts = packed[0, :, :w]
            val = packed[0, :, w] != u(0)
            table = HashSet(
                key_hi=pv(jnp.zeros((cap_s,), u)),
                key_lo=pv(jnp.zeros((cap_s,), u)),
            )
            store = pv(jnp.zeros((cap_s, w), u))
            parent = pv(jnp.full((cap_s,), u(NO_GID)))
            ebits_buf = pv(jnp.zeros((cap_s,), u))
            sts_c = sts if canon is None else jax.vmap(canon)(sts)
            hi, lo = device_fp64(sts_c[:, :fpw])
            table, slot, is_new, probe_ok, dd_overflow = insert_batch(
                table, hi, lo, val
            )
            sslot = jnp.where(is_new, slot, u(cap_s))
            store = store.at[sslot].set(sts, mode="drop")
            ebits_buf = ebits_buf.at[sslot].set(u(eb0), mode="drop")
            n_new = jnp.sum(is_new, dtype=u)
            queue = pv(jnp.zeros((qcap + f,), u))
            queue = queue.at[: is_new.shape[0]].set(
                compact(is_new, slot, is_new.shape[0])
            )
            ok = probe_ok & ~dd_overflow
            sc = jax.lax.psum(jnp.sum(val, dtype=u), "shards")
            unique_g = jax.lax.psum(n_new, "shards")
            seed_fail = jax.lax.psum((~ok).astype(u), "shards")
            zero = pv(jnp.zeros((), u))
            stats = jnp.concatenate(
                [
                    jnp.stack([
                        zero,  # level_start
                        n_new,  # level_end
                        n_new,  # tail
                        sc,  # sc_lo
                        zero,  # sc_hi
                        unique_g,
                        n_new,  # unique_l
                        zero,  # cand_lo
                        zero,  # cand_hi
                        zero,  # depth
                        jnp.where(seed_fail > u(0), u(16), zero),  # flags
                        zero,  # waves_left
                    ]),
                    pv(jnp.full((n_props,), u(NO_GID))),
                ]
            )
            return (
                table.key_hi,
                table.key_lo,
                store,
                parent,
                ebits_buf,
                queue,
                stats,
            )

        def build():
            sp = P("shards")
            return jax.jit(
                _shard_map(
                    seed_shard,
                    mesh=self._mesh,
                    in_specs=(sp,),
                    out_specs=(sp,) * 7,
                )
            )

        from .wave_common import cached_program

        return cached_program(
            _PROGRAM_CACHE, _PROGRAM_CACHE_MAX, key, build,
            label="ShardedTpuChecker.seed",
            journal=self._journal,
            provenance={"shards": self._n, "seed_w": seed_w},
        )

    # --- host loop -----------------------------------------------------------

    def _run(self) -> None:
        try:
            self._check()
        except BaseException as e:
            self._errors.append(e)
        finally:
            self._done.set()

    # --- traced (phase-timed) mode -------------------------------------------

    def _traced_programs(self):
        """Phase-program set for ``trace=True``, cached like the fused
        program.  Host-driven knobs (waves, finish_when, depth gating)
        are not baked in — the traced loop decides them per wave."""
        key = (
            "traced",
            self._compiled.cache_key(),
            hasattr(self._compiled, "step_valid")
            and hasattr(self._compiled, "step_lane"),
            self._canon is not None,
            self._cap_s,
            self._chunk,
            self._dedup_factor,
            self._sortless,  # the dedup path is a trace-time branch
            self._sort_width(),  # the live sort-geometry rung
            self._step_width(),  # the live step-geometry rung
            self._bucket_slack,  # shapes the exchange buckets
            tuple((d.platform, d.id) for d in self._mesh.devices.flat),
            tuple(p.expectation for p in self._properties),
        )
        from .wave_common import cached_program

        return cached_program(
            _PROGRAM_CACHE, _PROGRAM_CACHE_MAX, key, self._build_traced,
            label="ShardedTpuChecker.traced",
            journal=self._journal,
            provenance=self._key_provenance(),
        )

    def _build_traced(self):
        """The sharded wave as six separately-dispatched shard_map phase
        programs — the same kernels as the fused ``body``, cut at the
        roofline's phase boundaries (step kernel / canon+fp / local
        dedup-sort / exchange / table insert / append), with level and
        termination bookkeeping moved to the host (per-shard control
        scalars ride a tiny uploaded ctrl vector; all cross-shard
        reductions become host sums over per-shard outputs, so the only
        collective left is the all_to_all itself)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..ops.device_fp import device_fp64
        from .hashset import (
            HashSet, compact_valid_indices, insert_batch_claim,
            insert_batch_compact, prededup,
        )
        from .wave_common import wave_eval

        cm = self._compiled
        w = cm.state_width
        fpw = cm.fp_words or w
        canon = self._canon

        def fp_of(rows):
            rows_c = rows if canon is None else jax.vmap(canon)(rows)
            return device_fp64(rows_c[:, :fpw])

        a = cm.max_actions
        f_eff = self._step_width()  # the live step-geometry rung
        n = self._n
        cap_s = self._cap_s
        qcap = cap_s
        slot_bits = self._slot_bits
        props = self._properties
        ev_indices = self._ev_indices
        dedup_factor = self._dedup_factor
        sortless = self._sortless  # the dedup path (claim vs sort)
        sort_lanes = (
            None if self._sort_lanes is None else self._sort_width()
        )
        b = f_eff * a
        bkt = self._bucket_lanes()  # per-destination exchange bucket
        u = jnp.uint32
        shard = P("shards")

        def sharded(fn, n_in, donate=()):
            return jax.jit(
                _shard_map(
                    fn, mesh=self._mesh,
                    in_specs=(shard,) * n_in, out_specs=shard,
                ),
                donate_argnums=donate,
            )

        def step_shard(store, ebits, queue, disc, ctrl):
            me = jax.lax.axis_index("shards").astype(u)
            level_start = ctrl[0, 0]
            level_end = ctrl[0, 1]
            count = jnp.minimum(level_end - level_start, u(f_eff))
            chunk = jax.lax.dynamic_slice(queue, (level_start,), (f_eff,))
            lane = jnp.arange(f_eff, dtype=u)
            active = lane < count
            safe_slots = jnp.where(active, chunk, 0)
            states = store[safe_slots]
            my_gids = (me << u(slot_bits)) | safe_slots
            disc_v, eb, nexts, valid, gen_local, step_flag = wave_eval(
                cm, props, ev_indices, states, active, my_gids,
                ebits[safe_slots], disc[0], allow_two_phase=True,
            )
            flat_valid = valid.reshape(b)
            v_orig, v_act, _n_valid, local_overflow = compact_valid_indices(
                flat_valid, dedup_factor, sort_lanes=sort_lanes
            )
            if nexts is None:
                # Two-phase: construct successors only for the compacted
                # valid lanes (the fused body's phase B).
                rows_v, _vv, lane_flags_v = jax.vmap(cm.step_lane)(
                    states[v_orig // u(a)], v_orig % u(a)
                )
                step_flag = step_flag | jnp.any(lane_flags_v & v_act)
            else:
                rows_v = nexts.reshape(b, w)[v_orig]
            gid_v = my_gids[v_orig // u(a)]
            eb_v = eb[v_orig // u(a)]
            return (
                disc_v[None], rows_v, gid_v, eb_v, v_act,
                local_overflow[None], gen_local.astype(u)[None],
                step_flag[None],
            )

        def canon_shard(rows_v):
            hi, lo = fp_of(rows_v)
            return hi, lo

        def prededup_shard(hi, lo, rows_v, gid_v, eb_v, v_act):
            if sortless and n == 1:
                # 1-shard sortless: the claim insert IS the global
                # dedup — this phase is the identity (≈0 s in the
                # breakdown), exactly the fused body's elision.
                n_cand = jnp.sum(v_act, dtype=u)
                return hi, lo, rows_v, gid_v, eb_v, v_act, n_cand[None]
            # dd=1 over the already-compacted buffer, exactly the fused
            # body's local pre-dedup: representatives in sorted key
            # order, one lane per distinct local key.
            u_hi, u_lo, u_origin0, u_valid, _never = prededup(
                hi, lo, v_act, dedup_factor=1
            )
            rows_u = rows_v[u_origin0]
            gid_u = gid_v[u_origin0]
            eb_u = eb_v[u_origin0]
            n_cand = jnp.sum(u_valid, dtype=u)
            return u_hi, u_lo, rows_u, gid_u, eb_u, u_valid, n_cand[None]

        def exchange_shard(u_hi, u_lo, rows_u, gid_u, eb_u, u_valid):
            # Bucket by owner + the single packed all_to_all (the fused
            # body's BUCKETED exchange block), plus the receiver-side
            # re-fingerprint of the arrived rows — charged to this phase
            # because it only exists when an exchange happened.  The
            # per-shard bucket-overflow flag rides back so the host can
            # abort BEFORE the insert/append phases commit anything and
            # retry the wave at the next slack rung (the fused loop's
            # contract, one wave later here because the host drives).
            u_sz = u_hi.shape[0]
            owner = _owner_mix(u_hi, u_lo) % u(n)
            key = jnp.where(u_valid, owner, u(n))
            order = jnp.argsort(key, stable=True)
            key_s = key[order]
            counts = jnp.stack(
                [jnp.sum((key == u(d)).astype(u)) for d in range(n + 1)]
            )
            bucket_ovf = jnp.any(counts[:n] > u(bkt))
            offsets = jnp.concatenate(
                [jnp.zeros((1,), u), jnp.cumsum(counts)[:-1]]
            )
            pos = jnp.arange(u_sz, dtype=u) - offsets[key_s]
            dst = jnp.where(key_s < n, key_s, u(n))
            payload = jnp.concatenate(
                [
                    rows_u,
                    gid_u[:, None],
                    eb_u[:, None],
                    u_valid.astype(u)[:, None],
                ],
                axis=1,
            )
            send = jnp.zeros((n, bkt, w + 3), u)
            send = send.at[dst, pos].set(payload[order], mode="drop")
            recv = jax.lax.all_to_all(
                send, "shards", split_axis=0, concat_axis=0, tiled=False
            )
            flatrecv = recv.reshape(n * bkt, w + 3)
            rw = flatrecv[:, :w]
            rhi, rlo = fp_of(rw)
            return (
                rw, flatrecv[:, w], flatrecv[:, w + 1],
                flatrecv[:, w + 2], rhi, rlo, bucket_ovf[None],
            )

        def insert_shard(key_hi, key_lo, rhi, rlo, rv):
            if sortless:
                (
                    table, r_slot, r_new, r_origin, _ra, probe_ok,
                    dd_overflow, rounds,
                ) = insert_batch_claim(
                    HashSet(key_hi, key_lo), rhi, rlo,
                    rv.astype(jnp.bool_), with_rounds=True,
                )
            else:
                (
                    table, r_slot, r_new, r_origin, _ra, probe_ok,
                    dd_overflow, rounds,
                ) = insert_batch_compact(
                    HashSet(key_hi, key_lo), rhi, rlo,
                    rv.astype(jnp.bool_), dedup_factor=1,
                    with_rounds=True,
                )
            return (
                table.key_hi, table.key_lo, r_slot, r_new, r_origin,
                probe_ok[None], dd_overflow[None], rounds[None],
            )

        def append_shard(store, parent, ebits, queue, rw, rg, reb,
                         r_slot, r_new, r_origin, ctrl):
            tail = ctrl[0, 0]
            rows_r = rw[r_origin]
            sslot = jnp.where(r_new, r_slot, u(cap_s))
            store = store.at[sslot].set(rows_r, mode="drop")
            parent = parent.at[sslot].set(rg[r_origin], mode="drop")
            ebits = ebits.at[sslot].set(reb[r_origin], mode="drop")
            n_new = jnp.sum(r_new, dtype=u)
            qpos = tail + jnp.cumsum(r_new.astype(u)) - 1
            # Always-OOB drop sentinel (not qcap+f): growth may halve
            # the chunk mid-run while the queue keeps its minted length.
            qidx = jnp.where(r_new, qpos, u(0xFFFFFFFF))
            queue = queue.at[qidx].set(r_slot, mode="drop")
            return store, parent, ebits, queue, n_new[None]

        return {
            "step": sharded(step_shard, 5),
            "canon": sharded(canon_shard, 1),
            "prededup": sharded(prededup_shard, 6),
            "exchange": sharded(exchange_shard, 6),
            "insert": sharded(insert_shard, 5, donate=(0, 1)),
            "append": sharded(append_shard, 11, donate=(0, 1, 2, 3)),
        }

    def _traced_wave_bytes(self, probe_rounds: int, two_phase: bool) -> dict:
        """Modeled PER-SHARD HBM bytes for one traced wave (each shard
        streams the same fixed-width buffers in parallel, so per-shard
        bytes over measured wall time is per-device bandwidth;
        obs/roofline.py documents the model)."""
        from ..obs.roofline import copy_bytes, probe_bytes, sort_bytes

        cm = self._compiled
        w = cm.state_width
        fpw = cm.fp_words or w
        n = self._n
        f_eff = self._step_width()  # the live step rung (bytes.step)
        b = f_eff * cm.max_actions
        # The LIVE sort rung, not the worst-case unique_buffer_size:
        # bytes.dedup drops in proportion to the rung — the ladder's
        # regression gauge (docs/OBSERVABILITY.md).
        u_sz = self._sort_width()
        bkt = self._bucket_lanes()
        recv = n * bkt if n > 1 else u_sz  # post-exchange insert lanes
        step = copy_bytes(f_eff, w) + b * 4 + copy_bytes(u_sz, w)
        if not two_phase:
            step += b * w * 4
        canon = (copy_bytes(u_sz, w) if self._canon is not None else 0)
        canon += u_sz * fpw * 4 + 2 * u_sz * 4
        if self._sortless:
            # Claim-path dedup: the owner-side insert probes (no sort);
            # the local prededup sort survives only on n>1 meshes (the
            # exchange ships distinct keys) and is elided at n == 1.
            dedup = probe_bytes(recv, probe_rounds) + 2 * recv * 4
            if n > 1:
                dedup += (
                    sort_bytes(u_sz, 3) + 4 * u_sz * 4
                    + copy_bytes(u_sz, w)
                )
        else:
            dedup = (
                sort_bytes(u_sz, 3) + 4 * u_sz * 4 + copy_bytes(u_sz, w)
                + sort_bytes(recv, 3)
                + probe_bytes(recv, probe_rounds) + 4 * recv * 4
            )
        exchange = 0
        if n > 1:
            # send-buffer scatter + the a2a move (in and out) of the
            # BUCKETED [n, bkt, W+3] payload + the receiver-side
            # re-fingerprint.
            exchange = (
                3 * n * bkt * (w + 3) * 4
                + recv * fpw * 4 + 2 * recv * 4
            )
        append = copy_bytes(recv, w) + 2 * copy_bytes(recv, 1) + recv * 4
        return {
            "step": step, "canon": canon, "dedup": dedup,
            "exchange": exchange, "append": append,
        }

    def _check_traced(self) -> None:
        """The ``trace=True`` host loop: one wave per iteration, six
        phase dispatches timed with ``block_until_ready``, per-shard
        control scalars driven from the host, and the exchange measured
        live — payload bytes and lane occupancy per wave in the journal.
        Overflows raise (no growth path exists in this engine anyway);
        results match the fused loop exactly."""
        import time as _time

        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        opts = self._options
        cm = self._compiled
        props = self._properties
        n = self._n
        f = self._chunk
        f_eff = self._step_width()  # the live step-geometry rung
        cap_s = self._cap_s
        qcap = cap_s
        w = cm.state_width
        deadline = (
            _time.monotonic() + opts._timeout
            if opts._timeout is not None else None
        )
        from ..obs.trace import WaveTracer
        from .wave_common import two_phase_capable

        two_phase = two_phase_capable(cm)

        bkt = self._bucket_lanes()
        tracer = WaveTracer(
            self._mesh.devices.flat[0], f"tpu-sharded-{n}"
        )
        self._tracer = tracer
        shard = NamedSharding(self._mesh, P("shards"))
        k_stats = S_DISC + len(props)
        target_depth = opts._target_max_depth or 0

        progs = self._traced_programs()
        key_hi, key_lo, store, parent, ebits, queue, stats = (
            self._seed_initial(shard)
        )
        stats_h = np.asarray(stats).reshape(n, k_stats).astype(np.int64)
        if int(stats_h[0, S_FLAGS]) & 16:
            raise RuntimeError(
                "init-state seeding overflowed the insert buffers; "
                "raise capacity or lower dedup_factor"
            )
        level_start = np.zeros(n, np.int64)
        level_end = stats_h[:, S_LEVEL_END].copy()
        tails = stats_h[:, S_TAIL].copy()
        unique_l = stats_h[:, S_UNIQUE_L].copy()
        cand_total = np.zeros(n, np.int64)
        depth = 0
        disc = jax.device_put(
            jnp.asarray(np.full((n, len(props)), NO_GID, np.uint32)), shard
        )
        disc_h = np.asarray(disc).reshape(n, len(props))
        waves = 0
        # Always-on vitals (latency histogram, uniq/s EMA, density,
        # grow counters) — same registry keys as the fused loop's.
        from .wave_loop import LoopVitals, journal_geometry

        vitals = LoopVitals(
            self._metrics, initial_unique=self._unique_count,
            initial_states=self._state_count,
        )
        journal_geometry(self)

        while int((level_end - level_start).sum()) > 0:
            if target_depth and depth >= target_depth - 1:
                break
            if (
                f_eff < f
                and int((level_end - level_start).max()) > f_eff
            ):
                # Step-rung clamp (flag 128): pure host data here, so
                # the climb happens BEFORE the wave is even dispatched
                # — same non-committing semantics as the fused flag.
                if self._grow_knobs(128) is None:
                    raise RuntimeError(self._wl_overflow_message(128))
                f_eff = self._step_width()
                bkt = self._bucket_lanes()
                progs = self._traced_programs()
                vitals.record_overflow_recovery()
                continue
            counts = np.minimum(level_end - level_start, f_eff)
            ctrl = jax.device_put(
                jnp.asarray(
                    np.stack([level_start, level_end], axis=1)
                    .astype(np.uint32)
                ),
                shard,
            )
            t0 = _time.perf_counter()
            disc_before = disc  # restored on a retryable-overflow re-run
            # xprof hook (obs/timeline.py): under --xprof-dir the wave's
            # device phases land in a StepTraceAnnotation so hardware
            # profiles align with journal wave events; nullcontext
            # otherwise.
            from ..obs.timeline import step_annotation
            _step_ann = step_annotation(waves)
            _step_ann.__enter__()
            (
                disc, rows_v, gid_v, eb_v, v_act, local_ovf_d, gen_d,
                stepflag_d,
            ) = progs["step"](store, ebits, queue, disc, ctrl)
            jax.block_until_ready(rows_v)
            t1 = _time.perf_counter()
            hi_v, lo_v = progs["canon"](rows_v)
            jax.block_until_ready(lo_v)
            t2 = _time.perf_counter()
            (
                u_hi, u_lo, rows_u, gid_u, eb_u, u_valid, n_cand_d,
            ) = progs["prededup"](hi_v, lo_v, rows_v, gid_v, eb_v, v_act)
            jax.block_until_ready(u_valid)
            t3 = _time.perf_counter()
            if n > 1:
                rw, rg, reb, rv, rhi, rlo, ovf_d = progs["exchange"](
                    u_hi, u_lo, rows_u, gid_u, eb_u, u_valid
                )
                jax.block_until_ready(rlo)
            else:
                # 1-shard mesh: every owner is self — elide the whole
                # exchange, like the fused program.
                rw, rg, reb, rv, rhi, rlo = (
                    rows_u, gid_u, eb_u, u_valid, u_hi, u_lo
                )
                ovf_d = None
            t4 = _time.perf_counter()
            # Retryable-overflow gate BEFORE the insert/append phases,
            # so an overflowing wave commits nothing (the fused loop's
            # contract): grow the tripped knob in place, rebuild the
            # phase programs at the new shapes, and re-run this wave —
            # its inputs (store/queue/level bounds, and ``disc``, which
            # is restored) are untouched by growth.
            retry_flags = 0
            if bool(np.asarray(local_ovf_d).any()):
                retry_flags |= 4
            if ovf_d is not None and bool(np.asarray(ovf_d).any()):
                retry_flags |= 32
            if retry_flags:
                _step_ann.__exit__(None, None, None)
                if self._grow_knobs(retry_flags) is None:
                    raise RuntimeError(
                        self._wl_overflow_message(retry_flags)
                    )
                disc = disc_before
                f = self._chunk  # dedup growth may halve it
                f_eff = self._step_width()
                bkt = self._bucket_lanes()
                progs = self._traced_programs()
                vitals.record_overflow_recovery()
                continue
            (
                key_hi, key_lo, r_slot, r_new, r_origin, probe_ok_d,
                dd_ovf_d, rounds_d,
            ) = progs["insert"](key_hi, key_lo, rhi, rlo, rv)
            jax.block_until_ready(key_lo)
            t5 = _time.perf_counter()
            tailctrl = jax.device_put(
                jnp.asarray(tails[:, None].astype(np.uint32)), shard
            )
            store, parent, ebits, queue, n_new_d = progs["append"](
                store, parent, ebits, queue, rw, rg, reb, r_slot,
                r_new, r_origin, tailctrl,
            )
            jax.block_until_ready(queue)
            _step_ann.__exit__(None, None, None)
            t6 = _time.perf_counter()
            # Host readback: the per-wave scalar sync.
            n_new = np.asarray(n_new_d).astype(np.int64)
            gen_h = np.asarray(gen_d).astype(np.int64)
            n_cand = np.asarray(n_cand_d).astype(np.int64)
            rounds = int(np.asarray(rounds_d).max())
            disc_h = np.asarray(disc).reshape(n, len(props))
            flags = 0
            if (
                not bool(np.asarray(probe_ok_d).all())
                or ((unique_l + n_new) * 2 > cap_s).any()
            ):
                flags |= 1
            if ((tails + n_new) > qcap).any():
                flags |= 2
            # Pre-exchange compaction overflow already retried above;
            # the insert's own dd=1 buffer covers its whole batch, so
            # this is a can't-happen invariant guard.
            if bool(np.asarray(dd_ovf_d).any()):
                flags |= 4
            if bool(np.asarray(stepflag_d).any()):
                flags |= 8
            t7 = _time.perf_counter()

            tails += n_new
            unique_l += n_new
            cand_total += n_cand
            level_start = level_start + counts
            if int((level_end - level_start).sum()) == 0:
                depth += 1
                level_end = tails.copy()
            remaining = int((level_end - level_start).sum())
            waves += 1
            with self._lock:
                self._state_count += int(gen_h.sum())
                self._unique_count += int(n_new.sum())
                self._max_depth = depth + (1 if remaining else 0)
                for d in range(n):
                    for p, prop in enumerate(props):
                        g = int(disc_h[d, p])
                        if g != NO_GID:
                            self._discovery_gids.setdefault(prop.name, g)

            if flags & 1:
                raise RuntimeError(
                    f"sharded fingerprint table overfull (per-shard "
                    f"capacity {cap_s}); raise capacity"
                )
            if flags & 2:
                raise RuntimeError(
                    "a shard's frontier queue overflowed its backstop "
                    "bound; raise capacity"
                )
            if flags & 4:
                raise RuntimeError(
                    "the owner-side insert dedup buffer overflowed — "
                    "impossible by construction at dedup_factor=1 over "
                    "the receive batch; please report"
                )
            if flags & 8:
                raise RuntimeError(
                    "the model step kernel flagged an encoding-capacity "
                    "overflow (a successor exceeded the packed layout's "
                    "bounds)"
                )

            phases = {
                "step": t1 - t0,
                "canon": t2 - t1,
                "dedup": (t3 - t2) + (t5 - t4),
                "exchange": t4 - t3,
                "append": t6 - t5,
                "readback": t7 - t6,
            }
            # The MEASURED exchange instrumentation: useful payload
            # bytes this wave vs the BUCKETED transmitted buffer
            # (waves × n² × bkt lanes across the mesh).
            useful = int(n_cand.sum()) * (w + 3) * 4 if n > 1 else 0
            occ_wave = (
                float(n_cand.sum()) / (n * n * bkt) if n > 1 else 0.0
            )
            enrich = tracer.record_wave(
                phases, self._traced_wave_bytes(rounds, two_phase),
                probe_rounds=rounds,
                exchange_payload_bytes=useful,
            )
            enrich["exchange_occupancy"] = round(occ_wave, 6)
            vitals.record_quantum(
                t7 - t0, 1, self._unique_count, committed=True,
                states=self._state_count,
                cand_lanes=self._wl_cand_lanes(),
                occupancy=float(unique_l.max()) / cap_s,
            )
            vitals.record_host(phases["readback"])
            self._update_shard_metrics(
                level_end - level_start, unique_l, cand_total
            )
            if self._journal:
                self._journal.append(
                    "wave",
                    waves=waves,
                    remaining=remaining,
                    unique=self._unique_count,
                    states=self._state_count,
                    depth=depth,
                    flags=0,
                    call_sec=round(t7 - t0, 6),
                    occupancy=round(float(unique_l.max()) / cap_s, 6),
                    **(
                        {"density": round(vitals.last_density, 6)}
                        if vitals.last_density is not None else {}
                    ),
                    **enrich,
                )
            self._metrics.update(
                waves=waves,
                table_occupancy=round(float(unique_l.max()) / cap_s, 6),
                last_call_sec=round(t7 - t0, 6),
                exchange_occupancy=round(occ_wave, 6),
            )
            self._metrics.inc("device_call_sec_total", t7 - t0)
            self._metrics.inc("device_calls", 1)

            # Density-driven sort-rung downshift and frontier-driven
            # step-rung downshift, per committed wave (wave_loop's
            # shared tuners); a rung change re-keys the phase programs
            # and recomputes the rung-derived buckets.
            from .wave_loop import maybe_retune_sort, maybe_retune_step

            retuned = maybe_retune_sort(self, vitals.last_density)
            # Per-shard evidence: the fullest shard's backlog is what
            # the (per-shard) chunk rung must hold.  The fused loop
            # feeds the global sum instead — an upper bound, so its
            # downshifts are merely more conservative.
            peak_backlog = int((level_end - level_start).max())
            if maybe_retune_step(self, peak_backlog or None):
                retuned = True
            if retuned:
                f_eff = self._step_width()
                bkt = self._bucket_lanes()
                progs = self._traced_programs()

            # Shared termination tail (wave_loop.py): finish_when /
            # target_state_count / deadline / cooperative cancel, the
            # same predicate order as the fused loop by construction.
            from .wave_loop import loop_should_break

            if loop_should_break(self, remaining, depth, deadline):
                break

        self._accounting = self._build_accounting(
            waves, cand_total, unique_l
        )
        self._tables_dev = (parent, store)
        # Snapshot-ready carry, like the fused loop: the stats matrix is
        # reconstructed from the host-tracked control state (sc/unique_g
        # replicated per shard, exactly as the psums leave them).
        stats_np = np.zeros((n, k_stats), np.uint32)
        stats_np[:, S_LEVEL_START] = level_start.astype(np.uint32)
        stats_np[:, S_LEVEL_END] = level_end.astype(np.uint32)
        stats_np[:, S_TAIL] = tails.astype(np.uint32)
        stats_np[:, S_SC_LO] = self._state_count & 0xFFFFFFFF
        stats_np[:, S_SC_HI] = (self._state_count >> 32) & 0xFFFFFFFF
        stats_np[:, S_UNIQUE_G] = self._unique_count
        stats_np[:, S_UNIQUE_L] = unique_l.astype(np.uint32)
        stats_np[:, S_CAND_LO] = (cand_total & 0xFFFFFFFF).astype(np.uint32)
        stats_np[:, S_CAND_HI] = (cand_total >> 32).astype(np.uint32)
        stats_np[:, S_DEPTH] = depth
        stats_np[:, S_DISC:] = disc_h.astype(np.uint32)
        if self._journal:
            self._journal.append("trace_summary", **tracer.summary())
        # Final carry / completion checkpoint / engine_done via the
        # shared core, same as the fused loop.
        from .wave_loop import finalize_run

        finalize_run(self, {
            "key_hi": key_hi,
            "key_lo": key_lo,
            "store": store,
            "parent": parent,
            "ebits": ebits,
            "queue": queue,
            "stats": stats_np,
        })

    def _seed_initial(self, shard):
        """Host-side owner routing + the seed program: one upload + one
        dispatch mints every device buffer (the spawn-cost story in
        ``_seed_program``).  Shared by the fused and traced host loops
        so seeding semantics cannot drift between them."""
        import jax
        import jax.numpy as jnp

        cm = self._compiled
        n = self._n
        # Seed init states host-side: fingerprints and owners computed
        # on the HOST (bit-identical by the pinned host/device fp
        # parity), so the whole spawn is one upload + one seed
        # dispatch — the seed program mints every device buffer and
        # the run loop's stats vector itself.
        from ..ops.fingerprint import fp64_words

        init = cm.init_packed()
        n_init = init.shape[0]
        fpw = cm.fp_words or cm.state_width
        if self._canon is not None:
            # Owner placement must use the CANONICAL fingerprint (the
            # dedup/routing key); evaluated on the CPU backend via
            # the same traced kernel, so it is bit-identical to the
            # device's without a device round trip.  The shards still
            # receive (and store) the original rows.
            from .canon import canon_batch_host

            fp_rows = canon_batch_host(cm, init)
        else:
            fp_rows = init
        fps = [fp64_words(row[:fpw].tolist()) for row in fp_rows]
        owner = np.array(
            [
                _owner_mix_host((fp >> 32) & 0xFFFFFFFF, fp & 0xFFFFFFFF)
                % n
                for fp in fps
            ],
            np.uint32,
        )

        # Per-shard seed batches, padded to a common width; validity
        # rides as one extra word column so the upload is one array.
        seed_w = max(int((owner == d).sum()) for d in range(n)) or 1
        packed_np = np.zeros((n, seed_w, cm.state_width + 1), np.uint32)
        for d in range(n):
            idx = np.flatnonzero(owner == d)
            packed_np[d, : len(idx), : cm.state_width] = init[idx]
            packed_np[d, : len(idx), cm.state_width] = 1

        seed = self._seed_program(int(seed_w))
        out = seed(jax.device_put(jnp.asarray(packed_np), shard))

        self._state_count = n_init
        self._unique_count = len(set(fps))
        return out

    def _check(self) -> None:
        if self._trace:
            return self._check_traced()
        import time as _time

        import jax
        import jax.numpy as jnp

        opts = self._options
        props = self._properties
        n = self._n
        deadline = (
            _time.monotonic() + opts._timeout if opts._timeout is not None else None
        )

        # Global (host-side numpy) views of the stacked per-shard tables are
        # only pulled at the end; during the run everything stays sharded.
        from jax.sharding import NamedSharding, PartitionSpec as P

        shard = NamedSharding(self._mesh, P("shards"))
        k_stats = S_DISC + len(props)

        if self._resume_from is not None:
            # A resume ADOPTS the snapshot's per-shard geometry (cap_s
            # shapes the slot mask and the gid encoding, chunk the queue
            # backstop); only model identity + MESH SIZE are key-checked
            # — gids embed the owner shard, so a snapshot cannot move to
            # a different mesh size.
            snap = np.load(self._resume_from, allow_pickle=False)
            if (
                "n_shards" in snap.files
                and int(snap["n_shards"]) != self._n
            ):
                # The dedicated LOUD mesh-size error (the generic key
                # mismatch below also catches it for old snapshots, but
                # names neither size): gids encode the owner shard, so a
                # snapshot is bound to the exact mesh width that wrote it.
                raise ValueError(
                    f"sharded snapshot was written on a "
                    f"{int(snap['n_shards'])}-shard mesh and cannot "
                    f"resume on {self._n} shards directly: global "
                    "state ids encode the owner shard, so the only "
                    f"valid direct-resume size is "
                    f"{int(snap['n_shards'])} shards; to continue this "
                    f"run on a {self._n}-shard mesh, re-key the "
                    "snapshot first with the `reshard` verb "
                    "(stateright_tpu.tiered.reshard.reshard_snapshot) "
                    "and resume the converted snapshot with the "
                    "tiered-sharded engine"
                )
            want_key = self._snapshot_key()
            got_key = str(snap["engine_key"])
            if got_key != want_key:
                raise ValueError(
                    "snapshot does not match this sharded checker "
                    f"configuration (snapshot {got_key}, expected "
                    f"{want_key})"
                )
            self._cap_s = int(snap["cap_s"])
            self._slot_bits = self._cap_s.bit_length() - 1
            self._chunk = int(snap["chunk"])
            if "bucket_slack" in snap.files:
                # Adopt the saved run's discovered bucket rung so a
                # resume never re-pays the overflow-retry ramp.
                self._bucket_slack = int(snap["bucket_slack"])
            if "sort_lanes" in snap.files:
                # Same for the discovered sort-geometry rung (0 = the
                # saved run ran at the full buffer).  An adopted rung is
                # a PROVEN rung: the density tuner stands down, exactly
                # as for an explicit spawn argument.
                saved_rung = int(snap["sort_lanes"])
                if saved_rung:
                    self._sort_lanes = saved_rung
                    self._sort_tune = False
            if "sortless" in snap.files:
                # Adopt the saved run's dedup path: a resume of a
                # fallen-back run must not re-pay the fallback retry.
                self._sortless = bool(int(snap["sortless"]))
            if "step_lanes" in snap.files:
                saved_step = int(snap["step_lanes"])
                if saved_step:
                    self._step_lanes = saved_step
                    self._step_tune = False
            from .wavefront import _device_owned

            def up(x):
                # Sharded upload, forced into DEVICE-OWNED buffers: the
                # run program donates every argument, and donating a
                # borrowed host-upload buffer corrupts the run (see
                # wavefront._device_owned).
                return _device_owned(jax.device_put(jnp.asarray(x), shard))

            key_hi = up(snap["key_hi"])
            key_lo = up(snap["key_lo"])
            store = up(snap["store"])
            parent = up(snap["parent"])
            ebits = up(snap["ebits"])
            queue = up(snap["queue"])
            stats_np = np.asarray(snap["stats"]).astype(np.uint32)
            stats = up(stats_np.reshape(-1))
            snap_h = stats_np.astype(np.int64).reshape(n, k_stats)
            with self._lock:
                self._state_count = (
                    int(snap_h[0, S_SC_HI]) << 32
                ) | int(snap_h[0, S_SC_LO])
                self._unique_count = int(snap_h[0, S_UNIQUE_G])
                self._max_depth = int(snap_h[0, S_DEPTH])
                for d in range(n):
                    for p, prop in enumerate(props):
                        g = int(snap_h[d, S_DISC + p])
                        if g != NO_GID:
                            self._discovery_gids.setdefault(prop.name, g)
            if self._journal:
                self._journal.append(
                    "resume",
                    path=self._resume_from,
                    unique=self._unique_count,
                    states=self._state_count,
                    depth=self._max_depth,
                )
        else:
            key_hi, key_lo, store, parent, ebits, queue, stats = (
                self._seed_initial(shard)
            )

        # The steady-state loop is the SHARED wave-loop core
        # (parallel/wave_loop.py) — journal/metrics/checkpoint cadence,
        # overflow dispatch (grow in place for dedup/bucket overflows,
        # loud raise otherwise), and termination live there, identical
        # to the single-chip engine by construction.
        from .wave_loop import FusedWaveLoop, finalize_run

        self._run_fn = self._programs()
        carry = (key_hi, key_lo, store, parent, ebits, queue, stats)
        carry, waves_total = FusedWaveLoop(self).run(carry, deadline)
        key_hi, key_lo, store, parent, ebits, queue, stats = carry
        stats_h = self._last_stats_h.copy()
        # A keep-partial stop (deadline/cancel during a retryable
        # overflow) can leave flag bits in the final readback; the
        # flagged wave committed nothing, so the rest of the vector is
        # the exact pre-wave state and a resume must start flag-clean.
        stats_h[:, S_FLAGS] = 0

        # Weak-scaling accounting: lockstep waves, the bucketed all_to_all
        # payload, and its measured occupancy/skew (docs/SHARDED_SCALING.md;
        # replaces the former unquantified "statistically balanced" claim).
        cand_h = (
            stats_h[:, S_CAND_HI].astype(np.int64) << 32
        ) | stats_h[:, S_CAND_LO].astype(np.int64)
        uniq_h = stats_h[:, S_UNIQUE_L].astype(np.int64)
        self._accounting = self._build_accounting(waves_total, cand_h, uniq_h)

        # Keep the device arrays; path reconstruction pulls them lazily —
        # an eager pull is ~10 s of tunnel bandwidth for a 2^20-slot store
        # and most runs never reconstruct a path (same policy as the
        # single-chip engine).
        self._tables_dev = (parent, store)
        # Full run state for save_snapshot (the single-chip engine's
        # snapshot-ready policy, via the shared finalize): bounded sharded
        # runs persist and resume exactly like single-chip ones.
        finalize_run(self, {
            "key_hi": key_hi,
            "key_lo": key_lo,
            "store": store,
            "parent": parent,
            "ebits": ebits,
            "queue": queue,
            "stats": stats_h.astype(np.uint32),
        })

    # --- shared wave-loop adapter (parallel/wave_loop.py) --------------------

    def _wl_call(self, carry):
        return self._run_fn(*carry)

    def _wl_view(self, carry):
        from .wave_loop import WaveView

        props = self._properties
        n = self._n
        stats_h = (
            np.asarray(carry[6])
            .reshape(n, S_DISC + len(props))
            .astype(np.int64)
        )
        self._last_stats_h = stats_h
        # Per-shard skew gauges from the SAME readback (no extra sync).
        self._update_shard_metrics(
            stats_h[:, S_LEVEL_END] - stats_h[:, S_LEVEL_START],
            stats_h[:, S_UNIQUE_L],
            (stats_h[:, S_CAND_HI] << 32) | stats_h[:, S_CAND_LO],
        )
        remaining = int(
            (stats_h[:, S_LEVEL_END] - stats_h[:, S_LEVEL_START]).sum()
        )
        disc = []
        for d in range(n):
            for p, prop in enumerate(props):
                g = int(stats_h[d, S_DISC + p])
                if g != NO_GID:
                    disc.append((prop.name, g))
        return WaveView(
            waves_this_call=self._waves_per_call
            - int(np.uint32(stats_h[0, S_WAVES_LEFT]).astype(np.int32)),
            remaining=remaining,
            depth=int(stats_h[0, S_DEPTH]),
            flags=int(stats_h[0, S_FLAGS]),
            unique=int(stats_h[0, S_UNIQUE_G]),
            states=(int(stats_h[0, S_SC_HI]) << 32)
            | int(stats_h[0, S_SC_LO]),
            # Binding constraint: the FULLEST shard's table load.
            occupancy=float(stats_h[:, S_UNIQUE_L].max()) / self._cap_s,
            discoveries=tuple(disc),
            extra={},
        )

    def _wl_set_discovery(self, name: str, gid: int) -> None:
        self._discovery_gids.setdefault(name, gid)

    def _wl_discovered_names(self):
        return self._discovery_gids

    def _wl_cand_lanes(self) -> int:
        """Density denominator (wave_loop.LoopVitals): the mesh-global
        worst-case compaction width — every shard's ``U`` buffer —
        matching the psum'd generated-successor numerator.  Rung-
        independent, like the single-chip engine's (the rung is sized
        FROM this density)."""
        return self._n * self._u_sz()

    def _wl_full_sort_lanes(self) -> int:
        """The PER-SHARD worst-case width the rung is clamped to; with
        the mesh-global density this makes ``density × full`` the
        average per-shard valid count — what a shard's rung must hold
        (skew is absorbed by the tuner headroom, and an undersized rung
        is a retry, never a wrong answer)."""
        return self._u_sz()

    def _wl_apply_sort_rung(self, rung: int) -> None:
        """Apply a density-tuner downshift (wave_loop.maybe_retune_sort):
        swap the knob, re-journal the geometry event, and — in fused
        mode — rebuild the run program.  The carry (tables, store,
        queue, stats) is rung-independent."""
        self._sort_lanes = int(rung)
        self._sort_quanta = 0
        # Not mirrored into the metrics registry — metrics() reports
        # the live _sort_width(); a stale registry copy would shadow a
        # later ladder climb (wavefront.py's rule).
        if self._journal:
            self._journal.append("geometry", **self._wl_geometry())
        if getattr(self, "_run_fn", None) is not None:
            self._run_fn = self._programs()

    def _wl_full_step_lanes(self) -> int:
        return self._chunk

    def _wl_apply_step_rung(self, rung: int) -> None:
        """Apply a frontier-tuner downshift (wave_loop.
        maybe_retune_step) — the step-ladder twin of the sort hook
        above; same journal/recompile contract."""
        self._step_lanes = int(rung)
        self._step_quanta = 0
        if self._journal:
            self._journal.append("geometry", **self._wl_geometry())
        if getattr(self, "_run_fn", None) is not None:
            self._run_fn = self._programs()

    def _wl_geometry(self) -> dict:
        """The ``geometry`` journal event payload (wave_loop.
        journal_geometry) — the advisor's knob ground truth, incl. the
        exchange-bucket rung the bucket_slack recommendation is
        relative to."""
        return {
            "engine": "tpu-sharded",
            "shards": self._n,
            "capacity_per_shard": self._cap_s,
            "chunk_size": self._chunk,
            "dedup_factor": self._dedup_factor,
            "sortless": self._sortless,
            "sort_lanes": self._sort_width(),
            "step_lanes": self._step_width(),
            "bucket_slack": self._bucket_slack,
            "exchange_bucket_lanes": (
                0 if self._n == 1 else self._bucket_lanes()
            ),
            "u_lanes": self._wl_cand_lanes(),
            "waves_per_call": self._waves_per_call,
        }

    @staticmethod
    def _skew(arr) -> float:
        m = float(np.asarray(arr, np.float64).mean())
        return round(float(np.asarray(arr).max()) / m, 4) if m > 0 else 1.0

    def _update_shard_metrics(self, frontier, unique_l, cand) -> None:
        """Per-shard gauges + max/mean skew, refreshed from the stats
        readback the loop already holds (never an extra device sync):
        ``shard_frontier`` (remaining frontier backlog), ``shard_unique``
        (owner-table inserts), ``shard_exchange_bytes`` (cumulative
        useful exchange payload contributed) — each a flat numeric dict,
        which obs/prometheus.py renders as ONE labeled gauge family per
        name — plus the scalar ``*_skew_max_over_mean`` gauges the
        ROADMAP #2/#3 load-balance story watches
        (docs/OBSERVABILITY.md "Density and skew telemetry")."""
        w3 = (self._compiled.state_width + 3) * 4
        xbytes = [
            0 if self._n == 1 else int(c) * w3 for c in cand
        ]
        self._metrics.update(
            shard_frontier={
                str(d): int(frontier[d]) for d in range(self._n)
            },
            shard_unique={
                str(d): int(unique_l[d]) for d in range(self._n)
            },
            shard_exchange_bytes={
                str(d): xbytes[d] for d in range(self._n)
            },
            frontier_skew_max_over_mean=self._skew(frontier),
            unique_skew_max_over_mean=self._skew(unique_l),
            exchange_skew_max_over_mean=self._skew(cand),
        )

    def _wl_write_checkpoint(self, carry) -> dict:
        self._write_snapshot(
            self._checkpoint_path,
            {
                "key_hi": carry[0],
                "key_lo": carry[1],
                "store": carry[2],
                "parent": carry[3],
                "ebits": carry[4],
                "queue": carry[5],
                "stats": self._last_stats_h.astype(np.uint32),
            },
        )
        return {}

    def _wl_retryable_flags(self) -> int:
        # 4 = pre-exchange compaction/dedup overflow, 32 = exchange
        # bucket overflow, 128 = step-rung clamp: all detected before
        # any state mutation, so the aborted wave committed nothing and
        # a grown re-run is exact.  Table (1) / queue (2) growth would
        # change the gid encoding that parent links and snapshots bake
        # in, so those stay loud errors on this engine.
        return 4 | 32 | 128

    def _wl_overflow_message(self, flags: int) -> str:
        if flags & 16:
            return (
                "init-state seeding overflowed the insert buffers; "
                "raise capacity or lower dedup_factor"
            )
        if flags & 1:
            return (
                f"sharded fingerprint table overfull (per-shard "
                f"capacity {self._cap_s}); raise capacity"
            )
        if flags & 2:
            return (
                "a shard's frontier queue overflowed its backstop "
                "bound; raise capacity"
            )
        if flags & 8:
            return (
                "the model step kernel flagged an encoding-capacity "
                "overflow (a successor exceeded the packed layout's "
                "bounds); the compiled model's capacity assumptions "
                "do not hold for this configuration"
            )
        if flags & 4:
            return (
                "a shard's chunk had more VALID successor candidates "
                "than its compaction/dedup buffers hold even at "
                f"dedup_factor=1 (now {self._dedup_factor}); lower "
                "chunk_size"
            )
        if flags & 32:
            return (
                "the per-destination exchange bucket overflowed at the "
                f"full-buffer rung (bucket_slack={self._bucket_slack}) — "
                "this cannot happen by construction; please report"
            )
        if flags & 64:
            return (
                "the owner-side insert dedup buffer overflowed — "
                "impossible by construction at dedup_factor=1 over the "
                "receive batch; please report"
            )
        if flags & 128:
            return (
                "the step-rung ladder clamped a wave at the full chunk "
                "width — impossible by construction (the clamp is "
                "compiled out at the top rung); please report"
            )
        return f"sharded engine overflow flags={flags}"

    def _grow_knobs(self, flags: int):
        """The knob half of in-place growth, shared by the fused and
        traced retry paths: relax ``dedup_factor`` straight to 1 (flag 4,
        the rule shared with wavefront.py via wave_loop) and/or climb
        the exchange bucket-slack ladder (flag 32).  Both knobs only
        shape per-wave scratch buffers — never the table, store, queue,
        or gid encoding — so the re-run at grown shapes is exact.
        Returns the grow-note string, or None when the tripped knob
        cannot grow."""
        from .wave_loop import (
            climb_step_rung, log_grow, next_bucket_slack,
            relax_dedup_geometry,
        )

        notes = []
        if flags & 128:
            # Step-rung clamp: the fullest shard's remaining level
            # exceeded the chunk rung — climb one rung (shared ladder
            # rule, wave_loop.climb_step_rung).
            note = climb_step_rung(self, self._chunk)
            if note is None:
                return None
            self._step_retries += 1
            notes.append(note)
        if flags & 4:
            from .wave_loop import climb_sort_rung, reset_sort_rung_to_full

            # Sort-rung ladder first (the shared wave_loop rule, same as
            # the single-chip _grow): a flag-4 overflow at a rung below
            # the full U means the RUNG was too small; climb one rung
            # and re-run.  Only at the full buffer does the flag mean
            # the pre-ladder condition.
            full = self._u_sz()
            note = climb_sort_rung(self, full)
            if note is not None:
                self._sort_retries += 1
                notes.append(note)
            else:
                from .hashset import unique_buffer_size
                from .wavefront import max_safe_unique_lanes

                a = self._compiled.max_actions
                u_cap = max_safe_unique_lanes(
                    self._compiled.state_width + 3
                )
                relaxed = relax_dedup_geometry(
                    self._chunk,
                    self._dedup_factor,
                    lambda c, dd: self._n * unique_buffer_size(c * a, dd),
                    u_cap,
                    chunk_label="chunk_size",
                )
                if relaxed is None:
                    return None
                self._dedup_factor, self._chunk, note = relaxed
                # The full buffer overflowed on valid count: the relaxed
                # dd=1 geometry starts at its own full width (evidence +
                # geometry re-journal in the shared helper).
                reset_sort_rung_to_full(self, full)
                notes.append(note)
        if flags & 32:
            # Evaluate the slack ladder against the SAME width the live
            # buckets derive from (_bucket_lanes uses the sort rung):
            # stepping it against the worst-case U would double the
            # slack without widening the actual (tile-rounded) bucket
            # and deterministically re-fail the same chunk.
            nxt = next_bucket_slack(
                self._sort_width(), self._n, self._bucket_slack
            )
            if nxt is None:
                return None
            self._bucket_slack = nxt
            self._bucket_retries += 1
            notes.append(f"bucket_slack={nxt}")
        log_grow(
            self, flags, "; ".join(notes),
            self._unique_count, self._max_depth,
        )
        return "; ".join(notes)

    def _wl_grow(self, flags: int, carry):
        """In-place growth for the fused loop (the shared wave-loop
        core's grow hook): grow the knobs, then — because the aborted
        wave committed nothing, so the stats readback IS the exact
        pre-wave state — clear the flag bits in the host copy, re-upload
        it (one small transfer per retry; every other carry is reused
        as-is), recompile at the new shapes, and hand the loop the
        patched carry to re-run the same chunk."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._grow_knobs(flags) is None:
            return None
        from .wavefront import _device_owned

        stats_np = self._last_stats_h.astype(np.uint32).copy()
        stats_np[:, S_FLAGS] = 0
        shard = NamedSharding(self._mesh, P("shards"))
        stats = _device_owned(
            jax.device_put(jnp.asarray(stats_np.reshape(-1)), shard)
        )
        self._run_fn = self._programs()
        return carry[:6] + (stats,)

    def _build_accounting(self, waves_total: int, cand_h, uniq_h) -> dict:
        """The weak-scaling accounting dict from measured per-shard
        counters (``cand_h``/``uniq_h``: int64[n]); shared by the fused
        and traced host loops so the payload geometry and occupancy
        definitions cannot drift between them.

        The ``all_to_all_bytes_*`` keys derive from the ACTUAL bucket
        geometry (``_bucket_lanes()``, the same wave_loop function the
        device program compiled against) — never hand-computed from the
        static ``u_sz`` buffer shape — so the doc generator and bench
        read one source of truth.  If the slack rung ramped mid-run, the
        final (largest) bucket is reported: committed pre-ramp waves
        shipped smaller buckets, so totals are a slight over- and
        occupancy a slight under-statement, in the conservative
        direction."""
        cm = self._compiled
        n = self._n
        f = self._chunk
        u_sz = self._sort_width()  # the buffer the exchange buckets
        bkt = self._bucket_lanes()
        return {
            "shards": n,
            "waves": waves_total,
            "chunk_size": f,
            "exchange_lanes_per_shard": u_sz,
            # The discovered rungs + their retry counts, the
            # bucket_slack pattern (knob cache / warm-start evidence).
            "sort_lanes": u_sz,
            "sort_retries": self._sort_retries,
            "sortless": int(self._sortless),
            "step_lanes": self._step_width(),
            "step_retries": self._step_retries,
            # The bucketed payload shape: each shard ships one
            # [bkt, W+3] bucket per destination per wave.
            "exchange_bucket_lanes": 0 if n == 1 else bkt,
            "bucket_slack": self._bucket_slack,
            "bucket_retries": self._bucket_retries,
            # On a 1-shard mesh the whole exchange is elided at trace
            # time (owner is always self), so no bytes move at all.
            "exchange_elided": n == 1,
            "all_to_all_bytes_per_wave_per_shard": (
                0 if n == 1
                else int(n * bkt * (cm.state_width + 3) * 4)
            ),
            "all_to_all_bytes_total": (
                0 if n == 1
                else int(
                    waves_total * n * n * bkt * (cm.state_width + 3) * 4
                )
            ),
            "candidates_sent_per_shard": cand_h.tolist(),
            # Fraction of TRANSMITTED lanes carrying a real candidate:
            # each shard ships [n, bkt] lanes per wave (one bkt-wide
            # bucket per destination), so the denominator is
            # waves * n^2 * bkt across the mesh — occupancy *
            # all_to_all_bytes_total = useful bytes.
            # 0.0 when elided: nothing is transmitted, so the identity
            # occupancy × all_to_all_bytes_total = useful bytes holds.
            "exchange_occupancy": (
                float(cand_h.sum() / (waves_total * n * n * bkt))
                if waves_total and n > 1
                else 0.0
            ),
            "exchange_payload_bytes_total": int(
                cand_h.sum() * (cm.state_width + 3) * 4
            ) if n > 1 else 0,
            "unique_per_shard": uniq_h.tolist(),
            "unique_skew_max_over_mean": (
                float(uniq_h.max() / uniq_h.mean()) if uniq_h.sum() else 1.0
            ),
        }

    def _snapshot_key(self) -> str:
        """Process-stable compatibility key for sharded snapshots — the
        single-chip engine's recipe (model identity via the packed init
        digest, never ``repr``) plus the MESH SIZE, which global ids
        encode and so cannot change across a resume.  Per-shard capacity
        and chunk geometry travel as npz data and are adopted."""
        import hashlib

        cm = self._compiled
        init_digest = hashlib.sha256(
            cm.init_packed().tobytes()
        ).hexdigest()[:16]
        return repr(
            (
                "sharded-v1",
                type(cm).__qualname__,
                cm.state_width,
                cm.max_actions,
                tuple(p.name for p in self._properties),
                init_digest,
                self._n,
            )
            # Canonical-fp tables are not resumable as plain ones (and
            # vice versa); appended only when on, like wavefront.py.
            + (("sym",) if self._canon is not None else ())
        )

    def _write_snapshot(self, path: str, carry: dict) -> None:
        """Atomic (write + rename) persistence of the full sharded run
        state, in ``save_snapshot`` format."""
        import os

        arrays = {k: np.asarray(v) for k, v in carry.items()}
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as fh:
            np.savez_compressed(
                fh,
                engine_key=self._snapshot_key(),
                cap_s=self._cap_s,
                chunk=self._chunk,
                # Mesh width travels as explicit data too (not just key
                # material) so a wrong-mesh resume can say WHICH sizes
                # disagree; bucket_slack rides along so resumes skip the
                # overflow-retry ramp the saved run already climbed.
                n_shards=self._n,
                bucket_slack=self._bucket_slack,
                # The discovered sort rung rides along like the bucket
                # rung (0 = running at the full buffer), so a resume
                # skips the sort ladder's ramp too.
                sort_lanes=self._sort_lanes or 0,
                # The dedup path + step rung, same sentinel rules: a
                # resume must not re-pay the sortless fallback or the
                # step ladder's climb ramp.
                sortless=int(self._sortless),
                step_lanes=self._step_lanes or 0,
                **arrays,
            )
        os.replace(tmp, path)

    def save_snapshot(self, path: str) -> None:
        """Persist the full sharded checker state so a bounded run can be
        resumed with ``spawn_tpu_sharded(resume_from=path)`` on a mesh of
        the SAME SIZE (global ids encode the owner shard).  Same npz
        recipe as the single-chip engine's snapshots."""
        self.join()
        if self._carry_dev is None:
            raise RuntimeError("no run state to snapshot")
        self._write_snapshot(path, self._carry_dev)

    def tuned_kwargs(self) -> dict:
        """Engine kwargs right-sized to THIS run's final knobs (the
        single-chip engine's warm-start pattern): a fresh spawn of the
        same workload on the same mesh starts past the overflow-retry
        ramp — ``bucket_slack`` in particular is the discovered exchange
        rung the knob cache persists (runtime/knob_cache.py)."""
        self.join()
        return dict(
            capacity=self._cap_s * self._n,
            chunk_size=self._chunk,
            dedup_factor=self._dedup_factor,
            bucket_slack=self._bucket_slack,
            # The discovered sort rung (the second ladder the knob
            # cache persists — warm runs skip both ramps) — ONLY when
            # one was actually pinned AND the run ended on the sort
            # path; persisting the full worst-case width would disarm
            # every warm repeat's density tuner, and a SORTLESS run's
            # rung is the claim compaction buffer's tuner detail — an
            # explicit sort_lanes under sortless means a fallback-
            # forcing budget cap on the single-chip engine, so a warm
            # repeat must re-arm the tuner instead (wavefront.py's and
            # the serve scheduler's rule).
            **(
                {"sort_lanes": self._sort_width()}
                if self._sort_lanes is not None and not self._sortless
                else {}
            ),
            # The discovered dedup path + step rung (wavefront.py's
            # persistence rules: the path always, a rung only when
            # pinned).
            sortless=int(self._sortless),
            **(
                {"step_lanes": self._step_width()}
                if self._step_lanes is not None else {}
            ),
        )

    def discovered_fingerprints(self):
        """Sorted uint64 fingerprints of every discovered unique state
        (fingerprints of the ORIGINAL stored rows), for cross-engine
        discovery-set comparison against the single-chip engine — the
        bit-identity pin behind every scale claim
        (tests/test_tpu_sharded.py).  Pulls the per-shard stores to the
        host; size it like a path reconstruction, not a hot call."""
        self.join()
        if self._carry_dev is None:
            raise RuntimeError("no run state to fingerprint")
        from .wave_loop import fingerprints_of_rows

        n, cap_s, w = self._n, self._cap_s, self._compiled.state_width
        store = np.asarray(self._carry_dev["store"]).reshape(n, cap_s, w)
        queue = np.asarray(self._carry_dev["queue"]).reshape(n, -1)
        stats = np.asarray(self._carry_dev["stats"]).reshape(
            n, S_DISC + len(self._properties)
        )
        rows = [
            store[d, queue[d, : int(stats[d, S_TAIL])]] for d in range(n)
        ]
        return fingerprints_of_rows(
            self._compiled, np.concatenate(rows, axis=0), self._canon
        )

    # --- Checker surface -----------------------------------------------------

    def accounting(self) -> dict:
        """Weak-scaling accounting of the finished run: lockstep wave
        count, the (static) all_to_all payload per wave, its measured
        occupancy, and per-shard unique counts with the max/mean skew —
        the quantified form of this engine's load-balance story (the
        reference rebalances dynamically via its job market,
        src/job_market.rs:140-167; hash ownership balances statically and
        this dict is the evidence)."""
        self.join()
        return dict(self._accounting)

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return self._unique_count

    def max_depth(self) -> int:
        return self._max_depth

    def metrics(self) -> dict:
        """Live observability snapshot (names: docs/OBSERVABILITY.md);
        safe to call mid-run.  Includes the weak-scaling accounting once
        the run completes and, under ``trace=True``, the roofline trace
        summary with the measured per-wave exchange totals."""
        out = super().metrics()
        out.update(
            engine="tpu-sharded",
            shards=self._n,
            trace=self._trace,
            capacity=self._cap_s * self._n,
            capacity_per_shard=self._cap_s,
            chunk_size=self._chunk,
            dedup_factor=self._dedup_factor,
            sortless=self._sortless,
            sort_lanes=self._sort_width(),
            # Pinned rung vs live width: wavefront.py's rule.
            sort_lanes_rung=self._sort_lanes or 0,
            step_lanes=self._step_width(),
            step_lanes_rung=self._step_lanes or 0,
            bucket_slack=self._bucket_slack,
            exchange_bucket_lanes=(
                0 if self._n == 1 else self._bucket_lanes()
            ),
        )
        snap = self._metrics.snapshot()
        # Fullest shard's table load (= unique_max/cap_s here: every
        # sharded table entry is one unique state); same key as the
        # single-chip and tiered engines so /.metrics readers see one
        # name everywhere (docs/OBSERVABILITY.md).
        out["table_load_factor"] = snap.get("table_occupancy", 0.0)
        out.update(snap)
        hists = self._metrics.snapshot_histograms()
        if hists:
            out["histograms"] = hists
        if self._accounting:
            out["accounting"] = dict(self._accounting)
        if self._tracer is not None:
            out["trace_summary"] = self._tracer.summary()
        return out

    def trace_summary(self) -> dict:
        """The finished traced run's roofline reduction (per-phase
        seconds, modeled bytes, ``hbm_util_frac``, measured exchange
        payload totals).  Requires ``trace=True``."""
        self.join()
        if self._tracer is None:
            raise RuntimeError(
                "trace_summary() requires spawn_tpu_sharded(trace=True)"
            )
        return self._tracer.summary()

    def _gid_path(self, gid: int) -> Path:
        # The lazy ~GB-scale host pull happens at most once (guarded: two
        # concurrent path reconstructions must not both pull), and a query
        # against a run that never finished cleanly fails with a clear
        # error instead of unpacking None.
        with self._lock:
            if self._tables_host is None:
                if self._tables_dev is None:
                    raise RuntimeError(
                        "no run state to reconstruct paths from (the "
                        "checker did not complete cleanly)"
                    )
                parent_dev, store_dev = self._tables_dev
                n, cap_s, w = (
                    self._n, self._cap_s, self._compiled.state_width,
                )
                self._tables_host = (
                    np.asarray(parent_dev).reshape(n, cap_s),
                    np.asarray(store_dev).reshape(n, cap_s, w),
                )
            parent, store = self._tables_host
        chain: List[int] = []
        g = gid
        while g != NO_GID:
            chain.append(g)
            g = int(parent[g >> self._slot_bits, g & (self._cap_s - 1)])
        chain.reverse()
        fps = [
            self._model.fingerprint(
                self._compiled.decode(
                    store[g >> self._slot_bits, g & (self._cap_s - 1)]
                )
            )
            for g in chain
        ]
        return Path.from_fingerprints(self._model, fps)

    def discoveries(self) -> Dict[str, Path]:
        self.join()
        if self._discoveries_cache is None:
            with self._lock:
                items = list(self._discovery_gids.items())
            self._discoveries_cache = {
                name: self._gid_path(g) for name, g in items
            }
        return dict(self._discoveries_cache)

    def try_discovery(self, name: str) -> Optional[Path]:
        # Non-blocking while the run is live; a failed run surfaces its
        # error through join(), not here.
        if not self._done.is_set() or self._errors:
            return None
        return self.discoveries().get(name)

    def handles(self) -> List[threading.Thread]:
        return [self._thread]

    def is_done(self) -> bool:
        return self._done.is_set()

    def join(self) -> "ShardedTpuChecker":
        self._thread.join()
        if self._errors:
            raise self._errors[0]
        return self
