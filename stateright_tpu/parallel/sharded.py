"""Multi-chip wavefront checking: frontier + visited set sharded over a mesh.

The reference scales with OS threads sharing one DashMap and a job market
(src/job_market.rs, SURVEY §2.7).  The TPU-native analog shards *both* the
frontier and the fingerprint table across chips by fingerprint ownership:

- every fingerprint has one owner shard (a second hash of the fp modulo the
  mesh size), so a local insert on the owner IS the global dedup — no
  cross-chip locking, the moral equivalent of DashMap's hash-sharded locks;
- each wave, every chip expands its local frontier, buckets the successor
  candidates by owner, and exchanges them with a single ``all_to_all`` over
  ICI — the collective replacement for the job market's split_and_push;
- termination and counts are ``psum`` reductions: the frontier is globally
  empty exactly when every shard's insert produced nothing new.

Parent links cross shards, so table entries store a *global id*
(shard << slot_bits | slot); the host walks these across the stacked
per-shard tables for path reconstruction.

Hash-random ownership keeps shards statistically balanced (the job-market
rebalancing analog); skew shows up only as idle lanes in a chunked wave.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Dict, List, Optional

import numpy as np

from ..core.checker import Checker
from ..core.model import Expectation
from ..core.path import Path
from .compiled import CompiledModel, compiled_model_for

NO_GID = 0xFFFFFFFF


def _owner_mix(hi, lo):
    import jax.numpy as jnp

    from ..ops.device_fp import _fmix32, _rotl

    # Independent of both the key planes and the slot hash.
    return _fmix32(lo ^ _rotl(hi, 7) ^ jnp.uint32(0xA511E9B3))


class ShardedTpuChecker(Checker):
    """Wavefront checker running one program per mesh device via shard_map."""

    def __init__(
        self,
        options,
        mesh=None,
        capacity: int = 1 << 20,
        chunk_size: int = 1 << 11,
        dedup_factor: int = 4,
        compiled: Optional[CompiledModel] = None,
    ):
        super().__init__(options.model)
        import jax

        if options._visitor is not None:
            raise ValueError("spawn_tpu_sharded() does not support visitors")
        self._options = options
        self._compiled = compiled or compiled_model_for(options.model)
        if mesh is None:
            mesh = jax.sharding.Mesh(np.array(jax.devices()), ("shards",))
        self._mesh = mesh
        self._n = mesh.devices.size
        # Per-shard capacity: the largest power of two fitting the budget
        # (open addressing needs a power of two; the mesh size need not be).
        self._cap_s = 1 << max(capacity // self._n, 1 << 10).bit_length() - 1
        self._slot_bits = self._cap_s.bit_length() - 1
        # Global ids are shard << slot_bits | slot in one uint32; strict
        # < 32 keeps the all-ones NO_GID sentinel unreachable and the shift
        # from wrapping (shard bits must cover shard n-1, so ceil(log2 n)).
        if self._slot_bits + max(self._n - 1, 1).bit_length() >= 32:
            raise ValueError("capacity too large for 32-bit global ids")
        self._chunk = chunk_size
        self._dedup_factor = dedup_factor
        self._properties = self._model.properties()
        self._ev_indices = [
            i
            for i, p in enumerate(self._properties)
            if p.expectation is Expectation.EVENTUALLY
        ]
        self._discovery_gids: Dict[str, int] = {}
        self._state_count = 0
        self._unique_count = 0
        self._max_depth = 0
        self._done = threading.Event()
        self._errors: List[BaseException] = []
        self._lock = threading.Lock()
        self._tables_host: Optional[tuple] = None

        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # --- device program ------------------------------------------------------

    def _build_wave(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..ops.device_fp import device_fp64
        from .hashset import HashSet, insert_batch
        from .wave_common import compact, wave_eval

        cm = self._compiled
        w = cm.state_width
        a = cm.max_actions
        f = self._chunk
        n = self._n
        cap_s = self._cap_s
        slot_bits = self._slot_bits
        props = self._properties
        n_props = len(props)
        ev_indices = self._ev_indices
        dedup_factor = self._dedup_factor
        b = f * a  # per-shard candidate lanes; also the exchange bucket size

        def wave_shard(key_hi, key_lo, store, parent, ebits, slots, count):
            """One wave on one shard.  Shapes: per-shard views."""
            me = jax.lax.axis_index("shards").astype(jnp.uint32)
            lane = jnp.arange(f, dtype=jnp.uint32)
            active = lane < count[0]
            safe_slots = jnp.where(active, slots, 0)
            states = store[safe_slots]

            # Shared expansion-time evaluation; ids are global this time.
            my_gids = (me << jnp.uint32(slot_bits)) | safe_slots
            disc0 = jnp.full((n_props,), NO_GID, jnp.uint32) | (me & 0)
            cand, eb, nexts, valid, gen_local, step_flag = wave_eval(
                cm, props, ev_indices, states, active, my_gids,
                ebits[safe_slots], disc0,
            )
            generated = jax.lax.psum(gen_local, "shards")
            step_flag_global = (
                jax.lax.psum(step_flag.astype(jnp.uint32), "shards") > 0
            )

            # Bucket candidates by owner shard and exchange over ICI.
            flat = nexts.reshape(b, w)
            flat_valid = valid.reshape(b)
            par_gid = jnp.repeat(my_gids, a)
            child_eb = jnp.repeat(eb, a)
            hi, lo = device_fp64(flat)
            owner = _owner_mix(hi, lo) % jnp.uint32(n)
            key = jnp.where(flat_valid, owner, jnp.uint32(n))
            order = jnp.argsort(key, stable=True)
            key_s = key[order]
            counts = jnp.zeros((n + 1,), jnp.uint32).at[key].add(1)
            offsets = jnp.concatenate(
                [jnp.zeros((1,), jnp.uint32), jnp.cumsum(counts)[:-1]]
            )
            pos = jnp.arange(b, dtype=jnp.uint32) - offsets[key_s]
            dst = jnp.where(key_s < n, key_s, jnp.uint32(n))  # drop invalid

            send_words = jnp.zeros((n, b, w), jnp.uint32)
            send_words = send_words.at[dst, pos].set(flat[order], mode="drop")
            send_gid = jnp.full((n, b), NO_GID, jnp.uint32)
            send_gid = send_gid.at[dst, pos].set(par_gid[order], mode="drop")
            send_eb = jnp.zeros((n, b), jnp.uint32)
            send_eb = send_eb.at[dst, pos].set(child_eb[order], mode="drop")
            send_valid = jnp.zeros((n, b), jnp.bool_)
            send_valid = send_valid.at[dst, pos].set(
                flat_valid[order], mode="drop"
            )

            recv_words = jax.lax.all_to_all(
                send_words, "shards", split_axis=0, concat_axis=0, tiled=False
            )
            recv_gid = jax.lax.all_to_all(
                send_gid, "shards", split_axis=0, concat_axis=0, tiled=False
            )
            recv_eb = jax.lax.all_to_all(
                send_eb, "shards", split_axis=0, concat_axis=0, tiled=False
            )
            recv_valid = jax.lax.all_to_all(
                send_valid, "shards", split_axis=0, concat_axis=0, tiled=False
            )

            # Local insert — the owner's insert IS the global dedup.
            rw = recv_words.reshape(n * b, w)
            rv = recv_valid.reshape(n * b)
            rg = recv_gid.reshape(n * b)
            reb = recv_eb.reshape(n * b)
            rhi, rlo = device_fp64(rw)
            table, slot, is_new, probe_ok, dd_overflow = insert_batch(
                HashSet(key_hi, key_lo), rhi, rlo, rv,
                dedup_factor=dedup_factor,
            )
            sslot = jnp.where(is_new, slot, jnp.uint32(cap_s))
            store = store.at[sslot].set(rw, mode="drop")
            parent = parent.at[sslot].set(rg, mode="drop")
            ebits = ebits.at[sslot].set(reb, mode="drop")

            new_slots = compact(is_new, slot, f * a)
            n_new_local = jnp.sum(is_new, dtype=jnp.uint32)
            n_new_global = jax.lax.psum(n_new_local, "shards")
            probe_global = (
                jax.lax.psum(probe_ok.astype(jnp.uint32), "shards") == n
            )
            dd_global = (
                jax.lax.psum(dd_overflow.astype(jnp.uint32), "shards") > 0
            )
            return (
                table.key_hi,
                table.key_lo,
                store,
                parent,
                ebits,
                new_slots,
                n_new_local[None],
                n_new_global[None],
                generated[None],
                cand,
                probe_global[None],
                dd_global[None],
                step_flag_global[None],
            )

        shard = P("shards")
        specs_table = (shard, shard, shard, shard, shard)
        wave = jax.jit(
            jax.shard_map(
                wave_shard,
                mesh=self._mesh,
                in_specs=specs_table + (shard, shard),
                out_specs=(
                    specs_table
                    + (shard, shard, shard, shard, shard, shard, shard, shard)
                ),
            ),
            donate_argnums=(0, 1, 2, 3, 4),
        )
        return wave

    # --- host loop -----------------------------------------------------------

    def _run(self) -> None:
        try:
            self._check()
        except BaseException as e:
            self._errors.append(e)
        finally:
            self._done.set()

    def _check(self) -> None:
        import time as _time

        import jax
        import jax.numpy as jnp

        from ..ops.device_fp import device_fp64
        from .hashset import insert_batch

        opts = self._options
        cm = self._compiled
        props = self._properties
        n = self._n
        cap_s = self._cap_s
        f = self._chunk
        deadline = (
            _time.monotonic() + opts._timeout if opts._timeout is not None else None
        )

        # Global (host-side numpy) views of the stacked per-shard tables are
        # only pulled at the end; during the run everything stays sharded.
        from jax.sharding import NamedSharding, PartitionSpec as P

        shard = NamedSharding(self._mesh, P("shards"))

        def sharded_zeros(shape, dtype, fill=0):
            arr = jnp.full(shape, fill, dtype)
            return jax.device_put(arr, shard)

        key_hi = sharded_zeros((n * cap_s,), jnp.uint32)
        key_lo = sharded_zeros((n * cap_s,), jnp.uint32)
        store = sharded_zeros((n * cap_s, cm.state_width), jnp.uint32)
        parent = sharded_zeros((n * cap_s,), jnp.uint32, NO_GID)
        ebits = sharded_zeros((n * cap_s,), jnp.uint32)

        # Seed init states host-side: compute owners with the same mix and
        # place each init state in its owner's slice of a seeding program.
        init = cm.init_packed()
        n_init = init.shape[0]
        ih, il = (np.asarray(x) for x in device_fp64(jnp.asarray(init)))
        owner = np.asarray(
            _owner_mix(jnp.asarray(ih), jnp.asarray(il))
        ) % np.uint32(n)
        eb0 = (1 << len(self._ev_indices)) - 1

        # Per-shard seed batches, padded to a common width.
        seed_w = max(int((owner == d).sum()) for d in range(n)) or 1
        seed_states = np.zeros((n, seed_w, cm.state_width), np.uint32)
        seed_valid = np.zeros((n, seed_w), bool)
        for d in range(n):
            idx = np.flatnonzero(owner == d)
            seed_states[d, : len(idx)] = init[idx]
            seed_valid[d, : len(idx)] = True

        from .hashset import HashSet

        def seed_shard(key_hi, key_lo, store, ebits, states, valid):
            from .wave_common import compact

            sts = states[0]
            val = valid[0]
            hi, lo = device_fp64(sts)
            table, slot, is_new, probe_ok, dd_overflow = insert_batch(
                HashSet(key_hi, key_lo), hi, lo, val
            )
            sslot = jnp.where(is_new, slot, jnp.uint32(cap_s))
            store = store.at[sslot].set(sts, mode="drop")
            ebits = ebits.at[sslot].set(jnp.uint32(eb0), mode="drop")
            compacted = compact(is_new, slot, is_new.shape[0])
            ok = probe_ok & ~dd_overflow
            return (
                table.key_hi,
                table.key_lo,
                store,
                ebits,
                compacted,
                jnp.sum(is_new, dtype=jnp.uint32)[None],
                ok[None],
            )

        sp = P("shards")
        seed = jax.jit(
            jax.shard_map(
                seed_shard,
                mesh=self._mesh,
                in_specs=(sp, sp, sp, sp, sp, sp),
                out_specs=(sp, sp, sp, sp, sp, sp, sp),
            ),
            donate_argnums=(0, 1, 2, 3),
        )
        key_hi, key_lo, store, ebits, seed_slots, seed_counts, seed_ok = seed(
            key_hi,
            key_lo,
            store,
            ebits,
            jax.device_put(jnp.asarray(seed_states), shard),
            jax.device_put(jnp.asarray(seed_valid), shard),
        )
        if not np.asarray(seed_ok).all():
            raise RuntimeError(
                "init-state seeding overflowed the insert buffers; raise "
                "capacity or lower dedup_factor"
            )
        seed_slots = np.asarray(seed_slots).reshape(n, seed_w)
        seed_counts = np.asarray(seed_counts).reshape(n)
        frontiers = [seed_slots[d, : seed_counts[d]] for d in range(n)]

        self._state_count = n_init
        self._unique_count = int(seed_counts.sum())

        wave = self._build_wave()
        depth = 0

        while any(len(fr) for fr in frontiers):
            depth += 1
            with self._lock:
                self._max_depth = depth
            if (
                opts._target_max_depth is not None
                and depth >= opts._target_max_depth
            ):
                break
            if deadline is not None and _time.monotonic() >= deadline:
                break

            next_frontiers: List[List[np.ndarray]] = [[] for _ in range(n)]
            stop = False
            n_chunks = max(
                (len(fr) + f - 1) // f for fr in frontiers
            ) or 1
            for ci in range(n_chunks):
                slots_np = np.zeros((n, f), np.uint32)
                counts_np = np.zeros((n, 1), np.uint32)
                for d in range(n):
                    chunk = frontiers[d][ci * f : (ci + 1) * f]
                    slots_np[d, : len(chunk)] = chunk
                    counts_np[d, 0] = len(chunk)
                (
                    key_hi,
                    key_lo,
                    store,
                    parent,
                    ebits,
                    new_slots,
                    n_new_local,
                    n_new_global,
                    generated,
                    cand,
                    probe_ok,
                    dd_overflow,
                    step_flag,
                ) = wave(
                    key_hi,
                    key_lo,
                    store,
                    parent,
                    ebits,
                    jax.device_put(jnp.asarray(slots_np.reshape(-1)), shard),
                    jax.device_put(jnp.asarray(counts_np.reshape(-1)), shard),
                )
                if not np.asarray(probe_ok).all():
                    raise RuntimeError(
                        f"sharded fingerprint table overfull (per-shard "
                        f"capacity {cap_s}); raise capacity"
                    )
                if np.asarray(dd_overflow).any():
                    raise RuntimeError(
                        "a shard received more distinct states in one wave "
                        "than its insert dedup buffer holds; lower "
                        f"dedup_factor (now {self._dedup_factor}) or "
                        "chunk_size"
                    )
                if np.asarray(step_flag).any():
                    raise RuntimeError(
                        "the model step kernel flagged an encoding-capacity "
                        "overflow (a successor exceeded the packed layout's "
                        "bounds); the compiled model's capacity assumptions "
                        "do not hold for this configuration"
                    )
                n_new_local_h = np.asarray(n_new_local).reshape(n)
                new_slots_h = np.asarray(new_slots).reshape(n, -1)
                if (n_new_local_h > new_slots_h.shape[1]).any():
                    raise RuntimeError(
                        "per-shard wave produced more new states than the "
                        "frontier buffer holds; raise chunk_size"
                    )
                for d in range(n):
                    if n_new_local_h[d]:
                        next_frontiers[d].append(
                            new_slots_h[d, : n_new_local_h[d]]
                        )
                with self._lock:
                    self._state_count += int(np.asarray(generated)[0])
                    self._unique_count += int(n_new_local_h.sum())
                cand_h = np.asarray(cand).reshape(n, len(props))
                for d in range(n):
                    for p, prop in enumerate(props):
                        g = int(cand_h[d, p])
                        if g != NO_GID:
                            with self._lock:
                                self._discovery_gids.setdefault(prop.name, g)
                if self._unique_count > (n * cap_s) // 2:
                    raise RuntimeError(
                        "sharded fingerprint table beyond 50% load; raise "
                        "capacity"
                    )
                if opts._finish_when.matches(
                    frozenset(self._discovery_gids), props
                ):
                    stop = True
                    break
                if (
                    opts._target_state_count is not None
                    and opts._target_state_count <= self._state_count
                ):
                    stop = True
                    break
                if deadline is not None and _time.monotonic() >= deadline:
                    stop = True
                    break
            if stop:
                break
            frontiers = [
                np.concatenate(nf) if nf else np.zeros((0,), np.uint32)
                for nf in next_frontiers
            ]

        self._tables_host = (
            np.asarray(parent).reshape(n, cap_s),
            np.asarray(store).reshape(n, cap_s, cm.state_width),
        )

    # --- Checker surface -----------------------------------------------------

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return self._unique_count

    def max_depth(self) -> int:
        return self._max_depth

    def _gid_path(self, gid: int) -> Path:
        parent, store = self._tables_host
        chain: List[int] = []
        g = gid
        while g != NO_GID:
            chain.append(g)
            g = int(parent[g >> self._slot_bits, g & (self._cap_s - 1)])
        chain.reverse()
        fps = [
            self._model.fingerprint(
                self._compiled.decode(
                    store[g >> self._slot_bits, g & (self._cap_s - 1)]
                )
            )
            for g in chain
        ]
        return Path.from_fingerprints(self._model, fps)

    def discoveries(self) -> Dict[str, Path]:
        self.join()
        with self._lock:
            items = list(self._discovery_gids.items())
        return {name: self._gid_path(g) for name, g in items}

    def handles(self) -> List[threading.Thread]:
        return [self._thread]

    def is_done(self) -> bool:
        return self._done.is_set()

    def join(self) -> "ShardedTpuChecker":
        self._thread.join()
        if self._errors:
            raise self._errors[0]
        return self
