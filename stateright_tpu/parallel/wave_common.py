"""Wave-evaluation logic shared by the single-device and sharded engines.

Both engines evaluate the frontier the same way — property conditions at
expansion time (the pop-time analog of src/checker/bfs.rs:230-281),
eventually-bit clearing, successor expansion, and terminal
eventually-counterexample detection — differing only in how a state is
identified (a table slot on one device, a shard<<bits|slot global id across
a mesh).  Keeping it in one place keeps the two engines' discovery
semantics from diverging.
"""

from __future__ import annotations

from typing import NamedTuple

from ..core.model import Expectation

NO_ID = 0xFFFFFFFF


class WaveEval(NamedTuple):
    disc_cand: object  # uint32[P] candidate state-id per property (NO_ID none)
    eb: object  # uint32[F] eventually-bits after this state's own clears
    nexts: object  # uint32[F, A, W] successor candidates
    valid: object  # bool[F, A]
    generated: object  # uint32 scalar: local boundary-passing successors
    step_flag: object  # bool scalar: a successor overflowed the encoding


def finish_when_trivially_true(fw, props) -> bool:
    """Policies that match with zero discoveries (e.g. ALL with no
    properties); only the host-side ``matches()`` check stops those,
    preserving the at-least-one-block-first behavior of the reference."""
    fail_props = [p for p in props if p.expectation.discovery_is_failure]
    return (
        (fw._kind == "all" and not props)
        or (fw._kind == "all_failures" and not fail_props)
        or (fw._kind == "all_of" and not fw._names)
    )


def default_waves_per_call(options) -> int:
    """How many chunks each fused run() call may execute before a host
    sync.  Fidelity knobs that only the host can check (wall-clock timeout,
    target_state_count) and trivially-true finish_when policies force
    one-chunk granularity; everything else — including finish_when, which
    is mirrored on device — runs 256 chunks per sync.  Shared so the
    single-chip and sharded engines cannot drift apart."""
    fine_grained = (
        options._timeout is not None
        or options._target_state_count is not None
        or finish_when_trivially_true(
            options._finish_when, options.model.properties()
        )
    )
    return 1 if fine_grained else 256


def make_finish_when_device(fw, props):
    """Device mirror of ``HasDiscoveries.matches()`` (has_discoveries.py):
    returns ``fn(found: bool[P]) -> bool scalar`` deciding whether the
    policy is satisfied.  Constant-TRUE policies return False here — see
    :func:`finish_when_trivially_true`."""
    n_props = len(props)
    fail_idx = [
        i for i, p in enumerate(props) if p.expectation.discovery_is_failure
    ]
    name_idx = {p.name: i for i, p in enumerate(props)}
    named = [name_idx[n] for n in sorted(fw._names) if n in name_idx]
    names_all_known = all(n in name_idx for n in fw._names)
    kind = fw._kind

    def matched(found):
        import jax.numpy as jnp

        false = jnp.zeros((), jnp.bool_)
        if kind == "all":
            return jnp.all(found) if n_props else false
        if kind == "any":
            return jnp.any(found) if n_props else false
        if kind == "any_failures":
            return jnp.any(found[jnp.asarray(fail_idx)]) if fail_idx else false
        if kind == "all_failures":
            return jnp.all(found[jnp.asarray(fail_idx)]) if fail_idx else false
        if kind == "all_of":
            if not names_all_known or not named:
                return false
            return jnp.all(found[jnp.asarray(named)])
        if kind == "any_of":
            return jnp.any(found[jnp.asarray(named)]) if named else false
        raise ValueError(kind)

    return matched


def two_phase_capable(cm) -> bool:
    """Host-side mirror of :func:`wave_eval`'s two-phase gate: the model
    exposes ``step_valid`` + ``step_lane`` AND is unbounded (``boundary``
    None).  The traced engine loops (wavefront/sharded ``trace=True``)
    use it to pick the matching roofline byte model; keeping it beside
    the trace-time gate keeps the two from drifting."""
    import numpy as np

    if not (hasattr(cm, "step_valid") and hasattr(cm, "step_lane")):
        return False
    return cm.boundary(np.zeros((cm.state_width,), np.uint32)) is None


# --- compile observability (docs/OBSERVABILITY.md "Compile events") ----------
#
# A program-cache MISS is the recompile event the serving layer's
# warm-start story hinges on; these knobs turn misses into attributable
# evidence: each compiled program's FIRST invocation is timed (JAX
# compiles lazily at first call, so that wall time is compile + first
# execution — an upper bound on compile cost, documented as such), the
# knobs that formed the cache key travel as ``provenance`` on the
# journaled ``compile`` event, and a burst of misses inside the storm
# window raises a ``recompile_storms`` counter + a storm-flagged journal
# event (the `watch` verb and CI smoke alert on it).  A storm means the
# key is churning — knob defaults moving under a warm cache, or a
# geometry ladder thrashing — exactly the condition that silently eats a
# "warm" daemon's latency budget.
COMPILE_STORM_WINDOW_SEC = 120.0
COMPILE_STORM_THRESHOLD = 6
_COMPILE_TIMES: list = []  # monotonic stamps of recent first-call compiles
_STORM_ACTIVE = [False]


def _note_compile(now: float) -> bool:
    """Fold one compile stamp into the storm window; True exactly at the
    rising edge (quiet -> storm), so the counter counts storms, not
    compiles."""
    _COMPILE_TIMES.append(now)
    cutoff = now - COMPILE_STORM_WINDOW_SEC
    while _COMPILE_TIMES and _COMPILE_TIMES[0] < cutoff:
        _COMPILE_TIMES.pop(0)
    in_storm = len(_COMPILE_TIMES) >= COMPILE_STORM_THRESHOLD
    rising = in_storm and not _STORM_ACTIVE[0]
    _STORM_ACTIVE[0] = in_storm
    return rising


# Per-cache-entry instrumentation context, REFRESHED on every
# cached_program access (hit or miss) so a wrapper's deferred first
# call attributes the compile to the engine that actually invoked it —
# the builder's journal is never captured permanently (an engine that
# builds but dies before invoking must not receive a later caller's
# compile event into its finished run's record).  Keyed by
# (id(cache), key); entries evicted in lockstep with the cache.
_PROGRAM_CTX: dict = {}


def _record_compile(ctx, sublabel, sec) -> None:
    import logging
    import time

    from ..obs.metrics import GLOBAL, LATENCY_BUCKETS

    label = f"{ctx.get('label', 'program')}{sublabel}"
    GLOBAL.inc("compile_sec_total", sec)
    GLOBAL.set("last_compile_sec", round(sec, 4))
    GLOBAL.observe("compile_sec", sec, boundaries=LATENCY_BUCKETS)
    storm = _note_compile(time.monotonic())
    if storm:
        GLOBAL.inc("recompile_storms")
        logging.getLogger(__name__).warning(
            "recompile storm: %d compiles within %.0fs (latest: %s) — "
            "a program-cache key is churning",
            len(_COMPILE_TIMES), COMPILE_STORM_WINDOW_SEC, label,
        )
    journal = ctx.get("journal")
    if journal is not None:
        fields = {"label": label, "sec": round(sec, 4),
                  "cache_size": ctx.get("cache_size", 0)}
        if ctx.get("provenance"):
            fields["provenance"] = ctx["provenance"]
        if storm:
            fields["storm"] = True
            fields["storm_compiles"] = len(_COMPILE_TIMES)
        journal.append("compile", **fields)


def _timed_first_call(fn, sublabel, ctx):
    """Wrap one compiled callable so its FIRST invocation — where JAX
    actually traces + lowers + compiles — is timed and recorded; later
    calls pay one flag check.  ``ctx`` is the live per-cache-entry
    context (journal/label/provenance), read at FIRE time."""
    import time
    from functools import wraps

    state = [True]

    @wraps(fn)
    def wrapper(*args, **kwargs):
        if state[0]:
            state[0] = False
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            _record_compile(ctx, sublabel, time.perf_counter() - t0)
            return out
        return fn(*args, **kwargs)

    return wrapper


def _instrument_programs(prog, ctx):
    """Wrap every callable in a program (a bare callable, a tuple like
    the single-chip ``(seed, run)`` pair, or the traced-mode dict) —
    each is a distinct XLA program with its own compile."""
    if callable(prog):
        return _timed_first_call(prog, "", ctx)
    if isinstance(prog, tuple):
        return tuple(
            _timed_first_call(p, f"[{i}]", ctx) if callable(p) else p
            for i, p in enumerate(prog)
        )
    if isinstance(prog, dict):
        return {
            k: _timed_first_call(p, f".{k}", ctx) if callable(p) else p
            for k, p in prog.items()
        }
    return prog


def cached_program(cache: dict, max_size: int, key, build,
                   label: str = "program", journal=None, provenance=None):
    """Bounded-FIFO memo for compiled engine programs, shared by the
    single-chip and sharded engines so the key-tuple + eviction idiom
    exists once.  The KEY must cover everything the built closure traces
    over — a stale hit is a silent wrong-program bug.

    Hits and misses count into the process-global metrics registry
    (``program_cache_hits`` / ``program_cache_misses``): the observable
    evidence that a warm repeat of a workload skipped its compiles —
    the checking service's warmup-reuse counter (docs/SERVING.md).

    A miss additionally records COMPILE observability (the helpers
    above): each built callable's first invocation is timed, journaled
    as a ``compile`` event carrying ``label`` and ``provenance`` (the
    human-readable knobs behind the cache key), folded into the
    process-global ``compile_sec_total``/``compile_sec`` metrics, and
    watched by the recompile-storm detector.  ``journal``/``label``/
    ``provenance`` refresh the entry's live context on EVERY access —
    hits included — so a deferred first call journals into the engine
    that actually invoked (and paid for) the compile, never a dead
    builder's record; hits journal nothing themselves (a hit is the
    warm path the evidence exists to prove)."""
    from ..obs.metrics import GLOBAL

    ctx = _PROGRAM_CTX.setdefault((id(cache), key), {})
    ctx.update(label=label, journal=journal, provenance=provenance)
    prog = cache.get(key)
    if prog is None:
        GLOBAL.inc("program_cache_misses")
        ctx["cache_size"] = len(cache) + 1
        prog = _instrument_programs(build(), ctx)
        while len(cache) >= max_size:
            evicted = next(iter(cache))
            cache.pop(evicted)
            _PROGRAM_CTX.pop((id(cache), evicted), None)
        cache[key] = prog
    else:
        GLOBAL.inc("program_cache_hits")
    return prog


def compact(mask, values, size: int):
    """Stream-compact ``values[mask]`` into a ``size``-wide buffer (excess
    dropped; caller checks counts).  One shared definition of the
    cumsum/where/scatter idiom both engines and the hash set rely on."""
    import jax.numpy as jnp

    pos = jnp.cumsum(mask.astype(jnp.uint32)) - 1
    idx = jnp.where(mask, pos, jnp.uint32(size))
    if values.ndim == 1:
        buf = jnp.zeros((size,), values.dtype)
    else:
        buf = jnp.zeros((size,) + values.shape[1:], values.dtype)
    return buf.at[idx].set(values, mode="drop")


def wave_eval(cm, props, ev_indices, states, active, ids, eb_in, disc,
              allow_two_phase: bool = False):
    """The shared wave step (minus dedup/insert, which differs per engine).

    Returns :class:`WaveEval` with ``disc`` already folded (first-writer-
    wins against the incoming ``disc`` vector).  With ``allow_two_phase``
    and a model exposing BOTH ``step_valid`` and ``step_lane``, ``nexts``
    comes back None — the caller constructs successors itself (via
    ``step_lane``) on the compacted valid lanes.
    """
    import jax
    import jax.numpy as jnp

    n_props = len(props)
    always_idx = {
        i for i, p in enumerate(props) if p.expectation is Expectation.ALWAYS
    }
    sometimes_idx = {
        i for i, p in enumerate(props) if p.expectation is Expectation.SOMETIMES
    }

    conds = jax.vmap(cm.property_conds)(states)  # [F, P]
    # "Awaiting discoveries": the reference stops expanding a state when
    # every property already has a discovery and this state contributes
    # none (src/checker/bfs.rs:231-281) — checked against the discoveries
    # as of wave start, the parallel analog of the reference's block-order
    # (and thread-racy) reads.
    discovered0 = [disc[p] != jnp.uint32(NO_ID) for p in range(n_props)]
    awaiting = jnp.zeros(active.shape, jnp.bool_)
    for p in range(n_props):
        if p in always_idx:
            awaiting = awaiting | (~discovered0[p] & conds[:, p])
        elif p in sometimes_idx:
            awaiting = awaiting | (~discovered0[p] & ~conds[:, p])
        else:  # EVENTUALLY: discovered only at trace ends — always awaited
            awaiting = awaiting | ~discovered0[p]
    for p in range(n_props):
        if p in always_idx:
            hit = active & ~conds[:, p]
        elif p in sometimes_idx:
            hit = active & conds[:, p]
        else:
            continue
        idx = jnp.argmax(hit)
        cand = jnp.where(jnp.any(hit), ids[idx], jnp.uint32(NO_ID))
        disc = disc.at[p].set(jnp.where(disc[p] == jnp.uint32(NO_ID), cand, disc[p]))

    # Clear this state's own satisfied eventually bits.
    eb = eb_in
    for bit, p in enumerate(ev_indices):
        eb = eb & ~(conds[:, p].astype(jnp.uint32) << bit)

    # Successor expansion.  Two-phase models answer lane VALIDITY without
    # constructing successors (construction then runs compacted, on the
    # ~5% surviving lanes — the engine's phase B); their per-lane
    # capacity flags surface in phase B instead.
    two_phase = (
        allow_two_phase
        and hasattr(cm, "step_valid")
        and hasattr(cm, "step_lane")
        and cm.boundary(states[0]) is None
    )
    if two_phase:
        nexts = None
        valid = jax.vmap(cm.step_valid)(states)  # [F, A]
        step_flag = jnp.zeros((), jnp.bool_)
    elif getattr(cm, "step_flags", False):
        nexts, valid, lane_flags = jax.vmap(cm.step)(states)
        step_flag = jnp.any(jnp.asarray(lane_flags) & active)
    else:
        nexts, valid = jax.vmap(cm.step)(states)  # [F, A, W], [F, A]
        step_flag = jnp.zeros((), jnp.bool_)
    valid = valid & active[:, None]
    # With zero properties nothing is ever awaited and the reference
    # expands nothing at all — the gate reproduces that too.
    valid = valid & awaiting[:, None]
    if cm.boundary(states[0]) is not None:
        valid = valid & jax.vmap(jax.vmap(cm.boundary))(nexts)
    generated = jnp.sum(valid, dtype=jnp.uint32)

    # Terminal frontier states with leftover ebits -> eventually
    # counterexamples (src/checker/bfs.rs:326-333).
    terminal = active & ~jnp.any(valid, axis=1)
    for bit, p in enumerate(ev_indices):
        hit = terminal & (((eb >> bit) & 1) == 1)
        idx = jnp.argmax(hit)
        cand = jnp.where(jnp.any(hit), ids[idx], jnp.uint32(NO_ID))
        disc = disc.at[p].set(jnp.where(disc[p] == jnp.uint32(NO_ID), cand, disc[p]))

    return WaveEval(disc, eb, nexts, valid, generated, step_flag)
