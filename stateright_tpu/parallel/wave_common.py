"""Wave-evaluation logic shared by the single-device and sharded engines.

Both engines evaluate the frontier the same way — property conditions at
expansion time (the pop-time analog of src/checker/bfs.rs:230-281),
eventually-bit clearing, successor expansion, and terminal
eventually-counterexample detection — differing only in how a state is
identified (a table slot on one device, a shard<<bits|slot global id across
a mesh).  Keeping it in one place keeps the two engines' discovery
semantics from diverging.
"""

from __future__ import annotations

from typing import NamedTuple

from ..core.model import Expectation

NO_ID = 0xFFFFFFFF


class WaveEval(NamedTuple):
    disc_cand: object  # uint32[P] candidate state-id per property (NO_ID none)
    eb: object  # uint32[F] eventually-bits after this state's own clears
    nexts: object  # uint32[F, A, W] successor candidates
    valid: object  # bool[F, A]
    generated: object  # uint32 scalar: local boundary-passing successors
    step_flag: object  # bool scalar: a successor overflowed the encoding


def compact(mask, values, size: int):
    """Stream-compact ``values[mask]`` into a ``size``-wide buffer (excess
    dropped; caller checks counts).  One shared definition of the
    cumsum/where/scatter idiom both engines and the hash set rely on."""
    import jax.numpy as jnp

    pos = jnp.cumsum(mask.astype(jnp.uint32)) - 1
    idx = jnp.where(mask, pos, jnp.uint32(size))
    if values.ndim == 1:
        buf = jnp.zeros((size,), values.dtype)
    else:
        buf = jnp.zeros((size,) + values.shape[1:], values.dtype)
    return buf.at[idx].set(values, mode="drop")


def wave_eval(cm, props, ev_indices, states, active, ids, eb_in, disc):
    """The shared wave step (minus dedup/insert, which differs per engine).

    Returns :class:`WaveEval` with ``disc`` already folded (first-writer-
    wins against the incoming ``disc`` vector).
    """
    import jax
    import jax.numpy as jnp

    n_props = len(props)
    always_idx = {
        i for i, p in enumerate(props) if p.expectation is Expectation.ALWAYS
    }
    sometimes_idx = {
        i for i, p in enumerate(props) if p.expectation is Expectation.SOMETIMES
    }

    conds = jax.vmap(cm.property_conds)(states)  # [F, P]
    for p in range(n_props):
        if p in always_idx:
            hit = active & ~conds[:, p]
        elif p in sometimes_idx:
            hit = active & conds[:, p]
        else:
            continue
        idx = jnp.argmax(hit)
        cand = jnp.where(jnp.any(hit), ids[idx], jnp.uint32(NO_ID))
        disc = disc.at[p].set(jnp.where(disc[p] == jnp.uint32(NO_ID), cand, disc[p]))

    # Clear this state's own satisfied eventually bits.
    eb = eb_in
    for bit, p in enumerate(ev_indices):
        eb = eb & ~(conds[:, p].astype(jnp.uint32) << bit)

    # Successor expansion.
    if getattr(cm, "step_flags", False):
        nexts, valid, lane_flags = jax.vmap(cm.step)(states)
        step_flag = jnp.any(jnp.asarray(lane_flags) & active)
    else:
        nexts, valid = jax.vmap(cm.step)(states)  # [F, A, W], [F, A]
        step_flag = jnp.zeros((), jnp.bool_)
    valid = valid & active[:, None]
    if cm.boundary(states[0]) is not None:
        valid = valid & jax.vmap(jax.vmap(cm.boundary))(nexts)
    generated = jnp.sum(valid, dtype=jnp.uint32)

    # Terminal frontier states with leftover ebits -> eventually
    # counterexamples (src/checker/bfs.rs:326-333).
    terminal = active & ~jnp.any(valid, axis=1)
    for bit, p in enumerate(ev_indices):
        hit = terminal & (((eb >> bit) & 1) == 1)
        idx = jnp.argmax(hit)
        cand = jnp.where(jnp.any(hit), ids[idx], jnp.uint32(NO_ID))
        disc = disc.at[p].set(jnp.where(disc[p] == jnp.uint32(NO_ID), cand, disc[p]))

    return WaveEval(disc, eb, nexts, valid, generated, step_flag)
