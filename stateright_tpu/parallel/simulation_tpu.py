"""Stochastic checking on device: vmapped random trace walks.

The host simulation engine (core/simulation.py, reference
src/checker/simulation.rs) walks one random trace at a time per OS thread.
The TPU form walks a whole *batch* of traces in lockstep — one walker per
vmap lane, each carrying its own PRNG key, fingerprint history (for the
per-trace cycle check), eventually-bits, and discovery latches — with the
entire bounded walk unrolled into a single jitted program per batch.

Semantics mirrored from the host engine:

- properties are evaluated at every counted state; an always-violation or
  sometimes-satisfaction latches the walker's first hit;
- a trace ends at a cycle (the repeated fingerprint joins the path but is
  not counted), a boundary exit, or a terminal state (no action yields a
  successor — uniform choice among valid lanes is exactly the host's
  swap_remove retry loop, which never selects an invalid action);
- leftover eventually-bits at a trace that ended for any of those reasons
  are counterexamples; traces truncated by the depth bound skip that check
  (the host's ``ended_by_depth``, src/checker/simulation.rs:263-272);
- there is no global dedup: ``unique_state_count == state_count``.

Discovery paths are rebuilt host-side from the walker's fingerprint
history via ``Path.from_fingerprints`` — the same host-re-execution
mechanism the wavefront engines use.

Unlike the host engine, walkers within a batch do not see each other's
discoveries mid-trace, so they keep walking where a host thread would
early-exit its trace; that only affects how much work a batch does, never
which discoveries are valid.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..core.checker import Checker
from ..core.model import Expectation
from ..core.path import Path
from .compiled import CompiledModel, compiled_model_for

NO_STEP = 0xFFFFFFFF


def build_walk(compiled, properties, t_max: int, fault_hook=None):
    """One bounded random-trace walk as a pure device function — the
    loop body shared between the Monte-Carlo checker (below, no hook)
    and the chaos-ensemble engine (``ensemble/engine.py``), which
    supplies a ``fault_hook`` masking deliverable lanes by each
    member's fault schedule.

    Hook contract (both methods traced inside the jitted walk):

    - ``fault_hook.init(params)`` -> a carry pytree (per-walk arrays,
      e.g. per-link datagram counters);
    - ``fault_hook.apply(t, state, valid, carry, params)`` ->
      ``(valid, carry)`` — runs after the step kernel's valid mask and
      before the uniform lane choice, so masked lanes are never
      selected (a fully-masked step ends the trace as terminal).

    With ``fault_hook=None`` the emitted program is the checker's
    original walk, unchanged, and the returned callable takes ``key``
    alone; with a hook it takes ``(key, params)`` and both are vmapped
    by the caller.

    Returns ``walk -> (trace, disc, counted, appended, flag)`` where
    ``disc[p]`` is the trace index of property ``p``'s first discovery
    (``NO_STEP`` if none), ``counted`` the states this walk counted,
    ``appended`` the trace length, and ``flag`` the step kernel's
    encoding-overflow alarm.
    """
    import jax
    import jax.numpy as jnp

    from ..core.model import Expectation
    from ..ops.device_fp import device_fp64

    cm = compiled
    props = properties
    n_props = len(props)
    ev_indices = [
        i
        for i, p in enumerate(props)
        if p.expectation is Expectation.EVENTUALLY
    ]
    always_idx = {
        i for i, p in enumerate(props) if p.expectation is Expectation.ALWAYS
    }
    sometimes_idx = {
        i
        for i, p in enumerate(props)
        if p.expectation is Expectation.SOMETIMES
    }
    eb0 = (1 << len(ev_indices)) - 1
    has_flags = getattr(cm, "step_flags", False)

    init = cm.init_packed()
    n_init = init.shape[0]
    init_dev = jnp.asarray(init)
    has_boundary = cm.boundary(init_dev[0]) is not None

    def walk(key, params=None):
        u = jnp.uint32
        key, sub = jax.random.split(key)
        state0 = init_dev[jax.random.randint(sub, (), 0, n_init)]
        hook_carry = fault_hook.init(params) if fault_hook is not None else ()

        def body(t, carry):
            (
                state,
                fps_hi,
                fps_lo,
                trace,
                ebits,
                disc,
                done,
                counted,
                appended,
                flag,
                hook_carry,
                key,
            ) = carry
            active = ~done
            if has_boundary:
                in_bound = cm.boundary(state)
            else:
                in_bound = jnp.ones((), jnp.bool_)
            end_boundary = active & ~in_bound

            hi, lo = device_fp64(state[: cm.fp_words or cm.state_width])
            seen = jnp.any(
                (fps_hi == hi)
                & (fps_lo == lo)
                & (jnp.arange(t_max, dtype=u) < appended)
            )
            do_append = active & ~end_boundary
            idx = jnp.where(do_append, appended, u(t_max))
            fps_hi = fps_hi.at[idx].set(hi, mode="drop")
            fps_lo = fps_lo.at[idx].set(lo, mode="drop")
            trace = trace.at[idx].set(state, mode="drop")
            appended = appended + do_append.astype(u)
            end_cycle = do_append & seen
            count_this = do_append & ~seen
            counted = counted + count_this.astype(u)

            conds = cm.property_conds(state)
            here = appended - u(1)  # index of this state's fp
            for p in range(n_props):
                if p in always_idx:
                    hit = count_this & ~conds[p]
                elif p in sometimes_idx:
                    hit = count_this & conds[p]
                else:
                    continue
                cand = jnp.where(hit, here, u(NO_STEP))
                disc = disc.at[p].set(
                    jnp.where(disc[p] == u(NO_STEP), cand, disc[p])
                )
            for bit, p in enumerate(ev_indices):
                ebits = ebits & ~(
                    (count_this & conds[p]).astype(u) << bit
                )

            if has_flags:
                nexts, valid, sf = cm.step(state)
                flag = flag | (sf & count_this)
            else:
                nexts, valid = cm.step(state)
            valid = valid & count_this
            if fault_hook is not None:
                valid, hook_carry = fault_hook.apply(
                    t, state, valid, hook_carry, params
                )
            v = jnp.sum(valid, dtype=u)
            terminal = count_this & (v == u(0))
            key, sub = jax.random.split(key)
            j = jax.random.randint(sub, (), 0, jnp.maximum(v, u(1)))
            lane = jnp.argmax(jnp.cumsum(valid.astype(u)) == j + u(1))
            advance = count_this & (v > u(0))
            state = jnp.where(advance, nexts[lane], state)
            done = done | end_boundary | end_cycle | terminal
            return (
                state,
                fps_hi,
                fps_lo,
                trace,
                ebits,
                disc,
                done,
                counted,
                appended,
                flag,
                hook_carry,
                key,
            )

        carry = (
            state0,
            jnp.zeros((t_max,), jnp.uint32),
            jnp.zeros((t_max,), jnp.uint32),
            jnp.zeros((t_max, cm.state_width), jnp.uint32),
            jnp.uint32(eb0),
            jnp.full((n_props,), NO_STEP, jnp.uint32),
            jnp.zeros((), jnp.bool_),
            jnp.uint32(0),
            jnp.uint32(0),
            jnp.zeros((), jnp.bool_),
            hook_carry,
            key,
        )
        (
            _state,
            fps_hi,
            fps_lo,
            trace,
            ebits,
            disc,
            done,
            counted,
            appended,
            flag,
            _hook_carry,
            _key,
        ) = jax.lax.fori_loop(0, t_max, body, carry)

        # Trace truncated by the depth bound (never ended): skip the
        # leftover-eventually check, like the host's ended_by_depth.
        u = jnp.uint32
        for bit, p in enumerate(ev_indices):
            left = done & (((ebits >> bit) & u(1)) == u(1))
            cand = jnp.where(left, appended - u(1), u(NO_STEP))
            disc = disc.at[p].set(
                jnp.where(disc[p] == u(NO_STEP), cand, disc[p])
            )
        return trace, disc, counted, appended, flag

    if fault_hook is None:
        return lambda key: walk(key)
    return walk


class TpuSimulationChecker(Checker):
    """Monte-carlo checker running ``walkers`` traces per device batch."""

    def __init__(
        self,
        options,
        seed: int,
        walkers: int = 1024,
        max_trace_len: Optional[int] = None,
        device=None,
        compiled: Optional[CompiledModel] = None,
    ):
        super().__init__(options.model)
        import jax

        if options._visitor is not None:
            raise ValueError(
                "spawn_tpu_simulation() does not support visitors"
            )
        if options._symmetry is not None:
            raise ValueError(
                "spawn_tpu_simulation() does not support symmetry reduction"
            )
        self._options = options
        self._seed = seed
        self._walkers = walkers
        # The device walk is bounded; target_max_depth (if set) is exactly
        # the host's depth bound, otherwise a generous default.
        self._t = max_trace_len or options._target_max_depth or 256
        self._device = device or jax.devices()[0]
        self._compiled = compiled or compiled_model_for(options.model)
        self._properties = self._model.properties()
        self._ev_indices = [
            i
            for i, p in enumerate(self._properties)
            if p.expectation is Expectation.EVENTUALLY
        ]
        self._state_count = 0
        self._max_depth = 0
        self._discovery_fps: Dict[str, List[int]] = {}
        self._discoveries_cache: Optional[Dict[str, Path]] = None
        self._shutdown = threading.Event()
        self._done = threading.Event()
        self._errors: List[BaseException] = []
        self._lock = threading.Lock()

        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # --- device program ------------------------------------------------------

    def _build_batch(self):
        import jax

        walk = build_walk(self._compiled, self._properties, self._t)
        return jax.jit(jax.vmap(walk))

    # --- host loop -----------------------------------------------------------

    def _run(self) -> None:
        try:
            self._check()
        except BaseException as e:
            self._errors.append(e)
        finally:
            self._done.set()

    def _check(self) -> None:
        import jax

        opts = self._options
        props = self._properties
        deadline = (
            time.monotonic() + opts._timeout if opts._timeout is not None else None
        )

        with jax.default_device(self._device):
            batch = self._build_batch()
            base = jax.random.PRNGKey(self._seed)
            round_idx = 0
            while not self._shutdown.is_set():
                keys = jax.vmap(
                    lambda w: jax.random.fold_in(
                        jax.random.fold_in(base, round_idx), w
                    )
                )(np.arange(self._walkers))
                trace_dev, disc_dev, counted_dev, appended_dev, flag_dev = (
                    batch(keys)
                )
                disc = np.asarray(disc_dev)
                counted = np.asarray(counted_dev)
                appended = np.asarray(appended_dev)
                if bool(np.asarray(flag_dev).any()):
                    raise RuntimeError(
                        "the model step kernel flagged an encoding-capacity "
                        "overflow during a simulated trace"
                    )
                # Packed-state traces are pulled per discovered walker only
                # (one [T, W] row, not the whole batch — readback is the
                # expensive part on tunneled devices).
                with self._lock:
                    self._state_count += int(counted.sum())
                    self._max_depth = max(
                        self._max_depth, int(appended.max(initial=0))
                    )
                    for p, prop in enumerate(props):
                        if prop.name in self._discovery_fps:
                            continue
                        hits = np.flatnonzero(disc[:, p] != NO_STEP)
                        if len(hits):
                            wkr = int(hits[0])
                            end = int(disc[wkr, p]) + 1
                            row = np.asarray(trace_dev[wkr, :end])
                            fps = [
                                self._model.fingerprint(
                                    self._compiled.decode(row[i])
                                )
                                for i in range(end)
                            ]
                            self._discovery_fps[prop.name] = fps
                round_idx += 1
                if opts._finish_when.matches(
                    frozenset(self._discovery_fps), props
                ):
                    return
                if (
                    opts._target_state_count is not None
                    and opts._target_state_count <= self._state_count
                ):
                    return
                if deadline is not None and time.monotonic() >= deadline:
                    return

    # --- Checker surface -----------------------------------------------------

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        # No global visited set, matching the host simulation engine.
        return self._state_count

    def max_depth(self) -> int:
        return self._max_depth

    def discoveries(self) -> Dict[str, Path]:
        self.join()
        if self._discoveries_cache is None:
            with self._lock:
                items = list(self._discovery_fps.items())
            self._discoveries_cache = {
                name: Path.from_fingerprints(self._model, fps)
                for name, fps in items
            }
        return dict(self._discoveries_cache)

    def try_discovery(self, name: str) -> Optional[Path]:
        # Non-blocking while the run is live; a failed run surfaces its
        # error through join(), not here.
        if not self._done.is_set() or self._errors:
            return None
        return self.discoveries().get(name)

    def handles(self) -> List[threading.Thread]:
        return [self._thread]

    def is_done(self) -> bool:
        return self._done.is_set()

    def shutdown(self) -> None:
        """Stop after the in-flight batch: without this, a run whose
        ``finish_when`` never matches and that has neither ``timeout`` nor
        ``target_state_count`` would walk forever (the host engine's
        ``_shutdown`` event, core/simulation.py)."""
        self._shutdown.set()

    def request_stop(self) -> None:
        super().request_stop()
        self._shutdown.set()

    def join(self) -> "TpuSimulationChecker":
        self._thread.join()
        if self._errors:
            raise self._errors[0]
        return self
