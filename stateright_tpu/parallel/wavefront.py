"""The TPU wavefront checker.

This replaces the reference's thread-pool hot loop (pop job → evaluate
properties → expand successors → dedup insert → push, src/checker/
bfs.rs:177-335) with a *wavefront* BFS: the entire frontier is expanded at
once by a vmapped step kernel, deduplicated by a batched insert-if-absent
into an HBM-resident fingerprint table, and property conditions are fused
predicates over the whole wave.  One jitted program per wave chunk; the
host loop only orchestrates chunking, early exit, and discovery
bookkeeping.

Semantics parity with the host engine (core/engine.py):

- properties are evaluated when a unique state is *expanded* (the analog of
  pop-time evaluation), so states beyond ``target_max_depth`` or after an
  early exit are never evaluated — matching src/checker/bfs.rs:230-281;
- ``state_count`` counts boundary-passing generated successors pre-dedup
  plus init states; ``unique_state_count`` counts table insertions;
- eventually-bits travel with each table entry (parent's remaining bits),
  are cleared by the state's own satisfied conditions at expansion, and
  leftover bits at a terminal state (no valid successors) become
  counterexamples; the reference's documented join false-negative (ebits
  not part of the dedup key, src/checker/bfs.rs:295-315) is reproduced:
  first inserter's bits win;
- discoveries are first-writer-wins in deterministic wave order; paths are
  reconstructed by walking the parent-slot chain, decoding packed states,
  and re-executing the host model (core/path.py).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.checker import Checker
from ..core.model import Expectation
from ..core.path import Path
from .compiled import CompiledModel, compiled_model_for

NO_SLOT_HOST = 0xFFFFFFFF


class TpuChecker(Checker):
    """Single-device wavefront checker behind the standard Checker surface."""

    def __init__(
        self,
        options,
        capacity: int = 1 << 20,
        chunk_size: int = 1 << 13,
        device=None,
        compiled: Optional[CompiledModel] = None,
    ):
        super().__init__(options.model)
        import jax

        if options._visitor is not None:
            # The wavefront never materializes per-state paths during the
            # run; failing beats silently skipping the visits spawn_bfs
            # would have made.
            raise ValueError(
                "spawn_tpu() does not support visitors; use spawn_bfs()/"
                "spawn_dfs() for visitor-instrumented runs"
            )
        self._options = options
        self._compiled = compiled or compiled_model_for(options.model)
        self._capacity = capacity
        self._chunk = chunk_size
        self._device = device or jax.devices()[0]
        self._properties = self._model.properties()
        if len(self._properties) > 32:
            raise ValueError("at most 32 properties supported on device")
        self._ev_indices = [
            i
            for i, p in enumerate(self._properties)
            if p.expectation is Expectation.EVENTUALLY
        ]
        self._discovery_slots: Dict[str, int] = {}
        self._state_count = 0
        self._unique_count = 0
        self._max_depth = 0
        self._done = threading.Event()
        self._errors: List[BaseException] = []
        self._lock = threading.Lock()
        self._tables_host: Optional[tuple] = None  # (parent, states) np arrays

        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # --- device program ------------------------------------------------------

    def _build_wave(self):
        import jax
        import jax.numpy as jnp

        from ..ops.device_fp import device_fp64
        from .hashset import HashSet, NO_SLOT, insert_batch

        cm = self._compiled
        w = cm.state_width
        a = cm.max_actions
        f = self._chunk
        props = self._properties
        n_props = len(props)
        ev_indices = self._ev_indices
        always_idx = [
            i for i, p in enumerate(props) if p.expectation is Expectation.ALWAYS
        ]
        sometimes_idx = [
            i for i, p in enumerate(props) if p.expectation is Expectation.SOMETIMES
        ]
        step = cm.step
        prop_conds = cm.property_conds
        boundary = cm.boundary

        @partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
        def wave(key_hi, key_lo, store, parent, ebits, slots, count):
            """Expand one frontier chunk.

            key_hi/key_lo: uint32[capacity] fingerprint planes.
            store: uint32[capacity, W] packed states; parent: uint32[capacity]
            predecessor slots; ebits: uint32[capacity] remaining
            eventually-bits.  slots: uint32[F] frontier chunk (table slots);
            count: number of valid lanes.
            """
            lane = jnp.arange(f, dtype=jnp.uint32)
            active = lane < count
            safe_slots = jnp.where(active, slots, 0)
            states = store[safe_slots]  # [F, W]

            # Property evaluation at expansion (pop-time analog).
            conds = jax.vmap(prop_conds)(states)  # [F, P]
            cand = []
            for p in range(n_props):
                if p in always_idx:
                    hit = active & ~conds[:, p]
                elif p in sometimes_idx:
                    hit = active & conds[:, p]
                else:
                    hit = jnp.zeros((f,), jnp.bool_)
                idx = jnp.argmax(hit)
                cand.append(jnp.where(jnp.any(hit), safe_slots[idx], NO_SLOT))
            prop_cand = jnp.stack(cand) if cand else jnp.zeros((0,), jnp.uint32)

            # Clear this state's own satisfied eventually bits.
            eb = ebits[safe_slots]
            for bit, p in enumerate(ev_indices):
                eb = eb & ~(conds[:, p].astype(jnp.uint32) << bit)

            # Successor expansion.
            nexts, valid = jax.vmap(step)(states)  # [F, A, W], [F, A]
            valid = valid & active[:, None]
            if boundary(states[0]) is not None:
                inb = jax.vmap(jax.vmap(boundary))(nexts)
                valid = valid & inb
            generated = jnp.sum(valid, dtype=jnp.uint32)

            # Terminal frontier states with leftover ebits -> eventually
            # counterexamples (src/checker/bfs.rs:326-333).
            terminal = active & ~jnp.any(valid, axis=1)
            ev_cand = []
            for bit, _p in enumerate(ev_indices):
                hit = terminal & (((eb >> bit) & 1) == 1)
                idx = jnp.argmax(hit)
                ev_cand.append(jnp.where(jnp.any(hit), safe_slots[idx], NO_SLOT))
            ev_cand = (
                jnp.stack(ev_cand) if ev_cand else jnp.zeros((0,), jnp.uint32)
            )

            # Dedup + insert.
            flat = nexts.reshape(f * a, w)
            flat_valid = valid.reshape(f * a)
            par = jnp.repeat(safe_slots, a)
            child_eb = jnp.repeat(eb, a)
            hi, lo = device_fp64(flat)
            table, slot, is_new, ok = insert_batch(
                HashSet(key_hi, key_lo), hi, lo, flat_valid
            )
            sslot = jnp.where(is_new, slot, jnp.uint32(self._capacity))
            store = store.at[sslot].set(flat, mode="drop")
            parent = parent.at[sslot].set(par, mode="drop")
            ebits = ebits.at[sslot].set(child_eb, mode="drop")

            # Compact new slots to the front (stable: preserves wave order).
            order = jnp.argsort(~is_new, stable=True)
            new_slots = slot[order]
            n_new = jnp.sum(is_new, dtype=jnp.uint32)
            return (
                table.key_hi,
                table.key_lo,
                store,
                parent,
                ebits,
                new_slots,
                n_new,
                generated,
                prop_cand,
                ev_cand,
                ok,
            )

        return wave

    # --- host loop -----------------------------------------------------------

    def _run(self) -> None:
        try:
            self._check()
        except BaseException as e:  # propagate at join, like the host engine
            self._errors.append(e)
        finally:
            self._done.set()

    def _check(self) -> None:
        import time as _time

        import jax
        import jax.numpy as jnp

        from ..ops.device_fp import device_fp64
        from .hashset import insert_batch, make_hashset

        opts = self._options
        cm = self._compiled
        props = self._properties
        cap = self._capacity
        f = self._chunk
        a = cm.max_actions
        deadline = (
            _time.monotonic() + opts._timeout if opts._timeout is not None else None
        )

        with jax.default_device(self._device):
            table = make_hashset(cap)
            store = jnp.zeros((cap, cm.state_width), jnp.uint32)
            parent = jnp.full((cap,), NO_SLOT_HOST, jnp.uint32)
            ebits = jnp.zeros((cap,), jnp.uint32)

            # Seed init states.
            init = cm.init_packed()
            n_init = init.shape[0]
            if n_init > f:
                raise ValueError("more init states than chunk_size")
            pad = np.zeros((f - n_init, cm.state_width), np.uint32)
            init_padded = jnp.asarray(np.concatenate([init, pad]))
            hi, lo = device_fp64(init_padded)
            seed_active = jnp.arange(f) < n_init
            table, slot, is_new, ok = insert_batch(table, hi, lo, seed_active)
            sslot = jnp.where(is_new, slot, jnp.uint32(cap))
            store = store.at[sslot].set(init_padded, mode="drop")
            eb0 = (1 << len(self._ev_indices)) - 1
            ebits = ebits.at[sslot].set(jnp.uint32(eb0), mode="drop")
            order = jnp.argsort(~is_new, stable=True)
            frontier = np.asarray(slot[order])[: int(jnp.sum(is_new))]

            self._state_count = n_init
            self._unique_count = len(frontier)

            wave = self._build_wave()
            depth = 0
            key_hi, key_lo = table.key_hi, table.key_lo

            while len(frontier) > 0:
                depth += 1
                with self._lock:
                    self._max_depth = depth
                if (
                    opts._target_max_depth is not None
                    and depth >= opts._target_max_depth
                ):
                    break
                if deadline is not None and _time.monotonic() >= deadline:
                    break

                next_frontier: List[np.ndarray] = []
                stop = False
                for off in range(0, len(frontier), f):
                    chunk = frontier[off : off + f]
                    n = len(chunk)
                    chunk = np.pad(chunk, (0, f - n)).astype(np.uint32)
                    (
                        key_hi,
                        key_lo,
                        store,
                        parent,
                        ebits,
                        new_slots,
                        n_new,
                        generated,
                        prop_cand,
                        ev_cand,
                        ok,
                    ) = wave(
                        key_hi,
                        key_lo,
                        store,
                        parent,
                        ebits,
                        jnp.asarray(chunk),
                        jnp.uint32(n),
                    )
                    if not bool(ok):
                        raise RuntimeError(
                            f"fingerprint table overfull (capacity {cap}); "
                            "raise spawn_tpu(capacity=...)"
                        )
                    n_new_i = int(n_new)
                    with self._lock:
                        self._state_count += int(generated)
                        self._unique_count += n_new_i
                    if n_new_i:
                        next_frontier.append(np.asarray(new_slots[:n_new_i]))
                    # First-writer-wins discovery bookkeeping, deterministic
                    # in wave order.
                    prop_cand_h = np.asarray(prop_cand)
                    for p, prop in enumerate(props):
                        if prop.expectation is Expectation.EVENTUALLY:
                            continue
                        s = int(prop_cand_h[p])
                        if s != NO_SLOT_HOST:
                            with self._lock:
                                self._discovery_slots.setdefault(prop.name, s)
                    ev_cand_h = np.asarray(ev_cand)
                    for bit, p in enumerate(self._ev_indices):
                        s = int(ev_cand_h[bit])
                        if s != NO_SLOT_HOST:
                            with self._lock:
                                self._discovery_slots.setdefault(props[p].name, s)

                    if self._unique_count > cap // 2:
                        raise RuntimeError(
                            f"fingerprint table beyond 50% load (capacity {cap});"
                            " raise spawn_tpu(capacity=...)"
                        )
                    if opts._finish_when.matches(
                        frozenset(self._discovery_slots), props
                    ):
                        stop = True
                        break
                    if (
                        opts._target_state_count is not None
                        and opts._target_state_count <= self._state_count
                    ):
                        stop = True
                        break
                    if deadline is not None and _time.monotonic() >= deadline:
                        stop = True
                        break
                if stop:
                    break
                frontier = (
                    np.concatenate(next_frontier)
                    if next_frontier
                    else np.zeros((0,), np.uint32)
                )

            # Pull what path reconstruction needs to the host once.
            self._tables_host = (np.asarray(parent), np.asarray(store))

    # --- Checker surface -----------------------------------------------------

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return self._unique_count

    def max_depth(self) -> int:
        return self._max_depth

    def _slot_path(self, slot: int) -> Path:
        parent, store = self._tables_host
        chain: List[int] = []
        s = slot
        while s != NO_SLOT_HOST:
            chain.append(s)
            s = int(parent[s])
        chain.reverse()
        fps = [
            self._model.fingerprint(self._compiled.decode(store[s])) for s in chain
        ]
        return Path.from_fingerprints(self._model, fps)

    def discoveries(self) -> Dict[str, Path]:
        self.join()
        with self._lock:
            items = list(self._discovery_slots.items())
        return {name: self._slot_path(slot) for name, slot in items}

    def handles(self) -> List[threading.Thread]:
        return [self._thread]

    def is_done(self) -> bool:
        return self._done.is_set()

    def join(self) -> "TpuChecker":
        self._thread.join()
        if self._errors:
            raise self._errors[0]
        return self
