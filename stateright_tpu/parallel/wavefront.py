"""The TPU wavefront checker.

This replaces the reference's thread-pool hot loop (pop job → evaluate
properties → expand successors → dedup insert → push, src/checker/
bfs.rs:177-335) with a *wavefront* BFS: the entire frontier is expanded at
once by a vmapped step kernel, deduplicated by a batched insert-if-absent
into an HBM-resident fingerprint table, and property conditions are fused
predicates over the whole wave.

The whole wave loop runs on device inside one ``lax.while_loop`` program —
the append-only state-row log, visited table, counters, and discovery slots
all live in HBM, and the host reads back a handful of scalars every
``waves_per_call`` waves.  States are identified by *BFS position* (the
order of first discovery): positions within a level are contiguous, so the
frontier read and the new-state append are contiguous block transfers, and
the only randomly-indexed memory is the fingerprint hash table.
This matters doubly on hardware reached through a network tunnel: the
chunked-dispatch version spent ~95% of wall-clock on per-wave host↔device
round trips.

Semantics parity with the host engine (core/engine.py):

- properties are evaluated when a unique state is *expanded* (the analog of
  pop-time evaluation), so states beyond ``target_max_depth`` or after an
  early exit are never evaluated — matching src/checker/bfs.rs:230-281;
- ``state_count`` counts boundary-passing generated successors pre-dedup
  plus init states; ``unique_state_count`` counts table insertions;
- eventually-bits travel with each table entry (parent's remaining bits),
  are cleared by the state's own satisfied conditions at expansion, and
  leftover bits at a terminal state (no valid successors) become
  counterexamples; the reference's documented join false-negative (ebits
  not part of the dedup key, src/checker/bfs.rs:295-315) is reproduced:
  first inserter's bits win;
- discoveries are first-writer-wins in deterministic wave order; paths are
  reconstructed by walking the parent-slot chain, decoding packed states,
  and re-executing the host model (core/path.py);
- with ``symmetry()`` and a canon-capable compiled model (parallel/
  canon.py), dedup keys on the fingerprint of the CANONICAL row while the
  row log stores the original — the device form of the reference DFS's
  dedup-on-representative/continue-with-original (src/checker/
  dfs.rs:309-334); counts are traversal-invariant because the canon spec
  sorts full records (docs/SYMMETRY.md).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Dict, List, Optional

import numpy as np

from ..core.checker import Checker
from ..core.model import Expectation
from ..core.path import Path
from .compiled import CompiledModel, compiled_model_for

NO_SLOT_HOST = 0xFFFFFFFF

# Layout of the stats vector — the ONE array the host reads back per run()
# call (each distinct readback through a tunneled device is a full network
# round trip).  Used by run()/seed()/the resume builder/the host loop; a
# new slot must be added here, nowhere else.
(
    STAT_LEVEL_START,
    STAT_LEVEL_END,
    STAT_TAIL,
    STAT_SC_LO,
    STAT_SC_HI,
    STAT_UNIQUE,
    STAT_DEPTH,
    STAT_FLAGS,
) = range(8)
STAT_DISC = 8  # disc[P] rides at [STAT_DISC : STAT_DISC + n_props]

# Auto-tune growth bounds: the table's key planes cost 8 bytes a slot
# (2 GiB at the cap, plus a transient claim plane per insert) and the row
# log 4*state_width a position; growth stops at these bounds and the
# overflow surfaces as the ordinary loud RuntimeError.
_MAX_TABLE_CAPACITY = 1 << 28
_ROW_LOG_BYTE_BUDGET = 8 << 30
# Empirical device limits on the per-wave compact/dedup buffer
# U = unique_buffer_size(max_frontier * max_actions, dedup_factor): the
# v5e worker hard-CRASHES mid-wave ("kernel fault") instead of flagging
# when the buffer is too big, and the band depends on the state width.
# Validated safe / crash points (2026-07-31):
#   w=2  (2pc rm=10):  426K lanes safe, 1.7M lanes crash
#   w=42 (paxos c=3):  262K lanes safe (the headline's steady geometry)
#   w=77 (paxos c=6):  65K lanes safe, 524K lanes crash
# Two caps reproduce all five points: lanes <= the validated 426K AND
# lane-words (U*w) <= the validated 11M.  When auto-tune relaxes
# dedup_factor it halves max_frontier until U fits; halving the frontier
# alone cannot fix a dedup overflow (valid density is scale-free), but
# dd=1 can never overflow, so dd=1 plus a clamped frontier always
# terminates the growth sequence.
_MAX_UNIQUE_BUFFER = 425_984
_MAX_UNIQUE_LANE_WORDS = 11_010_048


def max_safe_unique_lanes(state_width: int) -> int:
    """The device-safe cap on the compact/dedup buffer's lane count for
    a model of this state width (see the validated points above)."""
    return min(
        _MAX_UNIQUE_BUFFER, _MAX_UNIQUE_LANE_WORDS // max(state_width, 1)
    )


class _OverflowRetry(Exception):
    """Internal: seed-time overflow aborted the run before any wave;
    auto-tune may restart the (empty) run with grown knobs."""

    def __init__(self, flag: int, message: str):
        super().__init__(message)
        self.flag = flag
        self.message = message


def _device_owned(x):
    """Force a host-uploaded array into a DEVICE-OWNED buffer before it
    ever reaches a donating program call.  ``jnp.asarray`` of a host
    numpy array may zero-copy borrow the host buffer on the CPU backend;
    DONATING such a borrowed buffer corrupts the run (observed on
    resumed runs in fresh processes with a warm persistent compile
    cache: previously-visited states re-inserted as new — 8417 "unique"
    states on the 1568-state 2pc(4) — or garbage parent chains at path
    reconstruction).  The eager elementwise add cannot be elided and
    materializes an XLA-owned output buffer that is safe to donate; a
    resume pays it once per array."""
    import jax.numpy as jnp

    return x + jnp.zeros((), x.dtype)


def _resize_flat(arr, new_len: int, fill):
    """Resize a flat device array, preserving the (new-length-bounded)
    prefix — the auto-tune path.  Shrink happens when a dedup-overflow
    growth halves ``max_frontier`` and with it the append-block pad; the
    committed log prefix is always shorter than the new length.

    Copy-growth unavoidably holds old + new live at once (donation cannot
    alias buffers of different sizes); the ×2 row-log growth step keeps
    the transient peak at 3× the old log, and the caller drops its last
    reference to the old array on return."""
    import jax
    import jax.numpy as jnp

    if new_len <= arr.shape[0]:
        return arr[:new_len]
    out = jnp.full((new_len,), fill, arr.dtype)
    return jax.lax.dynamic_update_slice(out, arr, (0,))

def snapshot_engine_key(cm, properties, symmetric: bool) -> str:
    """Process-stable compatibility key for engine snapshots.
    Deliberately avoids ``cache_key()`` (whose default embeds
    ``repr(model)``, which is identity-based for some models and would
    spuriously reject resumes in a new process); the packed init states
    hash in the model configuration instead.  Table/log geometry is NOT
    part of the key — a resume adopts the snapshot's persisted sizes
    (which may have been auto-tuned mid-run past the spawn arguments).
    Module-level (rather than a checker method) so the incremental
    store (incr/) can pre-check that a stored snapshot is seedable for
    a new spec WITHOUT spawning a checker that would die loudly on the
    mismatch."""
    import hashlib

    init_digest = hashlib.sha256(
        cm.init_packed().tobytes()
    ).hexdigest()[:16]
    return repr(
        (
            "rowlog-v3",  # flat row log + decoupled log_capacity (r4)
            type(cm).__qualname__,
            cm.state_width,
            cm.max_actions,
            tuple(p.name for p in properties),
            init_digest,
        )
        # A symmetry run's table holds CANONICAL fingerprints — not
        # resumable as a plain run (or vice versa).  Appended only
        # when on, so existing non-sym snapshots stay valid.
        + (("sym",) if symmetric else ())
    )


# Compiled device programs shared across checker instances (keyed by
# CompiledModel.cache_key() + engine shape knobs): re-tracing and re-jitting
# per spawn_tpu() call would otherwise dominate wall-clock.  Bounded FIFO:
# models with identity-repr cache keys would otherwise leak one program
# pair per spawn_tpu() call in long-lived processes.
_PROGRAM_CACHE: dict = {}
_PROGRAM_CACHE_MAX = 32


class TpuChecker(Checker):
    """Single-device wavefront checker behind the standard Checker surface."""

    def __init__(
        self,
        options,
        capacity: int = 1 << 20,
        max_frontier: int = 1 << 15,  # per-chunk batch size, not a level cap
        # 8 measured fastest for the sparse-valid protocol models (paxos3:
        # 557k vs 353k uniq/s at dedup_factor=4, r5 probe) — it sizes the
        # valid-lane compaction buffer, which the probe rounds sweep.
        # Dense-valid models trip flag 4 and auto-tune relaxes toward 1,
        # so the default only changes their discovery path, not their
        # final geometry; batches under the 16K buffer floor never see it.
        dedup_factor: int = 8,
        sort_lanes: Optional[int] = None,
        sortless: Optional[bool] = None,
        step_lanes: Optional[int] = None,
        waves_per_call: Optional[int] = None,
        device=None,
        compiled: Optional[CompiledModel] = None,
        resume_from: Optional[str] = None,
        log_capacity: Optional[int] = None,
        auto_tune: bool = True,
        journal=None,
        checkpoint_path: Optional[str] = None,
        checkpoint_every_waves: Optional[int] = None,
        checkpoint_every_sec: Optional[float] = None,
        trace: bool = False,
    ):
        """``capacity`` sizes the fingerprint table (slots; load is kept
        below 50%), ``log_capacity`` the append-only row log (positions =
        unique states; defaults to ``capacity``).  Decoupled because their
        per-entry costs differ by an order of magnitude: a table slot is 8
        bytes, a row-log position is ``4 * state_width`` (300+ bytes for
        the big register workloads) — a 2²⁶-slot table next to a
        12M-position log is how `paxos check 6` fits one 16 GB chip.

        ``auto_tune``: on a capacity-overflow flag (table overfull, row
        log full, dedup-buffer overflow) grow the tripped buffer IN PLACE
        and continue — the flagged wave never commits, the grown table is
        rebuilt from the committed row-log prefix on device, and no search
        work is redone.  Each growth recompiles (new buffer shapes), so
        sizing hints still save time, but no workload needs a hand-tuning
        session just to complete (VERDICT r3 weak #7).  Step-kernel
        encoding overflows are never retried: they mean the compiled
        model's layout cannot represent a reachable state.  Resumed runs
        adopt the snapshot's geometry and may auto-grow past it.

        ``sortless``: select the dedup path (docs/OBSERVABILITY.md
        "Sortless dedup and the rung ladders").  The default (None)
        resolves to the SORTLESS claim-plane election
        (hashset.insert_batch_claim — representatives elected inside
        the probe rounds, no 3-plane co-sort, density-insensitive)
        unless an explicit ``sort_lanes`` rung selects the sorted
        fallback path.  Under sortless, a flag-4 overflow of a
        TUNER-pinned compaction rung just climbs one rung (same as the
        sort path — the tuner guessed small, the election is fine);
        the FALLBACK to the sort-rung path fires when the claim
        compaction buffer overflows at its full worst-case width (a
        duplicate-heavy workload the election cannot represent at this
        ``dedup_factor``) — non-committing, the wave re-runs, no work
        lost — and ``tuned_kwargs()`` persists the flip, so the
        selection is per-workload through the knob cache.  Passing
        BOTH ``sortless=True`` and ``sort_lanes`` keeps the election
        but makes the rung an explicit claim BUDGET: its overflow
        falls back immediately — the forcing knob tests/CI use to
        exercise the fallback on small models.

        ``sort_lanes``: the sorted fallback path's adaptive rung (the
        PR 12 ladder) — a power-of-two width for the per-wave
        compact/dedup-sort buffers, replacing the worst-case ``U =
        max(min(B, 16K), B/dedup_factor)``.  Passing it selects the
        sort path (see ``sortless``) warm-started at the rung; on the
        sort path with no rung the density tuner downshifts mid-run.
        A wave whose valid candidates exceed the rung overflows
        (flag 4, nothing commits) and the host retries one rung up —
        identical discovery sets at every rung, by construction.

        ``step_lanes``: the frontier-sized step rung (wave_loop.py's
        second ladder) — a power-of-two per-wave CHUNK width replacing
        ``max_frontier``, so the expansion kernel and valid-lane
        compaction scan ``step_lanes × max_actions`` candidate lanes
        instead of the full worst-case ``B``.  None starts at the full
        chunk and lets the frontier tuner downshift; a wave whose
        remaining level exceeds the rung raises the non-committing
        flag 128 and the host climbs one rung (×2, capped at
        ``max_frontier``).  The discovered rung rides the knob cache
        exactly like ``sort_lanes``.

        ``journal`` (a :class:`~stateright_tpu.runtime.journal.Journal`
        or a path) streams wave-level telemetry — per-call frontier
        size, unique states, dedup occupancy, device-call wall time,
        checkpoint/resume/grow events — as JSON lines (schema:
        docs/RUNTIME.md).  ``checkpoint_path`` enables periodic MID-RUN
        snapshots (atomic write + rename, ``save_snapshot`` format)
        every ``checkpoint_every_waves`` waves (counted in
        ``waves_per_call`` quanta — the host-loop granularity) or
        ``checkpoint_every_sec`` seconds (default 30 when only the path
        is given); a killed run resumes from the latest checkpoint via
        ``resume_from``.

        ``trace``: run the wave loop in PHASE-TIMED SEGMENTS (step
        kernel / canon+fingerprint / dedup-sort+probe / append / host
        readback) instead of the fused ``lax.while_loop`` — one host
        sync per wave, each phase a separate dispatch timed with
        ``block_until_ready`` and charged modeled bytes against the
        device's peak HBM bandwidth (obs/roofline.py).  Results are
        identical (same kernels, same commit order); throughput is not —
        a traced run pays per-wave dispatch+sync overhead and exists to
        say WHERE the untraced run's time goes, never to be the measured
        number.  With ``trace=False`` (the default) the fused device
        program is byte-for-byte unchanged and the host loop issues no
        additional per-wave syncs.  Tracing surfaces: enriched ``wave``
        journal records, ``metrics()`` (the Explorer's ``/.metrics``),
        and ``trace_summary()``.  Traced runs auto-grow in place on
        overflow exactly like the fused loop (an aborted wave never
        commits; the rehash erases its keys), but do not support
        ``resume_from`` and ignore the mid-run checkpoint cadence (the
        final completion checkpoint still lands).  A visitor forces
        ``trace`` on — a visitor-instrumented default-knob run still
        completes, it just runs at traced speed.

        Visitors: a ``visitor()`` on the builder is supported at COARSE
        WAVE GRANULARITY via the traced readback path (``trace`` is
        forced on): every unique state is visited exactly once, at
        expansion, as a single-state path — BFS level order across
        waves, fingerprint-sorted order within a level, no action
        prefix.  docs/OBSERVABILITY.md states the full contract."""
        super().__init__(options.model)
        import jax

        if options._visitor is not None:
            # The wavefront never materializes per-state paths during
            # the run; visits ride the traced per-wave readback instead
            # (coarse wave granularity — see the docstring above).
            trace = True
        self._trace = bool(trace)
        self._options = options
        self._compiled = compiled or compiled_model_for(options.model)
        # Symmetry reduction: dedup on the fingerprint of the CANONICAL
        # row while logging the original (the device form of
        # src/checker/dfs.rs:309-334).  Honored when the compiled model
        # declares a canonicalization; a silent fallback to no reduction
        # would report full-space counts as if they were reduced, so a
        # missing canon is a loud spawn error (VERDICT r5 missing #1).
        from .canon import make_canon

        self._canon = (
            make_canon(self._compiled)
            if options._symmetry is not None
            else None
        )
        if options._symmetry is not None and self._canon is None:
            raise ValueError(
                "spawn_tpu() with symmetry() requires the compiled model "
                f"to declare a canonicalization, but "
                f"{type(self._compiled).__name__} defines neither "
                "canon_spec() nor canon_rows (parallel/canon.py); use "
                "spawn_dfs() for host-side symmetry"
            )
        self._capacity = capacity
        self._log_capacity = log_capacity or capacity
        # An explicit log_capacity is a user memory-geometry decision;
        # auto-tune must not silently inflate it when the TABLE grows.
        self._log_capacity_explicit = log_capacity is not None
        self._dedup_factor = dedup_factor
        # Adaptive sort-geometry rung (wave_loop.py's ladder, ROADMAP
        # #1): ``sort_lanes`` sizes the per-wave compact/sort/probe
        # buffers to a power-of-two rung instead of the worst-case U.
        # None starts at the full buffer (today's program) and lets the
        # density-driven tuner downshift once measured evidence exists;
        # an explicit rung (a knob-cache warm start) skips the ramp.
        # Overflowing a rung is the non-committing flag 4: the host
        # climbs one rung and re-runs the chunk, no work lost.
        from .wave_loop import (
            SORT_RUNG_MIN, STEP_RUNG_MIN, clamp_sort_lanes,
            clamp_step_lanes,
        )

        self._sort_lanes = (
            None if sort_lanes is None else clamp_sort_lanes(sort_lanes)
        )
        # The density tuner only drives runs that did NOT pin a rung:
        # an explicit sort_lanes is a warm start (or a measurement leg)
        # the tuner must not fight; the overflow ladder stays armed.
        self._sort_tune = sort_lanes is None
        self._sort_rung_floor = SORT_RUNG_MIN
        self._sort_peak_valid = 0.0
        self._sort_quanta = 0
        # Dedup-path selection (the sortless claim-plane election is the
        # default; an explicit sort_lanes rung selects the sorted
        # fallback path — see the docstring).
        self._sortless = (
            (sort_lanes is None) if sortless is None else bool(sortless)
        )
        # Frontier-sized step rung (wave_loop.py's second ladder).
        self._step_lanes = (
            None if step_lanes is None else clamp_step_lanes(step_lanes)
        )
        self._step_tune = step_lanes is None
        self._step_rung_floor = STEP_RUNG_MIN
        self._step_peak_frontier = 0.0
        self._step_quanta = 0
        self._auto_tune = bool(auto_tune)
        self._max_frontier = max_frontier
        # Spawn-time guard on the compact/dedup buffer width: configs past
        # _MAX_UNIQUE_BUFFER hard-CRASH the TPU worker mid-wave instead of
        # flagging (see the constant's comment), so a requested geometry in
        # the crash band is clamped here — same rule the auto-tune growth
        # path applies — with a logged warning.
        from .hashset import unique_buffer_size

        a = self._compiled.max_actions
        u_cap = max_safe_unique_lanes(self._compiled.state_width)
        clamped = False
        while (
            self._max_frontier > 2048
            and unique_buffer_size(self._max_frontier * a, self._dedup_factor)
            > u_cap
        ):
            self._max_frontier //= 2
            clamped = True
        if (
            unique_buffer_size(self._max_frontier * a, self._dedup_factor)
            > u_cap
        ):
            # Over budget even at the floor frontier (max_actions > 256):
            # refuse loudly, like the _grow path — proceeding means a
            # worker crash, not an overflow flag.
            raise ValueError(
                f"chunk geometry (max_frontier={self._max_frontier}, "
                f"max_actions={a}, dedup_factor={dedup_factor}) exceeds "
                "the device-safe compact-buffer band even at the floor "
                "frontier; raise dedup_factor"
            )
        if clamped:
            import logging

            logging.getLogger(__name__).warning(
                "spawn_tpu: max_frontier clamped to %d (max_actions=%d, "
                "dedup_factor=%d): the requested chunk geometry exceeds "
                "the device-safe compact-buffer band",
                self._max_frontier, a, dedup_factor,
            )
        if waves_per_call is None:
            from .wave_common import default_waves_per_call

            waves_per_call = default_waves_per_call(options)
        self._waves_per_call = waves_per_call
        self._device = device or jax.devices()[0]
        self._properties = self._model.properties()
        if len(self._properties) > 32:
            raise ValueError("at most 32 properties supported on device")
        self._ev_indices = [
            i
            for i, p in enumerate(self._properties)
            if p.expectation is Expectation.EVENTUALLY
        ]
        self._discovery_slots: Dict[str, int] = {}
        self._state_count = 0
        self._unique_count = 0
        self._max_depth = 0
        self._done = threading.Event()
        self._errors: List[BaseException] = []
        self._lock = threading.Lock()
        self._resume_from = resume_from
        if self._trace and resume_from is not None:
            raise ValueError(
                "spawn_tpu(trace=True) does not support resume_from: "
                "tracing is a diagnostic mode; resume the run untraced "
                "and trace a fresh (bounded) run instead"
            )
        from ..obs.metrics import MetricsRegistry

        self._metrics = MetricsRegistry()
        self._tracer = None  # built by the traced host loop
        from ..runtime.journal import as_journal

        self._journal = as_journal(journal)
        self._checkpoint_path = checkpoint_path
        self._ckpt_every_waves = checkpoint_every_waves
        self._ckpt_every_sec = checkpoint_every_sec
        if (
            checkpoint_path is not None
            and checkpoint_every_waves is None
            and checkpoint_every_sec is None
        ):
            self._ckpt_every_sec = 30.0
        self._carry_dev: Optional[dict] = None  # full run state at stop
        self._final_load_factor: Optional[float] = None  # metrics() cache
        self._discoveries_cache: Optional[Dict[str, Path]] = None
        self._tables_dev: Optional[tuple] = None  # (parent, rows) on device

        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # --- device program ------------------------------------------------------

    def _build_run(self):
        """Build the fused multi-chunk program.

        States live in an APPEND-ONLY row log indexed by *BFS position*
        (the order states were first discovered), with explicit level
        boundaries: each loop iteration expands one chunk (≤ ``f``
        positions) of the current level, appends newly inserted states'
        rows at the tail, and advances ``depth`` only when a level is fully
        drained — so levels may be arbitrarily wide (no frontier-overflow
        failure mode) while depth/target semantics stay exactly those of a
        level-at-a-time BFS.

        Because positions in a level are contiguous, the chunk read is one
        contiguous ``dynamic_slice`` and the append is one contiguous
        ``dynamic_update_slice`` — the only randomly-indexed memory left is
        the hash table itself.  The log is a FLAT u32 buffer: a 2-D
        ``[positions, W]`` layout gets its minor dim tile-padded to 128
        lanes (W=42 → 3×, W=75 → 1.7× HBM — the round-3 store shipped that
        way and it capped paxos c≥4 and raft depth 12 on a 16 GB chip),
        and XLA re-imposes that layout on a transposed store, so flat +
        block access is the only padding-free shape.  Offsets can exceed
        2³¹ (u32 starts; validated on-device up to 10.7 GB buffers).

        Carry: (key_hi, key_lo, rows, parent, ebits, level_start,
        level_end, tail, sc_lo, sc_hi, unique_count, depth, disc[P],
        waves_left, flags).  ``sc_lo``/``sc_hi`` form the 64-bit
        generated-state counter (no u64 on device).  flag values: 1 = table
        overfull (probe failure or beyond 50% load); 2 = row log full
        (unique states exceeded ``log_capacity``); 4 = insert dedup-buffer
        overflow; 8 = model step kernel capacity overflow.
        """
        import jax
        import jax.numpy as jnp

        from ..ops.device_fp import device_fp64
        from .hashset import (
            HashSet, compact_valid, insert_batch, insert_batch_claim,
            insert_batch_compact,
        )
        from .wave_common import wave_eval

        cm = self._compiled
        w = cm.state_width
        # State identity = the leading fp_words of a row (compiled.py);
        # trailing words ride along with the first-inserted representative.
        fpw = cm.fp_words or w
        # Symmetry: fingerprints (and only fingerprints) come from the
        # canonical row — the row log, parents, property evaluation, and
        # path re-execution all see the ORIGINAL rows, so discovery
        # traces stay bit-identical to reference semantics.
        canon = self._canon

        def fp_of(rows):
            rows_c = rows if canon is None else jax.vmap(canon)(rows)
            return device_fp64(rows_c[:, :fpw])
        a = cm.max_actions
        f = self._max_frontier  # worst-case chunk (seed/pad geometry)
        # The live step-geometry rung: the per-wave chunk width.  A wave
        # whose remaining level exceeds it raises the non-committing
        # flag 128 (compiled out at the top rung, where the clamp is
        # impossible) and the host climbs one rung.
        f_eff = self._step_width()
        cap = self._capacity
        qcap = self._log_capacity  # one row-log position per unique state
        pad = self._block_pad()  # append-block lanes past qcap
        dedup_factor = self._dedup_factor
        # Dedup path: the sortless claim-plane election by default; the
        # sorted fallback rung when selected (knob cache / explicit).
        sortless = self._sortless
        # The live sort-geometry rung: the compact/dedup/insert buffers
        # below span this width; everything downstream (probe rounds,
        # result gathers, the append-block compaction source) follows
        # the compacted buffer's shape automatically.  None = the
        # worst-case buffer of the LIVE (step-rung-sized) batch; pinned
        # only when a rung exists (sort path, or a sortless forcing
        # run capping the claim compaction buffer).
        sort_lanes = (
            None if self._sort_lanes is None else self._sort_width()
        )
        props = self._properties
        n_props = len(props)
        ev_indices = self._ev_indices
        target_depth = self._options._target_max_depth or 0

        # finish_when, mirrored on device (wave_common.py): the fused loop
        # exits as soon as the policy is satisfied, so e.g. time-to-first-
        # violation runs don't pay a host sync per chunk.
        from .wave_common import make_finish_when_device

        fw_found_matched = make_finish_when_device(
            self._options._finish_when, props
        )

        def fw_matched(disc):
            import jax.numpy as jnp

            return fw_found_matched(disc != jnp.uint32(0xFFFFFFFF))

        def wave_body(carry):
            (
                key_hi,
                key_lo,
                rows,
                parent,
                ebits,
                level_start,
                level_end,
                tail,
                sc_lo,
                sc_hi,
                unique_count,
                depth,
                disc,
                waves_left,
                flags,
            ) = carry

            count = jnp.minimum(level_end - level_start, jnp.uint32(f_eff))
            lane = jnp.arange(f_eff, dtype=jnp.uint32)
            active = lane < count
            ids = level_start + lane  # BFS positions are the state ids
            states = jax.lax.dynamic_slice(
                rows, (level_start * jnp.uint32(w),), (f_eff * w,)
            ).reshape(f_eff, w)
            eb_chunk = jax.lax.dynamic_slice(
                ebits, (level_start,), (f_eff,)
            )

            disc_prev = disc
            disc, eb, nexts, valid, generated, step_flag = wave_eval(
                cm, props, ev_indices, states, active, ids, eb_chunk, disc,
                allow_two_phase=True,
            )

            flat_valid = valid.reshape(f_eff * a)
            if nexts is None:
                # TWO-PHASE expansion: compact the ~5% valid lanes FIRST,
                # then construct successors (word assembly + per-lane slot
                # re-sort — the expensive half of the step kernel) only
                # for the survivors, and fingerprint U lanes instead of B.
                from .hashset import compact_valid_indices

                v_orig, v_act, n_valid, v_overflow = compact_valid_indices(
                    flat_valid, dedup_factor, sort_lanes=sort_lanes
                )
                src_state = v_orig // jnp.uint32(a)
                lane_k = v_orig % jnp.uint32(a)
                par_rows = states[src_state]  # [U, w] gather
                nexts_u, _valid_u, lane_flags_u = jax.vmap(
                    cm.step_lane
                )(par_rows, lane_k)
                step_flag = step_flag | jnp.any(lane_flags_u & v_act)
                hi, lo = fp_of(nexts_u)
                compact_rows = nexts_u
                compact_src = src_state
            else:
                # Dedup + insert, in compact form: results come back
                # U-sized (one lane per distinct key), so the append below
                # costs O(distinct keys) instead of O(candidate lanes).
                flat = nexts.reshape(f_eff * a, w)
                hi_b, lo_b = fp_of(flat)
                v_hi, v_lo, v_orig, v_act, v_overflow = compact_valid(
                    hi_b, lo_b, flat_valid, dedup_factor,
                    sort_lanes=sort_lanes,
                )
                hi, lo = v_hi, v_lo
                compact_rows = None
                compact_src = None
            if sortless:
                # SORTLESS default: claim-plane election inside the
                # probe rounds (hashset.insert_batch_claim) — no
                # 3-plane co-sort; representatives (lowest lane of each
                # equal-key run) and the downstream indexing contract
                # are identical (u_origin is the identity map).
                (
                    table, _u_slot, u_new, u_origin, _u_active, probe_ok,
                    dd_overflow,
                ) = insert_batch_claim(
                    HashSet(key_hi, key_lo), hi, lo, v_act,
                )
            else:
                (
                    table, _u_slot, u_new, u_origin, _u_active, probe_ok,
                    dd_overflow,
                ) = insert_batch_compact(
                    HashSet(key_hi, key_lo), hi, lo, v_act,
                    dedup_factor=1,
                )
            dd_overflow = dd_overflow | v_overflow
            n_new = jnp.sum(u_new, dtype=jnp.uint32)

            # An overflowing wave must NOT commit: the host grows the
            # tripped buffer in place (rebuilding the table from the row
            # log) and re-runs this chunk, so the carry it reads back has
            # to be exactly the pre-wave state.  The table itself may hold
            # the aborted wave's keys — every growth path rehashes it from
            # the committed log prefix, which erases them.
            flags = flags | jnp.where(probe_ok, 0, 1).astype(jnp.uint32)
            flags = flags | jnp.where(
                (unique_count + n_new) * 2 > jnp.uint32(cap), 1, 0
            ).astype(jnp.uint32)
            flags = flags | jnp.where(
                tail + n_new > jnp.uint32(qcap), 2, 0
            ).astype(jnp.uint32)
            flags = flags | jnp.where(dd_overflow, 4, 0).astype(jnp.uint32)
            flags = flags | jnp.where(step_flag, 8, 0).astype(jnp.uint32)
            if f_eff < f:
                # Step-rung clamp (flag 128, non-committing): the
                # remaining level exceeds the rung — the host climbs
                # one rung and re-runs; compiled out at the top rung,
                # where the clamp is impossible by construction.
                flags = flags | jnp.where(
                    level_end - level_start > jnp.uint32(f_eff), 128, 0
                ).astype(jnp.uint32)
            commit = flags == 0
            n_new = jnp.where(commit, n_new, jnp.uint32(0))
            count = jnp.where(commit, count, jnp.uint32(0))
            # Discoveries too: the re-run of an aborted chunk must see the
            # pre-wave discovery state, or first-discovery side effects
            # (e.g. eventually-bit awaiting masks) would diverge from a
            # committed execution of the same wave.
            disc = jnp.where(commit, disc, disc_prev)
            unique_count = unique_count + n_new
            new_lo = sc_lo + jnp.where(commit, generated, jnp.uint32(0))
            sc_hi = sc_hi + (new_lo < sc_lo).astype(jnp.uint32)
            sc_lo = new_lo

            # Select the newly inserted representatives (in sorted-key
            # order, matching position assignment) and APPEND their rows,
            # parent positions, and ebits as three contiguous block writes
            # — no table-sized scatters at all.  ``sel`` lanes beyond
            # n_new alias lane 0; their garbage lands at positions ≥ the
            # new tail, which only ever get (re)written by later appends
            # before any read (an aborted wave's whole block is such
            # garbage: tail does not advance).  First-inserter ebits
            # semantics are unchanged (u_origin is the lowest lane of each
            # key run).
            u = u_new.shape[0]
            from .wave_common import compact

            sel = compact(u_new, jnp.arange(u, dtype=jnp.uint32), pad)
            sel_u = u_origin[sel]  # lane in the compacted valid buffer
            if compact_rows is not None:  # two-phase: rows already built
                rows_blk = compact_rows[sel_u]  # [pad, w] gather
                src_state = compact_src[sel_u]
            else:
                idxs = v_orig[sel_u]  # original flat candidate lane
                rows_blk = flat[idxs]  # [pad, w] gather
                src_state = idxs // jnp.uint32(a)
            par_blk = level_start + src_state
            eb_blk = eb[src_state]
            rows = jax.lax.dynamic_update_slice(
                rows, rows_blk.reshape(-1), (tail * jnp.uint32(w),)
            )
            parent = jax.lax.dynamic_update_slice(parent, par_blk, (tail,))
            ebits = jax.lax.dynamic_update_slice(ebits, eb_blk, (tail,))
            tail = tail + n_new

            # Advance within the level; roll the level boundary when drained.
            level_start = level_start + count
            done_level = (level_start >= level_end) & commit
            depth = depth + done_level.astype(jnp.uint32)
            level_end = jnp.where(done_level, tail, level_end)

            return (
                table.key_hi,
                table.key_lo,
                rows,
                parent,
                ebits,
                level_start,
                level_end,
                tail,
                sc_lo,
                sc_hi,
                unique_count,
                depth,
                disc,
                waves_left - 1,
                flags,
            )

        def wave_cond(carry):
            level_start = carry[5]
            level_end = carry[6]
            depth = carry[11]
            disc = carry[12]
            waves_left = carry[13]
            flags = carry[14]
            go = (level_start < level_end) & (waves_left > 0) & (flags == 0)
            if target_depth:
                # The next chunk would expand states at depth+1; the
                # reference skips jobs with depth >= target at pop time, so
                # states at the target depth are counted but not expanded.
                go = go & (depth < target_depth - 1)
            go = go & ~fw_matched(disc)
            return go

        waves_per_call = self._waves_per_call

        @partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
        def run(key_hi, key_lo, rows, parent, ebits, stats):
            # All host-visible scalars travel in ONE small stats array:
            # through a tunneled device every distinct readback is a full
            # network round trip (~100 ms measured), which dominated
            # shallow runs like time-to-first-violation.
            carry = (
                key_hi,
                key_lo,
                rows,
                parent,
                ebits,
                stats[STAT_LEVEL_START],
                stats[STAT_LEVEL_END],
                stats[STAT_TAIL],
                stats[STAT_SC_LO],
                stats[STAT_SC_HI],
                stats[STAT_UNIQUE],
                stats[STAT_DEPTH],
                stats[STAT_DISC : STAT_DISC + len(props)],
                jnp.int32(waves_per_call),
                jnp.uint32(0),
            )
            out = jax.lax.while_loop(wave_cond, wave_body, carry)
            stats_out = jnp.concatenate(
                [jnp.stack([out[5], out[6], out[7], out[8], out[9],
                            out[10], out[11], out[14]]), out[12]]
            )
            return out[0], out[1], out[2], out[3], out[4], stats_out

        eb0 = (1 << len(ev_indices)) - 1

        @jax.jit
        def seed(init_rows, n_init):
            """One dispatch creates EVERY device buffer and seeds the init
            states: the table planes, row log, parent links, and ebits are
            minted inside the program (five separate host-side allocation
            dispatches used to cost a tunnel round trip each), and only
            the n_init packed init rows are uploaded."""
            from .wave_common import compact

            init_padded = jnp.zeros((f, w), jnp.uint32)
            init_padded = jax.lax.dynamic_update_slice(
                init_padded, init_rows, (0, 0)
            )
            key_hi = jnp.zeros((cap,), jnp.uint32)
            key_lo = jnp.zeros((cap,), jnp.uint32)
            rows = jnp.zeros(((qcap + pad) * w,), jnp.uint32)
            parent = jnp.full((qcap + pad,), NO_SLOT_HOST, jnp.uint32)
            ebits = jnp.zeros((qcap + pad,), jnp.uint32)

            hi, lo = fp_of(init_padded)
            seed_active = jnp.arange(f, dtype=jnp.uint32) < n_init
            # dedup_factor=1: the unique buffer covers the whole batch, so
            # seed failure is unambiguously a table-probe overflow — the
            # one condition growing ``capacity`` (flag 1) actually fixes.
            table, _slot, is_new, probe_ok, dd_overflow = insert_batch(
                HashSet(key_hi, key_lo), hi, lo, seed_active, dedup_factor=1
            )
            # Unique init states take positions 0..fcount in lane order.
            sel = compact(is_new, jnp.arange(f, dtype=jnp.uint32), f)
            rows = jax.lax.dynamic_update_slice(
                rows, init_padded[sel].reshape(-1), (jnp.uint32(0),)
            )
            ebits = jax.lax.dynamic_update_slice(
                ebits, jnp.full((f,), eb0, jnp.uint32), (jnp.uint32(0),)
            )
            fcount = jnp.sum(is_new, dtype=jnp.uint32)
            seed_fail = (~(probe_ok & ~dd_overflow)).astype(jnp.uint32)
            stats = jnp.concatenate(
                [
                    jnp.stack([
                        jnp.uint32(0),  # level_start
                        fcount,  # level_end
                        fcount,  # tail
                        n_init,  # sc_lo
                        jnp.uint32(0),  # sc_hi
                        fcount,  # unique_count
                        jnp.uint32(0),  # depth
                        seed_fail,  # flags (nonzero = seed overflow)
                    ]),
                    jnp.full((len(props),), NO_SLOT_HOST, jnp.uint32),
                ]
            )
            return table.key_hi, table.key_lo, rows, parent, ebits, stats

        return seed, run

    def _programs(self):
        key = (
            self._compiled.cache_key(),
            # The two-phase gate is evaluated at trace time (wave_eval's
            # hasattr checks) — it must key the program, or a model whose
            # capability set changes (e.g. tests forcing the single-phase
            # branch) would silently re-run the wrong compiled program.
            hasattr(self._compiled, "step_valid")
            and hasattr(self._compiled, "step_lane"),
            # Symmetry is a trace-time branch (canonical-fp dedup): a
            # sym and a non-sym run of the same model must never share a
            # compiled program.
            self._canon is not None,
            self._capacity,
            self._log_capacity,
            self._max_frontier,
            self._dedup_factor,
            self._sortless,  # the dedup path is a trace-time branch
            self._sort_width(),  # the live sort-geometry rung
            self._step_width(),  # the live step-geometry rung
            self._waves_per_call,  # baked into run() as a constant
            tuple(p.expectation for p in self._properties),
            (
                self._options._finish_when._kind,
                tuple(sorted(self._options._finish_when._names)),
                tuple(p.name for p in self._properties),
            ),
            self._options._target_max_depth or 0,
        )
        from .wave_common import cached_program

        return cached_program(
            _PROGRAM_CACHE, _PROGRAM_CACHE_MAX, key, self._build_run,
            label=f"{type(self).__name__}.fused",
            journal=self._journal,
            provenance=self._key_provenance(),
        )

    def _key_provenance(self) -> dict:
        """The human-readable knobs behind the program-cache keys — what
        a journaled ``compile`` event says CHANGED when a warm daemon
        recompiles (docs/OBSERVABILITY.md "Compile events")."""
        return {
            "model": type(self._compiled).__name__,
            "capacity": self._capacity,
            "log_capacity": self._log_capacity,
            "max_frontier": self._max_frontier,
            "dedup_factor": self._dedup_factor,
            "sortless": self._sortless,
            "sort_lanes": self._sort_width(),
            "step_lanes": self._step_width(),
            "waves_per_call": self._waves_per_call,
            "symmetry": self._canon is not None,
        }

    # --- host loop -----------------------------------------------------------

    def _run(self) -> None:
        try:
            self._check()
        except BaseException as e:  # propagate at join, like the host engine
            self._errors.append(e)
        finally:
            self._done.set()

    def _check(self) -> None:
        """Run to completion.  In-loop overflows grow in place inside
        ``_check_once``; the restart loop here only handles SEED-time
        overflow (raised before any search work exists).  The user
        deadline is fixed here, across attempts — a retry must not reset
        the clock."""
        import time as _time

        opts = self._options
        deadline = (
            _time.monotonic() + opts._timeout
            if opts._timeout is not None
            else None
        )
        attempts = 6 if self._auto_tune else 1
        for attempt in range(attempts):
            try:
                return self._check_once(deadline)
            except _OverflowRetry as o:
                grown = self._grow(o.flag) if attempt < attempts - 1 else None
                if grown is None:
                    raise RuntimeError(o.message) from None
                import logging

                logging.getLogger(__name__).warning(
                    "auto-tune: %s; restarting with %s", o.message, grown
                )
                if self._journal:
                    self._journal.append(
                        "grow", seed=True, flags=o.flag, grown=grown
                    )
                with self._lock:  # discard the aborted attempt's progress
                    self._discovery_slots.clear()
                    self._state_count = 0
                    self._unique_count = 0
                    self._max_depth = 0

    def _grow_on_flags(self, flags_h, qcap, pad, rows, parent, ebits,
                       tail_h, unique_h, depth_h):
        """In-place auto-tune growth for in-loop overflow flags (bits
        1/2/4), shared by the fused and traced host loops so their
        recovery semantics cannot drift: grow the tripped knobs
        (:meth:`_grow`, honoring the dragged-log rule), resize the
        row/parent/ebits buffers if the log geometry changed, and
        rebuild the table from the committed row-log prefix (erasing any
        keys the aborted wave wrote).  Returns ``(rows, parent, ebits,
        key_hi, key_lo, qcap, pad)``; raises RuntimeError when the
        tripped knob cannot grow (or ``auto_tune`` is off).  The caller
        re-derives its capacity/frontier locals and programs from self
        and re-runs the same chunk."""
        msgs = {
            1: (
                f"fingerprint table overfull (capacity "
                f"{self._capacity}); raise spawn_tpu(capacity=...)"
            ),
            2: (
                f"the state row log is full (log_capacity {qcap}); "
                "raise spawn_tpu(log_capacity=...)"
            ),
            4: (
                "a wave generated more VALID successor candidates than "
                "the compaction/dedup buffers hold (batch/dedup_factor); "
                f"lower spawn_tpu(dedup_factor=...) (now "
                f"{self._dedup_factor}; 1 is always safe)"
            ),
            128: (
                "the step-rung ladder clamped a wave at the full chunk "
                "width — impossible by construction (the clamp flag is "
                "compiled out at the top rung); please report"
            ),
        }
        grown = []
        for bit in (1, 2, 4, 128):
            if flags_h & bit:
                if bit == 2 and self._log_capacity > qcap:
                    # A simultaneous table growth (bit 1, processed
                    # above) already dragged the log past the tripped
                    # size — the flag is addressed; raising here would
                    # kill a run whose log just grew.
                    grown.append(
                        f"log_capacity={self._log_capacity} (dragged)"
                    )
                    continue
                g = self._grow(bit) if self._auto_tune else None
                if g is None:
                    raise RuntimeError(msgs[bit])
                grown.append(g)
        from .wave_loop import log_grow

        log_grow(self, flags_h, "; ".join(grown), unique_h, depth_h)
        new_qcap = self._log_capacity
        new_pad = self._block_pad()
        if (new_qcap + new_pad) != (qcap + pad):
            n_new_len = new_qcap + new_pad
            rows = _resize_flat(
                rows, n_new_len * self._compiled.state_width, 0
            )
            parent = _resize_flat(parent, n_new_len, NO_SLOT_HOST)
            ebits = _resize_flat(ebits, n_new_len, 0)
            qcap, pad = new_qcap, new_pad
        key_hi, key_lo = self._rehash(rows, tail_h)
        return rows, parent, ebits, key_hi, key_lo, qcap, pad

    def _grow(self, flag: int):
        """Adjust the knob named by ``flag``; None if it cannot grow.

        Table growth is aggressive (×16 — slots are 8 bytes and every
        retry pays a recompile plus a partial re-run) and drags a
        defaulted row log with it; the row log alone grows ×2 (positions
        are 4·state_width bytes and copy-growth holds old + new at once);
        a dedup overflow relaxes the factor toward the always-safe 1.
        """
        row_bytes = 4 * self._compiled.state_width
        log_cap_bound = max(self._log_capacity, _ROW_LOG_BYTE_BUDGET // row_bytes)
        if flag & 1:
            if self._capacity >= _MAX_TABLE_CAPACITY:
                return None
            self._capacity = min(self._capacity * 16, _MAX_TABLE_CAPACITY)
            # A DEFAULTED log tracks the table (unique states need both a
            # slot and a position — growing one without the other just
            # schedules the next overflow); an explicit one is the user's
            # memory geometry and only grows on its own flag.  The drag is
            # ×2 like the log's own growth step, NOT straight to
            # capacity/2: a row-log position costs 4·state_width bytes, so
            # at w=77 a capacity/2 drag after the ×16 table jump would
            # allocate gigabytes past what the run needs and risk HBM
            # exhaustion in the copy-growth transient.
            if not self._log_capacity_explicit:
                self._log_capacity = min(
                    max(
                        self._log_capacity,
                        min(self._capacity // 2, self._log_capacity * 2),
                    ),
                    log_cap_bound,
                )
            return f"capacity={self._capacity} log_capacity={self._log_capacity}"
        if flag & 2:
            if self._log_capacity >= log_cap_bound:
                return None
            # ×2, not ×16: a row-log position costs 4·state_width bytes
            # and copy-growth transiently holds old + new logs at once.
            self._log_capacity = min(self._log_capacity * 2, log_cap_bound)
            return f"log_capacity={self._log_capacity}"
        if flag & 128:
            from .wave_loop import climb_step_rung

            # Step-rung ladder: the live frontier level exceeded the
            # chunk rung — climb one rung (×2, capped at max_frontier,
            # where the clamp flag is compiled out); the climbed rung
            # becomes the floor the frontier tuner may never revisit.
            return climb_step_rung(self, self._max_frontier)
        if flag & 4:
            from .hashset import unique_buffer_size
            from .wave_loop import (
                climb_sort_rung, fall_back_to_sort, relax_dedup_geometry,
                reset_sort_rung_to_full,
            )

            # EXPLICIT claim-budget cap first: ``sortless=True`` with a
            # caller-pinned ``sort_lanes`` is a budget ("elect within
            # this compaction width or don't bother"), not a tuner
            # guess — its overflow is the per-workload fallback signal,
            # not a climb (the forcing knob tests/CI use, and the one
            # spawn shape tuned_kwargs deliberately never emits: a
            # sortless run's pinned rung is a tuner detail, so a warm
            # repeat re-arms the tuner instead of inheriting a
            # one-notch-tight explicit cap).
            if self._sortless and not self._sort_tune:
                return fall_back_to_sort(self)
            # Compact-rung ladder next, on BOTH dedup paths: when the
            # compact/claim buffers run at a TUNER-pinned rung below
            # the full U, a flag-4 overflow means the rung was too
            # small — the density tuner downshifted it past a growing
            # level — not the path or the worst-case geometry.  Climb
            # one rung (×2, capped at U) and re-run; the climbed rung
            # becomes the floor the tuner may never revisit.  A
            # rung-level overflow must NOT abandon the claim election:
            # the sorted path in the identical situation just climbs,
            # and the sharded engine orders its flag-4 dispatch the
            # same way (climb before relax) — the rule lives in
            # wave_loop (climb_sort_rung), shared so the engines
            # cannot drift.
            full = self._wl_full_sort_lanes()
            note = climb_sort_rung(self, full)
            if note is not None:
                return note
            # SORTLESS fallback at the FULL buffer: the valid batch
            # exceeded the claim compaction buffer at its worst-case
            # width — the per-workload signal that the election cannot
            # represent this (duplicate-heavy) batch at the current
            # dedup_factor.  Flip to the sorted fallback rung
            # (wave_loop.fall_back_to_sort; the flagged wave committed
            # nothing, so the re-run at the sorted program is exact)
            # and let ITS relax rules take over on subsequent
            # overflows.  tuned_kwargs persists the flip, so the
            # selection is per-workload through the knob cache.
            if self._sortless:
                return fall_back_to_sort(self)
            # Straight to the always-safe 1, not stepwise (the
            # intermediate dd=2-at-doubled-frontier stop measured as a
            # NEW worker-crash geometry on the 61.5M-state 2pc run),
            # halving the frontier while U exceeds the device-safe band
            # — the rule lives in wave_loop.relax_dedup_geometry, shared
            # with the sharded engine's flag-4 retry so the two engines'
            # growth semantics cannot drift.
            a = self._compiled.max_actions
            u_cap = max_safe_unique_lanes(self._compiled.state_width)
            relaxed = relax_dedup_geometry(
                self._max_frontier,
                self._dedup_factor,
                lambda c, dd: unique_buffer_size(c * a, dd),
                u_cap,
                chunk_label="max_frontier",
            )
            if relaxed is None:
                # Already at dd=1, or even the floor frontier cannot
                # keep the buffer in the safe band (max_actions > 256):
                # refuse loudly rather than proceed into the
                # worker-crash band.
                return None
            self._dedup_factor, self._max_frontier, note = relaxed
            # The FULL buffer overflowed on valid count: the relaxed
            # dd=1 geometry starts at its own full width (evidence +
            # geometry re-journal in the shared helper).
            reset_sort_rung_to_full(self, full)
            return note
        return None

    def _check_once(self, deadline=None) -> None:
        if self._trace:
            return self._check_once_traced(deadline)
        import jax
        import jax.numpy as jnp

        cm = self._compiled
        props = self._properties

        def sized(arr_np, n):
            """Pad/trim a 1-D snapshot array to ``n`` (the tail padding
            holds garbage by construction, so resumes may use different
            block-pad tuning than the run that saved the snapshot)."""
            if arr_np.shape[0] < n:
                return np.concatenate(
                    [arr_np, np.zeros(n - arr_np.shape[0], arr_np.dtype)]
                )
            return arr_np[:n]

        if self._resume_from is not None:
            # A resume ADOPTS the snapshot's table/log geometry (table
            # slots depend on the capacity mask, and a run that auto-tuned
            # mid-flight persisted the GROWN sizes, not the spawn
            # arguments) — only model/property identity is key-checked.
            snap = np.load(self._resume_from, allow_pickle=False)
            if "capacity" not in snap.files:
                raise ValueError(
                    "snapshot predates the rowlog-v3 format (no persisted "
                    "geometry); re-run the original check to produce a "
                    "fresh snapshot"
                )
            self._capacity = int(snap["capacity"])
            self._log_capacity = int(snap["log_capacity"])

        f = self._max_frontier
        qcap = self._log_capacity
        pad = self._block_pad()

        with jax.default_device(self._device):
            seed, run = self._programs()
            if self._resume_from is not None:
                want_key = self._snapshot_key()
                got_key = str(snap["engine_key"])
                if got_key != want_key:
                    raise ValueError(
                        "snapshot does not match this checker configuration"
                        f" (snapshot {got_key}, expected {want_key})"
                    )
                # Every upload goes through _device_owned: these arrays
                # are DONATED to the run program, and donating a borrowed
                # host-upload buffer corrupts the run (see the helper).
                key_hi = _device_owned(jnp.asarray(snap["key_hi"]))
                key_lo = _device_owned(jnp.asarray(snap["key_lo"]))
                rows = _device_owned(jnp.asarray(
                    sized(np.asarray(snap["rows"]), (qcap + pad) * cm.state_width)
                ))
                parent = _device_owned(
                    jnp.asarray(sized(np.asarray(snap["parent"]), qcap + pad))
                )
                ebits = _device_owned(
                    jnp.asarray(sized(np.asarray(snap["ebits"]), qcap + pad))
                )
                disc_np = np.asarray(snap["disc"]).astype(np.uint32)
                stats = _device_owned(jnp.asarray(
                    np.concatenate(
                        [
                            np.array(
                                [
                                    int(snap["level_start"]),
                                    int(snap["level_end"]),
                                    int(snap["tail"]),
                                    int(snap["sc_lo"]),
                                    int(snap["sc_hi"]),
                                    int(snap["unique_count"]),
                                    int(snap["depth"]),
                                    0,  # flags
                                ],
                                np.uint32,
                            ),
                            disc_np,
                        ]
                    )
                ))
                with self._lock:
                    self._state_count = (
                        int(snap["sc_hi"]) << 32
                    ) | int(snap["sc_lo"])
                    self._unique_count = int(snap["unique_count"])
                    self._max_depth = int(snap["depth"])
                    # Discovery names derive from the persisted disc array
                    # and the property order, which the key above pins.
                    for p, prop in enumerate(props):
                        if int(disc_np[p]) != NO_SLOT_HOST:
                            self._discovery_slots[prop.name] = int(disc_np[p])
                if self._journal:
                    self._journal.append(
                        "resume",
                        path=self._resume_from,
                        unique=self._unique_count,
                        states=self._state_count,
                        depth=self._max_depth,
                    )
            else:
                # Seed init states: ONE upload (the packed init rows) +
                # ONE dispatch that creates every device buffer — a
                # tunneled device pays ~100 ms per host-side round trip,
                # so shallow runs live and die on dispatch count.
                init = cm.init_packed()
                n_init = init.shape[0]
                if n_init > f:
                    # The one level still bounded by the chunk size: seeding
                    # writes the init batch into the log in one program.
                    raise ValueError(
                        f"{n_init} init states exceed the chunk size "
                        f"({f}); raise spawn_tpu(max_frontier=...) to at "
                        "least the init-state count (interior levels are "
                        "unbounded)"
                    )
                key_hi, key_lo, rows, parent, ebits, stats = seed(
                    jnp.asarray(init.astype(np.uint32)), jnp.uint32(n_init)
                )
                stats_h = np.asarray(stats)
                if int(stats_h[STAT_FLAGS]):
                    # Same auto-tunable condition as the in-loop flag 1: a
                    # dense init batch can exhaust probing before wave 0.
                    raise _OverflowRetry(
                        1,
                        "init-state seeding overflowed the fingerprint "
                        "table; raise spawn_tpu(capacity=...)",
                    )
                self._state_count = n_init
                self._unique_count = int(stats_h[STAT_UNIQUE])

            # The steady-state loop is the SHARED wave-loop core
            # (parallel/wave_loop.py) — journal/metrics/checkpoint
            # cadence, overflow dispatch (in-place auto-grow via
            # _wl_grow, loud raise otherwise), and termination live
            # there, identical to the sharded engine by construction.
            from .wave_loop import FusedWaveLoop, finalize_run

            self._run_fn = run
            self._loop_qcap, self._loop_pad = qcap, pad
            carry = (key_hi, key_lo, rows, parent, ebits, stats)
            carry, _waves = FusedWaveLoop(self).run(carry, deadline)
            key_hi, key_lo, rows, parent, ebits, stats = carry
            stats_h = self._last_stats_h

            # Keep the device arrays; path reconstruction walks the parent
            # chain ON DEVICE and reads back only the chain (a full-table
            # pull would be GBs through a tunneled device's ~18 MB/s link).
            self._tables_dev = (parent, rows)
            # Full run state, for snapshotting (via the shared finalize):
            # the reference cannot persist a run's visited set at all
            # (SURVEY §5); here the whole checker state is a handful of
            # dense arrays.  Scalars come from the last stats readback
            # (same npz keys as before).
            finalize_run(self, self._carry_from(
                key_hi, key_lo, rows, parent, ebits, stats_h
            ))

    # --- shared wave-loop adapter (parallel/wave_loop.py) --------------------

    def _wl_call(self, carry):
        return self._run_fn(*carry)

    def _wl_view(self, carry):
        from .wave_loop import WaveView

        # ONE small sync per waves_per_call chunks: every scalar the
        # host reads travels in the stats vector.
        stats_h = np.asarray(carry[5])
        self._last_stats_h = stats_h
        remaining = int(stats_h[STAT_LEVEL_END]) - int(
            stats_h[STAT_LEVEL_START]
        )
        disc = []
        for p, prop in enumerate(self._properties):
            s = int(stats_h[STAT_DISC + p])
            if s != NO_SLOT_HOST:
                disc.append((prop.name, s))
        unique_h = int(stats_h[STAT_UNIQUE])
        return WaveView(
            waves_this_call=self._waves_per_call,
            remaining=remaining,
            depth=int(stats_h[STAT_DEPTH]),
            flags=int(stats_h[STAT_FLAGS]),
            unique=unique_h,
            states=(int(stats_h[STAT_SC_HI]) << 32)
            | int(stats_h[STAT_SC_LO]),
            occupancy=unique_h / self._capacity,
            discoveries=tuple(disc),
            extra={"tail": int(stats_h[STAT_TAIL])},
        )

    def _wl_set_discovery(self, name: str, slot: int) -> None:
        self._discovery_slots.setdefault(name, slot)

    def _wl_discovered_names(self):
        return self._discovery_slots

    def _wl_cand_lanes(self) -> int:
        """The worst-case compaction/dedup buffer width ``U`` of the
        LIVE (step-rung-sized) batch — the denominator of the density
        telemetry (wave_loop.LoopVitals): measured valid candidates per
        wave over THIS is the fraction of the compact/probe work that
        touches live lanes.  Deliberately SORT-rung-independent (the
        sort rung is sized FROM density × this width; a sort-rung-
        relative density would be self-referential), but it follows the
        step rung — a step-rung-sized wave generates proportionally
        fewer candidates, and the sort tuner must size against the
        buffer those waves actually fill.  Queried per quantum because
        auto-grow and both ladders may move the geometry mid-run."""
        return self._wl_full_sort_lanes()

    # --- sort-geometry rung (wave_loop.py's ladder) --------------------------

    def _sort_width(self) -> int:
        """The EFFECTIVE per-wave compact/sort buffer width: the
        requested rung capped at the live worst-case ``U`` (auto-grow
        and the step rung may move U mid-run), or ``U`` itself when no
        rung is set.  The one number the device programs, cache keys,
        byte model, and knob-cache entries all derive from."""
        full = self._wl_full_sort_lanes()
        if self._sort_lanes is None:
            return full
        return min(self._sort_lanes, full)

    def _wl_full_sort_lanes(self) -> int:
        from .hashset import unique_buffer_size

        return unique_buffer_size(
            self._step_width() * self._compiled.max_actions,
            self._dedup_factor,
        )

    # --- step-geometry rung (wave_loop.py's second ladder) -------------------

    def _step_width(self) -> int:
        """The EFFECTIVE per-wave chunk width in frontier lanes: the
        step rung capped at the live ``max_frontier`` (auto-grow may
        halve it mid-run), or the full chunk when no rung is set."""
        full = self._max_frontier
        if self._step_lanes is None:
            return full
        return min(self._step_lanes, full)

    def _wl_full_step_lanes(self) -> int:
        return self._max_frontier

    def _wl_apply_step_rung(self, rung: int) -> None:
        """Apply a frontier-tuner downshift (wave_loop.
        maybe_retune_step): swap the knob, re-journal the geometry
        event, and — in fused mode — rebuild the run program at the new
        shapes.  The loop carry is untouched: the rung only shapes
        per-wave scratch buffers (the row log, table, and positions are
        rung-independent)."""
        self._step_lanes = int(rung)
        self._step_quanta = 0
        if self._journal:
            self._journal.append("geometry", **self._wl_geometry())
        if getattr(self, "_run_fn", None) is not None:
            _seed, self._run_fn = self._programs()

    def _wl_apply_sort_rung(self, rung: int) -> None:
        """Apply a density-tuner downshift (wave_loop.maybe_retune_sort):
        swap the knob, re-journal the geometry event (the watch verb's
        source for the current rung), and — in fused mode — rebuild the
        run program at the new shapes.  The loop carry is untouched:
        the rung only shapes per-wave scratch buffers."""
        self._sort_lanes = int(rung)
        self._sort_quanta = 0  # fresh evidence before another move
        # NOT mirrored into the metrics registry: metrics() reports the
        # live _sort_width(), and a stale registry copy would shadow a
        # later ladder climb (snapshot keys overwrite computed ones).
        if self._journal:
            self._journal.append("geometry", **self._wl_geometry())
        if getattr(self, "_run_fn", None) is not None:
            _seed, self._run_fn = self._programs()

    def _wl_geometry(self) -> dict:
        """The ``geometry`` journal event's payload (wave_loop.
        journal_geometry): live knobs + the density denominator, the
        advisor's ground truth for this run."""
        return {
            "engine": "tpu-wavefront",
            "capacity": self._capacity,
            "log_capacity": self._log_capacity,
            "max_frontier": self._max_frontier,
            "dedup_factor": self._dedup_factor,
            "sortless": self._sortless,
            "sort_lanes": self._sort_width(),
            "step_lanes": self._step_width(),
            "u_lanes": self._wl_cand_lanes(),
            "waves_per_call": self._waves_per_call,
        }

    def _wl_write_checkpoint(self, carry) -> dict:
        stats_h = self._last_stats_h
        self._write_snapshot(
            self._checkpoint_path,
            self._carry_from(
                carry[0], carry[1], carry[2], carry[3], carry[4], stats_h
            ),
        )
        return {"tail": int(stats_h[STAT_TAIL])}

    def _wl_retryable_flags(self) -> int:
        # 1 = table overfull, 2 = row log full, 4 = dedup-buffer
        # overflow (sortless fallback / sort-rung climb / dd relax),
        # 128 = step-rung clamp (climb one chunk rung): all grow in
        # place (auto_tune off raises the loud per-knob message from
        # _grow_on_flags instead).  8 (encoding overflow) is never
        # retryable.
        return 1 | 2 | 4 | 128

    def _wl_overflow_message(self, flags: int) -> str:
        if flags & 8:
            return (
                "the model step kernel flagged an encoding-capacity "
                "overflow (a successor exceeded the packed layout's "
                "bounds); the compiled model's capacity assumptions "
                "do not hold for this configuration"
            )
        return f"wavefront engine overflow flags={flags}"

    def _wl_abort_cleanup(self, carry):
        """Erase an aborted wave's fingerprint-table writes before a
        keep-partial (stop/deadline) break persists the carry: the
        growth path's rehash-from-committed-prefix, minus the growth.
        Without it a resume would find the aborted wave's keys already
        present, mark its states as duplicates, and silently drop
        their entire subtrees."""
        stats_h = self._last_stats_h
        key_hi, key_lo = self._rehash(carry[2], int(stats_h[STAT_TAIL]))
        return (key_hi, key_lo) + tuple(carry[2:])

    def _wl_grow(self, flags: int, carry):
        """In-place auto-tune growth for the fused loop (the shared
        core's grow hook): the flagged wave did not commit (see
        wave_body), so the carry is the exact pre-wave state — grow the
        tripped buffers, rebuild the table from the committed row-log
        prefix (erasing any keys the aborted wave managed to write),
        recompile, and re-run the same chunk with no work redone."""
        stats_h = self._last_stats_h
        rows, parent, ebits, key_hi, key_lo, qcap, pad = (
            self._grow_on_flags(
                flags, self._loop_qcap, self._loop_pad,
                carry[2], carry[3], carry[4],
                int(stats_h[STAT_TAIL]), int(stats_h[STAT_UNIQUE]),
                int(stats_h[STAT_DEPTH]),
            )
        )
        self._loop_qcap, self._loop_pad = qcap, pad
        _seed, self._run_fn = self._programs()
        return (key_hi, key_lo, rows, parent, ebits, carry[5])

    # --- traced (phase-timed) mode -------------------------------------------

    def _traced_programs(self):
        """Phase-program set for ``trace=True`` (cached like the fused
        pair).  The key covers everything the closures trace over; host-
        driven knobs (waves_per_call, finish_when, target depth) are NOT
        baked in — the traced loop decides them per wave on the host."""
        key = (
            "traced",
            self._compiled.cache_key(),
            hasattr(self._compiled, "step_valid")
            and hasattr(self._compiled, "step_lane"),
            self._canon is not None,
            self._max_frontier,
            self._dedup_factor,
            self._sortless,  # the dedup path is a trace-time branch
            self._sort_width(),  # the live sort-geometry rung
            self._step_width(),  # the live step-geometry rung
            self._block_pad(),
            tuple(p.expectation for p in self._properties),
        )
        from .wave_common import cached_program

        return cached_program(
            _PROGRAM_CACHE, _PROGRAM_CACHE_MAX, key, self._build_traced,
            label=f"{type(self).__name__}.traced",
            journal=self._journal,
            provenance=self._key_provenance(),
        )

    def _build_traced(self):
        """The wave loop as four separately-dispatched phase programs —
        the SAME kernels as the fused ``wave_body``, cut at the phase
        boundaries the roofline models (step kernel / canon+fingerprint /
        dedup-sort+probe / append) so the host can time each with
        ``block_until_ready``.  Commit order, dedup keys, position
        assignment, and discovery folding are identical to the fused
        path; level/depth bookkeeping moves to the host (one sync per
        wave is the traced mode's documented cost)."""
        import jax
        import jax.numpy as jnp

        from ..ops.device_fp import device_fp64
        from .hashset import (
            HashSet, compact_valid_indices, insert_batch_claim,
            insert_batch_compact,
        )
        from .wave_common import compact, wave_eval

        cm = self._compiled
        w = cm.state_width
        fpw = cm.fp_words or w
        canon = self._canon
        a = cm.max_actions
        f_eff = self._step_width()  # the live step-geometry rung
        pad = self._block_pad()
        dedup_factor = self._dedup_factor
        sortless = self._sortless  # the dedup path (claim vs sort)
        sort_lanes = (
            None if self._sort_lanes is None else self._sort_width()
        )
        props = self._properties
        ev_indices = self._ev_indices

        @jax.jit
        def t_step(rows, ebits, disc, level_start, level_end):
            count = jnp.minimum(level_end - level_start, jnp.uint32(f_eff))
            lane = jnp.arange(f_eff, dtype=jnp.uint32)
            active = lane < count
            ids = level_start + lane
            states = jax.lax.dynamic_slice(
                rows, (level_start * jnp.uint32(w),), (f_eff * w,)
            ).reshape(f_eff, w)
            eb_chunk = jax.lax.dynamic_slice(
                ebits, (level_start,), (f_eff,)
            )
            disc, eb, nexts, valid, generated, step_flag = wave_eval(
                cm, props, ev_indices, states, active, ids, eb_chunk,
                disc, allow_two_phase=True,
            )
            flat_valid = valid.reshape(f_eff * a)
            v_orig, v_act, n_valid, v_overflow = compact_valid_indices(
                flat_valid, dedup_factor, sort_lanes=sort_lanes
            )
            if nexts is None:
                # Two-phase: construct successors only for the compacted
                # valid lanes (the fused path's phase B).
                src_state = v_orig // jnp.uint32(a)
                cand_rows, _vu, lane_flags_u = jax.vmap(cm.step_lane)(
                    states[src_state], v_orig % jnp.uint32(a)
                )
                step_flag = step_flag | jnp.any(lane_flags_u & v_act)
                cand_src = src_state
            else:
                # Single-phase: compact the constructed rows.  Same keys
                # and representatives as the fused compact_valid-on-keys
                # order (compaction preserves lane order).
                cand_rows = nexts.reshape(f_eff * a, w)[v_orig]
                cand_src = v_orig // jnp.uint32(a)
            return (
                disc, eb, states, cand_rows, cand_src, v_act,
                n_valid, v_overflow, generated, step_flag,
            )

        @jax.jit
        def t_fp(cand_rows):
            rows_c = (
                cand_rows if canon is None else jax.vmap(canon)(cand_rows)
            )
            return device_fp64(rows_c[:, :fpw])

        @partial(jax.jit, donate_argnums=(0, 1))
        def t_insert(key_hi, key_lo, hi, lo, cand_act):
            if sortless:
                (
                    table, _u_slot, u_new, u_origin, _u_active, probe_ok,
                    dd_overflow, rounds,
                ) = insert_batch_claim(
                    HashSet(key_hi, key_lo), hi, lo, cand_act,
                    with_rounds=True,
                )
            else:
                (
                    table, _u_slot, u_new, u_origin, _u_active, probe_ok,
                    dd_overflow, rounds,
                ) = insert_batch_compact(
                    HashSet(key_hi, key_lo), hi, lo, cand_act,
                    dedup_factor=1, with_rounds=True,
                )
            n_new = jnp.sum(u_new, dtype=jnp.uint32)
            return (
                table.key_hi, table.key_lo, u_new, u_origin, n_new,
                probe_ok, dd_overflow, rounds,
            )

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def t_append(rows, parent, ebits, cand_rows, cand_src, eb, u_new,
                     u_origin, level_start, tail):
            u = u_new.shape[0]
            sel = compact(u_new, jnp.arange(u, dtype=jnp.uint32), pad)
            sel_u = u_origin[sel]
            rows_blk = cand_rows[sel_u]
            src_state = cand_src[sel_u]
            par_blk = level_start + src_state
            eb_blk = eb[src_state]
            rows = jax.lax.dynamic_update_slice(
                rows, rows_blk.reshape(-1), (tail * jnp.uint32(w),)
            )
            parent = jax.lax.dynamic_update_slice(parent, par_blk, (tail,))
            ebits = jax.lax.dynamic_update_slice(ebits, eb_blk, (tail,))
            return rows, parent, ebits

        return {
            "step": t_step, "fp": t_fp, "insert": t_insert,
            "append": t_append,
        }

    def _traced_wave_bytes(self, probe_rounds: int, two_phase: bool) -> dict:
        """Modeled HBM bytes touched by one traced wave, per phase
        (obs/roofline.py documents the model and its biases).  Buffer-
        proportional, not count-proportional: the device streams full
        fixed-width buffers regardless of how many lanes are live, so
        charging the full widths is what matches what HBM actually
        moves.  The chunk/candidate widths are the LIVE step rung
        (``_step_width``) — ``bytes.step`` drops in proportion to it,
        the step ladder's regression gauge (bench.py's step phase) —
        and the compact/canon/dedup widths the LIVE compact width.  On
        the sortless default path ``bytes.dedup`` carries NO sort term
        at all (the claim election probes, it never sorts): that is the
        density-insensitive drop bench's dedup phase gauges."""
        from ..obs.roofline import copy_bytes, probe_bytes, sort_bytes

        cm = self._compiled
        w = cm.state_width
        fpw = cm.fp_words or w
        a = cm.max_actions
        f_eff = self._step_width()
        b = f_eff * a
        u_sz = self._sort_width()
        pad = self._block_pad()
        # step: chunk read + candidate construction + the valid-lane
        # index compaction scan.  Two-phase constructs only U rows (and
        # gathers their U parents); single-phase materializes all B.
        step = f_eff * w * 4 + b * 4 + copy_bytes(u_sz, w)
        if not two_phase:
            step += b * w * 4
        canon = (copy_bytes(u_sz, w) if self._canon is not None else 0)
        canon += u_sz * fpw * 4 + 2 * u_sz * 4
        if self._sortless:
            # Claim election: probe rounds over the compact width plus
            # the claim-plane scatter/readback — no sort planes, no
            # representative re-compaction.
            dedup = probe_bytes(u_sz, probe_rounds) + 2 * u_sz * 4
        else:
            dedup = (
                sort_bytes(u_sz, 3)
                + probe_bytes(u_sz, probe_rounds)
                + 4 * u_sz * 4  # representative compaction planes
            )
        append = copy_bytes(pad, w) + 2 * copy_bytes(pad, 1) + u_sz * 4
        return {
            "step": step, "canon": canon, "dedup": dedup, "append": append,
        }

    def _check_once_traced(self, deadline=None) -> None:
        """The ``trace=True`` host loop: one wave per iteration, each
        phase dispatched and timed separately, scalars read back every
        wave (this is the documented trace cost), the visitor stream
        delivered from the chunk-state readback.  Overflow flags grow
        the tripped buffers in place and re-run the chunk, exactly like
        the fused loop (the aborted wave never commits its append or
        counters, and the rehash erases its table keys)."""
        import time as _time

        import jax
        import jax.numpy as jnp

        opts = self._options
        cm = self._compiled
        props = self._properties
        f = self._max_frontier
        f_eff = self._step_width()  # the live step-geometry rung
        cap = self._capacity
        qcap = self._log_capacity
        pad = self._block_pad()
        from .wave_common import two_phase_capable

        two_phase = two_phase_capable(cm)
        from ..obs.trace import WaveTracer

        tracer = WaveTracer(self._device, "tpu-wavefront")
        self._tracer = tracer
        visitor = opts._visitor
        model = self._model
        target_depth = opts._target_max_depth or 0

        with jax.default_device(self._device):
            seed, _run = self._programs()
            progs = self._traced_programs()
            init = cm.init_packed()
            n_init = init.shape[0]
            if n_init > f:
                raise ValueError(
                    f"{n_init} init states exceed the chunk size ({f}); "
                    "raise spawn_tpu(max_frontier=...) to at least the "
                    "init-state count (interior levels are unbounded)"
                )
            key_hi, key_lo, rows, parent, ebits, stats = seed(
                jnp.asarray(init.astype(np.uint32)), jnp.uint32(n_init)
            )
            stats_h = np.asarray(stats)
            if int(stats_h[STAT_FLAGS]):
                raise _OverflowRetry(
                    1,
                    "init-state seeding overflowed the fingerprint "
                    "table; raise spawn_tpu(capacity=...)",
                )
            level_start = int(stats_h[STAT_LEVEL_START])
            level_end = int(stats_h[STAT_LEVEL_END])
            tail = int(stats_h[STAT_TAIL])
            depth = 0
            disc = _device_owned(jnp.asarray(
                np.full((len(props),), NO_SLOT_HOST, np.uint32)
            ))
            disc_h = np.asarray(disc)
            with self._lock:
                self._state_count = n_init
                self._unique_count = int(stats_h[STAT_UNIQUE])

            # Always-on vitals (latency histogram, uniq/s EMA, density,
            # grow counters) — same registry keys as the fused loop's,
            # so /.metrics readers see one schema in either mode.
            from .wave_loop import LoopVitals, journal_geometry

            vitals = LoopVitals(
                self._metrics, initial_unique=self._unique_count,
                initial_states=self._state_count,
            )
            journal_geometry(self)
            wave_idx = 0
            while level_start < level_end:
                if target_depth and depth >= target_depth - 1:
                    # The next wave would expand states at depth+1; the
                    # reference counts-but-never-expands target-depth
                    # states (same gate as the fused wave_cond).
                    break
                count = min(level_end - level_start, f_eff)
                t0 = _time.perf_counter()
                disc_prev = disc  # t_step does not donate it
                # xprof hook (obs/timeline.py): under --xprof-dir each
                # traced wave's device phases land in a
                # StepTraceAnnotation so the hardware profile's steps
                # line up with the journal's wave events; a nullcontext
                # otherwise.
                from ..obs.timeline import step_annotation
                with step_annotation(wave_idx):
                    (
                        disc, eb, states, cand_rows, cand_src, cand_act,
                        n_valid_d, v_ovf_d, gen_d, stepflag_d,
                    ) = progs["step"](
                        rows, ebits, disc_prev,
                        jnp.uint32(level_start), jnp.uint32(level_end),
                    )
                    jax.block_until_ready(cand_rows)
                    t1 = _time.perf_counter()
                    hi, lo = progs["fp"](cand_rows)
                    jax.block_until_ready(lo)
                    t2 = _time.perf_counter()
                    (
                        key_hi, key_lo, u_new, u_origin, n_new_d,
                        probe_ok_d, dd_ovf_d, rounds_d,
                    ) = progs["insert"](key_hi, key_lo, hi, lo, cand_act)
                    jax.block_until_ready(key_lo)
                t3 = _time.perf_counter()
                # Host readback: the per-wave scalar sync, plus the chunk
                # states when a visitor is attached (the device visitor
                # stream), plus the visitor callbacks themselves.
                n_new = int(np.asarray(n_new_d))
                generated = int(np.asarray(gen_d))
                rounds = int(np.asarray(rounds_d))
                flags = 0
                if (
                    not bool(np.asarray(probe_ok_d))
                    or (self._unique_count + n_new) * 2 > cap
                ):
                    flags |= 1
                if tail + n_new > qcap:
                    flags |= 2
                if bool(np.asarray(dd_ovf_d)) or bool(np.asarray(v_ovf_d)):
                    flags |= 4
                if bool(np.asarray(stepflag_d)):
                    flags |= 8
                if f_eff < f and level_end - level_start > f_eff:
                    # Step-rung clamp (the fused wave_body's flag 128,
                    # host-computed here): the remaining level exceeds
                    # the chunk rung — abort, climb, re-run.
                    flags |= 128
                disc_h = np.asarray(disc)
                if visitor is not None and flags == 0:
                    states_h = np.asarray(states)
                    for i in range(count):
                        visitor.visit(
                            model,
                            Path([(cm.decode(states_h[i]), None)]),
                        )
                t4 = _time.perf_counter()
                if flags & 8:
                    raise RuntimeError(
                        "the model step kernel flagged an encoding-"
                        "capacity overflow (a successor exceeded the "
                        "packed layout's bounds); the compiled model's "
                        "capacity assumptions do not hold for this "
                        "configuration"
                    )
                if flags and (
                    self._stop_requested.is_set()
                    or (deadline is not None
                        and _time.monotonic() >= deadline)
                ):
                    # Growth costs a rehash + re-run; a run already past
                    # its budget (or asked to stop) keeps its partial
                    # result instead (the fused loop's policy).  The
                    # aborted wave's discoveries still REVERT (same rule
                    # as the growth branch below): the final snapshot
                    # must not persist a discovery from a wave that
                    # never committed, or a resume would run with its
                    # awaiting mask pruned and diverge from an
                    # uninterrupted run.  Its table writes are erased
                    # the same way (the fused loop's _wl_abort_cleanup):
                    # persisted aborted keys would make a resume drop
                    # the wave's states as duplicates.
                    disc = disc_prev
                    disc_h = np.asarray(disc_prev)
                    key_hi, key_lo = self._rehash(rows, tail)
                    break
                if flags:
                    # Same IN-PLACE auto-tune growth as the fused loop
                    # (one shared helper, so recovery semantics cannot
                    # drift): this wave's append and counters have not
                    # committed (both are gated below on flags == 0),
                    # and the rehash erases any keys the aborted insert
                    # wrote — the chunk simply re-runs at the grown
                    # geometry.  ``disc`` REVERTS to its pre-wave value,
                    # mirroring the fused loop's on-device
                    # `where(commit, disc, disc_prev)`: a kept discovery
                    # would change the re-run's awaiting mask (wave_eval
                    # prunes expansion once a property is discovered)
                    # and generate different successors than a committed
                    # execution of the same wave.
                    disc = disc_prev
                    rows, parent, ebits, key_hi, key_lo, qcap, pad = (
                        self._grow_on_flags(
                            flags, qcap, pad, rows, parent, ebits,
                            tail, self._unique_count, depth,
                        )
                    )
                    cap = self._capacity
                    f = self._max_frontier  # dd growth may halve it
                    f_eff = self._step_width()  # rung climbs move it
                    progs = self._traced_programs()
                    vitals.record_overflow_recovery()
                    continue
                rows, parent, ebits = progs["append"](
                    rows, parent, ebits, cand_rows, cand_src, eb, u_new,
                    u_origin, jnp.uint32(level_start), jnp.uint32(tail),
                )
                jax.block_until_ready(ebits)
                t5 = _time.perf_counter()

                tail += n_new
                level_start += count
                if level_start >= level_end:
                    depth += 1
                    level_end = tail
                remaining = level_end - level_start
                with self._lock:
                    self._state_count += generated
                    self._unique_count += n_new
                    self._max_depth = depth + (1 if remaining else 0)
                    for p, prop in enumerate(props):
                        if int(disc_h[p]) != NO_SLOT_HOST:
                            self._discovery_slots.setdefault(
                                prop.name, int(disc_h[p])
                            )
                wave_idx += 1
                phases = {
                    "step": t1 - t0,
                    "canon": t2 - t1,
                    "dedup": t3 - t2,
                    "append": t5 - t4,
                    "readback": t4 - t3,
                }
                enrich = tracer.record_wave(
                    phases, self._traced_wave_bytes(rounds, two_phase),
                    probe_rounds=rounds,
                )
                vitals.record_quantum(
                    t5 - t0, 1, self._unique_count, committed=True,
                    states=self._state_count,
                    cand_lanes=self._wl_cand_lanes(),
                    occupancy=self._unique_count / cap,
                )
                vitals.record_host(phases["readback"])
                if self._journal:
                    self._journal.append(
                        "wave",
                        waves=wave_idx,
                        remaining=remaining,
                        tail=tail,
                        unique=self._unique_count,
                        states=self._state_count,
                        depth=depth,
                        flags=0,
                        call_sec=round(t5 - t0, 6),
                        occupancy=round(self._unique_count / cap, 6),
                        **(
                            {"density": round(vitals.last_density, 6)}
                            if vitals.last_density is not None else {}
                        ),
                        **enrich,
                    )
                self._metrics.update(
                    waves=wave_idx,
                    table_occupancy=round(self._unique_count / cap, 6),
                    last_call_sec=round(t5 - t0, 6),
                )
                self._metrics.inc("device_call_sec_total", t5 - t0)
                self._metrics.inc("device_calls", 1)

                # Density-driven sort-rung downshift and frontier-driven
                # step-rung downshift, per committed wave (the traced
                # analogue of the fused loop's between-quanta retunes);
                # a rung change re-keys the phase programs.
                from .wave_loop import maybe_retune_sort, maybe_retune_step

                retuned = maybe_retune_sort(self, vitals.last_density)
                if maybe_retune_step(self, remaining or None):
                    retuned = True
                if retuned:
                    f_eff = self._step_width()
                    progs = self._traced_programs()

                # Shared termination tail (wave_loop.py): the same
                # predicate order as the fused loop by construction.
                from .wave_loop import loop_should_break

                if loop_should_break(self, remaining, depth, deadline):
                    break

            # Same snapshot-ready tail as the fused loop: device tables
            # for path reconstruction, a carry for save_snapshot, the
            # final checkpoint, and the engine_done journal record.
            self._tables_dev = (parent, rows)
            stats_final = np.concatenate([
                np.array(
                    [
                        level_start,
                        level_end,
                        tail,
                        self._state_count & 0xFFFFFFFF,
                        (self._state_count >> 32) & 0xFFFFFFFF,
                        self._unique_count,
                        depth,
                        0,
                    ],
                    np.uint32,
                ),
                disc_h.astype(np.uint32),
            ])
            if self._journal:
                self._journal.append("trace_summary", **tracer.summary())
            from .wave_loop import finalize_run

            finalize_run(self, self._carry_from(
                key_hi, key_lo, rows, parent, ebits, stats_final
            ))

    def _carry_from(self, key_hi, key_lo, rows, parent, ebits, stats_h):
        """Full run state as one dict — the ``save_snapshot`` npz layout
        (arrays may be device or host; scalars come from the last stats
        readback)."""
        return {
            "key_hi": key_hi,
            "key_lo": key_lo,
            "rows": rows,
            "parent": parent,
            "ebits": ebits,
            "level_start": stats_h[STAT_LEVEL_START],
            "level_end": stats_h[STAT_LEVEL_END],
            "tail": stats_h[STAT_TAIL],
            "sc_lo": stats_h[STAT_SC_LO],
            "sc_hi": stats_h[STAT_SC_HI],
            "unique_count": stats_h[STAT_UNIQUE],
            "depth": stats_h[STAT_DEPTH],
            "disc": stats_h[STAT_DISC:].copy(),
        }

    def _snapshot_extra(self) -> dict:
        """Extra npz fields an engine subclass persists beside the
        carry (the tiered engine's cold-tier state rides here) — so the
        atomic-write body and the base field set exist exactly once."""
        return {}

    def _write_snapshot(self, path: str, carry: dict) -> None:
        """Persist a carry dict atomically (write + rename), so a kill
        mid-checkpoint can never leave a torn snapshot where a resume
        would find it."""
        import os

        arrays = {k: np.asarray(v) for k, v in carry.items()}
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as fh:
            np.savez_compressed(
                fh,
                engine_key=self._snapshot_key(),
                # Geometry travels as data, not key material: a resume
                # adopts these (the run may have auto-tuned past the
                # spawn args).
                capacity=self._capacity,
                log_capacity=self._log_capacity,
                **self._snapshot_extra(),
                **arrays,
            )
        os.replace(tmp, path)

    def _block_pad(self) -> int:
        """Append-block lanes past the position log's capacity: one chunk's
        insert can mint up to U = max(min(B, 16K), B/dedup_factor) new
        states (hashset.py's unique-buffer size), and appends are whole
        U-blocks whose tail garbage must land in bounds."""
        b = self._max_frontier * self._compiled.max_actions
        from .hashset import unique_buffer_size

        u = unique_buffer_size(b, self._dedup_factor)
        return max(self._max_frontier, u)

    def _snapshot_key(self) -> str:
        return snapshot_engine_key(
            self._compiled, self._properties, self._canon is not None
        )

    def save_snapshot(self, path: str) -> None:
        """Persist the full checker state (visited table, row log, parent
        links, counters, discoveries) so a bounded run — e.g. stopped by
        ``timeout`` or ``target_state_count`` — can be resumed later with
        ``spawn_tpu(resume_from=path)``.  The reference has no checker
        persistence (its visited set is not persistable, SURVEY §5); on
        device the whole run state is dense arrays, so snapshots are a
        plain ``np.savez``.

        Note: to stay snapshot-ready, a finished checker keeps its key
        planes and ebits (12 bytes × capacity) on device alongside the
        row-log/parent arrays that path reconstruction already retains;
        dropping the checker object frees all of it.

        Engine tuning knobs that do not shape the persisted arrays —
        ``dedup_factor`` and the ``sort_lanes`` rung in particular — are
        deliberately NOT part of the snapshot key: a resume may use
        different tuning, in which case overflow-failure behavior (not
        correctness) can differ from the original run."""
        self.join()
        if self._carry_dev is None:
            raise RuntimeError("no run state to snapshot")
        self._write_snapshot(path, self._carry_dev)

    def tuned_kwargs(self) -> dict:
        """Engine kwargs right-sized to THIS run's final counts, so a
        fresh spawn of the same workload runs without any auto-tune
        growth pauses: a default-knob discovery run, then a measured run
        with the returned sizes (the bench.py pattern).  The table gets
        ≥2× the unique count (50% max load), the row log the exact count
        plus safety slack."""
        self.join()
        u = max(1, self._unique_count)
        return dict(
            capacity=1 << max(10, (2 * u).bit_length()),
            log_capacity=u + max(64, u // 64),
            max_frontier=self._max_frontier,
            dedup_factor=self._dedup_factor,
            # The discovered dedup path: a sortless→sort fallback is a
            # per-workload selection the knob cache must remember, so a
            # warm repeat skips the fallback retry entirely.
            sortless=int(self._sortless),
            # The discovered rungs — ONLY when one was actually pinned
            # (ladder climb, tuner, or explicit spawn): a warm spawn
            # from an explicit rung disarms the tuner, so persisting
            # the full worst-case width from a run too short to tune
            # would freeze that workload at full width forever (the
            # sharded snapshot's none-sentinel rule).  A SORTLESS run
            # never persists its sort rung: under the election the
            # rung is the claim compaction buffer's tuner detail, and
            # an explicit rung under sortless is the fallback-forcing
            # budget cap (_grow's flag-4 dispatch) — a warm repeat
            # must re-arm the tuner, not inherit a one-notch-tight
            # explicit cap that flips it onto the sort path.
            **(
                {"sort_lanes": self._sort_width()}
                if self._sort_lanes is not None and not self._sortless
                else {}
            ),
            **(
                {"step_lanes": self._step_width()}
                if self._step_lanes is not None else {}
            ),
        )

    def discovered_fingerprints(self):
        """Sorted uint64 IDENTITY fingerprints of every discovered
        unique state (the dedup-key fingerprints: original rows, or
        canonical rows under symmetry — wave_loop.fingerprints_of_rows
        documents why), for cross-engine discovery-set comparison — the
        sharded engine must reproduce this set bit-identically on every
        mesh size (tests/test_tpu_sharded.py), and the sortless and
        sort dedup paths on every geometry (tests/test_sortless.py).
        Pulls the committed row-log prefix to the host; size it like a
        path reconstruction, not a hot call."""
        self.join()
        if self._carry_dev is None:
            raise RuntimeError("no run state to fingerprint")
        from .wave_loop import fingerprints_of_rows

        w = self._compiled.state_width
        tail = int(self._carry_dev["tail"])
        rows = np.asarray(self._carry_dev["rows"])[: tail * w].reshape(
            tail, w
        )
        return fingerprints_of_rows(self._compiled, rows, self._canon)

    # --- Checker surface -----------------------------------------------------

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return self._unique_count

    def max_depth(self) -> int:
        return self._max_depth

    def metrics(self) -> dict:
        """Live observability snapshot (names: docs/OBSERVABILITY.md).
        Safe to call mid-run — it reads the registry the host loop
        updates from scalars it already synced, never the device.  (A
        FINISHED checker's first call additionally reads the key
        planes' true load factor back once and caches it.)  The
        Explorer's ``GET /.metrics`` serves exactly this."""
        out = super().metrics()
        out.update(
            engine="tpu-wavefront",
            device=str(self._device),
            trace=self._trace,
            capacity=self._capacity,
            log_capacity=self._log_capacity,
            max_frontier=self._max_frontier,
            dedup_factor=self._dedup_factor,
            sortless=self._sortless,
            sort_lanes=self._sort_width(),
            # The PINNED rungs (0 = running at the full buffer with the
            # tuner armed) — what warm-start stores persist, vs the
            # live widths (what the programs actually compiled).
            sort_lanes_rung=self._sort_lanes or 0,
            step_lanes=self._step_width(),
            step_lanes_rung=self._step_lanes or 0,
        )
        snap = self._metrics.snapshot()
        # Table load factor: mid-run it is the loop's already-synced
        # occupancy (metrics() never touches the device); a finished
        # checker reports the key planes' actual occupied fraction via
        # ONE cached HashSet.load_factor readback — ground truth even
        # for engines whose tables hold more than unique states, and
        # immutable once the run is done, so repeated /.metrics polls
        # never re-reduce the key planes.
        out["table_load_factor"] = snap.get("table_occupancy", 0.0)
        if self._done.is_set() and self._carry_dev is not None:
            if self._final_load_factor is None:
                from .hashset import HashSet

                try:
                    self._final_load_factor = round(HashSet(
                        self._carry_dev["key_hi"],
                        self._carry_dev["key_lo"],
                    ).load_factor(), 6)
                except Exception:
                    # Snapshot arrays already freed mid-teardown: keep
                    # the loop's occupancy (and stop retrying).
                    self._final_load_factor = out["table_load_factor"]
            out["table_load_factor"] = self._final_load_factor
        out.update(snap)
        # Always-on vitals histograms (wave_latency_sec, waves_per_grow;
        # obs/metrics.py documents the snapshot shape) — one nested key
        # so flat scrapers keep a numbers-only top level.
        hists = self._metrics.snapshot_histograms()
        if hists:
            out["histograms"] = hists
        if self._tracer is not None:
            out["trace_summary"] = self._tracer.summary()
        return out

    def trace_summary(self) -> dict:
        """The finished traced run's roofline reduction: per-phase
        seconds (``wave_breakdown``), modeled bytes, and
        ``hbm_util_frac`` against the device's peak table.  Requires
        ``trace=True``."""
        self.join()
        if self._tracer is None:
            raise RuntimeError(
                "trace_summary() requires spawn_tpu(trace=True)"
            )
        return self._tracer.summary()

    def _rehash_program(self):
        """Device program inserting one row-log chunk's fingerprints into
        a (fresh, larger) table — the auto-tune growth path.  Rows are the
        source of truth: every committed position holds exactly one
        distinct state, so the rebuild is chunked contiguous reads with
        ``dedup_factor=1`` inserts."""
        import jax
        import jax.numpy as jnp

        from ..ops.device_fp import device_fp64
        from .hashset import HashSet, insert_batch
        from .wave_common import cached_program

        cm = self._compiled
        w = cm.state_width
        fpw = cm.fp_words or w
        r = self._max_frontier
        canon = self._canon  # the log holds ORIGINAL rows; keys are canonical
        key = ("rehash", self._capacity, w, fpw, r, canon is not None,
               cm.cache_key() if canon is not None else None)

        def build():
            @partial(jax.jit, donate_argnums=(0, 1))
            def rehash_chunk(kh, kl, ok, rows, start, count):
                states = jax.lax.dynamic_slice(
                    rows, (start * jnp.uint32(w),), (r * w,)
                ).reshape(r, w)
                states_c = (
                    states if canon is None else jax.vmap(canon)(states)
                )
                hi, lo = device_fp64(states_c[:, :fpw])
                active = jnp.arange(r, dtype=jnp.uint32) < count
                table, _slot, _new, p_ok, _dd = insert_batch(
                    HashSet(kh, kl), hi, lo, active, dedup_factor=1
                )
                return table.key_hi, table.key_lo, ok & p_ok

            return rehash_chunk

        return cached_program(
            _PROGRAM_CACHE, _PROGRAM_CACHE_MAX, key, build,
            label=f"{type(self).__name__}.rehash",
            journal=self._journal,
            provenance={"capacity": self._capacity,
                        "max_frontier": self._max_frontier},
        )

    def _rehash(self, rows, tail_h: int, start_h: int = 0):
        """Rebuild the fingerprint table (sized to the CURRENT
        ``self._capacity``) from the committed row-log positions
        ``[start_h, tail_h)`` — the whole prefix for the auto-tune
        growth path, a suffix segment for the tiered engine (whose hot
        tier only ever holds states committed since the last spill;
        tiered/engine.py).  The OK accumulator stays on device so chunk
        dispatches pipeline without a per-chunk host round trip (the
        tunneled link makes each sync milliseconds; at bench scale that
        is thousands of chunks)."""
        import jax.numpy as jnp

        from .hashset import make_hashset

        prog = self._rehash_program()
        t = make_hashset(self._capacity)
        kh, kl = t.key_hi, t.key_lo
        ok = jnp.asarray(True)
        r = self._max_frontier
        for start in range(start_h, tail_h, r):
            kh, kl, ok = prog(
                kh,
                kl,
                ok,
                rows,
                jnp.uint32(start),
                jnp.uint32(min(r, tail_h - start)),
            )
        if not bool(ok):
            raise RuntimeError(
                "rehash after auto-tune growth could not place every "
                "committed state; the grown table is still overfull"
            )
        return kh, kl

    def _chain_program(self, length: int):
        """Device program walking a parent chain and gathering its rows:
        the readback is O(depth × W) instead of the full tables (which are
        GBs at bench capacities, behind a ~18 MB/s tunnel link)."""
        import jax
        import jax.numpy as jnp

        from .wave_common import cached_program

        w = self._compiled.state_width
        n = self._log_capacity + self._block_pad()
        key = ("chain", w, n, length)

        def build():
            @jax.jit
            def chain(parent, rows, pos):
                def walk(i, c):
                    ch, s = c
                    ch = ch.at[i].set(s)
                    nxt = parent[jnp.minimum(s, jnp.uint32(n - 1))]
                    s = jnp.where(s == jnp.uint32(NO_SLOT_HOST), s, nxt)
                    return ch, s

                ch, _ = jax.lax.fori_loop(
                    0, length,
                    walk,
                    (jnp.full((length,), NO_SLOT_HOST, jnp.uint32), pos),
                )

                def gather(i, buf):
                    s = jnp.minimum(ch[i], jnp.uint32(n - 1))
                    row = jax.lax.dynamic_slice(
                        rows, (s * jnp.uint32(w),), (w,)
                    )
                    return jax.lax.dynamic_update_slice(
                        buf, row[None, :], (i, 0)
                    )

                out = jax.lax.fori_loop(
                    0, length, gather, jnp.zeros((length, w), jnp.uint32)
                )
                return ch, out

            return chain

        return cached_program(
            _PROGRAM_CACHE, _PROGRAM_CACHE_MAX, key, build,
            label=f"{type(self).__name__}.chain",
            journal=self._journal,
            provenance={"length": length},
        )

    def _slot_path(self, slot: int) -> Path:
        import jax.numpy as jnp

        # Chain length bucketed to powers of two so a run's discoveries
        # share one compiled walk program.
        need = self._max_depth + 2
        length = 1 << max(4, (need - 1).bit_length())
        parent_dev, rows_dev = self._tables_dev
        n = self._log_capacity + self._block_pad()
        while True:
            chain_fn = self._chain_program(length)
            ch, rows_l = chain_fn(parent_dev, rows_dev, jnp.uint32(slot))
            ch = np.asarray(ch)
            rows_l = np.asarray(rows_l)
            chain = [i for i, s in enumerate(ch) if s != NO_SLOT_HOST]
            if len(chain) < length or length >= n:
                break
            # Every buffer lane came back valid: the chain may be
            # TRUNCATED.  The run's own max_depth under-estimates chain
            # length when the parent links predate this run — a seeded
            # incremental re-check (incr/recheck.py) carries a completed
            # store's parents, whose chains span the ORIGINAL run's
            # depth, not the seeded run's.  Double and re-walk.
            length *= 2
        chain.reverse()
        fps = [
            self._model.fingerprint(self._compiled.decode(rows_l[i]))
            for i in chain
        ]
        return Path.from_fingerprints(self._model, fps)

    def discoveries(self) -> Dict[str, Path]:
        self.join()
        if self._discoveries_cache is None:
            with self._lock:
                items = list(self._discovery_slots.items())
            self._discoveries_cache = {
                name: self._slot_path(slot) for name, slot in items
            }
        return dict(self._discoveries_cache)

    def try_discovery(self, name: str) -> Optional[Path]:
        # Non-blocking while the run is live (the Explorer polls status
        # mid-run); paths resolve once the run completes cleanly (a failed
        # run surfaces its error through join(), not here).
        if not self._done.is_set() or self._errors:
            return None
        return self.discoveries().get(name)

    def handles(self) -> List[threading.Thread]:
        return [self._thread]

    def is_done(self) -> bool:
        return self._done.is_set()

    def join(self) -> "TpuChecker":
        self._thread.join()
        if self._errors:
            raise self._errors[0]
        return self
